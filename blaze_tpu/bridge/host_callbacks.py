"""Host-engine callback surface over the C ABI.

Parity: the ~20 JNI statics the reference's native side calls back into
(ref auron-core/.../jni/JniBridge.java:57+ — conf getters,
openFileAsDataInputWrapper, getTaskOnHeapSpillManager, isTaskRunning,
getAuronUDFWrapperContext) and the `define_conf!` lazy conf proxies
(auron-jni-bridge/src/conf.rs:20-63).

The C++ bridge (native/src/host_bridge.cpp blaze_register_callbacks)
receives a `BlazeHostCallbacks` struct from the host and forwards the raw
function addresses here; this module wraps them with ctypes and installs
them into the engine's seams:

  conf_get        -> a resolver layer in config.ConfSession
  fs_*            -> a CallbackFs registered as the fallback filesystem
  spill_*         -> a host-engine Spill tier (OnHeapSpillManager analog)
  is_task_running -> the TaskContext cooperative-cancel probe
  udf_eval        -> a `udf://` resource resolver (Arrow IPC round trip)
"""

from __future__ import annotations

import ctypes
import io
from typing import Dict, Optional

import pyarrow as pa

ABI_VERSION = 1

# ctypes signatures mirroring BlazeHostCallbacks (host_bridge.cpp)
SIGNATURES = {
    "conf_get": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int64),
    "fs_open": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_char_p),
    "fs_size": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64),
    "fs_read": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_int64),
    "fs_close": ctypes.CFUNCTYPE(None, ctypes.c_int64),
    "spill_create": ctypes.CFUNCTYPE(ctypes.c_int64),
    "spill_write": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                    ctypes.POINTER(ctypes.c_uint8),
                                    ctypes.c_int64),
    "spill_read": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                   ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_int64),
    "spill_release": ctypes.CFUNCTYPE(None, ctypes.c_int64),
    "is_task_running": ctypes.CFUNCTYPE(ctypes.c_int32, ctypes.c_int64,
                                        ctypes.c_int64),
    "udf_eval": ctypes.CFUNCTYPE(
        ctypes.c_int64, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64)),
    "free_buffer": ctypes.CFUNCTYPE(None, ctypes.c_void_p),
}

_installed: Dict[str, object] = {}


def installed() -> Dict[str, object]:
    return dict(_installed)


def uninstall() -> None:
    """Remove every host hook (tests)."""
    from blaze_tpu import config
    from blaze_tpu.bridge import context, resource
    from blaze_tpu.bridge.fs import fs_provider
    from blaze_tpu.memory import spill as spill_mod
    _installed.clear()
    config.set_host_conf_provider(None)
    context.set_host_task_probe(None)
    resource.unregister_resolver("udf://")
    fs_provider.unregister_fallback()
    spill_mod.set_host_spill_factory(None)
    from blaze_tpu.bridge import adaptor as adaptor_mod
    adaptor_mod.note_installed(None)


def install_from_addresses(version: int, addrs: Dict[str, int]) -> None:
    """Called by blaze_register_callbacks with raw function addresses."""
    if version != ABI_VERSION:
        raise ValueError(f"host callback ABI version {version} != "
                         f"{ABI_VERSION}")
    fns = {}
    for name, addr in addrs.items():
        if addr:
            fns[name] = SIGNATURES[name](addr)
    install(fns)


def install(fns: Dict[str, object]) -> None:
    """Install ctypes-wrapped (or plain python, in tests) callbacks."""
    _installed.clear()
    _installed.update(fns)
    if "conf_get" in fns:
        _install_conf(fns["conf_get"])
    if "fs_open" in fns and "fs_read" in fns:
        _install_fs(fns)
    if "spill_create" in fns:
        _install_spill(fns)
    if "is_task_running" in fns:
        _install_task_probe(fns["is_task_running"])
    if "udf_eval" in fns:
        _install_udf(fns)
    # surface this installation through the engine-adaptor SPI
    # (AuronAdaptor.getInstance answers coherently for the C-ABI route)
    from blaze_tpu.bridge import adaptor as adaptor_mod
    adaptor_mod.note_installed(adaptor_mod.CallbackAdaptor(fns))


# ---------------------------------------------------------------------------

def _install_conf(conf_get) -> None:
    from blaze_tpu import config

    def resolver(key: str) -> Optional[str]:
        buf = ctypes.create_string_buffer(4096)
        found = conf_get(key.encode("utf-8"), buf, 4096)
        if found == 1:
            return buf.value.decode("utf-8")
        return None

    config.set_host_conf_provider(resolver)


class _HostFile(io.RawIOBase):
    """Random-access stream over host fs_read callbacks (the
    FsDataInputWrapper analog)."""

    def __init__(self, fns, fd: int, size: int):
        self._fns = fns
        self._fd = fd
        self._size = size
        self._pos = 0

    def readable(self):
        return True

    def seekable(self):
        return True

    def seek(self, offset, whence=io.SEEK_SET):
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self):
        return self._pos

    def readinto(self, b):
        n = len(b)
        if n == 0:
            return 0
        buf = (ctypes.c_uint8 * n)()
        got = self._fns["fs_read"](self._fd, self._pos, buf, n)
        if got < 0:
            raise IOError(f"host fs_read failed at {self._pos}")
        b[:got] = bytes(buf[:got])
        self._pos += got
        return got

    def close(self):
        if not self.closed and "fs_close" in self._fns:
            self._fns["fs_close"](self._fd)
        super().close()


def _install_fs(fns) -> None:
    from blaze_tpu.bridge.fs import CallbackFs, fs_provider

    def open_fn(path: str):
        fd = fns["fs_open"](path.encode("utf-8"))
        if fd <= 0:
            raise FileNotFoundError(f"host fs_open failed for {path!r}")
        if "fs_size" not in fns:
            # without a size callback there is no SEEK_END; slurp the
            # stream into memory so readers that seek from the end
            # (parquet footers) still work
            chunks = []
            pos = 0
            while True:
                buf = (ctypes.c_uint8 * (1 << 20))()
                got = fns["fs_read"](fd, pos, buf, 1 << 20)
                if got < 0:
                    raise IOError(f"host fs_read failed for {path!r}")
                if got == 0:
                    break
                chunks.append(bytes(buf[:got]))
                pos += got
            if "fs_close" in fns:
                fns["fs_close"](fd)
            return io.BytesIO(b"".join(chunks))
        size = fns["fs_size"](fd)
        return io.BufferedReader(_HostFile(fns, fd, size))

    def size_fn(path: str) -> int:
        fd = fns["fs_open"](path.encode("utf-8"))
        if fd <= 0:
            raise FileNotFoundError(path)
        try:
            return int(fns["fs_size"](fd))
        finally:
            if "fs_close" in fns:
                fns["fs_close"](fd)

    fs_provider.register_fallback(CallbackFs(open_fn, size_fn=size_fn))


def _install_spill(fns) -> None:
    from blaze_tpu.memory import spill as spill_mod

    class HostEngineSpill(spill_mod.Spill):
        """Spill run stored by the host engine (OnHeapSpill analog,
        spill.rs:180)."""

        def __init__(self):
            self._id = int(fns["spill_create"]())
            if self._id <= 0:
                # host declined (no on-heap room): local tiers take over
                raise spill_mod.HostSpillUnavailable(
                    "host spill_create declined")
            self._len = 0

        def write_batches(self, batches) -> int:
            from blaze_tpu.shuffle.ipc import IpcCompressionWriter
            sink = io.BytesIO()
            w = IpcCompressionWriter(sink)
            n = 0
            for b in batches:
                n += w.write_batch(b)
            w.finish()
            payload = sink.getvalue()
            buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
            wrote = fns["spill_write"](self._id, buf, len(payload))
            if wrote != len(payload):
                raise IOError("host spill_write failed")
            self._len = len(payload)
            return n

        def read_batches(self):
            from blaze_tpu.shuffle.ipc import IpcCompressionReader
            buf = (ctypes.c_uint8 * self._len)()
            got = fns["spill_read"](self._id, 0, buf, self._len)
            if got != self._len:
                raise IOError("host spill_read failed")
            yield from IpcCompressionReader(
                io.BytesIO(bytes(buf))).read_batches()

        def release(self):
            if "spill_release" in fns:
                fns["spill_release"](self._id)

        @property
        def stored_bytes(self) -> int:
            return self._len

    spill_mod.set_host_spill_factory(HostEngineSpill)


def _install_task_probe(is_task_running) -> None:
    from blaze_tpu.bridge import context

    def probe(stage_id: int, partition_id: int) -> bool:
        return bool(is_task_running(stage_id, partition_id))

    context.set_host_task_probe(probe)


def _install_udf(fns) -> None:
    from blaze_tpu.bridge import resource

    def factory(key: str):
        name = key[len("udf://"):]

        def call(*arrays: pa.Array):
            rb = pa.record_batch(list(arrays),
                                 names=[f"p{i}"
                                        for i in range(len(arrays))])
            sink = io.BytesIO()
            with pa.ipc.new_stream(sink, rb.schema) as w:
                w.write_batch(rb)
            payload = sink.getvalue()
            buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
            out_p = ctypes.c_void_p()
            out_len = ctypes.c_int64()
            rc = fns["udf_eval"](name.encode("utf-8"), buf, len(payload),
                                 ctypes.byref(out_p),
                                 ctypes.byref(out_len))
            if rc != 0 or not out_p.value:
                raise RuntimeError(f"host udf_eval({name!r}) failed "
                                   f"rc={rc}")
            data = ctypes.string_at(out_p.value, out_len.value)
            if "free_buffer" in fns:
                fns["free_buffer"](out_p)
            with pa.ipc.open_stream(io.BytesIO(data)) as r:
                out_rb = next(iter(r))
            return out_rb.column(0)

        return call

    resource.register_resolver("udf://", factory)
