"""XLA compile + host<->device transfer accounting.

The two TPU-specific hazards the profiler must surface (ROADMAP north
star; Flare and the Arrow-interface papers identify the analogous
native/JVM and host/device boundary costs):

* recompilation — every new (shape, dtype, static-arg) signature at a
  jit boundary triggers a fresh XLA compile; over a tunneled TPU these
  dominate cold starts.  `meter_jit` wraps `jax.jit` call sites so each
  dispatch is classified compile vs cache-hit, compile time accumulates
  per kernel, and shape churn (many distinct signatures on one kernel)
  is flagged.
* transfer volume — H2D on batch placement, D2H on Arrow export /
  host fetches.  `note_h2d`/`note_d2h` are called from the batch layer.

Compile detection is portable across jax versions: the traced Python
function only RUNS when XLA is actually tracing (i.e. compiling) the
call; a cache hit never re-enters it.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_lock = threading.Lock()

# kernel name -> stats dict
_kernels: Dict[str, Dict[str, Any]] = {}
_transfers = {"h2d_bytes": 0, "h2d_transfers": 0,
              "d2h_bytes": 0, "d2h_transfers": 0}
# batch-shaping + IO-pipeline counters (batch.bucket_capacity /
# ops.base.PrefetchIterator): how many capacity requests were quantized
# onto the bucket ladder (and the padding that cost), and how often the
# consumer actually waited on the prefetch queue (0 wait = IO fully
# overlapped with compute).
_pipeline = {"bucket_batches": 0, "bucket_pad_rows": 0,
             "prefetch_batches": 0, "prefetch_wait_ns": 0,
             "prefetch_waits": 0}
_bucket_caps: set = set()

# Whole-stage expression-program accounting (exprs/program.py).  Programs
# are keyed by expression FINGERPRINT, not callable identity: every
# partition-local evaluator instance resolves to the ONE process-wide
# metered callable per fingerprint, so per-partition instances cannot
# report false recompiles (each jit cache — and its compile counters
# above — is shared through the program cache).
_exprs = {"expr_programs_built": 0, "expr_program_cache_hits": 0,
          "expr_program_evictions": 0,
          "expr_fused_batches": 0, "expr_eager_batches": 0}

# Fault-tolerance accounting (bridge/tasks.py retry loop, shuffle
# readers, plan/stages.py lineage recovery, faults.py injector): how
# many attempts tasks burned, how long retries waited, how often a
# shuffle block came back poisoned and what recovery re-ran.
_faults = {"task_attempts": 0, "task_retries": 0, "task_retry_wait_ns": 0,
           "task_failures": 0, "fetch_failures": 0, "stage_recoveries": 0,
           "recovered_map_tasks": 0, "faults_injected": 0}

# Exchange-transport accounting (plan/stages.py DagScheduler,
# parallel/stage.py DeviceExchange): bytes moved through the on-device
# collective exchange vs the host file shuffle, collective dispatches,
# and how often the device lane bailed to the file fallback.
_shuffle = {"shuffle_device_bytes": 0, "shuffle_host_bytes": 0,
            "shuffle_device_rows": 0, "shuffle_device_exchanges": 0,
            "shuffle_device_collectives": 0,
            "shuffle_device_fallbacks": 0,
            # overlapped exchange (PR 18): per-task tickets drained in
            # the background, and the host-side barrier — time from the
            # last fold completing to the first collective dispatch —
            # the overlap exists to eliminate (sync pays it per stage;
            # the overlapped path records 0)
            "shuffle_device_overlap_exchanges": 0,
            "shuffle_barrier_idle_ns": 0,
            # io.compression.codec coverage beyond shuffle frames:
            # worker-pool control frames and RSS partition puts
            # (raw size - wire size, summed; 0 when the codec is raw
            # or compression grew the payload and was skipped)
            "worker_frame_compressed_bytes_saved": 0,
            "rss_put_compressed_bytes_saved": 0}

# Device-resident stage-loop accounting (runtime/loop.py,
# plan/stage_compiler.py): stage programs built vs served from the
# fingerprint cache, loop program calls (the O(1)-per-chunk dispatch
# the loop buys) vs the per-batch dispatches the staged path would have
# issued, rows folded device-side, overflow-driven table regrows, and
# wholesale fallbacks to the staged per-batch executor.
_stage_loop = {"stage_loop_programs_built": 0,
               "stage_loop_program_cache_hits": 0,
               "stage_loop_calls": 0, "stage_loop_chunks": 0,
               "stage_loop_batches": 0, "stage_loop_rows": 0,
               "stage_loop_tasks": 0, "stage_loop_regrows": 0,
               "stage_loop_fallbacks": 0,
               "stage_loop_staged_dispatches_avoided": 0}

# Adaptive partial-aggregation accounting (ops/agg/exec.py _AggState,
# plan/fused.py host lane): cardinality probes run, mode switches
# (ratio-triggered vs memory-pressure-triggered), and the rows that
# streamed through the pass-through lane un-aggregated.
_agg = {"partial_agg_skip_events": 0, "partial_agg_skipped_rows": 0,
        "partial_agg_probe_rows": 0, "partial_agg_probe_groups": 0,
        "partial_agg_switch_rows": 0, "partial_agg_spill_switches": 0}

# Pallas scatter/hash lane resolutions (kernels/lane.py): which lane
# each hash-update / radix-partition dispatch took, plus envelope
# declines and fault-injected fallbacks.  Surfaced in the
# explain_analyze footer.
_scatter_lane = {"scatter_lane_hash_pallas": 0,
                 "scatter_lane_hash_interpret": 0,
                 "scatter_lane_hash_scatter": 0,
                 "scatter_lane_partition_pallas": 0,
                 "scatter_lane_partition_interpret": 0,
                 "scatter_lane_partition_scatter": 0,
                 "scatter_lane_declines": 0,
                 "scatter_lane_fault_fallbacks": 0}

# Streaming-runtime accounting (streaming/executor.py StreamExecutor):
# committed epochs and their wall time, rows/records through the
# pipeline, late-record routing, checkpoint commits, recovery rounds
# and exactly-once sink outcomes.  The *_last entries are gauges (most
# recent observation), kept here so snapshot()/prometheus share one
# source: watermark delay (processing time - watermark), window-state
# retained bytes, and source lag (records staged but not yet polled).
_stream = {"stream_epochs": 0, "stream_epoch_wall_ns": 0,
           "stream_rows": 0, "stream_records": 0,
           "stream_late_records": 0, "stream_late_side_rows": 0,
           "stream_checkpoints": 0, "stream_checkpoint_bytes": 0,
           "stream_recoveries": 0, "stream_replayed_epochs": 0,
           "stream_sink_commits": 0, "stream_sink_dup_skips": 0,
           "stream_watermark_delay_ms_last": 0,
           "stream_window_state_bytes_last": 0,
           "stream_source_lag_records_last": 0}

# Worker-pool accounting (parallel/workers.py WorkerPool): processes
# spawned (incl. restarts), tasks shipped over the pipe, crashes (exit
# classified), hangs (liveness-deadline SIGKILLs), supervised restarts,
# slots blacklisted by the crash budget, and cancel escalations.
_workers = {"worker_spawns": 0, "worker_tasks": 0, "worker_crashes": 0,
            "worker_hangs": 0, "worker_restarts": 0,
            "worker_blacklisted": 0, "worker_cancels": 0,
            # child-process CPU actually burned running tasks (user+sys
            # os.times() delta shipped in each result frame) — what
            # bench.py --multichip derives host_core_limited from,
            # instead of a host-core-count heuristic
            "worker_cpu_ns": 0}

# Speculative-execution accounting (bridge/tasks.py wave loop,
# shuffle/writer.py + shuffle/rss.py commit arbitration): waves that
# hedged at least one straggler, duplicate attempts launched, duplicates
# that won the first-wins commit, losers cancelled via the cooperative
# token, forced commit races (the speculation-loser-commit-race site),
# loser commits rejected at a shuffle tier, and double-accepts (must
# stay 0 — the duplicate_output_blocks invariant the soak asserts).
_speculation = {"speculation_waves": 0, "speculation_attempts": 0,
                "speculation_wins": 0, "speculation_losers_cancelled": 0,
                "speculation_commit_races": 0,
                "speculation_loser_commits_rejected": 0,
                "speculation_duplicate_commits": 0}

# Observability-plane accounting (PR 13): spans stitched in from worker
# children, flight-recorder dumps written, and query-profile LRU
# evictions (bridge/profiling.py store bound).
_obs = {"obs_spans_ingested": 0, "obs_flight_dumps": 0,
        "obs_profile_evictions": 0}

# Cross-query work sharing (blaze_tpu/cache/, serving single-flight,
# shared scan decode).  scan_share_hits = follower rides a leader's
# decode; scan_share_misses = leader decoded itself.
# cache_used_bytes_last is the result/subplan cache's live footprint.
_cache = {"result_cache_hits": 0, "result_cache_misses": 0,
          "result_cache_puts": 0, "result_cache_evictions": 0,
          "result_cache_invalidations": 0,
          "subplan_cache_hits": 0, "subplan_cache_misses": 0,
          "subplan_cache_puts": 0,
          "single_flight_coalesces": 0, "single_flight_promotions": 0,
          "scan_share_hits": 0, "scan_share_misses": 0,
          "scan_share_bytes_saved": 0,
          "cache_used_bytes_last": 0}

# Statistics feedback plane (plan/statstore.py, plan/advisor.py):
# observations ingested, ingests that merged onto an existing record
# (run 2+ of a fingerprint), advisor findings emitted into history,
# progress ETAs seeded from a statstore prior, and the store's current
# on-disk fingerprint count (gauge).
_stats = {"stats_ingests": 0, "stats_runs_merged": 0,
          "stats_advisor_findings": 0, "stats_eta_seeded": 0,
          "stats_fingerprints_last": 0}

# Adaptive query execution (plan/adaptive.py): runtime rewrites applied
# at stage boundaries, split by rule; plans seeded from statstore
# history at bind time; producer stages elided outright (their exchange
# never ran); and the estimated shuffle bytes those rewrites avoided.
_aqe = {"aqe_rewrites": 0, "aqe_broadcast_switches": 0,
        "aqe_partitions_coalesced": 0, "aqe_skew_splits": 0,
        "aqe_history_seeds": 0, "aqe_bytes_saved": 0,
        "aqe_stages_elided": 0}

# Encoding lanes (config.ENCODING_*): utf8 columns dictionary-encoded
# at scan decode, cross-batch dictionary-unify remaps at concat/exchange
# boundaries, decimal dispatches split by storage tier (scaled int32 /
# scaled int64 / two-limb int128), and host-lane evictions split by the
# column dtype that caused them — the per-column accounting the advisor
# and BENCH_* compute_placement read instead of the old whole-stage
# "string somewhere -> host" verdict.
_encoding = {"dict_encoded_columns": 0, "dict_exchange_remaps": 0,
             "decimal_scaled_int32_dispatches": 0,
             "decimal_scaled_int64_dispatches": 0,
             "decimal_limb_dispatches": 0,
             "host_evictions_string": 0, "host_evictions_decimal": 0,
             "host_evictions_other": 0}

# Fleet-scope serving (blaze_tpu/fleet/): queries routed by the
# fingerprint-affine router, affinity hits (query landed on its
# rendezvous first choice — the replica whose result/subplan cache is
# warm), re-routes after replica death, end-to-end query retries,
# replica up/down transitions and heartbeat misses, torn socket frames
# survived, cross-replica hedges, and queries lost for good (must stay
# 0 — the kill-replica soak's core invariant).
# fleet_replicas_up_last is the router's current live-replica gauge.
_fleet = {"fleet_queries_routed": 0, "fleet_queries_completed": 0,
          "fleet_queries_lost": 0, "fleet_affinity_hits": 0,
          "fleet_affinity_misses": 0, "fleet_reroutes": 0,
          "fleet_retries": 0, "fleet_replica_down_events": 0,
          "fleet_replica_up_events": 0, "fleet_heartbeat_misses": 0,
          "fleet_torn_frames": 0, "fleet_hedges": 0,
          "fleet_hedge_wins": 0, "fleet_replicas_up_last": 0}

# Bounded raw-sample reservoirs feeding tail-latency percentiles
# (bench.py --workers / --speculate): successful task-attempt durations
# and run_tasks wave walls, in ns.  Lists, so NOT folded into
# snapshot()/delta() — read via duration_samples(), cleared by reset().
_task_duration_ns: List[int] = []
_wave_wall_ns: List[int] = []
_SAMPLE_CAP = 8192

# Prometheus histogram bucket upper bounds (seconds) for the task-
# latency and wave-wall exposition (bridge/profiling.py renders these
# as real `# TYPE ... histogram` families, not gauges).
HISTOGRAM_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.5, 5.0, 10.0, 30.0)

# Distinct signatures beyond this on one kernel = shape churn (the
# recompilation-storm smell: unpadded dynamic shapes hitting jit).
SHAPE_CHURN_THRESHOLD = 8


def _kernel_entry(name: str) -> Dict[str, Any]:
    entry = _kernels.get(name)
    if entry is None:
        entry = _kernels[name] = {
            "calls": 0, "compiles": 0, "cache_hits": 0,
            "compile_ns": 0, "dispatch_ns": 0, "signatures": set(),
        }
    return entry


def _signature(args, kwargs) -> tuple:
    def one(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None:
            return ("arr", tuple(shape), str(dtype))
        if isinstance(a, (int, float, bool, str, bytes, type(None))):
            return ("lit", a)
        if isinstance(a, (tuple, list)):
            return ("seq", tuple(one(x) for x in a))
        return ("obj", type(a).__name__)
    return (tuple(one(a) for a in args),
            tuple(sorted((k, one(v)) for k, v in kwargs.items())))


def meter_jit(fun: Callable, *, name: Optional[str] = None,
              **jit_kwargs) -> Callable:
    """`jax.jit` with compile/cache-hit accounting.

    Drop-in for `jax.jit(fun, **kwargs)` — supports static_argnums /
    static_argnames / donate_argnums.  Each call is timed; a call during
    which the traced body executed is a compile, otherwise a cache hit.
    """
    import jax

    kname = name or getattr(fun, "__name__", "jit_fn")
    traced = threading.local()

    @functools.wraps(fun)
    def _noting(*args, **kwargs):
        traced.hit = True
        return fun(*args, **kwargs)

    jitted = jax.jit(_noting, **jit_kwargs)

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        traced.hit = False
        t0 = time.perf_counter_ns()
        out = jitted(*args, **kwargs)
        dt = time.perf_counter_ns() - t0
        compiled = getattr(traced, "hit", False)
        with _lock:
            entry = _kernel_entry(kname)
            entry["calls"] += 1
            entry["dispatch_ns"] += dt
            try:
                entry["signatures"].add(_signature(args, kwargs))
            except TypeError:
                pass  # unhashable leaf: skip churn tracking for this call
            if compiled:
                entry["compiles"] += 1
                entry["compile_ns"] += dt
            else:
                entry["cache_hits"] += 1
        if compiled:
            from blaze_tpu.bridge import tracing
            tracing.instant("xla_compile", kernel=kname, ns=dt)
        return out

    wrapper._blaze_metered_jit = kname  # introspection / tests
    return wrapper


def note_h2d(nbytes: int) -> None:
    if nbytes <= 0:
        return
    with _lock:
        _transfers["h2d_bytes"] += int(nbytes)
        _transfers["h2d_transfers"] += 1


def note_d2h(nbytes: int) -> None:
    if nbytes <= 0:
        return
    with _lock:
        _transfers["d2h_bytes"] += int(nbytes)
        _transfers["d2h_transfers"] += 1


def note_bucket(capacity: int, pad_rows: int) -> None:
    """One capacity request quantized onto the bucket ladder
    (batch.bucket_capacity)."""
    with _lock:
        _pipeline["bucket_batches"] += 1
        _pipeline["bucket_pad_rows"] += max(0, int(pad_rows))
        _bucket_caps.add(int(capacity))


def note_prefetch(batches: int = 0, wait_ns: int = 0) -> None:
    """Prefetch-queue accounting from the consumer side: `batches` =
    items delivered through a prefetch queue, `wait_ns` = time the
    consumer blocked on the queue (the un-overlapped IO residue)."""
    with _lock:
        _pipeline["prefetch_batches"] += int(batches)
        if wait_ns > 0:
            _pipeline["prefetch_wait_ns"] += int(wait_ns)
            _pipeline["prefetch_waits"] += 1


def note_expr_program(built: bool = False, cache_hit: bool = False,
                      evicted: bool = False) -> None:
    """One program-cache resolution (exprs/program.py get_program)."""
    with _lock:
        if built:
            _exprs["expr_programs_built"] += 1
        if cache_hit:
            _exprs["expr_program_cache_hits"] += 1
        if evicted:
            _exprs["expr_program_evictions"] += 1


def note_expr_dispatch(fused: int = 0, eager: int = 0) -> None:
    """Per-batch dispatch accounting: `fused` batches went through a
    compiled expression program, `eager` fell back to the interpreted
    evaluator (host-only exprs, ANSI mode, non-device columns)."""
    with _lock:
        _exprs["expr_fused_batches"] += int(fused)
        _exprs["expr_eager_batches"] += int(eager)


def note_task_attempts(attempts: int = 1, retry_wait_ns: int = 0,
                       failed: bool = False) -> None:
    """One task reached a terminal state after `attempts` tries, having
    slept `retry_wait_ns` in backoff (bridge/tasks.py)."""
    with _lock:
        _faults["task_attempts"] += int(attempts)
        _faults["task_retries"] += max(0, int(attempts) - 1)
        _faults["task_retry_wait_ns"] += int(retry_wait_ns)
        if failed:
            _faults["task_failures"] += 1


def note_fetch_failure() -> None:
    """One shuffle block failed verification/fetch (FetchFailedError)."""
    with _lock:
        _faults["fetch_failures"] += 1


def note_stage_recovery(map_tasks: int = 1) -> None:
    """One lineage-recovery round re-ran `map_tasks` producer tasks."""
    with _lock:
        _faults["stage_recoveries"] += 1
        _faults["recovered_map_tasks"] += int(map_tasks)


def note_fault_injected() -> None:
    """The chaos injector fired one scripted fault (faults.py)."""
    with _lock:
        _faults["faults_injected"] += 1


def fault_stats() -> dict:
    with _lock:
        return dict(_faults)


def note_worker_spawn(restart: bool = False) -> None:
    """One worker process forked (restart=True when replacing a crash)."""
    with _lock:
        _workers["worker_spawns"] += 1
        if restart:
            _workers["worker_restarts"] += 1


def note_worker_task() -> None:
    """One task shipped over the pipe to a pool worker."""
    with _lock:
        _workers["worker_tasks"] += 1


def note_worker_crash(hang: bool = False) -> None:
    """A worker died mid-task (hang=True: liveness-deadline SIGKILL)."""
    with _lock:
        _workers["worker_crashes"] += 1
        if hang:
            _workers["worker_hangs"] += 1


def note_worker_blacklisted() -> None:
    """A slot exhausted its crash budget and was blacklisted."""
    with _lock:
        _workers["worker_blacklisted"] += 1


def note_worker_cancel() -> None:
    """A cancel/deadline escalated into the child (SIGTERM->SIGKILL)."""
    with _lock:
        _workers["worker_cancels"] += 1


def worker_stats() -> dict:
    with _lock:
        return dict(_workers)


def note_speculation(waves: int = 0, attempts: int = 0, wins: int = 0,
                     losers_cancelled: int = 0, commit_races: int = 0,
                     loser_commits_rejected: int = 0,
                     duplicate_commits: int = 0) -> None:
    """Speculative-execution events (bridge/tasks.py wave loop and the
    per-tier commit arbitration in shuffle/writer.py, shuffle/rss.py)."""
    with _lock:
        _speculation["speculation_waves"] += waves
        _speculation["speculation_attempts"] += attempts
        _speculation["speculation_wins"] += wins
        _speculation["speculation_losers_cancelled"] += losers_cancelled
        _speculation["speculation_commit_races"] += commit_races
        _speculation["speculation_loser_commits_rejected"] += \
            loser_commits_rejected
        _speculation["speculation_duplicate_commits"] += duplicate_commits


def speculation_stats() -> dict:
    with _lock:
        return dict(_speculation)


def note_task_duration(ns: int) -> None:
    """One successful task attempt's wall time (speculation's straggler
    cutoff and the bench's p50/p99 task percentiles feed from here)."""
    with _lock:
        if len(_task_duration_ns) < _SAMPLE_CAP:
            _task_duration_ns.append(int(ns))


def note_wave_wall(ns: int) -> None:
    """One run_tasks wave's wall time, submit to last result."""
    with _lock:
        if len(_wave_wall_ns) < _SAMPLE_CAP:
            _wave_wall_ns.append(int(ns))


def duration_samples() -> Dict[str, List[int]]:
    """Raw ns samples: {"task_ns": [...], "wave_ns": [...]}.  Bounded at
    _SAMPLE_CAP each; callers slice by remembered length for per-leg
    percentiles."""
    with _lock:
        return {"task_ns": list(_task_duration_ns),
                "wave_ns": list(_wave_wall_ns)}


def note_obs(spans_ingested: int = 0, flight_dumps: int = 0,
             profile_evictions: int = 0) -> None:
    with _lock:
        _obs["obs_spans_ingested"] += spans_ingested
        _obs["obs_flight_dumps"] += flight_dumps
        _obs["obs_profile_evictions"] += profile_evictions


def obs_stats() -> dict:
    with _lock:
        return dict(_obs)


def note_cache(**deltas: int) -> None:
    """Work-sharing plane mutator: kwargs name `_cache` keys; gauges
    (`*_last`) are set absolutely, counters are incremented."""
    with _lock:
        for k, v in deltas.items():
            if k not in _cache:
                continue
            if k.endswith("_last"):
                _cache[k] = int(v)
            else:
                _cache[k] += int(v)


def cache_stats() -> dict:
    with _lock:
        return dict(_cache)


def note_stats(**deltas: int) -> None:
    """Stats-plane mutator: kwargs name `_stats` keys with or without
    the `stats_` prefix; gauges (`*_last`) are set absolutely, counters
    are incremented (the note_cache contract)."""
    with _lock:
        for k, v in deltas.items():
            key = k if k.startswith("stats_") else f"stats_{k}"
            if key not in _stats:
                continue
            if key.endswith("_last"):
                _stats[key] = int(v)
            else:
                _stats[key] += int(v)


def statstore_stats() -> dict:
    with _lock:
        return dict(_stats)


def note_aqe(**deltas: int) -> None:
    """AQE-plane mutator: kwargs name `_aqe` keys with or without the
    `aqe_` prefix; gauges (`*_last`) are set absolutely, counters are
    incremented (the note_stats contract)."""
    with _lock:
        for k, v in deltas.items():
            key = k if k.startswith("aqe_") else f"aqe_{k}"
            if key not in _aqe:
                continue
            if key.endswith("_last"):
                _aqe[key] = int(v)
            else:
                _aqe[key] += int(v)


def aqe_stats() -> dict:
    with _lock:
        return dict(_aqe)


def note_encoding(**deltas: int) -> None:
    """Encoding-plane mutator (dict/decimal device lanes): kwargs name
    `_encoding` keys exactly; gauges (`*_last`) are set absolutely,
    counters are incremented (the note_stats contract)."""
    with _lock:
        for key, v in deltas.items():
            if key not in _encoding:
                continue
            if key.endswith("_last"):
                _encoding[key] = int(v)
            else:
                _encoding[key] += int(v)


def encoding_stats() -> dict:
    with _lock:
        return dict(_encoding)


def note_fleet(**deltas: int) -> None:
    """Fleet-plane mutator: kwargs name `_fleet` keys with or without
    the `fleet_` prefix; gauges (`*_last`) are set absolutely, counters
    are incremented (the note_stats contract)."""
    with _lock:
        for k, v in deltas.items():
            key = k if k.startswith("fleet_") else f"fleet_{k}"
            if key not in _fleet:
                continue
            if key.endswith("_last"):
                _fleet[key] = int(v)
            else:
                _fleet[key] += int(v)


def fleet_stats() -> dict:
    with _lock:
        return dict(_fleet)


def _histogram(samples_ns: List[int]) -> Dict[str, Any]:
    """Cumulative-bucket Prometheus histogram over an ns reservoir:
    {"buckets": [(le_seconds, cumulative_count), ...], "sum": seconds,
    "count": n}.  Buckets are HISTOGRAM_BUCKETS_S plus +Inf."""
    counts = [0] * len(HISTOGRAM_BUCKETS_S)
    total = 0.0
    for ns in samples_ns:
        s = ns / 1e9
        total += s
        for bi, le in enumerate(HISTOGRAM_BUCKETS_S):
            if s <= le:
                counts[bi] += 1  # every bucket with s <= le: cumulative
    return {"buckets": list(zip(HISTOGRAM_BUCKETS_S, counts)),
            "sum": total, "count": len(samples_ns)}


def latency_histograms() -> Dict[str, Dict[str, Any]]:
    """Histogram views of the duration reservoirs for /metrics.prom:
    task-attempt latency and run_tasks wave wall, in seconds."""
    with _lock:
        task = list(_task_duration_ns)
        wave = list(_wave_wall_ns)
    return {"task_duration_seconds": _histogram(task),
            "wave_wall_seconds": _histogram(wave)}


def note_device_exchange(rows: int, nbytes: int,
                         collectives: int = 1) -> None:
    """One map->reduce repartition completed over device collectives:
    `rows` real rows exchanged, `nbytes` buffer bytes that rode the
    all-to-all (padded send buffers — what actually moved), and the
    number of collective ops the program issued."""
    with _lock:
        _shuffle["shuffle_device_exchanges"] += 1
        _shuffle["shuffle_device_rows"] += int(rows)
        _shuffle["shuffle_device_bytes"] += int(nbytes)
        _shuffle["shuffle_device_collectives"] += int(collectives)


def note_host_exchange(nbytes: int) -> None:
    """One producer stage's map outputs landed in host shuffle files
    (`nbytes` = total .data bytes across its map tasks)."""
    with _lock:
        _shuffle["shuffle_host_bytes"] += int(nbytes)


def note_device_shuffle_fallback() -> None:
    """A device-resident exchange aborted (fault, overflow, capacity)
    and the stage re-ran through the file shuffle."""
    with _lock:
        _shuffle["shuffle_device_fallbacks"] += 1


def note_exchange_overlap() -> None:
    """One overlapped exchange ticket drained: its collective and
    partition split ran concurrently with a later task's fold."""
    with _lock:
        _shuffle["shuffle_device_overlap_exchanges"] += 1


def note_barrier_idle(ns: int) -> None:
    """Host-side fold-end -> first-collective-dispatch gap for one
    producer stage's device exchange (the barrier the overlapped
    exchange eliminates; clamped >= 0 by callers)."""
    with _lock:
        _shuffle["shuffle_barrier_idle_ns"] += int(ns)


def note_frame_compression(kind: str, saved: int) -> None:
    """io.compression.codec saved `saved` bytes on one frame: kind
    'worker' = a worker-pool control frame (task/result/heartbeat),
    'rss' = an RSS partition put."""
    key = ("worker_frame_compressed_bytes_saved" if kind == "worker"
           else "rss_put_compressed_bytes_saved")
    with _lock:
        _shuffle[key] += int(saved)


def note_worker_cpu(ns: int) -> None:
    """Child-process CPU (user+sys) reported in one result frame."""
    with _lock:
        _workers["worker_cpu_ns"] += int(ns)


def shuffle_stats() -> dict:
    with _lock:
        return dict(_shuffle)


def note_stage_program(cache_hit: bool) -> None:
    """A StageProgram lookup: built fresh (new stage fingerprint /
    capacity rung / dtype signature) or served from the process LRU."""
    with _lock:
        if cache_hit:
            _stage_loop["stage_loop_program_cache_hits"] += 1
        else:
            _stage_loop["stage_loop_programs_built"] += 1


def note_stage_loop_task(chunks: int, batches: int, rows: int,
                         regrows: int, dispatches_avoided: int) -> None:
    """One map task completed through the device-resident stage loop:
    `chunks` loop program calls folded `batches` batches / `rows` rows,
    growing the agg table `regrows` times; the staged per-batch path
    would have issued `dispatches_avoided` extra Python dispatches."""
    with _lock:
        _stage_loop["stage_loop_tasks"] += 1
        _stage_loop["stage_loop_calls"] += int(chunks)
        _stage_loop["stage_loop_chunks"] += int(chunks)
        _stage_loop["stage_loop_batches"] += int(batches)
        _stage_loop["stage_loop_rows"] += int(rows)
        _stage_loop["stage_loop_regrows"] += int(regrows)
        _stage_loop["stage_loop_staged_dispatches_avoided"] += \
            int(dispatches_avoided)


def note_stage_loop_fallback() -> None:
    """A stage-loop task aborted (ineligible chain, injected fault,
    overflow past the cap) and re-ran through the staged per-batch
    executor."""
    with _lock:
        _stage_loop["stage_loop_fallbacks"] += 1


def stage_loop_stats() -> dict:
    with _lock:
        return dict(_stage_loop)


def note_partial_agg_probe(rows: int, groups: int) -> None:
    """One cardinality probe over `rows` buffered rows that resolved
    `groups` distinct groups (the skip decision's evidence)."""
    with _lock:
        _agg["partial_agg_probe_rows"] += int(rows)
        _agg["partial_agg_probe_groups"] += int(groups)


def note_partial_agg_skip(switch_row: int, on_spill: bool = False) -> None:
    """One partial agg switched to pass-through after consuming
    `switch_row` rows; `on_spill` when memory pressure (not the ratio
    probe) forced the switch."""
    with _lock:
        _agg["partial_agg_skip_events"] += 1
        _agg["partial_agg_switch_rows"] += int(switch_row)
        if on_spill:
            _agg["partial_agg_spill_switches"] += 1


def note_partial_agg_rows(rows: int) -> None:
    """Rows streamed through the pass-through lane un-aggregated."""
    with _lock:
        _agg["partial_agg_skipped_rows"] += int(rows)


def agg_stats() -> dict:
    with _lock:
        return dict(_agg)


def note_scatter_lane(kind: str, lane: str) -> None:
    """One kernel-lane resolution: kind in hash/partition, lane in
    pallas/interpret/scatter (kernels/lane.py resolve)."""
    key = f"scatter_lane_{kind}_{lane}"
    with _lock:
        if key in _scatter_lane:
            _scatter_lane[key] += 1


def note_scatter_lane_decline() -> None:
    """A kernel-lane dispatch fell outside the kernel envelope (VMEM
    footprint) and degraded to the scatter formulation."""
    with _lock:
        _scatter_lane["scatter_lane_declines"] += 1


def note_scatter_lane_fault() -> None:
    """An injected pallas-kernel fault forced the scatter fallback."""
    with _lock:
        _scatter_lane["scatter_lane_fault_fallbacks"] += 1


def scatter_lane_stats() -> dict:
    with _lock:
        return dict(_scatter_lane)


def note_stream_epoch(wall_ns: int, rows: int = 0,
                      records: int = 0) -> None:
    """One committed micro-batch epoch: wall time, sink rows emitted,
    source records consumed."""
    with _lock:
        _stream["stream_epochs"] += 1
        _stream["stream_epoch_wall_ns"] += int(wall_ns)
        _stream["stream_rows"] += int(rows)
        _stream["stream_records"] += int(records)


def note_stream_late(records: int, side_rows: int = 0) -> None:
    """Late records seen past the watermark; side_rows counts the ones
    routed to the late-side output (policy `side`)."""
    with _lock:
        _stream["stream_late_records"] += int(records)
        _stream["stream_late_side_rows"] += int(side_rows)


def note_stream_checkpoint(nbytes: int = 0) -> None:
    with _lock:
        _stream["stream_checkpoints"] += 1
        _stream["stream_checkpoint_bytes"] += int(nbytes)


def note_stream_recovery(replayed_epochs: int = 0) -> None:
    """One recovery round: restore from the last committed manifest."""
    with _lock:
        _stream["stream_recoveries"] += 1
        _stream["stream_replayed_epochs"] += int(replayed_epochs)


def note_stream_sink(committed: int = 0, dup_skips: int = 0) -> None:
    """Exactly-once sink outcomes: first-wins commits vs replayed
    attempts skipped because the epoch manifest already existed."""
    with _lock:
        _stream["stream_sink_commits"] += int(committed)
        _stream["stream_sink_dup_skips"] += int(dup_skips)


def note_stream_gauges(watermark_delay_ms: Optional[int] = None,
                       window_state_bytes: Optional[int] = None,
                       source_lag_records: Optional[int] = None) -> None:
    """Latest-observation gauges (watermark delay, retained window-state
    bytes, unread source records)."""
    with _lock:
        if watermark_delay_ms is not None:
            _stream["stream_watermark_delay_ms_last"] = \
                int(watermark_delay_ms)
        if window_state_bytes is not None:
            _stream["stream_window_state_bytes_last"] = \
                int(window_state_bytes)
        if source_lag_records is not None:
            _stream["stream_source_lag_records_last"] = \
                int(source_lag_records)


def stream_stats() -> dict:
    with _lock:
        return dict(_stream)


def expr_stats() -> dict:
    """Expression-program counters; `expr_cache_hit_rate` is hits over
    cache resolutions (the recompile-guard's steady-state signal)."""
    with _lock:
        d = dict(_exprs)
    lookups = d["expr_programs_built"] + d["expr_program_cache_hits"]
    d["expr_cache_hit_rate"] = (
        d["expr_program_cache_hits"] / lookups if lookups else 0.0)
    return d


def pipeline_stats() -> dict:
    """Bucket + prefetch counters; `bucket_capacities` is the distinct
    ladder rungs observed (the static-shape universe jit kernels see)."""
    with _lock:
        d = dict(_pipeline)
        d["distinct_buckets"] = len(_bucket_caps)
        d["bucket_capacities"] = sorted(_bucket_caps)
        return d


def compile_report() -> dict:
    """Per-kernel compile stats + totals, JSON-ready."""
    with _lock:
        kernels = {}
        totals = {"calls": 0, "compiles": 0, "cache_hits": 0,
                  "compile_ns": 0}
        for kname, e in sorted(_kernels.items()):
            sigs = len(e["signatures"])
            kernels[kname] = {
                "calls": e["calls"], "compiles": e["compiles"],
                "cache_hits": e["cache_hits"],
                "compile_ns": e["compile_ns"],
                "dispatch_ns": e["dispatch_ns"],
                "distinct_signatures": sigs,
                "shape_churn": sigs > SHAPE_CHURN_THRESHOLD,
            }
            for k in totals:
                totals[k] += e[k]
        return {"kernels": kernels, "totals": totals}


def transfer_stats() -> dict:
    with _lock:
        return dict(_transfers)


def counter_families() -> Dict[str, Dict[str, int]]:
    """Every flat counter key, grouped by plane.  The single source the
    Prometheus exposition (bridge/profiling.py) and the history rollup
    (bridge/history.py) both iterate, so a new family cannot land in one
    surface and silently miss the other
    (tests/test_history_conformance.py).  Keys ending in `_last` are
    point-in-time gauges, everything else is a monotone counter."""
    with _lock:
        return {
            "transfers": dict(_transfers),
            "pipeline": dict(_pipeline),
            "exprs": dict(_exprs),
            "faults": dict(_faults),
            "shuffle": dict(_shuffle),
            "stage_loop": dict(_stage_loop),
            "agg": dict(_agg),
            "scatter_lane": dict(_scatter_lane),
            "stream": dict(_stream),
            "workers": dict(_workers),
            "speculation": dict(_speculation),
            "obs": dict(_obs),
            "cache": dict(_cache),
            "stats": dict(_stats),
            "aqe": dict(_aqe),
            "encoding": dict(_encoding),
            "fleet": dict(_fleet),
        }


def snapshot() -> dict:
    """Flat counter snapshot for before/after deltas (explain_analyze)."""
    rep = compile_report()
    flat = {"h2d_bytes": 0, "d2h_bytes": 0,
            "h2d_transfers": 0, "d2h_transfers": 0}
    flat.update(transfer_stats())
    ps = pipeline_stats()
    ps.pop("bucket_capacities", None)  # list: not delta-able
    flat.update(ps)
    es = expr_stats()
    es.pop("expr_cache_hit_rate", None)  # ratio: not delta-able
    flat.update(es)
    flat.update(fault_stats())
    flat.update(agg_stats())
    flat.update(shuffle_stats())
    flat.update(stage_loop_stats())
    flat.update(scatter_lane_stats())
    flat.update(stream_stats())
    flat.update(worker_stats())
    flat.update(speculation_stats())
    flat.update(obs_stats())
    flat.update(cache_stats())
    flat.update(statstore_stats())
    flat.update(aqe_stats())
    flat.update(encoding_stats())
    flat.update(fleet_stats())
    flat.update({f"total_{k}": v for k, v in rep["totals"].items()})
    return flat


def delta(before: dict) -> dict:
    now = snapshot()
    return {k: now.get(k, 0) - before.get(k, 0) for k in now}


def reset() -> None:
    """Test helper: clear all counters."""
    with _lock:
        _kernels.clear()
        for k in _transfers:
            _transfers[k] = 0
        for k in _pipeline:
            _pipeline[k] = 0
        for k in _exprs:
            _exprs[k] = 0
        for k in _faults:
            _faults[k] = 0
        for k in _agg:
            _agg[k] = 0
        for k in _shuffle:
            _shuffle[k] = 0
        for k in _stage_loop:
            _stage_loop[k] = 0
        for k in _scatter_lane:
            _scatter_lane[k] = 0
        for k in _stream:
            _stream[k] = 0
        for k in _workers:
            _workers[k] = 0
        for k in _speculation:
            _speculation[k] = 0
        for k in _obs:
            _obs[k] = 0
        for k in _cache:
            _cache[k] = 0
        for k in _stats:
            _stats[k] = 0
        for k in _aqe:
            _aqe[k] = 0
        for k in _encoding:
            _encoding[k] = 0
        for k in _fleet:
            _fleet[k] = 0
        _task_duration_ns.clear()
        _wave_wall_ns.clear()
        _bucket_caps.clear()
