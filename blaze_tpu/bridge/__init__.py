"""Host runtime bridge: task context, resource map, metrics, runtime.

Ref: auron-core (JVM core) + native-engine/auron (entry/runtime) layers.
"""

from blaze_tpu.bridge.context import (TaskContext, TaskKilledError,
                                      active_query, current_query,
                                      current_task, query_scope,
                                      set_current_task, task_scope)

__all__ = ["TaskContext", "TaskKilledError", "current_task",
           "set_current_task", "task_scope", "current_query",
           "active_query", "query_scope"]
