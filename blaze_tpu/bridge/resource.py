"""Process-wide resource map.

Parity: the JVM resource map the native side pulls shuffle-read block
iterators, broadcast byte arrays and cached build-side hash maps from
(ref: auron-core/.../jni/JniBridge.java getResource/putResource statics;
consumed at ipc_reader_exec.rs:144 and broadcast_join_exec.rs build-map
caching).  Values are arbitrary Python objects; `remove=True` gets preserve
the reference's take-once semantics for streaming resources.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_map: Dict[str, Any] = {}
_resolvers: Dict[str, Callable[[str], Any]] = {}


def put_resource(key: str, value: Any) -> None:
    with _lock:
        _map[key] = value


def get_resource(key: str, remove: bool = False) -> Optional[Any]:
    with _lock:
        if remove:
            found = _map.pop(key, None)
        else:
            found = _map.get(key)
        resolvers = list(_resolvers.items()) if found is None else ()
    if found is not None:
        return found
    # prefix resolvers let the host engine lazily materialize resources
    # (e.g. udf://<name> through the C-ABI udf_eval callback)
    for prefix, factory in resolvers:
        if key.startswith(prefix):
            return factory(key)
    return None


def register_resolver(prefix: str, factory: Callable[[str], Any]) -> None:
    """Lazy fallback for keys under `prefix` not present in the map."""
    with _lock:
        _resolvers[prefix] = factory


def unregister_resolver(prefix: str) -> None:
    with _lock:
        _resolvers.pop(prefix, None)


def get_or_create(key: str, factory: Callable[[], Any]) -> Any:
    """Cache for shared build artifacts (broadcast hash maps).

    The factory runs OUTSIDE the lock: building one broadcast map may
    recursively build another (nested broadcast joins), and holding the
    non-reentrant lock across the factory self-deadlocks.  Two racing
    threads may both build; setdefault keeps exactly one."""
    with _lock:
        if key in _map:
            return _map[key]
    value = factory()
    with _lock:
        return _map.setdefault(key, value)


def remove_resource(key: str) -> None:
    with _lock:
        _map.pop(key, None)


def clear_resources(prefix: str = "") -> None:
    with _lock:
        if not prefix:
            _map.clear()
        else:
            for k in [k for k in _map if k.startswith(prefix)]:
                del _map[k]
