"""Profiling / observability service.

Parity: the reference's optional native HTTP service (feature
`http-service`, ref auron/src/exec.rs:53-60; poem routes for CPU pprof
flamegraphs auron/src/http/pprof.rs:71 and jemalloc heap profiles
http/memory_profiling.rs:49).

TPU-native equivalents served over a stdlib HTTP endpoint:
  /status   — engine status: memory manager dump, device memory stats
  /metrics  — last collected metric trees (JSON)
  /trace    — start/stop a JAX profiler trace (XLA's own profiler is the
              pprof analog: it captures device + host timelines viewable
              in TensorBoard/Perfetto)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

_lock = threading.Lock()
_recent_metrics: List[dict] = []
_MAX_METRICS = 64


def record_metrics(tree: dict) -> None:
    """Runtimes push finalize()-time metric trees here (metrics.rs:22)."""
    with _lock:
        _recent_metrics.append(tree)
        del _recent_metrics[:-_MAX_METRICS]


def engine_status() -> dict:
    from blaze_tpu.memory import MemManager
    import jax
    status = {"mem_manager": MemManager.get().dump_status()}
    try:
        stats = jax.devices()[0].memory_stats() or {}
        status["device_memory"] = {k: v for k, v in stats.items()
                                   if isinstance(v, (int, float))}
    except Exception:
        status["device_memory"] = {}
    return status


class _Handler(BaseHTTPRequestHandler):
    _tracing = False

    def log_message(self, *args):
        pass

    def _send(self, code: int, body: str,
              ctype: str = "application/json"):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path == "/auron":
            from blaze_tpu.bridge import ui
            self._send(200, json.dumps(
                {"executions": ui.executions(),
                 "fallback_summary": ui.fallback_summary()}))
        elif self.path == "/auron.html":
            from blaze_tpu.bridge import ui
            self._send(200, ui.executions_html(), ctype="text/html")
        elif self.path == "/status":
            self._send(200, json.dumps(engine_status()))
        elif self.path == "/metrics":
            with _lock:
                self._send(200, json.dumps(_recent_metrics))
        elif self.path.startswith("/trace/start"):
            import jax
            out = "/tmp/blaze-tpu-trace"
            if "?" in self.path:
                out = self.path.split("?", 1)[1] or out
            try:
                jax.profiler.start_trace(out)
                _Handler._tracing = True
                self._send(200, json.dumps({"tracing": True, "dir": out}))
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
        elif self.path == "/trace/stop":
            import jax
            try:
                jax.profiler.stop_trace()
                _Handler._tracing = False
                self._send(200, json.dumps({"tracing": False}))
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
        else:
            self._send(404, json.dumps({"error": "unknown path",
                                        "paths": ["/status", "/metrics",
                                                  "/auron", "/auron.html",
                                                  "/trace/start",
                                                  "/trace/stop"]}))


_server: Optional[ThreadingHTTPServer] = None


def start_http_service(port: int = 0) -> int:
    """Start the service; returns the bound port (0 picks a free one)."""
    global _server
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="blaze-http-service")
    t.start()
    return _server.server_address[1]


def stop_http_service() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
