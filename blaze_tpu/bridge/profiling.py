"""Profiling / observability service.

Parity: the reference's optional native HTTP service (feature
`http-service`, ref auron/src/exec.rs:53-60; poem routes for CPU pprof
flamegraphs auron/src/http/pprof.rs:71 and jemalloc heap profiles
http/memory_profiling.rs:49).

TPU-native equivalents served over a stdlib HTTP endpoint:
  /status         — engine status: memory manager dump, device memory stats
  /metrics        — last collected metric trees (JSON)
  /metrics.prom   — Prometheus text exposition: XLA compile/cache-hit
                    counters per kernel, transfer volume, memory-manager
                    totals, per-operator aggregates
  /profile        — list of recorded query profiles (id + summary)
  /profile/<qid>  — full explain-analyze profile for one query (JSON)
  /trace/start?dir=<path>, /trace/stop — JAX profiler trace (XLA's own
                    profiler is the pprof analog: device + host timelines
                    viewable in TensorBoard/Perfetto)
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

_lock = threading.Lock()
_recent_metrics: List[dict] = []
_MAX_METRICS = 64
_profiles: Dict[str, dict] = {}
_profile_order: List[str] = []
_MAX_PROFILES = 64


def record_metrics(tree: dict) -> None:
    """Runtimes push finalize()-time metric trees here (metrics.rs:22)."""
    with _lock:
        _recent_metrics.append(tree)
        del _recent_metrics[:-_MAX_METRICS]


def recent_metrics() -> List[dict]:
    with _lock:
        return list(_recent_metrics)


def record_profile(query_id: str, profile: dict) -> None:
    """explain_analyze pushes finished query profiles here, keyed by the
    ui-store query id; served on /profile/<qid>."""
    with _lock:
        if query_id not in _profiles:
            _profile_order.append(query_id)
        _profiles[query_id] = profile
        while len(_profile_order) > _MAX_PROFILES:
            _profiles.pop(_profile_order.pop(0), None)


def get_profile(query_id: str) -> Optional[dict]:
    with _lock:
        return _profiles.get(query_id)


def list_profiles() -> List[dict]:
    with _lock:
        return [{"query_id": q,
                 "wall_ns": _profiles[q].get("wall_ns"),
                 "output_rows": (_profiles[q].get("tree") or {})
                 .get("values", {}).get("output_rows")}
                for q in _profile_order]


def _prom_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text() -> str:
    """Prometheus text exposition (version 0.0.4) of the engine gauges:
    XLA compile accounting, host<->device transfer volume, memory-manager
    spill totals, and per-operator aggregates over the recent trees."""
    from blaze_tpu.bridge import xla_stats
    from blaze_tpu.memory import MemManager
    lines: List[str] = []

    def emit(name, value, help_=None, labels=None, seen=set()):
        if help_ and name not in seen:
            seen.add(name)
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
        lab = ""
        if labels:
            lab = "{" + ",".join(
                f'{k}="{_prom_escape(str(v))}"'
                for k, v in sorted(labels.items())) + "}"
        lines.append(f"{name}{lab} {int(value)}")

    rep = xla_stats.compile_report()
    for kname, e in rep["kernels"].items():
        lab = {"kernel": kname}
        emit("blaze_xla_compiles_total", e["compiles"],
             "XLA compilations per kernel signature", lab)
        emit("blaze_xla_cache_hits_total", e["cache_hits"],
             "jit dispatches served from the compile cache", lab)
        emit("blaze_xla_compile_ns_total", e["compile_ns"],
             "nanoseconds spent compiling", lab)
        emit("blaze_xla_distinct_signatures", e["distinct_signatures"],
             "distinct arg signatures seen (churn when high)", lab)
    t = xla_stats.transfer_stats()
    emit("blaze_h2d_bytes_total", t["h2d_bytes"],
         "host-to-device bytes at batch placement")
    emit("blaze_d2h_bytes_total", t["d2h_bytes"],
         "device-to-host bytes (Arrow export, host fetches)")
    for k, v in xla_stats.stage_loop_stats().items():
        # device-resident stage loop (runtime/loop.py): engagement,
        # amortized dispatches, wholesale fallbacks
        emit(f"blaze_{k}_total", v,
             "device-resident stage loop counter")
    for k, v in xla_stats.stream_stats().items():
        # streaming runtime (streaming/executor.py): epochs, watermark
        # delay, window-state bytes, checkpoint/recovery/sink outcomes;
        # *_last keys are point-in-time gauges, the rest are totals
        if k.endswith("_last"):
            emit(f"blaze_{k[:-5]}", v, "streaming runtime gauge")
        else:
            emit(f"blaze_{k}_total", v, "streaming runtime counter")
    for k, v in xla_stats.worker_stats().items():
        # process-isolated worker pool (parallel/workers.py): spawns,
        # shipped tasks, crash/hang/blacklist/cancel supervision events
        emit(f"blaze_{k}_total", v, "worker pool counter")
    for k, v in xla_stats.speculation_stats().items():
        # speculative execution (bridge/tasks.py): hedged waves/attempts,
        # first-wins outcomes, rejected loser commits, forced races
        emit(f"blaze_{k}_total", v, "speculative execution counter")
    mm = MemManager.get()
    emit("blaze_mem_spill_count_total", mm.total_spill_count,
         "memory-manager spills")
    emit("blaze_mem_spilled_bytes_total", mm.total_spilled_bytes,
         "bytes released by spills")
    emit("blaze_mem_peak_used_bytes", mm.peak_used,
         "peak retained bytes across consumers")

    per_op: Dict[str, Dict[str, int]] = {}

    def fold(node):
        op = node.get("name") or "unknown"
        agg = per_op.setdefault(op, {})
        for k, v in node.get("values", {}).items():
            agg[k] = agg.get(k, 0) + int(v)
        for c in node.get("children", ()):
            fold(c)

    with _lock:
        for tree in _recent_metrics:
            fold(tree)
    for op, vals in sorted(per_op.items()):
        for metric in ("output_rows", "output_batches",
                       "elapsed_compute_ns", "spilled_bytes", "io_bytes"):
            if metric in vals:
                emit(f"blaze_operator_{metric}_total", vals[metric],
                     f"per-operator {metric} over recent metric trees",
                     {"operator": op})
    return "\n".join(lines) + "\n"


def engine_status() -> dict:
    from blaze_tpu.memory import MemManager
    import jax
    status = {"mem_manager": MemManager.get().dump_status()}
    try:
        stats = jax.devices()[0].memory_stats() or {}
        status["device_memory"] = {k: v for k, v in stats.items()
                                   if isinstance(v, (int, float))}
    except Exception:
        status["device_memory"] = {}
    return status


class _Handler(BaseHTTPRequestHandler):
    _tracing = False

    def log_message(self, *args):
        pass

    def _send(self, code: int, body: str,
              ctype: str = "application/json"):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        route = parsed.path
        if route == "/auron":
            from blaze_tpu.bridge import ui
            self._send(200, json.dumps(
                {"executions": ui.executions(),
                 "fallback_summary": ui.fallback_summary()}))
        elif route == "/auron.html":
            from blaze_tpu.bridge import ui
            self._send(200, ui.executions_html(), ctype="text/html")
        elif route == "/status":
            self._send(200, json.dumps(engine_status()))
        elif route == "/metrics":
            with _lock:
                self._send(200, json.dumps(_recent_metrics))
        elif route == "/metrics.prom":
            self._send(200, prometheus_text(),
                       ctype="text/plain; version=0.0.4")
        elif route == "/profile":
            self._send(200, json.dumps(list_profiles()))
        elif route.startswith("/profile/"):
            qid = urllib.parse.unquote(route[len("/profile/"):])
            profile = get_profile(qid)
            if profile is None:
                self._send(404, json.dumps(
                    {"error": f"no profile for {qid!r}",
                     "known": [p["query_id"] for p in list_profiles()]}))
            else:
                self._send(200, json.dumps(profile))
        elif route == "/trace/start":
            import jax
            # the trace dir arrives as ?dir=<path> (query STRING, not the
            # raw text after '?' — that produced directories literally
            # named "dir=/tmp/x")
            # keep_blank_values so a stray "?/tmp/x" (no '=') surfaces as
            # an unknown key instead of silently starting a default trace
            params = urllib.parse.parse_qs(parsed.query,
                                           keep_blank_values=True)
            out = params.get("dir", ["/tmp/blaze-tpu-trace"])[0]
            bad_keys = set(params) - {"dir"}
            if bad_keys:
                self._send(400, json.dumps(
                    {"error": f"unknown query params {sorted(bad_keys)}; "
                              f"expected ?dir=<path>"}))
                return
            if not out or "\x00" in out or not out.startswith("/"):
                self._send(400, json.dumps(
                    {"error": "trace dir must be an absolute path",
                     "dir": out}))
                return
            try:
                jax.profiler.start_trace(out)
                _Handler._tracing = True
                self._send(200, json.dumps({"tracing": True, "dir": out}))
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
        elif route == "/trace/stop":
            import jax
            try:
                jax.profiler.stop_trace()
                _Handler._tracing = False
                self._send(200, json.dumps({"tracing": False}))
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
        elif route == "/serving":
            from blaze_tpu.parallel.workers import pool_health
            from blaze_tpu.serving import serving_stats
            self._send(200, json.dumps({"services": serving_stats(),
                                        "workers": pool_health()}))
        elif route == "/serving/cancel":
            from blaze_tpu.serving import cancel_query
            params = urllib.parse.parse_qs(parsed.query,
                                           keep_blank_values=True)
            qid = params.get("qid", [""])[0]
            if not qid:
                self._send(400, json.dumps(
                    {"error": "expected ?qid=<query id>"}))
                return
            self._send(200, json.dumps({"query_id": qid,
                                        "cancelled": cancel_query(qid)}))
        else:
            self._send(404, json.dumps({"error": "unknown path",
                                        "paths": ["/status", "/metrics",
                                                  "/metrics.prom",
                                                  "/profile",
                                                  "/profile/<qid>",
                                                  "/auron", "/auron.html",
                                                  "/trace/start",
                                                  "/trace/stop",
                                                  "/serving",
                                                  "/serving/cancel"]}))


_server: Optional[ThreadingHTTPServer] = None


def start_http_service(port: int = 0) -> int:
    """Start the service; returns the bound port (0 picks a free one)."""
    global _server
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="blaze-http-service")
    t.start()
    return _server.server_address[1]


def stop_http_service() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
