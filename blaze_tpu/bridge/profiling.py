"""Profiling / observability service.

Parity: the reference's optional native HTTP service (feature
`http-service`, ref auron/src/exec.rs:53-60; poem routes for CPU pprof
flamegraphs auron/src/http/pprof.rs:71 and jemalloc heap profiles
http/memory_profiling.rs:49).

TPU-native equivalents served over a stdlib HTTP endpoint:
  /status         — engine status: memory manager dump, device memory stats
  /metrics        — last collected metric trees (JSON)
  /metrics.prom   — Prometheus text exposition: XLA compile/cache-hit
                    counters per kernel, transfer volume, memory-manager
                    totals, per-operator aggregates
  /profile        — list of recorded query profiles (id + summary)
  /profile/<qid>  — full explain-analyze profile for one query (JSON)
  /query/<qid>/timeline — Chrome-trace-event JSON (Perfetto-loadable)
                    of the query's stitched span trace: one track per
                    worker / device / stream epoch, plus a per-query
                    resource-attribution block
  /trace/start?dir=<path>, /trace/stop — JAX profiler trace (XLA's own
                    profiler is the pprof analog: device + host timelines
                    viewable in TensorBoard/Perfetto)
  /history        — replayed per-query summaries from the persistent
                    event log (bridge/history.py); /history/<qid> is one
                    query's full summary (final status, metric tree,
                    attribution, device ledger), /history/rollup the
                    fleet aggregate keyed by tenant and stage type

The query-profile store is a bounded LRU (auron.tpu.profile.maxEntries;
get_profile touches) so long-lived serving processes don't grow it
without limit; evictions count as obs_profile_evictions.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

_lock = threading.Lock()
_recent_metrics: List[dict] = []
_MAX_METRICS = 64
_profiles: Dict[str, dict] = {}
_profile_order: List[str] = []
_MAX_PROFILES = 64


def record_metrics(tree: dict) -> None:
    """Runtimes push finalize()-time metric trees here (metrics.rs:22)."""
    with _lock:
        _recent_metrics.append(tree)
        del _recent_metrics[:-_MAX_METRICS]


def recent_metrics() -> List[dict]:
    with _lock:
        return list(_recent_metrics)


def _profile_cap() -> int:
    try:
        from blaze_tpu import config
        return max(1, config.PROFILE_STORE_MAX.get())
    except Exception:
        return _MAX_PROFILES


def record_profile(query_id: str, profile: dict) -> None:
    """explain_analyze pushes finished query profiles here, keyed by the
    ui-store query id; served on /profile/<qid>.  The store is an LRU
    bounded by auron.tpu.profile.maxEntries — record and get_profile
    both refresh recency; evictions are counted in xla_stats."""
    cap = _profile_cap()
    evicted = 0
    with _lock:
        if query_id in _profiles:
            _profile_order.remove(query_id)
        _profile_order.append(query_id)
        _profiles[query_id] = profile
        while len(_profile_order) > cap:
            _profiles.pop(_profile_order.pop(0), None)
            evicted += 1
    if evicted:
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_obs(profile_evictions=evicted)


def get_profile(query_id: str) -> Optional[dict]:
    with _lock:
        p = _profiles.get(query_id)
        if p is not None:  # LRU touch
            _profile_order.remove(query_id)
            _profile_order.append(query_id)
        return p


def list_profiles() -> List[dict]:
    with _lock:
        return [{"query_id": q,
                 "wall_ns": _profiles[q].get("wall_ns"),
                 "output_rows": (_profiles[q].get("tree") or {})
                 .get("values", {}).get("output_rows")}
                for q in _profile_order]


def _prom_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text() -> str:
    """Prometheus text exposition (version 0.0.4) of the engine gauges:
    XLA compile accounting, host<->device transfer volume, memory-manager
    spill totals, and per-operator aggregates over the recent trees."""
    from blaze_tpu.bridge import xla_stats
    from blaze_tpu.memory import MemManager
    lines: List[str] = []
    # per-SCRAPE header dedup — a default-arg set here persisted across
    # calls, so every scrape after the first silently dropped all
    # HELP/TYPE headers (tests/test_metric_conformance.py pins this)
    seen: set = set()

    def emit(name, value, help_=None, labels=None):
        if help_ and name not in seen:
            seen.add(name)
            # *_total families are monotone counters, everything else a
            # point-in-time gauge — Prometheus rate() needs the former
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
        lab = ""
        if labels:
            lab = "{" + ",".join(
                f'{k}="{_prom_escape(str(v))}"'
                for k, v in sorted(labels.items())) + "}"
        lines.append(f"{name}{lab} {int(value)}")

    rep = xla_stats.compile_report()
    for kname, e in rep["kernels"].items():
        lab = {"kernel": kname}
        emit("blaze_xla_compiles_total", e["compiles"],
             "XLA compilations per kernel signature", lab)
        emit("blaze_xla_cache_hits_total", e["cache_hits"],
             "jit dispatches served from the compile cache", lab)
        emit("blaze_xla_compile_ns_total", e["compile_ns"],
             "nanoseconds spent compiling", lab)
        emit("blaze_xla_distinct_signatures", e["distinct_signatures"],
             "distinct arg signatures seen (churn when high)", lab)
    # every flat counter plane, from the one shared family registry (the
    # history rollup iterates the same source, so the two surfaces
    # cannot drift apart); *_last keys are point-in-time gauges
    fam_help = {
        "transfers": "host<->device transfer",
        "pipeline": "batch-shaping / IO-pipeline",
        "exprs": "whole-stage expression program",
        "faults": "fault-tolerance (retries, lineage recovery)",
        "shuffle": "exchange transport",
        "stage_loop": "device-resident stage loop",
        "agg": "adaptive partial aggregation",
        "scatter_lane": "pallas kernel-lane resolution",
        "stream": "streaming runtime",
        "workers": "worker pool supervision",
        "speculation": "speculative execution",
        "obs": "observability plane",
        "cache": "cross-query work sharing",
        "stats": "statistics feedback plane",
        "fleet": "replicated serving fleet",
    }
    families = xla_stats.counter_families()
    for fam in sorted(families):
        label = fam_help.get(fam, fam)
        for k in sorted(families[fam]):
            v = families[fam][k]
            if k.endswith("_last"):
                emit(f"blaze_{k[:-5]}", v, f"{label} gauge")
            else:
                emit(f"blaze_{k}_total", v, f"{label} counter")

    def emit_histogram(name, hist, help_, labels=None):
        # real Prometheus histogram exposition (cumulative le buckets +
        # _sum/_count), not the gauge families above
        lab_items = sorted((labels or {}).items())

        def fmt(extra):
            items = lab_items + sorted(extra.items())
            if not items:
                return ""
            return "{" + ",".join(
                f'{k}="{_prom_escape(str(v))}"' for k, v in items) + "}"

        if name not in seen:
            seen.add(name)
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
        for le, count in hist["buckets"]:
            lines.append(f"{name}_bucket{fmt({'le': le})} {count}")
        lines.append(f"{name}_bucket{fmt({'le': '+Inf'})} {hist['count']}")
        lines.append(f"{name}_sum{fmt({})} {hist['sum']:.6f}")
        lines.append(f"{name}_count{fmt({})} {hist['count']}")

    hists = xla_stats.latency_histograms()
    emit_histogram("blaze_task_duration_seconds",
                   hists["task_duration_seconds"],
                   "successful task-attempt wall time")
    emit_histogram("blaze_wave_wall_seconds", hists["wave_wall_seconds"],
                   "run_tasks wave wall, submit to last result")
    try:
        from blaze_tpu.serving.service import tenant_wall_samples
        for tenant, samples in sorted(tenant_wall_samples().items()):
            emit_histogram(
                "blaze_tenant_query_wall_seconds",
                xla_stats._histogram([int(s * 1e9) for s in samples]),
                "per-tenant completed-query wall time (attribution)",
                {"tenant": tenant})
    except Exception:
        pass  # serving layer not in use
    mm = MemManager.get()
    emit("blaze_mem_spill_count_total", mm.total_spill_count,
         "memory-manager spills")
    emit("blaze_mem_spilled_bytes_total", mm.total_spilled_bytes,
         "bytes released by spills")
    emit("blaze_mem_peak_used_bytes", mm.peak_used,
         "peak retained bytes across consumers")

    per_op: Dict[str, Dict[str, int]] = {}

    def fold(node):
        op = node.get("name") or "unknown"
        agg = per_op.setdefault(op, {})
        for k, v in node.get("values", {}).items():
            agg[k] = agg.get(k, 0) + int(v)
        for c in node.get("children", ()):
            fold(c)

    with _lock:
        for tree in _recent_metrics:
            fold(tree)
    for op, vals in sorted(per_op.items()):
        for metric in ("output_rows", "output_batches",
                       "elapsed_compute_ns", "spilled_bytes", "io_bytes"):
            if metric in vals:
                emit(f"blaze_operator_{metric}_total", vals[metric],
                     f"per-operator {metric} over recent metric trees",
                     {"operator": op})
    return "\n".join(lines) + "\n"


def query_timeline(query_id: str) -> Optional[dict]:
    """Chrome-trace-event JSON for one query's stitched span trace.

    Loads directly in Perfetto / chrome://tracing: a top-level object
    with `traceEvents` (complete "X" events for spans, instant "i"
    events for markers), one process track per origin (driver, each
    worker slot) and dedicated tracks for device dispatches and each
    stream epoch.  A per-query resource-attribution block (task CPU
    seconds, shuffle bytes by tier, device dispatches, spill bytes,
    speculation hedge cost) rides as a top-level key — extra keys are
    legal in the trace-event object format.  Returns None when no spans
    name the query."""
    from blaze_tpu.bridge import tracing
    spans = tracing.spans_for_query(query_id)
    if not spans:
        return None

    _DRIVER_PID, _WORKER_PID0 = 1, 100
    events: List[dict] = []
    tids: Dict[tuple, int] = {}
    procs: Dict[int, str] = {_DRIVER_PID: "driver"}

    def tid_for(pid, key, label):
        t = tids.get((pid, key))
        if t is None:
            t = tids[(pid, key)] = len(tids) + 1
            events.append({"ph": "M", "pid": pid, "tid": t,
                           "name": "thread_name",
                           "args": {"name": label}})
        return t

    attribution = {"task_cpu_seconds": 0.0, "worker_task_seconds": 0.0,
                   "device_dispatches": 0,
                   "spill_bytes": 0, "speculation_attempts": 0,
                   "speculation_hedge_seconds": 0.0,
                   "shuffle_bytes_by_tier": {"device": 0, "rss": 0,
                                             "file": 0}, "span_count": 0}
    profile = get_profile(query_id)
    if profile:
        x = profile.get("xla") or {}
        attribution["shuffle_bytes_by_tier"]["device"] = int(
            x.get("shuffle_device_bytes", 0))
        attribution["shuffle_bytes_by_tier"]["file"] = int(
            x.get("shuffle_host_bytes", 0))

    for r in spans:
        name = r.get("name", "?")
        attrs = r.get("attrs") or {}
        ctx = r.get("ctx") or {}
        worker = r.get("worker")
        if worker is not None:
            try:
                pid = _WORKER_PID0 + int(worker)
            except (TypeError, ValueError):
                pid = _WORKER_PID0 + (hash(str(worker)) % 97)
            procs.setdefault(pid, f"worker-{worker}")
            tid = tid_for(pid, r.get("thread", "main"),
                          str(r.get("thread", "main")))
        elif name in ("device_exchange", "stage_loop_chunk",
                      "xla_compile"):
            pid = _DRIVER_PID
            tid = tid_for(pid, "device", "device")
        elif name in ("stream_epoch", "stream_recovery"):
            pid = _DRIVER_PID
            ep = attrs.get("epoch", ctx.get("epoch", 0)) or 0
            tid = tid_for(pid, ("epoch", ep), f"epoch-{ep}")
        else:
            pid = _DRIVER_PID
            tid = tid_for(pid, r.get("thread", "main"),
                          str(r.get("thread", "main")))
        args = dict(ctx)
        args.update(attrs)
        if "sid" in r:
            args["sid"] = r["sid"]
        if "parent" in r:
            args["parent"] = r["parent"]
        ev = {"name": name, "pid": pid, "tid": tid,
              "ts": r.get("t0_ns", 0) / 1e3, "args": args}
        if r.get("dur_ns", 0) > 0:
            ev["ph"] = "X"
            ev["dur"] = r["dur_ns"] / 1e3
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)

        attribution["span_count"] += 1
        dur_s = r.get("dur_ns", 0) / 1e9
        if name == "task_attempt":
            # driver-side attempt wall; child-process execution is the
            # separate worker_task_seconds (summing both double-counts)
            attribution["task_cpu_seconds"] += dur_s
            if attrs.get("speculative"):
                attribution["speculation_hedge_seconds"] += dur_s
        elif name == "worker_task":
            attribution["worker_task_seconds"] += dur_s
        elif name in ("device_exchange", "stage_loop_chunk"):
            attribution["device_dispatches"] += 1
        elif name == "mem_spill":
            attribution["spill_bytes"] += int(attrs.get("bytes", 0) or 0)
        elif name == "speculation_attempt":
            attribution["speculation_attempts"] += 1
        elif name == "rss_exchange":
            attribution["shuffle_bytes_by_tier"]["rss"] += int(
                attrs.get("nbytes", 0) or 0)

    for pid, pname in procs.items():
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name", "args": {"name": pname}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "query_id": str(query_id), "attribution": attribution}


def engine_status() -> dict:
    from blaze_tpu.memory import MemManager
    import jax
    status = {"mem_manager": MemManager.get().dump_status()}
    try:
        stats = jax.devices()[0].memory_stats() or {}
        status["device_memory"] = {k: v for k, v in stats.items()
                                   if isinstance(v, (int, float))}
    except Exception:
        status["device_memory"] = {}
    return status


#: every GET route the service answers, placeholders included; the 404
#: payload and the HTTP conformance sweep
#: (tests/test_http_conformance.py) both read this — a handler branch
#: without a row here, or vice versa, fails the sweep.
ROUTES = (
    "/status", "/metrics", "/metrics.prom",
    "/profile", "/profile/<qid>",
    "/query/<qid>/timeline", "/query/<qid>/bottleneck",
    "/query/<qid>/progress",
    "/auron", "/auron.html",
    "/trace/start", "/trace/stop",
    "/history", "/history/<qid>", "/history/rollup",
    "/stats", "/stats/<fingerprint>",
    "/progress",
    "/serving", "/serving/cancel",
    "/fleet",
)


class _Handler(BaseHTTPRequestHandler):
    _tracing = False

    def log_message(self, *args):
        pass

    def _send(self, code: int, body: str,
              ctype: str = "application/json"):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        route = parsed.path
        if route == "/auron":
            from blaze_tpu.bridge import ui
            self._send(200, json.dumps(
                {"executions": ui.executions(),
                 "fallback_summary": ui.fallback_summary()}))
        elif route == "/auron.html":
            from blaze_tpu.bridge import ui
            self._send(200, ui.executions_html(), ctype="text/html")
        elif route == "/status":
            self._send(200, json.dumps(engine_status()))
        elif route == "/metrics":
            with _lock:
                self._send(200, json.dumps(_recent_metrics))
        elif route == "/metrics.prom":
            self._send(200, prometheus_text(),
                       ctype="text/plain; version=0.0.4")
        elif route == "/profile":
            self._send(200, json.dumps(list_profiles()))
        elif route.startswith("/profile/"):
            qid = urllib.parse.unquote(route[len("/profile/"):])
            profile = get_profile(qid)
            if profile is None:
                self._send(404, json.dumps(
                    {"error": f"no profile for {qid!r}",
                     "known": [p["query_id"] for p in list_profiles()]}))
            else:
                self._send(200, json.dumps(profile))
        elif route.startswith("/query/") and route.endswith("/timeline"):
            qid = urllib.parse.unquote(
                route[len("/query/"):-len("/timeline")])
            timeline = query_timeline(qid)
            if timeline is None:
                self._send(404, json.dumps(
                    {"error": f"no spans recorded for query {qid!r} "
                              f"(is tracing enabled?)"}))
            else:
                self._send(200, json.dumps(timeline, default=str))
        elif route.startswith("/query/") and route.endswith("/bottleneck"):
            from blaze_tpu.bridge import critical_path, tracing
            qid = urllib.parse.unquote(
                route[len("/query/"):-len("/bottleneck")])
            report = None
            spans = tracing.spans_for_query(qid)
            if spans:
                report = critical_path.bottleneck_report(spans)
            if report is None:
                # the live buffer may have rotated; the history finished
                # event keeps the report alongside the device ledger
                from blaze_tpu.bridge.history import HistoryStore
                summary = HistoryStore().summary(qid)
                if summary:
                    report = summary.get("bottleneck")
            if report is None:
                self._send(404, json.dumps(
                    {"error": f"no bottleneck report for query {qid!r} "
                              f"(is tracing or history enabled?)"}))
            else:
                self._send(200, json.dumps(report, sort_keys=True))
        elif route.startswith("/query/") and route.endswith("/progress"):
            from blaze_tpu.serving import progress as progress_mod
            qid = urllib.parse.unquote(
                route[len("/query/"):-len("/progress")])
            p = progress_mod.progress(qid)
            if p is None:
                self._send(404, json.dumps(
                    {"error": f"no progress for query {qid!r} "
                              f"(is auron.tpu.stats.enable on?)",
                     "live": progress_mod.live()}))
            else:
                self._send(200, json.dumps(p, sort_keys=True))
        elif route == "/progress":
            from blaze_tpu.serving import progress as progress_mod
            self._send(200, json.dumps(progress_mod.snapshot_all(),
                                       sort_keys=True))
        elif route == "/stats":
            from blaze_tpu.plan.statstore import StatStore
            self._send(200, json.dumps(StatStore().summary(),
                                       sort_keys=True))
        elif route.startswith("/stats/"):
            from blaze_tpu.plan.statstore import StatStore
            fp = urllib.parse.unquote(route[len("/stats/"):])
            store = StatStore()
            rec = store.record(fp)
            if rec is None:
                self._send(404, json.dumps(
                    {"error": f"no statistics for fingerprint {fp!r}",
                     "known": store.fingerprints()}))
            else:
                self._send(200, json.dumps(rec, sort_keys=True))
        elif route == "/trace/start":
            import jax
            # the trace dir arrives as ?dir=<path> (query STRING, not the
            # raw text after '?' — that produced directories literally
            # named "dir=/tmp/x")
            # keep_blank_values so a stray "?/tmp/x" (no '=') surfaces as
            # an unknown key instead of silently starting a default trace
            params = urllib.parse.parse_qs(parsed.query,
                                           keep_blank_values=True)
            out = params.get("dir", ["/tmp/blaze-tpu-trace"])[0]
            bad_keys = set(params) - {"dir"}
            if bad_keys:
                self._send(400, json.dumps(
                    {"error": f"unknown query params {sorted(bad_keys)}; "
                              f"expected ?dir=<path>"}))
                return
            if not out or "\x00" in out or not out.startswith("/"):
                self._send(400, json.dumps(
                    {"error": "trace dir must be an absolute path",
                     "dir": out}))
                return
            try:
                jax.profiler.start_trace(out)
                _Handler._tracing = True
                self._send(200, json.dumps({"tracing": True, "dir": out}))
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
        elif route == "/trace/stop":
            import jax
            try:
                jax.profiler.stop_trace()
                _Handler._tracing = False
                self._send(200, json.dumps({"tracing": False}))
            except Exception as e:
                self._send(500, json.dumps({"error": str(e)}))
        elif route == "/history":
            from blaze_tpu.bridge.history import HistoryStore
            self._send(200, json.dumps(HistoryStore().summaries(),
                                       sort_keys=True))
        elif route == "/history/rollup":
            from blaze_tpu.bridge.history import HistoryStore
            self._send(200, json.dumps(HistoryStore().rollup(),
                                       sort_keys=True))
        elif route.startswith("/history/"):
            from blaze_tpu.bridge.history import HistoryStore
            qid = urllib.parse.unquote(route[len("/history/"):])
            store = HistoryStore()
            summary = store.summary(qid)
            if summary is None:
                self._send(404, json.dumps(
                    {"error": f"no history for query {qid!r} "
                              f"(is auron.tpu.history.enable on?)",
                     "known": store.query_ids()}))
            else:
                self._send(200, json.dumps(summary, sort_keys=True))
        elif route == "/serving":
            from blaze_tpu.parallel.workers import pool_health
            from blaze_tpu.serving import serving_stats
            self._send(200, json.dumps({"services": serving_stats(),
                                        "workers": pool_health()}))
        elif route == "/fleet":
            # fleet health: every live router's replica table (state,
            # heartbeat age, affinity hit-rate) + the fleet counter
            # family.  Empty-but-200 when no fleet is running, so the
            # conformance sweep and dashboards can always scrape it.
            from blaze_tpu.fleet.router import fleet_health
            self._send(200, json.dumps(fleet_health(), sort_keys=True,
                                       default=str))
        elif route == "/serving/cancel":
            from blaze_tpu.serving import cancel_query
            params = urllib.parse.parse_qs(parsed.query,
                                           keep_blank_values=True)
            qid = params.get("qid", [""])[0]
            if not qid:
                self._send(400, json.dumps(
                    {"error": "expected ?qid=<query id>"}))
                return
            self._send(200, json.dumps({"query_id": qid,
                                        "cancelled": cancel_query(qid)}))
        else:
            self._send(404, json.dumps({"error": "unknown path",
                                        "paths": list(ROUTES)}))


_server: Optional[ThreadingHTTPServer] = None


def start_http_service(port: int = 0) -> int:
    """Start the service; returns the bound port (0 picks a free one)."""
    global _server
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="blaze-http-service")
    t.start()
    return _server.server_address[1]


def stop_http_service() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
