"""Pluggable engine adaptor — the AuronAdaptor SPI analog.

Parity: `auron-core/src/main/java/org/apache/auron/jni/AuronAdaptor.java`
(abstract engine surface: loadAuronLib, getJVMTotalMemoryLimited,
isTaskRunning, getDirectWriteSpillToDiskFile, get/setThreadContext,
getOnHeapSpillManager, getAuronConfiguration, getAuronUDFWrapperContext,
getEngineName) and its ServiceLoader discovery
(`AuronAdaptor.getInstance()` iterating `AuronAdaptorProvider`s).

Each host engine (Spark-shim, Flink-shim, embedded tests, a future
service front-end) implements ONE `EngineAdaptor` instead of installing
loose module-level callbacks; `set_adaptor()` wires every existing hook
point (conf provider, task probe, spill factory, UDF resolver, FS
fallback) through it.  The function-address path used by the C ABI
(`host_callbacks.install_from_addresses`) keeps working — it builds a
`CallbackAdaptor` under the hood, so the JNI/C boundary and the Python
SPI share one installation surface.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_instance: Optional["EngineAdaptor"] = None
_providers: Dict[str, Callable[[], "EngineAdaptor"]] = {}


class EngineAdaptor:
    """Engine-integration surface.  Subclass and override what the host
    engine provides; every default is the reference's documented default
    (AuronAdaptor.java: memory unlimited, task always running, disabled
    on-heap spill manager)."""

    #: engine name (AuronAdaptor.getEngineName: "Spark", "Flink", ...)
    name = "host"

    # -- native library ----------------------------------------------------
    def load_native_lib(self) -> None:
        """loadAuronLib analog: make the native kernels available.  The
        default loads the C++ host-bridge/kernel libraries lazily."""
        from blaze_tpu.bridge import native
        native.get_host_bridge()

    # -- memory ------------------------------------------------------------
    def total_memory_limited(self) -> int:
        """getJVMTotalMemoryLimited: engine memory cap in bytes."""
        return (1 << 63) - 1

    def on_heap_spill_factory(self):
        """getOnHeapSpillManager analog: a factory producing host-memory
        spill objects, or None for the disabled manager."""
        return None

    # -- task lifecycle ----------------------------------------------------
    def is_task_running(self, stage_id: int, partition_id: int) -> bool:
        """isTaskRunning: False aborts native computation cooperatively."""
        return True

    def get_thread_context(self) -> Any:
        """getThreadContext (Spark: TaskContext of the current thread)."""
        from blaze_tpu.bridge import context
        return context.current_task()

    def set_thread_context(self, ctx: Any) -> None:
        """setThreadContext: propagate the engine task context into
        worker threads the runtime spawns."""
        from blaze_tpu.bridge import context
        context.set_current_task(ctx)

    # -- spill -------------------------------------------------------------
    def direct_write_spill_file(self) -> str:
        """getDirectWriteSpillToDiskFile: absolute path of a fresh temp
        file for direct-write spills."""
        from blaze_tpu import config
        dirs = config.SPILL_DIRS.get() if hasattr(config, "SPILL_DIRS") \
            else None
        base = (dirs.split(",")[0] if isinstance(dirs, str) and dirs
                else tempfile.gettempdir())
        os.makedirs(base, exist_ok=True)
        fd, path = tempfile.mkstemp(prefix="auron_spill_", dir=base)
        os.close(fd)
        return path

    # -- configuration -----------------------------------------------------
    def conf_get(self, key: str) -> Optional[str]:
        """getAuronConfiguration analog: resolve one engine conf key, or
        None when unset (lazily memoized by config.set_host_conf_provider
        like the reference's define_conf! proxies)."""
        return None

    # -- UDFs --------------------------------------------------------------
    def udf_wrapper_context(self, name: str) -> Optional[Callable]:
        """getAuronUDFWrapperContext: resolve a host evaluator for a
        wrapped UDF by name, or None when unknown."""
        return None


def register_provider(name: str,
                      factory: Callable[[], EngineAdaptor]) -> None:
    """ServiceLoader-registration analog: front-ends register a factory
    at import time; `get_adaptor()` instantiates the one selected by
    `BLAZE_TPU_ADAPTOR` (or the first registered)."""
    with _lock:
        _providers[name] = factory


def set_adaptor(adaptor: Optional[EngineAdaptor]) -> None:
    """Install `adaptor` as THE engine integration: wires the conf
    provider, task probe, spill factory, and UDF resolver hook points
    through it.  None uninstalls (tests)."""
    global _instance
    from blaze_tpu import config
    from blaze_tpu.bridge import context, resource
    from blaze_tpu.memory import spill as spill_mod
    with _lock:
        _instance = adaptor
    if adaptor is None:
        config.set_host_conf_provider(None)
        context.set_host_task_probe(None)
        resource.unregister_resolver("udf://")
        spill_mod.set_host_spill_factory(None)
        return
    config.set_host_conf_provider(adaptor.conf_get)
    context.set_host_task_probe(adaptor.is_task_running)
    # unconditional: switching to an adaptor WITHOUT a spill factory
    # must clear the previous adaptor's, not keep routing through it
    spill_mod.set_host_spill_factory(adaptor.on_heap_spill_factory())

    def _resolve_udf(key: str):
        return adaptor.udf_wrapper_context(key[len("udf://"):])
    resource.register_resolver("udf://", _resolve_udf)
    adaptor.load_native_lib()


class CallbackAdaptor(EngineAdaptor):
    """Adaptor view over raw C-ABI callbacks installed through
    `host_callbacks.install_from_addresses` (the JNI path): the hook
    points are already wired ctypes-directly for per-batch hot paths;
    this class exposes the same installation through the SPI surface so
    `get_adaptor()` answers coherently for either route."""

    name = "c-abi-host"

    def __init__(self, fns: Dict[str, Any]):
        self._fns = fns

    def conf_get(self, key: str) -> Optional[str]:
        from blaze_tpu import config
        provider = config._host_conf_provider
        return provider(key) if provider else None

    def is_task_running(self, stage_id: int, partition_id: int) -> bool:
        from blaze_tpu.bridge import context
        probe = context._host_task_probe
        return probe(stage_id, partition_id) if probe else True

    def udf_wrapper_context(self, name: str) -> Optional[Callable]:
        from blaze_tpu.bridge import resource
        return resource.get_resource(f"udf://{name}")


def note_installed(adaptor: EngineAdaptor) -> None:
    """Record `adaptor` as the live instance WITHOUT rewiring hook
    points (they were installed directly, e.g. by the ctypes path)."""
    global _instance
    with _lock:
        _instance = adaptor


def get_adaptor() -> EngineAdaptor:
    """AuronAdaptor.getInstance analog: the installed adaptor, else the
    provider selected by BLAZE_TPU_ADAPTOR, else a plain EngineAdaptor
    (unlike the JVM reference, a headless default exists — embedded
    Python use needs no engine)."""
    global _instance
    with _lock:
        if _instance is not None:
            return _instance
        want = os.environ.get("BLAZE_TPU_ADAPTOR")
        factory = None
        if want and want in _providers:
            factory = _providers[want]
        elif _providers:
            factory = next(iter(_providers.values()))
        inst = factory() if factory else EngineAdaptor()
    set_adaptor(inst)
    return inst
