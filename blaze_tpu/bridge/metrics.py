"""Metric tree mirroring the operator tree.

Parity: auron-core MetricNode (ref: auron-core/.../metric/MetricNode.java:27 —
a tree of named counters the native side pushes into on finalize,
native-engine/auron/src/metrics.rs:22 update_metric_node) surfaced to Spark
SQLMetrics (SparkMetricNode.scala).  Operators own a MetricNode; the runtime
collects the tree after execution.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Standard metric vocabulary every operator reports (the port of
# auron-core's baseline_metrics convention: each ExecutionPlan emits
# these regardless of operator-specific extras).  `elapsed_compute_ns`
# is INCLUSIVE of child pull time; renderers derive self-time as
# node - sum(children).
BASELINE_METRICS = (
    "output_rows",
    "output_batches",
    "elapsed_compute_ns",
    "spilled_bytes",
    "mem_used",
    "io_bytes",
)


@dataclass
class MetricNode:
    name: str = ""
    values: Dict[str, int] = field(default_factory=dict)
    children: List["MetricNode"] = field(default_factory=list)

    def add(self, metric: str, value: int = 1) -> None:
        self.values[metric] = self.values.get(metric, 0) + int(value)

    def set(self, metric: str, value: int) -> None:
        self.values[metric] = int(value)

    def set_max(self, metric: str, value: int) -> None:
        """Record a high-water mark (peak memory style)."""
        if int(value) > self.values.get(metric, 0):
            self.values[metric] = int(value)

    def get(self, metric: str) -> int:
        return self.values.get(metric, 0)

    def child(self, i: int, name: str = "") -> "MetricNode":
        while len(self.children) <= i:
            self.children.append(MetricNode())
        node = self.children[i]
        if name and not node.name:
            node.name = name
        return node

    @contextmanager
    def timer(self, metric: str):
        """Accumulate elapsed nanoseconds (ref common/timer_helper.rs)."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.add(metric, time.perf_counter_ns() - t0)

    def to_dict(self) -> dict:
        return {"name": self.name, "values": dict(self.values),
                "children": [c.to_dict() for c in self.children]}

    @classmethod
    def from_dict(cls, d: dict) -> "MetricNode":
        return cls(name=d.get("name", ""),
                   values={k: int(v) for k, v in d.get("values", {}).items()},
                   children=[cls.from_dict(c) for c in d.get("children", ())])

    def merge_from(self, other: "MetricNode") -> None:
        """Accumulate another tree (per-partition trees merging into the
        query-level profile).  Child names propagate: merging used to
        produce unnamed operator nodes when `self` was a bare skeleton."""
        if other.name and not self.name:
            self.name = other.name
        for k, v in other.values.items():
            if k == "mem_used":
                self.set_max(k, v)  # peaks don't sum across partitions
            else:
                self.add(k, v)
        for i, c in enumerate(other.children):
            self.child(i, name=c.name).merge_from(c)

    def snapshot(self) -> "MetricNode":
        """Deep copy of the current counter state."""
        return MetricNode(name=self.name, values=dict(self.values),
                          children=[c.snapshot() for c in self.children])

    def diff(self, before: "MetricNode") -> "MetricNode":
        """Per-partition delta: current counters minus a snapshot()."""
        out = MetricNode(name=self.name)
        for k, v in self.values.items():
            d = v - before.values.get(k, 0)
            if d or k in self.values:
                out.values[k] = d
        for i, c in enumerate(self.children):
            prev = (before.children[i] if i < len(before.children)
                    else MetricNode())
            out.children.append(c.diff(prev))
        return out
