"""Metric tree mirroring the operator tree.

Parity: auron-core MetricNode (ref: auron-core/.../metric/MetricNode.java:27 —
a tree of named counters the native side pushes into on finalize,
native-engine/auron/src/metrics.rs:22 update_metric_node) surfaced to Spark
SQLMetrics (SparkMetricNode.scala).  Operators own a MetricNode; the runtime
collects the tree after execution.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class MetricNode:
    name: str = ""
    values: Dict[str, int] = field(default_factory=dict)
    children: List["MetricNode"] = field(default_factory=list)

    def add(self, metric: str, value: int = 1) -> None:
        self.values[metric] = self.values.get(metric, 0) + int(value)

    def set(self, metric: str, value: int) -> None:
        self.values[metric] = int(value)

    def get(self, metric: str) -> int:
        return self.values.get(metric, 0)

    def child(self, i: int) -> "MetricNode":
        while len(self.children) <= i:
            self.children.append(MetricNode())
        return self.children[i]

    @contextmanager
    def timer(self, metric: str):
        """Accumulate elapsed nanoseconds (ref common/timer_helper.rs)."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.add(metric, time.perf_counter_ns() - t0)

    def to_dict(self) -> dict:
        return {"name": self.name, "values": dict(self.values),
                "children": [c.to_dict() for c in self.children]}

    def merge_from(self, other: "MetricNode") -> None:
        for k, v in other.values.items():
            self.add(k, v)
        for i, c in enumerate(other.children):
            self.child(i).merge_from(c)
