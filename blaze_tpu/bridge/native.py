"""ctypes loaders for the native libraries.

The codec library accelerates the framed-IPC hot path (shuffle/spill
compression); the host-bridge library is the embedding surface for
non-Python host engines.  Both degrade gracefully: pure-Python zstd when
the codec .so is absent, in-process python calls when the bridge is.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SEARCH = [
    os.path.join(_HERE, "native", "build"),
    os.path.join(_HERE, "native", "lib"),
    os.environ.get("BLAZE_TPU_NATIVE_DIR", ""),
]


def _find(name: str) -> Optional[str]:
    for d in _SEARCH:
        if not d:
            continue
        p = os.path.join(d, name)
        if os.path.exists(p):
            return p
    return None


class _Codec:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.blaze_ipc_compress_frame.restype = ctypes.c_int64
        lib.blaze_ipc_compress_frame.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.blaze_ipc_decompress.restype = ctypes.c_int64
        lib.blaze_ipc_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        lib.blaze_ipc_decompressed_size.restype = ctypes.c_int64
        lib.blaze_ipc_decompressed_size.argtypes = [
            ctypes.c_char_p, ctypes.c_int64]
        lib.blaze_free.argtypes = [ctypes.c_void_p]

    def compress_frame(self, payload: bytes, level: int = 1) -> bytes:
        """Whole frame (header + compressed payload)."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.blaze_ipc_compress_frame(payload, len(payload), level,
                                               ctypes.byref(out))
        if n < 0:
            raise RuntimeError("native zstd compression failed")
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.blaze_free(out)

    def decompress(self, payload: bytes) -> bytes:
        size = self._lib.blaze_ipc_decompressed_size(payload, len(payload))
        if size < 0:
            raise RuntimeError("unknown decompressed size")
        buf = ctypes.create_string_buffer(int(size))
        n = self._lib.blaze_ipc_decompress(payload, len(payload), buf, size)
        if n < 0:
            raise RuntimeError("native zstd decompression failed")
        return buf.raw[:n]


_codec: Optional[_Codec] = None
_codec_checked = False


def get_codec() -> Optional[_Codec]:
    global _codec, _codec_checked
    if not _codec_checked:
        _codec_checked = True
        path = _find("libblaze_ipc_codec.so")
        if path:
            try:
                _codec = _Codec(ctypes.CDLL(path))
            except OSError:
                _codec = None
    return _codec


_kernels: dict = {}  # so_name -> CDLL | None, cached incl. misses


def _load_kernel(so_name: str, configure) -> Optional[ctypes.CDLL]:
    """Shared cached loader: find the .so, CDLL it, apply `configure`
    (restype/argtypes setup); None — and remembered as None — when the
    library is absent or unloadable (the pure-Python fallback path)."""
    if so_name not in _kernels:
        lib = None
        path = _find(so_name)
        if path:
            try:
                lib = ctypes.CDLL(path)
                configure(lib)
            except (OSError, AttributeError):
                # AttributeError: stale .so missing a symbol configure
                # binds — a miss to cache, not an error to re-raise on
                # every hot-path call
                lib = None
        _kernels[so_name] = lib
    return _kernels[so_name]


def get_partition_kernel() -> Optional[ctypes.CDLL]:
    """Fused Spark-murmur3 + pmod partition-id kernel
    (partition_kernel.cpp); None (numpy fallback) when unbuilt."""
    def configure(lib):
        lib.blaze_murmur3_pmod.restype = ctypes.c_int64
        lib.blaze_murmur3_pmod.argtypes = [
            ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int32, ctypes.c_void_p]
    return _load_kernel("libblaze_partition_kernel.so", configure)


def get_agg_kernel() -> Optional[ctypes.CDLL]:
    """Specialized i64-key hash group-aggregation (agg_kernel.cpp);
    None (pure-Arrow fallback) when unbuilt."""
    def configure(lib):
        lib.blaze_group_agg_i64.restype = ctypes.c_int64
        lib.blaze_group_agg_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p)]
        # first-row-index variant (newer builds); callers probe with
        # hasattr
        if hasattr(lib, "blaze_group_agg_i64_rows"):
            lib.blaze_group_agg_i64_rows.restype = ctypes.c_int64
            lib.blaze_group_agg_i64_rows.argtypes = (
                lib.blaze_group_agg_i64.argtypes + [ctypes.c_void_p])
    return _load_kernel("libblaze_agg_kernel.so", configure)


def get_host_bridge() -> Optional[ctypes.CDLL]:
    """The C-ABI entry-point library (tests exercise it in-process)."""
    path = _find("libblaze_host_bridge.so")
    if not path:
        return None
    lib = ctypes.CDLL(path)
    lib.blaze_call_native.restype = ctypes.c_int64
    lib.blaze_call_native.argtypes = [ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_char_p)]
    lib.blaze_next_batch.restype = ctypes.c_int64
    lib.blaze_next_batch.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_char_p)]
    lib.blaze_finalize_native.restype = ctypes.c_int64
    lib.blaze_finalize_native.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p)]
    lib.blaze_free_buffer.argtypes = [ctypes.c_void_p]
    # Arrow C-Data zero-copy surface (include/arrow_abi.h); a stale .so
    # from before the FFI symbols must degrade to the IPC path, not
    # crash the loader (same policy as _load_kernel's AttributeError
    # handling)
    try:
        lib.blaze_next_batch_ffi.restype = ctypes.c_int64
        lib.blaze_next_batch_ffi.argtypes = [
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p)]
        lib.blaze_ffi_import_batch.restype = ctypes.c_int64
        lib.blaze_ffi_import_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p)]
        lib.has_cdata_ffi = True
    except AttributeError:
        lib.has_cdata_ffi = False
    return lib


class ArrowArrayStruct(ctypes.Structure):
    """Arrow C-Data ArrowArray (arrow_abi.h), for in-process FFI pulls."""
    _fields_ = [("length", ctypes.c_int64), ("null_count", ctypes.c_int64),
                ("offset", ctypes.c_int64), ("n_buffers", ctypes.c_int64),
                ("n_children", ctypes.c_int64), ("buffers", ctypes.c_void_p),
                ("children", ctypes.c_void_p),
                ("dictionary", ctypes.c_void_p),
                ("release", ctypes.c_void_p),
                ("private_data", ctypes.c_void_p)]


class ArrowSchemaStruct(ctypes.Structure):
    _fields_ = [("format", ctypes.c_char_p), ("name", ctypes.c_char_p),
                ("metadata", ctypes.c_void_p), ("flags", ctypes.c_int64),
                ("n_children", ctypes.c_int64),
                ("children", ctypes.c_void_p),
                ("dictionary", ctypes.c_void_p),
                ("release", ctypes.c_void_p),
                ("private_data", ctypes.c_void_p)]


def bridge_pull_batch(lib: ctypes.CDLL, handle: int):
    """Pull one batch from a host-bridge task handle as a pyarrow
    RecordBatch (None = end of stream).

    Prefers the zero-copy Arrow C-Data path; a stale .so without the FFI
    symbols (has_cdata_ffi False) degrades to the IPC-bytes path — the
    documented fallback policy, enforced here rather than at every call
    site."""
    import pyarrow as pa
    err = ctypes.c_char_p()
    if getattr(lib, "has_cdata_ffi", False):
        arr = ArrowArrayStruct()
        schema = ArrowSchemaStruct()
        r = lib.blaze_next_batch_ffi(handle, ctypes.byref(arr),
                                     ctypes.byref(schema),
                                     ctypes.byref(err))
        if r < 0:
            raise RuntimeError((err.value or b"ffi pull failed").decode())
        if r == 0:
            return None
        return pa.RecordBatch._import_from_c(ctypes.addressof(arr),
                                             ctypes.addressof(schema))
    buf = ctypes.POINTER(ctypes.c_uint8)()
    n = lib.blaze_next_batch(handle, ctypes.byref(buf), ctypes.byref(err))
    if n < 0:
        raise RuntimeError((err.value or b"pull failed").decode())
    if n == 0:
        return None
    try:
        data = ctypes.string_at(buf, n)
    finally:
        lib.blaze_free_buffer(buf)
    with pa.ipc.open_stream(data) as rd:
        batches = list(rd)
    return batches[0] if batches else None
