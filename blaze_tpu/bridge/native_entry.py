"""Python half of the C-ABI host bridge.

The C++ library (native/src/host_bridge.cpp) embeds CPython and calls
these four functions — the exec.rs entry-point bodies.  Handles are
process-global ints mapping to live NativeExecutionRuntimes (the reference
stashes a raw pointer in the JVM wrapper; a handle table is the safe
equivalent).
"""

from __future__ import annotations

import io
import json
import threading
from typing import Dict, Optional

import pyarrow as pa

from blaze_tpu.bridge.runtime import NativeExecutionRuntime

_lock = threading.Lock()
_handles: Dict[int, NativeExecutionRuntime] = {}
_next_handle = 1


def call_native(task_definition_json: str) -> int:
    """(ref exec.rs:42 callNative)"""
    global _next_handle
    rt = NativeExecutionRuntime(task_definition_json).start()
    with _lock:
        handle = _next_handle
        _next_handle += 1
        _handles[handle] = rt
    return handle


def call_native_bytes(task_definition: bytes) -> int:
    """Raw protobuf TaskDefinition bytes — the preserved wire contract
    (ref AuronCallNativeWrapper.java:170 getRawTaskDefinition).  The
    runtime's decoder dispatches on the payload type, so the handle
    bookkeeping is shared with the JSON entry."""
    return call_native(task_definition)


def next_batch(handle: int) -> Optional[bytes]:
    """Arrow IPC stream bytes for one batch; None = end (ref exec.rs:122)."""
    with _lock:
        rt = _handles.get(handle)
    if rt is None:
        raise KeyError(f"invalid native handle {handle}")
    rb = rt.next_batch()
    if rb is None:
        return None
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def next_batch_ffi(handle: int, array_addr: int, schema_addr: int) -> int:
    """Zero-copy batch handoff over the Arrow C-Data interface — the
    importBatch path of the reference (AuronCallNativeWrapper.java:145
    imports the FFI array the native side exported, exec.rs:122).  The
    caller provides addresses of an ArrowArray and ArrowSchema struct;
    the batch's buffers are exported WITHOUT serialization and stay
    alive until the consumer invokes the structs' release callbacks.
    Returns 1 when a batch was exported, 0 at end of stream."""
    with _lock:
        rt = _handles.get(handle)
    if rt is None:
        raise KeyError(f"invalid native handle {handle}")
    rb = rt.next_batch()
    if rb is None:
        return 0
    rb._export_to_c(array_addr, schema_addr)
    return 1


def ffi_import_batch(resource_id: str, array_addr: int,
                     schema_addr: int) -> int:
    """Host -> engine zero-copy: import one C-Data batch and append it
    to the named resource consumed by `ffi_reader` plans (the
    ConvertToNative / ArrowFFIExporter direction,
    spark-extension ArrowFFIExporter.scala).  Returns rows imported."""
    from blaze_tpu.bridge.resource import get_resource, put_resource
    rb = pa.RecordBatch._import_from_c(array_addr, schema_addr)
    existing = get_resource(resource_id)
    if existing is None:
        existing = []
        put_resource(resource_id, existing)
    existing.append(rb)
    return rb.num_rows


def finalize_native(handle: int) -> str:
    """Tear down; returns the metric tree as JSON (ref exec.rs:133 +
    metrics.rs:22)."""
    with _lock:
        rt = _handles.pop(handle, None)
    if rt is None:
        return "{}"
    metrics = rt.finalize()
    return json.dumps(metrics.to_dict())


def on_exit() -> None:
    """(ref exec.rs:144 onExit)"""
    with _lock:
        handles = list(_handles.items())
        _handles.clear()
    for _, rt in handles:
        try:
            rt.finalize()
        except Exception:
            pass
