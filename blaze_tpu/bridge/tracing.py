"""Lightweight span tracer for query execution.

The reference exposes pprof flamegraphs over its HTTP service; the
TPU-port equivalent is a structured span log: every task, shuffle
exchange, operator stream, and fused-kernel dispatch can emit a span
carrying the (query, stage, partition) execution context.  Spans are
buffered in memory and optionally streamed to a JSONL file (one JSON
object per line: name, t0/t1 ns, thread, context, attrs) that loads
directly into Perfetto-style tooling or pandas.

Since the worker pool (PR 11) the runtime spans process boundaries, so
the tracer does too: `wire_context()` packs the current (query, stage,
task, attempt, parent-span) context into the task message riding the
CRC32C-framed worker protocol, the child adopts it under
`remote_task_scope()` and buffers its spans locally, heartbeat/result
frames carry the buffered spans back (`take_buffered()`), and the
parent stitches them into the one per-query trace via `ingest()` with
a monotonic-clock rebase — child `perf_counter_ns` origins differ per
process, so the frame carries the child clock at send time and the
parent shifts every span by the observed offset.

Tracing can be enabled programmatically (`start_tracing()`) or from
conf (`auron.tpu.trace.enable`, probed once lazily, same one-shot
pattern as faults._current).  Disabled tracing is a near-free boolean
check — operators call `span(...)` unconditionally.

Every span name the runtime can emit is registered in SPAN_NAMES
(enforced by tests/test_span_names.py: undocumented or dead names fail
conformance).  Names with a trailing `*` are prefix families — the
suffix is dynamic (operator class names).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_enabled = False
_conf_probed = False  # lazy one-shot auron.tpu.trace.enable probe
_lock = threading.Lock()
_spans: List[dict] = []
_MAX_SPANS = 100_000
_sink = None  # open JSONL file, when exporting
_tls = threading.local()
_ids = itertools.count(1)

# Worker-child mode: spans are buffered locally and shipped back to the
# parent in heartbeat/result frames instead of accumulating here.
_child_mode = False
_child_buf: List[dict] = []
_CHILD_BUF_CAP = 10_000

#: Registry of every span/instant name the runtime emits, with the
#: one-line doc rendered into docs/observability.md.  A trailing `*`
#: marks a prefix family (dynamic suffix).
SPAN_NAMES: Dict[str, str] = {
    # -- spans (dur_ns > 0) -------------------------------------------
    "task": "per-partition runtime stream covering one task's operator "
            "chain (bridge/runtime.py; mode=sync|producer)",
    "task_attempt": "one scheduled attempt of a task in the wave loop, "
                    "local or routed to a pool worker (bridge/tasks.py; "
                    "attrs task/attempt/what/speculative/remote)",
    "backoff_wait": "retry backoff sleep between task attempts "
                    "(bridge/tasks.py; interruptible by cancel/deadline)",
    "admission_wait": "queue wait from QueryService.submit() to the "
                      "worker pop that starts running the query "
                      "(serving/service.py; attrs query/tenant)",
    "worker_task": "child-process execution of a remote task inside a "
                   "pool worker (parallel/workers.py child_main)",
    "device_exchange": "on-device collective shuffle dispatch for one "
                       "stage (plan/stages.py -> DeviceExchange)",
    "rss_exchange": "remote-shuffle-service exchange tier for one stage "
                    "(plan/stages.py)",
    "shuffle_exchange": "file-tier shuffle exchange for one stage "
                        "(plan/stages.py)",
    "stage_recovery": "lineage re-run of a poisoned producer map task "
                      "after FetchFailedError (plan/stages.py)",
    "stage_loop_chunk": "one fused device-loop chunk dispatch folding a "
                        "window of batches in a single XLA call "
                        "(runtime/loop.py; overlap vs device_exchange "
                        "is the ROADMAP item-4 signal)",
    "stream_epoch": "one streaming micro-batch epoch: poll -> plan -> "
                    "window/watermark -> sink attempt -> checkpoint "
                    "commit (streaming/executor.py; attrs epoch/rows)",
    "explain_analyze": "whole-query profiled execution (plan/explain.py)",
    "operator:*": "per-operator stream total accumulated across next() "
                  "calls; suffix is the ExecutionPlan class name "
                  "(ops/base.py stream meter)",
    # -- instants (dur_ns == 0) ---------------------------------------
    "task_retry": "a failed attempt was classified retryable and will "
                  "back off and retry (bridge/tasks.py)",
    "fault_injected": "a seeded chaos fault fired at a registered site "
                      "(faults.py)",
    "xla_compile": "an XLA kernel compiled (cache miss) with wall ns "
                   "(bridge/xla_stats.py meter_jit)",
    "device_shuffle_fallback": "device collective exchange declined or "
                               "failed; stage fell back a tier "
                               "(plan/stages.py)",
    "rss_shuffle_fallback": "RSS exchange tier failed; stage fell back "
                            "to the file tier (plan/stages.py)",
    "stage_loop_fallback": "fused device loop bailed; stage re-ran "
                           "staged per-batch (plan/stages.py)",
    "quota_breach": "per-query memory quota breach climbed one degrade "
                    "rung (memory/manager.py; attrs query/used/quota/"
                    "rung)",
    "mem_spill": "a memory consumer spilled under pressure or quota "
                 "shed (memory/manager.py; attrs consumer/bytes/query)",
    "worker_heartbeat": "pool-worker child liveness beat observed while "
                        "a task runs (parallel/workers.py)",
    "worker_cancel_escalation": "cancel/abandon escalated on a worker "
                                "slot: cancel msg, SIGTERM or SIGKILL "
                                "(parallel/workers.py; attrs action)",
    "speculation_attempt": "a duplicate attempt was hedged against a "
                           "straggler (bridge/tasks.py; attrs task/"
                           "attempt)",
    "speculation_win": "an attempt committed first; links the "
                       "winner/loser attempt pair (bridge/tasks.py; "
                       "attrs task/winner_attempt/loser_attempts)",
    "speculation_loser": "a losing attempt was cancelled or abandoned "
                         "after the sibling committed (bridge/tasks.py)",
    "aqe_rewrite": "an adaptive-execution rule rewrote a not-yet-"
                   "dispatched consumer stage at the boundary "
                   "(plan/adaptive.py; attrs stage/rule)",
    "aqe_history_seed": "bind-time planning applied statstore-derived "
                        "seeds to the plan (plan/adaptive.py; attrs "
                        "seeds)",
    "stream_recovery": "streaming epoch restored from the latest "
                       "checkpoint manifest after a retryable failure "
                       "(streaming/executor.py)",
    "flight_dump": "the flight recorder wrote a post-mortem artifact "
                   "for a fatally-classified query (bridge/context.py)",
    "result_cache_hit": "a whole-query result was served from the "
                        "work-sharing cache, skipping execution "
                        "(serving/service.py; attrs query/fingerprint/"
                        "nbytes)",
    "subplan_cache_hit": "a leaf map stage replayed cached "
                         "exchange-boundary blocks instead of running "
                         "its tasks (plan/stages.py; attrs stage/"
                         "fingerprint)",
    "fleet_replica_down": "the fleet router marked a replica down "
                          "after a transport error, a missed liveness "
                          "deadline, or drain (fleet/router.py; attrs "
                          "replica/reason)",
    "fleet_replica_up": "a down replica answered a backoff probe and "
                        "rejoined the routable set (fleet/router.py; "
                        "attrs replica)",
}


def register_span(name: str, doc: str) -> None:
    """Escape hatch for out-of-tree emitters; mirrors
    faults.register_site so conformance keeps covering them."""
    SPAN_NAMES[name] = doc


def _check_name(name: str) -> None:
    """Emitting an unregistered span name is a bug, not telemetry: the
    registry is the conformance contract (tests/test_span_names.py).
    Only reached when tracing is ON — the disabled path never gets here."""
    if name in SPAN_NAMES:
        return
    i = name.find(":")
    if i > 0 and name[:i + 1] + "*" in SPAN_NAMES:
        return
    raise ValueError(
        f"unregistered span name {name!r}: add it to tracing.SPAN_NAMES "
        "(or register_span) and document it in docs/observability.md")


def _probe_conf() -> None:
    global _conf_probed, _enabled
    with _lock:
        if _conf_probed:
            return
        _conf_probed = True
    try:
        from blaze_tpu import config
        if config.TRACE_ENABLE.get():
            _enabled = True
    except Exception:
        pass


def enabled() -> bool:
    if not _conf_probed:
        _probe_conf()
    return _enabled


def _ctx_stack() -> List[Dict[str, Any]]:
    stack = getattr(_tls, "ctx", None)
    if stack is None:
        stack = _tls.ctx = []
    return stack


def _span_stack() -> List[int]:
    stack = getattr(_tls, "span_stack", None)
    if stack is None:
        stack = _tls.span_stack = []
    return stack


def current_context() -> Dict[str, Any]:
    """Innermost query/stage/partition context on this thread."""
    out: Dict[str, Any] = {}
    for frame in _ctx_stack():
        out.update(frame)
    return out


@contextmanager
def execution_context(**fields):
    """Push query_id/stage/partition (any subset) for spans emitted on
    this thread; nests — inner frames override outer keys."""
    stack = _ctx_stack()
    stack.append({k: v for k, v in fields.items() if v is not None})
    try:
        yield
    finally:
        stack.pop()


@contextmanager
def span(name: str, **attrs):
    """Emit one span covering the `with` body.  No-op when disabled."""
    if not _enabled:
        if _conf_probed or not enabled():
            yield
            return
    _check_name(name)
    sid = next(_ids)
    stack = _span_stack()
    parent = stack[-1] if stack else None
    stack.append(sid)
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        t1 = time.perf_counter_ns()
        stack.pop()
        record = {"name": name, "t0_ns": t0, "t1_ns": t1,
                  "dur_ns": t1 - t0, "sid": sid,
                  "thread": threading.current_thread().name}
        if parent is not None:
            record["parent"] = parent
        ctx = current_context()
        if ctx:
            record["ctx"] = ctx
        if attrs:
            record["attrs"] = attrs
        _emit(record)


def emit_span(name: str, dur_ns: int, **attrs) -> None:
    """Record a span whose duration was measured externally (the operator
    stream meter accumulates time across many next() calls)."""
    if not _enabled:
        if _conf_probed or not enabled():
            return
    _check_name(name)
    t1 = time.perf_counter_ns()
    record = {"name": name, "t0_ns": t1 - int(dur_ns), "t1_ns": t1,
              "dur_ns": int(dur_ns), "sid": next(_ids),
              "thread": threading.current_thread().name}
    stack = _span_stack()
    if stack:
        record["parent"] = stack[-1]
    ctx = current_context()
    if ctx:
        record["ctx"] = ctx
    if attrs:
        record["attrs"] = attrs
    _emit(record)


def instant(name: str, **attrs) -> None:
    """Zero-duration event (e.g. an XLA compile)."""
    if not _enabled:
        if _conf_probed or not enabled():
            return
    _check_name(name)
    t = time.perf_counter_ns()
    record = {"name": name, "t0_ns": t, "t1_ns": t, "dur_ns": 0,
              "sid": next(_ids),
              "thread": threading.current_thread().name}
    stack = _span_stack()
    if stack:
        record["parent"] = stack[-1]
    ctx = current_context()
    if ctx:
        record["ctx"] = ctx
    if attrs:
        record["attrs"] = attrs
    _emit(record)


def _emit(record: dict) -> None:
    with _lock:
        if _child_mode:
            _child_buf.append(record)
            del _child_buf[:-_CHILD_BUF_CAP]
            return
        _spans.append(record)
        del _spans[:-_MAX_SPANS]
        if _sink is not None:
            _sink.write(json.dumps(record, default=str) + "\n")
            _sink.flush()


# -- cross-process propagation ---------------------------------------------

_WIRE_KEYS = ("query", "stage", "task", "attempt", "what", "partition")


def wire_context(**extra) -> Optional[dict]:
    """Compact trace context to ride the worker wire protocol: the
    current (query, stage, task, attempt) plus the enclosing span id as
    `parent`.  Returns None when tracing is off, so the task message
    grows by nothing on the disabled path."""
    if not enabled():
        return None
    ctx = current_context()
    out = {k: ctx[k] for k in _WIRE_KEYS if k in ctx}
    stack = getattr(_tls, "span_stack", None)
    if stack:
        out["parent"] = stack[-1]
    for k, v in extra.items():
        if v is not None:
            out[k] = v
    return out


@contextmanager
def remote_task_scope(wire_ctx: Optional[dict]):
    """Child-process side: adopt a parent trace context for the duration
    of one task.  Enables span collection in child-buffer mode (spans go
    to a local buffer drained by take_buffered() into heartbeat/result
    frames) and parents every child span under the dispatching span."""
    if not wire_ctx:
        yield
        return
    global _enabled, _conf_probed, _child_mode
    with _lock:
        saved = (_enabled, _conf_probed, _child_mode)
        _enabled = True
        _conf_probed = True
        _child_mode = True
    parent = wire_ctx.get("parent")
    fields = {k: v for k, v in wire_ctx.items() if k != "parent"}
    stack = _span_stack()
    if parent is not None:
        stack.append(parent)
    try:
        with execution_context(**fields):
            yield
    finally:
        if parent is not None:
            stack.pop()
        with _lock:
            _enabled, _conf_probed, _child_mode = saved


def take_buffered() -> List[dict]:
    """Drain the child-mode span buffer (heartbeat/result frame payload)."""
    with _lock:
        out = list(_child_buf)
        del _child_buf[:]
    return out


def ingest(records: Optional[List[dict]], worker=None,
           clock_ns: Optional[int] = None) -> int:
    """Parent side: stitch spans shipped back from a worker child into
    the process trace.  `worker` tags the originating slot; `clock_ns`
    is the child's perf_counter_ns at frame-send time, used to rebase
    the child's clock origin onto ours (transit latency is absorbed
    into the offset — fine at heartbeat granularity)."""
    if not records or not _enabled:
        return 0
    offset = 0
    if clock_ns is not None:
        offset = time.perf_counter_ns() - int(clock_ns)
    with _lock:
        for r in records:
            if not isinstance(r, dict):
                continue
            if worker is not None:
                r.setdefault("worker", worker)
            if offset:
                r["t0_ns"] = r.get("t0_ns", 0) + offset
                r["t1_ns"] = r.get("t1_ns", 0) + offset
            _spans.append(r)
            if _sink is not None:
                _sink.write(json.dumps(r, default=str) + "\n")
        del _spans[:-_MAX_SPANS]
        if _sink is not None:
            _sink.flush()
    try:
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_obs(spans_ingested=len(records))
    except Exception:
        pass
    return len(records)


def spans_for_query(query_id) -> List[dict]:
    """All buffered spans whose context names this query (the timeline
    endpoint and the flight recorder read this)."""
    with _lock:
        return [r for r in _spans
                if r.get("ctx", {}).get("query") == query_id]


# -- lifecycle --------------------------------------------------------------

def start_tracing(path: Optional[str] = None) -> None:
    """Enable span collection; `path` additionally streams JSONL there."""
    global _enabled, _sink, _conf_probed
    with _lock:
        _spans.clear()
        if _sink is not None:
            _sink.close()
            _sink = None
        if path:
            _sink = open(path, "w")
        _conf_probed = True
    _enabled = True


def stop_tracing() -> List[dict]:
    """Disable collection; returns (and keeps) the buffered spans."""
    global _enabled, _sink
    _enabled = False
    with _lock:
        if _sink is not None:
            _sink.close()
            _sink = None
        return list(_spans)


def reset_conf_probe() -> None:
    """Forget the lazy auron.tpu.trace.enable probe (tests/bench)."""
    global _conf_probed, _enabled, _child_mode
    with _lock:
        _conf_probed = False
        _enabled = False
        _child_mode = False
        del _child_buf[:]


def spans() -> List[dict]:
    with _lock:
        return list(_spans)
