"""Lightweight span tracer for query execution.

The reference exposes pprof flamegraphs over its HTTP service; the
TPU-port equivalent is a structured span log: every task, shuffle
exchange, operator stream, and fused-kernel dispatch can emit a span
carrying the (query, stage, partition) execution context.  Spans are
buffered in memory and optionally streamed to a JSONL file (one JSON
object per line: name, t0/t1 ns, thread, context, attrs) that loads
directly into Perfetto-style tooling or pandas.

Disabled tracing is a near-free boolean check — operators call
`span(...)` unconditionally.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_enabled = False
_lock = threading.Lock()
_spans: List[dict] = []
_MAX_SPANS = 100_000
_sink = None  # open JSONL file, when exporting
_tls = threading.local()


def enabled() -> bool:
    return _enabled


def _ctx_stack() -> List[Dict[str, Any]]:
    stack = getattr(_tls, "ctx", None)
    if stack is None:
        stack = _tls.ctx = []
    return stack


def current_context() -> Dict[str, Any]:
    """Innermost query/stage/partition context on this thread."""
    out: Dict[str, Any] = {}
    for frame in _ctx_stack():
        out.update(frame)
    return out


@contextmanager
def execution_context(**fields):
    """Push query_id/stage/partition (any subset) for spans emitted on
    this thread; nests — inner frames override outer keys."""
    stack = _ctx_stack()
    stack.append({k: v for k, v in fields.items() if v is not None})
    try:
        yield
    finally:
        stack.pop()


@contextmanager
def span(name: str, **attrs):
    """Emit one span covering the `with` body.  No-op when disabled."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        t1 = time.perf_counter_ns()
        record = {"name": name, "t0_ns": t0, "t1_ns": t1,
                  "dur_ns": t1 - t0,
                  "thread": threading.current_thread().name}
        ctx = current_context()
        if ctx:
            record["ctx"] = ctx
        if attrs:
            record["attrs"] = attrs
        _emit(record)


def emit_span(name: str, dur_ns: int, **attrs) -> None:
    """Record a span whose duration was measured externally (the operator
    stream meter accumulates time across many next() calls)."""
    if not _enabled:
        return
    t1 = time.perf_counter_ns()
    record = {"name": name, "t0_ns": t1 - int(dur_ns), "t1_ns": t1,
              "dur_ns": int(dur_ns),
              "thread": threading.current_thread().name}
    ctx = current_context()
    if ctx:
        record["ctx"] = ctx
    if attrs:
        record["attrs"] = attrs
    _emit(record)


def instant(name: str, **attrs) -> None:
    """Zero-duration event (e.g. an XLA compile)."""
    if not _enabled:
        return
    t = time.perf_counter_ns()
    record = {"name": name, "t0_ns": t, "t1_ns": t, "dur_ns": 0,
              "thread": threading.current_thread().name}
    ctx = current_context()
    if ctx:
        record["ctx"] = ctx
    if attrs:
        record["attrs"] = attrs
    _emit(record)


def _emit(record: dict) -> None:
    with _lock:
        _spans.append(record)
        del _spans[:-_MAX_SPANS]
        if _sink is not None:
            _sink.write(json.dumps(record, default=str) + "\n")
            _sink.flush()


def start_tracing(path: Optional[str] = None) -> None:
    """Enable span collection; `path` additionally streams JSONL there."""
    global _enabled, _sink
    with _lock:
        _spans.clear()
        if _sink is not None:
            _sink.close()
            _sink = None
        if path:
            _sink = open(path, "w")
    _enabled = True


def stop_tracing() -> List[dict]:
    """Disable collection; returns (and keeps) the buffered spans."""
    global _enabled, _sink
    _enabled = False
    with _lock:
        if _sink is not None:
            _sink.close()
            _sink = None
        return list(_spans)


def spans() -> List[dict]:
    with _lock:
        return list(_spans)
