"""Spark-semantics conformance corpus (the auron-spark-tests analog).

Parity: the reference re-runs 14.8K LoC of Spark's own SQL suites under
the accelerator, governed by an include/exclude DSL
(ref auron-spark-tests/common/.../SparkTestSettings.scala:28-160:
`enableSuite[T]`, `include`, `exclude`, `includeByPrefix`,
`excludeAllAuronTests`).  No Spark runtime exists in this image, so the
corpus itself is vendored: hand-written vectors whose EXPECTED values
encode documented Spark behavior (1-based string indexing, Java division
and modulo, HALF_UP round vs HALF_EVEN bround, concat_ws null-skipping,
three-valued logic, NaN ordering in greatest/least, non-ANSI
overflow-wraps and div-by-zero-null...).  Each case runs through the
REAL engine path: IR dict -> create_plan -> execute over a memory scan.

The DSL mirrors the reference's:

    settings = CorpusSettings()
    settings.enable_suite("StringFunctionsSuite") \\
            .exclude("substring_index - negative count", reason="...")
    results = run_corpus(settings)

`exclude(..., reason=...)` entries are the declared-divergence ledger —
exactly how the reference records cases the accelerator intentionally
fails (SparkTestSettings exclusion comments).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa


# ---------------------------------------------------------------------------
# DSL (ref SparkTestSettings.scala)
# ---------------------------------------------------------------------------

@dataclass
class SuiteSettings:
    name: str
    included: Optional[List[str]] = None   # None = all
    excluded: Dict[str, str] = field(default_factory=dict)  # case -> reason
    include_prefixes: List[str] = field(default_factory=list)

    def include(self, *names: str) -> "SuiteSettings":
        if self.included is None:
            self.included = []
        self.included.extend(names)
        return self

    def include_by_prefix(self, *prefixes: str) -> "SuiteSettings":
        self.include_prefixes.extend(prefixes)
        return self

    def exclude(self, name: str, reason: str = "") -> "SuiteSettings":
        self.excluded[name] = reason
        return self

    def selects(self, case_name: str) -> bool:
        if case_name in self.excluded:
            return False
        if self.included is None and not self.include_prefixes:
            return True
        if self.included and case_name in self.included:
            return True
        return any(case_name.startswith(p) for p in self.include_prefixes)


class CorpusSettings:
    def __init__(self):
        self.suites: Dict[str, SuiteSettings] = {}

    def enable_suite(self, name: str) -> SuiteSettings:
        if name not in SUITES:
            raise KeyError(f"unknown suite {name!r}; have {sorted(SUITES)}")
        s = SuiteSettings(name)
        self.suites[name] = s
        return s

    def enable_all(self) -> "CorpusSettings":
        for name in SUITES:
            self.enable_suite(name)
        return self


# ---------------------------------------------------------------------------
# case model
# ---------------------------------------------------------------------------

@dataclass
class Case:
    """One conformance vector: expression(s) over an input column set.

    Two shapes:
      * projection vectors — `exprs` are projected over `input`;
      * plan vectors — `plan(scan_ir[, scan2_ir])` builds an arbitrary
        root plan (sort / agg / join...) over the memory scan(s), the
        analog of the reference's full-suite re-runs that exercise
        operators, not just expressions.
    `confs` scopes engine config keys around the run (the ANSI-toggle
    analog of SparkTestSettings' per-suite conf overrides).
    `unordered` compares results as multisets (agg/join output order is
    not contractual, like Spark's checkAnswer).
    """

    name: str
    input: pa.Table                      # input columns c0..cn
    exprs: List[dict]                    # IR expression dicts
    expected: List[tuple]                # rows of expected output
    rtol: float = 0.0                    # float tolerance (0 = exact)
    confs: Optional[Dict[str, Any]] = None
    plan: Optional[Callable[..., dict]] = None
    input2: Optional[pa.Table] = None    # second scan for join vectors
    unordered: bool = False
    raises: Optional[str] = None         # expect failure containing this


def _col(i: int) -> dict:
    return {"kind": "column", "index": i}


def _lit(v, t="int64") -> dict:
    return {"kind": "literal", "value": v, "type": {"id": t}}


def _fn(name: str, *args, rt: Optional[str] = None) -> dict:
    d = {"kind": "scalar_function", "name": name, "args": list(args)}
    if rt:
        d["return_type"] = {"id": rt}
    return d


def _bin(op, l, r) -> dict:
    return {"kind": "binary", "op": op, "l": l, "r": r}


SUITES: Dict[str, List[Case]] = {}


def _suite(name: str):
    def deco(build: Callable[[], List[Case]]):
        SUITES[name] = build()
        return build
    return deco


# ---------------------------------------------------------------------------
# suites
# ---------------------------------------------------------------------------

I64MAX = (1 << 63) - 1
I64MIN = -(1 << 63)


@_suite("ArithmeticSuite")
def _arith():
    ints = pa.table({"a": pa.array([7, -7, 7, -7, None, I64MAX]),
                     "b": pa.array([3, 3, -3, -3, 3, 1])})
    return [
        Case("division by zero yields null (non-ANSI)",
             pa.table({"a": pa.array([10, 0, None])}),
             [_bin("%", _col(0), _lit(0))],
             [(None,), (None,), (None,)]),
        Case("java modulo sign follows dividend",
             ints, [_bin("%", _col(0), _col(1))],
             [(1,), (-1,), (1,), (-1,), (None,), (0,)]),
        Case("pmod sign follows divisor",
             ints, [_bin("pmod", _col(0), _col(1))],
             [(1,), (2,), (1,), (-1,), (None,), (0,)]),
        Case("int64 overflow wraps (non-ANSI two's complement)",
             pa.table({"a": pa.array([I64MAX])}),
             [_bin("+", _col(0), _lit(1))],
             [(I64MIN,)]),
        Case("float division by zero is NULL (DivModLike, non-ANSI)",
             pa.table({"a": pa.array([1.0, -1.0, 0.0])}),
             [_bin("/", _col(0), _lit(0.0, "float64"))],
             [(None,), (None,), (None,)]),
    ]


@_suite("StringFunctionsSuite")
def _strings():
    s = pa.table({"s": pa.array(["Spark SQL", "", None, "abcdef"])})
    return [
        Case("substring is 1-based",
             s, [_fn("substring", _col(0), _lit(1), _lit(5), rt="utf8")],
             [("Spark",), ("",), (None,), ("abcde",)]),
        Case("substring negative start counts from end",
             s, [_fn("substring", _col(0), _lit(-3), _lit(3), rt="utf8")],
             [("SQL",), ("",), (None,), ("def",)]),
        Case("instr is 1-based, 0 when absent",
             s, [_fn("instr", _col(0), _lit("SQL", "utf8"), rt="int32")],
             [(7,), (0,), (None,), (0,)]),
        Case("concat null poisons",
             s, [_fn("concat", _col(0), _lit("!", "utf8"), rt="utf8")],
             [("Spark SQL!",), ("!",), (None,), ("abcdef!",)]),
        Case("concat_ws skips nulls",
             pa.table({"a": pa.array(["x", None]),
                       "b": pa.array(["y", "z"])}),
             [_fn("concat_ws", _lit(",", "utf8"), _col(0), _col(1),
                  rt="utf8")],
             [("x,y",), ("z",)]),
        Case("lpad truncates when longer than target",
             pa.table({"s": pa.array(["abcd"])}),
             [_fn("lpad", _col(0), _lit(2), _lit("#", "utf8"),
                  rt="utf8")],
             [("ab",)]),
        Case("initcap capitalizes each word",
             pa.table({"s": pa.array(["sPark sql"])}),
             [_fn("initcap", _col(0), rt="utf8")],
             [("Spark Sql",)]),
        Case("substring_index positive and sign",
             pa.table({"s": pa.array(["www.apache.org"] * 2),
                       "n": pa.array([2, -2])}),
             [_fn("substring_index", _col(0), _lit(".", "utf8"), _col(1),
                  rt="utf8")],
             [("www.apache",), ("apache.org",)]),
        Case("translate maps and drops",
             pa.table({"s": pa.array(["AaBbCc"])}),
             [_fn("translate", _col(0), _lit("abc", "utf8"),
                  _lit("12", "utf8"), rt="utf8")],
             [("A1B2C",)]),
        Case("repeat and reverse",
             pa.table({"s": pa.array(["ab"])}),
             [_fn("repeat", _col(0), _lit(3), rt="utf8"),
              _fn("reverse", _col(0), rt="utf8")],
             [("ababab", "ba")]),
        Case("length counts characters not bytes",
             pa.table({"s": pa.array(["héllo"])}),
             [_fn("length", _col(0), rt="int32")],
             [(5,)]),
        Case("ascii and chr",
             pa.table({"s": pa.array(["A"]), "n": pa.array([66])}),
             [_fn("ascii", _col(0), rt="int32"),
              _fn("chr", _col(1), rt="utf8")],
             [(65, "B")]),
        Case("chr edge codes: negative empty, 256 is NUL",
             pa.table({"n": pa.array([-1, 0, 256, 321])}),
             [_fn("chr", _col(0), rt="utf8")],
             [("",), ("\x00",), ("\x00",), ("A",)]),
    ]


@_suite("MathSuite")
def _math():
    return [
        Case("round is HALF_UP away from zero",
             pa.table({"a": pa.array([2.5, 3.5, -2.5, 0.35])}),
             [_fn("round", _col(0), rt="float64"),
              _fn("round", _col(0), _lit(1), rt="float64")],
             [(3.0, 2.5), (4.0, 3.5), (-3.0, -2.5), (0.0, 0.4)],
             rtol=1e-9),
        Case("bround is HALF_EVEN",
             pa.table({"a": pa.array([2.5, 3.5, -2.5])}),
             [_fn("bround", _col(0), rt="float64")],
             [(2.0,), (4.0,), (-2.0,)]),
        Case("signum and abs",
             pa.table({"a": pa.array([-5.0, 0.0, 7.5])}),
             [_fn("signum", _col(0), rt="float64"),
              _fn("abs", _col(0), rt="float64")],
             [(-1.0, 5.0), (0.0, 0.0), (1.0, 7.5)]),
        Case("greatest skips nulls, NaN is largest",
             pa.table({"a": pa.array([1.0, None, float("nan")]),
                       "b": pa.array([2.0, 3.0, 2.0])}),
             [_fn("greatest", _col(0), _col(1), rt="float64")],
             [(2.0,), (3.0,), (float("nan"),)]),
        Case("least skips nulls",
             pa.table({"a": pa.array([1.0, None]),
                       "b": pa.array([2.0, 3.0])}),
             [_fn("least", _col(0), _col(1), rt="float64")],
             [(1.0,), (3.0,)]),
        Case("nanvl replaces NaN only",
             pa.table({"a": pa.array([float("nan"), 1.0]),
                       "b": pa.array([9.0, 9.0])}),
             [_fn("nanvl", _col(0), _col(1), rt="float64")],
             [(9.0,), (1.0,)]),
    ]


@_suite("MathEdgeSuite")
def _math_edge():
    import math as _m
    return [
        Case("ln/log of non-positive is null (Spark, not -inf)",
             pa.table({"a": pa.array([0.0, -1.0, _m.e])}),
             [_fn("ln", _col(0), rt="float64")],
             [(None,), (None,), (1.0,)], rtol=1e-12),
        Case("log10 and log2 exact powers",
             pa.table({"a": pa.array([100.0, 8.0])}),
             [_fn("log10", _col(0), rt="float64"),
              _fn("log2", _col(0), rt="float64")],
             [(2.0, _m.log2(100.0)), (_m.log10(8.0), 3.0)], rtol=1e-12),
        Case("sqrt of negative is NaN",
             pa.table({"a": pa.array([-1.0, 4.0])}),
             [_fn("sqrt", _col(0), rt="float64")],
             [(float("nan"),), (2.0,)]),
        Case("pow zero zero is one; cbrt of negative is real",
             pa.table({"a": pa.array([0.0, -8.0])}),
             [_fn("pow", _col(0), _lit(0.0, "float64"), rt="float64"),
              _fn("cbrt", _col(0), rt="float64")],
             [(1.0, 0.0), (1.0, -2.0)], rtol=1e-12),
        Case("expm1/log1p stay precise near zero",
             pa.table({"a": pa.array([0.0, 1e-10])}),
             [_fn("expm1", _col(0), rt="float64"),
              _fn("log1p", _col(0), rt="float64")],
             [(0.0, 0.0), (1.00000000005e-10, 9.9999999995e-11)],
             rtol=1e-9),
        Case("atan2 quadrants",
             pa.table({"y": pa.array([1.0, -1.0]),
                       "x": pa.array([1.0, -1.0])}),
             [_fn("atan2", _col(0), _col(1), rt="float64")],
             [(_m.pi / 4,), (-3 * _m.pi / 4,)], rtol=1e-12),
        Case("log of NaN stays NaN, not null",
             pa.table({"a": pa.array([float("nan")])}),
             [_fn("ln", _col(0), rt="float64")],
             [(float("nan"),)]),
    ]


@_suite("DateTimeEdgeSuite")
def _dates_edge():
    import datetime as dt
    d = pa.table({"d": pa.array([dt.date(2001, 1, 31),
                                 dt.date(2001, 2, 3)])})
    return [
        Case("add_months clamps to month end",
             d, [_fn("add_months", _col(0), _lit(1),
                     rt="date32")],
             [(dt.date(2001, 2, 28),), (dt.date(2001, 3, 3),)]),
        Case("last_day of february",
             d, [_fn("last_day", _col(0), rt="date32")],
             [(dt.date(2001, 1, 31),), (dt.date(2001, 2, 28),)]),
        Case("datediff sign",
             pa.table({"a": pa.array([dt.date(2001, 1, 1)]),
                       "b": pa.array([dt.date(2000, 12, 31)])}),
             [_fn("datediff", _col(0), _col(1), rt="int32"),
              _fn("datediff", _col(1), _col(0), rt="int32")],
             [(1, -1)]),
        Case("weekday is 0-Monday while dayofweek is 1-Sunday",
             pa.table({"d": pa.array([dt.date(2001, 1, 1)])}),  # a Monday
             [_fn("weekday", _col(0), rt="int32"),
              _fn("dayofweek", _col(0), rt="int32")],
             [(0, 2)]),
        Case("months_between integer when both month ends",
             pa.table({"a": pa.array([dt.date(2001, 3, 31)]),
                       "b": pa.array([dt.date(2001, 2, 28)])}),
             [_fn("months_between", _col(0), _col(1), rt="float64")],
             [(1.0,)], rtol=1e-9),
    ]


@_suite("CryptoSuite")
def _crypto():
    s = pa.table({"s": pa.array(["ABC", None])})
    return [
        Case("md5 digest",
             s, [_fn("md5", _col(0), rt="utf8")],
             [("902fbdd2b1df0c4f70b4a5d23525e932",), (None,)]),
        Case("sha1 digest",
             s, [_fn("sha1", _col(0), rt="utf8")],
             [("3c01bdbb26f358bab27f267924aa2c9a03fcfdb8",), (None,)]),
        Case("sha2-256 digest",
             s, [_fn("sha2", _col(0), _lit(256), rt="utf8")],
             [("b5d4045c3f466fa91fe2cc6abe79232a1a57cdf1"
               "04f7a26e716e0a1e2789df78",), (None,)]),
        Case("crc32 value",
             s, [_fn("crc32", _col(0), rt="int64")],
             [(2743272264,), (None,)]),
    ]


@_suite("StringEdgeSuite")
def _string_edge():
    return [
        Case("locate and position are 1-based with 0 for missing",
             pa.table({"s": pa.array(["abcb", "xyz"])}),
             [_fn("locate", _lit("b", "utf8"), _col(0), rt="int32"),
              _fn("position", _lit("b", "utf8"), _col(0), rt="int32")],
             [(2, 2), (0, 0)]),
        Case("split on literal delimiter",
             pa.table({"s": pa.array(["aXbXc"])}),
             [_fn("split", _col(0), _lit("X", "utf8"))],
             [((["a", "b", "c"]),)]),
        Case("space builds and clamps at zero",
             pa.table({"n": pa.array([3, 0, -2])}),
             [_fn("space", _col(0), rt="utf8")],
             [("   ",), ("",), ("",)]),
        Case("octet_length counts bytes, char_length characters",
             pa.table({"s": pa.array(["h\u00e9llo"])}),
             [_fn("octet_length", _col(0), rt="int32"),
              _fn("char_length", _col(0), rt="int32")],
             [(6, 5)]),
        Case("replace replaces every occurrence",
             pa.table({"s": pa.array(["ababa"])}),
             [_fn("replace", _col(0), _lit("b", "utf8"),
                  _lit("z", "utf8"), rt="utf8")],
             [("azaza",)]),
        Case("substring zero position behaves as one",
             pa.table({"s": pa.array(["Spark SQL"])}),
             [_fn("substring", _col(0), _lit(0), _lit(3), rt="utf8")],
             [("Spa",)]),
        Case("locate with start offset (NULL start yields 0, not NULL)",
             pa.table({"s": pa.array(["abcb", "abcb", "abcb"]),
                       "p": pa.array([3, 0, None])}),
             [_fn("locate", _lit("b", "utf8"), _col(0), _col(1),
                  rt="int32")],
             [(4,), (0,), (0,)]),
        Case("strpos uses datafusion (str, substr) order",
             pa.table({"s": pa.array(["abcb"])}),
             [_fn("strpos", _col(0), _lit("b", "utf8"), rt="int32")],
             [(2,)]),
    ]


@_suite("ConditionalSuite")
def _cond():
    return [
        Case("three-valued AND",
             pa.table({"a": pa.array([True, True, False, None]),
                       "b": pa.array([None, True, None, None])}),
             [_bin("and", _col(0), _col(1))],
             [(None,), (True,), (False,), (None,)]),
        Case("three-valued OR",
             pa.table({"a": pa.array([True, False, None]),
                       "b": pa.array([None, None, None])}),
             [_bin("or", _col(0), _col(1))],
             [(True,), (None,), (None,)]),
        Case("in-list with null member is never FALSE",
             pa.table({"a": pa.array([1, 2, None])}),
             [{"kind": "in_list", "child": _col(0),
               "values": [1, None], "type": {"id": "int64"}}],
             [(True,), (None,), (None,)]),
        Case("coalesce picks first non-null",
             pa.table({"a": pa.array([None, 1], type=pa.int64()),
                       "b": pa.array([2, 3], type=pa.int64())}),
             [{"kind": "coalesce", "args": [_col(0), _col(1)]}],
             [(2,), (1,)]),
        Case("null-safe equal",
             pa.table({"a": pa.array([1, None, None]),
                       "b": pa.array([1, None, 2])}),
             [_bin("<=>", _col(0), _col(1))],
             [(True,), (True,), (False,)]),
        Case("case with no match and no else is null",
             pa.table({"a": pa.array([1, 5])}),
             [{"kind": "case",
               "branches": [[_bin("==", _col(0), _lit(1)), _lit(10)]]}],
             [(10,), (None,)]),
    ]


@_suite("DateTimeSuite")
def _dates():
    import datetime as dt
    d = pa.table({"d": pa.array([dt.date(2001, 2, 28),
                                 dt.date(2000, 1, 31)])})
    return [
        Case("date_add / date_sub",
             d, [_fn("date_add", _col(0), _lit(1), rt="date32"),
                 _fn("date_sub", _col(0), _lit(28), rt="date32")],
             [(dt.date(2001, 3, 1), dt.date(2001, 1, 31)),
              (dt.date(2000, 2, 1), dt.date(2000, 1, 3))]),
        Case("add_months clamps to month end",
             d, [_fn("add_months", _col(0), _lit(1), rt="date32")],
             [(dt.date(2001, 3, 28),), (dt.date(2000, 2, 29),)]),
        Case("last_day",
             d, [_fn("last_day", _col(0), rt="date32")],
             [(dt.date(2001, 2, 28),), (dt.date(2000, 1, 31),)]),
        Case("year month day dayofweek",
             d, [_fn("year", _col(0), rt="int32"),
                 _fn("month", _col(0), rt="int32"),
                 _fn("dayofweek", _col(0), rt="int32")],
             [(2001, 2, 4), (2000, 1, 2)]),  # dayofweek: 1=Sunday
        Case("datediff is signed",
             pa.table({"a": pa.array([dt.date(2001, 1, 10)]),
                       "b": pa.array([dt.date(2001, 1, 1)])}),
             [_fn("datediff", _col(0), _col(1), rt="int32")],
             [(9,)]),
        Case("months_between 31-day fraction",
             pa.table({"a": pa.array([dt.date(2001, 3, 31)]),
                       "b": pa.array([dt.date(2001, 2, 28)])}),
             [_fn("months_between", _col(0), _col(1), rt="float64")],
             [(1.0,)]),
    ]


@_suite("HashSuite")
def _hash():
    # Spark-generated vectors (seed 42): hash(1L)= -7723843922299065623?
    # — authoritative int vectors already live in tests/test_hashing.py;
    # here the corpus pins the EXPRESSION surface (int32 output, null
    # handling: null input leaves the seed untouched)
    return [
        Case("murmur3 null input keeps seed",
             pa.table({"a": pa.array([None], type=pa.int64())}),
             [_fn("murmur3_hash", _col(0), rt="int32")],
             [(42,)]),
        Case("crc32 of utf8 bytes",
             pa.table({"s": pa.array(["ABC"])}),
             [_fn("crc32", _col(0), rt="int64")],
             [(2743272264,)]),
        Case("md5 hex",
             pa.table({"s": pa.array(["abc"])}),
             [_fn("md5", _col(0), rt="utf8")],
             [("900150983cd24fb0d6963f7d28e17f72",)]),
        Case("sha2-256 hex",
             pa.table({"s": pa.array(["abc"])}),
             [_fn("sha2", _col(0), _lit(256), rt="utf8")],
             [("ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff"
               "61f20015ad",)]),
    ]


@_suite("CollectionSuite")
def _coll():
    lst = pa.table({"a": pa.array([[1, 2, 2, None], [], None],
                                  type=pa.list_(pa.int64()))})
    return [
        Case("size of null is -1 (legacy spark.sql.legacy.sizeOfNull)",
             lst, [_fn("size", _col(0), rt="int32")],
             [(4,), (0,), (-1,)]),
        Case("array_distinct keeps order",
             pa.table({"a": pa.array([[3, 1, 3, 2]],
                                     type=pa.list_(pa.int64()))}),
             [_fn("array_distinct", _col(0))],
             [([3, 1, 2],)]),
        Case("array_contains null semantics",
             lst, [_fn("array_contains", _col(0), _lit(2), rt="bool")],
             [(True,), (False,), (None,)]),
        Case("element_at is 1-based",
             pa.table({"a": pa.array([[10, 20]],
                                     type=pa.list_(pa.int64()))}),
             [_fn("element_at", _col(0), _lit(2), rt="int64")],
             [(20,)]),
        Case("str_to_map default delimiters",
             pa.table({"s": pa.array(["a:1,b:2"])}),
             [_fn("map_keys", _fn("str_to_map", _col(0)))],
             [(["a", "b"],)]),
    ]


@_suite("CollectionEdgeSuite")
def _collection_edge():
    arr = pa.table({"a": pa.array([[1, 2, 3], [5], None])})
    return [
        Case("element_at is 1-based, negative from end, OOB null",
             arr,
             [_fn("element_at", _col(0), _lit(2), rt="int64"),
              _fn("element_at", _col(0), _lit(-1), rt="int64"),
              _fn("element_at", _col(0), _lit(4), rt="int64")],
             [(2, 3, None), (None, 5, None), (None, None, None)]),
        Case("array_join skips null elements",
             pa.table({"a": pa.array([["x", None, "y"]])}),
             [_fn("array_join", _col(0), _lit(",", "utf8"), rt="utf8")],
             [("x,y",)]),
        Case("split delimiter is a regex",
             pa.table({"s": pa.array(["a.b.c"])}),
             [_fn("split", _col(0), _lit("\\.", "utf8"))],
             [((["a", "b", "c"]),)]),
    ]


@_suite("RegexpEdgeSuite")
def _regexp_edge():
    return [
        Case("regexp_extract returns empty string on no match",
             pa.table({"s": pa.array(["a123b", "zzz"])}),
             [_fn("regexp_extract", _col(0),
                  _lit("([0-9]+)", "utf8"), _lit(1), rt="utf8")],
             [("123",), ("",)]),
    ]


@_suite("MathSignSuite")
def _math_sign():
    return [
        Case("round HALF_UP is away from zero on negatives",
             pa.table({"a": pa.array([-2.5, 2.5, -0.45])}),
             [_fn("round", _col(0), rt="float64"),
              _fn("round", _col(0), _lit(1), rt="float64")],
             [(-3.0, -2.5), (3.0, 2.5), (-0.0, -0.5)], rtol=1e-12),
        Case("signum preserves negative zero",
             pa.table({"a": pa.array([-0.0, 0.0])}),
             [_fn("signum", _col(0), rt="float64")],
             [(-0.0,), (0.0,)]),
    ]


@_suite("RegexpLikeSuite")
def _regexp():
    s = pa.table({"s": pa.array(["Spark", "park", None, "SPARK"])})
    return [
        Case("LIKE percent and underscore",
             s, [{"kind": "like", "child": _col(0), "pattern": "%par_"}],
             # '%' matches empty or any prefix: both "Spark" and "park"
             # satisfy %par_ ; SPARK fails case-sensitively
             [(True,), (True,), (None,), (False,)]),
        Case("LIKE is case sensitive",
             s, [{"kind": "like", "child": _col(0), "pattern": "spark"}],
             [(False,), (False,), (None,), (False,)]),
        Case("RLIKE finds substring matches",
             s, [{"kind": "rlike", "child": _col(0),
                  "pattern": "ar(k|t)"}],
             [(True,), (True,), (None,), (False,)]),
        Case("regexp_replace all occurrences",
             pa.table({"s": pa.array(["a1b2c3"])}),
             [_fn("regexp_replace", _col(0), _lit("[0-9]", "utf8"),
                  _lit("#", "utf8"), rt="utf8")],
             [("a#b#c#",)]),
        Case("regexp_extract group and no-match empty",
             pa.table({"s": pa.array(["100-200", "foo"])}),
             [_fn("regexp_extract", _col(0),
                  _lit(r"(\d+)-(\d+)", "utf8"), _lit(2), rt="utf8")],
             [("200",), ("",)]),
        Case("split drops nothing by default",
             pa.table({"s": pa.array(["a,b,,c"])}),
             [_fn("split", _col(0), _lit(",", "utf8"))],
             [(["a", "b", "", "c"],)]),
    ]


@_suite("JsonSuite")
def _json():
    j = pa.table({"j": pa.array(
        ['{"a": 1, "b": {"c": "x"}, "d": [5, 6]}', "not json", None])})
    return [
        Case("get_json_object dotted path",
             j, [_fn("get_json_object", _col(0), _lit("$.b.c", "utf8"),
                     rt="utf8")],
             [("x",), (None,), (None,)]),
        Case("get_json_object array index",
             j, [_fn("get_json_object", _col(0), _lit("$.d[1]", "utf8"),
                     rt="utf8")],
             [("6",), (None,), (None,)]),
        Case("get_json_object missing key is null",
             j, [_fn("get_json_object", _col(0), _lit("$.zz", "utf8"),
                     rt="utf8")],
             [(None,), (None,), (None,)]),
    ]


@_suite("DecimalSuite")
def _decimal():
    dec = {"id": "decimal", "precision": 10, "scale": 2}
    return [
        Case("cast int to decimal renders full scale",
             pa.table({"a": pa.array([7, None])}),
             [{"kind": "cast",
               "child": {"kind": "cast", "child": _col(0), "type": dec},
               "type": {"id": "utf8"}}],
             [("7.00",), (None,)]),
        Case("decimal overflow to null (non-ANSI)",
             pa.table({"a": pa.array([10 ** 12])}),
             [{"kind": "cast", "child": _col(0),
               "type": {"id": "decimal", "precision": 5, "scale": 2}}],
             [(None,)]),
        Case("string to decimal HALF_UP at scale",
             pa.table({"s": pa.array(["1.005", "-1.005"])}),
             [{"kind": "cast",
               "child": {"kind": "cast", "child": _col(0), "type": dec},
               "type": {"id": "utf8"}}],
             [("1.01",), ("-1.01",)]),
    ]


@_suite("TrigMathSuite")
def _trig():
    import math
    x = pa.table({"x": pa.array([0.0, 0.5, None])})
    return [
        Case("sin/cos/tan at zero",
             pa.table({"x": pa.array([0.0])}),
             [_fn("sin", _col(0), rt="float64"),
              _fn("cos", _col(0), rt="float64"),
              _fn("tan", _col(0), rt="float64")],
             [(0.0, 1.0, 0.0)], rtol=1e-12),
        Case("asin/acos outside [-1,1] give NaN, not error",
             pa.table({"x": pa.array([2.0, -2.0])}),
             [_fn("asin", _col(0), rt="float64"),
              _fn("acos", _col(0), rt="float64")],
             [(float("nan"), float("nan")),
              (float("nan"), float("nan"))]),
        Case("asin/atan principal values",
             x,
             [_fn("asin", _col(0), rt="float64"),
              _fn("atan", _col(0), rt="float64")],
             [(0.0, 0.0), (math.asin(0.5), math.atan(0.5)),
              (None, None)], rtol=1e-12),
        Case("hyperbolics and exp",
             pa.table({"x": pa.array([1.0])}),
             [_fn("sinh", _col(0), rt="float64"),
              _fn("cosh", _col(0), rt="float64"),
              _fn("tanh", _col(0), rt="float64"),
              _fn("exp", _col(0), rt="float64")],
             [(math.sinh(1.0), math.cosh(1.0), math.tanh(1.0),
               math.e)], rtol=1e-12),
        Case("degrees/radians round trip",
             pa.table({"x": pa.array([math.pi, 0.0])}),
             [_fn("degrees", _col(0), rt="float64")],
             [(180.0,), (0.0,)], rtol=1e-12),
        Case("radians of 180",
             pa.table({"x": pa.array([180.0])}),
             [_fn("radians", _col(0), rt="float64")],
             [(math.pi,)], rtol=1e-12),
        Case("negative flips sign, passes null",
             pa.table({"x": pa.array([5, -3, None])}),
             [_fn("negative", _col(0), rt="int64")],
             [(-5,), (3,), (None,)]),
        Case("isnan: null input is false, not null",
             pa.table({"x": pa.array([float("nan"), 1.0, None])}),
             [_fn("isnan", _col(0), rt="bool")],
             [(True,), (False,), (False,)]),
        Case("ceil/floor return LONG for double input",
             pa.table({"x": pa.array([2.5, -0.1, -2.5, None])}),
             [_fn("ceil", _col(0), rt="int64"),
              _fn("floor", _col(0), rt="int64")],
             [(3, 2), (0, -1), (-2, -3), (None, None)]),
    ]


@_suite("DateFieldsSuite")
def _date_fields():
    import datetime as _dt
    d = pa.table({"d": pa.array([_dt.date(2016, 4, 9),
                                 _dt.date(2008, 2, 20), None],
                                pa.date32())})
    ts = pa.table({"t": pa.array([_dt.datetime(2015, 3, 5, 9, 32, 5)],
                                 pa.timestamp("us"))})
    return [
        Case("day/dayofmonth agree",
             d, [_fn("day", _col(0), rt="int32"),
                 _fn("dayofmonth", _col(0), rt="int32")],
             [(9, 9), (20, 20), (None, None)]),
        Case("dayofyear",
             d, [_fn("dayofyear", _col(0), rt="int32")],
             [(100,), (51,), (None,)]),
        Case("weekofyear is ISO-8601",
             d, [_fn("weekofyear", _col(0), rt="int32")],
             [(14,), (8,), (None,)]),
        Case("quarter",
             d, [_fn("quarter", _col(0), rt="int32")],
             [(2,), (1,), (None,)]),
        Case("hour/minute/second from timestamp",
             ts, [_fn("hour", _col(0), rt="int32"),
                  _fn("minute", _col(0), rt="int32"),
                  _fn("second", _col(0), rt="int32")],
             [(9, 32, 5)]),
    ]


@_suite("DateNavSuite")
def _date_nav():
    import datetime as _dt
    d = pa.table({"d": pa.array([_dt.date(2016, 4, 9),   # a Saturday
                                 _dt.date(2019, 8, 4)],  # a Sunday
                                pa.date32())})
    return [
        Case("next_day by abbreviated day name",
             d, [_fn("next_day", _col(0), _lit("TU", "utf8"),
                     rt="date32")],
             [(_dt.date(2016, 4, 12),), (_dt.date(2019, 8, 6),)]),
        Case("next_day invalid day name yields null (non-ANSI)",
             d, [_fn("next_day", _col(0), _lit("XX", "utf8"),
                     rt="date32")],
             [(None,), (None,)]),
        Case("trunc to month and ISO week (Monday)",
             d, [_fn("trunc", _col(0), _lit("MM", "utf8"), rt="date32"),
                 _fn("trunc", _col(0), _lit("week", "utf8"),
                     rt="date32")],
             [(_dt.date(2016, 4, 1), _dt.date(2016, 4, 4)),
              (_dt.date(2019, 8, 1), _dt.date(2019, 7, 29))]),
        Case("date_trunc HOUR on timestamp",
             pa.table({"t": pa.array(
                 [_dt.datetime(2015, 3, 5, 9, 32, 5, 359000)],
                 pa.timestamp("us"))}),
             [_fn("date_trunc", _lit("HOUR", "utf8"), _col(0))],
             [(_dt.datetime(2015, 3, 5, 9, 0),)]),
        Case("to_date parses date and timestamp strings, null on junk",
             pa.table({"s": pa.array(["2009-07-30 04:17:52",
                                      "2016-12-31", "bad"])}),
             [_fn("to_date", _col(0), rt="date32")],
             [(_dt.date(2009, 7, 30),), (_dt.date(2016, 12, 31),),
              (None,)]),
        Case("from_unixtime default pattern, UTC session tz",
             pa.table({"u": pa.array([0, 86400])}),
             [_fn("from_unixtime", _col(0), rt="utf8")],
             [("1970-01-01 00:00:00",), ("1970-01-02 00:00:00",)]),
        Case("unix_timestamp parses default pattern, null on junk",
             pa.table({"s": pa.array(["1970-01-02 00:00:00",
                                      "2016-04-09", "junk", None])}),
             [_fn("unix_timestamp", _col(0))],
             [(86400,), (1460160000,), (None,), (None,)]),
    ]


@_suite("ArrayExtraSuite")
def _array_extra():
    lt = pa.list_(pa.int64())
    a = pa.table({"a": pa.array([[2, 1, None], [5], None], lt)})
    return [
        Case("array_min/max skip nulls inside the array",
             a, [_fn("array_min", _col(0), rt="int64"),
                 _fn("array_max", _col(0), rt="int64")],
             [(1, 2), (5, 5), (None, None)]),
        Case("cardinality counts elements; null input is -1 "
             "(legacy sizeOfNull, the Spark default)",
             a, [_fn("cardinality", _col(0), rt="int32")],
             [(3,), (1,), (-1,)]),
        Case("array_union dedups keeping first-seen order",
             pa.table({"a": pa.array([[1, 2, 2]], lt),
                       "b": pa.array([[2, 3, 1]], lt)}),
             [_fn("array_union", _col(0), _col(1))],
             [([1, 2, 3],)]),
        Case("array builder from columns",
             pa.table({"x": pa.array([1, 4]), "y": pa.array([2, 5])}),
             [_fn("make_array", _col(0), _col(1))],
             [([1, 2],), ([4, 5],)]),
        Case("map_values",
             pa.table({"m": pa.array([[("a", 1), ("b", 2)]],
                                     pa.map_(pa.utf8(), pa.int64()))}),
             [_fn("map_values", _col(0))],
             [([1, 2],)]),
    ]


@_suite("CaseTrimSuite")
def _case_trim():
    return [
        Case("upper/lower",
             pa.table({"s": pa.array(["Spark", None])}),
             [_fn("upper", _col(0), rt="utf8"),
              _fn("lower", _col(0), rt="utf8")],
             [("SPARK", "spark"), (None, None)]),
        Case("trim strips ONLY spaces, not tabs "
             "(UTF8String.trim semantics)",
             pa.table({"s": pa.array(["  \tabc \t ", " x "])}),
             [_fn("trim", _col(0), rt="utf8")],
             [("\tabc \t",), ("x",)]),
        Case("ltrim/rtrim one-sided space strip",
             pa.table({"s": pa.array([" \ta "])}),
             [_fn("ltrim", _col(0), rt="utf8"),
              _fn("rtrim", _col(0), rt="utf8")],
             [("\ta ", " \ta")]),
        Case("rpad truncates when target is shorter",
             pa.table({"s": pa.array(["abcd", "ab"])}),
             [_fn("rpad", _col(0), _lit(3), _lit("x", "utf8"),
                  rt="utf8")],
             [("abc",), ("abx",)]),
        Case("substr position 0 behaves as 1; negative counts "
             "from the end",
             pa.table({"s": pa.array(["Spark"])}),
             [_fn("substr", _col(0), _lit(0), _lit(3), rt="utf8"),
              _fn("substr", _col(0), _lit(-3), _lit(2), rt="utf8")],
             [("Spa", "ar")]),
    ]


@_suite("HashExprSuite")
def _hash_expr():
    return [
        Case("hash() is Spark murmur3 seed 42, bit-exact",
             pa.table({"x": pa.array([1, 2], pa.int32())}),
             [_fn("hash", _col(0), rt="int32")],
             [(-559580957,), (1765031574,)]),
        Case("xxhash64 seed 42, bit-exact",
             pa.table({"x": pa.array([1], pa.int64())}),
             [_fn("xxhash64", _col(0), rt="int64")],
             [(-7001672635703045582,)]),
    ]


# ---------------------------------------------------------------------------
# runner (ref SparkQueryTestsBase: run case, compare, report)
# ---------------------------------------------------------------------------

@dataclass
class CaseResult:
    suite: str
    case: str
    passed: bool
    detail: str = ""


def _values_equal(got, want, rtol: float) -> bool:
    if want is None or got is None:
        return got is None and want is None
    if isinstance(want, float):
        if math.isnan(want):
            return isinstance(got, float) and math.isnan(got)
        if rtol:
            return got == want or abs(got - want) <= rtol * abs(want)
        return float(got) == want
    return got == want


def _scan_ir(rid: str, table: pa.Table) -> dict:
    from blaze_tpu.plan.types import schema_to_dict
    from blaze_tpu.schema import Schema
    return {"kind": "memory_scan", "resource_id": rid,
            "schema": schema_to_dict(Schema.from_arrow(table.schema)),
            "num_partitions": 1}


def run_case(suite: str, case: Case) -> CaseResult:
    from blaze_tpu import config
    from blaze_tpu.bridge.resource import put_resource
    from blaze_tpu.plan import create_plan

    rid = f"corpus://{suite}/{case.name}"
    put_resource(rid, case.input)
    scan = _scan_ir(rid, case.input)
    if case.plan is not None:
        if case.input2 is not None:
            rid2 = rid + "/2"
            put_resource(rid2, case.input2)
            ir = case.plan(scan, _scan_ir(rid2, case.input2))
        else:
            ir = case.plan(scan)
    else:
        ir = {"kind": "project",
              "exprs": case.exprs,
              "names": [f"o{i}" for i in range(len(case.exprs))],
              "input": scan}
    try:
        with config.scoped(**(case.confs or {})):
            plan = create_plan(ir)
            batches = [b.compact().to_arrow() for b in plan.execute(0)]
        ncols = (len(case.exprs) if case.plan is None
                 else (len(case.expected[0]) if case.expected else 1))
        tbl = (pa.Table.from_batches(batches) if batches
               else pa.Table.from_batches(
                   [], schema=pa.schema(
                       [(f"o{i}", pa.null()) for i in range(ncols)])))
        got = [tuple(r) for r in zip(*[c.to_pylist()
                                       for c in tbl.columns])] \
            if tbl.num_rows else []
    except Exception as e:  # noqa: BLE001 — recorded, like a test failure
        if case.raises is not None and case.raises in repr(e):
            return CaseResult(suite, case.name, True)
        return CaseResult(suite, case.name, False, f"raised {e!r}")
    if case.raises is not None:
        return CaseResult(suite, case.name, False,
                          f"expected raise {case.raises!r}, got rows")
    if len(got) != len(case.expected):
        return CaseResult(suite, case.name, False,
                          f"rows {len(got)} != {len(case.expected)}")
    want_rows = case.expected
    if case.unordered:
        key = repr
        got = sorted(got, key=key)
        want_rows = sorted(want_rows, key=key)
    for i, (g, w) in enumerate(zip(got, want_rows)):
        if len(g) != len(w):
            return CaseResult(suite, case.name, False,
                              f"row {i}: arity {len(g)} != {len(w)}")
        for j, (gv, wv) in enumerate(zip(g, w)):
            if not _values_equal(gv, wv, case.rtol):
                return CaseResult(
                    suite, case.name, False,
                    f"row {i} col {j}: got {gv!r}, want {wv!r}")
    return CaseResult(suite, case.name, True)


def run_corpus(settings: CorpusSettings) -> List[CaseResult]:
    out: List[CaseResult] = []
    for sname, ss in settings.suites.items():
        for case in SUITES[sname]:
            if ss.selects(case.name):
                out.append(run_case(sname, case))
    return out


# The extended tier (round-5 expansion: cast edges, decimal38, ANSI,
# nested types, NaN/-0.0 ordering, agg/join/window semantics) registers
# its suites into SUITES on import.
from blaze_tpu.itest import spark_corpus_ext  # noqa: E402,F401


def default_settings() -> CorpusSettings:
    """The checked-in settings: every suite enabled; exclusions document
    declared divergences (the SparkTestSettings exclusion-ledger analog).
    An empty ledger means full conformance on the vendored corpus."""
    return CorpusSettings().enable_all()

