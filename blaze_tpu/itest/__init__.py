"""Integration harness (ref: dev/auron-it — TPC-DS golden testing)."""

from blaze_tpu.itest.runner import (QueryResult, check_plan_stability,
                                    compare_frames, normalize_plan,
                                    run_query)
from blaze_tpu.itest.tpcds_data import generate, write_parquet_dataset

__all__ = ["QueryResult", "check_plan_stability", "compare_frames",
           "normalize_plan", "run_query", "generate",
           "write_parquet_dataset"]

# register the breadth-extension queries into QUERIES (import side effect)
from blaze_tpu.itest import queries_ext  # noqa: E402,F401
from blaze_tpu.itest import queries_ext2  # noqa: E402,F401
