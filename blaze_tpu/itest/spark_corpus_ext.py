"""Spark-semantics conformance corpus — extended tier (round 5).

Doubles the vendored corpus where Spark's semantics bite hardest
(VERDICT r4 next-step #6; ref auron-spark-tests re-runs Spark's own
CastSuite / DecimalExpressionSuite / DataFrameAggregateSuite /
JoinSuite / DataFrameWindowFunctionsSuite under the accelerator,
governed by SparkTestSettings.scala:28-160):

  * numeric / string / boolean / timestamp cast edges,
  * decimal(38,_) arithmetic and overflow -> null vs ANSI raise,
  * three-valued logic + null-safe equality,
  * nested struct / array / map access,
  * NaN and -0.0 ordering in sort keys, group keys, join keys, min/max,
  * aggregate null semantics (the DataFrameAggregateSuite analog),
  * join-key semantics incl. null-aware anti,
  * window ranks/ties (the DataFrameWindowFunctionsSuite analog).

Every EXPECTED value encodes documented Spark behavior; plan-shaped
vectors (`Case.plan`) exercise the real operator path, not just
expression evaluation.  Divergences must be excluded with a reason in
`default_settings()` — the declared-divergence ledger.
"""

from __future__ import annotations

import datetime as _dt
import math

import pyarrow as pa

from blaze_tpu.itest.spark_corpus import (Case, _bin, _col, _fn, _lit,
                                          _suite)

NAN = float("nan")
INF = float("inf")
I64MAX = (1 << 63) - 1
I64MIN = -(1 << 63)
I32MAX = (1 << 31) - 1
I32MIN = -(1 << 31)


def _cast(child, t, **kw):
    t = t if isinstance(t, dict) else {"id": t}
    return {"kind": "cast", "child": child, "type": dict(t, **kw)}


def _try_cast(child, t):
    t = t if isinstance(t, dict) else {"id": t}
    return {"kind": "try_cast", "child": child, "type": t}


def _sort_plan(*specs, fetch=None):
    def mk(scan):
        d = {"kind": "sort", "input": scan,
             "specs": [{"expr": _col(i), "descending": desc,
                        "nulls_first": nf}
                       for (i, desc, nf) in specs]}
        if fetch is not None:
            d["fetch"] = fetch
        return d
    return mk


def _agg_plan(group_idx, aggs):
    """aggs: [(fn, arg_expr_or_None, name)] in COMPLETE mode."""
    def mk(scan):
        return {"kind": "hash_agg", "input": scan,
                "groupings": [{"expr": _col(i), "name": f"g{i}"}
                              for i in group_idx],
                "aggs": [{"fn": fn, "mode": "complete", "name": name,
                          "args": ([] if arg is None else [arg])}
                         for fn, arg, name in aggs]}
    return mk


def _join_plan(kind, join_type, lkeys=(0,), rkeys=(0,), **kw):
    def mk(scan, scan2):
        d = {"kind": kind, "left": scan, "right": scan2,
             "left_keys": [_col(i) for i in lkeys],
             "right_keys": [_col(i) for i in rkeys],
             "join_type": join_type}
        d.update(kw)
        return d
    return mk


# ---------------------------------------------------------------------------
# cast suites (ref CastSuite / native cast.rs)
# ---------------------------------------------------------------------------

@_suite("CastNumericSuite")
def _cast_numeric():
    return [
        Case("double to int saturates at int bounds (Scala toInt)",
             pa.table({"x": pa.array([1e20, -1e20, 2.9, -2.9, None])}),
             [_cast(_col(0), "int32")],
             [(I32MAX,), (I32MIN,), (2,), (-2,), (None,)]),
        Case("NaN to int is 0, not null",
             pa.table({"x": pa.array([NAN, INF, -INF])}),
             [_cast(_col(0), "int32")],
             [(0,), (I32MAX,), (I32MIN,)]),
        Case("long to int truncates low 32 bits (two's complement)",
             pa.table({"x": pa.array([4294967297, -1, 1 << 31, None])}),
             [_cast(_col(0), "int32")],
             [(1,), (-1,), (I32MIN,), (None,)]),
        Case("long to short and byte wrap",
             pa.table({"x": pa.array([65537, 257])}),
             [_cast(_col(0), "int16"), _cast(_col(0), "int8")],
             [(1, 1), (257, 1)]),
        Case("int to double is exact for small values",
             pa.table({"x": pa.array([7, -7, None])}),
             [_cast(_col(0), "float64")],
             [(7.0,), (-7.0,), (None,)]),
        Case("double to long saturates",
             pa.table({"x": pa.array([1e30, -1e30])}),
             [_cast(_col(0), "int64")],
             [(I64MAX,), (I64MIN,)]),
        Case("float widens to double",
             pa.table({"x": pa.array([1.5], pa.float32())}),
             [_cast(_col(0), "float64")],
             [(1.5,)]),
        Case("bool to int is 0/1",
             pa.table({"b": pa.array([True, False, None])}),
             [_cast(_col(0), "int32")],
             [(1,), (0,), (None,)]),
        Case("int to bool is zero-test",
             pa.table({"x": pa.array([0, 1, -3, None])}),
             [_cast(_col(0), "bool")],
             [(False,), (True,), (True,), (None,)]),
    ]


@_suite("CastStringNumericSuite")
def _cast_string_numeric():
    return [
        Case("string to int trims whitespace",
             pa.table({"s": pa.array([" 42 ", "\t7\n", "-7"])}),
             [_cast(_col(0), "int32")],
             [(42,), (7,), (-7,)]),
        Case("string with decimal point truncates toward zero",
             pa.table({"s": pa.array(["42.5", "-42.9", "1.0"])}),
             [_cast(_col(0), "int32")],
             [(42,), (-42,), (1,)]),
        Case("non-numeric string to int is null (non-ANSI)",
             pa.table({"s": pa.array(["0x1A", "", "abc", "1 2"])}),
             [_cast(_col(0), "int32")],
             [(None,), (None,), (None,), (None,)]),
        Case("string to double parses scientific notation",
             pa.table({"s": pa.array(["1.5e2", "-2E-1", ".5"])}),
             [_cast(_col(0), "float64")],
             [(150.0,), (-0.2,), (0.5,)]),
        Case("string Infinity/NaN spellings to double",
             pa.table({"s": pa.array(["Infinity", "-Infinity", "NaN",
                                      "inf"])}),
             [_cast(_col(0), "float64")],
             [(INF,), (-INF,), (NAN,), (INF,)]),
        Case("int renders to string without sign noise",
             pa.table({"x": pa.array([42, -7, 0, None])}),
             [_cast(_col(0), "utf8")],
             [("42",), ("-7",), ("0",), (None,)]),
        Case("double renders Spark-style",
             pa.table({"x": pa.array([1.5, -0.5])}),
             [_cast(_col(0), "utf8")],
             [("1.5",), ("-0.5",)]),
        Case("bool renders lowercase true/false",
             pa.table({"b": pa.array([True, False])}),
             [_cast(_col(0), "utf8")],
             [("true",), ("false",)]),
    ]


@_suite("CastBooleanSuite")
def _cast_boolean():
    return [
        Case("accepted true spellings",
             pa.table({"s": pa.array(["t", "true", "y", "yes", "1",
                                      "TRUE"])}),
             [_cast(_col(0), "bool")],
             [(True,)] * 6),
        Case("accepted false spellings",
             pa.table({"s": pa.array(["f", "false", "n", "no", "0",
                                      "FALSE"])}),
             [_cast(_col(0), "bool")],
             [(False,)] * 6),
        Case("unrecognized string to bool is null (non-ANSI)",
             pa.table({"s": pa.array(["2", "tr", "", "on"])}),
             [_cast(_col(0), "bool")],
             [(None,), (None,), (None,), (None,)]),
    ]


@_suite("CastTimestampSuite")
def _cast_timestamp():
    ts = _dt.datetime(2015, 3, 5, 9, 32, 5)
    us = int(ts.replace(tzinfo=_dt.timezone.utc).timestamp() * 1_000_000)
    return [
        Case("timestamp to long is epoch SECONDS (floored)",
             pa.table({"t": pa.array([us, us + 999_999],
                                     pa.timestamp("us"))}),
             [_cast(_col(0), "int64")],
             [(1425547925,), (1425547925,)]),
        Case("long to timestamp treats input as seconds",
             pa.table({"x": pa.array([1425547925, 0])}),
             [_cast(_col(0), "timestamp_us")],
             [(ts,), (_dt.datetime(1970, 1, 1),)]),
        Case("date to timestamp is midnight",
             pa.table({"d": pa.array([_dt.date(2016, 4, 9), None],
                                     pa.date32())}),
             [_cast(_col(0), "timestamp_us")],
             [(_dt.datetime(2016, 4, 9, 0, 0, 0),), (None,)]),
        Case("timestamp to date truncates time-of-day",
             pa.table({"t": pa.array([us], pa.timestamp("us"))}),
             [_cast(_col(0), "date32")],
             [(_dt.date(2015, 3, 5),)]),
        Case("timestamp renders ISO with space separator",
             pa.table({"t": pa.array([us], pa.timestamp("us"))}),
             [_cast(_col(0), "utf8")],
             [("2015-03-05 09:32:05",)]),
        Case("string to date, junk is null (non-ANSI)",
             pa.table({"s": pa.array(["2016-04-09", "2016-4-9",
                                      "not a date", None])}),
             [_cast(_col(0), "date32")],
             [(_dt.date(2016, 4, 9),), (_dt.date(2016, 4, 9),),
              (None,), (None,)]),
        Case("double to timestamp keeps fraction as micros",
             pa.table({"x": pa.array([1.5])}),
             [_cast(_col(0), "timestamp_us")],
             [(_dt.datetime(1970, 1, 1, 0, 0, 1, 500000),)]),
    ]


# ---------------------------------------------------------------------------
# decimal38 (ref DecimalExpressionSuite / spark_check_overflow.rs)
# ---------------------------------------------------------------------------

@_suite("Decimal38Suite")
def _decimal38():
    d38 = {"id": "decimal", "precision": 38, "scale": 18}
    d38s2 = {"id": "decimal", "precision": 38, "scale": 2}
    return [
        Case("int to decimal(38,18) renders full scale",
             pa.table({"a": pa.array([7, -3, None])}),
             [_cast(_cast(_col(0), d38), "utf8")],
             [("7.000000000000000000",), ("-3.000000000000000000",),
              (None,)]),
        Case("string to decimal(38,18) keeps 18 digits",
             pa.table({"s": pa.array(["1.234567890123456789",
                                      "-0.000000000000000001"])}),
             [_cast(_cast(_col(0), d38), "utf8")],
             [("1.234567890123456789",), ("-0.000000000000000001",)]),
        Case("decimal(38,2) holds values beyond int64 unscaled",
             pa.table({"s": pa.array(["123456789012345678901234567890.12"])}),
             [_cast(_cast(_col(0), d38s2), "utf8")],
             [("123456789012345678901234567890.12",)]),
        Case("rescale 38,18 -> 10,2 rounds HALF_UP",
             pa.table({"s": pa.array(["1.005000000000000000",
                                      "-1.005000000000000000"])}),
             [_cast(_cast(_cast(_col(0), d38),
                          {"id": "decimal", "precision": 10, "scale": 2}),
                    "utf8")],
             [("1.01",), ("-1.01",)]),
        Case("narrowing overflow to null (non-ANSI)",
             pa.table({"s": pa.array(["123456789012345678901234567890.12",
                                      "1.00"])}),
             [_cast(_cast(_cast(_col(0), d38s2),
                          {"id": "decimal", "precision": 5, "scale": 2}),
                    "utf8")],
             [(None,), ("1.00",)]),
        Case("decimal to long truncates the fraction",
             pa.table({"s": pa.array(["42.99", "-42.99"])}),
             [_cast(_cast(_col(0),
                          {"id": "decimal", "precision": 10, "scale": 2}),
                    "int64")],
             [(42,), (-42,)]),
        Case("decimal to double is exact at short scale",
             pa.table({"s": pa.array(["2.50"])}),
             [_cast(_cast(_col(0),
                          {"id": "decimal", "precision": 10, "scale": 2}),
                    "float64")],
             [(2.5,)]),
        Case("make_decimal/unscaled_value round trip",
             pa.table({"x": pa.array([12345])}),
             [{"kind": "scalar_function", "name": "unscaled_value",
               "args": [{"kind": "scalar_function", "name": "make_decimal",
                         "args": [_col(0)],
                         "return_type": {"id": "decimal", "precision": 10,
                                         "scale": 2}}],
               "return_type": {"id": "int64"}}],
             [(12345,)]),
    ]


# ---------------------------------------------------------------------------
# ANSI mode (ref ansi-enabled suite splits in SparkTestSettings)
# ---------------------------------------------------------------------------

_ANSI_ON = {"spark.sql.ansi.enabled": "true"}


@_suite("AnsiModeSuite")
def _ansi():
    return [
        Case("ANSI: invalid string to int raises",
             pa.table({"s": pa.array(["abc"])}),
             [_cast(_col(0), "int32")], [], confs=_ANSI_ON,
             raises="CAST_INVALID_INPUT"),
        Case("ANSI: valid string to int still casts",
             pa.table({"s": pa.array(["42"])}),
             [_cast(_col(0), "int32")],
             [(42,)], confs=_ANSI_ON),
        Case("ANSI: try_cast stays null on invalid input",
             pa.table({"s": pa.array(["abc", "7"])}),
             [_try_cast(_col(0), "int32")],
             [(None,), (7,)], confs=_ANSI_ON),
        Case("ANSI: array index out of bounds raises",
             pa.table({"a": pa.array([[1, 2, 3]])}),
             [{"kind": "get_indexed_field", "child": _col(0), "index": 9,
               "type": {"id": "int64"}}],
             [], confs=_ANSI_ON, raises="INVALID_ARRAY_INDEX"),
        Case("non-ANSI: same invalid cast is null",
             pa.table({"s": pa.array(["abc"])}),
             [_cast(_col(0), "int32")],
             [(None,)]),
        Case("ANSI: element_at out of bounds raises",
             pa.table({"a": pa.array([[1, 2]])}),
             [_fn("element_at", _col(0), _lit(5), rt="int64")],
             [], confs=_ANSI_ON,
             raises="INVALID_ARRAY_INDEX_IN_ELEMENT_AT"),
        Case("ANSI: element_at on a missing map key raises",
             pa.table({"m": pa.array([[("a", 1)]],
                                     pa.map_(pa.utf8(), pa.int64()))}),
             [_fn("element_at", _col(0), _lit("zz", "utf8"),
                  rt="int64")],
             [], confs=_ANSI_ON, raises="MAP_KEY_DOES_NOT_EXIST"),
        Case("months_between roundOff=false keeps full precision",
             pa.table({"a": pa.array([_dt.date(2020, 1, 14)],
                                     pa.date32()),
                       "b": pa.array([_dt.date(2020, 1, 10)],
                                     pa.date32())}),
             [_fn("months_between", _col(0), _col(1),
                  _lit(False, "bool"), rt="float64")],
             [(4.0 / 31.0,)]),
        Case("raises honor the filter selection mask",
             # row 2 has i=0, which would raise INVALID_INDEX_OF_ZERO —
             # but the filter deselects it, so the query must succeed
             pa.table({"a": pa.array([[1, 2], [3]]),
                       "i": pa.array([2, 0])}),
             [], [(2,)],
             plan=lambda scan: {
                 "kind": "project",
                 "exprs": [_fn("element_at", _col(0), _col(1),
                               rt="int64")],
                 "names": ["v"],
                 "input": {"kind": "filter",
                           "predicates": [_bin("!=", _col(1), _lit(0))],
                           "input": scan}}),
    ]


# ---------------------------------------------------------------------------
# three-valued logic (ref PredicateSuite)
# ---------------------------------------------------------------------------

@_suite("ThreeValuedLogicSuite")
def _tvl():
    b = pa.table({"a": pa.array([True, True, False, False, None, None]),
                  "b": pa.array([True, None, True, None, True, None])})
    return [
        Case("Kleene AND truth table",
             b, [_bin("and", _col(0), _col(1))],
             [(True,), (None,), (False,), (False,), (None,), (None,)]),
        Case("Kleene OR truth table",
             b, [_bin("or", _col(0), _col(1))],
             [(True,), (True,), (True,), (None,), (True,), (None,)]),
        Case("NOT of null is null",
             pa.table({"a": pa.array([True, False, None])}),
             [{"kind": "not", "child": _col(0)}],
             [(False,), (True,), (None,)]),
        Case("null-safe equal over null patterns",
             pa.table({"a": pa.array([1, None, None, 2]),
                       "b": pa.array([1, None, 3, 9])}),
             [_bin("<=>", _col(0), _col(1))],
             [(True,), (True,), (False,), (False,)]),
        Case("comparison with null is null, not false",
             pa.table({"a": pa.array([1, None])}),
             [_bin("<", _col(0), _lit(5)),
              _bin("==", _col(0), _lit(1))],
             [(True, True), (None, None)]),
        Case("in-list: match wins over null member",
             pa.table({"a": pa.array([1, 3, None])}),
             [{"kind": "in_list", "child": _col(0), "values": [1, None]}],
             [(True,), (None,), (None,)]),
        Case("negated in-list keeps null as null",
             pa.table({"a": pa.array([1, 3])}),
             [{"kind": "in_list", "child": _col(0), "values": [1, None],
               "negated": True}],
             [(False,), (None,)]),
        Case("is_null / is_not_null never return null",
             pa.table({"a": pa.array([1, None])}),
             [{"kind": "is_null", "child": _col(0)},
              {"kind": "is_not_null", "child": _col(0)}],
             [(False, True), (True, False)]),
        Case("if with null condition takes else",
             pa.table({"c": pa.array([True, False, None])}),
             [{"kind": "if", "cond": _col(0), "then": _lit(1),
               "else": _lit(2)}],
             [(1,), (2,), (2,)]),
    ]


# ---------------------------------------------------------------------------
# bitwise (ref BitwiseExpressionsSuite)
# ---------------------------------------------------------------------------

@_suite("BitwiseSuite")
def _bitwise():
    t = pa.table({"a": pa.array([0b1100, -1, None]),
                  "b": pa.array([0b1010, 1, 1])})
    return [
        Case("AND/OR/XOR with negatives and nulls",
             t, [_bin("&", _col(0), _col(1)),
                 _bin("|", _col(0), _col(1)),
                 _bin("^", _col(0), _col(1))],
             [(0b1000, 0b1110, 0b0110), (1, -1, -2), (None, None, None)]),
        Case("shift left grows, arithmetic shift right keeps sign",
             pa.table({"a": pa.array([1, -8])}),
             [_bin("<<", _col(0), _lit(3)),
              _bin(">>", _col(0), _lit(1))],
             [(8, 0), (-64, -4)]),
        Case("xor with self is zero",
             pa.table({"a": pa.array([12345, -9])}),
             [_bin("^", _col(0), _col(0))],
             [(0,), (0,)]),
    ]


# ---------------------------------------------------------------------------
# nested types (ref ComplexTypeSuite)
# ---------------------------------------------------------------------------

@_suite("NestedStructSuite")
def _nested_struct():
    s = pa.table({"s": pa.array([{"x": 1, "y": "a"},
                                 {"x": 2, "y": None}, None],
                                pa.struct([("x", pa.int64()),
                                           ("y", pa.utf8())]))})
    return [
        Case("struct field access by ordinal",
             s, [{"kind": "get_indexed_field", "child": _col(0),
                  "index": 0, "type": {"id": "int64"}}],
             [(1,), (2,), (None,)]),
        Case("null struct yields null field, not garbage",
             s, [{"kind": "get_indexed_field", "child": _col(0),
                  "index": 1, "type": {"id": "utf8"}}],
             [("a",), (None,), (None,)]),
        Case("named_struct builds then projects back",
             pa.table({"a": pa.array([5, None])}),
             [{"kind": "get_indexed_field",
               "child": {"kind": "named_struct", "names": ["v", "w"],
                         "args": [_col(0), _lit(9)]},
               "index": 0, "type": {"id": "int64"}}],
             [(5,), (None,)]),
        Case("nested struct-in-struct access",
             pa.table({"s": pa.array(
                 [{"inner": {"z": 7}}, None],
                 pa.struct([("inner", pa.struct([("z", pa.int64())]))]))}),
             [{"kind": "get_indexed_field",
               "child": {"kind": "get_indexed_field", "child": _col(0),
                         "index": 0,
                         "type": {"id": "struct",
                                  "children": [{"name": "z",
                                                "type": {"id": "int64"}}]}},
               "index": 0, "type": {"id": "int64"}}],
             [(7,), (None,)]),
    ]


@_suite("MapAccessSuite")
def _map_access():
    m = pa.table({"m": pa.array([[("k1", 1), ("k2", 2)], [], None],
                                pa.map_(pa.utf8(), pa.int64()))})
    return [
        Case("map value by literal key",
             m, [{"kind": "get_map_value", "child": _col(0), "key": "k2",
                  "type": {"id": "int64"}}],
             [(2,), (None,), (None,)]),
        Case("missing key is null",
             m, [{"kind": "get_map_value", "child": _col(0), "key": "zz",
                  "type": {"id": "int64"}}],
             [(None,), (None,), (None,)]),
        Case("map_keys preserves insertion order",
             m, [_fn("map_keys", _col(0))],
             [(["k1", "k2"],), ([],), (None,)]),
        Case("element_at on map is key lookup",
             m, [_fn("element_at", _col(0), _lit("k1", "utf8"),
                     rt="int64")],
             [(1,), (None,), (None,)]),
        Case("cardinality of a map counts entries",
             m, [_fn("cardinality", _col(0), rt="int32")],
             [(2,), (0,), (-1,)]),
    ]


@_suite("ArrayAccessSuite")
def _array_access():
    a = pa.table({"a": pa.array([[10, 20, 30], [], None])})
    return [
        Case("array ordinal access, OOB is null (non-ANSI)",
             a, [{"kind": "get_indexed_field", "child": _col(0),
                  "index": 1, "type": {"id": "int64"}},
                 {"kind": "get_indexed_field", "child": _col(0),
                  "index": 9, "type": {"id": "int64"}}],
             [(20, None), (None, None), (None, None)]),
        Case("element_at index 0 raises in every mode",
             pa.table({"a": pa.array([[1, 2, 3]])}),
             [_fn("element_at", _col(0), _lit(0), rt="int64")],
             [], raises="INVALID_INDEX_OF_ZERO"),
        Case("element_at beyond either end is null",
             pa.table({"a": pa.array([[1, 2, 3]])}),
             [_fn("element_at", _col(0), _lit(4), rt="int64"),
              _fn("element_at", _col(0), _lit(-4), rt="int64")],
             [(None, None)]),
        Case("array of strings ordinal access",
             pa.table({"a": pa.array([["x", None, "z"]])}),
             [{"kind": "get_indexed_field", "child": _col(0),
               "index": 1, "type": {"id": "utf8"}}],
             [(None,)]),
        Case("make_array then index round trips",
             pa.table({"x": pa.array([1, 2]), "y": pa.array([3, 4])}),
             [{"kind": "get_indexed_field",
               "child": _fn("make_array", _col(0), _col(1)),
               "index": 1, "type": {"id": "int64"}}],
             [(3,), (4,)]),
    ]


# ---------------------------------------------------------------------------
# NaN / -0.0 ordering (ref DataFrameAggregateSuite "NaN and -0.0" cases)
# ---------------------------------------------------------------------------

@_suite("NaNOrderingSuite")
def _nan_ordering():
    f = pa.table({"x": pa.array([NAN, 1.0, INF, -INF, None])})
    return [
        Case("sort asc: NaN after +Infinity, nulls first",
             f, [], [(None,), (-INF,), (1.0,), (INF,), (NAN,)],
             plan=_sort_plan((0, False, True))),
        Case("sort desc: NaN before +Infinity, nulls last",
             f, [], [(NAN,), (INF,), (1.0,), (-INF,), (None,)],
             plan=_sort_plan((0, True, False))),
        Case("max treats NaN as largest",
             pa.table({"x": pa.array([1.0, NAN, INF])}),
             [], [(NAN,)],
             plan=_agg_plan((), [("max", _col(0), "mx")])),
        Case("min skips NaN (NaN is largest, not smallest)",
             pa.table({"x": pa.array([1.0, NAN, 2.0])}),
             [], [(1.0,)],
             plan=_agg_plan((), [("min", _col(0), "mn")])),
        Case("min of all-NaN group is NaN",
             pa.table({"x": pa.array([NAN, NAN])}),
             [], [(NAN,)],
             plan=_agg_plan((), [("min", _col(0), "mn")])),
        Case("group keys: all NaN bit patterns are one group",
             pa.table({"k": pa.array([NAN, NAN, 1.0]),
                       "v": pa.array([1, 2, 3])}),
             [], [(1.0, 3), (NAN, 3)], unordered=True,
             plan=_agg_plan((0,), [("sum", _col(1), "s")])),
        Case("group keys: -0.0 and 0.0 are one group",
             pa.table({"k": pa.array([-0.0, 0.0]),
                       "v": pa.array([1, 2])}),
             [], [(0.0, 3)],
             plan=_agg_plan((0,), [("sum", _col(1), "s")])),
    ]


@_suite("SortNullsSuite")
def _sort_nulls():
    t = pa.table({"a": pa.array([3, None, 1, 2]),
                  "b": pa.array(["x", "y", "z", None])})
    return [
        Case("asc nulls first (Spark default asc)",
             t, [], [(None, "y"), (1, "z"), (2, None), (3, "x")],
             plan=_sort_plan((0, False, True))),
        Case("asc nulls last",
             t, [], [(1, "z"), (2, None), (3, "x"), (None, "y")],
             plan=_sort_plan((0, False, False))),
        Case("desc nulls last (Spark default desc)",
             t, [], [(3, "x"), (2, None), (1, "z"), (None, "y")],
             plan=_sort_plan((0, True, False))),
        Case("desc nulls first",
             t, [], [(None, "y"), (3, "x"), (2, None), (1, "z")],
             plan=_sort_plan((0, True, True))),
        Case("two keys: second breaks ties incl. null",
             pa.table({"a": pa.array([1, 1, 1]),
                       "b": pa.array([None, "b", "a"])}),
             [], [(1, None), (1, "a"), (1, "b")],
             plan=_sort_plan((0, False, True), (1, False, True))),
        Case("top-n fetch keeps sort contract",
             pa.table({"a": pa.array([5, 1, 4, 2, 3])}),
             [], [(1,), (2,)],
             plan=_sort_plan((0, False, True), fetch=2)),
        Case("utf8 sort is bytewise, empty first",
             pa.table({"s": pa.array(["b", "", "a", "B"])}),
             [], [("",), ("B",), ("a",), ("b",)],
             plan=_sort_plan((0, False, True))),
    ]


# ---------------------------------------------------------------------------
# aggregate null semantics (ref DataFrameAggregateSuite)
# ---------------------------------------------------------------------------

@_suite("AggNullSemanticsSuite")
def _agg_nulls():
    t = pa.table({"k": pa.array(["a", "a", "b", "b"]),
                  "v": pa.array([1, None, None, None])})
    return [
        Case("count(1) counts rows, count(col) skips nulls",
             t, [], [("a", 2, 1), ("b", 2, 0)], unordered=True,
             plan=_agg_plan((0,), [("count", _lit(1), "c1"),
                                   ("count", _col(1), "cv")])),
        Case("sum of an all-null group is null, not 0",
             t, [], [("a", 1), ("b", None)], unordered=True,
             plan=_agg_plan((0,), [("sum", _col(1), "s")])),
        Case("avg ignores nulls in the denominator",
             pa.table({"k": pa.array(["a", "a", "a"]),
                       "v": pa.array([2, None, 4])}),
             [], [("a", 3.0)],
             plan=_agg_plan((0,), [("avg", _col(1), "m")])),
        Case("min/max of all-null group are null",
             t, [], [("a", 1, 1), ("b", None, None)], unordered=True,
             plan=_agg_plan((0,), [("min", _col(1), "mn"),
                                   ("max", _col(1), "mx")])),
        Case("global agg over empty input: count 0, sum null",
             pa.table({"v": pa.array([], pa.int64())}),
             [], [(0, None)],
             plan=_agg_plan((), [("count", _col(0), "c"),
                                 ("sum", _col(0), "s")])),
        Case("grouped agg over empty input has no rows",
             pa.table({"k": pa.array([], pa.utf8()),
                       "v": pa.array([], pa.int64())}),
             [], [],
             plan=_agg_plan((0,), [("sum", _col(1), "s")])),
        Case("sum int64 overflow wraps (non-ANSI)",
             pa.table({"v": pa.array([I64MAX, 1])}),
             [], [(I64MIN,)],
             plan=_agg_plan((), [("sum", _col(0), "s")])),
        Case("first takes first row even when null",
             pa.table({"v": pa.array([None, 7, 8])}),
             [], [(None,)],
             plan=_agg_plan((), [("first", _col(0), "f")])),
        Case("first_ignores_null skips leading nulls",
             pa.table({"v": pa.array([None, 7, 8])}),
             [], [(7,)],
             plan=_agg_plan((), [("first_ignores_null", _col(0), "f")])),
        Case("null group key forms its own group",
             pa.table({"k": pa.array(["a", None, None]),
                       "v": pa.array([1, 2, 3])}),
             [], [("a", 1), (None, 5)], unordered=True,
             plan=_agg_plan((0,), [("sum", _col(1), "s")])),
        Case("avg of int column widens to double",
             pa.table({"v": pa.array([1, 2])}),
             [], [(1.5,)],
             plan=_agg_plan((), [("avg", _col(0), "m")])),
        Case("collect_list keeps duplicates, skips nulls",
             pa.table({"v": pa.array([1, None, 1, 2])}),
             [], [([1, 1, 2],)],
             plan=_agg_plan((), [("collect_list", _col(0), "l")])),
    ]


# ---------------------------------------------------------------------------
# join-key semantics (ref JoinSuite / OuterJoinSuite)
# ---------------------------------------------------------------------------

def _join_inputs():
    l = pa.table({"a": pa.array([1.0, NAN, -0.0, None]),
                  "lv": pa.array([10, 20, 30, 40])})
    r = pa.table({"b": pa.array([NAN, 0.0, None]),
                  "rv": pa.array([100, 200, 300])})
    return l, r


@_suite("JoinKeySemanticsSuite")
def _join_keys():
    l, r = _join_inputs()
    il = pa.table({"a": pa.array([1, 2, None]),
                   "lv": pa.array([10, 20, 30])})
    ir = pa.table({"b": pa.array([2, None, 2]),
                   "rv": pa.array([100, 200, 300])})
    out = [
        Case("inner: NaN matches NaN, -0.0 matches 0.0, null never",
             l, [], [(NAN, 20, NAN, 100), (-0.0, 30, 0.0, 200)],
             unordered=True, input2=r,
             plan=_join_plan("hash_join", "inner")),
        Case("SMJ agrees with hash join on NaN/-0.0 keys",
             l, [], [(NAN, 20, NAN, 100), (-0.0, 30, 0.0, 200)],
             unordered=True, input2=r,
             plan=_join_plan("sort_merge_join", "inner")),
        Case("left outer: unmatched and null-keyed rows null-extend",
             l, [], [(NAN, 20, NAN, 100), (-0.0, 30, 0.0, 200),
                     (1.0, 10, None, None), (None, 40, None, None)],
             unordered=True, input2=r,
             plan=_join_plan("hash_join", "left")),
        Case("left semi keeps each match once",
             il, [], [(2, 20)], unordered=True, input2=ir,
             plan=_join_plan("hash_join", "left_semi")),
        Case("left anti keeps null-keyed probe rows",
             il, [], [(1, 10), (None, 30)], unordered=True, input2=ir,
             plan=_join_plan("hash_join", "left_anti")),
        Case("null-aware anti drops everything when build has null",
             il, [], [], input2=ir,
             plan=_join_plan("hash_join", "left_anti",
                             null_aware_anti=True)),
        Case("full outer covers both dangling sides",
             pa.table({"a": pa.array([1, 2]), "lv": pa.array([10, 20])}),
             [], [(1, 10, None, None), (2, 20, 2, 100),
                  (None, None, 3, 300)],
             unordered=True,
             input2=pa.table({"b": pa.array([2, 3]),
                              "rv": pa.array([100, 300])}),
             plan=_join_plan("sort_merge_join", "full")),
        Case("duplicate keys produce the cross product of matches",
             pa.table({"a": pa.array([7, 7]), "lv": pa.array([1, 2])}),
             [], [(7, 1, 7, 100), (7, 1, 7, 200), (7, 2, 7, 100),
                  (7, 2, 7, 200)],
             unordered=True,
             input2=pa.table({"b": pa.array([7, 7]),
                              "rv": pa.array([100, 200])}),
             plan=_join_plan("hash_join", "inner")),
    ]
    return out


# ---------------------------------------------------------------------------
# window functions (ref DataFrameWindowFunctionsSuite)
# ---------------------------------------------------------------------------

def _window_plan(functions, part_idx=(), order=()):
    """Window over Sort — the real plan shape: WindowExec requires its
    child pre-sorted by (partition, order) keys exactly like the
    reference (window_exec.rs expects the planner-inserted SortExec)."""
    def mk(scan):
        sort = {"kind": "sort", "input": scan,
                "specs": ([{"expr": _col(i), "descending": False,
                            "nulls_first": True} for i in part_idx] +
                          [{"expr": _col(i), "descending": d,
                            "nulls_first": not d} for (i, d) in order])}
        return {"kind": "window", "input": sort, "functions": functions,
                "partition_by": [_col(i) for i in part_idx],
                "order_by": [{"expr": _col(i), "descending": d}
                             for (i, d) in order]}
    return mk


@_suite("WindowFunctionsSuite")
def _window_fns():
    t = pa.table({"g": pa.array([1, 1, 1, 2]),
                  "x": pa.array([10, 10, 5, 7])})
    return [
        Case("rank leaves gaps on ties, dense_rank does not",
             t, [],
             [(1, 10, 1, 1, 1), (1, 10, 2, 1, 1), (1, 5, 3, 3, 2),
              (2, 7, 1, 1, 1)],
             plan=_window_plan([{"kind": "row_number", "name": "rn"},
                                {"kind": "rank", "name": "rk"},
                                {"kind": "dense_rank", "name": "dr"}],
                               part_idx=(0,), order=((1, True),))),
        Case("lag at partition head takes default null",
             t, [],
             [(1, 5, None), (1, 10, 5), (1, 10, 10), (2, 7, None)],
             unordered=True,
             plan=_window_plan([{"kind": "lag", "name": "lg",
                                 "expr": _col(1), "offset": 1}],
                               part_idx=(0,), order=((1, False),))),
        Case("lead past partition end is null",
             t, [],
             [(1, 5, 10), (1, 10, 10), (1, 10, None), (2, 7, None)],
             unordered=True,
             plan=_window_plan([{"kind": "lead", "name": "ld",
                                 "expr": _col(1), "offset": 1}],
                               part_idx=(0,), order=((1, False),))),
        Case("running sum over the ordered frame",
             pa.table({"g": pa.array([1, 1, 1]),
                       "x": pa.array([1, 2, 3])}),
             [], [(1, 1, 1), (1, 2, 3), (1, 3, 6)],
             plan=_window_plan([{"kind": "agg", "name": "rs",
                                 "fn": "sum", "args": [_col(1)],
                                 "running": True}],
                               part_idx=(0,), order=((1, False),))),
        Case("unpartitioned window ranks the whole input",
             pa.table({"x": pa.array([3, 1, 2])}),
             [], [(1, 1), (2, 2), (3, 3)], unordered=True,
             plan=_window_plan([{"kind": "row_number", "name": "rn"}],
                               order=((0, False),))),
    ]


# ---------------------------------------------------------------------------
# string predicates beyond the basics (ref StringFunctionsSuite)
# ---------------------------------------------------------------------------

@_suite("StringPredicateExtSuite")
def _string_pred_ext():
    s = pa.table({"s": pa.array(["50%", "50x", "a_b", "axb", None])})
    return [
        Case("LIKE escapes backslashed percent",
             s, [{"kind": "like", "child": _col(0),
                  "pattern": "50\\%"}],
             [(True,), (False,), (False,), (False,), (None,)]),
        Case("LIKE escapes backslashed underscore",
             s, [{"kind": "like", "child": _col(0),
                  "pattern": "a\\_b"}],
             [(False,), (False,), (True,), (False,), (None,)]),
        Case("NOT LIKE keeps null as null",
             pa.table({"s": pa.array(["abc", "xyz", None])}),
             [{"kind": "like", "child": _col(0), "pattern": "a%",
               "negated": True}],
             [(False,), (True,), (None,)]),
        Case("case-insensitive LIKE (ILIKE)",
             pa.table({"s": pa.array(["ABC", "abc", "xbc"])}),
             [{"kind": "like", "child": _col(0), "pattern": "a%",
               "case_insensitive": True}],
             [(True,), (True,), (False,)]),
        Case("LIKE regex metacharacters are literal",
             pa.table({"s": pa.array(["a.c", "abc", "a+c"])}),
             [{"kind": "like", "child": _col(0), "pattern": "a.c"}],
             [(True,), (False,), (False,)]),
        Case("starts/ends/contains predicates",
             pa.table({"s": pa.array(["spark sql", "sql spark", None])}),
             [{"kind": "string_starts_with", "child": _col(0),
               "pattern": "spark"},
              {"kind": "string_ends_with", "child": _col(0),
               "pattern": "spark"},
              {"kind": "string_contains", "child": _col(0),
               "pattern": "k s"}],
             [(True, False, True), (False, True, False),
              (None, None, None)]),
        Case("RLIKE anchors make a full match",
             pa.table({"s": pa.array(["abc", "zabc"])}),
             [{"kind": "rlike", "child": _col(0), "pattern": "^abc$"}],
             [(True,), (False,)]),
    ]


# ---------------------------------------------------------------------------
# multi-column hash vectors (ref HashExpressionsSuite)
# ---------------------------------------------------------------------------

@_suite("HashMultiColumnSuite")
def _hash_multi():
    return [
        Case("hash chains columns left to right",
             pa.table({"a": pa.array([1], pa.int32()),
                       "b": pa.array([2], pa.int32())}),
             [_fn("hash", _col(0), _col(1), rt="int32")],
             [(-222940379,)]),
        Case("null column keeps the running seed",
             pa.table({"a": pa.array([1], pa.int32()),
                       "b": pa.array([None], pa.int32())}),
             [_fn("hash", _col(0), _col(1), rt="int32")],
             [(-559580957,)]),
        Case("hash of utf8 is bit-exact",
             pa.table({"s": pa.array(["Spark"])}),
             [_fn("hash", _col(0), rt="int32")],
             [(228093765,)]),
        Case("xxhash64 of utf8 is bit-exact",
             pa.table({"s": pa.array(["Spark"])}),
             [_fn("xxhash64", _col(0), rt="int64")],
             [(-4294468057691064905,)]),
    ]


# ---------------------------------------------------------------------------
# wave 2: collections with NaN, regexp backrefs, months_between time
# fraction, generate / expand / limit operators, string + math edges
# ---------------------------------------------------------------------------

@_suite("CollectionNaNSuite")
def _collection_nan():
    return [
        Case("array_contains matches NaN (ordering.equiv)",
             pa.table({"a": pa.array([[1.0, NAN]])}),
             [_fn("array_contains", _col(0), _lit(NAN, "float64"),
                  rt="bool")],
             [(True,)]),
        Case("array_contains: no match + null element is null",
             pa.table({"a": pa.array([[1, None], [1, 2]],
                                     pa.list_(pa.int64()))}),
             [_fn("array_contains", _col(0), _lit(9), rt="bool")],
             [(None,), (False,)]),
        Case("array_max treats NaN as largest",
             pa.table({"a": pa.array([[1.0, NAN, 2.0]])}),
             [_fn("array_max", _col(0), rt="float64")],
             [(NAN,)]),
        Case("array_min skips NaN",
             pa.table({"a": pa.array([[1.0, NAN, 2.0]])}),
             [_fn("array_min", _col(0), rt="float64")],
             [(1.0,)]),
        Case("array_min of all-NaN array is NaN",
             pa.table({"a": pa.array([[NAN, NAN]])}),
             [_fn("array_min", _col(0), rt="float64")],
             [(NAN,)]),
        Case("concat_ws flattens array arguments",
             pa.table({"a": pa.array([["a", "b"]]),
                       "s": pa.array(["z"])}),
             [_fn("concat_ws", _lit(",", "utf8"), _col(0), _col(1),
                  rt="utf8")],
             [("a,b,z",)]),
        Case("concat_ws skips null elements inside arrays",
             pa.table({"a": pa.array([["a", None, "c"]])}),
             [_fn("concat_ws", _lit("-", "utf8"), _col(0), rt="utf8")],
             [("a-c",)]),
    ]


@_suite("RegexpBackrefSuite")
def _regexp_backref():
    return [
        Case("regexp_replace substitutes $1 group references",
             pa.table({"s": pa.array(["a1b2"])}),
             [_fn("regexp_replace", _col(0), _lit("(\\d)", "utf8"),
                  _lit("[$1]", "utf8"), rt="utf8")],
             [("a[1]b[2]",)]),
        Case("regexp_replace swaps two groups",
             pa.table({"s": pa.array(["john smith"])}),
             [_fn("regexp_replace", _col(0),
                  _lit("(\\w+) (\\w+)", "utf8"),
                  _lit("$2 $1", "utf8"), rt="utf8")],
             [("smith john",)]),
        Case("escaped dollar stays literal",
             pa.table({"s": pa.array(["x"])}),
             [_fn("regexp_replace", _col(0), _lit("x", "utf8"),
                  _lit("\\$9", "utf8"), rt="utf8")],
             [("$9",)]),
        Case("backslash-digit is a literal, not a group ref (Java)",
             pa.table({"s": pa.array(["ab"])}),
             [_fn("regexp_replace", _col(0), _lit("(a)b", "utf8"),
                  _lit("\\1", "utf8"), rt="utf8")],
             [("1",)]),
        Case("regexp_extract group 0 is the whole match",
             pa.table({"s": pa.array(["a1", "zzz"])}),
             [_fn("regexp_extract", _col(0), _lit("([a-z])(\\d)", "utf8"),
                  _lit(0), rt="utf8")],
             [("a1",), ("",)]),
        Case("unmatched optional group extracts empty string",
             pa.table({"s": pa.array(["a1", "b"])}),
             [_fn("regexp_extract", _col(0),
                  _lit("([a-z])(\\d)?", "utf8"), _lit(2), rt="utf8")],
             [("1",), ("",)]),
    ]


@_suite("MonthsBetweenSuite")
def _months_between_suite():
    import numpy as _np

    def ts(s):
        return pa.array([_np.datetime64(s, "us")], pa.timestamp("us"))
    return [
        Case("doc example includes the time-of-day fraction",
             pa.table({"a": ts("1997-02-28T10:30:00"),
                       "b": ts("1996-10-30T00:00:00")}),
             [_fn("months_between", _col(0), _col(1), rt="float64")],
             [(3.94959677,)]),
        Case("same day-of-month ignores time of day",
             pa.table({"a": ts("2020-03-15T23:00:00"),
                       "b": ts("2020-01-15T01:00:00")}),
             [_fn("months_between", _col(0), _col(1), rt="float64")],
             [(2.0,)]),
        Case("both month-ends are integral",
             pa.table({"a": ts("2020-02-29T12:00:00"),
                       "b": ts("2019-11-30T00:00:00")}),
             [_fn("months_between", _col(0), _col(1), rt="float64")],
             [(3.0,)]),
        Case("negative when first is earlier",
             pa.table({"a": ts("2020-01-10T00:00:00"),
                       "b": ts("2020-02-10T00:00:00")}),
             [_fn("months_between", _col(0), _col(1), rt="float64")],
             [(-1.0,)]),
    ]


@_suite("GenerateOperatorSuite")
def _generate_operator():
    t = pa.table({"id": pa.array([1, 2, 3]),
                  "a": pa.array([[10, 20], [], None])})

    def gen_plan(kind, outer):
        def mk(scan):
            return {"kind": "generate",
                    "generator": {"kind": kind, "child": _col(1),
                                  "outer": outer},
                    "required_cols": [0], "input": scan}
        return mk
    jt = pa.table({"j": pa.array(['{"a": 1, "b": "x"}', "bad", None])})

    def json_tuple_plan(scan):
        return {"kind": "generate",
                "generator": {"kind": "json_tuple", "child": _col(0),
                              "fields": ["a", "b"]},
                "required_cols": [], "input": scan}
    return [
        Case("explode drops empty and null arrays",
             t, [], [(1, 10), (1, 20)],
             plan=gen_plan("explode", False)),
        Case("explode_outer keeps them as null rows",
             t, [], [(1, 10), (1, 20), (2, None), (3, None)],
             plan=gen_plan("explode", True)),
        Case("posexplode emits 0-based positions",
             t, [], [(1, 0, 10), (1, 1, 20)],
             plan=gen_plan("posexplode", False)),
        Case("posexplode_outer null position on empty",
             t, [], [(1, 0, 10), (1, 1, 20), (2, None, None),
                     (3, None, None)],
             plan=gen_plan("posexplode", True)),
        Case("json_tuple extracts fields, null row on bad json",
             jt, [], [("1", "x"), (None, None), (None, None)],
             plan=json_tuple_plan),
    ]


@_suite("ExpandUnionLimitSuite")
def _expand_union_limit():
    t = pa.table({"a": pa.array([1, 2]), "b": pa.array([10, 20])})

    def expand_plan(scan):
        return {"kind": "expand",
                "projections": [[_col(0), _lit(None, "int64")],
                                [_lit(None, "int64"), _col(1)]],
                "names": ["a", "b"], "input": scan}

    def union_plan(scan, scan2):
        return {"kind": "union", "inputs": [scan, scan2]}

    def limit_plan(limit, offset):
        def mk(scan):
            return {"kind": "limit", "limit": limit, "offset": offset,
                    "input": scan}
        return mk
    five = pa.table({"x": pa.array([1, 2, 3, 4, 5])})
    return [
        Case("expand replicates each row per projection (rollup shape)",
             t, [], [(1, None), (2, None), (None, 10), (None, 20)],
             unordered=True, plan=expand_plan),
        Case("union concatenates without dedup",
             pa.table({"x": pa.array([1, 2])}), [],
             [(1,), (2,), (2,), (3,)], unordered=True,
             input2=pa.table({"x": pa.array([2, 3])}),
             plan=union_plan),
        Case("limit with offset skips then takes",
             five, [], [(2,), (3,)], plan=limit_plan(2, 1)),
        Case("limit beyond input is the whole input",
             five, [], [(1,), (2,), (3,), (4,), (5,)],
             plan=limit_plan(99, 0)),
        Case("offset beyond input is empty",
             five, [], [], plan=limit_plan(5, 99)),
    ]


@_suite("MathIntegerEdgeSuite")
def _math_integer_edge():
    return [
        Case("abs of int64 min wraps to itself (non-ANSI)",
             pa.table({"x": pa.array([I64MIN, -7])}),
             [_fn("abs", _col(0), rt="int64")],
             [(I64MIN,), (7,)]),
        Case("int32 addition wraps at int32 width",
             pa.table({"a": pa.array([I32MAX], pa.int32()),
                       "b": pa.array([1], pa.int32())}),
             [_bin("+", _col(0), _col(1))],
             [(I32MIN,)]),
        Case("int32 multiplication wraps at int32 width",
             pa.table({"a": pa.array([1 << 30], pa.int32())}),
             [_bin("*", _col(0), _lit(4, "int32"))],
             [(0,)]),
        Case("float modulo sign follows dividend",
             pa.table({"a": pa.array([7.5, -7.5])}),
             [_bin("%", _col(0), _lit(3.0, "float64"))],
             [(1.5,), (-1.5,)]),
        Case("pmod of float is non-negative",
             pa.table({"a": pa.array([-7.0])}),
             [_bin("pmod", _col(0), _lit(3.0, "float64"))],
             [(2.0,)]),
        Case("round with negative digits",
             pa.table({"x": pa.array([1254.0, 1249.0])}),
             [_fn("round", _col(0), _lit(-2), rt="float64")],
             [(1300.0,), (1200.0,)]),
        Case("sqrt of negative zero is negative zero (IEEE)",
             pa.table({"x": pa.array([-0.0])}),
             [_fn("sqrt", _col(0), rt="float64")],
             [(-0.0,)]),
        Case("signum of NaN is NaN",
             pa.table({"x": pa.array([NAN, -0.0])}),
             [_fn("signum", _col(0), rt="float64")],
             [(NAN,), (-0.0,)]),
    ]


@_suite("StringFnEdgeSuite")
def _string_fn_edge():
    return [
        Case("lpad cycles a multi-char pad",
             pa.table({"s": pa.array(["7"])}),
             [_fn("lpad", _col(0), _lit(5), _lit("xy", "utf8"),
                  rt="utf8")],
             [("xyxy7",)]),
        Case("repeat of zero or negative count is empty",
             pa.table({"s": pa.array(["ab"])}),
             [_fn("repeat", _col(0), _lit(0), rt="utf8"),
              _fn("repeat", _col(0), _lit(-1), rt="utf8")],
             [("", "")]),
        Case("ascii returns the first code point, 0 for empty",
             pa.table({"s": pa.array(["€x", "", "A"])}),
             [_fn("ascii", _col(0), rt="int32")],
             [(8364,), (0,), (65,)]),
        Case("reverse is character-wise, not byte-wise",
             pa.table({"s": pa.array(["ab€"])}),
             [_fn("reverse", _col(0), rt="utf8")],
             [("€ba",)]),
        Case("substring_index with negative count takes from the right",
             pa.table({"s": pa.array(["a.b.c.d"])}),
             [_fn("substring_index", _col(0), _lit(".", "utf8"),
                  _lit(-2), rt="utf8")],
             [("c.d",)]),
        Case("locate start beyond length is 0",
             pa.table({"s": pa.array(["hello"])}),
             [_fn("locate", _lit("l", "utf8"), _col(0), _lit(99),
                  rt="int32")],
             [(0,)]),
        Case("trim of only-space strings is empty not null",
             pa.table({"s": pa.array(["   ", ""])}),
             [_fn("trim", _col(0), rt="utf8")],
             [("",), ("",)]),
    ]


@_suite("DateTruncExtSuite")
def _date_trunc_ext():
    import numpy as _np
    t = pa.table({"t": pa.array([_np.datetime64("2015-03-05T09:32:05.359",
                                                "us")],
                                pa.timestamp("us"))})

    def dt(*a):
        return _dt.datetime(*a)
    return [
        Case("date_trunc across the unit ladder",
             t, [_fn("date_trunc", _lit("MINUTE", "utf8"), _col(0),
                     rt="timestamp_us"),
                 _fn("date_trunc", _lit("DAY", "utf8"), _col(0),
                     rt="timestamp_us"),
                 _fn("date_trunc", _lit("WEEK", "utf8"), _col(0),
                     rt="timestamp_us"),
                 _fn("date_trunc", _lit("QUARTER", "utf8"), _col(0),
                     rt="timestamp_us"),
                 _fn("date_trunc", _lit("YEAR", "utf8"), _col(0),
                     rt="timestamp_us")],
             [(dt(2015, 3, 5, 9, 32), dt(2015, 3, 5),
               dt(2015, 3, 2), dt(2015, 1, 1), dt(2015, 1, 1))]),
        Case("date_trunc SECOND drops fractional seconds",
             t, [_fn("date_trunc", _lit("SECOND", "utf8"), _col(0),
                     rt="timestamp_us")],
             [(dt(2015, 3, 5, 9, 32, 5),)]),
        Case("date_trunc HOUR",
             t, [_fn("date_trunc", _lit("HOUR", "utf8"), _col(0),
                     rt="timestamp_us")],
             [(dt(2015, 3, 5, 9),)]),
    ]


@_suite("ConditionalExtSuite")
def _conditional_ext():
    return [
        Case("case takes the FIRST matching branch",
             pa.table({"x": pa.array([5])}),
             [{"kind": "case",
               "branches": [[_bin(">", _col(0), _lit(1)), _lit(10)],
                            [_bin(">", _col(0), _lit(2)), _lit(20)]],
               "else": _lit(0)}],
             [(10,)]),
        Case("case with null condition falls through",
             pa.table({"x": pa.array([None], pa.int64())}),
             [{"kind": "case",
               "branches": [[_bin(">", _col(0), _lit(1)), _lit(10)]],
               "else": _lit(99)}],
             [(99,)]),
        Case("nested coalesce picks leftmost non-null",
             pa.table({"a": pa.array([None, 1], pa.int64()),
                       "b": pa.array([None, 9], pa.int64())}),
             [{"kind": "coalesce",
               "args": [_col(0), _col(1), _lit(7)]}],
             [(7,), (1,)]),
        Case("if propagates the chosen branch's null",
             pa.table({"c": pa.array([True, False]),
                       "x": pa.array([None, None], pa.int64())}),
             [{"kind": "if", "cond": _col(0), "then": _col(1),
               "else": _lit(3)}],
             [(None,), (3,)]),
    ]


@_suite("SortTypesSuite")
def _sort_types():
    import numpy as _np
    return [
        Case("date32 sorts chronologically",
             pa.table({"d": pa.array([_dt.date(2020, 5, 1),
                                      _dt.date(2019, 1, 1), None],
                                     pa.date32())}),
             [], [(None,), (_dt.date(2019, 1, 1),),
                  (_dt.date(2020, 5, 1),)],
             plan=_sort_plan((0, False, True))),
        Case("bool sorts false before true",
             pa.table({"b": pa.array([True, False, None])}),
             [], [(None,), (False,), (True,)],
             plan=_sort_plan((0, False, True))),
        Case("negative zero and zero are equal sort keys",
             pa.table({"x": pa.array([0.0, -0.0, -1.0])}),
             [], [(-1.0,), (-0.0,), (0.0,)], unordered=True,
             plan=_sort_plan((0, False, True))),
        Case("timestamp sorts by instant",
             pa.table({"t": pa.array([_np.datetime64("2020-01-02", "us"),
                                      _np.datetime64("2020-01-01", "us")],
                                     pa.timestamp("us"))}),
             [], [(_dt.datetime(2020, 1, 1),),
                  (_dt.datetime(2020, 1, 2),)],
             plan=_sort_plan((0, False, True))),
    ]


@_suite("AggTypedMinMaxSuite")
def _agg_typed_minmax():
    t = pa.table({"k": pa.array(["g1", "g1", "g2"]),
                  "s": pa.array(["b", "a", None]),
                  "b": pa.array([True, False, None]),
                  "d": pa.array([_dt.date(2020, 1, 1), None,
                                 _dt.date(2019, 1, 1)], pa.date32())})
    return [
        Case("min/max over utf8 is lexicographic, host-accumulated",
             t, [], [("g1", "a", "b"), ("g2", None, None)],
             unordered=True,
             plan=_agg_plan((0,), [("min", _col(1), "mn"),
                                   ("max", _col(1), "mx")])),
        Case("min/max over bool orders false < true",
             t, [], [("g1", False, True), ("g2", None, None)],
             unordered=True,
             plan=_agg_plan((0,), [("min", _col(2), "mn"),
                                   ("max", _col(2), "mx")])),
        Case("min over date32 is chronological",
             t, [], [("g1", _dt.date(2020, 1, 1)),
                     ("g2", _dt.date(2019, 1, 1))],
             unordered=True,
             plan=_agg_plan((0,), [("min", _col(3), "mn")])),
        Case("global min/max over utf8 without grouping",
             pa.table({"s": pa.array(["m", "z", "a"])}),
             [], [("a", "z")],
             plan=_agg_plan((), [("min", _col(0), "mn"),
                                 ("max", _col(0), "mx")])),
        Case("sum of float64 propagates NaN",
             pa.table({"x": pa.array([1.0, NAN])}),
             [], [(NAN,)],
             plan=_agg_plan((), [("sum", _col(0), "s")])),
    ]


@_suite("BroadcastJoinSuite")
def _broadcast_join():
    l = pa.table({"a": pa.array([1, 2, 3]), "lv": pa.array([10, 20, 30])})
    r = pa.table({"b": pa.array([2, 3, 4]), "rv": pa.array([200, 300,
                                                            400])})
    return [
        Case("broadcast inner matches shuffled-hash results",
             l, [], [(2, 20, 2, 200), (3, 30, 3, 300)], unordered=True,
             input2=r,
             plan=_join_plan("broadcast_join", "inner",
                             build_side="right")),
        Case("broadcast left outer null-extends",
             l, [], [(1, 10, None, None), (2, 20, 2, 200),
                     (3, 30, 3, 300)], unordered=True, input2=r,
             plan=_join_plan("broadcast_join", "left",
                             build_side="right")),
        Case("nested-loop join applies a non-equi filter",
             l, [],
             [(1, 10, 2, 200), (1, 10, 3, 300), (1, 10, 4, 400),
              (2, 20, 3, 300), (2, 20, 4, 400), (3, 30, 4, 400)],
             unordered=True, input2=r,
             plan=lambda scan, scan2: {
                 "kind": "broadcast_nested_loop_join",
                 "left": scan, "right": scan2, "join_type": "inner",
                 "build_side": "right",
                 "join_filter": _bin("<", _col(0), _col(2))}),
        Case("join filter references the joined row",
             l, [], [(3, 30, 3, 300)], unordered=True, input2=r,
             plan=lambda scan, scan2: dict(
                 _join_plan("hash_join", "inner")(scan, scan2),
                 join_filter=_bin(">", _col(3), _lit(200)))),
        Case("right outer keeps dangling build rows",
             l, [], [(2, 20, 2, 200), (3, 30, 3, 300),
                     (None, None, 4, 400)], unordered=True, input2=r,
             plan=_join_plan("sort_merge_join", "right")),
    ]


@_suite("TimestampFieldsExtSuite")
def _timestamp_fields_ext():
    import numpy as _np
    t = pa.table({"t": pa.array([_np.datetime64("1970-01-01T00:00:00",
                                                "us"),
                                 _np.datetime64("2015-03-05T23:59:59",
                                                "us")],
                                pa.timestamp("us"))})
    return [
        Case("hour/minute/second at the epoch and day end",
             t, [_fn("hour", _col(0), rt="int32"),
                 _fn("minute", _col(0), rt="int32"),
                 _fn("second", _col(0), rt="int32")],
             [(0, 0, 0), (23, 59, 59)]),
        Case("from_unixtime of zero is the epoch (UTC session)",
             pa.table({"x": pa.array([0])}),
             [_fn("from_unixtime", _col(0), rt="utf8")],
             [("1970-01-01 00:00:00",)]),
        Case("unix_timestamp round trips from_unixtime",
             pa.table({"x": pa.array([1425547925])}),
             [_fn("unix_timestamp",
                  _fn("from_unixtime", _col(0), rt="utf8"),
                  rt="int64")],
             [(1425547925,)]),
        Case("to_date truncates a timestamp string",
             pa.table({"s": pa.array(["2015-03-05 09:32:05"])}),
             [_fn("to_date", _col(0), rt="date32")],
             [(_dt.date(2015, 3, 5),)]),
    ]


@_suite("DecimalArithmeticSuite")
def _decimal_arithmetic():
    from decimal import Decimal as D
    d102 = pa.array([D("12.34"), D("-1.50")], pa.decimal128(10, 2))
    d103 = pa.array([D("1.234"), D("2.000")], pa.decimal128(10, 3))
    t = pa.table({"a": d102, "b": d103})
    return [
        Case("add aligns scales, widens precision (12,3)",
             t, [_bin("+", _col(0), _col(1))],
             [(D("13.574"),), (D("0.500"),)]),
        Case("multiply scale is s1+s2",
             t, [_bin("*", _col(0), _col(1))],
             [(D("15.22756"),), (D("-3.00000"),)]),
        Case("divide scale is max(6, s1+p2+1)",
             t, [_bin("/", _col(0), _col(1))],
             [(D("10.0000000000000"),), (D("-0.7500000000000"),)]),
        Case("comparison aligns scales first",
             pa.table({"a": pa.array([D("1.00")], pa.decimal128(10, 2)),
                       "b": pa.array([D("0.500")],
                                     pa.decimal128(10, 3))}),
             [_bin(">", _col(0), _col(1)),
              _bin("==", _col(0), _col(1))],
             [(True, False)]),
        Case("integer operand widens to decimal",
             pa.table({"a": pa.array([5]),
                       "b": pa.array([D("0.25")],
                                     pa.decimal128(10, 2))}),
             [_bin("+", _col(0), _col(1))],
             [(D("5.25"),)]),
        Case("addition overflow at precision 38 is null",
             pa.table({"a": pa.array([D("9" * 38)],
                                     pa.decimal128(38, 0)),
                       "b": pa.array([D("9" * 38)],
                                     pa.decimal128(38, 0))}),
             [_bin("+", _col(0), _col(1))],
             [(None,)]),
        Case("decimal division by zero is null (non-ANSI)",
             pa.table({"a": pa.array([D("1.00")], pa.decimal128(10, 2)),
                       "b": pa.array([D("0.00")],
                                     pa.decimal128(10, 2))}),
             [_bin("/", _col(0), _col(1))],
             [(None,)]),
        Case("modulo sign follows dividend, pmod the divisor",
             pa.table({"a": pa.array([D("-7.0")], pa.decimal128(10, 1)),
                       "b": pa.array([D("3.0")],
                                     pa.decimal128(10, 1))}),
             [_bin("%", _col(0), _col(1)),
              _bin("pmod", _col(0), _col(1))],
             [(D("-1.0"), D("2.0"))]),
        Case("sum widens precision by 10, avg adds scale 4",
             pa.table({"k": pa.array(["a", "a"]),
                       "v": pa.array([D("12.34"), D("-1.50")],
                                     pa.decimal128(10, 2))}),
             [], [("a", D("10.84"), D("5.420000"))], unordered=True,
             plan=_agg_plan((0,), [("sum", _col(1), "s"),
                                   ("avg", _col(1), "m")])),
        Case("check_overflow keeps wide (p>18) products exact",
             # Spark wraps decimal arithmetic in CheckOverflow; a wide
             # host result must NOT round-trip through int64 device
             # storage (low-8-bytes truncation, r5 review finding)
             pa.table({"a": pa.array([D("1" + "0" * 17)],
                                     pa.decimal128(18, 0)),
                       "b": pa.array([D("1" + "0" * 17)],
                                     pa.decimal128(18, 0))}),
             [{"kind": "scalar_function", "name": "check_overflow",
               "args": [_bin("*", _col(0), _col(1))],
               "return_type": {"id": "decimal", "precision": 38,
                               "scale": 0}}],
             [(D("1" + "0" * 34),)]),
        Case("nested arithmetic chains host intermediates correctly",
             # (a + b) + c: the inner add returns a HOST decimal of a
             # widened type; the outer equal-scale add must not fall
             # into the host comparator path (r5 review finding)
             pa.table({"a": pa.array([D("12.34")], pa.decimal128(10, 2)),
                       "b": pa.array([D("1.234")], pa.decimal128(10, 3)),
                       "c": pa.array([D("0.006")],
                                     pa.decimal128(12, 3))}),
             [_bin("+", _bin("+", _col(0), _col(1)), _col(2))],
             [(D("13.580"),)]),
        Case("date compared to decimal stays a device comparison",
             pa.table({"d": pa.array([_dt.date(2020, 1, 1)],
                                     pa.date32()),
                       "x": pa.array([_dt.date(2019, 1, 1)],
                                     pa.date32())}),
             [_bin(">", _col(0), _col(1))],
             [(True,)]),
        Case("null decimal operand poisons the row",
             pa.table({"a": pa.array([D("1.00"), None],
                                     pa.decimal128(10, 2)),
                       "b": pa.array([D("2.00"), D("2.00")],
                                     pa.decimal128(10, 2))}),
             [_bin("+", _col(0), _col(1))],
             [(D("3.00"),), (None,)]),
    ]


# ---------------------------------------------------------------------------
# wave 3: json rendering, nested-type display casts, crypto widths,
# window group-limit, SMJ semi/anti (the final breadth push)
# ---------------------------------------------------------------------------

@_suite("ToJsonSuite")
def _to_json_suite():
    return [
        Case("to_json omits null fields (ignoreNullFields default)",
             pa.table({"a": pa.array([1, None])}),
             [_fn("to_json", {"kind": "named_struct",
                              "names": ["x", "y"],
                              "args": [_col(0), _lit("s", "utf8")]},
                  rt="utf8")],
             [('{"x":1,"y":"s"}',), ('{"y":"s"}',)]),
        Case("to_json over an array value",
             pa.table({"a": pa.array([[1, 2]])}),
             [_fn("to_json", _col(0), rt="utf8")],
             [("[1,2]",)]),
    ]


@_suite("NestedDisplayCastSuite")
def _nested_display_cast():
    return [
        Case("array renders Spark-style with null literal",
             pa.table({"a": pa.array([[1, 2, None]])}),
             [_cast(_col(0), "utf8")],
             [("[1, 2, null]",)]),
        Case("struct renders value tuple without field names",
             pa.table({"s": pa.array([{"x": 1, "y": "a"}],
                                     pa.struct([("x", pa.int64()),
                                                ("y", pa.utf8())]))}),
             [_cast(_col(0), "utf8")],
             [("{1, a}",)]),
    ]


@_suite("CryptoWidthSuite")
def _crypto_width():
    return [
        Case("sha2 bit widths select the digest family",
             pa.table({"s": pa.array(["abc"])}),
             [_fn("sha2", _col(0), _lit(224), rt="utf8"),
              _fn("sha2", _col(0), _lit(384), rt="utf8")],
             [("23097d223405d8228642a477bda255b32aadbce4bda0b3f7e36c"
               "9da7",
               "cb00753f45a35e8bb5a03d699ac65007272c32ab0eded1631a8b"
               "605a43ff5bed8086072ba1e7cc2358baeca134c825a7")]),
        Case("md5 of empty string",
             pa.table({"s": pa.array([""])}),
             [_fn("md5", _col(0), rt="utf8")],
             [("d41d8cd98f00b204e9800998ecf8427e",)]),
    ]


@_suite("WindowGroupLimitSuite")
def _window_group_limit():
    t = pa.table({"g": pa.array([1, 1, 1, 2, 2]),
                  "x": pa.array([9, 7, 5, 4, 8])})

    def plan(scan):
        return {"kind": "window",
                "input": {"kind": "sort", "input": scan,
                          "specs": [{"expr": _col(0),
                                     "descending": False,
                                     "nulls_first": True},
                                    {"expr": _col(1),
                                     "descending": True,
                                     "nulls_first": False}]},
                "functions": [{"kind": "rank", "name": "rk"}],
                "partition_by": [_col(0)],
                "order_by": [{"expr": _col(1), "descending": True}],
                "group_limit": 2}
    return [
        Case("window-group-limit keeps top-k rows per partition",
             t, [], [(1, 9, 1), (1, 7, 2), (2, 8, 1), (2, 4, 2)],
             plan=plan),
    ]


@_suite("SortMergeJoinTypesSuite")
def _smj_types():
    l = pa.table({"a": pa.array([1, 2, None]),
                  "lv": pa.array([10, 20, 30])})
    r = pa.table({"b": pa.array([2, None, 2]),
                  "rv": pa.array([100, 200, 300])})
    return [
        Case("SMJ left semi keeps each probe match once",
             l, [], [(2, 20)], unordered=True, input2=r,
             plan=_join_plan("sort_merge_join", "left_semi")),
        Case("SMJ left anti keeps null-keyed probe rows",
             l, [], [(1, 10), (None, 30)], unordered=True, input2=r,
             plan=_join_plan("sort_merge_join", "left_anti")),
        Case("SMJ right semi mirrors build-side membership",
             l, [], [(2, 100), (2, 300)], unordered=True, input2=r,
             plan=_join_plan("sort_merge_join", "right_semi")),
    ]


@_suite("ToJsonShapeSuite")
def _to_json_shape():
    nested = pa.struct([("a", pa.struct([("b", pa.int64()),
                                         ("c", pa.int64())]))])
    return [
        Case("null struct fields are omitted RECURSIVELY",
             pa.table({"s": pa.array([{"a": {"b": None, "c": 1}}],
                                     nested)}),
             [_fn("to_json", _col(0), rt="utf8")],
             [('{"a":{"c":1}}',)]),
        Case("null MAP values are kept (ignoreNullFields is "
             "struct-only, JacksonGenerator.writeMapData)",
             pa.table({"m": pa.array([[("k", None), ("j", 1)]],
                                     pa.map_(pa.utf8(), pa.int64()))}),
             [_fn("to_json", _col(0), rt="utf8")],
             [('{"k":null,"j":1}',)]),
        Case("empty map renders as {} not []",
             pa.table({"m": pa.array([[]],
                                     pa.map_(pa.utf8(), pa.int64()))}),
             [_fn("to_json", _col(0), rt="utf8")],
             [("{}",)]),
        Case("null array elements are kept",
             pa.table({"a": pa.array([[1, None, 3]])}),
             [_fn("to_json", _col(0), rt="utf8")],
             [("[1,null,3]",)]),
    ]


@_suite("AnsiArithmeticSuite")
def _ansi_arithmetic():
    return [
        Case("ANSI: integral division by zero raises",
             pa.table({"a": pa.array([10])}),
             [_bin("%", _col(0), _lit(0))],
             [], confs=_ANSI_ON, raises="DIVIDE_BY_ZERO"),
        Case("ANSI: int64 addition overflow raises",
             pa.table({"a": pa.array([I64MAX])}),
             [_bin("+", _col(0), _lit(1))],
             [], confs=_ANSI_ON, raises="ARITHMETIC_OVERFLOW"),
        Case("ANSI: int64 multiply overflow raises",
             pa.table({"a": pa.array([1 << 62])}),
             [_bin("*", _col(0), _lit(4))],
             [], confs=_ANSI_ON, raises="ARITHMETIC_OVERFLOW"),
        Case("ANSI: subtraction underflow raises",
             pa.table({"a": pa.array([I64MIN])}),
             [_bin("-", _col(0), _lit(1))],
             [], confs=_ANSI_ON, raises="ARITHMETIC_OVERFLOW"),
        Case("ANSI: in-range arithmetic still computes",
             pa.table({"a": pa.array([3])}),
             [_bin("*", _col(0), _lit(4)),
              _bin("%", _col(0), _lit(2))],
             [(12, 1)], confs=_ANSI_ON),
        Case("ANSI: float division by zero raises DIVIDE_BY_ZERO",
             pa.table({"a": pa.array([1.0])}),
             [_bin("/", _col(0), _lit(0.0, "float64"))],
             [], confs=_ANSI_ON, raises="DIVIDE_BY_ZERO"),
        Case("ANSI: filtered-out rows cannot raise",
             pa.table({"a": pa.array([10, 10]),
                       "b": pa.array([2, 0])}),
             [], [(5,)],
             confs=_ANSI_ON,
             plan=lambda scan: {
                 "kind": "project",
                 "exprs": [_bin("/", _col(0), _col(1))],
                 "names": ["q"],
                 "input": {"kind": "filter",
                           "predicates": [_bin("!=", _col(1), _lit(0))],
                           "input": scan}}),
    ]


@_suite("AnsiArithmeticEdgeSuite")
def _ansi_arith_edge():
    from decimal import Decimal as D
    return [
        Case("ANSI: INT64_MIN * -1 raises (verify-division wraps)",
             pa.table({"a": pa.array([I64MIN])}),
             [_bin("*", _col(0), _lit(-1))],
             [], confs=_ANSI_ON, raises="ARITHMETIC_OVERFLOW"),
        Case("ANSI: INT64_MIN / -1 raises, not wraps",
             pa.table({"a": pa.array([I64MIN])}),
             [_bin("/", _col(0), _lit(-1))],
             [], confs=_ANSI_ON, raises="ARITHMETIC_OVERFLOW"),
        Case("ANSI: decimal division by zero raises",
             pa.table({"a": pa.array([D("1.00")], pa.decimal128(10, 2)),
                       "b": pa.array([D("0.00")],
                                     pa.decimal128(10, 2))}),
             [_bin("/", _col(0), _col(1))],
             [], confs=_ANSI_ON, raises="DIVIDE_BY_ZERO"),
        Case("ANSI: decimal overflow raises",
             pa.table({"a": pa.array([D("9" * 38)],
                                     pa.decimal128(38, 0)),
                       "b": pa.array([D("9" * 38)],
                                     pa.decimal128(38, 0))}),
             [_bin("+", _col(0), _col(1))],
             [], confs=_ANSI_ON, raises="NUMERIC_VALUE_OUT_OF_RANGE"),
        Case("non-ANSI: the same edges stay null/wrap",
             pa.table({"a": pa.array([I64MIN])}),
             [_bin("*", _col(0), _lit(-1))],
             [(I64MIN,)]),
    ]


@_suite("TryArithmeticSuite")
def _try_arithmetic():
    from decimal import Decimal as D
    return [
        Case("try_add nulls int64 overflow in every mode",
             pa.table({"a": pa.array([I64MAX, 5])}),
             [_fn("try_add", _col(0), _lit(1), rt="int64")],
             [(None,), (6,)], confs=_ANSI_ON),
        Case("try_subtract nulls underflow",
             pa.table({"a": pa.array([I64MIN])}),
             [_fn("try_subtract", _col(0), _lit(1), rt="int64")],
             [(None,)]),
        Case("try_multiply nulls overflow incl INT64_MIN * -1",
             pa.table({"a": pa.array([I64MIN, 3])}),
             [_fn("try_multiply", _col(0), _lit(-1), rt="int64")],
             [(None,), (-3,)]),
        Case("try_divide is double division, /0 null even for floats",
             pa.table({"a": pa.array([7, 1])}),
             [_fn("try_divide", _col(0), _lit(2), rt="float64"),
              _fn("try_divide", _col(0), _lit(0), rt="float64")],
             [(3.5, None), (0.5, None)]),
        Case("try_divide decimal keeps decimal, /0 null under ANSI",
             pa.table({"a": pa.array([D("1.00")], pa.decimal128(10, 2)),
                       "b": pa.array([D("0.00")],
                                     pa.decimal128(10, 2))}),
             [_fn("try_divide", _col(0), _col(1))],
             [(None,)], confs=_ANSI_ON),
        Case("try_element_at out-of-bounds is null under ANSI too",
             pa.table({"a": pa.array([[1, 2]])}),
             [_fn("try_element_at", _col(0), _lit(5), rt="int64")],
             [(None,)], confs=_ANSI_ON),
        Case("try_element_at index 0 still raises",
             pa.table({"a": pa.array([[1, 2]])}),
             [_fn("try_element_at", _col(0), _lit(0), rt="int64")],
             [], raises="INVALID_INDEX_OF_ZERO"),
    ]


@_suite("TryArithmeticWidthSuite")
def _try_arith_width():
    from decimal import Decimal as D
    return [
        Case("try_add nulls at INT32 bounds for int32 operands",
             pa.table({"a": pa.array([I32MAX, 5], pa.int32()),
                       "b": pa.array([1, 1], pa.int32())}),
             [_fn("try_add", _col(0), _col(1))],
             [(None,), (6,)]),
        Case("try_multiply on decimals reports the widened type",
             pa.table({"a": pa.array([D("2.50")], pa.decimal128(10, 2)),
                       "b": pa.array([D("4.00")],
                                     pa.decimal128(10, 2))}),
             [_fn("try_multiply", _col(0), _col(1))],
             [(D("10.0000"),)]),
        Case("array() is an alias of make_array",
             pa.table({"x": pa.array([1]), "y": pa.array([2])}),
             [_fn("array", _col(0), _col(1))],
             [([1, 2],)]),
        Case("try_add float operands widen to double",
             pa.table({"a": pa.array([1.5], pa.float64()),
                       "b": pa.array([2], pa.int64())}),
             [_fn("try_add", _col(0), _col(1))],
             [(3.5,)]),
    ]
