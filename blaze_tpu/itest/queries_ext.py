"""TPC-DS breadth extension: 24 more queries (VERDICT r3 #6).

Same contract as queries.py: each builder returns (plan_dict, oracle);
oracles are pandas (the QueryResultComparator analog,
ref dev/auron-it/.../QueryResultComparator.scala).  Shapes prioritized
per the verdict: multi-stage monsters (q23/q14/q64), intersect/except
(q38/q87), exists/in-subquery (q10/q35/q69), the reference's best-case
q24, plus the ss-sr-cs chains, rollups, disjunction filters, case-when
bucket pivots, time/household-demographic dimensions and the full-outer
customer-item matrix (q97).

Date windows use the same day arithmetic as tpcds_data.gen_date_dim.
"""

from __future__ import annotations

import pandas as pd

from blaze_tpu.itest.queries import (QUERIES, _day_range, _partial_final,
                                     agg, binop, c, ci, exchange, filter_,
                                     join, lit, project, scan, sort_limit)

W1 = _day_range(60, 150)   # ~3 month window
Y1999 = _day_range(365, 729)


def _case(branches, otherwise=None):
    d = {"kind": "case", "branches": [[w, t] for w, t in branches]}
    if otherwise is not None:
        d["else"] = otherwise
    return d


def _global_agg(inp, fns):
    """partial -> single exchange -> final, no group keys."""
    partial = agg(inp, [], [(f, "partial", n, a) for f, n, a in fns])
    ex = exchange(partial, [], 1)
    final = []
    pos = 0
    for f, n, _a in fns:
        nacc = 2 if f == "avg" else 1
        final.append((f, "final", n, [ci(pos + t) for t in range(nacc)]))
        pos += nacc
    return agg(ex, [], final)


def _exists(left, right_plan, lkeys, rkeys, partitions):
    """EXISTS via the existence join (left rows + bool column)."""
    l_ex = exchange(left, lkeys, partitions)
    r_ex = exchange(right_plan, rkeys, partitions)
    return join("hash_join", l_ex, r_ex, lkeys, rkeys, jt="existence")


# ---------------------------------------------------------------------------
# exists / in-subquery family: q10, q35, q69
# ---------------------------------------------------------------------------

def _exists_family(paths, tables, partitions, *, want_web, want_cat,
                   negate_other):
    """customer ⨝ ca ⨝ cd with EXISTS store_sales AND
    (EXISTS web | EXISTS catalog)  (q10/q35) or AND NOT EXISTS for q69."""
    cu, ca, cd = (tables["customer"], tables["customer_address"],
                  tables["customer_demographics"])
    ss, ws, cs = (tables["store_sales"], tables["web_sales"],
                  tables["catalog_sales"])

    ss_c = project(filter_(scan(paths, tables, "store_sales"),
                           binop(">=", c("ss_sold_date_sk"), lit(W1[0])),
                           binop("<=", c("ss_sold_date_sk"), lit(W1[1]))),
                   [c("ss_customer_sk")], ["ss_customer_sk"])
    ws_c = project(filter_(scan(paths, tables, "web_sales"),
                           binop(">=", c("ws_sold_date_sk"), lit(W1[0])),
                           binop("<=", c("ws_sold_date_sk"), lit(W1[1]))),
                   [c("ws_bill_customer_sk")], ["ws_customer_sk"])
    cs_c = project(filter_(scan(paths, tables, "catalog_sales"),
                           binop(">=", c("cs_sold_date_sk"), lit(W1[0])),
                           binop("<=", c("cs_sold_date_sk"), lit(W1[1]))),
                   [c("cs_bill_customer_sk")], ["cs_customer_sk"])

    base = project(scan(paths, tables, "customer"),
                   [c("c_customer_sk"), c("c_current_addr_sk"),
                    c("c_current_cdemo_sk"), c("c_birth_year")],
                   ["c_customer_sk", "c_current_addr_sk",
                    "c_current_cdemo_sk", "c_birth_year"])
    # semi join: EXISTS store sale in window
    semi = join("hash_join", exchange(base, [ci(0)], partitions),
                exchange(ss_c, [ci(0)], partitions),
                [ci(0)], [ci(0)], jt="left_semi")
    # existence joins for the disjunction legs
    e1 = _exists(semi, ws_c, [ci(0)], [ci(0)], partitions)  # +exists_w
    e2 = _exists(e1, cs_c, [ci(0)], [ci(0)], partitions)    # +exists_c
    if negate_other:  # q69: NOT EXISTS web AND NOT EXISTS catalog
        cond = binop("and", {"kind": "not", "child": ci(4)},
                     {"kind": "not", "child": ci(5)})
    elif want_web and want_cat:  # q10/q35: EXISTS web OR EXISTS catalog
        cond = binop("or", ci(4), ci(5))
    else:
        cond = ci(4) if want_web else ci(5)
    flt = filter_(e2, cond)

    j_ca = join("broadcast_join", flt,
                scan(paths, tables, "customer_address"),
                [ci(1)], [c("ca_address_sk")])
    j_cd = join("broadcast_join", j_ca,
                scan(paths, tables, "customer_demographics"),
                [ci(2)], [c("cd_demo_sk")])
    counted = _partial_final(
        j_cd,
        [(c("cd_gender"), "cd_gender"),
         (c("cd_education_status"), "cd_education_status")],
        [("count", "cnt", [ci(0)]),
         ("min", "min_by", [c("c_birth_year")]),
         ("max", "max_by", [c("c_birth_year")]),
         ("avg", "avg_by", [c("c_birth_year")])], partitions)
    single = exchange(counted, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        cud, cad, cdd = cu.to_pandas(), ca.to_pandas(), cd.to_pandas()
        ssd, wsd, csd = ss.to_pandas(), ws.to_pandas(), cs.to_pandas()
        in_w = lambda df, k: set(df[(df[k + "_sold_date_sk"] >= W1[0]) &
                                    (df[k + "_sold_date_sk"] <= W1[1])]
                                 [_cust_col(k)])
        s_set = in_w(ssd, "ss")
        w_set = in_w(wsd, "ws")
        c_set = in_w(csd, "cs")
        f = cud[cud.c_customer_sk.isin(s_set)]
        if negate_other:
            f = f[~f.c_customer_sk.isin(w_set) &
                  ~f.c_customer_sk.isin(c_set)]
        else:
            f = f[f.c_customer_sk.isin(w_set) |
                  f.c_customer_sk.isin(c_set)]
        m = f.merge(cad, left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        m = m.merge(cdd, left_on="c_current_cdemo_sk",
                    right_on="cd_demo_sk")
        out = m.groupby(["cd_gender", "cd_education_status"],
                        as_index=False).agg(
            cnt=("c_customer_sk", "count"),
            min_by=("c_birth_year", "min"),
            max_by=("c_birth_year", "max"),
            avg_by=("c_birth_year", "mean"))
        out = out.sort_values(["cd_gender",
                               "cd_education_status"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def _cust_col(prefix):
    return {"ss": "ss_customer_sk", "ws": "ws_bill_customer_sk",
            "cs": "cs_bill_customer_sk"}[prefix]


def q10(paths, tables, partitions: int = 2):
    return _exists_family(paths, tables, partitions, want_web=True,
                          want_cat=True, negate_other=False)


def q35(paths, tables, partitions: int = 2):
    return _exists_family(paths, tables, partitions, want_web=True,
                          want_cat=True, negate_other=False)


def q69(paths, tables, partitions: int = 2):
    return _exists_family(paths, tables, partitions, want_web=False,
                          want_cat=False, negate_other=True)


# ---------------------------------------------------------------------------
# intersect / except family: q38, q87  (+ q14 cross-channel items)
# ---------------------------------------------------------------------------

def _channel_customers(paths, tables, prefix, fact, partitions):
    f = filter_(scan(paths, tables, fact),
                binop(">=", c(prefix + "_sold_date_sk"), lit(W1[0])),
                binop("<=", c(prefix + "_sold_date_sk"), lit(W1[1])))
    p = project(f, [c(_cust_col(prefix))], ["customer_sk"])
    # distinct via group-by (how Spark plans INTERSECT legs)
    return _partial_final(p, [(ci(0), "customer_sk")],
                          [("count", "cnt", [ci(0)])], partitions)


def _set_op_customers(paths, tables, partitions, op):
    """count(*) of customers in store INTERSECT/EXCEPT web & catalog."""
    ss_d = _channel_customers(paths, tables, "ss", "store_sales",
                              partitions)
    ws_d = _channel_customers(paths, tables, "ws", "web_sales", partitions)
    cs_d = _channel_customers(paths, tables, "cs", "catalog_sales",
                              partitions)
    jt = "left_semi" if op == "intersect" else "left_anti"
    step1 = join("hash_join", exchange(ss_d, [ci(0)], partitions),
                 exchange(ws_d, [ci(0)], partitions), [ci(0)], [ci(0)],
                 jt=jt)
    step2 = join("hash_join", exchange(step1, [ci(0)], partitions),
                 exchange(cs_d, [ci(0)], partitions), [ci(0)], [ci(0)],
                 jt=jt)
    plan = _global_agg(step2, [("count", "num_customers", [ci(0)])])

    ss, ws, cs = (tables["store_sales"], tables["web_sales"],
                  tables["catalog_sales"])

    def oracle():
        in_w = lambda df, k: set(df[(df[k + "_sold_date_sk"] >= W1[0]) &
                                    (df[k + "_sold_date_sk"] <= W1[1])]
                                 [_cust_col(k)].dropna())
        s = in_w(ss.to_pandas(), "ss")
        w = in_w(ws.to_pandas(), "ws")
        cset = in_w(cs.to_pandas(), "cs")
        n = len(s & w & cset) if op == "intersect" else len(s - w - cset)
        return pd.DataFrame({"num_customers": [n]})

    return plan, oracle


def q38(paths, tables, partitions: int = 2):
    return _set_op_customers(paths, tables, partitions, "intersect")


def q87(paths, tables, partitions: int = 2):
    return _set_op_customers(paths, tables, partitions, "except")


def q14(paths, tables, partitions: int = 2):
    """Cross-channel items: brands whose items sold in ALL three channels
    (the q14 intersect CTE), revenue from store sales of those items."""
    ss, cs, ws, it = (tables["store_sales"], tables["catalog_sales"],
                      tables["web_sales"], tables["item"])

    def items(prefix, fact, col):
        f = filter_(scan(paths, tables, fact),
                    binop(">=", c(prefix + "_sold_date_sk"), lit(W1[0])),
                    binop("<=", c(prefix + "_sold_date_sk"), lit(W1[1])))
        return _partial_final(project(f, [c(col)], ["item_sk"]),
                              [(ci(0), "item_sk")],
                              [("count", "cnt", [ci(0)])], partitions)

    ss_i = items("ss", "store_sales", "ss_item_sk")
    cs_i = items("cs", "catalog_sales", "cs_item_sk")
    ws_i = items("ws", "web_sales", "ws_item_sk")
    both = join("hash_join", exchange(ss_i, [ci(0)], partitions),
                exchange(cs_i, [ci(0)], partitions), [ci(0)], [ci(0)],
                jt="left_semi")
    cross = join("hash_join", exchange(both, [ci(0)], partitions),
                 exchange(ws_i, [ci(0)], partitions), [ci(0)], [ci(0)],
                 jt="left_semi")

    ss_f = filter_(scan(paths, tables, "store_sales"),
                   binop(">=", c("ss_sold_date_sk"), lit(W1[0])),
                   binop("<=", c("ss_sold_date_sk"), lit(W1[1])))
    sold = join("hash_join", exchange(ss_f, [c("ss_item_sk")], partitions),
                exchange(cross, [ci(0)], partitions),
                [c("ss_item_sk")], [ci(0)], jt="left_semi")
    j_it = join("broadcast_join", sold, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    rev = _partial_final(
        j_it, [(c("i_brand_id"), "brand_id")],
        [("sum", "sales", [c("ss_ext_sales_price")]),
         ("count", "number_sales", [c("ss_ext_sales_price")])],
        partitions)
    single = exchange(rev, [ci(0)], 1)
    plan = sort_limit(single, [(ci(1), True), (ci(0), False)], 100)

    def oracle():
        ssd, csd, wsd = ss.to_pandas(), cs.to_pandas(), ws.to_pandas()
        itd = it.to_pandas()
        win = lambda df, k, col: set(
            df[(df[k + "_sold_date_sk"] >= W1[0]) &
               (df[k + "_sold_date_sk"] <= W1[1])][col])
        cross_items = (win(ssd, "ss", "ss_item_sk") &
                       win(csd, "cs", "cs_item_sk") &
                       win(wsd, "ws", "ws_item_sk"))
        f = ssd[(ssd.ss_sold_date_sk >= W1[0]) &
                (ssd.ss_sold_date_sk <= W1[1]) &
                ssd.ss_item_sk.isin(cross_items)]
        m = f.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        out = m.groupby("i_brand_id", as_index=False).agg(
            sales=("ss_ext_sales_price", "sum"),
            number_sales=("ss_ext_sales_price", "count"))
        out = out.sort_values(["sales", "i_brand_id"],
                              ascending=[False, True])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# multi-stage: q23 (frequent items + best customers), q24, q64
# ---------------------------------------------------------------------------

def q23(paths, tables, partitions: int = 2):
    """Catalog sales restricted to frequently-sold items AND
    best-by-spend customers (two independent agg sub-pipelines feeding
    semi joins — the q23 multi-stage skeleton)."""
    ss, cs = tables["store_sales"], tables["catalog_sales"]

    # frequent items: sold on >= 4 distinct tickets in the window
    ss_f = filter_(scan(paths, tables, "store_sales"),
                   binop(">=", c("ss_sold_date_sk"), lit(W1[0])),
                   binop("<=", c("ss_sold_date_sk"), lit(W1[1])))
    item_cnt = _partial_final(ss_f, [(c("ss_item_sk"), "item_sk")],
                              [("count", "cnt", [c("ss_ticket_number")])],
                              partitions)
    freq = filter_(item_cnt, binop(">=", ci(1), lit(4)))

    # best customers: total quantity*price above 500
    spend = project(scan(paths, tables, "store_sales"),
                    [c("ss_customer_sk"),
                     binop("*", {"kind": "cast", "child": c("ss_quantity"),
                                 "type": {"id": "float64"}},
                           c("ss_sales_price"))],
                    ["customer_sk", "spend"])
    cust_spend = _partial_final(spend, [(ci(0), "customer_sk")],
                                [("sum", "total", [ci(1)])], partitions)
    best = filter_(cust_spend, binop(">", ci(1), lit(500.0, "float64")))

    cs_f = filter_(scan(paths, tables, "catalog_sales"),
                   binop(">=", c("cs_sold_date_sk"), lit(W1[0])),
                   binop("<=", c("cs_sold_date_sk"), lit(W1[1])))
    semi_i = join("hash_join",
                  exchange(cs_f, [c("cs_item_sk")], partitions),
                  exchange(freq, [ci(0)], partitions),
                  [c("cs_item_sk")], [ci(0)], jt="left_semi")
    semi_c = join("hash_join",
                  exchange(semi_i, [c("cs_bill_customer_sk")], partitions),
                  exchange(best, [ci(0)], partitions),
                  [c("cs_bill_customer_sk")], [ci(0)], jt="left_semi")
    sales = project(semi_c,
                    [binop("*", {"kind": "cast", "child": c("cs_quantity"),
                                 "type": {"id": "float64"}},
                           c("cs_list_price"))], ["sales"])
    plan = _global_agg(sales, [("sum", "total_sales", [ci(0)])])

    def oracle():
        ssd, csd = ss.to_pandas(), cs.to_pandas()
        w = ssd[(ssd.ss_sold_date_sk >= W1[0]) &
                (ssd.ss_sold_date_sk <= W1[1])]
        freq_items = set(
            w.groupby("ss_item_sk").ss_ticket_number.count()
            .loc[lambda s: s >= 4].index)
        spend = ssd.assign(sp=ssd.ss_quantity * ssd.ss_sales_price) \
            .groupby("ss_customer_sk").sp.sum()
        best_c = set(spend.loc[spend > 500.0].index)
        f = csd[(csd.cs_sold_date_sk >= W1[0]) &
                (csd.cs_sold_date_sk <= W1[1]) &
                csd.cs_item_sk.isin(freq_items) &
                csd.cs_bill_customer_sk.isin(best_c)]
        total = (f.cs_quantity * f.cs_list_price).sum()
        return pd.DataFrame({"total_sales": [total if len(f) else None]})

    return plan, oracle


def q24(paths, tables, partitions: int = 2):
    """ss ⨝ sr ⨝ store ⨝ item ⨝ customer: per-customer/store netpaid,
    HAVING netpaid > 0.05 * avg(netpaid) — the scalar-subquery threshold
    via a broadcast nested-loop join (ref q24, the reference's best-case
    3.3x query)."""
    ss, sr, st = (tables["store_sales"], tables["store_returns"],
                  tables["store"])
    it, cu = tables["item"], tables["customer"]

    ss_ex = exchange(scan(paths, tables, "store_sales"),
                     [c("ss_ticket_number"), c("ss_item_sk")], partitions)
    sr_ex = exchange(scan(paths, tables, "store_returns"),
                     [c("sr_ticket_number"), c("sr_item_sk")], partitions)
    ss_sr = join("hash_join", ss_ex, sr_ex,
                 [c("ss_ticket_number"), c("ss_item_sk")],
                 [c("sr_ticket_number"), c("sr_item_sk")])
    j_st = join("broadcast_join", ss_sr,
                filter_(scan(paths, tables, "store"),
                        binop("==", c("s_state"), lit("TN", "utf8"))),
                [c("ss_store_sk")], [c("s_store_sk")])
    j_it = join("broadcast_join", j_st, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    j_cu = join("hash_join",
                exchange(j_it, [c("ss_customer_sk")], partitions),
                exchange(scan(paths, tables, "customer"),
                         [c("c_customer_sk")], partitions),
                [c("ss_customer_sk")], [c("c_customer_sk")])
    netpaid = _partial_final(
        j_cu,
        [(c("c_customer_id"), "c_customer_id"),
         (c("s_store_name"), "s_store_name")],
        [("sum", "netpaid", [c("ss_sales_price")])], partitions)
    avg_np = _global_agg(netpaid, [("avg", "avg_netpaid", [ci(2)])])
    # scalar threshold: cross (BNLJ) against the single avg row
    crossed = {"kind": "broadcast_nested_loop_join",
               "left": netpaid, "right": avg_np, "join_type": "inner",
               "build_side": "right"}
    flt = filter_(crossed, binop(">", ci(2),
                                 binop("*", ci(3), lit(0.05, "float64"))))
    picked = project(flt, [ci(0), ci(1), ci(2)],
                     ["c_customer_id", "s_store_name", "netpaid"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd, srd = ss.to_pandas(), sr.to_pandas()
        std, itd, cud = st.to_pandas(), it.to_pandas(), cu.to_pandas()
        m = ssd.merge(srd, left_on=["ss_ticket_number", "ss_item_sk"],
                      right_on=["sr_ticket_number", "sr_item_sk"])
        m = m.merge(std[std.s_state == "TN"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(cud, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        np_ = m.groupby(["c_customer_id", "s_store_name"],
                        as_index=False).agg(
            netpaid=("ss_sales_price", "sum"))
        np_ = np_[np_.netpaid > 0.05 * np_.netpaid.mean()]
        out = np_.sort_values(["c_customer_id", "s_store_name"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q64(paths, tables, partitions: int = 2):
    """The widest join tree: ss ⨝ sr ⨝ customer ⨝ cd ⨝ hd ⨝ ca ⨝ dd ⨝
    item ⨝ store ⨝ promotion (9 joins), grouped sale/refund stats."""
    ss, sr = tables["store_sales"], tables["store_returns"]
    cu, cd, hd = (tables["customer"], tables["customer_demographics"],
                  tables["household_demographics"])
    ca, dd, it = (tables["customer_address"], tables["date_dim"],
                  tables["item"])
    st, pr = tables["store"], tables["promotion"]

    ss_ex = exchange(scan(paths, tables, "store_sales"),
                     [c("ss_ticket_number"), c("ss_item_sk")], partitions)
    sr_ex = exchange(scan(paths, tables, "store_returns"),
                     [c("sr_ticket_number"), c("sr_item_sk")], partitions)
    j = join("hash_join", ss_ex, sr_ex,
             [c("ss_ticket_number"), c("ss_item_sk")],
             [c("sr_ticket_number"), c("sr_item_sk")])
    j = join("hash_join",
             exchange(j, [c("ss_customer_sk")], partitions),
             exchange(scan(paths, tables, "customer"),
                      [c("c_customer_sk")], partitions),
             [c("ss_customer_sk")], [c("c_customer_sk")])
    j = join("broadcast_join", j,
             scan(paths, tables, "customer_demographics"),
             [c("ss_cdemo_sk")], [c("cd_demo_sk")])
    j = join("broadcast_join", j,
             scan(paths, tables, "household_demographics"),
             [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    j = join("broadcast_join", j,
             scan(paths, tables, "customer_address"),
             [c("ss_addr_sk")], [c("ca_address_sk")])
    j = join("broadcast_join", j,
             filter_(scan(paths, tables, "date_dim"),
                     binop("==", c("d_year"), lit(1999, "int32"))),
             [c("ss_sold_date_sk")], [c("d_date_sk")])
    j = join("broadcast_join", j,
             filter_(scan(paths, tables, "item"),
                     binop("<=", c("i_current_price"),
                           lit(60.0, "float64"))),
             [c("ss_item_sk")], [c("i_item_sk")])
    j = join("broadcast_join", j, scan(paths, tables, "store"),
             [c("ss_store_sk")], [c("s_store_sk")])
    j = join("broadcast_join", j, scan(paths, tables, "promotion"),
             [c("ss_promo_sk")], [c("p_promo_sk")])
    stats = _partial_final(
        j,
        [(c("i_item_id"), "item_id"), (c("s_store_name"), "store_name"),
         (c("ca_state"), "ca_state")],
        [("count", "cnt", [c("ss_ticket_number")]),
         ("sum", "sales", [c("ss_ext_sales_price")]),
         ("sum", "refunds", [c("sr_return_amt")])], partitions)
    single = exchange(stats, [ci(0)], 1)
    plan = sort_limit(single,
                      [(ci(0), False), (ci(1), False), (ci(2), False)],
                      100)

    def oracle():
        m = ss.to_pandas().merge(
            sr.to_pandas(),
            left_on=["ss_ticket_number", "ss_item_sk"],
            right_on=["sr_ticket_number", "sr_item_sk"])
        m = m.merge(cu.to_pandas(), left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        m = m.merge(cd.to_pandas(), left_on="ss_cdemo_sk",
                    right_on="cd_demo_sk")
        m = m.merge(hd.to_pandas(), left_on="ss_hdemo_sk",
                    right_on="hd_demo_sk")
        m = m.merge(ca.to_pandas(), left_on="ss_addr_sk",
                    right_on="ca_address_sk")
        ddd = dd.to_pandas()
        m = m.merge(ddd[ddd.d_year == 1999], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        itd = it.to_pandas()
        m = m.merge(itd[itd.i_current_price <= 60.0],
                    left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(st.to_pandas(), left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m.merge(pr.to_pandas(), left_on="ss_promo_sk",
                    right_on="p_promo_sk")
        out = m.groupby(["i_item_id", "s_store_name", "ca_state"],
                        as_index=False).agg(
            cnt=("ss_ticket_number", "count"),
            sales=("ss_ext_sales_price", "sum"),
            refunds=("sr_return_amt", "sum"))
        out.columns = ["item_id", "store_name", "ca_state", "cnt",
                       "sales", "refunds"]
        out = out.sort_values(["item_id", "store_name",
                               "ca_state"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# ss-sr-cs chains (q25, q29) — q17 skeleton with different measures
# ---------------------------------------------------------------------------

def _ss_sr_cs(paths, tables, partitions, measures, oracle_aggs):
    from blaze_tpu.itest.queries import SR_CS_WINDOW, SS_WINDOW
    ss, sr, cs = (tables["store_sales"], tables["store_returns"],
                  tables["catalog_sales"])
    st, it = tables["store"], tables["item"]

    ss_f = filter_(scan(paths, tables, "store_sales"),
                   binop(">=", c("ss_sold_date_sk"), lit(SS_WINDOW[0])),
                   binop("<=", c("ss_sold_date_sk"), lit(SS_WINDOW[1])))
    sr_f = filter_(scan(paths, tables, "store_returns"),
                   binop(">=", c("sr_returned_date_sk"),
                         lit(SR_CS_WINDOW[0])),
                   binop("<=", c("sr_returned_date_sk"),
                         lit(SR_CS_WINDOW[1])))
    cs_f = filter_(scan(paths, tables, "catalog_sales"),
                   binop(">=", c("cs_sold_date_sk"), lit(SR_CS_WINDOW[0])),
                   binop("<=", c("cs_sold_date_sk"), lit(SR_CS_WINDOW[1])))
    ss_sr = join("hash_join",
                 exchange(ss_f, [c("ss_ticket_number"), c("ss_item_sk")],
                          partitions),
                 exchange(sr_f, [c("sr_ticket_number"), c("sr_item_sk")],
                          partitions),
                 [c("ss_ticket_number"), c("ss_item_sk")],
                 [c("sr_ticket_number"), c("sr_item_sk")])
    three = join("hash_join",
                 exchange(ss_sr, [c("sr_customer_sk"), c("sr_item_sk")],
                          partitions),
                 exchange(cs_f, [c("cs_bill_customer_sk"),
                                 c("cs_item_sk")], partitions),
                 [c("sr_customer_sk"), c("sr_item_sk")],
                 [c("cs_bill_customer_sk"), c("cs_item_sk")])
    j_it = join("broadcast_join", three, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    j_st = join("broadcast_join", j_it, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    stats = _partial_final(
        j_st,
        [(c("i_item_id"), "i_item_id"), (c("s_store_name"),
                                         "s_store_name")],
        measures, partitions)
    single = exchange(stats, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        from blaze_tpu.itest.queries import SR_CS_WINDOW, SS_WINDOW
        ssd, srd, csd = ss.to_pandas(), sr.to_pandas(), cs.to_pandas()
        std, itd = st.to_pandas(), it.to_pandas()
        ssd = ssd[(ssd.ss_sold_date_sk >= SS_WINDOW[0]) &
                  (ssd.ss_sold_date_sk <= SS_WINDOW[1])]
        srd = srd[(srd.sr_returned_date_sk >= SR_CS_WINDOW[0]) &
                  (srd.sr_returned_date_sk <= SR_CS_WINDOW[1])]
        csd = csd[(csd.cs_sold_date_sk >= SR_CS_WINDOW[0]) &
                  (csd.cs_sold_date_sk <= SR_CS_WINDOW[1])]
        m = ssd.merge(srd, left_on=["ss_ticket_number", "ss_item_sk"],
                      right_on=["sr_ticket_number", "sr_item_sk"])
        m = m.dropna(subset=["sr_customer_sk"]).merge(
            csd, left_on=["sr_customer_sk", "sr_item_sk"],
            right_on=["cs_bill_customer_sk", "cs_item_sk"])
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        out = m.groupby(["i_item_id", "s_store_name"],
                        as_index=False).agg(**oracle_aggs)
        out = out.sort_values(["i_item_id", "s_store_name"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q25(paths, tables, partitions: int = 2):
    return _ss_sr_cs(
        paths, tables, partitions,
        [("sum", "store_profit", [c("ss_net_profit")]),
         ("sum", "return_loss", [c("sr_net_loss")]),
         ("sum", "catalog_profit", [c("cs_net_profit")])],
        {"store_profit": ("ss_net_profit", "sum"),
         "return_loss": ("sr_net_loss", "sum"),
         "catalog_profit": ("cs_net_profit", "sum")})


def q29(paths, tables, partitions: int = 2):
    return _ss_sr_cs(
        paths, tables, partitions,
        [("sum", "store_qty", [c("ss_quantity")]),
         ("sum", "return_qty", [c("sr_return_quantity")]),
         ("sum", "catalog_qty", [c("cs_quantity")])],
        {"store_qty": ("ss_quantity", "sum"),
         "return_qty": ("sr_return_quantity", "sum"),
         "catalog_qty": ("cs_quantity", "sum")})


QUERIES.update({
    "q10": (q10, ["customer", "customer_address",
                  "customer_demographics", "store_sales", "web_sales",
                  "catalog_sales"]),
    "q14": (q14, ["store_sales", "catalog_sales", "web_sales", "item"]),
    "q23": (q23, ["store_sales", "catalog_sales"]),
    "q24": (q24, ["store_sales", "store_returns", "store", "item",
                  "customer"]),
    "q25": (q25, ["store_sales", "store_returns", "catalog_sales",
                  "store", "item"]),
    "q29": (q29, ["store_sales", "store_returns", "catalog_sales",
                  "store", "item"]),
    "q35": (q35, ["customer", "customer_address",
                  "customer_demographics", "store_sales", "web_sales",
                  "catalog_sales"]),
    "q38": (q38, ["store_sales", "web_sales", "catalog_sales"]),
    "q64": (q64, ["store_sales", "store_returns", "customer",
                  "customer_demographics", "household_demographics",
                  "customer_address", "date_dim", "item", "store",
                  "promotion"]),
    "q69": (q69, ["customer", "customer_address",
                  "customer_demographics", "store_sales", "web_sales",
                  "catalog_sales"]),
    "q87": (q87, ["store_sales", "web_sales", "catalog_sales"]),
})


# ---------------------------------------------------------------------------
# second batch: rollups, disjunctions, case-pivots, time/hd dims, q97
# ---------------------------------------------------------------------------

def q26(paths, tables, partitions: int = 2):
    """q07's catalog twin: cs ⨝ cd ⨝ dd ⨝ promo ⨝ item, avg stats."""
    cs, cd, it = (tables["catalog_sales"],
                  tables["customer_demographics"], tables["item"])
    pr, dd = tables["promotion"], tables["date_dim"]

    cd_f = filter_(scan(paths, tables, "customer_demographics"),
                   binop("==", c("cd_gender"), lit("F", "utf8")),
                   binop("==", c("cd_education_status"),
                         lit("Primary", "utf8")))
    j_cd = join("broadcast_join", scan(paths, tables, "catalog_sales"),
                cd_f, [c("cs_bill_cdemo_sk")], [c("cd_demo_sk")])
    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(2000, "int32")))
    j_dd = join("broadcast_join", j_cd, dd_f,
                [c("cs_sold_date_sk")], [c("d_date_sk")])
    pr_f = filter_(scan(paths, tables, "promotion"),
                   binop("==", c("p_channel_event"), lit("N", "utf8")))
    j_pr = join("broadcast_join", j_dd, pr_f,
                [c("cs_promo_sk")], [c("p_promo_sk")])
    j_it = join("broadcast_join", j_pr, scan(paths, tables, "item"),
                [c("cs_item_sk")], [c("i_item_sk")])
    stats = _partial_final(
        j_it, [(c("i_item_id"), "i_item_id")],
        [("avg", "agg1", [c("cs_quantity")]),
         ("avg", "agg2", [c("cs_list_price")]),
         ("avg", "agg3", [c("cs_coupon_amt")]),
         ("avg", "agg4", [c("cs_sales_price")])], partitions)
    single = exchange(stats, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        csd, cdd, itd = cs.to_pandas(), cd.to_pandas(), it.to_pandas()
        prd, ddd = pr.to_pandas(), dd.to_pandas()
        m = csd.merge(cdd[(cdd.cd_gender == "F") &
                          (cdd.cd_education_status == "Primary")],
                      left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(ddd[ddd.d_year == 2000], left_on="cs_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(prd[prd.p_channel_event == "N"],
                    left_on="cs_promo_sk", right_on="p_promo_sk")
        m = m.merge(itd, left_on="cs_item_sk", right_on="i_item_sk")
        out = m.groupby("i_item_id", as_index=False).agg(
            agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
            agg3=("cs_coupon_amt", "mean"),
            agg4=("cs_sales_price", "mean"))
        return out.sort_values("i_item_id")[:100].reset_index(drop=True)

    return plan, oracle


def _rollup2(paths, tables, partitions, filt_preds, filt_oracle,
             measure_col, measure_name):
    """q27/q36 shape: ss (+dd/+store filter) rollup(i_category, i_class)
    via Expand, aggregated measure."""
    ss, it, dd, st = (tables["store_sales"], tables["item"],
                      tables["date_dim"], tables["store"])

    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(1999, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    st_f = filter_(scan(paths, tables, "store"), *filt_preds)
    j_st = join("broadcast_join", j_dd, st_f,
                [c("ss_store_sk")], [c("s_store_sk")])
    j_it = join("broadcast_join", j_st, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    nul = {"kind": "literal", "value": None, "type": {"id": "utf8"}}
    projections = []
    for kept, gid in ((2, 0), (1, 1), (0, 3)):
        projections.append(
            [c("i_category") if kept >= 1 else nul,
             c("i_class") if kept >= 2 else nul,
             lit(gid), c(measure_col)])
    expanded = {"kind": "expand", "input": j_it,
                "projections": projections,
                "names": ["i_category", "i_class", "g_id", measure_col]}
    out_agg = _partial_final(
        expanded,
        [(ci(0), "i_category"), (ci(1), "i_class"), (ci(2), "g_id")],
        [("sum", measure_name, [ci(3)])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False),
                               (ci(2), False)], 100)

    def oracle():
        ssd, itd = ss.to_pandas(), it.to_pandas()
        ddd, std = dd.to_pandas(), st.to_pandas()
        m = ssd.merge(ddd[ddd.d_year == 1999],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(filt_oracle(std), left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        frames = []
        for kept, gid in ((2, 0), (1, 1), (0, 3)):
            keys = ["i_category", "i_class"][:kept] if kept else []
            if keys:
                g = m.groupby(keys, as_index=False, dropna=False).agg(
                    v=(measure_col, "sum"))
            else:
                g = pd.DataFrame({"v": [m[measure_col].sum()]})
            for cn in ["i_category", "i_class"][kept:]:
                g[cn] = None
            g["g_id"] = gid
            frames.append(g[["i_category", "i_class", "g_id", "v"]])
        allf = pd.concat(frames, ignore_index=True).rename(
            columns={"v": measure_name})
        out = allf.sort_values(["i_category", "i_class", "g_id"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q27(paths, tables, partitions: int = 2):
    return _rollup2(paths, tables, partitions,
                    [binop("==", c("s_state"), lit("TN", "utf8"))],
                    lambda std: std[std.s_state == "TN"],
                    "ss_quantity", "qty_sum")


def q36(paths, tables, partitions: int = 2):
    return _rollup2(paths, tables, partitions,
                    [binop("!=", c("s_state"), lit("XX", "utf8"))],
                    lambda std: std[std.s_state != "XX"],
                    "ss_net_profit", "profit_sum")


def q43(paths, tables, partitions: int = 2):
    """Store revenue pivoted by day-of-week (case-when sums)."""
    ss, dd, st = (tables["store_sales"], tables["date_dim"],
                  tables["store"])
    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(1999, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_st = join("broadcast_join", j_dd, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    day_exprs = []
    names = []
    for dow in range(7):
        day_exprs.append(_case(
            [(binop("==", c("d_dow"), lit(dow, "int32")),
              c("ss_ext_sales_price"))],
            lit(0.0, "float64")))
        names.append(f"d{dow}_sales")
    proj = project(j_st, [c("s_store_name")] + day_exprs,
                   ["s_store_name"] + names)
    out_agg = _partial_final(
        proj, [(ci(0), "s_store_name")],
        [("sum", n, [ci(i + 1)]) for i, n in enumerate(names)],
        partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        ssd, ddd, std = ss.to_pandas(), dd.to_pandas(), st.to_pandas()
        m = ssd.merge(ddd[ddd.d_year == 1999],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        for dow in range(7):
            m[f"d{dow}_sales"] = m.ss_ext_sales_price.where(
                m.d_dow == dow, 0.0)
        out = m.groupby("s_store_name", as_index=False)[
            [f"d{d}_sales" for d in range(7)]].sum()
        return out.sort_values("s_store_name")[:100] \
            .reset_index(drop=True)

    return plan, oracle


def q46(paths, tables, partitions: int = 2):
    """ss ⨝ dd(weekend) ⨝ store ⨝ hd(dep=4 OR vehicle=3) ⨝ ca: sales by
    city (the q46 household-demographics shape)."""
    ss, dd, st = (tables["store_sales"], tables["date_dim"],
                  tables["store"])
    hd, ca = (tables["household_demographics"],
              tables["customer_address"])
    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("or", binop("==", c("d_dow"), lit(6, "int32")),
                         binop("==", c("d_dow"), lit(0, "int32"))))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_st = join("broadcast_join", j_dd, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    hd_f = filter_(scan(paths, tables, "household_demographics"),
                   binop("or",
                         binop("==", c("hd_dep_count"), lit(4, "int32")),
                         binop("==", c("hd_vehicle_count"),
                               lit(3, "int32"))))
    j_hd = join("broadcast_join", j_st, hd_f,
                [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    j_ca = join("hash_join",
                exchange(j_hd, [c("ss_addr_sk")], partitions),
                exchange(scan(paths, tables, "customer_address"),
                         [c("ca_address_sk")], partitions),
                [c("ss_addr_sk")], [c("ca_address_sk")])
    out_agg = _partial_final(
        j_ca,
        [(c("ca_city"), "ca_city"),
         (c("ss_ticket_number"), "ss_ticket_number")],
        [("sum", "amt", [c("ss_coupon_amt")]),
         ("sum", "profit", [c("ss_net_profit")])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd, ddd, std = ss.to_pandas(), dd.to_pandas(), st.to_pandas()
        hdd, cad = hd.to_pandas(), ca.to_pandas()
        m = ssd.merge(ddd[(ddd.d_dow == 6) | (ddd.d_dow == 0)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hdd[(hdd.hd_dep_count == 4) |
                        (hdd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(cad, left_on="ss_addr_sk", right_on="ca_address_sk")
        out = m.groupby(["ca_city", "ss_ticket_number"],
                        as_index=False).agg(
            amt=("ss_coupon_amt", "sum"),
            profit=("ss_net_profit", "sum"))
        out = out.sort_values(["ca_city", "ss_ticket_number"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q48(paths, tables, partitions: int = 2):
    """OR-disjunction over (marital x education x price band): the q48
    multi-arm predicate, sum(ss_quantity)."""
    ss, cd = tables["store_sales"], tables["customer_demographics"]
    j_cd = join("broadcast_join", scan(paths, tables, "store_sales"),
                scan(paths, tables, "customer_demographics"),
                [c("ss_cdemo_sk")], [c("cd_demo_sk")])
    arm = lambda ms, ed, lo, hi: binop(
        "and", binop("and", binop("==", c("cd_marital_status"),
                                  lit(ms, "utf8")),
                     binop("==", c("cd_education_status"),
                           lit(ed, "utf8"))),
        binop("and", binop(">=", c("ss_sales_price"),
                           lit(lo, "float64")),
              binop("<=", c("ss_sales_price"), lit(hi, "float64"))))
    flt = filter_(j_cd, binop("or", binop("or",
                                          arm("M", "4 yr Degree", 100.0,
                                              150.0),
                                          arm("D", "Primary", 50.0,
                                              100.0)),
                              arm("W", "College", 150.0, 200.0)))
    plan = _global_agg(flt, [("sum", "qty", [c("ss_quantity")])])

    def oracle():
        m = ss.to_pandas().merge(cd.to_pandas(),
                                 left_on="ss_cdemo_sk",
                                 right_on="cd_demo_sk")
        keep = (((m.cd_marital_status == "M") &
                 (m.cd_education_status == "4 yr Degree") &
                 m.ss_sales_price.between(100.0, 150.0)) |
                ((m.cd_marital_status == "D") &
                 (m.cd_education_status == "Primary") &
                 m.ss_sales_price.between(50.0, 100.0)) |
                ((m.cd_marital_status == "W") &
                 (m.cd_education_status == "College") &
                 m.ss_sales_price.between(150.0, 200.0)))
        f = m[keep]
        return pd.DataFrame(
            {"qty": [f.ss_quantity.sum() if len(f) else None]})

    return plan, oracle


def q50(paths, tables, partitions: int = 2):
    """ss ⨝ sr return-latency buckets (case-when day-difference pivot)."""
    ss, sr, st = (tables["store_sales"], tables["store_returns"],
                  tables["store"])
    ss_ex = exchange(scan(paths, tables, "store_sales"),
                     [c("ss_ticket_number"), c("ss_item_sk")], partitions)
    sr_ex = exchange(scan(paths, tables, "store_returns"),
                     [c("sr_ticket_number"), c("sr_item_sk")], partitions)
    j = join("hash_join", ss_ex, sr_ex,
             [c("ss_ticket_number"), c("ss_item_sk")],
             [c("sr_ticket_number"), c("sr_item_sk")])
    j_st = join("broadcast_join", j, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    diff = binop("-", c("sr_returned_date_sk"), c("ss_sold_date_sk"))
    bucket = lambda lo, hi: _case(
        [(binop("and", binop(">", diff, lit(lo)),
                binop("<=", diff, lit(hi))), lit(1))], lit(0))
    proj = project(
        j_st,
        [c("s_store_name"),
         _case([(binop("<=", diff, lit(30)), lit(1))], lit(0)),
         bucket(30, 60), bucket(60, 90), bucket(90, 120),
         _case([(binop(">", diff, lit(120)), lit(1))], lit(0))],
        ["s_store_name", "d30", "d60", "d90", "d120", "dmore"])
    out_agg = _partial_final(
        proj, [(ci(0), "s_store_name")],
        [("sum", n, [ci(i + 1)]) for i, n in
         enumerate(["d30", "d60", "d90", "d120", "dmore"])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        m = ss.to_pandas().merge(
            sr.to_pandas(),
            left_on=["ss_ticket_number", "ss_item_sk"],
            right_on=["sr_ticket_number", "sr_item_sk"])
        m = m.merge(st.to_pandas(), left_on="ss_store_sk",
                    right_on="s_store_sk")
        d = m.sr_returned_date_sk - m.ss_sold_date_sk
        m = m.assign(
            d30=(d <= 30).astype(int),
            d60=((d > 30) & (d <= 60)).astype(int),
            d90=((d > 60) & (d <= 90)).astype(int),
            d120=((d > 90) & (d <= 120)).astype(int),
            dmore=(d > 120).astype(int))
        out = m.groupby("s_store_name", as_index=False)[
            ["d30", "d60", "d90", "d120", "dmore"]].sum()
        return out.sort_values("s_store_name")[:100] \
            .reset_index(drop=True)

    return plan, oracle


def q65(paths, tables, partitions: int = 2):
    """Items whose store revenue <= 0.1 * the store's average item
    revenue (two-level aggregation + join on the threshold)."""
    ss, it, st = (tables["store_sales"], tables["item"],
                  tables["store"])
    rev = _partial_final(
        scan(paths, tables, "store_sales"),
        [(c("ss_store_sk"), "store_sk"), (c("ss_item_sk"), "item_sk")],
        [("sum", "revenue", [c("ss_sales_price")])], partitions)
    avg_in = exchange(rev, [ci(0)], partitions)
    avg_rev = agg(
        agg(avg_in, [(ci(0), "store_sk")],
            [("avg", "partial", "ave", [ci(2)])]),
        [(ci(0), "store_sk")],
        [("avg", "final", "ave", [ci(1), ci(2)])])
    j = join("sort_merge_join", exchange(rev, [ci(0)], partitions),
             avg_rev, [ci(0)], [ci(0)])
    flt = filter_(j, binop("<=", ci(2),
                           binop("*", ci(4), lit(0.1, "float64"))))
    j_st = join("broadcast_join", flt, scan(paths, tables, "store"),
                [ci(0)], [c("s_store_sk")])
    j_it = join("broadcast_join", j_st, scan(paths, tables, "item"),
                [ci(1)], [c("i_item_sk")])
    picked = project(j_it, [c("s_store_name"), c("i_item_id"), ci(2)],
                     ["s_store_name", "i_item_id", "revenue"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd = ss.to_pandas()
        rev = ssd.groupby(["ss_store_sk", "ss_item_sk"],
                          as_index=False).agg(
            revenue=("ss_sales_price", "sum"))
        ave = rev.groupby("ss_store_sk", as_index=False) \
            .revenue.mean().rename(columns={"revenue": "ave"})
        m = rev.merge(ave, on="ss_store_sk")
        m = m[m.revenue <= 0.1 * m.ave]
        m = m.merge(st.to_pandas(), left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m.merge(it.to_pandas(), left_on="ss_item_sk",
                    right_on="i_item_sk")
        out = m[["s_store_name", "i_item_id", "revenue"]] \
            .sort_values(["s_store_name", "i_item_id"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def _ticket_counts(paths, tables, partitions, hd_preds, hd_oracle,
                   lo, hi):
    """q73/q34/q79 family: per-(ticket, customer) item counts for a
    household-demographics selection, HAVING count BETWEEN lo AND hi,
    joined back to customer."""
    ss, hd, cu = (tables["store_sales"],
                  tables["household_demographics"], tables["customer"])
    hd_f = filter_(scan(paths, tables, "household_demographics"),
                   *hd_preds)
    j_hd = join("broadcast_join", scan(paths, tables, "store_sales"),
                hd_f, [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    cnt = _partial_final(
        j_hd,
        [(c("ss_ticket_number"), "ticket"),
         (c("ss_customer_sk"), "customer_sk")],
        [("count", "cnt", [c("ss_item_sk")])], partitions)
    flt = filter_(cnt, binop("and", binop(">=", ci(2), lit(lo)),
                             binop("<=", ci(2), lit(hi))))
    j_cu = join("hash_join", exchange(flt, [ci(1)], partitions),
                exchange(scan(paths, tables, "customer"),
                         [c("c_customer_sk")], partitions),
                [ci(1)], [c("c_customer_sk")])
    picked = project(j_cu, [c("c_customer_id"), ci(0), ci(2)],
                     ["c_customer_id", "ticket", "cnt"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(2), True), (ci(0), False),
                               (ci(1), False)], 100)

    def oracle():
        ssd, hdd = ss.to_pandas(), hd.to_pandas()
        cud = cu.to_pandas()
        m = ssd.merge(hd_oracle(hdd), left_on="ss_hdemo_sk",
                      right_on="hd_demo_sk")
        g = m.groupby(["ss_ticket_number", "ss_customer_sk"],
                      as_index=False).agg(cnt=("ss_item_sk", "count"))
        g = g[(g.cnt >= lo) & (g.cnt <= hi)]
        g = g.merge(cud, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        out = g[["c_customer_id", "ss_ticket_number", "cnt"]].rename(
            columns={"ss_ticket_number": "ticket"})
        out = out.sort_values(["cnt", "c_customer_id", "ticket"],
                              ascending=[False, True, True])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q73(paths, tables, partitions: int = 2):
    """Tickets by high-dependency households (q73 shape)."""
    return _ticket_counts(
        paths, tables, partitions,
        [binop(">", c("hd_dep_count"), lit(6, "int32"))],
        lambda hdd: hdd[hdd.hd_dep_count > 6], 1, 5)



def q93(paths, tables, partitions: int = 2):
    """ss LEFT JOIN sr (+reason): per-customer actual sales where
    returned quantity is deducted (case-when over the outer side)."""
    ss, sr, re = (tables["store_sales"], tables["store_returns"],
                  tables["reason"])
    sr_re = join("broadcast_join", scan(paths, tables, "store_returns"),
                 filter_(scan(paths, tables, "reason"),
                         binop("<=", c("r_reason_sk"), lit(20))),
                 [c("sr_reason_sk")], [c("r_reason_sk")])
    j = join("hash_join",
             exchange(scan(paths, tables, "store_sales"),
                      [c("ss_ticket_number"), c("ss_item_sk")],
                      partitions),
             exchange(sr_re, [c("sr_ticket_number"), c("sr_item_sk")],
                      partitions),
             [c("ss_ticket_number"), c("ss_item_sk")],
             [c("sr_ticket_number"), c("sr_item_sk")], jt="left")
    act = project(
        j,
        [c("ss_customer_sk"),
         _case([({"kind": "is_not_null", "child": c("sr_ticket_number")},
                 binop("*",
                       {"kind": "cast",
                        "child": binop("-", c("ss_quantity"),
                                       c("sr_return_quantity")),
                        "type": {"id": "float64"}},
                       c("ss_sales_price")))],
               binop("*", {"kind": "cast", "child": c("ss_quantity"),
                           "type": {"id": "float64"}},
                     c("ss_sales_price")))],
        ["ss_customer_sk", "act_sales"])
    out_agg = _partial_final(act, [(ci(0), "ss_customer_sk")],
                             [("sum", "sumsales", [ci(1)])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(1), False), (ci(0), False)], 100)

    def oracle():
        ssd, srd, red = ss.to_pandas(), sr.to_pandas(), re.to_pandas()
        srj = srd.merge(red[red.r_reason_sk <= 20],
                        left_on="sr_reason_sk", right_on="r_reason_sk")
        m = ssd.merge(srj, how="left",
                      left_on=["ss_ticket_number", "ss_item_sk"],
                      right_on=["sr_ticket_number", "sr_item_sk"])
        act = m.ss_quantity * m.ss_sales_price
        returned = (m.ss_quantity - m.sr_return_quantity) * \
            m.ss_sales_price
        m = m.assign(act_sales=returned.where(
            m.sr_ticket_number.notna(), act))
        out = m.groupby("ss_customer_sk", as_index=False).agg(
            sumsales=("act_sales", "sum"))
        out = out.sort_values(["sumsales", "ss_customer_sk"],
                              ascending=[True, True])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q96(paths, tables, partitions: int = 2):
    """count(*) of evening high-dependency store traffic: ss ⨝
    time_dim(hour=20) ⨝ hd(dep=7) ⨝ store."""
    ss, td, hd = (tables["store_sales"], tables["time_dim"],
                  tables["household_demographics"])
    td_f = filter_(scan(paths, tables, "time_dim"),
                   binop("==", c("t_hour"), lit(20, "int32")),
                   binop(">=", c("t_minute"), lit(30, "int32")))
    j_td = join("broadcast_join", scan(paths, tables, "store_sales"),
                td_f, [c("ss_sold_time_sk")], [c("t_time_sk")])
    hd_f = filter_(scan(paths, tables, "household_demographics"),
                   binop("==", c("hd_dep_count"), lit(7, "int32")))
    j_hd = join("broadcast_join", j_td, hd_f,
                [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    j_st = join("broadcast_join", j_hd, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    plan = _global_agg(j_st, [("count", "cnt", [c("ss_ticket_number")])])

    def oracle():
        ssd, tdd, hdd = ss.to_pandas(), td.to_pandas(), hd.to_pandas()
        m = ssd.merge(tdd[(tdd.t_hour == 20) & (tdd.t_minute >= 30)],
                      left_on="ss_sold_time_sk", right_on="t_time_sk")
        m = m.merge(hdd[hdd.hd_dep_count == 7],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        return pd.DataFrame({"cnt": [len(m)]})

    return plan, oracle


def q97(paths, tables, partitions: int = 2):
    """FULL OUTER of distinct store vs catalog customer-item pairs:
    counts of store-only / catalog-only / both (the q97 matrix)."""
    ss, cs = tables["store_sales"], tables["catalog_sales"]
    ss_d = _partial_final(
        project(scan(paths, tables, "store_sales"),
                [c("ss_customer_sk"), c("ss_item_sk")],
                ["customer_sk", "item_sk"]),
        [(ci(0), "customer_sk"), (ci(1), "item_sk")],
        [("count", "cnt", [ci(0)])], partitions)
    cs_d = _partial_final(
        project(scan(paths, tables, "catalog_sales"),
                [c("cs_bill_customer_sk"), c("cs_item_sk")],
                ["customer_sk", "item_sk"]),
        [(ci(0), "customer_sk"), (ci(1), "item_sk")],
        [("count", "cnt", [ci(0)])], partitions)
    j = join("sort_merge_join", exchange(ss_d, [ci(0), ci(1)], partitions),
             exchange(cs_d, [ci(0), ci(1)], partitions),
             [ci(0), ci(1)], [ci(0), ci(1)], jt="full")
    both = _case([(binop("and",
                         {"kind": "is_not_null", "child": ci(0)},
                         {"kind": "is_not_null", "child": ci(3)}),
                   lit(1))], lit(0))
    s_only = _case([(binop("and",
                           {"kind": "is_not_null", "child": ci(0)},
                           {"kind": "is_null", "child": ci(3)}),
                     lit(1))], lit(0))
    c_only = _case([(binop("and",
                           {"kind": "is_null", "child": ci(0)},
                           {"kind": "is_not_null", "child": ci(3)}),
                     lit(1))], lit(0))
    proj = project(j, [s_only, c_only, both],
                   ["store_only", "catalog_only", "store_and_catalog"])
    plan = _global_agg(proj,
                       [("sum", "store_only", [ci(0)]),
                        ("sum", "catalog_only", [ci(1)]),
                        ("sum", "store_and_catalog", [ci(2)])])

    def oracle():
        s = set(map(tuple, ss.to_pandas()[
            ["ss_customer_sk", "ss_item_sk"]].values))
        cset = set(map(tuple, cs.to_pandas()[
            ["cs_bill_customer_sk", "cs_item_sk"]].values))
        return pd.DataFrame({
            "store_only": [len(s - cset)],
            "catalog_only": [len(cset - s)],
            "store_and_catalog": [len(s & cset)]})

    return plan, oracle


def q28(paths, tables, partitions: int = 2):
    """Six price-band global aggregates unioned (the q28 bucket shape)."""
    ss = tables["store_sales"]
    bands = [(0.0, 50.0), (50.0, 100.0), (100.0, 150.0),
             (150.0, 200.0), (200.0, 250.0), (250.0, 300.0)]
    legs = []
    for i, (lo, hi) in enumerate(bands):
        f = filter_(scan(paths, tables, "store_sales"),
                    binop(">=", c("ss_list_price"), lit(lo, "float64")),
                    binop("<", c("ss_list_price"), lit(hi, "float64")))
        leg = _global_agg(f, [("avg", "avg_price", [c("ss_list_price")]),
                              ("count", "cnt", [c("ss_list_price")])])
        legs.append(project(leg, [lit(i), ci(0), ci(1)],
                            ["band", "avg_price", "cnt"]))
    u = {"kind": "union", "inputs": legs}
    plan = sort_limit(u, [(ci(0), False)], 10)

    def oracle():
        ssd = ss.to_pandas()
        rows = []
        for i, (lo, hi) in enumerate(bands):
            f = ssd[(ssd.ss_list_price >= lo) & (ssd.ss_list_price < hi)]
            rows.append({"band": i,
                         "avg_price": f.ss_list_price.mean()
                         if len(f) else None,
                         "cnt": len(f)})
        return pd.DataFrame(rows)

    return plan, oracle


def q15(paths, tables, partitions: int = 2):
    """Catalog sales by customer zip-state (in-list + threshold OR): the
    q15 disjunction over ca columns."""
    cs, cu, ca, dd = (tables["catalog_sales"], tables["customer"],
                      tables["customer_address"], tables["date_dim"])
    j_cu = join("hash_join",
                exchange(scan(paths, tables, "catalog_sales"),
                         [c("cs_bill_customer_sk")], partitions),
                exchange(scan(paths, tables, "customer"),
                         [c("c_customer_sk")], partitions),
                [c("cs_bill_customer_sk")], [c("c_customer_sk")])
    j_ca = join("broadcast_join", j_cu,
                scan(paths, tables, "customer_address"),
                [c("c_current_addr_sk")], [c("ca_address_sk")])
    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(2000, "int32")),
                   binop("==", c("d_qoy"), lit(1, "int32")))
    j_dd = join("broadcast_join", j_ca, dd_f,
                [c("cs_sold_date_sk")], [c("d_date_sk")])
    flt = filter_(j_dd, binop(
        "or",
        {"kind": "in_list", "child": c("ca_state"),
         "values": ["CA", "WA", "GA"], "type": {"id": "utf8"}},
        binop(">", c("cs_sales_price"), lit(240.0, "float64"))))
    out_agg = _partial_final(flt, [(c("ca_state"), "ca_state")],
                             [("sum", "total", [c("cs_sales_price")])],
                             partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        m = cs.to_pandas().merge(cu.to_pandas(),
                                 left_on="cs_bill_customer_sk",
                                 right_on="c_customer_sk")
        m = m.merge(ca.to_pandas(), left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        ddd = dd.to_pandas()
        m = m.merge(ddd[(ddd.d_year == 2000) & (ddd.d_qoy == 1)],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
        m = m[m.ca_state.isin(["CA", "WA", "GA"]) |
              (m.cs_sales_price > 240.0)]
        out = m.groupby("ca_state", as_index=False).agg(
            total=("cs_sales_price", "sum"))
        return out.sort_values("ca_state")[:100].reset_index(drop=True)

    return plan, oracle


def q13(paths, tables, partitions: int = 2):
    """Demographic/address disjunction with avg/sum measures (q13)."""
    ss, cd, ca, hd = (tables["store_sales"],
                      tables["customer_demographics"],
                      tables["customer_address"],
                      tables["household_demographics"])
    j_cd = join("broadcast_join", scan(paths, tables, "store_sales"),
                scan(paths, tables, "customer_demographics"),
                [c("ss_cdemo_sk")], [c("cd_demo_sk")])
    j_hd = join("broadcast_join", j_cd,
                scan(paths, tables, "household_demographics"),
                [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    j_ca = join("hash_join",
                exchange(j_hd, [c("ss_addr_sk")], partitions),
                exchange(scan(paths, tables, "customer_address"),
                         [c("ca_address_sk")], partitions),
                [c("ss_addr_sk")], [c("ca_address_sk")])
    arm1 = binop("and",
                 binop("==", c("cd_marital_status"), lit("M", "utf8")),
                 binop(">=", c("hd_dep_count"), lit(3, "int32")))
    arm2 = binop("and",
                 binop("==", c("cd_marital_status"), lit("S", "utf8")),
                 {"kind": "in_list", "child": c("ca_state"),
                  "values": ["TX", "OH", "IL"], "type": {"id": "utf8"}})
    flt = filter_(j_ca, binop("or", arm1, arm2))
    plan = _global_agg(flt,
                       [("avg", "avg_quantity", [c("ss_quantity")]),
                        ("avg", "avg_ext_price",
                         [c("ss_ext_sales_price")]),
                        ("sum", "sum_wholesale", [c("ss_net_profit")])])

    def oracle():
        m = ss.to_pandas().merge(cd.to_pandas(),
                                 left_on="ss_cdemo_sk",
                                 right_on="cd_demo_sk")
        m = m.merge(hd.to_pandas(), left_on="ss_hdemo_sk",
                    right_on="hd_demo_sk")
        m = m.merge(ca.to_pandas(), left_on="ss_addr_sk",
                    right_on="ca_address_sk")
        keep = (((m.cd_marital_status == "M") & (m.hd_dep_count >= 3)) |
                ((m.cd_marital_status == "S") &
                 m.ca_state.isin(["TX", "OH", "IL"])))
        f = m[keep]
        return pd.DataFrame({
            "avg_quantity": [f.ss_quantity.mean() if len(f) else None],
            "avg_ext_price": [f.ss_ext_sales_price.mean()
                              if len(f) else None],
            "sum_wholesale": [f.ss_net_profit.sum()
                              if len(f) else None]})

    return plan, oracle


QUERIES.update({
    "q13": (q13, ["store_sales", "customer_demographics",
                  "customer_address", "household_demographics"]),
    "q15": (q15, ["catalog_sales", "customer", "customer_address",
                  "date_dim"]),
    "q26": (q26, ["catalog_sales", "customer_demographics", "item",
                  "promotion", "date_dim"]),
    "q27": (q27, ["store_sales", "item", "date_dim", "store"]),
    "q28": (q28, ["store_sales"]),
    "q36": (q36, ["store_sales", "item", "date_dim", "store"]),
    "q43": (q43, ["store_sales", "date_dim", "store"]),
    "q46": (q46, ["store_sales", "date_dim", "store",
                  "household_demographics", "customer_address"]),
    "q48": (q48, ["store_sales", "customer_demographics"]),
    "q50": (q50, ["store_sales", "store_returns", "store"]),
    "q65": (q65, ["store_sales", "item", "store"]),
    "q73": (q73, ["store_sales", "household_demographics", "customer"]),
    "q93": (q93, ["store_sales", "store_returns", "reason"]),
    "q96": (q96, ["store_sales", "time_dim",
                  "household_demographics", "store"]),
    "q97": (q97, ["store_sales", "catalog_sales"]),
})


# ---------------------------------------------------------------------------
# third batch: window lag (q47/q57), hd-count tickets (q34/q68/q79),
# time buckets (q88), catalog anti/semi (q94-shape) and ship-latency (q99)
# ---------------------------------------------------------------------------

def _lag_over_monthly(paths, tables, partitions, fact, date_col, item_col,
                      price_col):
    """The q47/q57 shape: monthly brand revenue with LAG/LEAD over the
    (brand, year) window ordered by month."""
    ft, it, dd = tables[fact], tables["item"], tables["date_dim"]

    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(1999, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, fact), dd_f,
                [c(date_col)], [c("d_date_sk")])
    j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                [c(item_col)], [c("i_item_sk")])
    rev = _partial_final(
        j_it,
        [(c("i_brand_id"), "brand_id"), (c("d_moy"), "moy")],
        [("sum", "sum_sales", [c(price_col)])], partitions)
    ex = exchange(rev, [ci(0)], 1)
    srt = {"kind": "sort", "input": ex,
           "specs": [{"expr": ci(0), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(1), "descending": False,
                      "nulls_first": True}]}
    win = {"kind": "window", "input": srt,
           "functions": [
               {"kind": "lag", "name": "psum", "offset": 1,
                "expr": ci(2)},
               {"kind": "lead", "name": "nsum", "offset": 1,
                "expr": ci(2)}],
           "partition_by": [ci(0)],
           "order_by": [{"expr": ci(1), "descending": False,
                         "nulls_first": True}]}
    plan = sort_limit(win, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        fd, itd, ddd = ft.to_pandas(), it.to_pandas(), dd.to_pandas()
        m = fd.merge(ddd[ddd.d_year == 1999], left_on=date_col,
                     right_on="d_date_sk")
        m = m.merge(itd, left_on=item_col, right_on="i_item_sk")
        g = (m.groupby(["i_brand_id", "d_moy"], as_index=False)
             .agg(sum_sales=(price_col, "sum"))
             .rename(columns={"i_brand_id": "brand_id", "d_moy": "moy"}))
        g = g.sort_values(["brand_id", "moy"]).reset_index(drop=True)
        g["psum"] = g.groupby("brand_id").sum_sales.shift(1)
        g["nsum"] = g.groupby("brand_id").sum_sales.shift(-1)
        return g.sort_values(["brand_id", "moy"])[:100] \
            .reset_index(drop=True)

    return plan, oracle


def q47(paths, tables, partitions: int = 2):
    return _lag_over_monthly(paths, tables, partitions, "store_sales",
                             "ss_sold_date_sk", "ss_item_sk",
                             "ss_sales_price")


def q57(paths, tables, partitions: int = 2):
    return _lag_over_monthly(paths, tables, partitions, "catalog_sales",
                             "cs_sold_date_sk", "cs_item_sk",
                             "cs_sales_price")


def q34(paths, tables, partitions: int = 2):
    """q34 shape: ticket counts for buy-potential households with a
    vehicle (distinct hd selection from q73).  NOTE the synthetic
    generator makes ss_ticket_number unique per row, so the HAVING lower
    bound is 1 (a >=2 bound would select nothing and test only the
    empty path — review-caught)."""
    return _ticket_counts(
        paths, tables, partitions,
        [binop("or",
               binop("==", c("hd_buy_potential"), lit(">10000", "utf8")),
               binop("==", c("hd_buy_potential"),
                     lit("Unknown", "utf8"))),
         binop(">", c("hd_vehicle_count"), lit(0, "int32"))],
        lambda hdd: hdd[(hdd.hd_buy_potential.isin([">10000",
                                                    "Unknown"])) &
                        (hdd.hd_vehicle_count > 0)], 1, 20)



def q68(paths, tables, partitions: int = 2):
    """q46's sibling: start-of-month (d_dom <= 2) city sales with
    extended amounts by ticket — the real q68 pairs this day-of-month
    filter with demographic predicates."""
    ss, dd, st = (tables["store_sales"], tables["date_dim"],
                  tables["store"])
    hd, ca = (tables["household_demographics"],
              tables["customer_address"])
    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("<=", c("d_dom"), lit(2, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_st = join("broadcast_join", j_dd, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    hd_f = filter_(scan(paths, tables, "household_demographics"),
                   binop("or",
                         binop("==", c("hd_dep_count"), lit(3, "int32")),
                         binop("==", c("hd_vehicle_count"),
                               lit(4, "int32"))))
    j_hd = join("broadcast_join", j_st, hd_f,
                [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    j_ca = join("hash_join",
                exchange(j_hd, [c("ss_addr_sk")], partitions),
                exchange(scan(paths, tables, "customer_address"),
                         [c("ca_address_sk")], partitions),
                [c("ss_addr_sk")], [c("ca_address_sk")])
    out_agg = _partial_final(
        j_ca,
        [(c("ca_city"), "ca_city"),
         (c("ss_ticket_number"), "ss_ticket_number")],
        [("sum", "ext_price", [c("ss_ext_sales_price")]),
         ("sum", "list_price", [c("ss_list_price")])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd, ddd, std = ss.to_pandas(), dd.to_pandas(), st.to_pandas()
        hdd, cad = hd.to_pandas(), ca.to_pandas()
        m = ssd.merge(ddd[ddd.d_dom <= 2], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hdd[(hdd.hd_dep_count == 3) |
                        (hdd.hd_vehicle_count == 4)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(cad, left_on="ss_addr_sk", right_on="ca_address_sk")
        out = m.groupby(["ca_city", "ss_ticket_number"],
                        as_index=False).agg(
            ext_price=("ss_ext_sales_price", "sum"),
            list_price=("ss_list_price", "sum"))
        out = out.sort_values(["ca_city", "ss_ticket_number"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q79(paths, tables, partitions: int = 2):
    """Per-ticket profit for high-dep or no-vehicle households (q79)."""
    ss, hd, st, cu = (tables["store_sales"],
                      tables["household_demographics"],
                      tables["store"], tables["customer"])
    hd_f = filter_(scan(paths, tables, "household_demographics"),
                   binop("or",
                         binop("==", c("hd_dep_count"), lit(6, "int32")),
                         binop(">", c("hd_vehicle_count"),
                               lit(2, "int32"))))
    j_hd = join("broadcast_join", scan(paths, tables, "store_sales"),
                hd_f, [c("ss_hdemo_sk")], [c("hd_demo_sk")])
    j_st = join("broadcast_join", j_hd, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    g = _partial_final(
        j_st,
        [(c("ss_ticket_number"), "ticket"),
         (c("ss_customer_sk"), "customer_sk"),
         (c("s_store_name"), "s_store_name")],
        [("sum", "amt", [c("ss_coupon_amt")]),
         ("sum", "profit", [c("ss_net_profit")])], partitions)
    j_cu = join("hash_join", exchange(g, [ci(1)], partitions),
                exchange(scan(paths, tables, "customer"),
                         [c("c_customer_sk")], partitions),
                [ci(1)], [c("c_customer_sk")])
    picked = project(j_cu, [c("c_customer_id"), ci(0), ci(2), ci(3),
                            ci(4)],
                     ["c_customer_id", "ticket", "s_store_name", "amt",
                      "profit"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd, hdd = ss.to_pandas(), hd.to_pandas()
        std, cud = st.to_pandas(), cu.to_pandas()
        m = ssd.merge(hdd[(hdd.hd_dep_count == 6) |
                          (hdd.hd_vehicle_count > 2)],
                      left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        g = m.groupby(["ss_ticket_number", "ss_customer_sk",
                       "s_store_name"], as_index=False).agg(
            amt=("ss_coupon_amt", "sum"),
            profit=("ss_net_profit", "sum"))
        g = g.merge(cud, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        out = g[["c_customer_id", "ss_ticket_number", "s_store_name",
                 "amt", "profit"]].rename(
            columns={"ss_ticket_number": "ticket"})
        out = out.sort_values(["c_customer_id", "ticket"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q88(paths, tables, partitions: int = 2):
    """Eight half-hour traffic counts unioned (the q88 time-bucket
    shape over time_dim + household_demographics)."""
    ss, td, hd = (tables["store_sales"], tables["time_dim"],
                  tables["household_demographics"])
    hd_f = filter_(scan(paths, tables, "household_demographics"),
                   binop("<=", c("hd_dep_count"), lit(5, "int32")))
    legs = []
    buckets = [(8, 0, 30), (8, 30, 60), (9, 0, 30), (9, 30, 60),
               (10, 0, 30), (10, 30, 60), (11, 0, 30), (11, 30, 60)]
    for i, (hour, mlo, mhi) in enumerate(buckets):
        td_f = filter_(scan(paths, tables, "time_dim"),
                       binop("==", c("t_hour"), lit(hour, "int32")),
                       binop(">=", c("t_minute"), lit(mlo, "int32")),
                       binop("<", c("t_minute"), lit(mhi, "int32")))
        j_td = join("broadcast_join", scan(paths, tables, "store_sales"),
                    td_f, [c("ss_sold_time_sk")], [c("t_time_sk")])
        j_hd = join("broadcast_join", j_td, hd_f,
                    [c("ss_hdemo_sk")], [c("hd_demo_sk")])
        leg = _global_agg(j_hd, [("count", "cnt",
                                  [c("ss_ticket_number")])])
        legs.append(project(leg, [lit(i), ci(0)], ["bucket", "cnt"]))
    u = {"kind": "union", "inputs": legs}
    plan = sort_limit(u, [(ci(0), False)], 10)

    def oracle():
        ssd, tdd, hdd = ss.to_pandas(), td.to_pandas(), hd.to_pandas()
        hsel = hdd[hdd.hd_dep_count <= 5]
        rows = []
        for i, (hour, mlo, mhi) in enumerate(buckets):
            t = tdd[(tdd.t_hour == hour) & (tdd.t_minute >= mlo) &
                    (tdd.t_minute < mhi)]
            m = ssd.merge(t, left_on="ss_sold_time_sk",
                          right_on="t_time_sk")
            m = m.merge(hsel, left_on="ss_hdemo_sk",
                        right_on="hd_demo_sk")
            rows.append({"bucket": i, "cnt": len(m)})
        return pd.DataFrame(rows)

    return plan, oracle


def q94(paths, tables, partitions: int = 2):
    """Catalog orders shipped cross-warehouse with no return: q94 is the
    catalog twin of q95 (EXISTS different-warehouse + NOT EXISTS
    return)."""
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]

    base = project(filter_(scan(paths, tables, "catalog_sales"),
                           binop("<=", c("cs_call_center_sk"), lit(3))),
                   [c("cs_order_number"), c("cs_warehouse_sk"),
                    c("cs_ext_sales_price"), c("cs_net_profit")],
                   ["order_number", "warehouse_sk", "price", "profit"])
    base_ex = exchange(base, [ci(0)], partitions)
    all_cs = project(scan(paths, tables, "catalog_sales"),
                     [c("cs_order_number"), c("cs_warehouse_sk")],
                     ["o2", "w2"])
    all_ex = exchange(all_cs, [ci(0)], partitions)
    semi = join("hash_join", base_ex, all_ex, [ci(0)], [ci(0)],
                jt="left_semi", flt=binop("!=", ci(1), ci(5)))
    cr_ex = exchange(project(scan(paths, tables, "catalog_returns"),
                             [c("cr_order_number")], ["cr_order_number"]),
                     [ci(0)], partitions)
    anti = join("hash_join", semi, cr_ex, [ci(0)], [ci(0)],
                jt="left_anti")
    per_order = agg(
        agg(anti, [(ci(0), "order_number")],
            [("sum", "partial", "price", [ci(2)]),
             ("sum", "partial", "profit", [ci(3)])]),
        [(ci(0), "order_number")],
        [("sum", "final", "price", [ci(1)]),
         ("sum", "final", "profit", [ci(2)])])
    single = exchange(per_order, [ci(0)], 1)
    plan = _global_agg(single,
                       [("count", "order_count", [ci(0)]),
                        ("sum", "total_price", [ci(1)]),
                        ("sum", "total_profit", [ci(2)])])

    def oracle():
        csd, crd = cs.to_pandas(), cr.to_pandas()
        f = csd[csd.cs_call_center_sk <= 3]
        wh = csd.groupby("cs_order_number").cs_warehouse_sk.agg(set)
        ok = f[f.apply(lambda r: bool(
            wh.get(r.cs_order_number, set()) - {r.cs_warehouse_sk}),
            axis=1)] if len(f) else f
        ok = ok[~ok.cs_order_number.isin(set(crd.cr_order_number))]
        return pd.DataFrame({
            "order_count": [ok.cs_order_number.nunique()],
            "total_price": [ok.cs_ext_sales_price.sum() if len(ok)
                            else None],
            "total_profit": [ok.cs_net_profit.sum() if len(ok)
                             else None]})

    return plan, oracle


def q99(paths, tables, partitions: int = 2):
    """Catalog ship-latency buckets by warehouse (the q99 case-when
    pivot over cs_ship_date - cs_sold_date)."""
    cs, wh = tables["catalog_sales"], tables["warehouse"]
    j_wh = join("broadcast_join", scan(paths, tables, "catalog_sales"),
                scan(paths, tables, "warehouse"),
                [c("cs_warehouse_sk")], [c("w_warehouse_sk")])
    diff = binop("-", c("cs_ship_date_sk"), c("cs_sold_date_sk"))
    bucket = lambda lo, hi: _case(
        [(binop("and", binop(">", diff, lit(lo)),
                binop("<=", diff, lit(hi))), lit(1))], lit(0))
    proj = project(
        j_wh,
        [c("w_warehouse_name"),
         _case([(binop("<=", diff, lit(30)), lit(1))], lit(0)),
         bucket(30, 60), bucket(60, 90), bucket(90, 120),
         _case([(binop(">", diff, lit(120)), lit(1))], lit(0))],
        ["w_warehouse_name", "d30", "d60", "d90", "d120", "dmore"])
    out_agg = _partial_final(
        proj, [(ci(0), "w_warehouse_name")],
        [("sum", n, [ci(i + 1)]) for i, n in
         enumerate(["d30", "d60", "d90", "d120", "dmore"])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        m = cs.to_pandas().merge(wh.to_pandas(),
                                 left_on="cs_warehouse_sk",
                                 right_on="w_warehouse_sk")
        d = m.cs_ship_date_sk - m.cs_sold_date_sk
        m = m.assign(
            d30=(d <= 30).astype(int),
            d60=((d > 30) & (d <= 60)).astype(int),
            d90=((d > 60) & (d <= 90)).astype(int),
            d120=((d > 90) & (d <= 120)).astype(int),
            dmore=(d > 120).astype(int))
        out = m.groupby("w_warehouse_name", as_index=False)[
            ["d30", "d60", "d90", "d120", "dmore"]].sum()
        return out.sort_values("w_warehouse_name")[:100] \
            .reset_index(drop=True)

    return plan, oracle


QUERIES.update({
    "q34": (q34, ["store_sales", "household_demographics", "customer"]),
    "q47": (q47, ["store_sales", "item", "date_dim"]),
    "q57": (q57, ["catalog_sales", "item", "date_dim"]),
    "q68": (q68, ["store_sales", "date_dim", "store",
                  "household_demographics", "customer_address"]),
    "q79": (q79, ["store_sales", "household_demographics", "store",
                  "customer"]),
    "q88": (q88, ["store_sales", "time_dim",
                  "household_demographics"]),
    "q94": (q94, ["catalog_sales", "catalog_returns"]),
    "q99": (q99, ["catalog_sales", "warehouse"]),
})


# ---------------------------------------------------------------------------
# fourth batch: year-over-year self joins (q04/q11/q31), weekly self join
# (q59), web ship buckets (q62), warehouse month pivot (q66), rank over
# state rollup (q70), windowed deviation (q89), above-average web (q92)
# ---------------------------------------------------------------------------

def _yearly_customer_totals(paths, tables, partitions, fact, cust_col,
                            date_col, price_col, year):
    f = join("broadcast_join", scan(paths, tables, fact),
             filter_(scan(paths, tables, "date_dim"),
                     binop("==", c("d_year"), lit(year, "int32"))),
             [c(date_col)], [c("d_date_sk")])
    return _partial_final(f, [(c(cust_col), "customer_sk")],
                          [("sum", "total", [c(price_col)])], partitions)


def _yoy_growth(paths, tables, partitions, fact, cust_col, date_col,
                price_col):
    """The q04/q11 skeleton: customers whose year-2 spend grew vs year 1
    in THIS channel (the real queries compare growth across channels;
    the self-join-on-customer shape is identical)."""
    cu = tables["customer"]
    y1 = _yearly_customer_totals(paths, tables, partitions, fact,
                                 cust_col, date_col, price_col, 1999)
    y2 = _yearly_customer_totals(paths, tables, partitions, fact,
                                 cust_col, date_col, price_col, 2000)
    j = join("hash_join", exchange(y1, [ci(0)], partitions),
             exchange(y2, [ci(0)], partitions), [ci(0)], [ci(0)])
    grown = filter_(j, binop(">", ci(3), ci(1)))
    j_cu = join("hash_join", exchange(grown, [ci(0)], partitions),
                exchange(scan(paths, tables, "customer"),
                         [c("c_customer_sk")], partitions),
                [ci(0)], [c("c_customer_sk")])
    picked = project(j_cu, [c("c_customer_id"), ci(1), ci(3)],
                     ["c_customer_id", "year1_total", "year2_total"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    ft, dd = tables[fact], tables["date_dim"]

    def oracle():
        fd, ddd, cud = ft.to_pandas(), dd.to_pandas(), cu.to_pandas()

        def year_tot(y):
            m = fd.merge(ddd[ddd.d_year == y], left_on=date_col,
                         right_on="d_date_sk")
            return (m.groupby(cust_col, as_index=False)
                    .agg(total=(price_col, "sum")))

        a = year_tot(1999).rename(columns={"total": "year1_total"})
        b = year_tot(2000).rename(columns={"total": "year2_total"})
        m = a.merge(b, on=cust_col)
        m = m[m.year2_total > m.year1_total]
        m = m.merge(cud, left_on=cust_col, right_on="c_customer_sk")
        out = m[["c_customer_id", "year1_total", "year2_total"]] \
            .sort_values("c_customer_id")[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q04(paths, tables, partitions: int = 2):
    return _yoy_growth(paths, tables, partitions, "catalog_sales",
                       "cs_bill_customer_sk", "cs_sold_date_sk",
                       "cs_sales_price")


def q11(paths, tables, partitions: int = 2):
    return _yoy_growth(paths, tables, partitions, "web_sales",
                       "ws_bill_customer_sk", "ws_sold_date_sk",
                       "ws_ext_sales_price")


def q31(paths, tables, partitions: int = 2):
    """County quarter-over-quarter growth: ss by (county, quarter) self-
    joined across q1->q2, compared against the same web growth."""
    ss, ws = tables["store_sales"], tables["web_sales"]
    ca, dd, cu = (tables["customer_address"], tables["date_dim"],
                  tables["customer"])

    def county_q(fact, cust_col, date_col, price_col, qoy, name):
        f = join("broadcast_join", scan(paths, tables, fact),
                 filter_(scan(paths, tables, "date_dim"),
                         binop("==", c("d_year"), lit(2000, "int32")),
                         binop("==", c("d_qoy"), lit(qoy, "int32"))),
                 [c(date_col)], [c("d_date_sk")])
        j_cu = join("hash_join", exchange(f, [c(cust_col)], partitions),
                    exchange(scan(paths, tables, "customer"),
                             [c("c_customer_sk")], partitions),
                    [c(cust_col)], [c("c_customer_sk")])
        j_ca = join("broadcast_join", j_cu,
                    scan(paths, tables, "customer_address"),
                    [c("c_current_addr_sk")], [c("ca_address_sk")])
        return _partial_final(j_ca, [(c("ca_county"), "county")],
                              [("sum", name, [c(price_col)])],
                              partitions)

    ss1 = county_q("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                   "ss_ext_sales_price", 1, "ss1")
    ss2 = county_q("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                   "ss_ext_sales_price", 2, "ss2")
    ws1 = county_q("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                   "ws_ext_sales_price", 1, "ws1")
    ws2 = county_q("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
                   "ws_ext_sales_price", 2, "ws2")
    j = join("sort_merge_join", exchange(ss1, [ci(0)], partitions),
             exchange(ss2, [ci(0)], partitions), [ci(0)], [ci(0)])
    j = join("sort_merge_join", j,
             exchange(ws1, [ci(0)], partitions), [ci(0)], [ci(0)])
    j = join("sort_merge_join", j,
             exchange(ws2, [ci(0)], partitions), [ci(0)], [ci(0)])
    # web growth > store growth  <=>  ws2/ws1 > ss2/ss1, cross-
    # multiplied (all sums positive): ws2*ss1 > ss2*ws1
    grown = filter_(j, binop(">", binop("*", ci(7), ci(1)),
                             binop("*", ci(3), ci(5))))
    picked = project(grown, [ci(0), ci(1), ci(3), ci(5), ci(7)],
                     ["county", "ss1", "ss2", "ws1", "ws2"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        ssd, wsd = ss.to_pandas(), ws.to_pandas()
        cad, ddd, cud = (ca.to_pandas(), dd.to_pandas(),
                         cu.to_pandas())

        def cq(fd, cust_col, date_col, price_col, qoy):
            m = fd.merge(ddd[(ddd.d_year == 2000) & (ddd.d_qoy == qoy)],
                         left_on=date_col, right_on="d_date_sk")
            m = m.merge(cud, left_on=cust_col, right_on="c_customer_sk")
            m = m.merge(cad, left_on="c_current_addr_sk",
                        right_on="ca_address_sk")
            return (m.groupby("ca_county", as_index=False)
                    .agg(v=(price_col, "sum"))
                    .rename(columns={"ca_county": "county"}))

        s1 = cq(ssd, "ss_customer_sk", "ss_sold_date_sk",
                "ss_ext_sales_price", 1).rename(columns={"v": "ss1"})
        s2 = cq(ssd, "ss_customer_sk", "ss_sold_date_sk",
                "ss_ext_sales_price", 2).rename(columns={"v": "ss2"})
        w1 = cq(wsd, "ws_bill_customer_sk", "ws_sold_date_sk",
                "ws_ext_sales_price", 1).rename(columns={"v": "ws1"})
        w2 = cq(wsd, "ws_bill_customer_sk", "ws_sold_date_sk",
                "ws_ext_sales_price", 2).rename(columns={"v": "ws2"})
        m = s1.merge(s2, on="county").merge(w1, on="county") \
            .merge(w2, on="county")
        m = m[m.ws2 * m.ss1 > m.ss2 * m.ws1]
        out = m.sort_values("county")[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q59(paths, tables, partitions: int = 2):
    """Weekly store revenue: this-year vs next-year same-week self join
    (the q59 d_week_seq shape)."""
    ss, dd, st = (tables["store_sales"], tables["date_dim"],
                  tables["store"])

    def weekly(year):
        f = join("broadcast_join", scan(paths, tables, "store_sales"),
                 filter_(scan(paths, tables, "date_dim"),
                         binop("==", c("d_year"), lit(year, "int32"))),
                 [c("ss_sold_date_sk")], [c("d_date_sk")])
        j_st = join("broadcast_join", f, scan(paths, tables, "store"),
                    [c("ss_store_sk")], [c("s_store_sk")])
        # week-of-year aligns weeks ACROSS years (d_week_seq is global)
        wk = binop("%", c("d_week_seq"), lit(53))
        p = project(j_st, [c("s_store_name"), wk,
                           c("ss_ext_sales_price")],
                    ["store_name", "wk", "price"])
        return _partial_final(
            p, [(ci(0), "store_name"), (ci(1), "wk")],
            [("sum", "sales", [ci(2)])], partitions)

    a = weekly(1999)
    b = weekly(2000)
    j = join("sort_merge_join",
             exchange(a, [ci(0), ci(1)], partitions),
             exchange(b, [ci(0), ci(1)], partitions),
             [ci(0), ci(1)], [ci(0), ci(1)])
    picked = project(j, [ci(0), ci(1), ci(2), ci(5)],
                     ["store_name", "wk", "sales_y1", "sales_y2"])
    single = exchange(picked, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd, ddd, std = ss.to_pandas(), dd.to_pandas(), st.to_pandas()

        def wkly(year):
            m = ssd.merge(ddd[ddd.d_year == year],
                          left_on="ss_sold_date_sk",
                          right_on="d_date_sk")
            m = m.merge(std, left_on="ss_store_sk",
                        right_on="s_store_sk")
            m["wk"] = m.d_week_seq % 53
            return (m.groupby(["s_store_name", "wk"], as_index=False)
                    .agg(sales=("ss_ext_sales_price", "sum"))
                    .rename(columns={"s_store_name": "store_name"}))

        m = wkly(1999).merge(wkly(2000), on=["store_name", "wk"],
                             suffixes=("_y1", "_y2"))
        out = m.rename(columns={"sales_y1": "sales_y1",
                                "sales_y2": "sales_y2"})
        out = out.sort_values(["store_name", "wk"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q62(paths, tables, partitions: int = 2):
    """Web ship-latency buckets by site (q62 shape; q99's web twin over
    ws_ship_date - ws_sold_date grouped by web site)."""
    ws = tables["web_sales"]
    diff = binop("-", c("ws_ship_date_sk"), c("ws_sold_date_sk"))
    bucket = lambda lo, hi: _case(
        [(binop("and", binop(">", diff, lit(lo)),
                binop("<=", diff, lit(hi))), lit(1))], lit(0))
    proj = project(
        scan(paths, tables, "web_sales"),
        [c("ws_web_site_sk"),
         _case([(binop("<=", diff, lit(30)), lit(1))], lit(0)),
         bucket(30, 60), bucket(60, 90), bucket(90, 120),
         _case([(binop(">", diff, lit(120)), lit(1))], lit(0))],
        ["web_site_sk", "d30", "d60", "d90", "d120", "dmore"])
    out_agg = _partial_final(
        proj, [(ci(0), "web_site_sk")],
        [("sum", n, [ci(i + 1)]) for i, n in
         enumerate(["d30", "d60", "d90", "d120", "dmore"])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        m = ws.to_pandas()
        d = m.ws_ship_date_sk - m.ws_sold_date_sk
        m = m.assign(
            d30=(d <= 30).astype(int),
            d60=((d > 30) & (d <= 60)).astype(int),
            d90=((d > 60) & (d <= 90)).astype(int),
            d120=((d > 90) & (d <= 120)).astype(int),
            dmore=(d > 120).astype(int))
        out = m.groupby("ws_web_site_sk", as_index=False)[
            ["d30", "d60", "d90", "d120", "dmore"]].sum() \
            .rename(columns={"ws_web_site_sk": "web_site_sk"})
        return out.sort_values("web_site_sk")[:100] \
            .reset_index(drop=True)

    return plan, oracle


def q66(paths, tables, partitions: int = 2):
    """Warehouse monthly sales pivot (q66 shape: 12 case-when month sums
    by warehouse over web sales)."""
    ws, wh, dd = (tables["web_sales"], tables["warehouse"],
                  tables["date_dim"])
    j_dd = join("broadcast_join", scan(paths, tables, "web_sales"),
                filter_(scan(paths, tables, "date_dim"),
                        binop("==", c("d_year"), lit(1999, "int32"))),
                [c("ws_sold_date_sk")], [c("d_date_sk")])
    j_wh = join("broadcast_join", j_dd, scan(paths, tables, "warehouse"),
                [c("ws_warehouse_sk")], [c("w_warehouse_sk")])
    month_exprs = [
        _case([(binop("==", c("d_moy"), lit(m, "int32")),
                c("ws_ext_sales_price"))], lit(0.0, "float64"))
        for m in range(1, 13)]
    names = [f"m{m:02d}_sales" for m in range(1, 13)]
    proj = project(j_wh, [c("w_warehouse_name")] + month_exprs,
                   ["w_warehouse_name"] + names)
    out_agg = _partial_final(
        proj, [(ci(0), "w_warehouse_name")],
        [("sum", n, [ci(i + 1)]) for i, n in enumerate(names)],
        partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        m = ws.to_pandas().merge(
            dd.to_pandas().query("d_year == 1999"),
            left_on="ws_sold_date_sk", right_on="d_date_sk")
        m = m.merge(wh.to_pandas(), left_on="ws_warehouse_sk",
                    right_on="w_warehouse_sk")
        for mo in range(1, 13):
            m[f"m{mo:02d}_sales"] = m.ws_ext_sales_price.where(
                m.d_moy == mo, 0.0)
        out = m.groupby("w_warehouse_name", as_index=False)[
            [f"m{mo:02d}_sales" for mo in range(1, 13)]].sum()
        return out.sort_values("w_warehouse_name")[:100] \
            .reset_index(drop=True)

    return plan, oracle


def q70(paths, tables, partitions: int = 2):
    """State/county profit rollup + rank() within state (q70 shape)."""
    ss, st, dd = (tables["store_sales"], tables["store"],
                  tables["date_dim"])
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                filter_(scan(paths, tables, "date_dim"),
                        binop("==", c("d_year"), lit(2000, "int32"))),
                [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_st = join("broadcast_join", j_dd, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    nul = {"kind": "literal", "value": None, "type": {"id": "utf8"}}
    projections = []
    for kept, gid in ((2, 0), (1, 1), (0, 3)):
        projections.append(
            [c("s_state") if kept >= 1 else nul,
             c("s_store_name") if kept >= 2 else nul,
             lit(gid), c("ss_net_profit")])
    expanded = {"kind": "expand", "input": j_st,
                "projections": projections,
                "names": ["s_state", "s_store_name", "g_id",
                          "ss_net_profit"]}
    rolled = _partial_final(
        expanded,
        [(ci(0), "s_state"), (ci(1), "s_store_name"), (ci(2), "g_id")],
        [("sum", "total_profit", [ci(3)])], partitions)
    ex = exchange(rolled, [ci(0)], 1)
    srt = {"kind": "sort", "input": ex,
           "specs": [{"expr": ci(0), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(3), "descending": True,
                      "nulls_first": False}]}
    win = {"kind": "window", "input": srt,
           "functions": [{"kind": "rank", "name": "rk"}],
           "partition_by": [ci(0)],
           "order_by": [{"expr": ci(3), "descending": True,
                         "nulls_first": False}]}
    flt = filter_(win, binop("<=", ci(4), lit(5)))
    plan = sort_limit(flt, [(ci(0), False), (ci(4), False)], 100)

    def oracle():
        m = ss.to_pandas().merge(
            dd.to_pandas().query("d_year == 2000"),
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st.to_pandas(), left_on="ss_store_sk",
                    right_on="s_store_sk")
        frames = []
        for kept, gid in ((2, 0), (1, 1), (0, 3)):
            keys = ["s_state", "s_store_name"][:kept] if kept else []
            if keys:
                g = m.groupby(keys, as_index=False, dropna=False).agg(
                    total_profit=("ss_net_profit", "sum"))
            else:
                g = pd.DataFrame(
                    {"total_profit": [m.ss_net_profit.sum()]})
            for cn in ["s_state", "s_store_name"][kept:]:
                g[cn] = None
            g["g_id"] = gid
            frames.append(g[["s_state", "s_store_name", "g_id",
                             "total_profit"]])
        allf = pd.concat(frames, ignore_index=True)
        allf["rk"] = (allf.sort_values("total_profit", ascending=False)
                      .groupby("s_state", dropna=False)
                      .total_profit.rank(method="min", ascending=False))
        allf = allf[allf.rk <= 5]
        out = allf.sort_values(["s_state", "rk"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q89(paths, tables, partitions: int = 2):
    """Monthly class revenue vs the class's yearly average: window AVG
    partition + deviation filter (q89 shape)."""
    ss, it, dd = (tables["store_sales"], tables["item"],
                  tables["date_dim"])
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                filter_(scan(paths, tables, "date_dim"),
                        binop("==", c("d_year"), lit(1999, "int32"))),
                [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    rev = _partial_final(
        j_it,
        [(c("i_category"), "i_category"), (c("i_class"), "i_class"),
         (c("d_moy"), "d_moy")],
        [("sum", "sum_sales", [c("ss_sales_price")])], partitions)
    ex = exchange(rev, [ci(0)], 1)
    srt = {"kind": "sort", "input": ex,
           "specs": [{"expr": ci(0), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(1), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(2), "descending": False,
                      "nulls_first": True}]}
    win = {"kind": "window", "input": srt,
           "functions": [{"kind": "agg", "fn": "avg",
                          "name": "avg_monthly", "running": False,
                          "args": [ci(3)]}],
           "partition_by": [ci(0), ci(1)], "order_by": []}
    flt = filter_(win, binop(">", ci(3),
                             binop("*", ci(4), lit(1.1, "float64"))))
    plan = sort_limit(flt, [(ci(0), False), (ci(1), False),
                            (ci(2), False)], 100)

    def oracle():
        m = ss.to_pandas().merge(
            dd.to_pandas().query("d_year == 1999"),
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it.to_pandas(), left_on="ss_item_sk",
                    right_on="i_item_sk")
        g = (m.groupby(["i_category", "i_class", "d_moy"],
                       as_index=False)
             .agg(sum_sales=("ss_sales_price", "sum")))
        g["avg_monthly"] = g.groupby(["i_category", "i_class"]) \
            .sum_sales.transform("mean")
        g = g[g.sum_sales > 1.1 * g.avg_monthly]
        out = g.sort_values(["i_category", "i_class", "d_moy"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q92(paths, tables, partitions: int = 2):
    """Web sales above 1.3x the item's average discount: per-item avg
    subquery joined back (q92/q65-family threshold shape)."""
    ws = tables["web_sales"]
    per_item = _partial_final(
        scan(paths, tables, "web_sales"),
        [(c("ws_item_sk"), "item_sk")],
        [("avg", "avg_price", [c("ws_ext_sales_price")])], partitions)
    j = join("hash_join",
             exchange(scan(paths, tables, "web_sales"),
                      [c("ws_item_sk")], partitions),
             exchange(per_item, [ci(0)], partitions),
             [c("ws_item_sk")], [ci(0)])
    flt = filter_(j, binop(">", c("ws_ext_sales_price"),
                           binop("*", c("avg_price"),
                                 lit(1.3, "float64"))))
    total = project(flt, [c("ws_ext_sales_price")], ["p"])
    plan = _global_agg(total, [("sum", "total_excess", [ci(0)]),
                               ("count", "n_rows", [ci(0)])])

    def oracle():
        m = ws.to_pandas()
        avg = m.groupby("ws_item_sk").ws_ext_sales_price \
            .transform("mean")
        f = m[m.ws_ext_sales_price > 1.3 * avg]
        return pd.DataFrame({
            "total_excess": [f.ws_ext_sales_price.sum() if len(f)
                             else None],
            "n_rows": [len(f)]})

    return plan, oracle


QUERIES.update({
    "q04": (q04, ["catalog_sales", "date_dim", "customer"]),
    "q11": (q11, ["web_sales", "date_dim", "customer"]),
    "q31": (q31, ["store_sales", "web_sales", "customer_address",
                  "date_dim", "customer"]),
    "q59": (q59, ["store_sales", "date_dim", "store"]),
    "q62": (q62, ["web_sales"]),
    "q66": (q66, ["web_sales", "warehouse", "date_dim"]),
    "q70": (q70, ["store_sales", "store", "date_dim"]),
    "q89": (q89, ["store_sales", "item", "date_dim"]),
    "q92": (q92, ["web_sales"]),
})


# ---------------------------------------------------------------------------
# fifth batch: 3-channel manufacturer union (q33/q56/q60), zip in-list
# (q45), am/pm scalar ratio over BNLJ (q90)
# ---------------------------------------------------------------------------

def _three_channel_by_item_attr(paths, tables, partitions, attr,
                                attr_filter_vals):
    """q33/q56/q60 shape: per-channel revenue for items in a category
    selection, all three channels unioned, re-aggregated by item attr."""
    ss, cs, ws, it, dd = (tables["store_sales"], tables["catalog_sales"],
                          tables["web_sales"], tables["item"],
                          tables["date_dim"])
    it_f = filter_(scan(paths, tables, "item"),
                   {"kind": "in_list", "child": c("i_category"),
                    "values": list(attr_filter_vals),
                    "type": {"id": "utf8"}})
    legs = []
    for fact, date_col, item_col, price_col in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price")):
        j_dd = join("broadcast_join", scan(paths, tables, fact),
                    filter_(scan(paths, tables, "date_dim"),
                            binop("==", c("d_year"), lit(1999, "int32")),
                            binop("==", c("d_moy"), lit(5, "int32"))),
                    [c(date_col)], [c("d_date_sk")])
        j_it = join("broadcast_join", j_dd, it_f,
                    [c(item_col)], [c("i_item_sk")])
        leg = _partial_final(j_it, [(c(attr), "attr")],
                             [("sum", "total_sales", [c(price_col)])],
                             partitions)
        legs.append(leg)
    u = {"kind": "union", "inputs": legs}
    merged = _partial_final(u, [(ci(0), "attr")],
                            [("sum", "total_sales", [ci(1)])], partitions)
    single = exchange(merged, [ci(0)], 1)
    plan = sort_limit(single, [(ci(1), True), (ci(0), False)], 100)

    def oracle():
        itd = it.to_pandas()
        isel = itd[itd.i_category.isin(attr_filter_vals)]
        ddd = dd.to_pandas()
        dsel = ddd[(ddd.d_year == 1999) & (ddd.d_moy == 5)]
        frames = []
        for tbl, date_col, item_col, price_col in (
                (ss, "ss_sold_date_sk", "ss_item_sk",
                 "ss_ext_sales_price"),
                (cs, "cs_sold_date_sk", "cs_item_sk",
                 "cs_ext_sales_price"),
                (ws, "ws_sold_date_sk", "ws_item_sk",
                 "ws_ext_sales_price")):
            m = tbl.to_pandas().merge(dsel, left_on=date_col,
                                      right_on="d_date_sk")
            m = m.merge(isel, left_on=item_col, right_on="i_item_sk")
            frames.append(m.groupby(attr, as_index=False)
                          .agg(total_sales=(price_col, "sum")))
        allf = pd.concat(frames, ignore_index=True)
        out = (allf.groupby(attr, as_index=False).total_sales.sum()
               .rename(columns={attr: "attr"}))
        out = out.sort_values(["total_sales", "attr"],
                              ascending=[False, True])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q33(paths, tables, partitions: int = 2):
    return _three_channel_by_item_attr(paths, tables, partitions,
                                       "i_manufact_id", ["Books"])


def q56(paths, tables, partitions: int = 2):
    return _three_channel_by_item_attr(paths, tables, partitions,
                                       "i_item_id", ["Home", "Music"])


def q60(paths, tables, partitions: int = 2):
    return _three_channel_by_item_attr(paths, tables, partitions,
                                       "i_item_id", ["Sports"])


def q45(paths, tables, partitions: int = 2):
    """Web sales by customer zip, kept when the zip prefix is in a list
    OR the item is in a chosen set (the q45 disjunction)."""
    ws, cu, ca, it = (tables["web_sales"], tables["customer"],
                      tables["customer_address"], tables["item"])
    j_cu = join("hash_join",
                exchange(scan(paths, tables, "web_sales"),
                         [c("ws_bill_customer_sk")], partitions),
                exchange(scan(paths, tables, "customer"),
                         [c("c_customer_sk")], partitions),
                [c("ws_bill_customer_sk")], [c("c_customer_sk")])
    j_ca = join("broadcast_join", j_cu,
                scan(paths, tables, "customer_address"),
                [c("c_current_addr_sk")], [c("ca_address_sk")])
    j_it = join("broadcast_join", j_ca, scan(paths, tables, "item"),
                [c("ws_item_sk")], [c("i_item_sk")])
    zip2 = {"kind": "scalar_function", "name": "substring",
            "args": [c("ca_zip"), lit(1, "int32"), lit(2, "int32")],
            "return_type": {"id": "utf8"}}
    flt = filter_(j_it, binop(
        "or",
        {"kind": "in_list", "child": zip2,
         "values": ["85", "86", "88"], "type": {"id": "utf8"}},
        {"kind": "in_list", "child": c("i_item_sk"),
         "values": [2, 3, 5, 7, 11, 13, 17, 19],
         "type": {"id": "int64"}}))
    out_agg = _partial_final(
        flt, [(c("ca_zip"), "ca_zip")],
        [("sum", "total", [c("ws_ext_sales_price")])], partitions)
    single = exchange(out_agg, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        m = ws.to_pandas().merge(cu.to_pandas(),
                                 left_on="ws_bill_customer_sk",
                                 right_on="c_customer_sk")
        m = m.merge(ca.to_pandas(), left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        m = m.merge(it.to_pandas(), left_on="ws_item_sk",
                    right_on="i_item_sk")
        keep = (m.ca_zip.str[:2].isin(["85", "86", "88"]) |
                m.ws_item_sk.isin([2, 3, 5, 7, 11, 13, 17, 19]))
        f = m[keep]
        out = f.groupby("ca_zip", as_index=False).agg(
            total=("ws_ext_sales_price", "sum"))
        return out.sort_values("ca_zip")[:100].reset_index(drop=True)

    return plan, oracle


def q90(paths, tables, partitions: int = 2):
    """AM/PM sales-count ratio: two global counts combined through a
    broadcast nested-loop join (the q90 scalar-ratio shape)."""
    ss, td = tables["store_sales"], tables["time_dim"]

    def bucket_count(h_lo, h_hi, name):
        td_f = filter_(scan(paths, tables, "time_dim"),
                       binop(">=", c("t_hour"), lit(h_lo, "int32")),
                       binop("<", c("t_hour"), lit(h_hi, "int32")))
        j = join("broadcast_join", scan(paths, tables, "store_sales"),
                 td_f, [c("ss_sold_time_sk")], [c("t_time_sk")])
        return _global_agg(j, [("count", name,
                                [c("ss_ticket_number")])])

    am = bucket_count(8, 12, "amc")
    pm = bucket_count(14, 18, "pmc")
    crossed = {"kind": "broadcast_nested_loop_join",
               "left": am, "right": pm, "join_type": "inner",
               "build_side": "right"}
    ratio = project(
        crossed,
        [ci(0), ci(1),
         binop("/", {"kind": "cast", "child": ci(0),
                     "type": {"id": "float64"}},
               {"kind": "cast", "child": ci(1),
                "type": {"id": "float64"}})],
        ["am_count", "pm_count", "am_pm_ratio"])
    plan = ratio

    def oracle():
        ssd, tdd = ss.to_pandas(), td.to_pandas()
        am_n = len(ssd.merge(
            tdd[(tdd.t_hour >= 8) & (tdd.t_hour < 12)],
            left_on="ss_sold_time_sk", right_on="t_time_sk"))
        pm_n = len(ssd.merge(
            tdd[(tdd.t_hour >= 14) & (tdd.t_hour < 18)],
            left_on="ss_sold_time_sk", right_on="t_time_sk"))
        return pd.DataFrame({"am_count": [am_n], "pm_count": [pm_n],
                             "am_pm_ratio": [am_n / pm_n]})

    return plan, oracle


QUERIES.update({
    "q33": (q33, ["store_sales", "catalog_sales", "web_sales", "item",
                  "date_dim"]),
    "q45": (q45, ["web_sales", "customer", "customer_address", "item"]),
    "q56": (q56, ["store_sales", "catalog_sales", "web_sales", "item",
                  "date_dim"]),
    "q60": (q60, ["store_sales", "catalog_sales", "web_sales", "item",
                  "date_dim"]),
    "q90": (q90, ["store_sales", "time_dim"]),
})
