"""TPC-DS breadth batch 3: the 35 queries completing all 99.

Same contract as queries.py/queries_ext.py: each builder returns
(plan_dict, oracle).  Shapes follow the TPC-DS originals over the
synthetic schema subset (inventory snapshots, extended return tables);
monetary/statistical functions simplify the same way earlier batches do
(stddev -> count/avg pairs), matching dev/auron-it's role as a
shape-coverage gate rather than a benchmark kit.

Date arithmetic mirrors tpcds_data.gen_date_dim: sk = 2450815 + day,
d_year = 1998 + day//365.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from blaze_tpu.itest.queries import (D0, QUERIES, _day_range,
                                     _partial_final, agg, binop, c, ci,
                                     exchange, filter_, join, lit, project,
                                     scan, sort_limit)
from blaze_tpu.itest.queries_ext import _case, _global_agg


def _year(frame, col):
    return 1998 + (frame[col] - D0) // 365


def _top(inp: dict, specs, limit: int) -> dict:
    """Global ORDER BY + LIMIT: coalesce to ONE partition first (a
    per-partition limit would return partitions x limit rows)."""
    return sort_limit(exchange(inp, [], 1), specs, limit)


# ---------------------------------------------------------------------------
# inventory family: q21 q22 q37 q39 q72 q82
# ---------------------------------------------------------------------------

def q21(paths, tables, partitions: int = 2):
    """Inventory before/after a pivot date by warehouse+item, keeping
    items whose after/before ratio stays within [2/3, 3/2]."""
    inv, wh, it = (tables["inventory"], tables["warehouse"],
                   tables["item"])
    pivot = D0 + 400
    lo, hi = pivot - 30, pivot + 30
    base = filter_(scan(paths, tables, "inventory"),
                   binop(">=", c("inv_date_sk"), lit(lo)),
                   binop("<=", c("inv_date_sk"), lit(hi)))
    j_wh = join("broadcast_join", base, scan(paths, tables, "warehouse"),
                [c("inv_warehouse_sk")], [c("w_warehouse_sk")])
    j_it = join("broadcast_join", j_wh, scan(paths, tables, "item"),
                [c("inv_item_sk")], [c("i_item_sk")])
    before = _case([(binop("<", c("inv_date_sk"), lit(pivot)),
                     c("inv_quantity_on_hand"))], lit(0))
    after = _case([(binop(">=", c("inv_date_sk"), lit(pivot)),
                    c("inv_quantity_on_hand"))], lit(0))
    proj = project(j_it, [c("w_warehouse_name"), c("i_item_id"),
                          before, after],
                   ["w_warehouse_name", "i_item_id", "before_q",
                    "after_q"])
    sums = _partial_final(
        proj, [(ci(0), "w_warehouse_name"), (ci(1), "i_item_id")],
        [("sum", "inv_before", [ci(2)]), ("sum", "inv_after", [ci(3)])],
        partitions)
    flt = filter_(
        sums,
        binop(">", c("inv_before"), lit(0)),
        binop(">=", binop("*", c("inv_after"), lit(3)),
              binop("*", c("inv_before"), lit(2))),
        binop("<=", binop("*", c("inv_after"), lit(2)),
              binop("*", c("inv_before"), lit(3))))
    plan = _top(flt, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        d = inv.to_pandas().merge(
            wh.to_pandas(), left_on="inv_warehouse_sk",
            right_on="w_warehouse_sk").merge(
            it.to_pandas(), left_on="inv_item_sk", right_on="i_item_sk")
        d = d[(d.inv_date_sk >= lo) & (d.inv_date_sk <= hi)]
        d["before_q"] = np.where(d.inv_date_sk < pivot,
                                 d.inv_quantity_on_hand, 0)
        d["after_q"] = np.where(d.inv_date_sk >= pivot,
                                d.inv_quantity_on_hand, 0)
        g = d.groupby(["w_warehouse_name", "i_item_id"],
                      as_index=False)[["before_q", "after_q"]].sum()
        g = g[(g.before_q > 0) & (g.after_q * 3 >= g.before_q * 2)
              & (g.after_q * 2 <= g.before_q * 3)]
        g = g.sort_values(["w_warehouse_name", "i_item_id"]).head(100)
        return g.rename(columns={"before_q": "inv_before",
                                 "after_q": "inv_after"}) \
            .reset_index(drop=True)

    return plan, oracle


def q22(paths, tables, partitions: int = 2):
    """Average quantity-on-hand ROLLUP(category, brand) via Expand."""
    inv, it = tables["inventory"], tables["item"]
    lo, hi = D0 + 300, D0 + 600
    base = filter_(scan(paths, tables, "inventory"),
                   binop(">=", c("inv_date_sk"), lit(lo)),
                   binop("<=", c("inv_date_sk"), lit(hi)))
    j_it = join("broadcast_join", base, scan(paths, tables, "item"),
                [c("inv_item_sk")], [c("i_item_sk")])
    projections = []
    for gid, keep in enumerate([(True, True), (True, False),
                                (False, False)]):
        projections.append([
            c("i_category") if keep[0] else lit(None, "utf8"),
            c("i_brand") if keep[1] else lit(None, "utf8"),
            lit(gid), c("inv_quantity_on_hand")])
    expanded = {"kind": "expand", "input": j_it,
                "projections": projections,
                "names": ["i_category", "i_brand", "g_id", "qoh"]}
    stats = _partial_final(
        expanded,
        [(ci(0), "i_category"), (ci(1), "i_brand"), (ci(2), "g_id")],
        [("avg", "qoh", [ci(3)])], partitions)
    plan = _top(project(stats, [ci(0), ci(1), ci(3)],
                        ["i_category", "i_brand", "qoh"]),
                [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        d = inv.to_pandas().merge(
            it.to_pandas(), left_on="inv_item_sk", right_on="i_item_sk")
        d = d[(d.inv_date_sk >= lo) & (d.inv_date_sk <= hi)]
        outs = []
        full = d.groupby(["i_category", "i_brand"], as_index=False) \
            .inv_quantity_on_hand.mean()
        outs.append(full.rename(
            columns={"inv_quantity_on_hand": "qoh"}))
        cat = d.groupby(["i_category"], as_index=False) \
            .inv_quantity_on_hand.mean()
        cat["i_brand"] = None
        outs.append(cat.rename(columns={"inv_quantity_on_hand": "qoh"}))
        tot = pd.DataFrame({"i_category": [None], "i_brand": [None],
                            "qoh": [d.inv_quantity_on_hand.mean()]})
        outs.append(tot)
        allr = pd.concat(outs, ignore_index=True)[
            ["i_category", "i_brand", "qoh"]]
        return allr.sort_values(
            ["i_category", "i_brand"], na_position="first") \
            .head(100).reset_index(drop=True)

    return plan, oracle


def q37(paths, tables, partitions: int = 2):
    """Items in a price band with healthy on-hand inventory that also
    sold through catalog."""
    inv, it, cs = (tables["inventory"], tables["item"],
                   tables["catalog_sales"])
    it_f = filter_(scan(paths, tables, "item"),
                   binop(">=", c("i_current_price"), lit(20)),
                   binop("<=", c("i_current_price"), lit(50)))
    j_inv = join("broadcast_join", scan(paths, tables, "inventory"),
                 it_f, [c("inv_item_sk")], [c("i_item_sk")])
    inv_ok = filter_(j_inv,
                     binop(">=", c("inv_quantity_on_hand"), lit(100)),
                     binop("<=", c("inv_quantity_on_hand"), lit(500)))
    cs_ex = exchange(project(scan(paths, tables, "catalog_sales"),
                             [c("cs_item_sk")], ["cs_item_sk"]),
                     [ci(0)], partitions)
    inv_ex = exchange(project(inv_ok, [c("i_item_id"),
                                       c("i_current_price"),
                                       c("i_item_sk")],
                              ["i_item_id", "i_current_price",
                               "i_item_sk"]),
                      [ci(2)], partitions)
    semi = join("hash_join", inv_ex, cs_ex, [ci(2)], [ci(0)],
                jt="left_semi")
    dedup = _partial_final(
        semi, [(ci(0), "i_item_id"), (ci(1), "i_current_price")],
        [("count", "cnt", [ci(2)])], partitions)
    plan = _top(project(dedup, [ci(0), ci(1)],
                        ["i_item_id", "i_current_price"]),
                [(ci(0), False)], 100)

    def oracle():
        itd = it.to_pandas()
        itd = itd[(itd.i_current_price >= 20) & (itd.i_current_price <= 50)]
        d = inv.to_pandas().merge(itd, left_on="inv_item_sk",
                                  right_on="i_item_sk")
        d = d[(d.inv_quantity_on_hand >= 100)
              & (d.inv_quantity_on_hand <= 500)]
        d = d[d.i_item_sk.isin(set(cs.to_pandas().cs_item_sk))]
        g = d[["i_item_id", "i_current_price"]].drop_duplicates()
        return g.sort_values("i_item_id").head(100).reset_index(drop=True)

    return plan, oracle


def q39(paths, tables, partitions: int = 2):
    """Inventory spread by item/warehouse/month: count+avg stats for two
    consecutive months joined on (item, warehouse) — the q39 two-month
    variance pairing with stdev simplified to count/avg (as q17 does)."""
    inv = tables["inventory"]
    m1_lo, m1_hi = D0 + 365, D0 + 395
    m2_lo, m2_hi = D0 + 396, D0 + 426

    def month_stats(lo, hi):
        base = filter_(scan(paths, tables, "inventory"),
                       binop(">=", c("inv_date_sk"), lit(lo)),
                       binop("<=", c("inv_date_sk"), lit(hi)))
        return _partial_final(
            base,
            [(c("inv_item_sk"), "item_sk"),
             (c("inv_warehouse_sk"), "warehouse_sk")],
            [("count", "cnt", [c("inv_quantity_on_hand")]),
             ("avg", "mean_qoh", [c("inv_quantity_on_hand")])],
            partitions)

    m1 = exchange(month_stats(m1_lo, m1_hi), [ci(0), ci(1)], partitions)
    m2 = exchange(month_stats(m2_lo, m2_hi), [ci(0), ci(1)], partitions)
    j = join("sort_merge_join", m1, m2, [ci(0), ci(1)], [ci(0), ci(1)])
    flt = filter_(j, binop(">", ci(2), lit(1)), binop(">", ci(6), lit(1)))
    proj = project(flt, [ci(0), ci(1), ci(3), ci(7)],
                   ["item_sk", "warehouse_sk", "mean1", "mean2"])
    plan = _top(proj, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        d = inv.to_pandas()

        def stats(lo, hi):
            m = d[(d.inv_date_sk >= lo) & (d.inv_date_sk <= hi)]
            return m.groupby(["inv_item_sk", "inv_warehouse_sk"]) \
                .inv_quantity_on_hand.agg(["count", "mean"]).reset_index()

        a = stats(m1_lo, m1_hi)
        b = stats(m2_lo, m2_hi)
        m = a.merge(b, on=["inv_item_sk", "inv_warehouse_sk"])
        m = m[(m.count_x > 1) & (m.count_y > 1)]
        out = m.rename(columns={
            "inv_item_sk": "item_sk", "inv_warehouse_sk": "warehouse_sk",
            "mean_x": "mean1", "mean_y": "mean2"})[
            ["item_sk", "warehouse_sk", "mean1", "mean2"]]
        return out.sort_values(["item_sk", "warehouse_sk"]) \
            .head(100).reset_index(drop=True)

    return plan, oracle


def q72(paths, tables, partitions: int = 2):
    """Catalog demand vs inventory: orders where on-hand quantity at the
    nearest weekly snapshot falls below the ordered quantity, counted by
    item."""
    cs, inv, it = (tables["catalog_sales"], tables["inventory"],
                   tables["item"])
    lo, hi = _day_range(365, 500)
    cs_f = project(
        filter_(scan(paths, tables, "catalog_sales"),
                binop(">=", c("cs_sold_date_sk"), lit(lo)),
                binop("<=", c("cs_sold_date_sk"), lit(hi))),
        [c("cs_item_sk"), c("cs_quantity")], ["item_sk", "quantity"])
    cs_ex = exchange(cs_f, [ci(0)], partitions)
    inv_f = project(
        filter_(scan(paths, tables, "inventory"),
                binop(">=", c("inv_date_sk"), lit(lo)),
                binop("<=", c("inv_date_sk"), lit(hi))),
        [c("inv_item_sk"), c("inv_quantity_on_hand")],
        ["inv_item_sk", "qoh"])
    inv_ex = exchange(inv_f, [ci(0)], partitions)
    j = join("hash_join", cs_ex, inv_ex, [ci(0)], [ci(0)],
             flt=binop("<", ci(3), ci(1)))
    j_it = join("broadcast_join", j, scan(paths, tables, "item"),
                [ci(0)], [c("i_item_sk")])
    cnt = _partial_final(j_it, [(c("i_item_id"), "i_item_id")],
                         [("count", "low_stock_cnt", [ci(0)])],
                         partitions)
    plan = _top(cnt, [(ci(1), True), (ci(0), False)], 100)

    def oracle():
        csd = cs.to_pandas()
        csd = csd[(csd.cs_sold_date_sk >= lo) & (csd.cs_sold_date_sk <= hi)]
        invd = inv.to_pandas()
        invd = invd[(invd.inv_date_sk >= lo) & (invd.inv_date_sk <= hi)]
        m = csd.merge(invd, left_on="cs_item_sk", right_on="inv_item_sk")
        m = m[m.inv_quantity_on_hand < m.cs_quantity]
        m = m.merge(tables["item"].to_pandas(), left_on="cs_item_sk",
                    right_on="i_item_sk")
        g = m.groupby("i_item_id").size().reset_index(
            name="low_stock_cnt")
        return g.sort_values(["low_stock_cnt", "i_item_id"],
                             ascending=[False, True]).head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q82(paths, tables, partitions: int = 2):
    """q37's store twin: priced items with mid-range inventory that sold
    in store."""
    inv, it, ss = (tables["inventory"], tables["item"],
                   tables["store_sales"])
    it_f = filter_(scan(paths, tables, "item"),
                   binop(">=", c("i_current_price"), lit(30)),
                   binop("<=", c("i_current_price"), lit(60)))
    j_inv = join("broadcast_join", scan(paths, tables, "inventory"),
                 it_f, [c("inv_item_sk")], [c("i_item_sk")])
    inv_ok = filter_(j_inv,
                     binop(">=", c("inv_quantity_on_hand"), lit(100)),
                     binop("<=", c("inv_quantity_on_hand"), lit(500)))
    ss_ex = exchange(project(scan(paths, tables, "store_sales"),
                             [c("ss_item_sk")], ["ss_item_sk"]),
                     [ci(0)], partitions)
    inv_ex = exchange(project(inv_ok, [c("i_item_id"),
                                       c("i_current_price"),
                                       c("i_item_sk")],
                              ["i_item_id", "i_current_price",
                               "i_item_sk"]),
                      [ci(2)], partitions)
    semi = join("hash_join", inv_ex, ss_ex, [ci(2)], [ci(0)],
                jt="left_semi")
    dedup = _partial_final(
        semi, [(ci(0), "i_item_id"), (ci(1), "i_current_price")],
        [("count", "cnt", [ci(2)])], partitions)
    plan = _top(project(dedup, [ci(0), ci(1)],
                        ["i_item_id", "i_current_price"]),
                [(ci(0), False)], 100)

    def oracle():
        itd = it.to_pandas()
        itd = itd[(itd.i_current_price >= 30) & (itd.i_current_price <= 60)]
        d = inv.to_pandas().merge(itd, left_on="inv_item_sk",
                                  right_on="i_item_sk")
        d = d[(d.inv_quantity_on_hand >= 100)
              & (d.inv_quantity_on_hand <= 500)]
        d = d[d.i_item_sk.isin(set(ss.to_pandas().ss_item_sk))]
        g = d[["i_item_id", "i_current_price"]].drop_duplicates()
        return g.sort_values("i_item_id").head(100).reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# returns family: q16 q30 q32 q40 q41 q49 q81 q83 q85 q91
# ---------------------------------------------------------------------------

def q16(paths, tables, partitions: int = 2):
    """q94's catalog original: cross-warehouse shipped orders with no
    return — count + totals."""
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]
    lo, hi = _day_range(60, 120)
    base = project(
        filter_(scan(paths, tables, "catalog_sales"),
                binop(">=", c("cs_ship_date_sk"), lit(lo)),
                binop("<=", c("cs_ship_date_sk"), lit(hi))),
        [c("cs_order_number"), c("cs_warehouse_sk"),
         c("cs_ext_sales_price"), c("cs_net_profit")],
        ["order_number", "warehouse_sk", "price", "profit"])
    base_ex = exchange(base, [ci(0)], partitions)
    all_cs = exchange(project(scan(paths, tables, "catalog_sales"),
                              [c("cs_order_number"),
                               c("cs_warehouse_sk")], ["o2", "w2"]),
                      [ci(0)], partitions)
    semi = join("hash_join", base_ex, all_cs, [ci(0)], [ci(0)],
                jt="left_semi", flt=binop("!=", ci(1), ci(5)))
    cr_ex = exchange(project(scan(paths, tables, "catalog_returns"),
                             [c("cr_order_number")], ["cr_order_number"]),
                     [ci(0)], partitions)
    anti = join("hash_join", semi, cr_ex, [ci(0)], [ci(0)],
                jt="left_anti")
    per_order = _partial_final(
        anti, [(ci(0), "order_number")],
        [("sum", "price", [ci(2)]), ("sum", "profit", [ci(3)])],
        partitions)
    single = exchange(per_order, [ci(0)], 1)
    plan = _global_agg(single,
                       [("count", "order_count", [ci(0)]),
                        ("sum", "total_price", [ci(1)]),
                        ("sum", "total_profit", [ci(2)])])

    def oracle():
        csd, crd = cs.to_pandas(), cr.to_pandas()
        f = csd[(csd.cs_ship_date_sk >= lo) & (csd.cs_ship_date_sk <= hi)]
        wh = csd.groupby("cs_order_number").cs_warehouse_sk.agg(set)
        ok = f[f.apply(lambda r: bool(
            wh.get(r.cs_order_number, set()) - {r.cs_warehouse_sk}),
            axis=1)] if len(f) else f
        ok = ok[~ok.cs_order_number.isin(set(crd.cr_order_number))]
        return pd.DataFrame({
            "order_count": [ok.cs_order_number.nunique()],
            "total_price": [ok.cs_ext_sales_price.sum() if len(ok)
                            else None],
            "total_profit": [ok.cs_net_profit.sum() if len(ok)
                             else None]})

    return plan, oracle


def q30(paths, tables, partitions: int = 2):
    """Web-return customers whose yearly state total exceeds 1.2x the
    state average (q01's web-returns twin over wr + customer/address)."""
    wr, cu, ca = (tables["web_returns"], tables["customer"],
                  tables["customer_address"])
    lo, hi = _day_range(730, 1094)  # year 2000
    base = filter_(scan(paths, tables, "web_returns"),
                   binop(">=", c("wr_returned_date_sk"), lit(lo)),
                   binop("<=", c("wr_returned_date_sk"), lit(hi)))
    j_cu = join("broadcast_join", base, scan(paths, tables, "customer"),
                [c("wr_returning_customer_sk")], [c("c_customer_sk")])
    j_ca = join("broadcast_join", j_cu,
                scan(paths, tables, "customer_address"),
                [c("c_current_addr_sk")], [c("ca_address_sk")])
    ctr = _partial_final(
        j_ca,
        [(c("wr_returning_customer_sk"), "ctr_customer_sk"),
         (c("ca_state"), "ctr_state")],
        [("sum", "ctr_total_return", [c("wr_return_amt")])], partitions)
    avg_in = exchange(ctr, [ci(1)], partitions)
    avg_by_state = agg(
        agg(avg_in, [(ci(1), "avg_state")],
            [("avg", "partial", "avg_return", [ci(2)])]),
        [(ci(0), "avg_state")],
        [("avg", "final", "avg_return", [ci(1), ci(2)])])
    ctr2 = exchange(ctr, [ci(1)], partitions)
    joined = join("sort_merge_join", ctr2, avg_by_state, [ci(1)], [ci(0)])
    flt = filter_(joined, binop(">", c("ctr_total_return"),
                                binop("*", c("avg_return"),
                                      lit(1.2, "float64"))))
    j_id = join("broadcast_join", flt, scan(paths, tables, "customer"),
                [ci(0)], [c("c_customer_sk")])
    proj = project(j_id, [c("c_customer_id"), c("ctr_total_return")],
                   ["c_customer_id", "ctr_total_return"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        d = wr.to_pandas()
        d = d[(d.wr_returned_date_sk >= lo) & (d.wr_returned_date_sk <= hi)]
        d = d.merge(cu.to_pandas(), left_on="wr_returning_customer_sk",
                    right_on="c_customer_sk")
        d = d.merge(ca.to_pandas(), left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        g = d.groupby(["wr_returning_customer_sk", "ca_state"],
                      as_index=False).wr_return_amt.sum()
        avg = g.groupby("ca_state").wr_return_amt.mean().rename("avg")
        m = g.join(avg, on="ca_state")
        m = m[m.wr_return_amt > 1.2 * m.avg]
        m = m.merge(cu.to_pandas(), left_on="wr_returning_customer_sk",
                    right_on="c_customer_sk")
        out = m[["c_customer_id", "wr_return_amt"]].rename(
            columns={"wr_return_amt": "ctr_total_return"})
        return out.sort_values("c_customer_id").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q32(paths, tables, partitions: int = 2):
    """Excess-discount catalog sales: coupon amounts above 1.3x the
    item's average during a window (scalar-average join)."""
    cs = tables["catalog_sales"]
    lo, hi = _day_range(200, 290)
    base = filter_(scan(paths, tables, "catalog_sales"),
                   binop(">=", c("cs_sold_date_sk"), lit(lo)),
                   binop("<=", c("cs_sold_date_sk"), lit(hi)))
    avg_item = _partial_final(
        project(base, [c("cs_item_sk"), c("cs_coupon_amt")],
                ["item_sk", "coupon"]),
        [(ci(0), "item_sk")],
        [("avg", "avg_coupon", [ci(1)])], partitions)
    base2 = exchange(project(base, [c("cs_item_sk"), c("cs_coupon_amt")],
                             ["i2", "c2"]), [ci(0)], partitions)
    j = join("hash_join", base2, exchange(avg_item, [ci(0)], partitions),
             [ci(0)], [ci(0)],
             flt=binop(">", ci(1), binop("*", ci(3), lit(1.3, "float64"))))
    single = exchange(project(j, [ci(1)], ["excess"]), [], 1)
    plan = _global_agg(single, [("sum", "excess_discount", [ci(0)])])

    def oracle():
        d = cs.to_pandas()
        d = d[(d.cs_sold_date_sk >= lo) & (d.cs_sold_date_sk <= hi)]
        avg = d.groupby("cs_item_sk").cs_coupon_amt.mean().rename("avg")
        m = d.join(avg, on="cs_item_sk")
        ex = m[m.cs_coupon_amt > 1.3 * m.avg]
        return pd.DataFrame({"excess_discount":
                             [ex.cs_coupon_amt.sum() if len(ex)
                              else None]})

    return plan, oracle


def q40(paths, tables, partitions: int = 2):
    """Catalog sales value before/after a pivot date by warehouse+item,
    returns subtracted (cs left-join cr on order+item)."""
    cs, cr, wh = (tables["catalog_sales"], tables["catalog_returns"],
                  tables["warehouse"])
    pivot = D0 + 420
    lo, hi = pivot - 30, pivot + 30
    base = project(
        filter_(scan(paths, tables, "catalog_sales"),
                binop(">=", c("cs_sold_date_sk"), lit(lo)),
                binop("<=", c("cs_sold_date_sk"), lit(hi))),
        [c("cs_order_number"), c("cs_item_sk"), c("cs_warehouse_sk"),
         c("cs_sales_price"), c("cs_sold_date_sk")],
        ["order_number", "item_sk", "warehouse_sk", "price", "sold_sk"])
    base_ex = exchange(base, [ci(0), ci(1)], partitions)
    cr_ex = exchange(project(scan(paths, tables, "catalog_returns"),
                             [c("cr_order_number"), c("cr_item_sk"),
                              c("cr_return_amount")],
                             ["ro", "ri", "ramt"]),
                     [ci(0), ci(1)], partitions)
    j = join("hash_join", base_ex, cr_ex, [ci(0), ci(1)],
             [ci(0), ci(1)], jt="left")
    net = binop("-", ci(3),
                {"kind": "coalesce", "args": [ci(7), lit(0.0, "float64")]})
    before = _case([(binop("<", ci(4), lit(pivot)), net)],
                   lit(0.0, "float64"))
    after = _case([(binop(">=", ci(4), lit(pivot)), net)],
                  lit(0.0, "float64"))
    j_wh = join("broadcast_join",
                project(j, [ci(2), before, after],
                        ["warehouse_sk", "before_v", "after_v"]),
                scan(paths, tables, "warehouse"),
                [ci(0)], [c("w_warehouse_sk")])
    sums = _partial_final(
        j_wh, [(c("w_state"), "w_state")],
        [("sum", "sales_before", [ci(1)]),
         ("sum", "sales_after", [ci(2)])], partitions)
    plan = _top(sums, [(ci(0), False)], 100)

    def oracle():
        d = cs.to_pandas()
        d = d[(d.cs_sold_date_sk >= lo) & (d.cs_sold_date_sk <= hi)]
        # a multi-return order contributes once per matching return row
        # in the join; merge WITHOUT pre-aggregation to mirror that
        m = d.merge(cr.to_pandas()[["cr_order_number", "cr_item_sk",
                                    "cr_return_amount"]],
                    left_on=["cs_order_number", "cs_item_sk"],
                    right_on=["cr_order_number", "cr_item_sk"],
                    how="left")
        m["net"] = m.cs_sales_price - m.cr_return_amount.fillna(0.0)
        m["before_v"] = np.where(m.cs_sold_date_sk < pivot, m.net, 0.0)
        m["after_v"] = np.where(m.cs_sold_date_sk >= pivot, m.net, 0.0)
        m = m.merge(wh.to_pandas(), left_on="cs_warehouse_sk",
                    right_on="w_warehouse_sk")
        g = m.groupby("w_state", as_index=False)[
            ["before_v", "after_v"]].sum()
        g = g.rename(columns={"before_v": "sales_before",
                              "after_v": "sales_after"})
        return g.sort_values("w_state").head(100).reset_index(drop=True)

    return plan, oracle


def q41(paths, tables, partitions: int = 2):
    """Distinct item ids within a manufacturer band (q41's
    manufacturer-window distinct-product probe)."""
    it = tables["item"]
    base = filter_(scan(paths, tables, "item"),
                   binop(">=", c("i_manufact_id"), lit(700)),
                   binop("<=", c("i_manufact_id"), lit(740)),
                   binop("<", c("i_current_price"), lit(50)))
    dedup = _partial_final(base, [(c("i_item_id"), "i_item_id")],
                           [("count", "cnt", [c("i_item_sk")])],
                           partitions)
    plan = _top(project(dedup, [ci(0)], ["i_item_id"]),
                [(ci(0), False)], 100)

    def oracle():
        d = it.to_pandas()
        d = d[(d.i_manufact_id >= 700) & (d.i_manufact_id <= 740)
              & (d.i_current_price < 50)]
        out = pd.DataFrame({"i_item_id":
                            sorted(d.i_item_id.unique())[:100]})
        return out

    return plan, oracle


def q49(paths, tables, partitions: int = 2):
    """Worst return ratios per channel: returns/sales by order for web +
    catalog + store, unioned with channel tags, rank-limited."""
    ws, wr = tables["web_sales"], tables["web_returns"]
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]
    ss, sr = tables["store_sales"], tables["store_returns"]

    def channel(sales_tbl, ret_tbl, s_key, s_amt, r_key, r_amt, tag):
        s = _partial_final(
            project(scan(paths, tables, sales_tbl),
                    [c(s_key), c(s_amt)], ["k", "amt"]),
            [(ci(0), "k")], [("sum", "sales", [ci(1)])], partitions)
        r = _partial_final(
            project(scan(paths, tables, ret_tbl),
                    [c(r_key), c(r_amt)], ["k", "ramt"]),
            [(ci(0), "k")], [("sum", "returns", [ci(1)])], partitions)
        j = join("sort_merge_join", exchange(s, [ci(0)], partitions),
                 exchange(r, [ci(0)], partitions), [ci(0)], [ci(0)])
        ratio = binop("/", ci(3), ci(1))
        return project(j, [lit(tag, "utf8"), ci(0), ratio],
                       ["channel", "k", "ratio"])

    u = {"kind": "union", "inputs": [
        channel("web_sales", "web_returns", "ws_order_number",
                "ws_ext_sales_price", "wr_order_number", "wr_return_amt",
                "web"),
        channel("catalog_sales", "catalog_returns", "cs_order_number",
                "cs_ext_sales_price", "cr_order_number",
                "cr_return_amount", "catalog"),
        channel("store_sales", "store_returns", "ss_ticket_number",
                "ss_ext_sales_price", "sr_ticket_number",
                "sr_return_amt", "store")]}
    flt = filter_(u, binop(">", ci(2), lit(0.7, "float64")))
    cnt = _partial_final(flt, [(ci(0), "channel")],
                         [("count", "bad_orders", [ci(1)]),
                          ("avg", "avg_ratio", [ci(2)])], partitions)
    plan = _top(cnt, [(ci(0), False)], 10)

    def oracle():
        outs = []
        for sd, rd, sk, sa, rk, ra, tag in [
                (ws, wr, "ws_order_number", "ws_ext_sales_price",
                 "wr_order_number", "wr_return_amt", "web"),
                (cs, cr, "cs_order_number", "cs_ext_sales_price",
                 "cr_order_number", "cr_return_amount", "catalog"),
                (ss, sr, "ss_ticket_number", "ss_ext_sales_price",
                 "sr_ticket_number", "sr_return_amt", "store")]:
            s = sd.to_pandas().groupby(sk)[sa].sum()
            r = rd.to_pandas().groupby(rk)[ra].sum()
            m = pd.concat([s.rename("sales"), r.rename("returns")],
                          axis=1, join="inner")
            m["ratio"] = m["returns"] / m["sales"]
            bad = m[m.ratio > 0.7]
            outs.append((tag, len(bad),
                         bad.ratio.mean() if len(bad) else None))
        out = pd.DataFrame(outs, columns=["channel", "bad_orders",
                                          "avg_ratio"])
        return out.sort_values("channel").reset_index(drop=True)

    return plan, oracle


def q81(paths, tables, partitions: int = 2):
    """q30's catalog twin: catalog-return customers above 1.2x their
    state's average return."""
    cr, cu, ca = (tables["catalog_returns"], tables["customer"],
                  tables["customer_address"])
    lo, hi = _day_range(730, 1094)
    base = filter_(scan(paths, tables, "catalog_returns"),
                   binop(">=", c("cr_returned_date_sk"), lit(lo)),
                   binop("<=", c("cr_returned_date_sk"), lit(hi)))
    j_cu = join("broadcast_join", base, scan(paths, tables, "customer"),
                [c("cr_returning_customer_sk")], [c("c_customer_sk")])
    j_ca = join("broadcast_join", j_cu,
                scan(paths, tables, "customer_address"),
                [c("c_current_addr_sk")], [c("ca_address_sk")])
    ctr = _partial_final(
        j_ca,
        [(c("cr_returning_customer_sk"), "ctr_customer_sk"),
         (c("ca_state"), "ctr_state")],
        [("sum", "ctr_total_return", [c("cr_return_amount")])],
        partitions)
    avg_in = exchange(ctr, [ci(1)], partitions)
    avg_by_state = agg(
        agg(avg_in, [(ci(1), "avg_state")],
            [("avg", "partial", "avg_return", [ci(2)])]),
        [(ci(0), "avg_state")],
        [("avg", "final", "avg_return", [ci(1), ci(2)])])
    ctr2 = exchange(ctr, [ci(1)], partitions)
    joined = join("sort_merge_join", ctr2, avg_by_state, [ci(1)], [ci(0)])
    flt = filter_(joined, binop(">", c("ctr_total_return"),
                                binop("*", c("avg_return"),
                                      lit(1.2, "float64"))))
    j_id = join("broadcast_join", flt, scan(paths, tables, "customer"),
                [ci(0)], [c("c_customer_sk")])
    proj = project(j_id, [c("c_customer_id"), c("ctr_total_return")],
                   ["c_customer_id", "ctr_total_return"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        d = cr.to_pandas()
        d = d[(d.cr_returned_date_sk >= lo) & (d.cr_returned_date_sk <= hi)]
        d = d.merge(cu.to_pandas(), left_on="cr_returning_customer_sk",
                    right_on="c_customer_sk")
        d = d.merge(ca.to_pandas(), left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        g = d.groupby(["cr_returning_customer_sk", "ca_state"],
                      as_index=False).cr_return_amount.sum()
        avg = g.groupby("ca_state").cr_return_amount.mean().rename("avg")
        m = g.join(avg, on="ca_state")
        m = m[m.cr_return_amount > 1.2 * m.avg]
        m = m.merge(cu.to_pandas(), left_on="cr_returning_customer_sk",
                    right_on="c_customer_sk")
        out = m[["c_customer_id", "cr_return_amount"]].rename(
            columns={"cr_return_amount": "ctr_total_return"})
        return out.sort_values("c_customer_id").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q83(paths, tables, partitions: int = 2):
    """Return quantities equal-footing across the three channels by
    item: sr/cr/wr joined on item id."""
    sr, cr, wr, it = (tables["store_returns"], tables["catalog_returns"],
                      tables["web_returns"], tables["item"])
    lo, hi = _day_range(365, 729)

    def chan(tbl, item_col, amt_col, date_col, out):
        base = filter_(scan(paths, tables, tbl),
                       binop(">=", c(date_col), lit(lo)),
                       binop("<=", c(date_col), lit(hi)))
        j_it = join("broadcast_join", base, scan(paths, tables, "item"),
                    [c(item_col)], [c("i_item_sk")])
        return _partial_final(j_it, [(c("i_item_id"), "i_item_id")],
                              [("sum", out, [c(amt_col)])], partitions)

    s = exchange(chan("store_returns", "sr_item_sk", "sr_return_amt",
                      "sr_returned_date_sk", "s_amt"), [ci(0)],
                 partitions)
    cch = exchange(chan("catalog_returns", "cr_item_sk",
                        "cr_return_amount", "cr_returned_date_sk",
                        "c_amt"), [ci(0)], partitions)
    w = exchange(chan("web_returns", "wr_item_sk", "wr_return_amt",
                      "wr_returned_date_sk", "w_amt"), [ci(0)],
                 partitions)
    j1 = join("sort_merge_join", s, cch, [ci(0)], [ci(0)])
    j2 = join("sort_merge_join", j1, w, [ci(0)], [ci(0)])
    proj = project(j2, [ci(0), ci(1), ci(3), ci(5)],
                   ["i_item_id", "sr_amt", "cr_amt", "wr_amt"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        itd = tables["item"].to_pandas()

        def chan_df(tbl, item_col, amt_col, date_col, out):
            d = tbl.to_pandas()
            d = d[(d[date_col] >= lo) & (d[date_col] <= hi)]
            d = d.merge(itd, left_on=item_col, right_on="i_item_sk")
            return d.groupby("i_item_id")[amt_col].sum().rename(out)

        a = chan_df(sr, "sr_item_sk", "sr_return_amt",
                    "sr_returned_date_sk", "sr_amt")
        b = chan_df(cr, "cr_item_sk", "cr_return_amount",
                    "cr_returned_date_sk", "cr_amt")
        cc = chan_df(wr, "wr_item_sk", "wr_return_amt",
                     "wr_returned_date_sk", "wr_amt")
        m = pd.concat([a, b, cc], axis=1, join="inner").reset_index()
        return m.sort_values("i_item_id").head(100).reset_index(drop=True)

    return plan, oracle


def q85(paths, tables, partitions: int = 2):
    """Web returns by reason with quantity/amount averages (reason ⨝
    wr, the q85 reason-breakdown shape)."""
    wr, rs = tables["web_returns"], tables["reason"]
    j = join("broadcast_join", scan(paths, tables, "web_returns"),
             scan(paths, tables, "reason"),
             [c("wr_reason_sk")], [c("r_reason_sk")])
    stats = _partial_final(
        j, [(c("r_reason_desc"), "r_reason_desc")],
        [("count", "cnt", [c("wr_order_number")]),
         ("avg", "avg_amt", [c("wr_return_amt")]),
         ("avg", "avg_loss", [c("wr_net_loss")])], partitions)
    plan = _top(stats, [(ci(0), False)], 100)

    def oracle():
        d = wr.to_pandas().merge(rs.to_pandas(),
                                 left_on="wr_reason_sk",
                                 right_on="r_reason_sk")
        g = d.groupby("r_reason_desc").agg(
            cnt=("wr_order_number", "count"),
            avg_amt=("wr_return_amt", "mean"),
            avg_loss=("wr_net_loss", "mean")).reset_index()
        return g.sort_values("r_reason_desc").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q91(paths, tables, partitions: int = 2):
    """Call-center catalog returns by month: cr grouped by call center
    and return month."""
    cr = tables["catalog_returns"]
    lo, hi = _day_range(365, 729)
    base = filter_(scan(paths, tables, "catalog_returns"),
                   binop(">=", c("cr_returned_date_sk"), lit(lo)),
                   binop("<=", c("cr_returned_date_sk"), lit(hi)))
    j_dd = join("broadcast_join", base, scan(paths, tables, "date_dim"),
                [c("cr_returned_date_sk")], [c("d_date_sk")])
    sums = _partial_final(
        j_dd,
        [(c("cr_call_center_sk"), "call_center"), (c("d_moy"), "moy")],
        [("sum", "returns_loss", [c("cr_net_loss")])], partitions)
    plan = _top(sums, [(ci(2), True), (ci(0), False), (ci(1), False)],
                100)

    def oracle():
        d = cr.to_pandas()
        d = d[(d.cr_returned_date_sk >= lo) & (d.cr_returned_date_sk <= hi)]
        dd = tables["date_dim"].to_pandas()
        d = d.merge(dd, left_on="cr_returned_date_sk",
                    right_on="d_date_sk")
        g = d.groupby(["cr_call_center_sk", "d_moy"], as_index=False) \
            .cr_net_loss.sum()
        g = g.rename(columns={"cr_call_center_sk": "call_center",
                              "d_moy": "moy",
                              "cr_net_loss": "returns_loss"})
        return g.sort_values(["returns_loss", "call_center", "moy"],
                             ascending=[False, True, True]).head(100) \
            .reset_index(drop=True)

    return plan, oracle


QUERIES.update({
    "q16": (q16, ["catalog_sales", "catalog_returns"]),
    "q21": (q21, ["inventory", "warehouse", "item"]),
    "q22": (q22, ["inventory", "item"]),
    "q30": (q30, ["web_returns", "customer", "customer_address"]),
    "q32": (q32, ["catalog_sales"]),
    "q37": (q37, ["inventory", "item", "catalog_sales"]),
    "q39": (q39, ["inventory"]),
    "q40": (q40, ["catalog_sales", "catalog_returns", "warehouse"]),
    "q41": (q41, ["item"]),
    "q49": (q49, ["web_sales", "web_returns", "catalog_sales",
                  "catalog_returns", "store_sales", "store_returns"]),
    "q72": (q72, ["catalog_sales", "inventory", "item"]),
    "q81": (q81, ["catalog_returns", "customer", "customer_address"]),
    "q82": (q82, ["inventory", "item", "store_sales"]),
    "q83": (q83, ["store_returns", "catalog_returns", "web_returns",
                  "item"]),
    "q85": (q85, ["web_returns", "reason"]),
    "q91": (q91, ["catalog_returns", "date_dim"]),
})


# ---------------------------------------------------------------------------
# channel/ratio family: q02 q05 q08 q09 q44 q53 q54 q58 q61 q63 q71 q74
#                       q75 q76 q77 q78 q80 q84 q86
# ---------------------------------------------------------------------------

def q02(paths, tables, partitions: int = 2):
    """Web+catalog weekly revenue per day-of-week, adjacent-year ratio
    (join on week_seq vs week_seq+53)."""
    ws, cs, dd = (tables["web_sales"], tables["catalog_sales"],
                  tables["date_dim"])

    def weekly(year):
        dd_f = filter_(scan(paths, tables, "date_dim"),
                       binop("==", c("d_year"), lit(year, "int32")))
        w = join("broadcast_join",
                 project(scan(paths, tables, "web_sales"),
                         [c("ws_sold_date_sk"), c("ws_ext_sales_price")],
                         ["date_sk", "price"]),
                 dd_f, [ci(0)], [c("d_date_sk")])
        cch = join("broadcast_join",
                   project(scan(paths, tables, "catalog_sales"),
                           [c("cs_sold_date_sk"),
                            c("cs_ext_sales_price")],
                           ["date_sk", "price"]),
                   dd_f, [ci(0)], [c("d_date_sk")])
        wk64 = {"kind": "cast", "child": c("d_week_seq"),
                "type": {"id": "int64"}}  # both year legs hash the SAME
        #         width: int32 vs int64 keys murmur to different
        #         partitions (Spark inserts this cast too)
        u = {"kind": "union", "inputs": [
            project(w, [wk64, ci(1)], ["week_seq", "price"]),
            project(cch, [wk64, ci(1)], ["week_seq", "price"])]}
        return _partial_final(u, [(ci(0), "week_seq")],
                              [("sum", "rev", [ci(1)])], partitions)

    y1 = exchange(weekly(1999), [ci(0)], partitions)
    y2 = project(weekly(2000), [binop("-", ci(0), lit(53)), ci(1)],
                 ["week_seq_m53", "rev2"])
    j = join("sort_merge_join", y1, exchange(y2, [ci(0)], partitions),
             [ci(0)], [ci(0)])
    ratio = project(j, [ci(0), binop("/", ci(3), ci(1))],
                    ["week_seq", "ratio"])
    plan = _top(ratio, [(ci(0), False)], 100)

    def oracle():
        ddd = dd.to_pandas()

        def weekly_df(year):
            d = ddd[ddd.d_year == year]
            w = ws.to_pandas().merge(d, left_on="ws_sold_date_sk",
                                     right_on="d_date_sk")[
                ["d_week_seq", "ws_ext_sales_price"]].rename(
                columns={"ws_ext_sales_price": "price"})
            cc = cs.to_pandas().merge(d, left_on="cs_sold_date_sk",
                                      right_on="d_date_sk")[
                ["d_week_seq", "cs_ext_sales_price"]].rename(
                columns={"cs_ext_sales_price": "price"})
            u = pd.concat([w, cc], ignore_index=True)
            return u.groupby("d_week_seq").price.sum()

        a, b = weekly_df(1999), weekly_df(2000)
        b.index = b.index - 53
        m = pd.concat([a.rename("rev"), b.rename("rev2")], axis=1,
                      join="inner")
        m["ratio"] = m.rev2 / m.rev
        out = m.reset_index().rename(columns={"d_week_seq": "week_seq"})[
            ["week_seq", "ratio"]]
        return out.sort_values("week_seq").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q05(paths, tables, partitions: int = 2):
    """Per-channel sales vs returns vs net profit/loss summary."""
    ss, sr = tables["store_sales"], tables["store_returns"]
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]
    ws, wr = tables["web_sales"], tables["web_returns"]

    def leg(sales_tbl, s_amt, s_profit, ret_tbl, r_amt, r_loss, tag):
        s = project(scan(paths, tables, sales_tbl),
                    [lit(tag, "utf8"), c(s_amt), c(s_profit),
                     lit(0.0, "float64"), lit(0.0, "float64")],
                    ["channel", "sales", "profit", "returns", "loss"])
        r = project(scan(paths, tables, ret_tbl),
                    [lit(tag, "utf8"), lit(0.0, "float64"),
                     lit(0.0, "float64"), c(r_amt), c(r_loss)],
                    ["channel", "sales", "profit", "returns", "loss"])
        return [s, r]

    legs = (leg("store_sales", "ss_ext_sales_price", "ss_net_profit",
                "store_returns", "sr_return_amt", "sr_net_loss",
                "store channel") +
            leg("catalog_sales", "cs_ext_sales_price", "cs_net_profit",
                "catalog_returns", "cr_return_amount", "cr_net_loss",
                "catalog channel") +
            leg("web_sales", "ws_ext_sales_price", "ws_net_profit",
                "web_returns", "wr_return_amt", "wr_net_loss",
                "web channel"))
    u = {"kind": "union", "inputs": legs}
    sums = _partial_final(
        u, [(ci(0), "channel")],
        [("sum", "sales", [ci(1)]), ("sum", "returns", [ci(3)]),
         ("sum", "profit", [ci(2)]), ("sum", "loss", [ci(4)])],
        partitions)
    plan = _top(sums, [(ci(0), False)], 10)

    def oracle():
        rows = []
        for tag, sd, sa, sp, rd, ra, rl in [
                ("store channel", ss, "ss_ext_sales_price",
                 "ss_net_profit", sr, "sr_return_amt", "sr_net_loss"),
                ("catalog channel", cs, "cs_ext_sales_price",
                 "cs_net_profit", cr, "cr_return_amount", "cr_net_loss"),
                ("web channel", ws, "ws_ext_sales_price",
                 "ws_net_profit", wr, "wr_return_amt", "wr_net_loss")]:
            sdf, rdf = sd.to_pandas(), rd.to_pandas()
            rows.append((tag, sdf[sa].sum(), rdf[ra].sum(),
                         sdf[sp].sum(), rdf[rl].sum()))
        out = pd.DataFrame(rows, columns=["channel", "sales", "returns",
                                          "profit", "loss"])
        return out.sort_values("channel").reset_index(drop=True)

    return plan, oracle


def q08(paths, tables, partitions: int = 2):
    """Store sales for customers whose zip prefix matches the store's
    short list (q08's zip-prefix semi join, simplified to a customer
    address prefix filter)."""
    ss, st, cu, ca = (tables["store_sales"], tables["store"],
                      tables["customer"], tables["customer_address"])
    ca_f = filter_(scan(paths, tables, "customer_address"),
                   binop("<", c("ca_zip"), lit("20000", "utf8")))
    j_cu = join("broadcast_join", scan(paths, tables, "customer"),
                ca_f, [c("c_current_addr_sk")], [c("ca_address_sk")])
    cu_ex = exchange(project(j_cu, [c("c_customer_sk")], ["cust_sk"]),
                     [ci(0)], partitions)
    ss_ex = exchange(project(scan(paths, tables, "store_sales"),
                             [c("ss_customer_sk"), c("ss_store_sk"),
                              c("ss_net_profit")],
                             ["cust", "store_sk", "profit"]),
                     [ci(0)], partitions)
    semi = join("hash_join", ss_ex, cu_ex, [ci(0)], [ci(0)],
                jt="left_semi")
    j_st = join("broadcast_join", semi, scan(paths, tables, "store"),
                [ci(1)], [c("s_store_sk")])
    sums = _partial_final(j_st, [(c("s_store_name"), "s_store_name")],
                          [("sum", "net_profit", [ci(2)])], partitions)
    plan = _top(sums, [(ci(0), False)], 100)

    def oracle():
        cad = ca.to_pandas()
        ok_addr = set(cad[cad.ca_zip < "20000"].ca_address_sk)
        cud = cu.to_pandas()
        ok_cust = set(cud[cud.c_current_addr_sk.isin(ok_addr)]
                      .c_customer_sk)
        d = ss.to_pandas()
        d = d[d.ss_customer_sk.isin(ok_cust)]
        d = d.merge(st.to_pandas(), left_on="ss_store_sk",
                    right_on="s_store_sk")
        g = d.groupby("s_store_name", as_index=False).ss_net_profit.sum()
        g = g.rename(columns={"ss_net_profit": "net_profit"})
        return g.sort_values("s_store_name").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q09(paths, tables, partitions: int = 2):
    """Five quantity-band conditional aggregates over store_sales in one
    pass (the q09 case-bucket probe)."""
    ss = tables["store_sales"]
    bands = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    exprs = []
    names = []
    for i, (lo, hi) in enumerate(bands):
        inband = binop("and",
                       binop(">=", c("ss_quantity"), lit(lo, "int32")),
                       binop("<=", c("ss_quantity"), lit(hi, "int32")))
        exprs.append(_case([(inband, lit(1))], lit(0)))
        names.append(f"cnt_{i}")
        exprs.append(_case([(inband, c("ss_ext_sales_price"))],
                           lit(0.0, "float64")))
        names.append(f"amt_{i}")
    proj = project(scan(paths, tables, "store_sales"), exprs, names)
    single = exchange(proj, [], 1)
    plan = _global_agg(single,
                       [("sum", n, [ci(i)])
                        for i, n in enumerate(names)])

    def oracle():
        d = ss.to_pandas()
        vals = {}
        for i, (lo, hi) in enumerate(bands):
            m = d[(d.ss_quantity >= lo) & (d.ss_quantity <= hi)]
            vals[f"cnt_{i}"] = [len(m)]
            vals[f"amt_{i}"] = [m.ss_ext_sales_price.sum()]
        return pd.DataFrame(vals)

    return plan, oracle


def q44(paths, tables, partitions: int = 2):
    """Best and worst items by average net profit: two rank windows
    (asc + desc) joined on rank (the q44 ascender/descender pairing)."""
    ss, it = tables["store_sales"], tables["item"]
    avg_item = _partial_final(
        project(scan(paths, tables, "store_sales"),
                [c("ss_item_sk"), c("ss_net_profit")],
                ["item_sk", "profit"]),
        [(ci(0), "item_sk")], [("avg", "avg_profit", [ci(1)])],
        partitions)
    ex = exchange(avg_item, [], 1)

    def ranked(desc):
        srt = {"kind": "sort", "input": ex,
               "specs": [{"expr": ci(1), "descending": desc,
                          "nulls_first": not desc},
                         {"expr": ci(0), "descending": False,
                          "nulls_first": True}]}
        win = {"kind": "window", "input": srt,
               "functions": [{"kind": "row_number", "name": "rnk"}],
               "partition_by": [],
               "order_by": [{"expr": ci(1), "descending": desc,
                             "nulls_first": not desc}]}
        return filter_(win, binop("<=", ci(2), lit(10, "int32")))

    best = ranked(True)
    worst = ranked(False)
    j = join("broadcast_join", best, worst, [ci(2)], [ci(2)])
    j_it1 = join("broadcast_join", j, scan(paths, tables, "item"),
                 [ci(0)], [c("i_item_sk")])
    j_it2 = join("broadcast_join", j_it1, scan(paths, tables, "item"),
                 [ci(3)], [c("i_item_sk")])
    nb = len(["item_sk", "avg_profit", "rnk"]) * 2
    it_w = len(it.schema.names)
    proj = project(j_it2,
                   [ci(2), ci(nb + 1), ci(nb + it_w + 1)],
                   ["rnk", "best_item_id", "worst_item_id"])
    plan = _top(proj, [(ci(0), False)], 10)

    def oracle():
        d = ss.to_pandas().groupby("ss_item_sk", as_index=False) \
            .ss_net_profit.mean()
        d = d.sort_values(["ss_net_profit", "ss_item_sk"],
                          ascending=[False, True]).reset_index(drop=True)
        best = d.head(10).copy()
        best["rnk"] = np.arange(1, len(best) + 1)
        d2 = d.sort_values(["ss_net_profit", "ss_item_sk"],
                           ascending=[True, True]).reset_index(drop=True)
        worst = d2.head(10).copy()
        worst["rnk"] = np.arange(1, len(worst) + 1)
        itd = it.to_pandas()
        m = best.merge(worst, on="rnk")
        m = m.merge(itd, left_on="ss_item_sk_x", right_on="i_item_sk")
        m = m.merge(itd, left_on="ss_item_sk_y", right_on="i_item_sk",
                    suffixes=("", "_w"))
        out = m[["rnk", "i_item_id", "i_item_id_w"]].rename(
            columns={"i_item_id": "best_item_id",
                     "i_item_id_w": "worst_item_id"})
        return out.sort_values("rnk").reset_index(drop=True)

    return plan, oracle


def _quarterly_window(paths, tables, partitions, group_col, out_name):
    """q53/q63 shape: quarterly item-group revenue vs the group's
    all-quarter average (sum > 1.1x avg)."""
    ss, it, dd = (tables["store_sales"], tables["item"],
                  tables["date_dim"])
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                scan(paths, tables, "date_dim"),
                [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    rev = _partial_final(
        j_it,
        [(c(group_col), out_name), (c("d_year"), "year"),
         (c("d_qoy"), "qoy")],
        [("sum", "sum_sales", [c("ss_sales_price")])], partitions)
    ex = exchange(rev, [], 1)
    srt = {"kind": "sort", "input": ex,
           "specs": [{"expr": ci(0), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(1), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(2), "descending": False,
                      "nulls_first": True}]}
    # whole-partition frame = window agg with NO order_by (the wire
    # has no frame spec; Spark expresses the same thing the same way)
    win = {"kind": "window", "input": srt,
           "functions": [{"kind": "agg", "name": "avg_quarterly",
                          "fn": "avg", "args": [ci(3)]}],
           "partition_by": [ci(0)],
           "order_by": []}
    flt = filter_(win, binop(">", ci(3),
                             binop("*", ci(4), lit(1.1, "float64"))))
    proj = project(flt, [ci(0), ci(1), ci(2), ci(3)],
                   [out_name, "year", "qoy", "sum_sales"])
    plan = _top(proj, [(ci(0), False), (ci(1), False), (ci(2), False)],
                100)

    def oracle():
        m = ss.to_pandas().merge(dd.to_pandas(),
                                 left_on="ss_sold_date_sk",
                                 right_on="d_date_sk")
        m = m.merge(it.to_pandas(), left_on="ss_item_sk",
                    right_on="i_item_sk")
        g = m.groupby([group_col, "d_year", "d_qoy"], as_index=False) \
            .ss_sales_price.sum()
        avg = g.groupby(group_col).ss_sales_price.mean().rename("avg")
        g = g.join(avg, on=group_col)
        g = g[g.ss_sales_price > 1.1 * g.avg]
        out = g.rename(columns={group_col: out_name, "d_year": "year",
                                "d_qoy": "qoy",
                                "ss_sales_price": "sum_sales"})[
            [out_name, "year", "qoy", "sum_sales"]]
        return out.sort_values([out_name, "year", "qoy"]).head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q53(paths, tables, partitions: int = 2):
    return _quarterly_window(paths, tables, partitions, "i_manufact_id",
                             "manufact_id")


def q63(paths, tables, partitions: int = 2):
    return _quarterly_window(paths, tables, partitions, "i_manager_id",
                             "manager_id")


def q54(paths, tables, partitions: int = 2):
    """Revenue-band customer segmentation: customers active in a month
    bucketed by 50-unit total-revenue bands, counted per band."""
    ss, cu = tables["store_sales"], tables["customer"]
    lo, hi = _day_range(730, 760)
    active = filter_(scan(paths, tables, "store_sales"),
                     binop(">=", c("ss_sold_date_sk"), lit(lo)),
                     binop("<=", c("ss_sold_date_sk"), lit(hi)))
    totals = _partial_final(
        project(active, [c("ss_customer_sk"), c("ss_ext_sales_price")],
                ["cust", "price"]),
        [(ci(0), "cust")], [("sum", "revenue", [ci(1)])], partitions)
    band = {"kind": "cast",
            "child": binop("/", ci(1), lit(50.0, "float64")),
            "type": {"id": "int64"}}
    counts = _partial_final(
        project(totals, [band], ["segment"]),
        [(ci(0), "segment")], [("count", "num_customers", [ci(0)])],
        partitions)
    plan = _top(counts, [(ci(0), False)], 100)

    def oracle():
        d = ss.to_pandas()
        d = d[(d.ss_sold_date_sk >= lo) & (d.ss_sold_date_sk <= hi)]
        g = d.groupby("ss_customer_sk").ss_ext_sales_price.sum()
        seg = (g / 50.0).astype(np.int64)
        out = seg.value_counts().sort_index().reset_index()
        out.columns = ["segment", "num_customers"]
        return out.sort_values("segment").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q58(paths, tables, partitions: int = 2):
    """Items whose revenue is within 10% of the three-channel average in
    every channel."""
    ss, cs, ws, it = (tables["store_sales"], tables["catalog_sales"],
                      tables["web_sales"], tables["item"])
    lo, hi = _day_range(365, 455)

    def chan(tbl, date_col, item_col, amt_col, out):
        base = filter_(scan(paths, tables, tbl),
                       binop(">=", c(date_col), lit(lo)),
                       binop("<=", c(date_col), lit(hi)))
        j_it = join("broadcast_join", base, scan(paths, tables, "item"),
                    [c(item_col)], [c("i_item_sk")])
        return _partial_final(j_it, [(c("i_item_id"), "i_item_id")],
                              [("sum", out, [c(amt_col)])], partitions)

    s = exchange(chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
                      "ss_ext_sales_price", "ss_rev"), [ci(0)],
                 partitions)
    cc = exchange(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                       "cs_ext_sales_price", "cs_rev"), [ci(0)],
                  partitions)
    w = exchange(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                      "ws_ext_sales_price", "ws_rev"), [ci(0)],
                 partitions)
    j1 = join("sort_merge_join", s, cc, [ci(0)], [ci(0)])
    j2 = join("sort_merge_join", j1, w, [ci(0)], [ci(0)])
    avg = binop("/", binop("+", binop("+", ci(1), ci(3)), ci(5)),
                lit(3.0, "float64"))
    proj = project(j2, [ci(0), ci(1), ci(3), ci(5), avg],
                   ["i_item_id", "ss_rev", "cs_rev", "ws_rev", "avg_rev"])
    within = lambda col: binop(
        "and",
        binop(">=", col, binop("*", ci(4), lit(0.9, "float64"))),
        binop("<=", col, binop("*", ci(4), lit(1.1, "float64"))))
    flt = filter_(proj, within(ci(1)), within(ci(2)), within(ci(3)))
    plan = _top(flt, [(ci(0), False)], 100)

    def oracle():
        itd = it.to_pandas()

        def chan_df(tbl, date_col, item_col, amt_col, out):
            d = tbl.to_pandas()
            d = d[(d[date_col] >= lo) & (d[date_col] <= hi)]
            d = d.merge(itd, left_on=item_col, right_on="i_item_sk")
            return d.groupby("i_item_id")[amt_col].sum().rename(out)

        a = chan_df(ss, "ss_sold_date_sk", "ss_item_sk",
                    "ss_ext_sales_price", "ss_rev")
        b = chan_df(cs, "cs_sold_date_sk", "cs_item_sk",
                    "cs_ext_sales_price", "cs_rev")
        cc2 = chan_df(ws, "ws_sold_date_sk", "ws_item_sk",
                      "ws_ext_sales_price", "ws_rev")
        m = pd.concat([a, b, cc2], axis=1, join="inner").reset_index()
        m["avg_rev"] = (m.ss_rev + m.cs_rev + m.ws_rev) / 3.0
        for col in ("ss_rev", "cs_rev", "ws_rev"):
            m = m[(m[col] >= 0.9 * m.avg_rev) & (m[col] <= 1.1 * m.avg_rev)]
        return m.sort_values("i_item_id").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q61(paths, tables, partitions: int = 2):
    """Promotional vs total store revenue ratio (one-row output)."""
    ss, pr = tables["store_sales"], tables["promotion"]
    lo, hi = _day_range(400, 430)
    base = filter_(scan(paths, tables, "store_sales"),
                   binop(">=", c("ss_sold_date_sk"), lit(lo)),
                   binop("<=", c("ss_sold_date_sk"), lit(hi)))
    pr_f = filter_(scan(paths, tables, "promotion"),
                   binop("==", c("p_channel_email"), lit("Y", "utf8")))
    promo = join("broadcast_join", base, pr_f,
                 [c("ss_promo_sk")], [c("p_promo_sk")])
    promo_sum = _global_agg(
        exchange(project(promo, [c("ss_ext_sales_price")], ["p"]),
                 [], 1),
        [("sum", "promotions", [ci(0)])])
    total_sum = _global_agg(
        exchange(project(base, [c("ss_ext_sales_price")], ["t"]),
                 [], 1),
        [("sum", "total", [ci(0)])])
    j = {"kind": "broadcast_nested_loop_join", "join_type": "inner",
         "left": promo_sum, "right": total_sum, "build_side": "right"}
    plan = project(j, [ci(0), ci(1),
                       binop("/", binop("*", ci(0),
                                        lit(100.0, "float64")), ci(1))],
                   ["promotions", "total", "promo_pct"])

    def oracle():
        d = ss.to_pandas()
        d = d[(d.ss_sold_date_sk >= lo) & (d.ss_sold_date_sk <= hi)]
        prd = pr.to_pandas()
        ok = set(prd[prd.p_channel_email == "Y"].p_promo_sk)
        p = d[d.ss_promo_sk.isin(ok)].ss_ext_sales_price.sum()
        t = d.ss_ext_sales_price.sum()
        return pd.DataFrame({"promotions": [p], "total": [t],
                             "promo_pct": [p * 100.0 / t]})

    return plan, oracle


def q71(paths, tables, partitions: int = 2):
    """Brand revenue by hour across the three channels in one union
    (q71's time-of-day brand breakdown, ext_price by brand+hour)."""
    ss, cs, ws = (tables["store_sales"], tables["catalog_sales"],
                  tables["web_sales"])
    it, td = tables["item"], tables["time_dim"]

    legs = []
    # only store_sales carries a time key in the synthetic schema; the
    # union shape keeps all three channels with web/catalog at hour -1
    s_leg = join("broadcast_join",
                 project(scan(paths, tables, "store_sales"),
                         [c("ss_item_sk"), c("ss_ext_sales_price"),
                          c("ss_sold_time_sk")],
                         ["item_sk", "price", "time_sk"]),
                 scan(paths, tables, "time_dim"),
                 [ci(2)], [c("t_time_sk")])
    legs.append(project(s_leg, [ci(0), ci(1), c("t_hour")],
                        ["item_sk", "price", "hour"]))
    legs.append(project(scan(paths, tables, "catalog_sales"),
                        [c("cs_item_sk"), c("cs_ext_sales_price"),
                         lit(-1, "int32")],
                        ["item_sk", "price", "hour"]))
    legs.append(project(scan(paths, tables, "web_sales"),
                        [c("ws_item_sk"), c("ws_ext_sales_price"),
                         lit(-1, "int32")],
                        ["item_sk", "price", "hour"]))
    u = {"kind": "union", "inputs": legs}
    j_it = join("broadcast_join", u, scan(paths, tables, "item"),
                [ci(0)], [c("i_item_sk")])
    rev = _partial_final(
        j_it, [(c("i_brand_id"), "brand_id"), (ci(2), "hour")],
        [("sum", "ext_price", [ci(1)])], partitions)
    plan = _top(rev, [(ci(2), True), (ci(0), False), (ci(1), False)],
                100)

    def oracle():
        itd = it.to_pandas()
        tdd = td.to_pandas()
        s = ss.to_pandas().merge(tdd, left_on="ss_sold_time_sk",
                                 right_on="t_time_sk")
        s = s[["ss_item_sk", "ss_ext_sales_price", "t_hour"]]
        s.columns = ["item_sk", "price", "hour"]
        cc = cs.to_pandas()[["cs_item_sk", "cs_ext_sales_price"]].copy()
        cc["hour"] = -1
        cc.columns = ["item_sk", "price", "hour"]
        w = ws.to_pandas()[["ws_item_sk", "ws_ext_sales_price"]].copy()
        w["hour"] = -1
        w.columns = ["item_sk", "price", "hour"]
        u2 = pd.concat([s, cc, w], ignore_index=True)
        u2 = u2.merge(itd, left_on="item_sk", right_on="i_item_sk")
        g = u2.groupby(["i_brand_id", "hour"], as_index=False) \
            .price.sum()
        g = g.rename(columns={"i_brand_id": "brand_id",
                              "price": "ext_price"})
        return g.sort_values(["ext_price", "brand_id", "hour"],
                             ascending=[False, True, True]).head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q74(paths, tables, partitions: int = 2):
    """Year-over-year customer spend growth, web faster than store
    (q74 = q11 over AVG instead of SUM)."""
    ss, ws, cu = (tables["store_sales"], tables["web_sales"],
                  tables["customer"])
    y1_lo, y1_hi = _day_range(365, 729)
    y2_lo, y2_hi = _day_range(730, 1094)

    def totals(tbl, date_col, cust_col, amt_col, lo, hi, out):
        base = filter_(scan(paths, tables, tbl),
                       binop(">=", c(date_col), lit(lo)),
                       binop("<=", c(date_col), lit(hi)))
        return _partial_final(
            project(base, [c(cust_col), c(amt_col)], ["cust", "amt"]),
            [(ci(0), "cust")], [("avg", out, [ci(1)])], partitions)

    s1 = exchange(totals("store_sales", "ss_sold_date_sk",
                         "ss_customer_sk", "ss_ext_sales_price",
                         y1_lo, y1_hi, "s1"), [ci(0)], partitions)
    s2 = exchange(totals("store_sales", "ss_sold_date_sk",
                         "ss_customer_sk", "ss_ext_sales_price",
                         y2_lo, y2_hi, "s2"), [ci(0)], partitions)
    w1 = exchange(totals("web_sales", "ws_sold_date_sk",
                         "ws_bill_customer_sk", "ws_ext_sales_price",
                         y1_lo, y1_hi, "w1"), [ci(0)], partitions)
    w2 = exchange(totals("web_sales", "ws_sold_date_sk",
                         "ws_bill_customer_sk", "ws_ext_sales_price",
                         y2_lo, y2_hi, "w2"), [ci(0)], partitions)
    j = join("sort_merge_join",
             join("sort_merge_join",
                  join("sort_merge_join", s1, s2, [ci(0)], [ci(0)]),
                  w1, [ci(0)], [ci(0)]),
             w2, [ci(0)], [ci(0)])
    flt = filter_(j,
                  binop(">", ci(1), lit(0.0, "float64")),
                  binop(">", ci(5), lit(0.0, "float64")),
                  binop(">", binop("/", ci(7), ci(5)),
                        binop("/", ci(3), ci(1))))
    j_cu = join("broadcast_join", flt, scan(paths, tables, "customer"),
                [ci(0)], [c("c_customer_sk")])
    proj = project(j_cu, [c("c_customer_id")], ["customer_id"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        ssd, wsd = ss.to_pandas(), ws.to_pandas()

        def tot(df, dc, cc2, ac, lo, hi):
            d = df[(df[dc] >= lo) & (df[dc] <= hi)]
            return d.groupby(cc2)[ac].mean()

        s1d = tot(ssd, "ss_sold_date_sk", "ss_customer_sk",
                  "ss_ext_sales_price", y1_lo, y1_hi)
        s2d = tot(ssd, "ss_sold_date_sk", "ss_customer_sk",
                  "ss_ext_sales_price", y2_lo, y2_hi)
        w1d = tot(wsd, "ws_sold_date_sk", "ws_bill_customer_sk",
                  "ws_ext_sales_price", y1_lo, y1_hi)
        w2d = tot(wsd, "ws_sold_date_sk", "ws_bill_customer_sk",
                  "ws_ext_sales_price", y2_lo, y2_hi)
        m = pd.concat([s1d.rename("s1"), s2d.rename("s2"),
                       w1d.rename("w1"), w2d.rename("w2")],
                      axis=1, join="inner")
        m = m[(m.s1 > 0) & (m.w1 > 0) & (m.w2 / m.w1 > m.s2 / m.s1)]
        cud = cu.to_pandas()
        out = cud[cud.c_customer_sk.isin(m.index)][["c_customer_id"]]
        out = out.rename(columns={"c_customer_id": "customer_id"})
        return out.sort_values("customer_id").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q75(paths, tables, partitions: int = 2):
    """Yearly brand sales net of returns across all three channels,
    consecutive-year delta (q75's declining-brand scan)."""
    ss, sr = tables["store_sales"], tables["store_returns"]
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]
    ws, wr = tables["web_sales"], tables["web_returns"]
    it, dd = tables["item"], tables["date_dim"]

    def chan(sales_tbl, date_col, item_col, qty_col, ret_tbl,
             r_item_col, r_date_col):
        j_dd = join("broadcast_join", scan(paths, tables, sales_tbl),
                    scan(paths, tables, "date_dim"),
                    [c(date_col)], [c("d_date_sk")])
        j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                    [c(item_col)], [c("i_item_sk")])
        sales = project(j_it, [c("i_brand_id"), c("d_year"), c(qty_col),
                               lit(0, "int32")],
                        ["brand_id", "year", "qty", "rqty"])
        rj_dd = join("broadcast_join", scan(paths, tables, ret_tbl),
                     scan(paths, tables, "date_dim"),
                     [c(r_date_col)], [c("d_date_sk")])
        rj_it = join("broadcast_join", rj_dd,
                     scan(paths, tables, "item"),
                     [c(r_item_col)], [c("i_item_sk")])
        rets = project(rj_it, [c("i_brand_id"), c("d_year"),
                               lit(0, "int32"), lit(1, "int32")],
                       ["brand_id", "year", "qty", "rqty"])
        return [sales, rets]

    legs = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
                 "ss_quantity", "store_returns", "sr_item_sk",
                 "sr_returned_date_sk") +
            chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_quantity", "catalog_returns", "cr_item_sk",
                 "cr_returned_date_sk") +
            chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                 "ws_quantity", "web_returns", "wr_item_sk",
                 "wr_returned_date_sk"))
    u = {"kind": "union", "inputs": legs}
    yearly = _partial_final(
        u, [(ci(0), "brand_id"), (ci(1), "year")],
        [("sum", "qty", [ci(2)]), ("sum", "rqty", [ci(3)])], partitions)
    net = project(yearly, [ci(0), ci(1), binop("-", ci(2), ci(3))],
                  ["brand_id", "year", "net_qty"])
    y1 = exchange(filter_(net, binop("==", ci(1), lit(1999, "int32"))),
                  [ci(0)], partitions)
    y2 = exchange(filter_(net, binop("==", ci(1), lit(2000, "int32"))),
                  [ci(0)], partitions)
    j = join("sort_merge_join", y1, y2, [ci(0)], [ci(0)])
    flt = filter_(j, binop("<", ci(5), ci(2)))
    proj = project(flt, [ci(0), ci(2), ci(5)],
                   ["brand_id", "net_1999", "net_2000"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        itd, ddd = it.to_pandas(), dd.to_pandas()
        frames = []
        for sd, dc, ic, qc, rd, ric, rdc in [
                (ss, "ss_sold_date_sk", "ss_item_sk", "ss_quantity",
                 sr, "sr_item_sk", "sr_returned_date_sk"),
                (cs, "cs_sold_date_sk", "cs_item_sk", "cs_quantity",
                 cr, "cr_item_sk", "cr_returned_date_sk"),
                (ws, "ws_sold_date_sk", "ws_item_sk", "ws_quantity",
                 wr, "wr_item_sk", "wr_returned_date_sk")]:
            s = sd.to_pandas().merge(ddd, left_on=dc,
                                     right_on="d_date_sk")
            s = s.merge(itd, left_on=ic, right_on="i_item_sk")
            s = s[["i_brand_id", "d_year", qc]].rename(
                columns={qc: "qty"})
            s["rqty"] = 0
            r = rd.to_pandas().merge(ddd, left_on=rdc,
                                     right_on="d_date_sk")
            r = r.merge(itd, left_on=ric, right_on="i_item_sk")
            r = r[["i_brand_id", "d_year"]].copy()
            r["qty"] = 0
            r["rqty"] = 1
            frames.extend([s, r])
        u2 = pd.concat(frames, ignore_index=True)
        g = u2.groupby(["i_brand_id", "d_year"], as_index=False)[
            ["qty", "rqty"]].sum()
        g["net"] = g.qty - g.rqty
        a = g[g.d_year == 1999].set_index("i_brand_id").net
        b = g[g.d_year == 2000].set_index("i_brand_id").net
        m = pd.concat([a.rename("net_1999"), b.rename("net_2000")],
                      axis=1, join="inner")
        m = m[m.net_2000 < m.net_1999].reset_index().rename(
            columns={"i_brand_id": "brand_id"})
        return m.sort_values("brand_id").head(100).reset_index(drop=True)

    return plan, oracle


def q76(paths, tables, partitions: int = 2):
    """Null-key sales counts per channel/year (q76 counts rows whose
    dimension key is NULL; sr_customer_sk carries real nulls)."""
    sr, ss, dd = (tables["store_returns"], tables["store_sales"],
                  tables["date_dim"])
    legs = []
    sr_null = filter_(scan(paths, tables, "store_returns"),
                      {"kind": "is_null", "child": c("sr_customer_sk")})
    j1 = join("broadcast_join", sr_null, scan(paths, tables, "date_dim"),
              [c("sr_returned_date_sk")], [c("d_date_sk")])
    legs.append(project(j1, [lit("store_returns", "utf8"), c("d_year"),
                             c("sr_return_amt")],
                        ["channel", "year", "amt"]))
    j2 = join("broadcast_join", scan(paths, tables, "store_sales"),
              scan(paths, tables, "date_dim"),
              [c("ss_sold_date_sk")], [c("d_date_sk")])
    legs.append(project(j2, [lit("store_sales", "utf8"), c("d_year"),
                             c("ss_ext_sales_price")],
                        ["channel", "year", "amt"]))
    u = {"kind": "union", "inputs": legs}
    sums = _partial_final(
        u, [(ci(0), "channel"), (ci(1), "year")],
        [("count", "cnt", [ci(2)]), ("sum", "amt", [ci(2)])], partitions)
    plan = _top(sums, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ddd = dd.to_pandas()
        srd = sr.to_pandas()
        a = srd[srd.sr_customer_sk.isna()].merge(
            ddd, left_on="sr_returned_date_sk", right_on="d_date_sk")
        a = a.groupby("d_year").sr_return_amt.agg(["count", "sum"]) \
            .reset_index()
        a["channel"] = "store_returns"
        b = ss.to_pandas().merge(ddd, left_on="ss_sold_date_sk",
                                 right_on="d_date_sk")
        b = b.groupby("d_year").ss_ext_sales_price \
            .agg(["count", "sum"]).reset_index()
        b["channel"] = "store_sales"
        out = pd.concat([a, b], ignore_index=True).rename(
            columns={"d_year": "year", "count": "cnt", "sum": "amt"})[
            ["channel", "year", "cnt", "amt"]]
        return out.sort_values(["channel", "year"]).head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q77(paths, tables, partitions: int = 2):
    """Per-channel profit & loss rollup: sales profit and return loss by
    channel with an Expand total row."""
    ss, sr = tables["store_sales"], tables["store_returns"]
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]
    ws, wr = tables["web_sales"], tables["web_returns"]

    legs = []
    for tag, sales_tbl, p_col, ret_tbl, l_col in [
            ("store", "store_sales", "ss_net_profit", "store_returns",
             "sr_net_loss"),
            ("catalog", "catalog_sales", "cs_net_profit",
             "catalog_returns", "cr_net_loss"),
            ("web", "web_sales", "ws_net_profit", "web_returns",
             "wr_net_loss")]:
        legs.append(project(scan(paths, tables, sales_tbl),
                            [lit(tag, "utf8"), c(p_col),
                             lit(0.0, "float64")],
                            ["channel", "profit", "loss"]))
        legs.append(project(scan(paths, tables, ret_tbl),
                            [lit(tag, "utf8"), lit(0.0, "float64"),
                             c(l_col)],
                            ["channel", "profit", "loss"]))
    u = {"kind": "union", "inputs": legs}
    expanded = {"kind": "expand", "input": u,
                "projections": [
                    [ci(0), lit(0), ci(1), ci(2)],
                    [lit(None, "utf8"), lit(1), ci(1), ci(2)]],
                "names": ["channel", "g_id", "profit", "loss"]}
    sums = _partial_final(
        expanded, [(ci(0), "channel"), (ci(1), "g_id")],
        [("sum", "profit", [ci(2)]), ("sum", "loss", [ci(3)])],
        partitions)
    proj = project(sums, [ci(0), ci(2), ci(3)],
                   ["channel", "profit", "loss"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        rows = []
        tp = tl = 0.0
        for tag, sd, pc2, rd, lc in [
                ("store", ss, "ss_net_profit", sr, "sr_net_loss"),
                ("catalog", cs, "cs_net_profit", cr, "cr_net_loss"),
                ("web", ws, "ws_net_profit", wr, "wr_net_loss")]:
            p = sd.to_pandas()[pc2].sum()
            l = rd.to_pandas()[lc].sum()
            rows.append((tag, p, l))
            tp += p
            tl += l
        rows.append((None, tp, tl))
        out = pd.DataFrame(rows, columns=["channel", "profit", "loss"])
        return out.sort_values("channel", na_position="first") \
            .head(100).reset_index(drop=True)

    return plan, oracle


def q78(paths, tables, partitions: int = 2):
    """Customer-item yearly sums per channel excluding returned sales,
    web/store quantity ratio (q78's unreturned-sales comparison)."""
    ss, sr = tables["store_sales"], tables["store_returns"]
    ws, wr = tables["web_sales"], tables["web_returns"]
    lo, hi = _day_range(730, 1094)

    ss_f = filter_(scan(paths, tables, "store_sales"),
                   binop(">=", c("ss_sold_date_sk"), lit(lo)),
                   binop("<=", c("ss_sold_date_sk"), lit(hi)))
    ss_ex = exchange(project(ss_f, [c("ss_ticket_number"),
                                    c("ss_item_sk"), c("ss_customer_sk"),
                                    c("ss_quantity")],
                             ["ticket", "item", "cust", "qty"]),
                     [ci(0), ci(1)], partitions)
    sr_ex = exchange(project(scan(paths, tables, "store_returns"),
                             [c("sr_ticket_number"), c("sr_item_sk")],
                             ["rt", "ri"]),
                     [ci(0), ci(1)], partitions)
    ss_anti = join("hash_join", ss_ex, sr_ex, [ci(0), ci(1)],
                   [ci(0), ci(1)], jt="left_anti")
    s_tot = _partial_final(ss_anti, [(ci(2), "cust")],
                           [("sum", "s_qty", [ci(3)])], partitions)

    ws_f = filter_(scan(paths, tables, "web_sales"),
                   binop(">=", c("ws_sold_date_sk"), lit(lo)),
                   binop("<=", c("ws_sold_date_sk"), lit(hi)))
    ws_ex = exchange(project(ws_f, [c("ws_order_number"),
                                    c("ws_item_sk"),
                                    c("ws_bill_customer_sk"),
                                    c("ws_quantity")],
                             ["order", "item", "cust", "qty"]),
                     [ci(0), ci(1)], partitions)
    wr_ex = exchange(project(scan(paths, tables, "web_returns"),
                             [c("wr_order_number"), c("wr_item_sk")],
                             ["ro", "ri"]),
                     [ci(0), ci(1)], partitions)
    ws_anti = join("hash_join", ws_ex, wr_ex, [ci(0), ci(1)],
                   [ci(0), ci(1)], jt="left_anti")
    w_tot = _partial_final(ws_anti, [(ci(2), "cust")],
                           [("sum", "w_qty", [ci(3)])], partitions)

    j = join("sort_merge_join", exchange(s_tot, [ci(0)], partitions),
             exchange(w_tot, [ci(0)], partitions), [ci(0)], [ci(0)])
    ratio = project(j, [ci(0), ci(1), ci(3),
                        binop("/", {"kind": "cast", "child": ci(3),
                                    "type": {"id": "float64"}},
                              {"kind": "cast", "child": ci(1),
                               "type": {"id": "float64"}})],
                    ["cust", "s_qty", "w_qty", "ratio"])
    plan = _top(ratio, [(ci(3), True), (ci(0), False)], 100)

    def oracle():
        ssd = ss.to_pandas()
        ssd = ssd[(ssd.ss_sold_date_sk >= lo) & (ssd.ss_sold_date_sk <= hi)]
        srd = sr.to_pandas()
        ret = set(zip(srd.sr_ticket_number, srd.sr_item_sk))
        keep = ~ssd.apply(lambda r: (r.ss_ticket_number, r.ss_item_sk)
                          in ret, axis=1)
        s_tot_d = ssd[keep].groupby("ss_customer_sk").ss_quantity.sum()
        wsd = ws.to_pandas()
        wsd = wsd[(wsd.ws_sold_date_sk >= lo) & (wsd.ws_sold_date_sk <= hi)]
        wrd = wr.to_pandas()
        wret = set(zip(wrd.wr_order_number, wrd.wr_item_sk))
        wkeep = ~wsd.apply(lambda r: (r.ws_order_number, r.ws_item_sk)
                           in wret, axis=1)
        w_tot_d = wsd[wkeep].groupby("ws_bill_customer_sk") \
            .ws_quantity.sum()
        m = pd.concat([s_tot_d.rename("s_qty"), w_tot_d.rename("w_qty")],
                      axis=1, join="inner").reset_index().rename(
            columns={"index": "cust"})
        m["ratio"] = m.w_qty.astype(float) / m.s_qty.astype(float)
        return m.sort_values(["ratio", "cust"],
                             ascending=[False, True]).head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q80(paths, tables, partitions: int = 2):
    """Sales minus returns per channel in a date window (q80's channel
    P&L with returns netted by order/ticket+item)."""
    ss, sr = tables["store_sales"], tables["store_returns"]
    cs, cr = tables["catalog_sales"], tables["catalog_returns"]
    ws, wr = tables["web_sales"], tables["web_returns"]
    lo, hi = _day_range(365, 455)

    def leg(tag, sales_tbl, date_col, key_cols, amt_col, ret_tbl,
            r_keys, r_amt):
        base = filter_(scan(paths, tables, sales_tbl),
                       binop(">=", c(date_col), lit(lo)),
                       binop("<=", c(date_col), lit(hi)))
        s_ex = exchange(project(base, [c(k) for k in key_cols] +
                                [c(amt_col)],
                                ["k0", "k1", "amt"]),
                        [ci(0), ci(1)], partitions)
        r_ex = exchange(project(scan(paths, tables, ret_tbl),
                                [c(k) for k in r_keys] + [c(r_amt)],
                                ["rk0", "rk1", "ramt"]),
                        [ci(0), ci(1)], partitions)
        j = join("hash_join", s_ex, r_ex, [ci(0), ci(1)],
                 [ci(0), ci(1)], jt="left")
        net = binop("-", ci(2),
                    {"kind": "coalesce",
                     "args": [ci(5), lit(0.0, "float64")]})
        return project(j, [lit(tag, "utf8"), net], ["channel", "net"])

    u = {"kind": "union", "inputs": [
        leg("store", "store_sales", "ss_sold_date_sk",
            ["ss_ticket_number", "ss_item_sk"], "ss_ext_sales_price",
            "store_returns", ["sr_ticket_number", "sr_item_sk"],
            "sr_return_amt"),
        leg("catalog", "catalog_sales", "cs_sold_date_sk",
            ["cs_order_number", "cs_item_sk"], "cs_ext_sales_price",
            "catalog_returns", ["cr_order_number", "cr_item_sk"],
            "cr_return_amount"),
        leg("web", "web_sales", "ws_sold_date_sk",
            ["ws_order_number", "ws_item_sk"], "ws_ext_sales_price",
            "web_returns", ["wr_order_number", "wr_item_sk"],
            "wr_return_amt")]}
    sums = _partial_final(u, [(ci(0), "channel")],
                          [("sum", "net_sales", [ci(1)])], partitions)
    plan = _top(sums, [(ci(0), False)], 10)

    def oracle():
        rows = []
        for tag, sd, dc, ks, ac, rd, rks, ra in [
                ("store", ss, "ss_sold_date_sk",
                 ["ss_ticket_number", "ss_item_sk"],
                 "ss_ext_sales_price", sr,
                 ["sr_ticket_number", "sr_item_sk"], "sr_return_amt"),
                ("catalog", cs, "cs_sold_date_sk",
                 ["cs_order_number", "cs_item_sk"],
                 "cs_ext_sales_price", cr,
                 ["cr_order_number", "cr_item_sk"], "cr_return_amount"),
                ("web", ws, "ws_sold_date_sk",
                 ["ws_order_number", "ws_item_sk"],
                 "ws_ext_sales_price", wr,
                 ["wr_order_number", "wr_item_sk"], "wr_return_amt")]:
            sdf = sd.to_pandas()
            sdf = sdf[(sdf[dc] >= lo) & (sdf[dc] <= hi)]
            rdf = rd.to_pandas()[rks + [ra]]
            m = sdf.merge(rdf, left_on=ks, right_on=rks, how="left")
            net = (m[ac] - m[ra].fillna(0.0)).sum()
            rows.append((tag, net))
        out = pd.DataFrame(rows, columns=["channel", "net_sales"])
        return out.sort_values("channel").reset_index(drop=True)

    return plan, oracle


def q84(paths, tables, partitions: int = 2):
    """Customer lookup by city + demographic bands (q84's income-band
    ident list, buy-potential standing in for the band table)."""
    cu, ca, cd = (tables["customer"], tables["customer_address"],
                  tables["customer_demographics"])
    ca_f = filter_(scan(paths, tables, "customer_address"),
                   binop("==", c("ca_city"), lit("city_7", "utf8")))
    j_ca = join("broadcast_join", scan(paths, tables, "customer"),
                ca_f, [c("c_current_addr_sk")], [c("ca_address_sk")])
    cd_f = filter_(scan(paths, tables, "customer_demographics"),
                   binop("==", c("cd_marital_status"), lit("M", "utf8")))
    j_cd = join("broadcast_join", j_ca, cd_f,
                [c("c_current_cdemo_sk")], [c("cd_demo_sk")])
    proj = project(j_cd, [c("c_customer_id")], ["customer_id"])
    plan = _top(proj, [(ci(0), False)], 100)

    def oracle():
        cad = ca.to_pandas()
        ok = set(cad[cad.ca_city == "city_7"].ca_address_sk)
        cdd = cd.to_pandas()
        okd = set(cdd[cdd.cd_marital_status == "M"].cd_demo_sk)
        d = cu.to_pandas()
        d = d[d.c_current_addr_sk.isin(ok)
              & d.c_current_cdemo_sk.isin(okd)]
        out = d[["c_customer_id"]].rename(
            columns={"c_customer_id": "customer_id"})
        return out.sort_values("customer_id").head(100) \
            .reset_index(drop=True)

    return plan, oracle


def q86(paths, tables, partitions: int = 2):
    """ROLLUP(category, class) web net profit with Expand (q86 is q67's
    web profit sibling)."""
    ws, it = tables["web_sales"], tables["item"]
    j_it = join("broadcast_join", scan(paths, tables, "web_sales"),
                scan(paths, tables, "item"),
                [c("ws_item_sk")], [c("i_item_sk")])
    projections = []
    for gid, keep in enumerate([(True, True), (True, False),
                                (False, False)]):
        projections.append([
            c("i_category") if keep[0] else lit(None, "utf8"),
            c("i_class") if keep[1] else lit(None, "utf8"),
            lit(gid), c("ws_net_profit")])
    expanded = {"kind": "expand", "input": j_it,
                "projections": projections,
                "names": ["i_category", "i_class", "g_id", "profit"]}
    sums = _partial_final(
        expanded,
        [(ci(0), "i_category"), (ci(1), "i_class"), (ci(2), "g_id")],
        [("sum", "total_profit", [ci(3)])], partitions)
    proj = project(sums, [ci(0), ci(1), ci(3)],
                   ["i_category", "i_class", "total_profit"])
    plan = _top(proj, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        d = ws.to_pandas().merge(it.to_pandas(), left_on="ws_item_sk",
                                 right_on="i_item_sk")
        outs = []
        full = d.groupby(["i_category", "i_class"], as_index=False) \
            .ws_net_profit.sum()
        outs.append(full)
        cat = d.groupby(["i_category"], as_index=False) \
            .ws_net_profit.sum()
        cat["i_class"] = None
        outs.append(cat)
        outs.append(pd.DataFrame({"i_category": [None],
                                  "i_class": [None],
                                  "ws_net_profit":
                                  [d.ws_net_profit.sum()]}))
        allr = pd.concat(outs, ignore_index=True).rename(
            columns={"ws_net_profit": "total_profit"})[
            ["i_category", "i_class", "total_profit"]]
        return allr.sort_values(["i_category", "i_class"],
                                na_position="first").head(100) \
            .reset_index(drop=True)

    return plan, oracle


QUERIES.update({
    "q02": (q02, ["web_sales", "catalog_sales", "date_dim"]),
    "q05": (q05, ["store_sales", "store_returns", "catalog_sales",
                  "catalog_returns", "web_sales", "web_returns"]),
    "q08": (q08, ["store_sales", "store", "customer",
                  "customer_address"]),
    "q09": (q09, ["store_sales"]),
    "q44": (q44, ["store_sales", "item"]),
    "q53": (q53, ["store_sales", "item", "date_dim"]),
    "q54": (q54, ["store_sales", "customer"]),
    "q58": (q58, ["store_sales", "catalog_sales", "web_sales", "item"]),
    "q61": (q61, ["store_sales", "promotion"]),
    "q63": (q63, ["store_sales", "item", "date_dim"]),
    "q71": (q71, ["store_sales", "catalog_sales", "web_sales", "item",
                  "time_dim"]),
    "q74": (q74, ["store_sales", "web_sales", "customer"]),
    "q75": (q75, ["store_sales", "store_returns", "catalog_sales",
                  "catalog_returns", "web_sales", "web_returns", "item",
                  "date_dim"]),
    "q76": (q76, ["store_returns", "store_sales", "date_dim"]),
    "q77": (q77, ["store_sales", "store_returns", "catalog_sales",
                  "catalog_returns", "web_sales", "web_returns"]),
    "q78": (q78, ["store_sales", "store_returns", "web_sales",
                  "web_returns"]),
    "q80": (q80, ["store_sales", "store_returns", "catalog_sales",
                  "catalog_returns", "web_sales", "web_returns"]),
    "q84": (q84, ["customer", "customer_address",
                  "customer_demographics"]),
    "q86": (q86, ["web_sales", "item"]),
})
