"""TPC-DS progression queries as plan-IR dicts (BASELINE.md configs).

Parity role: dev/auron-it query set.  DEMOTED to the secondary tier
since round 3: the PRIMARY integration tier is
tests/test_spark_fixtures.py, which drives the same queries from
checked-in Spark `toJSON` fixtures (itest/spark_plans.py) through the
L6 converter, the stage-DAG scheduler, and per-task protobuf
TaskDefinitions — the full production path.  This module remains the
oracle source (shared with the fixture tier) and the direct-IR
regression net for the in-process planner path.  Fact tables are read
from parquet file splits; exchanges are `local_exchange` nodes;
aggregations use partial/final pairs exactly as a Spark plan would emit
them (COMPLETE has no wire encoding).

Queries:
  q01 — customers returning >1.2x their store's average (config #1)
  q06 — items above 1.2x category-average price (config #2 shape)
  q17 — ss->sr->cs multi-join with per-role date windows + grouped
        count/avg stats (config #3 shape; stdev simplified to count/avg)
  q18 — catalog sales demographics with ROLLUP(item, country, state,
        county) via Expand grouping sets (config #3 rollup)
  q95 — web orders shipped from >1 warehouse with no return: EXISTS as a
        filtered semi join + NOT EXISTS as an anti join, wide exchange on
        order number (config #4)

Each builder returns (plan_dict, oracle) where oracle computes the
expected frame with pandas (QueryResultComparator analog).

Date key arithmetic mirrors tpcds_data.gen_date_dim: sk = 2450815 + day,
d_year = 1998 + day//365.  Engine-side date-role predicates use pushed sk
ranges (the DPP/broadcast form); oracles use the identical ranges.
"""

from __future__ import annotations

import uuid
from typing import Callable, Dict, List, Tuple

import pandas as pd
import pyarrow as pa

from blaze_tpu.plan.types import schema_to_dict
from blaze_tpu.schema import Schema

D0 = 2450815  # first d_date_sk


def _day_range(start_day: int, end_day: int) -> Tuple[int, int]:
    return D0 + start_day, D0 + end_day


def c(name: str) -> dict:
    return {"kind": "column", "name": name}


def ci(index: int) -> dict:
    return {"kind": "column", "index": index}


def lit(v, t: str = "int64") -> dict:
    return {"kind": "literal", "value": v, "type": {"id": t}}


def binop(op: str, l: dict, r: dict) -> dict:
    return {"kind": "binary", "op": op, "l": l, "r": r}


def scan(paths: Dict[str, List[List[str]]], tables: Dict[str, pa.Table],
         name: str) -> dict:
    return {"kind": "parquet_scan",
            "schema": schema_to_dict(Schema.from_arrow(tables[name].schema)),
            "file_groups": paths[name]}


def filter_(inp: dict, *preds: dict) -> dict:
    return {"kind": "filter", "input": inp, "predicates": list(preds)}


def project(inp: dict, exprs: List[dict], names: List[str]) -> dict:
    return {"kind": "project", "input": inp, "exprs": exprs, "names": names}


def exchange(inp: dict, keys: List[dict], partitions: int) -> dict:
    return {"kind": "local_exchange",
            "partitioning": {"kind": "hash", "exprs": keys,
                             "num_partitions": partitions},
            "stage_id": uuid.uuid4().int % (1 << 31),
            "input": inp}


def join(kind: str, left: dict, right: dict, lkeys: List[dict],
         rkeys: List[dict], jt: str = "inner", build: str = "right",
         flt: dict = None) -> dict:
    d = {"kind": kind, "left": left, "right": right, "left_keys": lkeys,
         "right_keys": rkeys, "join_type": jt}
    if kind != "sort_merge_join":
        d["build_side"] = build
    if kind == "broadcast_join":
        d["broadcast_id"] = f"itest-{uuid.uuid4().hex[:10]}"
    if flt is not None:
        d["join_filter"] = flt
    return d


def agg(inp: dict, groups: List[Tuple[dict, str]],
        aggs: List[Tuple[str, str, str, List[dict]]]) -> dict:
    """aggs: (fn, mode, name, args)."""
    return {"kind": "hash_agg", "input": inp,
            "groupings": [{"expr": e, "name": n} for e, n in groups],
            "aggs": [{"fn": f, "mode": m, "name": n, "args": a}
                     for f, m, n, a in aggs]}


def sort_limit(inp: dict, specs: List[Tuple[dict, bool]], limit: int) -> dict:
    return {"kind": "limit", "limit": limit,
            "input": {"kind": "sort", "input": inp,
                      "specs": [{"expr": e, "descending": d,
                                 "nulls_first": not d} for e, d in specs],
                      "fetch": limit}}


def _partial_final(inp: dict, group_names: List[Tuple[dict, str]],
                   fns: List[Tuple[str, str, List[dict]]],
                   partitions: int) -> dict:
    """partial agg -> hash exchange on the group keys -> final agg (the
    two-stage pair Spark emits; acc columns rebind positionally)."""
    partial = agg(inp, group_names,
                  [(f, "partial", n, a) for f, n, a in fns])
    ng = len(group_names)
    ex = exchange(partial, [ci(i) for i in range(ng)], partitions)
    final_groups = [(ci(i), name) for i, (_e, name) in
                    enumerate(group_names)]
    final_aggs = []
    pos = ng
    for f, n, _a in fns:
        nacc = 2 if f == "avg" else 1
        final_aggs.append((f, "final", n,
                           [ci(pos + t) for t in range(nacc)]))
        pos += nacc
    return agg(ex, final_groups, final_aggs)


# ---------------------------------------------------------------------------
# q01
# ---------------------------------------------------------------------------

def q01(paths, tables, partitions: int = 2):
    sr, dd, st, cu = (tables["store_returns"], tables["date_dim"],
                      tables["store"], tables["customer"])

    dd_flt = filter_(scan(paths, tables, "date_dim"),
                     binop("==", c("d_year"), lit(2000, "int32")))
    sr_dd = join("broadcast_join", scan(paths, tables, "store_returns"),
                 dd_flt, [c("sr_returned_date_sk")], [c("d_date_sk")])
    ctr = _partial_final(
        sr_dd,
        [(c("sr_customer_sk"), "ctr_customer_sk"),
         (c("sr_store_sk"), "ctr_store_sk")],
        [("sum", "ctr_total_return", [c("sr_return_amt")])],
        partitions)

    # avg(ctr_total_return) by store over a re-exchange of ctr
    avg_in = exchange(ctr, [ci(1)], partitions)
    avg_by_store = agg(
        agg(avg_in, [(ci(1), "avg_store_sk")],
            [("avg", "partial", "avg_return", [ci(2)])]),
        [(ci(0), "avg_store_sk")],
        [("avg", "final", "avg_return", [ci(1), ci(2)])])

    ctr2 = exchange(ctr, [ci(1)], partitions)
    joined = join("sort_merge_join", ctr2, avg_by_store, [ci(1)], [ci(0)])
    flt = filter_(joined, binop(">", c("ctr_total_return"),
                                binop("*", c("avg_return"),
                                      lit(1.2, "float64"))))
    st_flt = filter_(scan(paths, tables, "store"),
                     binop("==", c("s_state"), lit("TN", "utf8")))
    j_store = join("broadcast_join", flt, st_flt,
                   [c("ctr_store_sk")], [c("s_store_sk")])
    j_cust = join("broadcast_join", j_store,
                  scan(paths, tables, "customer"),
                  [c("ctr_customer_sk")], [c("c_customer_sk")])
    proj = project(j_cust, [c("c_customer_id")], ["c_customer_id"])
    single = exchange(proj, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        srd, ddd = sr.to_pandas(), dd.to_pandas()
        std, cud = st.to_pandas(), cu.to_pandas()
        m = srd.merge(ddd[ddd.d_year == 2000],
                      left_on="sr_returned_date_sk", right_on="d_date_sk")
        # GROUP BY keeps the NULL-customer group (SQL semantics); only the
        # final inner join to customer drops it
        ctr = (m.groupby(["sr_customer_sk", "sr_store_sk"],
                         as_index=False, dropna=False)
               .sr_return_amt.sum()
               .rename(columns={"sr_return_amt": "ctr_total"}))
        avg = ctr.groupby("sr_store_sk", as_index=False).ctr_total.mean() \
            .rename(columns={"ctr_total": "avg_return"})
        j = ctr.merge(avg, on="sr_store_sk")
        j = j[j.ctr_total > 1.2 * j.avg_return]
        j = j.merge(std[std.s_state == "TN"], left_on="sr_store_sk",
                    right_on="s_store_sk")
        j = j.merge(cud, left_on="sr_customer_sk", right_on="c_customer_sk")
        out = j[["c_customer_id"]].sort_values("c_customer_id")[:100]
        return out.reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# q06 shape
# ---------------------------------------------------------------------------

def q06(paths, tables, partitions: int = 4):
    ss, it = tables["store_sales"], tables["item"]

    cat_avg = agg(
        agg(scan(paths, tables, "item"), [(c("i_category"), "cat")],
            [("avg", "partial", "avg_price", [c("i_current_price")])]),
        [(ci(0), "cat")],
        [("avg", "final", "avg_price", [ci(1), ci(2)])])
    it_j = join("broadcast_join", scan(paths, tables, "item"), cat_avg,
                [c("i_category")], [c("cat")])
    it_flt = filter_(it_j, binop(">", c("i_current_price"),
                                 binop("*", c("avg_price"),
                                       lit(1.2, "float64"))))
    ss_j = join("broadcast_join", scan(paths, tables, "store_sales"),
                it_flt, [c("ss_item_sk")], [c("i_item_sk")])
    counted = _partial_final(
        ss_j, [(c("ss_store_sk"), "store")],
        [("count", "cnt", [c("ss_sold_date_sk")])], partitions)
    single = exchange(counted, [ci(0)], 1)
    plan = {"kind": "sort", "input": single,
            "specs": [{"expr": ci(0), "descending": False,
                       "nulls_first": True}]}

    def oracle():
        ssd, itd = ss.to_pandas(), it.to_pandas()
        avg = itd.groupby("i_category", as_index=False) \
            .i_current_price.mean().rename(
                columns={"i_current_price": "avg_price"})
        j = itd.merge(avg, on="i_category")
        sel = j[j.i_current_price > 1.2 * j.avg_price]
        m = ssd.merge(sel, left_on="ss_item_sk", right_on="i_item_sk")
        out = (m.groupby("ss_store_sk", as_index=False)
               .agg(cnt=("ss_sold_date_sk", "count"))
               .rename(columns={"ss_store_sk": "store"})
               .sort_values("store"))
        return out.reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# q17 shape: ss -> sr -> cs with three date roles, grouped stats
# ---------------------------------------------------------------------------

SS_WINDOW = _day_range(730, 820)      # Q1 2000
SR_CS_WINDOW = _day_range(730, 1003)  # Q1-Q3 2000


def q17(paths, tables, partitions: int = 4):
    ss, sr, cs = (tables["store_sales"], tables["store_returns"],
                  tables["catalog_sales"])
    st, it = tables["store"], tables["item"]

    ss_f = filter_(scan(paths, tables, "store_sales"),
                   binop(">=", c("ss_sold_date_sk"), lit(SS_WINDOW[0])),
                   binop("<=", c("ss_sold_date_sk"), lit(SS_WINDOW[1])))
    sr_f = filter_(scan(paths, tables, "store_returns"),
                   binop(">=", c("sr_returned_date_sk"),
                         lit(SR_CS_WINDOW[0])),
                   binop("<=", c("sr_returned_date_sk"),
                         lit(SR_CS_WINDOW[1])))
    cs_f = filter_(scan(paths, tables, "catalog_sales"),
                   binop(">=", c("cs_sold_date_sk"), lit(SR_CS_WINDOW[0])),
                   binop("<=", c("cs_sold_date_sk"), lit(SR_CS_WINDOW[1])))

    ss_ex = exchange(ss_f, [c("ss_ticket_number"), c("ss_item_sk")],
                     partitions)
    sr_ex = exchange(sr_f, [c("sr_ticket_number"), c("sr_item_sk")],
                     partitions)
    ss_sr = join("hash_join", ss_ex, sr_ex,
                 [c("ss_ticket_number"), c("ss_item_sk")],
                 [c("sr_ticket_number"), c("sr_item_sk")])

    left_ex = exchange(ss_sr, [c("sr_customer_sk"), c("sr_item_sk")],
                       partitions)
    cs_ex = exchange(cs_f, [c("cs_bill_customer_sk"), c("cs_item_sk")],
                     partitions)
    three = join("hash_join", left_ex, cs_ex,
                 [c("sr_customer_sk"), c("sr_item_sk")],
                 [c("cs_bill_customer_sk"), c("cs_item_sk")])

    j_it = join("broadcast_join", three, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    j_st = join("broadcast_join", j_it, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])

    stats = _partial_final(
        j_st,
        [(c("i_item_id"), "i_item_id"), (c("s_state"), "s_state")],
        [("count", "store_sales_cnt", [c("ss_quantity")]),
         ("avg", "store_sales_avg", [c("ss_quantity")]),
         ("count", "store_returns_cnt", [c("sr_return_quantity")]),
         ("avg", "store_returns_avg", [c("sr_return_quantity")]),
         ("count", "catalog_sales_cnt", [c("cs_quantity")]),
         ("avg", "catalog_sales_avg", [c("cs_quantity")])],
        partitions)
    single = exchange(stats, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        ssd, srd, csd = ss.to_pandas(), sr.to_pandas(), cs.to_pandas()
        std, itd = st.to_pandas(), it.to_pandas()
        ssd = ssd[(ssd.ss_sold_date_sk >= SS_WINDOW[0]) &
                  (ssd.ss_sold_date_sk <= SS_WINDOW[1])]
        srd = srd[(srd.sr_returned_date_sk >= SR_CS_WINDOW[0]) &
                  (srd.sr_returned_date_sk <= SR_CS_WINDOW[1])]
        csd = csd[(csd.cs_sold_date_sk >= SR_CS_WINDOW[0]) &
                  (csd.cs_sold_date_sk <= SR_CS_WINDOW[1])]
        m = ssd.merge(srd, left_on=["ss_ticket_number", "ss_item_sk"],
                      right_on=["sr_ticket_number", "sr_item_sk"])
        m = m.dropna(subset=["sr_customer_sk"]).merge(
            csd, left_on=["sr_customer_sk", "sr_item_sk"],
            right_on=["cs_bill_customer_sk", "cs_item_sk"])
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        out = m.groupby(["i_item_id", "s_state"], as_index=False).agg(
            store_sales_cnt=("ss_quantity", "count"),
            store_sales_avg=("ss_quantity", "mean"),
            store_returns_cnt=("sr_return_quantity", "count"),
            store_returns_avg=("sr_return_quantity", "mean"),
            catalog_sales_cnt=("cs_quantity", "count"),
            catalog_sales_avg=("cs_quantity", "mean"))
        out = out.sort_values(["i_item_id", "s_state"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# q18 shape: demographics joins + ROLLUP via Expand grouping sets
# ---------------------------------------------------------------------------

Y1998 = _day_range(0, 364)
Q18_STATES = ["TX", "OH", "IL"]


def q18(paths, tables, partitions: int = 4):
    cs, cd, cu = (tables["catalog_sales"], tables["customer_demographics"],
                  tables["customer"])
    ca, it = tables["customer_address"], tables["item"]

    cs_f = filter_(scan(paths, tables, "catalog_sales"),
                   binop(">=", c("cs_sold_date_sk"), lit(Y1998[0])),
                   binop("<=", c("cs_sold_date_sk"), lit(Y1998[1])))
    cd_f = filter_(scan(paths, tables, "customer_demographics"),
                   binop("==", c("cd_gender"), lit("F", "utf8")),
                   binop("==", c("cd_education_status"),
                         lit("Unknown", "utf8")))
    j_cd = join("broadcast_join", cs_f, cd_f,
                [c("cs_bill_cdemo_sk")], [c("cd_demo_sk")])

    cs_ex = exchange(j_cd, [c("cs_bill_customer_sk")], partitions)
    cu_ex = exchange(scan(paths, tables, "customer"),
                     [c("c_customer_sk")], partitions)
    j_cu = join("hash_join", cs_ex, cu_ex,
                [c("cs_bill_customer_sk")], [c("c_customer_sk")])

    ca_f = filter_(scan(paths, tables, "customer_address"),
                   {"kind": "in_list", "child": c("ca_state"),
                    "values": Q18_STATES, "negated": False})
    j_ca = join("broadcast_join", j_cu, ca_f,
                [c("c_current_addr_sk")], [c("ca_address_sk")])
    j_it = join("broadcast_join", j_ca, scan(paths, tables, "item"),
                [c("cs_item_sk")], [c("i_item_sk")])

    # ROLLUP(i_item_id, ca_country, ca_state, ca_county): 5 grouping sets
    # (ref expand_exec.rs:506 fan-out; Spark emits Expand + grouping id)
    nul = {"kind": "literal", "value": None, "type": {"id": "utf8"}}
    grp = [c("i_item_id"), c("ca_country"), c("ca_state"), c("ca_county")]
    aggs_src = [c("cs_quantity"), c("cs_list_price"), c("cs_coupon_amt"),
                c("cs_net_profit")]
    projections = []
    for kept, gid in ((4, 0), (3, 1), (2, 3), (1, 7), (0, 15)):
        row = [grp[i] if i < kept else nul for i in range(4)]
        row.append(lit(gid))
        row.extend(aggs_src)
        projections.append(row)
    expanded = {"kind": "expand", "input": j_it,
                "projections": projections,
                "names": ["i_item_id", "ca_country", "ca_state",
                          "ca_county", "g_id", "cs_quantity",
                          "cs_list_price", "cs_coupon_amt",
                          "cs_net_profit"]}

    stats = _partial_final(
        expanded,
        [(ci(0), "i_item_id"), (ci(1), "ca_country"), (ci(2), "ca_state"),
         (ci(3), "ca_county"), (ci(4), "g_id")],
        [("avg", "agg1", [ci(5)]), ("avg", "agg2", [ci(6)]),
         ("avg", "agg3", [ci(7)]), ("avg", "agg4", [ci(8)])],
        partitions)
    single = exchange(stats, [ci(0)], 1)
    plan = sort_limit(single,
                      [(ci(4), False), (ci(0), False), (ci(1), False),
                       (ci(2), False), (ci(3), False)], 100)

    def oracle():
        csd, cdd = cs.to_pandas(), cd.to_pandas()
        cud, cad, itd = cu.to_pandas(), ca.to_pandas(), it.to_pandas()
        csd = csd[(csd.cs_sold_date_sk >= Y1998[0]) &
                  (csd.cs_sold_date_sk <= Y1998[1])]
        cdd = cdd[(cdd.cd_gender == "F") &
                  (cdd.cd_education_status == "Unknown")]
        m = csd.merge(cdd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(cud, left_on="cs_bill_customer_sk",
                    right_on="c_customer_sk")
        m = m.merge(cad[cad.ca_state.isin(Q18_STATES)],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(itd, left_on="cs_item_sk", right_on="i_item_sk")
        frames = []
        cols = ["i_item_id", "ca_country", "ca_state", "ca_county"]
        for kept, gid in ((4, 0), (3, 1), (2, 3), (1, 7), (0, 15)):
            keys = cols[:kept]
            if keys:
                g = m.groupby(keys, as_index=False, dropna=False).agg(
                    agg1=("cs_quantity", "mean"),
                    agg2=("cs_list_price", "mean"),
                    agg3=("cs_coupon_amt", "mean"),
                    agg4=("cs_net_profit", "mean"))
            else:
                g = pd.DataFrame({
                    "agg1": [m.cs_quantity.mean()],
                    "agg2": [m.cs_list_price.mean()],
                    "agg3": [m.cs_coupon_amt.mean()],
                    "agg4": [m.cs_net_profit.mean()]})
            for col_name in cols[kept:]:
                g[col_name] = None
            g["g_id"] = gid
            frames.append(g[cols + ["g_id", "agg1", "agg2", "agg3",
                                    "agg4"]])
        out = pd.concat(frames, ignore_index=True)
        out = out.sort_values(["g_id"] + cols)[:100]
        return out.reset_index(drop=True)

    return plan, oracle


# ---------------------------------------------------------------------------
# q95 shape: EXISTS (filtered semi join) + NOT EXISTS (anti join)
# ---------------------------------------------------------------------------

Q95_WINDOW = _day_range(761, 821)


def q95(paths, tables, partitions: int = 4):
    ws, wr, ca = (tables["web_sales"], tables["web_returns"],
                  tables["customer_address"])

    ws1 = filter_(scan(paths, tables, "web_sales"),
                  binop(">=", c("ws_ship_date_sk"), lit(Q95_WINDOW[0])),
                  binop("<=", c("ws_ship_date_sk"), lit(Q95_WINDOW[1])),
                  binop("<=", c("ws_web_site_sk"), lit(2)))
    ca_f = filter_(scan(paths, tables, "customer_address"),
                   binop("==", c("ca_state"), lit("IL", "utf8")))
    ws1 = join("broadcast_join", ws1, ca_f,
               [c("ws_ship_addr_sk")], [c("ca_address_sk")])
    ws1 = project(ws1,
                  [c("ws_order_number"), c("ws_warehouse_sk"),
                   c("ws_ext_ship_cost"), c("ws_net_profit")],
                  ["ws_order_number", "ws_warehouse_sk",
                   "ws_ext_ship_cost", "ws_net_profit"])
    ws1_ex = exchange(ws1, [ci(0)], partitions)

    ws_all = project(scan(paths, tables, "web_sales"),
                     [c("ws_order_number"), c("ws_warehouse_sk")],
                     ["wh_order_number", "wh_warehouse_sk"])
    ws_all_ex = exchange(ws_all, [ci(0)], partitions)

    # EXISTS ws2 with same order, different warehouse: semi join with a
    # joined-schema filter (left 4 cols + right 2 cols)
    semi = join("hash_join", ws1_ex, ws_all_ex, [ci(0)], [ci(0)],
                jt="left_semi",
                flt=binop("!=", ci(1), ci(5)))

    wr_ex = exchange(project(scan(paths, tables, "web_returns"),
                             [c("wr_order_number")], ["wr_order_number"]),
                     [ci(0)], partitions)
    anti = join("hash_join", semi, wr_ex, [ci(0)], [ci(0)],
                jt="left_anti")

    # per-order sums (orders are co-partitioned after the exchange), then
    # one global row: count(distinct order) = count of per-order groups
    per_order = agg(
        agg(anti, [(ci(0), "ws_order_number")],
            [("sum", "partial", "ship_cost", [ci(2)]),
             ("sum", "partial", "net_profit", [ci(3)])]),
        [(ci(0), "ws_order_number")],
        [("sum", "final", "ship_cost", [ci(1)]),
         ("sum", "final", "net_profit", [ci(2)])])
    single = exchange(per_order, [ci(0)], 1)
    totals = agg(
        agg(single, [],
            [("count", "partial", "order_count", [ci(0)]),
             ("sum", "partial", "total_ship_cost", [ci(1)]),
             ("sum", "partial", "total_net_profit", [ci(2)])]),
        [],
        [("count", "final", "order_count", [ci(0)]),
         ("sum", "final", "total_ship_cost", [ci(1)]),
         ("sum", "final", "total_net_profit", [ci(2)])])
    plan = totals

    def oracle():
        wsd, wrd, cad = ws.to_pandas(), wr.to_pandas(), ca.to_pandas()
        f = wsd[(wsd.ws_ship_date_sk >= Q95_WINDOW[0]) &
                (wsd.ws_ship_date_sk <= Q95_WINDOW[1]) &
                (wsd.ws_web_site_sk <= 2)]
        f = f.merge(cad[cad.ca_state == "IL"],
                    left_on="ws_ship_addr_sk", right_on="ca_address_sk")
        # EXISTS: some ws row of the same order with a different warehouse
        wh_sets = wsd.groupby("ws_order_number").ws_warehouse_sk \
            .agg(lambda s: set(s))
        def qualifies(row):
            whs = wh_sets.get(row.ws_order_number, set())
            return bool(whs - {row.ws_warehouse_sk})
        if len(f):
            f = f[f.apply(qualifies, axis=1)]
        f = f[~f.ws_order_number.isin(set(wrd.wr_order_number))]
        # SQL SUM over zero rows is NULL, not pandas' 0.0
        return pd.DataFrame({
            "order_count": [f.ws_order_number.nunique()],
            "total_ship_cost": [f.ws_ext_ship_cost.sum() if len(f)
                                else None],
            "total_net_profit": [f.ws_net_profit.sum() if len(f)
                                 else None]})

    return plan, oracle


QUERIES: Dict[str, Tuple[Callable, list]] = {
    "q01": (q01, ["store_returns", "date_dim", "store", "customer"]),
    "q06": (q06, ["store_sales", "item"]),
    "q17": (q17, ["store_sales", "store_returns", "catalog_sales",
                  "store", "item"]),
    "q18": (q18, ["catalog_sales", "customer_demographics", "customer",
                  "customer_address", "item"]),
    "q95": (q95, ["web_sales", "web_returns", "customer_address"]),
}


# ---------------------------------------------------------------------------
# round-3 breadth: brand-revenue family, ratio-over-window family,
# cumulative windows, rollup+rank, and a Generate-bearing workload
# (VERDICT r2 #9: 15+ queries, rows 18/19 exercised by the harness)
# ---------------------------------------------------------------------------

def _brand_revenue(paths, tables, partitions, moy, price_col,
                   group_cols=("i_brand_id", "i_brand")):
    """The q03/q42/q52/q55 shape: dd(moy) ⨝ ss ⨝ item, revenue by brand."""
    ss, it, dd = tables["store_sales"], tables["item"], tables["date_dim"]

    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_moy"), lit(moy, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    groups = [(c("d_year"), "d_year")] + \
        [(c(g), g) for g in group_cols]
    rev = _partial_final(j_it, groups,
                         [("sum", "revenue", [c(price_col)])], partitions)
    single = exchange(rev, [ci(0)], 1)
    n = len(groups)
    plan = sort_limit(single, [(ci(n), True), (ci(1), False)], 100)

    def oracle():
        ssd, itd, ddd = (ss.to_pandas(), it.to_pandas(), dd.to_pandas())
        m = ssd.merge(ddd[ddd.d_moy == moy], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        out = (m.groupby(["d_year"] + list(group_cols), as_index=False)
               .agg(revenue=(price_col, "sum")))
        out = out.sort_values(["revenue", list(out.columns)[1]],
                              ascending=[False, True])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q03(paths, tables, partitions: int = 2):
    return _brand_revenue(paths, tables, partitions, 11,
                          "ss_ext_sales_price")


def q42(paths, tables, partitions: int = 2):
    return _brand_revenue(paths, tables, partitions, 12,
                          "ss_ext_sales_price", ("i_category",))


def q52(paths, tables, partitions: int = 2):
    return _brand_revenue(paths, tables, partitions, 12,
                          "ss_ext_sales_price")


def q55(paths, tables, partitions: int = 2):
    return _brand_revenue(paths, tables, partitions, 11,
                          "ss_sales_price")


def q07(paths, tables, partitions: int = 4):
    """ss ⨝ cd(gender/edu) ⨝ dd ⨝ item ⨝ promotion, avg stats by item."""
    ss, cd, it = (tables["store_sales"], tables["customer_demographics"],
                  tables["item"])
    pr, dd = tables["promotion"], tables["date_dim"]

    cd_f = filter_(scan(paths, tables, "customer_demographics"),
                   binop("==", c("cd_gender"), lit("M", "utf8")),
                   binop("==", c("cd_education_status"),
                         lit("College", "utf8")))
    j_cd = join("broadcast_join", scan(paths, tables, "store_sales"),
                cd_f, [c("ss_cdemo_sk")], [c("cd_demo_sk")])
    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(2000, "int32")))
    j_dd = join("broadcast_join", j_cd, dd_f,
                [c("ss_sold_date_sk")], [c("d_date_sk")])
    pr_f = filter_(scan(paths, tables, "promotion"),
                   binop("==", c("p_channel_email"), lit("N", "utf8")))
    j_pr = join("broadcast_join", j_dd, pr_f,
                [c("ss_promo_sk")], [c("p_promo_sk")])
    j_it = join("broadcast_join", j_pr, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    stats = _partial_final(
        j_it, [(c("i_item_id"), "i_item_id")],
        [("avg", "agg1", [c("ss_quantity")]),
         ("avg", "agg2", [c("ss_list_price")]),
         ("avg", "agg3", [c("ss_coupon_amt")]),
         ("avg", "agg4", [c("ss_sales_price")])], partitions)
    single = exchange(stats, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        ssd, cdd, itd = ss.to_pandas(), cd.to_pandas(), it.to_pandas()
        prd, ddd = pr.to_pandas(), dd.to_pandas()
        m = ssd.merge(cdd[(cdd.cd_gender == "M") &
                          (cdd.cd_education_status == "College")],
                      left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(ddd[ddd.d_year == 2000], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(prd[prd.p_channel_email == "N"],
                    left_on="ss_promo_sk", right_on="p_promo_sk")
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        out = m.groupby("i_item_id", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
            agg3=("ss_coupon_amt", "mean"),
            agg4=("ss_sales_price", "mean"))
        return out.sort_values("i_item_id")[:100].reset_index(drop=True)

    return plan, oracle


def _ratio_over_window(paths, tables, partitions, fact, date_col,
                       item_col, price_col, window):
    """The q12/q20/q98 shape: revenue by item, plus each item's share of
    its class total via an UNBOUNDED window aggregate."""
    ft, it = tables[fact], tables["item"]

    f = filter_(scan(paths, tables, fact),
                binop(">=", c(date_col), lit(window[0])),
                binop("<=", c(date_col), lit(window[1])))
    j = join("broadcast_join", f, scan(paths, tables, "item"),
             [c(item_col)], [c("i_item_sk")])
    rev = _partial_final(
        j, [(c("i_item_id"), "i_item_id"), (c("i_class"), "i_class")],
        [("sum", "itemrevenue", [c(price_col)])], partitions)
    # co-locate each class in one partition, sort, whole-partition window
    ex = exchange(rev, [ci(1)], 1)
    srt = {"kind": "sort", "input": ex,
           "specs": [{"expr": ci(1), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(0), "descending": False,
                      "nulls_first": True}]}
    win = {"kind": "window", "input": srt,
           "functions": [{"kind": "agg", "fn": "sum",
                          "name": "classrevenue", "running": False,
                          "args": [ci(2)]}],
           "partition_by": [ci(1)], "order_by": []}
    plan = project(
        win,
        [ci(0), ci(1), ci(2),
         binop("/", binop("*", ci(2), lit(100.0, "float64")), ci(3))],
        ["i_item_id", "i_class", "itemrevenue", "revenueratio"])

    def oracle():
        fd, itd = ft.to_pandas(), it.to_pandas()
        m = fd[(fd[date_col] >= window[0]) & (fd[date_col] <= window[1])]
        m = m.merge(itd, left_on=item_col, right_on="i_item_sk")
        out = (m.groupby(["i_item_id", "i_class"], as_index=False)
               .agg(itemrevenue=(price_col, "sum")))
        out["revenueratio"] = out.itemrevenue * 100.0 / \
            out.groupby("i_class").itemrevenue.transform("sum")
        return out.reset_index(drop=True)

    return plan, oracle


Q12_WINDOW = _day_range(730, 760)


def q12(paths, tables, partitions: int = 2):
    return _ratio_over_window(paths, tables, partitions, "web_sales",
                              "ws_sold_date_sk", "ws_item_sk",
                              "ws_ext_sales_price", Q12_WINDOW)


def q20(paths, tables, partitions: int = 2):
    return _ratio_over_window(paths, tables, partitions, "catalog_sales",
                              "cs_sold_date_sk", "cs_item_sk",
                              "cs_sales_price", Q12_WINDOW)


def q98(paths, tables, partitions: int = 2):
    return _ratio_over_window(paths, tables, partitions, "store_sales",
                              "ss_sold_date_sk", "ss_item_sk",
                              "ss_ext_sales_price", Q12_WINDOW)


Q51_WINDOW = _day_range(700, 760)


def q51(paths, tables, partitions: int = 2):
    """Cumulative web vs store revenue per item/date (FULL OUTER join of
    two windowed streams — the q51 shape with max-over-cumulative)."""
    ws, ss = tables["web_sales"], tables["store_sales"]

    def daily(fact, date_col, item_col, price_col):
        f = filter_(scan(paths, tables, fact),
                    binop(">=", c(date_col), lit(Q51_WINDOW[0])),
                    binop("<=", c(date_col), lit(Q51_WINDOW[1])))
        d = _partial_final(
            f, [(c(item_col), "item_sk"), (c(date_col), "date_sk")],
            [("sum", "rev", [c(price_col)])], partitions)
        ex = exchange(d, [ci(0)], 1)
        srt = {"kind": "sort", "input": ex,
               "specs": [{"expr": ci(0), "descending": False,
                          "nulls_first": True},
                         {"expr": ci(1), "descending": False,
                          "nulls_first": True}]}
        return {"kind": "window", "input": srt,
                "functions": [{"kind": "agg", "fn": "sum",
                               "name": "cume", "running": True,
                               "args": [ci(2)]}],
                "partition_by": [ci(0)],
                "order_by": [{"expr": ci(1), "descending": False,
                              "nulls_first": True}]}

    web = daily("web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_ext_sales_price")
    store = daily("store_sales", "ss_sold_date_sk", "ss_item_sk",
                  "ss_ext_sales_price")
    j = join("sort_merge_join", web, store, [ci(0), ci(1)],
             [ci(0), ci(1)], jt="full")
    flt = filter_(j, binop(">", ci(3), {"kind": "coalesce",
                                        "args": [ci(7), lit(0.0,
                                                            "float64")]}))
    plan = sort_limit(flt, [(ci(0), False), (ci(1), False)], 100)

    def oracle():
        wsd, ssd = ws.to_pandas(), ss.to_pandas()

        def cume(fd, date_col, item_col, price_col):
            f = fd[(fd[date_col] >= Q51_WINDOW[0]) &
                   (fd[date_col] <= Q51_WINDOW[1])]
            d = (f.groupby([item_col, date_col], as_index=False)
                 .agg(rev=(price_col, "sum"))
                 .rename(columns={item_col: "item_sk",
                                  date_col: "date_sk"}))
            d = d.sort_values(["item_sk", "date_sk"])
            d["cume"] = d.groupby("item_sk").rev.cumsum()
            return d

        w = cume(wsd, "ws_sold_date_sk", "ws_item_sk",
                 "ws_ext_sales_price").rename(columns={
                     "item_sk": "item_w", "date_sk": "date_w",
                     "rev": "rev_w", "cume": "cume_w"})
        s = cume(ssd, "ss_sold_date_sk", "ss_item_sk",
                 "ss_ext_sales_price").rename(columns={
                     "item_sk": "item_s", "date_sk": "date_s",
                     "rev": "rev_s", "cume": "cume_s"})
        # FULL join keeps both key sets (8 columns), like the engine plan
        m = w.merge(s, left_on=["item_w", "date_w"],
                    right_on=["item_s", "date_s"], how="outer")
        m = m[m.cume_w > m.cume_s.fillna(0.0)]
        out = m[["item_w", "date_w", "rev_w", "cume_w",
                 "item_s", "date_s", "rev_s", "cume_s"]]
        out = out.sort_values(["item_w", "date_w"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q67(paths, tables, partitions: int = 2):
    """Rollup(category, class) of store revenue + rank() within category
    by revenue desc, rank <= 10 (the q67 shape: Expand + window rank)."""
    ss, it, dd = tables["store_sales"], tables["item"], tables["date_dim"]

    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(1999, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    nul = {"kind": "literal", "value": None, "type": {"id": "utf8"}}
    projections = []
    for kept, gid in ((2, 0), (1, 1), (0, 3)):
        row = [c("i_category") if kept >= 1 else nul,
               c("i_class") if kept >= 2 else nul,
               lit(gid), c("ss_ext_sales_price")]
        projections.append(row)
    expanded = {"kind": "expand", "input": j_it,
                "projections": projections,
                "names": ["i_category", "i_class", "g_id",
                          "ss_ext_sales_price"]}
    rev = _partial_final(
        expanded,
        [(ci(0), "i_category"), (ci(1), "i_class"), (ci(2), "g_id")],
        [("sum", "sumsales", [ci(3)])], partitions)
    ex = exchange(rev, [ci(0)], 1)
    srt = {"kind": "sort", "input": ex,
           "specs": [{"expr": ci(0), "descending": False,
                      "nulls_first": True},
                     {"expr": ci(3), "descending": True,
                      "nulls_first": False}]}
    win = {"kind": "window", "input": srt,
           "functions": [{"kind": "rank", "name": "rk"}],
           "partition_by": [ci(0)],
           "order_by": [{"expr": ci(3), "descending": True,
                         "nulls_first": False}]}
    flt = filter_(win, binop("<=", ci(4), lit(10)))
    plan = sort_limit(flt, [(ci(0), False), (ci(4), False)], 100)

    def oracle():
        ssd, itd, ddd = ss.to_pandas(), it.to_pandas(), dd.to_pandas()
        m = ssd.merge(ddd[ddd.d_year == 1999],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        frames = []
        for kept, gid in ((2, 0), (1, 1), (0, 3)):
            keys = ["i_category", "i_class"][:kept] if kept else []
            if keys:
                g = m.groupby(keys, as_index=False, dropna=False).agg(
                    sumsales=("ss_ext_sales_price", "sum"))
            else:
                g = pd.DataFrame(
                    {"sumsales": [m.ss_ext_sales_price.sum()]})
            for col_name in ["i_category", "i_class"][kept:]:
                g[col_name] = None
            g["g_id"] = gid
            frames.append(g[["i_category", "i_class", "g_id",
                             "sumsales"]])
        allf = pd.concat(frames, ignore_index=True)
        allf["rk"] = (allf.sort_values("sumsales", ascending=False)
                      .groupby("i_category", dropna=False)
                      .sumsales.rank(method="min", ascending=False))
        allf = allf[allf.rk <= 10]
        out = allf.sort_values(["i_category", "rk"])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def gq1(paths, tables, partitions: int = 2):
    """Generate-bearing workload: posexplode the clickstream list column,
    join items, count clicks by category (exercises inventory row 19
    through the integration harness)."""
    wc, it = tables["web_clickstreams"], tables["item"]

    gen = {"kind": "generate",
           "input": scan(paths, tables, "web_clickstreams"),
           "generator": {"kind": "posexplode",
                         "child": c("wc_clicked_items"), "outer": False},
           "required_cols": [0]}
    renamed = {"kind": "rename_columns", "input": gen,
               "names": ["wc_session_sk", "pos", "item_sk"]}
    j = join("broadcast_join", renamed, scan(paths, tables, "item"),
             [ci(2)], [c("i_item_sk")])
    counted = _partial_final(
        j, [(c("i_category"), "i_category")],
        [("count", "clicks", [ci(0)])], partitions)
    single = exchange(counted, [ci(0)], 1)
    plan = sort_limit(single, [(ci(0), False)], 100)

    def oracle():
        wcd = wc.to_pandas()
        itd = it.to_pandas()
        rows = []
        for _sess, items in zip(wcd.wc_session_sk,
                                wcd.wc_clicked_items):
            if items is not None:
                rows.extend(items)
        e = pd.DataFrame({"item_sk": rows})
        m = e.merge(itd, left_on="item_sk", right_on="i_item_sk")
        out = (m.groupby("i_category", as_index=False)
               .agg(clicks=("item_sk", "count"))
               .sort_values("i_category"))
        return out.reset_index(drop=True)

    return plan, oracle


def q19(paths, tables, partitions: int = 2):
    """Brand revenue through customer/address joins (q19 shape without
    the manager filter; exercises the 4-join chain)."""
    ss, it, dd = tables["store_sales"], tables["item"], tables["date_dim"]
    cu, ca, st = (tables["customer"], tables["customer_address"],
                  tables["store"])

    dd_f = filter_(scan(paths, tables, "date_dim"),
                   binop("==", c("d_year"), lit(1999, "int32")),
                   binop("==", c("d_moy"), lit(11, "int32")))
    j_dd = join("broadcast_join", scan(paths, tables, "store_sales"),
                dd_f, [c("ss_sold_date_sk")], [c("d_date_sk")])
    j_it = join("broadcast_join", j_dd, scan(paths, tables, "item"),
                [c("ss_item_sk")], [c("i_item_sk")])
    cs_ex = exchange(j_it, [c("ss_customer_sk")], partitions)
    cu_ex = exchange(scan(paths, tables, "customer"),
                     [c("c_customer_sk")], partitions)
    j_cu = join("hash_join", cs_ex, cu_ex, [c("ss_customer_sk")],
                [c("c_customer_sk")])
    j_ca = join("broadcast_join", j_cu,
                scan(paths, tables, "customer_address"),
                [c("c_current_addr_sk")], [c("ca_address_sk")])
    j_st = join("broadcast_join", j_ca, scan(paths, tables, "store"),
                [c("ss_store_sk")], [c("s_store_sk")])
    rev = _partial_final(
        j_st, [(c("i_brand_id"), "brand_id"), (c("i_brand"), "brand")],
        [("sum", "ext_price", [c("ss_ext_sales_price")])], partitions)
    single = exchange(rev, [ci(0)], 1)
    plan = sort_limit(single, [(ci(2), True), (ci(0), False)], 100)

    def oracle():
        ssd, itd, ddd = ss.to_pandas(), it.to_pandas(), dd.to_pandas()
        cud, cad, std = cu.to_pandas(), ca.to_pandas(), st.to_pandas()
        m = ssd.merge(ddd[(ddd.d_year == 1999) & (ddd.d_moy == 11)],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(itd, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(cud, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        m = m.merge(cad, left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        m = m.merge(std, left_on="ss_store_sk", right_on="s_store_sk")
        out = (m.groupby(["i_brand_id", "i_brand"], as_index=False)
               .agg(ext_price=("ss_ext_sales_price", "sum")))
        out = out.sort_values(["ext_price", "i_brand_id"],
                              ascending=[False, True])[:100]
        return out.reset_index(drop=True)

    return plan, oracle


QUERIES.update({
    "q03": (q03, ["store_sales", "item", "date_dim"]),
    "q07": (q07, ["store_sales", "customer_demographics", "item",
                  "promotion", "date_dim"]),
    "q12": (q12, ["web_sales", "item"]),
    "q19": (q19, ["store_sales", "item", "date_dim", "customer",
                  "customer_address", "store"]),
    "q20": (q20, ["catalog_sales", "item"]),
    "q42": (q42, ["store_sales", "item", "date_dim"]),
    "q51": (q51, ["web_sales", "store_sales"]),
    "q52": (q52, ["store_sales", "item", "date_dim"]),
    "q55": (q55, ["store_sales", "item", "date_dim"]),
    "q67": (q67, ["store_sales", "item", "date_dim"]),
    "q98": (q98, ["store_sales", "item"]),
    "gq1": (gq1, ["web_clickstreams", "item"]),
})
