"""TPC-DS progression queries as operator plans (BASELINE.md configs).

Parity role: dev/auron-it query set.  Queries build against the synthetic
tables of tpcds_data.py; each returns (plan, oracle) where `oracle` computes
the expected result with pandas — the QueryRunner compares them cell-wise
(comparison/QueryResultComparator.scala analog).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from blaze_tpu.exprs import BinaryExpr, and_, col, lit
from blaze_tpu.ops import (AggExec, AggMode, FilterExec, LimitExec,
                           MemoryScanExec, ProjectExec, SortExec,
                           SortMergeJoinExec, BroadcastJoinExec, JoinType,
                           make_agg)
from blaze_tpu.shuffle import HashPartitioning, LocalShuffleExchange


def _scan(t: pa.Table, partitions=2, batch_rows=8192):
    return MemoryScanExec.from_arrow(t, num_partitions=partitions,
                                     batch_rows=batch_rows)


def q01(tables: Dict[str, pa.Table], partitions: int = 2):
    """TPC-DS q01: customers returning more than 1.2x their store's average
    (correlated subquery decorrelated into an avg-by-store join)."""
    sr, dd, st, cu = (tables["store_returns"], tables["date_dim"],
                      tables["store"], tables["customer"])

    # ctr: returns joined to year-2000 dates, grouped by (customer, store)
    dd_flt = FilterExec(_scan(dd, 1),
                        [BinaryExpr("==", col(1, "d_year"), lit(2000))])
    sr_dd = BroadcastJoinExec(
        _scan(sr, partitions), dd_flt,
        [col(0, "sr_returned_date_sk")], [col(0, "d_date_sk")],
        JoinType.INNER, build_side="right")
    # columns: sr_returned_date_sk, sr_customer_sk, sr_store_sk,
    #          sr_return_amt, sr_ticket_number, d_date_sk, d_year, ...
    ctr_partial = AggExec(sr_dd,
                          [(col(1, "sr_customer_sk"), "ctr_customer_sk"),
                           (col(2, "sr_store_sk"), "ctr_store_sk")],
                          [(make_agg("sum", [col(3)]), AggMode.PARTIAL,
                            "ctr_total_return")])
    ctr_ex = LocalShuffleExchange(
        ctr_partial, HashPartitioning([col(0), col(1)], partitions))
    ctr = AggExec(ctr_ex,
                  [(col(0, "ctr_customer_sk"), "ctr_customer_sk"),
                   (col(1, "ctr_store_sk"), "ctr_store_sk")],
                  [(make_agg("sum", [col(2)]), AggMode.PARTIAL_MERGE,
                    "ctr_total_return")])

    # avg(ctr_total_return) by store
    avg_ex = LocalShuffleExchange(ctr, HashPartitioning([col(1)], partitions))
    avg_by_store = AggExec(
        avg_ex, [(col(1, "ctr_store_sk"), "avg_store_sk")],
        [(make_agg("avg", [col(2)]), AggMode.COMPLETE, "avg_return")])

    # ctr join avg_by_store on store, filter > 1.2*avg
    ctr2 = LocalShuffleExchange(ctr, HashPartitioning([col(1)], partitions))
    joined = SortMergeJoinExec(ctr2, avg_by_store,
                               [col(1)], [col(0)], JoinType.INNER)
    # cols: ctr_customer_sk, ctr_store_sk, ctr_total_return,
    #       avg_store_sk, avg_return
    flt = FilterExec(joined, [BinaryExpr(
        ">", col(2), BinaryExpr("*", col(4), lit(1.2)))])

    # join store (s_state = 'TN'), join customer, project id
    st_flt = FilterExec(_scan(st, 1),
                        [BinaryExpr("==", col(1, "s_state"), lit("TN"))])
    j_store = BroadcastJoinExec(flt, st_flt, [col(1)], [col(0)],
                                JoinType.INNER, build_side="right")
    j_cust = BroadcastJoinExec(
        j_store, _scan(cu, 1), [col(0)], [col(0, "c_customer_sk")],
        JoinType.INNER, build_side="right")
    # c_customer_id is at offset: flt(5 cols) + store(3) + customer: sk,id,addr
    id_idx = 5 + 3 + 1
    proj = ProjectExec(j_cust, [col(id_idx)], ["c_customer_id"])
    single = LocalShuffleExchange(proj, HashPartitioning([col(0)], 1))
    plan = LimitExec(SortExec(single, [(col(0), False, True)], fetch=100),
                     100)

    def oracle():
        srd = sr.to_pandas()
        ddd = dd.to_pandas()
        std = st.to_pandas()
        cud = cu.to_pandas()
        m = srd.merge(ddd[ddd.d_year == 2000], left_on="sr_returned_date_sk",
                      right_on="d_date_sk")
        ctr = (m.dropna(subset=["sr_customer_sk"])
               .groupby(["sr_customer_sk", "sr_store_sk"], as_index=False)
               .sr_return_amt.sum()
               .rename(columns={"sr_return_amt": "ctr_total"}))
        avg = ctr.groupby("sr_store_sk", as_index=False).ctr_total.mean() \
            .rename(columns={"ctr_total": "avg_return"})
        j = ctr.merge(avg, on="sr_store_sk")
        j = j[j.ctr_total > 1.2 * j.avg_return]
        j = j.merge(std[std.s_state == "TN"], left_on="sr_store_sk",
                    right_on="s_store_sk")
        j = j.merge(cud, left_on="sr_customer_sk", right_on="c_customer_sk")
        out = j[["c_customer_id"]].sort_values("c_customer_id")[:100]
        return out.reset_index(drop=True)

    return plan, oracle


def q06_like(tables: Dict[str, pa.Table], partitions: int = 4):
    """q06 shape (BASELINE config #2): sales joined to items above the
    category-average price, counted by state-ish key — hash-join +
    group-by over `partitions` partitions."""
    ss, it = tables["store_sales"], tables["item"]

    # avg price per category
    cat_avg = AggExec(_scan(it, 1), [(col(1, "i_category"), "cat")],
                      [(make_agg("avg", [col(2)]), AggMode.COMPLETE,
                        "avg_price")])
    # items priced > 1.2x their category average
    it_j = BroadcastJoinExec(_scan(it, 1), cat_avg,
                             [col(1)], [col(0)], JoinType.INNER,
                             build_side="right")
    it_flt = FilterExec(it_j, [BinaryExpr(
        ">", col(2), BinaryExpr("*", col(4), lit(1.2)))])

    ss_j = BroadcastJoinExec(_scan(ss, partitions), it_flt,
                             [col(3, "ss_item_sk")], [col(0, "i_item_sk")],
                             JoinType.INNER, build_side="right")
    partial = AggExec(ss_j, [(col(2, "ss_store_sk"), "store")],
                      [(make_agg("count", [col(0)]), AggMode.PARTIAL, "cnt")])
    ex = LocalShuffleExchange(partial, HashPartitioning([col(0)], partitions))
    final = AggExec(ex, [(col(0, "store"), "store")],
                    [(make_agg("sum", [col(1)]), AggMode.PARTIAL_MERGE,
                      "cnt")])
    single = LocalShuffleExchange(final, HashPartitioning([col(0)], 1))
    plan = SortExec(single, [(col(0), False, True)])

    def oracle():
        ssd = ss.to_pandas()
        itd = it.to_pandas()
        avg = itd.groupby("i_category", as_index=False) \
            .i_current_price.mean().rename(
                columns={"i_current_price": "avg_price"})
        j = itd.merge(avg, on="i_category")
        sel = j[j.i_current_price > 1.2 * j.avg_price]
        m = ssd.merge(sel, left_on="ss_item_sk", right_on="i_item_sk")
        out = (m.groupby("ss_store_sk", as_index=False)
               .agg(cnt=("ss_sold_date_sk", "count"))
               .rename(columns={"ss_store_sk": "store"})
               .sort_values("store"))
        return out.reset_index(drop=True)

    return plan, oracle


QUERIES: Dict[str, Tuple[Callable, list]] = {
    "q01": (q01, ["store_returns", "date_dim", "store", "customer"]),
    "q06": (q06_like, ["store_sales", "item"]),
}
