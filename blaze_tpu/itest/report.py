"""Per-query speedup report: run every itest query, compare against the
pandas oracle, and print the TPCDSSuite-style table.

Parity: dev/auron-it Main.scala/QueryRunner.scala (each query runs
baseline and accelerated, QueryResultComparator checks results, per-query
speedup is logged).  Usage:

    python -m blaze_tpu.itest.report [--scale 0.2] [--partitions 2]
                                     [--queries q01,q06,...] [--wire]

`--wire` routes execution through the DagScheduler (per-task protobuf
TaskDefinitions + shuffle files) instead of the in-process planner path.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time


def run_report(scale: float, partitions: int, names=None,
               wire: bool = False, budget_bytes: int = 4 << 30):
    import pandas as pd

    # engine init (backend probe + placement decision) amortizes across
    # the report, not charged to whichever query happens to run first —
    # the dev/auron-it harness likewise starts one Spark session before
    # timing any query
    import os as _os
    if _os.environ.get("JAX_PLATFORMS"):
        import jax
        # the axon plugin ignores the env var; force through jax.config
        try:
            jax.config.update("jax_platforms",
                              _os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    from blaze_tpu.bridge.placement import ensure_placement
    ensure_placement()

    from blaze_tpu.itest import generate
    from blaze_tpu.itest.queries import QUERIES
    from blaze_tpu.itest.runner import compare_frames
    from blaze_tpu.itest.tpcds_data import write_parquet_splits
    from blaze_tpu.memory import MemManager
    from blaze_tpu.plan import create_plan, explain_analyze
    from blaze_tpu.plan.fused import fuse_plan

    MemManager.init(budget_bytes)
    rows = []
    for qname in sorted(names or QUERIES):
        builder, table_names = QUERIES[qname]
        tables = generate(table_names, scale=scale)
        with tempfile.TemporaryDirectory(prefix=f"blaze-it-{qname}-") \
                as tmp:
            paths = write_parquet_splits(tables, tmp, partitions)
            plan_dict, oracle = builder(paths, tables, partitions)
            t0 = time.perf_counter()
            if wire:
                # work_dir defaults to the RAM disk (stages.py); the
                # per-query tmp dir here is disk-backed
                prof = explain_analyze(plan_dict, keep_result=True,
                                       query_id=f"itest-{qname}")
                exec_mode = prof.exec_mode
            else:
                from blaze_tpu.plan.planner import collapse_filter_project
                plan = fuse_plan(collapse_filter_project(
                    create_plan(plan_dict)))
                prof = explain_analyze(plan, keep_result=True,
                                       query_id=f"itest-{qname}")
                exec_mode = "in-process"
            got_tbl = prof.result
            engine_s = time.perf_counter() - t0
            # the baseline reads the SAME parquet splits the engine
            # scans — the reference's comparison has both sides go
            # through FileScan (dev/auron-it runs two Spark sessions
            # over one parquet dataset); an oracle computing from
            # pre-loaded memory would be charged no input IO at all
            t1 = time.perf_counter()
            import pyarrow.parquet as _pq
            for _tn, _groups in paths.items():
                _pq.read_table([f for g in _groups for f in g])
            want = oracle()
            oracle_s = time.perf_counter() - t1
            got = got_tbl.to_pandas() if got_tbl.num_rows else \
                pd.DataFrame({n: [] for n in got_tbl.schema.names})
            err = compare_frames(got, want)
            mm = MemManager.get()
            rows.append({
                "query": qname, "rows": int(got_tbl.num_rows),
                "engine_s": round(engine_s, 3),
                "baseline_s": round(oracle_s, 3),
                "speedup": round(oracle_s / max(engine_s, 1e-9), 3),
                "passed": err is None, "detail": err or "",
                "scale": scale, "wire": wire, "exec_mode": exec_mode,
                "budget_bytes": mm.total,
                "spill_count": mm.total_spill_count,
                "spilled_bytes": mm.total_spilled_bytes,
                "peak_mem_bytes": mm.peak_used,
                # per-operator profile (explain_analyze), also served on
                # /profile/itest-<query> by the HTTP service
                "profile": prof.to_dict()})
            # per-query deltas, not cumulative across the report
            mm.total_spill_count = 0
            mm.total_spilled_bytes = 0
            mm.peak_used = 0
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.2)
    ap.add_argument("--partitions", type=int, default=2)
    ap.add_argument("--queries", type=str, default="")
    ap.add_argument("--wire", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--budget-mb", type=int, default=4096,
                    help="MemManager budget; set low to force spills "
                         "(VERDICT r3 #4 scale evidence)")
    args = ap.parse_args(argv)
    names = [q for q in args.queries.split(",") if q] or None
    rows = run_report(args.scale, args.partitions, names, args.wire,
                      budget_bytes=args.budget_mb << 20)
    if args.json:
        print(json.dumps(rows))
    else:
        hdr = f"{'query':6} {'rows':>8} {'engine_s':>9} " \
              f"{'baseline_s':>11} {'speedup':>8}  status"
        print(hdr)
        print("-" * len(hdr))
        for r in rows:
            status = "OK" if r["passed"] else f"FAIL {r['detail'][:50]}"
            print(f"{r['query']:6} {r['rows']:>8} {r['engine_s']:>9} "
                  f"{r['baseline_s']:>11} {r['speedup']:>8}  {status}")
        n_fail = sum(not r["passed"] for r in rows)
        print(f"\n{len(rows)} queries, {n_fail} failed")
    return 1 if any(not r["passed"] for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
