"""TPC-DS progression queries as Spark `toJSON` physical-plan JSON.

Parity role: the plan corpus the L6 converter consumes in production
(AuronConverters.scala:189 receives executed SparkPlans; here the same
trees arrive as their TreeNode.toJSON rendering, the format a thin JVM
shim emits — convert/spark.py module docstring).  These builders author
the plans in SPARK's vocabulary — FileSourceScanExec / FilterExec /
BroadcastHashJoinExec / HashAggregateExec(Partial|Final) /
ShuffleExchangeExec / ExpandExec / TakeOrderedAndProjectExec — with
Catalyst exprId-based attribute identity, exactly as Spark 3.5 serializes
them (verified against the field names NativeConverters.scala:140-213 and
AuronConverters.scala:212-271 consume).  No JVM exists in this
environment, so the corpus is synthesized rather than captured from a
live Spark; the checked-in fixtures under tests/fixtures/ pin the JSON
byte-for-byte so any converter change against the format is visible in
review.

Each builder returns (plan_json_array, oracle) where the oracle is shared
with itest/queries.py (QueryResultComparator analog).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from blaze_tpu.itest import queries as Q

CAT = "org.apache.spark.sql.catalyst.expressions."
EXEC = "org.apache.spark.sql.execution."

_ids = itertools.count(1000)


def _reset_ids() -> None:
    global _ids
    _ids = itertools.count(1000)


def _catalyst_type(t: pa.DataType) -> Any:
    if pa.types.is_int64(t):
        return "long"
    if pa.types.is_int32(t):
        return "integer"
    if pa.types.is_float64(t):
        return "double"
    if pa.types.is_float32(t):
        return "float"
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return "string"
    if pa.types.is_boolean(t):
        return "boolean"
    if pa.types.is_date32(t):
        return "date"
    if pa.types.is_list_(t):
        return {"type": "array",
                "elementType": _catalyst_type(t.value_type),
                "containsNull": True}
    raise TypeError(f"no catalyst mapping for {t}")


class A:
    """A Catalyst attribute: stable (name, dataType, exprId)."""

    def __init__(self, name: str, dt: Any, eid: Optional[int] = None):
        self.name = name
        self.dt = dt
        self.id = next(_ids) if eid is None else eid

    def ref(self) -> List[dict]:
        return [{"class": CAT + "AttributeReference", "num-children": 0,
                 "name": self.name, "dataType": self.dt, "nullable": True,
                 "metadata": {},
                 "exprId": {"product-class":
                            CAT + "ExprId", "id": self.id, "jvmId": "u"},
                 "qualifier": []}]


def lit(value, dt) -> List[dict]:
    return [{"class": CAT + "Literal", "num-children": 0,
             "value": None if value is None else str(value),
             "dataType": dt}]


def e2(cls: str, l: List[dict], r: List[dict]) -> List[dict]:
    return [{"class": CAT + cls, "num-children": 2}] + l + r


def not_(child: List[dict]) -> List[dict]:
    return [{"class": CAT + "Not", "num-children": 1}] + child


def alias(child: List[dict], a: A) -> List[dict]:
    return [{"class": CAT + "Alias", "num-children": 1, "name": a.name,
             "exprId": {"id": a.id, "jvmId": "u"}}] + child


def in_list(child: List[dict], values: List[str], dt: str) -> List[dict]:
    items = [lit(v, dt) for v in values]
    out = [{"class": CAT + "In",
            "num-children": 1 + len(items)}] + child
    for i in items:
        out += i
    return out


def sort_order(child: List[dict], desc: bool = False) -> List[dict]:
    return [{"class": CAT + "SortOrder", "num-children": 1,
             "direction": "Descending" if desc else "Ascending",
             "nullOrdering": "NullsLast" if desc else "NullsFirst"}] + child


def agg_expr(fn_cls: str, arg: Optional[List[dict]], mode: str,
             result: A) -> List[dict]:
    fn = [{"class": CAT + f"aggregate.{fn_cls}",
           "num-children": 1 if arg else 0}] + (arg or [])
    return [{"class": CAT + "aggregate.AggregateExpression",
             "num-children": 1, "mode": mode, "isDistinct": False,
             "resultId": {"id": result.id, "jvmId": "u"}}] + fn


def node(cls: str, fields: dict, children: List[List[dict]]) -> List[dict]:
    out = [{"class": EXEC + cls, "num-children": len(children), **fields}]
    for c in children:
        out += c
    return out


class Table:
    """Scan-side attribute book-keeping for one table."""

    def __init__(self, name: str, arrow: pa.Table,
                 files: List[List[str]]):
        self.name = name
        self.files = files
        self.attrs: Dict[str, A] = {
            f.name: A(f.name, _catalyst_type(f.type))
            for f in arrow.schema}

    def a(self, col: str) -> A:
        return self.attrs[col]

    def scan(self, cols: Optional[List[str]] = None) -> List[dict]:
        names = cols or list(self.attrs)
        return [{"class": EXEC + "FileSourceScanExec", "num-children": 0,
                 "output": [self.attrs[n].ref() for n in names],
                 "files": self.files}]


def filter_(cond: List[dict], child: List[dict]) -> List[dict]:
    return node("FilterExec", {"condition": [cond]}, [child])


def project(named: List[List[dict]], child: List[dict]) -> List[dict]:
    return node("ProjectExec", {"projectList": named}, [child])


def exchange(keys: List[A], n: int, child: List[dict]) -> List[dict]:
    part = [{"class": CAT + "HashPartitioning",
             "num-children": len(keys), "numPartitions": n}]
    for k in keys:
        part += k.ref()
    return node("exchange.ShuffleExchangeExec",
                {"outputPartitioning": part}, [child])


def single_exchange(child: List[dict]) -> List[dict]:
    return node("exchange.ShuffleExchangeExec",
                {"outputPartitioning": [
                    {"class": CAT + "SinglePartition$",
                     "num-children": 0}]}, [child])


def bcast(child: List[dict]) -> List[dict]:
    return node("exchange.BroadcastExchangeExec", {}, [child])


def sort(keys: List[A], child: List[dict], desc: bool = False
         ) -> List[dict]:
    return node("SortExec",
                {"sortOrder": [sort_order(k.ref(), desc) for k in keys]},
                [child])


def _join(cls: str, lkeys: List[A], rkeys: List[A], left: List[dict],
          right: List[dict], jt: str = "Inner",
          build: Optional[str] = "BuildRight",
          cond: Optional[List[dict]] = None) -> List[dict]:
    fields: Dict[str, Any] = {
        "leftKeys": [k.ref() for k in lkeys],
        "rightKeys": [k.ref() for k in rkeys],
        "joinType": jt}
    if build is not None:
        fields["buildSide"] = build
    if cond is not None:
        fields["condition"] = [cond]
    return node(cls, fields, [left, right])


def bhj(lkeys, rkeys, left, right, jt="Inner", cond=None) -> List[dict]:
    return _join("joins.BroadcastHashJoinExec", lkeys, rkeys, left,
                 bcast(right), jt=jt, cond=cond)


def shj(lkeys, rkeys, left, right, jt="Inner", cond=None) -> List[dict]:
    return _join("joins.ShuffledHashJoinExec", lkeys, rkeys, left, right,
                 jt=jt, cond=cond)


def smj(lkeys, rkeys, left, right, jt="Inner", cond=None) -> List[dict]:
    return _join("joins.SortMergeJoinExec", lkeys, rkeys,
                 sort(lkeys, left), sort(rkeys, right), jt=jt, build=None,
                 cond=cond)


def hash_agg(groups: List[A],
             aggs: List[Tuple[str, Optional[List[dict]], str, A]],
             child: List[dict]) -> List[dict]:
    return node("aggregate.HashAggregateExec",
                {"groupingExpressions": [g.ref() for g in groups],
                 "aggregateExpressions": [
                     agg_expr(fn, arg, mode, res)
                     for fn, arg, mode, res in aggs]},
                [child])


def partial_final(groups: List[A],
                  fns: List[Tuple[str, List[dict], A]],
                  partitions: int, child: List[dict],
                  with_exchange: bool = True) -> List[dict]:
    """The Partial -> (exchange) -> Final pair Spark emits."""
    partial = hash_agg(groups, [(fn, arg, "Partial", res)
                                for fn, arg, res in fns], child)
    mid = exchange(groups, partitions, partial) if with_exchange \
        else partial
    return hash_agg(groups, [(fn, None, "Final", res)
                             for fn, _arg, res in fns], mid)


def window_rank(rk: A, part_by: List[A],
                order: List[Tuple[A, bool]], child: List[dict]
                ) -> List[dict]:
    """window.WindowExec with one Alias(WindowExpression(Rank,
    WindowSpecDefinition)) — the rank-family shape Spark 3.5 serializes
    (AuronConverters window path)."""
    rank_fn = [{"class": CAT + "Rank", "num-children": 0}]
    spec = [{"class": CAT + "WindowSpecDefinition", "num-children": 0}]
    wex = [{"class": CAT + "WindowExpression",
            "num-children": 2}] + rank_fn + spec
    return node("window.WindowExec",
                {"windowExpression": [alias(wex, rk)],
                 "partitionSpec": [[a.ref()[0]] for a in part_by],
                 "orderSpec": [sort_order(a.ref(), desc)
                               for a, desc in order]},
                [child])


def take_ordered(limit: int, keys: List[A], proj: List[A],
                 child: List[dict]) -> List[dict]:
    return node("TakeOrderedAndProjectExec",
                {"limit": limit,
                 "sortOrder": [sort_order(k.ref()) for k in keys],
                 "projectList": [p.ref() for p in proj]},
                [child])


# ===========================================================================
# queries — structures mirror itest/queries.py (oracles are shared)
# ===========================================================================

def q01(paths, tables, partitions: int = 2):
    _reset_ids()
    sr = Table("store_returns", tables["store_returns"],
               paths["store_returns"])
    dd = Table("date_dim", tables["date_dim"], paths["date_dim"])
    st = Table("store", tables["store"], paths["store"])
    cu = Table("customer", tables["customer"], paths["customer"])

    dd_flt = filter_(e2("EqualTo", dd.a("d_year").ref(),
                        lit(2000, "integer")), dd.scan())
    sr_dd = bhj([sr.a("sr_returned_date_sk")], [dd.a("d_date_sk")],
                sr.scan(), dd_flt)

    total = A("ctr_total_return", "double")
    ctr = partial_final(
        [sr.a("sr_customer_sk"), sr.a("sr_store_sk")],
        [("Sum", sr.a("sr_return_amt").ref(), total)],
        partitions, sr_dd)

    avg_ret = A("avg_return", "double")
    avg_by_store = partial_final(
        [sr.a("sr_store_sk")], [("Average", total.ref(), avg_ret)],
        partitions,
        exchange([sr.a("sr_store_sk")], partitions, ctr),
        with_exchange=False)

    ctr2 = exchange([sr.a("sr_store_sk")], partitions, ctr)
    joined = smj([sr.a("sr_store_sk")], [sr.a("sr_store_sk")],
                 ctr2, avg_by_store)
    flt = filter_(e2("GreaterThan", total.ref(),
                     e2("Multiply", avg_ret.ref(),
                        lit(1.2, "double"))), joined)
    st_flt = filter_(e2("EqualTo", st.a("s_state").ref(),
                        lit("TN", "string")), st.scan())
    j_store = bhj([sr.a("sr_store_sk")], [st.a("s_store_sk")], flt,
                  st_flt)
    j_cust = bhj([sr.a("sr_customer_sk")], [cu.a("c_customer_sk")],
                 j_store, cu.scan())
    cid = cu.a("c_customer_id")
    plan = take_ordered(100, [cid], [cid],
                        project([cid.ref()], j_cust))

    _plan, oracle = Q.q01(paths, tables, partitions)
    return plan, oracle


def q06(paths, tables, partitions: int = 4):
    _reset_ids()
    ss = Table("store_sales", tables["store_sales"],
               paths["store_sales"])
    it = Table("item", tables["item"], paths["item"])
    it2 = Table("item", tables["item"], paths["item"])  # second scan

    avg_price = A("avg_price", "double")
    cat_avg = partial_final(
        [it2.a("i_category")],
        [("Average", it2.a("i_current_price").ref(), avg_price)],
        partitions, it2.scan(), with_exchange=False)
    it_j = bhj([it.a("i_category")], [it2.a("i_category")], it.scan(),
               cat_avg)
    it_flt = filter_(e2("GreaterThan", it.a("i_current_price").ref(),
                        e2("Multiply", avg_price.ref(),
                           lit(1.2, "double"))), it_j)
    ss_j = bhj([ss.a("ss_item_sk")], [it.a("i_item_sk")], ss.scan(),
               it_flt)
    cnt = A("cnt", "long")
    counted = partial_final(
        [ss.a("ss_store_sk")],
        [("Count", ss.a("ss_sold_date_sk").ref(), cnt)],
        partitions, ss_j)
    plan = sort([ss.a("ss_store_sk")], single_exchange(counted))

    _plan, oracle = Q.q06(paths, tables, partitions)
    return plan, oracle


def q17(paths, tables, partitions: int = 4):
    _reset_ids()
    ss = Table("store_sales", tables["store_sales"],
               paths["store_sales"])
    sr = Table("store_returns", tables["store_returns"],
               paths["store_returns"])
    cs = Table("catalog_sales", tables["catalog_sales"],
               paths["catalog_sales"])
    st = Table("store", tables["store"], paths["store"])
    it = Table("item", tables["item"], paths["item"])

    def window(tbl, col, lo, hi):
        return filter_(
            e2("And",
               e2("GreaterThanOrEqual", tbl.a(col).ref(),
                  lit(lo, "long")),
               e2("LessThanOrEqual", tbl.a(col).ref(),
                  lit(hi, "long"))), tbl.scan())

    ss_f = window(ss, "ss_sold_date_sk", *Q.SS_WINDOW)
    sr_f = window(sr, "sr_returned_date_sk", *Q.SR_CS_WINDOW)
    cs_f = window(cs, "cs_sold_date_sk", *Q.SR_CS_WINDOW)

    ss_ex = exchange([ss.a("ss_ticket_number"), ss.a("ss_item_sk")],
                     partitions, ss_f)
    sr_ex = exchange([sr.a("sr_ticket_number"), sr.a("sr_item_sk")],
                     partitions, sr_f)
    ss_sr = shj([ss.a("ss_ticket_number"), ss.a("ss_item_sk")],
                [sr.a("sr_ticket_number"), sr.a("sr_item_sk")],
                ss_ex, sr_ex)

    left_ex = exchange([sr.a("sr_customer_sk"), sr.a("sr_item_sk")],
                       partitions, ss_sr)
    cs_ex = exchange([cs.a("cs_bill_customer_sk"), cs.a("cs_item_sk")],
                     partitions, cs_f)
    three = shj([sr.a("sr_customer_sk"), sr.a("sr_item_sk")],
                [cs.a("cs_bill_customer_sk"), cs.a("cs_item_sk")],
                left_ex, cs_ex)

    j_it = bhj([ss.a("ss_item_sk")], [it.a("i_item_sk")], three,
               it.scan())
    j_st = bhj([ss.a("ss_store_sk")], [st.a("s_store_sk")], j_it,
               st.scan())

    res = [A("store_sales_cnt", "long"), A("store_sales_avg", "double"),
           A("store_returns_cnt", "long"),
           A("store_returns_avg", "double"),
           A("catalog_sales_cnt", "long"),
           A("catalog_sales_avg", "double")]
    stats = partial_final(
        [it.a("i_item_id"), st.a("s_state")],
        [("Count", ss.a("ss_quantity").ref(), res[0]),
         ("Average", ss.a("ss_quantity").ref(), res[1]),
         ("Count", sr.a("sr_return_quantity").ref(), res[2]),
         ("Average", sr.a("sr_return_quantity").ref(), res[3]),
         ("Count", cs.a("cs_quantity").ref(), res[4]),
         ("Average", cs.a("cs_quantity").ref(), res[5])],
        partitions, j_st)
    keys = [it.a("i_item_id"), st.a("s_state")]
    plan = take_ordered(100, keys, keys + res, stats)

    _plan, oracle = Q.q17(paths, tables, partitions)
    return plan, oracle


def q18(paths, tables, partitions: int = 4):
    _reset_ids()
    cs = Table("catalog_sales", tables["catalog_sales"],
               paths["catalog_sales"])
    cd = Table("customer_demographics", tables["customer_demographics"],
               paths["customer_demographics"])
    cu = Table("customer", tables["customer"], paths["customer"])
    ca = Table("customer_address", tables["customer_address"],
               paths["customer_address"])
    it = Table("item", tables["item"], paths["item"])

    cs_f = filter_(
        e2("And",
           e2("GreaterThanOrEqual", cs.a("cs_sold_date_sk").ref(),
              lit(Q.Y1998[0], "long")),
           e2("LessThanOrEqual", cs.a("cs_sold_date_sk").ref(),
              lit(Q.Y1998[1], "long"))), cs.scan())
    cd_f = filter_(
        e2("And",
           e2("EqualTo", cd.a("cd_gender").ref(), lit("F", "string")),
           e2("EqualTo", cd.a("cd_education_status").ref(),
              lit("Unknown", "string"))), cd.scan())
    j_cd = bhj([cs.a("cs_bill_cdemo_sk")], [cd.a("cd_demo_sk")], cs_f,
               cd_f)

    cs_ex = exchange([cs.a("cs_bill_customer_sk")], partitions, j_cd)
    cu_ex = exchange([cu.a("c_customer_sk")], partitions, cu.scan())
    j_cu = shj([cs.a("cs_bill_customer_sk")], [cu.a("c_customer_sk")],
               cs_ex, cu_ex)

    ca_f = filter_(in_list(ca.a("ca_state").ref(), Q.Q18_STATES,
                           "string"), ca.scan())
    j_ca = bhj([cu.a("c_current_addr_sk")], [ca.a("ca_address_sk")],
               j_cu, ca_f)
    j_it = bhj([cs.a("cs_item_sk")], [it.a("i_item_sk")], j_ca,
               it.scan())

    # ROLLUP via ExpandExec: 5 grouping sets + grouping id
    grp = [it.a("i_item_id"), ca.a("ca_country"), ca.a("ca_state"),
           ca.a("ca_county")]
    vals = [cs.a("cs_quantity"), cs.a("cs_list_price"),
            cs.a("cs_coupon_amt"), cs.a("cs_net_profit")]
    out_attrs = [A("i_item_id", "string"), A("ca_country", "string"),
                 A("ca_state", "string"), A("ca_county", "string"),
                 A("g_id", "long"),
                 A("cs_quantity", "long"), A("cs_list_price", "double"),
                 A("cs_coupon_amt", "double"),
                 A("cs_net_profit", "double")]
    projections = []
    for kept, gid in ((4, 0), (3, 1), (2, 3), (1, 7), (0, 15)):
        row = [grp[i].ref() if i < kept else lit(None, "string")
               for i in range(4)]
        row.append(lit(gid, "long"))
        row.extend(v.ref() for v in vals)
        projections.append(row)
    expanded = node("ExpandExec",
                    {"projections": projections,
                     "output": [a.ref() for a in out_attrs]}, [j_it])

    res = [A("agg1", "double"), A("agg2", "double"), A("agg3", "double"),
           A("agg4", "double")]
    stats = partial_final(
        out_attrs[:5],
        [("Average", out_attrs[5].ref(), res[0]),
         ("Average", out_attrs[6].ref(), res[1]),
         ("Average", out_attrs[7].ref(), res[2]),
         ("Average", out_attrs[8].ref(), res[3])],
        partitions, expanded)
    order = [out_attrs[4]] + out_attrs[:4]
    plan = take_ordered(100, order, out_attrs[:5] + res, stats)

    _plan, oracle = Q.q18(paths, tables, partitions)
    return plan, oracle


def q95(paths, tables, partitions: int = 4):
    _reset_ids()
    ws = Table("web_sales", tables["web_sales"], paths["web_sales"])
    wh = Table("web_sales", tables["web_sales"], paths["web_sales"])
    wr = Table("web_returns", tables["web_returns"],
               paths["web_returns"])
    ca = Table("customer_address", tables["customer_address"],
               paths["customer_address"])

    ws1 = filter_(
        e2("And",
           e2("And",
              e2("GreaterThanOrEqual", ws.a("ws_ship_date_sk").ref(),
                 lit(Q.Q95_WINDOW[0], "long")),
              e2("LessThanOrEqual", ws.a("ws_ship_date_sk").ref(),
                 lit(Q.Q95_WINDOW[1], "long"))),
           e2("LessThanOrEqual", ws.a("ws_web_site_sk").ref(),
              lit(2, "long"))), ws.scan())
    ca_f = filter_(e2("EqualTo", ca.a("ca_state").ref(),
                      lit("IL", "string")), ca.scan())
    ws1 = bhj([ws.a("ws_ship_addr_sk")], [ca.a("ca_address_sk")], ws1,
              ca_f)
    keep = [ws.a("ws_order_number"), ws.a("ws_warehouse_sk"),
            ws.a("ws_ext_ship_cost"), ws.a("ws_net_profit")]
    ws1 = project([k.ref() for k in keep], ws1)
    ws1_ex = exchange([ws.a("ws_order_number")], partitions, ws1)

    wh_on = A("wh_order_number", "long")
    wh_wh = A("wh_warehouse_sk", "long")
    ws_all = project(
        [alias(wh.a("ws_order_number").ref(), wh_on),
         alias(wh.a("ws_warehouse_sk").ref(), wh_wh)], wh.scan())
    ws_all_ex = exchange([wh_on], partitions, ws_all)

    semi = shj([ws.a("ws_order_number")], [wh_on], ws1_ex, ws_all_ex,
               jt="LeftSemi",
               cond=not_(e2("EqualTo", ws.a("ws_warehouse_sk").ref(),
                            wh_wh.ref())))

    wr_on = A("wr_order_number", "long")
    wr_ex = exchange(
        [wr_on], partitions,
        project([alias(wr.a("wr_order_number").ref(), wr_on)],
                wr.scan()))
    anti = shj([ws.a("ws_order_number")], [wr_on], semi, wr_ex,
               jt="LeftAnti")

    ship = A("ship_cost", "double")
    prof = A("net_profit", "double")
    per_order = partial_final(
        [ws.a("ws_order_number")],
        [("Sum", ws.a("ws_ext_ship_cost").ref(), ship),
         ("Sum", ws.a("ws_net_profit").ref(), prof)],
        partitions, anti, with_exchange=False)

    oc = A("order_count", "long")
    tsc = A("total_ship_cost", "double")
    tnp = A("total_net_profit", "double")
    plan = partial_final(
        [],
        [("Count", ws.a("ws_order_number").ref(), oc),
         ("Sum", ship.ref(), tsc), ("Sum", prof.ref(), tnp)],
        1, single_exchange(per_order), with_exchange=False)

    _plan, oracle = Q.q95(paths, tables, partitions)
    return plan, oracle


def q67(paths, tables, partitions: int = 4):
    """Expand rollup + window rank over category revenue (the window-
    bearing converter path: WindowExec + Rank through toJSON)."""
    _reset_ids()
    ss = Table("store_sales", tables["store_sales"],
               paths["store_sales"])
    it = Table("item", tables["item"], paths["item"])
    dd = Table("date_dim", tables["date_dim"], paths["date_dim"])

    dd_f = filter_(e2("EqualTo", dd.a("d_year").ref(),
                      lit(1999, "integer")), dd.scan())
    j_dd = bhj([ss.a("ss_sold_date_sk")], [dd.a("d_date_sk")],
               ss.scan(), dd_f)
    j_it = bhj([ss.a("ss_item_sk")], [it.a("i_item_sk")], j_dd,
               it.scan())

    out_attrs = [A("i_category", "string"), A("i_class", "string"),
                 A("g_id", "long"), A("ss_ext_sales_price", "double")]
    projections = []
    for kept, gid in ((2, 0), (1, 1), (0, 3)):
        row = [it.a("i_category").ref() if kept >= 1
               else lit(None, "string"),
               it.a("i_class").ref() if kept >= 2
               else lit(None, "string"),
               lit(gid, "long"),
               ss.a("ss_ext_sales_price").ref()]
        projections.append(row)
    expanded = node("ExpandExec",
                    {"projections": projections,
                     "output": [a.ref() for a in out_attrs]}, [j_it])

    sumsales = A("sumsales", "double")
    rev = partial_final(
        out_attrs[:3],
        [("Sum", out_attrs[3].ref(), sumsales)],
        partitions, expanded)
    # category asc then revenue desc — the converter consumes
    # sortOrder as given
    srt = node("SortExec",
               {"sortOrder": [sort_order(out_attrs[0].ref()),
                              sort_order(sumsales.ref(), desc=True)]},
               [single_exchange(rev)])
    rk = A("rk", "integer")
    win = window_rank(rk, [out_attrs[0]], [(sumsales, True)], srt)
    flt = filter_(e2("LessThanOrEqual", rk.ref(), lit(10, "integer")),
                  win)
    plan = take_ordered(100, [out_attrs[0], rk],
                        out_attrs[:3] + [sumsales, rk], flt)

    _plan, oracle = Q.q67(paths, tables, partitions)
    return plan, oracle


SPARK_QUERIES = {
    "q01": (q01, ["store_returns", "date_dim", "store", "customer"]),
    "q06": (q06, ["store_sales", "item"]),
    "q17": (q17, ["store_sales", "store_returns", "catalog_sales",
                  "store", "item"]),
    "q18": (q18, ["catalog_sales", "customer_demographics", "customer",
                  "customer_address", "item"]),
    "q95": (q95, ["web_sales", "web_returns", "customer_address"]),
    "q67": (q67, ["store_sales", "item", "date_dim"]),
}
