"""Synthetic TPC-DS-shaped data generator.

Parity role: the 1GB TPC-DS dataset of dev/auron-it/local-run-tpcds.sh.
Zero-egress environment: generate schema-faithful synthetic tables (same
columns/types/key relationships as the TPC-DS subset the progression
queries touch) with deterministic seeds, scaled by `scale` (1.0 ~ SF1 row
counts for the used tables).
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

SF1_ROWS = {
    "inventory": 783_000,
    "household_demographics": 7_200,
    "time_dim": 86_400,
    "reason": 35,
    "store_returns": 287_514,
    "store_sales": 2_880_404,
    "catalog_sales": 1_441_548,
    "web_sales": 719_384,
    "web_returns": 71_763,
    "store": 12,
    "customer": 100_000,
    "customer_address": 50_000,
    "customer_demographics": 1_920_800,
    "date_dim": 73_049,
    "item": 18_000,
    "warehouse": 5,
    "promotion": 300,
    "web_clickstreams": 50_000,
}


def _date_ordered(tbl: pa.Table, date_col: str) -> pa.Table:
    """Fact tables come out of dsdgen in date order (rows are emitted per
    calendar date), so real TPC-DS parquet loads carry strong date-key
    clustering and selective row-group min/max statistics — the layout
    the reference's parquet page/row-group filtering exists to exploit
    (ref conf.rs:43 `enable.pageFiltering`, parquet_exec.rs).  The
    uniform-random dates emitted here previously were unfaithful in
    exactly the way that disabled that feature; sort to match dsdgen."""
    return tbl.sort_by([(date_col, "ascending")])


def _rows(name: str, scale: float) -> int:
    base = SF1_ROWS[name]
    if name in ("store", "date_dim", "warehouse", "promotion",
                "household_demographics", "time_dim", "reason"):
        return base  # dimension tables do not scale
    if name == "customer_demographics":
        # fixed-size cross-product dimension in TPC-DS
        return min(base, max(1, int(base * max(scale, 0.01))))
    return max(1, int(base * scale))


def gen_date_dim(scale: float, seed: int = 11) -> pa.Table:
    n = _rows("date_dim", scale)
    sk = np.arange(2450815, 2450815 + n)
    year = 1998 + (np.arange(n) // 365)
    moy = (np.arange(n) % 365) // 31 + 1
    return pa.table({
        "d_date_sk": pa.array(sk),
        "d_year": pa.array(year.astype(np.int32)),
        "d_moy": pa.array(np.minimum(moy, 12).astype(np.int32)),
        "d_dom": pa.array(((np.arange(n) % 31) + 1).astype(np.int32)),
        "d_dow": pa.array((np.arange(n) % 7).astype(np.int32)),
        "d_week_seq": pa.array((np.arange(n) // 7 + 1).astype(np.int32)),
        "d_qoy": pa.array((((np.minimum(moy, 12) - 1) // 3) + 1)
                          .astype(np.int32)),
    })


def gen_store(scale: float, seed: int = 12) -> pa.Table:
    n = _rows("store", scale)
    rng = np.random.default_rng(seed)
    states = np.array(["TN", "CA", "NY", "TX", "WA"])
    return pa.table({
        "s_store_sk": pa.array(np.arange(1, n + 1)),
        "s_state": pa.array(states[rng.integers(0, len(states), n)]),
        "s_store_name": pa.array([f"store_{i}" for i in range(1, n + 1)]),
    })


def gen_customer(scale: float, seed: int = 13) -> pa.Table:
    n = _rows("customer", scale)
    rng = np.random.default_rng(seed)
    return pa.table({
        "c_customer_sk": pa.array(np.arange(1, n + 1)),
        "c_customer_id": pa.array([f"C{i:011d}" for i in range(1, n + 1)]),
        "c_current_addr_sk": pa.array(
            rng.integers(1, _rows("customer_address", scale) + 1, n)),
        "c_current_cdemo_sk": pa.array(
            rng.integers(1, _rows("customer_demographics", scale) + 1, n)),
        "c_birth_year": pa.array(
            rng.integers(1924, 1993, n).astype(np.int32)),
    })


SALES_DATE_DAYS = 1826  # TPC-DS facts span ~5 years (1998-2002), not the
#                         full 200-year date_dim


def gen_store_returns(scale: float, seed: int = 14) -> pa.Table:
    n = _rows("store_returns", scale)
    rng = np.random.default_rng(seed)
    date_n = min(_rows("date_dim", scale), SALES_DATE_DAYS)
    null_mask = rng.random(n) < 0.02
    cust = rng.integers(1, _rows("customer", scale) + 1, n).astype(float)
    cust[null_mask] = np.nan
    return _date_ordered(pa.table({
        "sr_returned_date_sk": pa.array(
            rng.integers(2450815, 2450815 + date_n, n)),
        "sr_customer_sk": pa.array(
            np.where(null_mask, None, cust).tolist(), type=pa.int64()),
        "sr_store_sk": pa.array(rng.integers(1, _rows("store", scale) + 1, n)),
        "sr_return_amt": pa.array(np.round(rng.random(n) * 500, 2)),
        "sr_ticket_number": pa.array(np.arange(1, n + 1)),
        "sr_item_sk": pa.array(rng.integers(1, _rows("item", scale) + 1, n)),
        "sr_return_quantity": pa.array(
            rng.integers(1, 50, n).astype(np.int32)),
        "sr_reason_sk": pa.array(rng.integers(1, 36, n)),
        "sr_net_loss": pa.array(np.round(rng.random(n) * 60, 2)),
    }), "sr_returned_date_sk")


def gen_store_sales(scale: float, seed: int = 15) -> pa.Table:
    n = _rows("store_sales", scale)
    rng = np.random.default_rng(seed)
    date_n = min(_rows("date_dim", scale), SALES_DATE_DAYS)
    return _date_ordered(pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(2450815, 2450815 + date_n, n)),
        "ss_customer_sk": pa.array(
            rng.integers(1, _rows("customer", scale) + 1, n)),
        "ss_store_sk": pa.array(rng.integers(1, _rows("store", scale) + 1, n)),
        "ss_item_sk": pa.array(rng.integers(1, _rows("item", scale) + 1, n)),
        "ss_ext_sales_price": pa.array(np.round(rng.random(n) * 300, 2)),
        "ss_quantity": pa.array(rng.integers(1, 100, n).astype(np.int32)),
        "ss_ticket_number": pa.array(np.arange(1, n + 1)),
        "ss_cdemo_sk": pa.array(
            rng.integers(1, _rows("customer_demographics", scale) + 1, n)),
        "ss_promo_sk": pa.array(rng.integers(1, 301, n)),
        "ss_list_price": pa.array(np.round(rng.random(n) * 320, 2)),
        "ss_coupon_amt": pa.array(np.round(rng.random(n) * 40, 2)),
        "ss_sales_price": pa.array(np.round(rng.random(n) * 280, 2)),
        "ss_net_profit": pa.array(np.round(rng.random(n) * 120 - 20, 2)),
        "ss_hdemo_sk": pa.array(rng.integers(1, 7_201, n)),
        "ss_addr_sk": pa.array(
            rng.integers(1, _rows("customer_address", scale) + 1, n)),
        "ss_sold_time_sk": pa.array(rng.integers(0, 86_400, n)),
    }), "ss_sold_date_sk")


def gen_catalog_sales(scale: float, seed: int = 17) -> pa.Table:
    n = _rows("catalog_sales", scale)
    rng = np.random.default_rng(seed)
    date_n = min(_rows("date_dim", scale), SALES_DATE_DAYS)
    sold = rng.integers(2450815, 2450815 + date_n, n)
    return _date_ordered(pa.table({
        "cs_sold_date_sk": pa.array(sold),
        "cs_bill_customer_sk": pa.array(
            rng.integers(1, _rows("customer", scale) + 1, n)),
        "cs_bill_cdemo_sk": pa.array(
            rng.integers(1, _rows("customer_demographics", scale) + 1, n)),
        "cs_item_sk": pa.array(rng.integers(1, _rows("item", scale) + 1, n)),
        "cs_quantity": pa.array(rng.integers(1, 100, n).astype(np.int32)),
        "cs_list_price": pa.array(np.round(rng.random(n) * 300, 2)),
        "cs_coupon_amt": pa.array(np.round(rng.random(n) * 50, 2)),
        "cs_sales_price": pa.array(np.round(rng.random(n) * 250, 2)),
        "cs_net_profit": pa.array(np.round(rng.random(n) * 100 - 20, 2)),
        "cs_promo_sk": pa.array(rng.integers(1, 301, n)),
        "cs_ext_sales_price": pa.array(np.round(rng.random(n) * 280, 2)),
        "cs_ship_date_sk": pa.array(
            sold + rng.integers(1, 150, n)),  # latency 1-149 days: every
        #                                       q99 bucket gets real rows
        "cs_warehouse_sk": pa.array(
            rng.integers(1, _rows("warehouse", scale) + 1, n)),
        "cs_order_number": pa.array(rng.integers(1, max(1, n // 2) + 1,
                                                 n)),
        "cs_ship_mode_sk": pa.array(rng.integers(1, 21, n)),
        "cs_call_center_sk": pa.array(rng.integers(1, 7, n)),
    }), "cs_sold_date_sk")


def gen_catalog_returns(scale: float, seed: int = 28) -> pa.Table:
    n = max(1, int(144_067 * scale))
    rng = np.random.default_rng(seed)
    cs_n = _rows("catalog_sales", scale)
    date_n = min(_rows("date_dim", scale), SALES_DATE_DAYS)
    return _date_ordered(pa.table({
        "cr_order_number": pa.array(
            rng.integers(1, max(1, cs_n // 2) + 1, n)),
        "cr_return_amount": pa.array(np.round(rng.random(n) * 90, 2)),
        "cr_item_sk": pa.array(rng.integers(1, _rows("item", scale) + 1, n)),
        "cr_returning_customer_sk": pa.array(
            rng.integers(1, _rows("customer", scale) + 1, n)),
        "cr_returned_date_sk": pa.array(
            rng.integers(2450815, 2450815 + date_n, n)),
        "cr_call_center_sk": pa.array(rng.integers(1, 7, n)),
        "cr_net_loss": pa.array(np.round(rng.random(n) * 70, 2)),
    }), "cr_returned_date_sk")


def gen_web_sales(scale: float, seed: int = 18) -> pa.Table:
    n = _rows("web_sales", scale)
    rng = np.random.default_rng(seed)
    date_n = min(_rows("date_dim", scale), SALES_DATE_DAYS)
    n_orders = max(1, n // 3)  # ~3 line items per order
    return _date_ordered(pa.table({
        "ws_ship_date_sk": pa.array(
            rng.integers(2450815, 2450815 + date_n, n)),
        "ws_ship_addr_sk": pa.array(
            rng.integers(1, _rows("customer_address", scale) + 1, n)),
        "ws_web_site_sk": pa.array(rng.integers(1, 31, n)),
        "ws_order_number": pa.array(rng.integers(1, n_orders + 1, n)),
        "ws_warehouse_sk": pa.array(
            rng.integers(1, _rows("warehouse", scale) + 1, n)),
        "ws_ext_ship_cost": pa.array(np.round(rng.random(n) * 100, 2)),
        "ws_net_profit": pa.array(np.round(rng.random(n) * 200 - 40, 2)),
        "ws_sold_date_sk": pa.array(
            rng.integers(2450815, 2450815 + date_n, n)),
        "ws_item_sk": pa.array(rng.integers(1, _rows("item", scale) + 1, n)),
        "ws_ext_sales_price": pa.array(np.round(rng.random(n) * 300, 2)),
        "ws_bill_customer_sk": pa.array(
            rng.integers(1, _rows("customer", scale) + 1, n)),
        "ws_quantity": pa.array(rng.integers(1, 100, n).astype(np.int32)),
        "ws_sales_price": pa.array(np.round(rng.random(n) * 260, 2)),
    }), "ws_sold_date_sk")


def gen_web_returns(scale: float, seed: int = 19) -> pa.Table:
    n = _rows("web_returns", scale)
    rng = np.random.default_rng(seed)
    n_orders = max(1, _rows("web_sales", scale) // 3)
    date_n = min(_rows("date_dim", scale), SALES_DATE_DAYS)
    return _date_ordered(pa.table({
        "wr_order_number": pa.array(rng.integers(1, n_orders + 1, n)),
        "wr_return_amt": pa.array(np.round(rng.random(n) * 80, 2)),
        "wr_item_sk": pa.array(rng.integers(1, _rows("item", scale) + 1, n)),
        "wr_returning_customer_sk": pa.array(
            rng.integers(1, _rows("customer", scale) + 1, n)),
        "wr_returned_date_sk": pa.array(
            rng.integers(2450815, 2450815 + date_n, n)),
        "wr_reason_sk": pa.array(rng.integers(1, 36, n)),
        "wr_net_loss": pa.array(np.round(rng.random(n) * 50, 2)),
    }), "wr_returned_date_sk")


def gen_customer_demographics(scale: float, seed: int = 20) -> pa.Table:
    n = _rows("customer_demographics", scale)
    rng = np.random.default_rng(seed)
    genders = np.array(["M", "F"])
    edu = np.array(["Primary", "Secondary", "College", "2 yr Degree",
                    "4 yr Degree", "Advanced Degree", "Unknown"])
    return pa.table({
        "cd_demo_sk": pa.array(np.arange(1, n + 1)),
        "cd_gender": pa.array(genders[rng.integers(0, 2, n)]),
        "cd_education_status": pa.array(edu[rng.integers(0, len(edu), n)]),
        "cd_dep_count": pa.array(rng.integers(0, 7, n).astype(np.int32)),
        "cd_marital_status": pa.array(
            np.array(["S", "M", "D", "W", "U"])[rng.integers(0, 5, n)]),
    })


def gen_customer_address(scale: float, seed: int = 21) -> pa.Table:
    n = _rows("customer_address", scale)
    rng = np.random.default_rng(seed)
    states = np.array(["TN", "CA", "NY", "TX", "WA", "GA", "IL", "IN",
                       "OH", "NE"])
    counties = np.array([f"county_{i}" for i in range(40)])
    return pa.table({
        "ca_address_sk": pa.array(np.arange(1, n + 1)),
        "ca_state": pa.array(states[rng.integers(0, len(states), n)]),
        "ca_city": pa.array(
            np.array([f"city_{i}" for i in range(60)])[
                rng.integers(0, 60, n)]),
        "ca_county": pa.array(counties[rng.integers(0, len(counties), n)]),
        "ca_country": pa.array(np.array(["United States"]).repeat(n)),
        "ca_zip": pa.array(np.char.zfill(
            rng.integers(0, 100000, n).astype(str), 5)),  # real leading
        #                                                    zeros: "08540"
        "ca_gmt_offset": pa.array(
            rng.integers(-8, -4, n).astype(np.int32)),
    })


def gen_item(scale: float, seed: int = 16) -> pa.Table:
    n = _rows("item", scale)
    rng = np.random.default_rng(seed)
    cats = np.array(["Books", "Home", "Sports", "Music", "Electronics"])
    brands = np.array([f"brand_{i}" for i in range(50)])
    classes = np.array([f"class_{i}" for i in range(16)])
    brand_ids = rng.integers(1, 51, n)
    return pa.table({
        "i_item_sk": pa.array(np.arange(1, n + 1)),
        "i_item_id": pa.array([f"I{i:09d}" for i in range(1, n + 1)]),
        "i_category": pa.array(cats[rng.integers(0, len(cats), n)]),
        "i_class": pa.array(classes[rng.integers(0, len(classes), n)]),
        "i_brand_id": pa.array(brand_ids.astype(np.int32)),
        "i_brand": pa.array(brands[brand_ids - 1]),
        "i_manager_id": pa.array(rng.integers(1, 100, n).astype(np.int32)),
        "i_manufact_id": pa.array(
            rng.integers(1, 1001, n).astype(np.int32)),
        "i_current_price": pa.array(np.round(rng.random(n) * 100, 2)),
    })


def gen_promotion(scale: float, seed: int = 22) -> pa.Table:
    n = _rows("promotion", scale)
    rng = np.random.default_rng(seed)
    yn = np.array(["Y", "N"])
    return pa.table({
        "p_promo_sk": pa.array(np.arange(1, n + 1)),
        "p_channel_email": pa.array(yn[rng.integers(0, 2, n)]),
        "p_channel_event": pa.array(yn[rng.integers(0, 2, n)]),
    })


def gen_web_clickstreams(scale: float, seed: int = 23) -> pa.Table:
    """Synthetic clickstream with a LIST column: the Generate-bearing
    integration workload (TPC-DS has no array columns; the reference
    exercises Generate through the Spark suites instead)."""
    n = _rows("web_clickstreams", scale)
    rng = np.random.default_rng(seed)
    n_items = _rows("item", scale)
    lengths = rng.integers(0, 6, n)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    values = rng.integers(1, n_items + 1, int(offsets[-1]))
    pages = pa.ListArray.from_arrays(pa.array(offsets, type=pa.int32()),
                                     pa.array(values, type=pa.int64()))
    return pa.table({
        "wc_session_sk": pa.array(np.arange(1, n + 1)),
        "wc_clicked_items": pages,
    })


def gen_inventory(scale: float, seed: int = 29) -> pa.Table:
    """Weekly on-hand snapshots (TPC-DS inventory): one row per
    (week, item-sample, warehouse); dsdgen emits them in date order."""
    n = _rows("inventory", scale)
    rng = np.random.default_rng(seed)
    week_starts = np.arange(0, SALES_DATE_DAYS, 7)
    return _date_ordered(pa.table({
        "inv_date_sk": pa.array(
            2450815 + week_starts[rng.integers(0, len(week_starts), n)]),
        "inv_item_sk": pa.array(
            rng.integers(1, _rows("item", scale) + 1, n)),
        "inv_warehouse_sk": pa.array(
            rng.integers(1, _rows("warehouse", scale) + 1, n)),
        "inv_quantity_on_hand": pa.array(
            rng.integers(0, 1000, n).astype(np.int32)),
    }), "inv_date_sk")


def gen_warehouse(scale: float, seed: int = 27) -> pa.Table:
    n = _rows("warehouse", scale)
    return pa.table({
        "w_warehouse_sk": pa.array(np.arange(1, n + 1)),
        "w_warehouse_name": pa.array([f"warehouse_{i}"
                                      for i in range(1, n + 1)]),
        "w_state": pa.array(np.array(["TN", "CA", "NY", "TX", "WA"])
                            [np.arange(n) % 5]),
    })


def gen_household_demographics(scale: float, seed: int = 24) -> pa.Table:
    n = _rows("household_demographics", scale)
    rng = np.random.default_rng(seed)
    pot = np.array([">10000", "5001-10000", "1001-5000", "501-1000",
                    "0-500", "Unknown"])
    return pa.table({
        "hd_demo_sk": pa.array(np.arange(1, n + 1)),
        "hd_dep_count": pa.array(rng.integers(0, 10, n).astype(np.int32)),
        "hd_vehicle_count": pa.array(
            rng.integers(-1, 5, n).astype(np.int32)),
        "hd_buy_potential": pa.array(pot[rng.integers(0, len(pot), n)]),
    })


def gen_time_dim(scale: float, seed: int = 25) -> pa.Table:
    n = _rows("time_dim", scale)
    t = np.arange(n)
    return pa.table({
        "t_time_sk": pa.array(t),
        "t_hour": pa.array((t // 3600).astype(np.int32)),
        "t_minute": pa.array(((t % 3600) // 60).astype(np.int32)),
    })


def gen_reason(scale: float, seed: int = 26) -> pa.Table:
    n = _rows("reason", scale)
    return pa.table({
        "r_reason_sk": pa.array(np.arange(1, n + 1)),
        "r_reason_desc": pa.array([f"reason {i}" for i in range(1, n + 1)]),
    })


GENERATORS = {
    "inventory": gen_inventory,
    "warehouse": gen_warehouse,
    "household_demographics": gen_household_demographics,
    "time_dim": gen_time_dim,
    "reason": gen_reason,
    "date_dim": gen_date_dim,
    "store": gen_store,
    "customer": gen_customer,
    "store_returns": gen_store_returns,
    "store_sales": gen_store_sales,
    "catalog_sales": gen_catalog_sales,
    "catalog_returns": gen_catalog_returns,
    "web_sales": gen_web_sales,
    "web_returns": gen_web_returns,
    "customer_demographics": gen_customer_demographics,
    "customer_address": gen_customer_address,
    "item": gen_item,
    "promotion": gen_promotion,
    "web_clickstreams": gen_web_clickstreams,
}


def generate(names, scale: float = 0.01):
    return {name: GENERATORS[name](scale) for name in names}


def write_parquet_dataset(tables, out_dir: str, row_group_size: int = 1 << 17):
    import os
    import pyarrow.parquet as pq
    paths = {}
    for name, t in tables.items():
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        p = os.path.join(d, "part-00000.parquet")
        pq.write_table(t, p, row_group_size=row_group_size)
        paths[name] = p
    return paths


def write_parquet_splits(tables, out_dir: str, partitions: int,
                         row_group_size: int = 1 << 16):
    """Fact tables split into `partitions` files, one scan file-group per
    partition; dimension tables stay single-file.  Returns
    {name: [[file], [file], ...]} in the parquet_scan IR shape."""
    import os
    import pyarrow.parquet as pq
    paths = {}
    for name, t in tables.items():
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        nparts = partitions if t.num_rows > 10_000 else 1
        per = -(-t.num_rows // nparts)
        groups = []
        for i in range(nparts):
            p = os.path.join(d, f"part-{i:05d}.parquet")
            pq.write_table(t.slice(i * per, per), p,
                           row_group_size=row_group_size)
            groups.append([p])
        paths[name] = groups
    return paths
