"""Query runner + result comparator + plan-stability checker.

Parity: dev/auron-it (QueryRunner.scala runs baseline vs accelerated and
reports per-query speedup; comparison/QueryResultComparator.scala checks
row counts + cell equality with double tolerance;
comparison/PlanStabilityChecker.scala:30-107 normalizes plans and diffs
against goldens).
"""

from __future__ import annotations

import math
import re
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np
import pandas as pd
import pyarrow as pa

DOUBLE_TOL = 1e-6


@dataclass
class QueryResult:
    name: str
    rows: int
    engine_seconds: float
    oracle_seconds: float
    passed: bool
    detail: str = ""

    @property
    def speedup(self) -> float:
        return self.oracle_seconds / max(self.engine_seconds, 1e-9)


def compare_frames(got: pd.DataFrame, want: pd.DataFrame) -> Optional[str]:
    """Row-count + cell equality with double tolerance, order-insensitive
    (QueryResultComparator semantics)."""
    if len(got) != len(want):
        return f"row count mismatch: got {len(got)} want {len(want)}"
    if got.shape[1] != want.shape[1]:
        return f"column count mismatch: {got.shape[1]} vs {want.shape[1]}"
    g = got.copy()
    w = want.copy()
    g.columns = list(range(g.shape[1]))
    w.columns = list(range(w.shape[1]))
    key = sorted(range(g.shape[1]),
                 key=lambda i: str(g[i].dtype))  # stable sort key order
    g = g.sort_values(by=list(range(g.shape[1]))).reset_index(drop=True)
    w = w.sort_values(by=list(range(w.shape[1]))).reset_index(drop=True)
    for ci in range(g.shape[1]):
        gc, wc = g[ci], w[ci]
        for ri in range(len(g)):
            a, b = gc.iloc[ri], wc.iloc[ri]
            if _cell_equal(a, b):
                continue
            return f"cell mismatch at row {ri} col {ci}: {a!r} != {b!r}"
    return None


def _cell_equal(a, b) -> bool:
    a_null = a is None or (isinstance(a, float) and math.isnan(a)) or a is pd.NA
    b_null = b is None or (isinstance(b, float) and math.isnan(b)) or b is pd.NA
    if a_null or b_null:
        return a_null and b_null
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if math.isinf(fa) or math.isinf(fb):
            # exact match only: inf <= tol*inf would otherwise pass ANY
            # value against an infinity
            return fa == fb
        return abs(fa - fb) <= DOUBLE_TOL * max(1.0, abs(fa), abs(fb))
    return a == b


def run_query(name: str, plan, oracle) -> QueryResult:
    t0 = time.perf_counter()
    got_rb = plan.execute_collect().to_arrow()
    engine_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    want = oracle()
    oracle_s = time.perf_counter() - t1
    got = got_rb.to_pandas() if got_rb.num_rows else pd.DataFrame(
        {n: [] for n in got_rb.schema.names})
    err = compare_frames(got, want)
    return QueryResult(name, got_rb.num_rows, engine_s, oracle_s,
                       err is None, err or "")


# -- plan stability (PlanStabilityChecker analog) ----------------------------

_NORMALIZERS = [
    (re.compile(r"0x[0-9a-f]+"), "<addr>"),
    (re.compile(r"/[\w/.-]*/(blaze-[\w.-]+)"), r"<tmp>/\1"),
    (re.compile(r"shuffle://[0-9a-f]+"), "shuffle://<id>"),
    (re.compile(r"bhj-\d+"), "bhj-<id>"),
]


def normalize_plan(plan) -> str:
    text = plan.pretty()
    for pat, repl in _NORMALIZERS:
        text = pat.sub(repl, text)
    return text.strip() + "\n"


def check_plan_stability(plan, golden_path: str,
                         update: bool = False) -> Optional[str]:
    import os
    text = normalize_plan(plan)
    if update or not os.path.exists(golden_path):
        os.makedirs(os.path.dirname(golden_path), exist_ok=True)
        with open(golden_path, "w") as f:
            f.write(text)
        return None
    with open(golden_path) as f:
        want = f.read()
    if text != want:
        import difflib
        diff = "".join(difflib.unified_diff(
            want.splitlines(keepends=True), text.splitlines(keepends=True),
            "golden", "current"))
        return diff
    return None
