"""Stage DAG scheduler: execute a whole multi-stage plan over the wire.

Parity role: what Spark's driver + AuronShuffleManager do around the
reference engine.  Auron never schedules stages itself — Spark splits the
physical plan at exchange boundaries, runs map tasks that end in
ShuffleWriterExec (.data/.index files, AuronShuffleWriterBase.scala:39),
tracks map outputs, and starts reduce stages whose plans begin with
IpcReaderExec over the fetched blocks (AuronBlockStoreShuffleReaderBase
.scala:29-66).  This module is that driver: it takes ONE engine-IR plan
containing `local_exchange` nodes (what convert/spark.py emits for
ShuffleExchangeExec), cuts it into stages, and runs every task of every
stage as protobuf TaskDefinition bytes through NativeExecutionRuntime —
the full production wire path, no in-process shortcuts.

Cutting rules:
  * `local_exchange` -> the child becomes a producer stage whose per-task
    plan is wrapped in `shuffle_writer` (hash/round-robin/single
    partitioning, per-map .data/.index files); the consumer side reads an
    `ipc_reader` bound to the producer's registered block map (the
    MapOutputTracker analog).
  * scans carry ONE file group per task on the wire (FileScanExecConf),
    so each task's plan keeps only its own group — except under a
    broadcast build side, where the scan collapses to ALL files (a
    broadcast is a full copy; BroadcastJoinExec pulls every partition of
    its build child).
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from blaze_tpu.bridge.metrics import MetricNode
from blaze_tpu.bridge.resource import put_resource, remove_resource
from blaze_tpu.faults import FetchFailedError

log = logging.getLogger("blaze_tpu.stages")

_SCAN_KINDS = ("parquet_scan", "orc_scan")


def _broadcast_reader_rids(d: Any, in_broadcast: bool = False) -> set:
    """Resource ids of ipc_readers sitting under a broadcast build side
    anywhere in `d` (those exchanges stay on the file shuffle)."""
    rids: set = set()
    if not isinstance(d, dict) or "kind" not in d:
        return rids
    k = d.get("kind")
    if k == "ipc_reader" and in_broadcast:
        rids.add(d.get("resource_id"))
    if k in ("broadcast_join", "broadcast_nested_loop_join"):
        build = d.get("build_side", "right")
        for side in ("left", "right"):
            rids |= _broadcast_reader_rids(d.get(side),
                                           in_broadcast or side == build)
        return rids
    if k == "broadcast_join_build_hash_map":
        return rids | _broadcast_reader_rids(d.get("input"), True)
    for key, val in d.items():
        if isinstance(val, dict) and "kind" in val:
            rids |= _broadcast_reader_rids(val, in_broadcast)
        elif key == "inputs" and isinstance(val, list):
            for v in val:
                rids |= _broadcast_reader_rids(v, in_broadcast)
    return rids


def _batches_to_columns(batches: List[pa.RecordBatch], schema):
    """Concatenate record batches into per-column (data, validity) numpy
    arrays — the flat layout DeviceExchange shards over the mesh."""
    import numpy as np

    from blaze_tpu.batch import _arrow_fixed_values, _unpack_validity
    ncols = len(schema.fields)
    datas: List[list] = [[] for _ in range(ncols)]
    valids: List[list] = [[] for _ in range(ncols)]
    for rb in batches:
        for i, f in enumerate(schema.fields):
            arr = rb.column(i)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            datas[i].append(np.ascontiguousarray(
                _arrow_fixed_values(arr, f.data_type)))
            valids[i].append(_unpack_validity(arr))
    return ([np.concatenate(d) for d in datas],
            [np.concatenate(v) for v in valids])


def _columns_to_batch(datas, valids, arrow_schema: pa.Schema
                      ) -> pa.RecordBatch:
    """Inverse of _batches_to_columns for one reduce partition.  date32
    and timestamps travelled the mesh as their integer storage; the
    cast back to the logical arrow type is lossless."""
    import numpy as np
    arrays = []
    for data, valid, f in zip(datas, valids, arrow_schema):
        valid = np.asarray(valid, dtype=bool)
        mask = None if bool(valid.all()) else ~valid
        t = f.type
        if pa.types.is_date32(t) or pa.types.is_timestamp(t):
            arrays.append(pa.array(data, mask=mask).cast(t))
        elif pa.types.is_boolean(t):
            arrays.append(pa.array(np.asarray(data, dtype=bool), mask=mask))
        elif pa.types.is_decimal(t):
            # the mesh carried the unscaled ints; a pa.array(..., type=t)
            # would read them as whole decimal values and rescale
            from blaze_tpu.batch import decimal_from_unscaled
            arrays.append(decimal_from_unscaled(
                np.asarray(data, dtype=np.int64), valid, t))
        else:
            arrays.append(pa.array(data, type=t, mask=mask))
    return pa.RecordBatch.from_arrays(arrays, schema=arrow_schema)


def _shuffle_scratch_base() -> Optional[str]:
    """Shuffle files are transient: prefer the RAM disk (the standard
    spark.local.dir-on-tmpfs deployment) when it has real headroom —
    ext4 journaling is pure critical-path overhead for data read back
    milliseconds later.  None -> tempfile's default."""
    try:
        sv = os.statvfs("/dev/shm")
        if sv.f_bavail * sv.f_frsize >= (2 << 30):
            return "/dev/shm"
    except OSError:
        pass
    return None


@dataclass
class Stage:
    sid: int
    plan: Dict[str, Any]          # stage-root IR (no shuffle_writer yet)
    partitioning: Optional[Dict[str, Any]]  # None for the result stage
    resource_id: Optional[str]
    num_tasks: int = 1            # producer-side task count
    deps: List[int] = field(default_factory=list)
    out_schema: Optional[Dict[str, Any]] = None
    # planner verdict for the device-resident exchange (plan/planner.py
    # exchange_device_spec); None = this boundary stays on file shuffle
    device_spec: Optional[Dict[str, Any]] = None
    # adaptive execution (plan/adaptive.py): set when a runtime rule
    # rewrote this stage — carries the rule name and the DERIVED
    # fingerprint that replaces the static subtree identity everywhere
    # downstream (statstore, subplan cache)
    aqe: Optional[Dict[str, Any]] = None


class DagScheduler:
    """Split at exchanges, then run stages bottom-up over the proto wire."""

    def __init__(self, work_dir: Optional[str] = None,
                 max_task_parallelism: Optional[int] = None,
                 task_timeout_s: float = 600.0,
                 query_ctx=None):
        self._owns_dir = work_dir is None
        self._dir = work_dir or tempfile.mkdtemp(
            prefix="blaze-dag-", dir=_shuffle_scratch_base())
        os.makedirs(self._dir, exist_ok=True)
        self._files: List[str] = []
        # owning serving.QueryContext: threaded to every task slot so
        # cancellation/deadline interrupts retries, pool waits and batch
        # loops (None = standalone single-query use, unchanged)
        from blaze_tpu.bridge.context import current_query
        self._query = query_ctx if query_ctx is not None else current_query()
        # elastic-shuffle clients (auron.tpu.shuffle.service), torn down
        # with the rest of the scratch state
        self._rss_clients: List[Any] = []
        self._cleanup_lock = threading.Lock()
        if max_task_parallelism is None:
            # executor sizing knob (ref rt.rs:108-112 tokio worker threads
            # = TOKIO_WORKER_THREADS_PER_CPU x task cpus)
            from blaze_tpu import config
            per_cpu = max(1, config.TOKIO_WORKER_THREADS_PER_CPU.get())
            max_task_parallelism = min(16, per_cpu *
                                       max(1, (os.cpu_count() or 4) // 2))
        self._par = max_task_parallelism
        self._timeout = task_timeout_s
        self._run_id = uuid.uuid4().hex[:10]
        self.stages: List[Stage] = []
        self._resources: List[str] = []
        self.exec_mode: Optional[str] = None  # "local" | "staged"
        # sid -> {map_id -> (data_file, offsets)}: the MapOutputTracker
        # analog.  blocks_for closures read THIS dict at call time, so a
        # recovered map task's fresh output is what the retried reduce
        # task fetches — never a stale snapshot of the poisoned one.
        self._stage_outputs: Dict[int, Dict[int, tuple]] = {}
        # (sid, map_id) -> pool worker id that produced the committed
        # output (None on the in-process path).  A worker crash
        # re-validates exactly these entries; validation failure marks
        # the table entry None, which blocks_for converts into the
        # FetchFailedError the lineage recovery already handles.
        self._map_worker: Dict[tuple, Optional[int]] = {}
        # (sid, map_id) -> times the task body ran; lineage-recovery
        # tests assert exactly ONE map task re-ran after a poisoned block
        self.task_runs: Dict[tuple, int] = {}
        # speculation: monotone per-(sid, map) attempt-id allocator (each
        # retry OR speculative duplicate gets a fresh id), and the table
        # of WINNING attempt ids — lineage recovery and crash
        # invalidation only ever deal with the committed winner
        self._attempt_seq: Dict[tuple, int] = {}
        self._map_attempt: Dict[tuple, int] = {}
        self._attempt_lock = threading.Lock()
        # per-stage operator-metric trees, merged across that stage's
        # tasks at finalize time (the MetricsUpdater analog)
        self.stage_metrics: Dict[int, MetricNode] = {}
        self._metrics_lock = threading.Lock()
        # sid -> {"compute": "device-loop"|"staged"|"mixed",
        #         "exchange": "device"|"rss"|"file"|"result"} — the
        # OBSERVED per-stage placement (bench/explain derive
        # compute_placement from this instead of the session-level
        # default, which reported "cpu" even when device lanes ran)
        self.stage_placement: Dict[int, Dict[str, str]] = {}
        # work-sharing (auron.tpu.cache.subplan): sid -> (fp, snapshot)
        # of stages served FROM the cross-query cache this run, and of
        # stages whose fresh output should be stored after the map wave
        self._cached_stages: Dict[int, tuple] = {}
        self._pending_subplan: Dict[int, tuple] = {}
        # statistics feedback plane (plan/statstore.py; armed per run by
        # _stats_begin only when auron.tpu.stats.enable): the run's plan
        # fingerprint, per-shuffle-boundary observations captured at
        # producer completion (the map-output table is gone by cleanup),
        # and the counter/reservoir baselines the final ingest deltas
        self.stats_fingerprint: Optional[str] = None
        self.stage_boundaries: Dict[int, Dict[str, Any]] = {}
        # adaptive execution (plan/adaptive.py): the run's rewrite/seed
        # event log, copied onto the serving QueryHandle at finish
        self.aqe_events: List[Dict[str, Any]] = []
        self._stats_base: Optional[dict] = None
        self._stats_dur0: Dict[str, int] = {}
        self._stats_t0: float = 0.0

    def _record_task_metrics(self, sid: int, tree: MetricNode) -> None:
        from blaze_tpu.bridge import profiling
        with self._metrics_lock:
            merged = self.stage_metrics.setdefault(
                sid, MetricNode(name=tree.name))
            merged.merge_from(tree)
        profiling.record_metrics(tree.to_dict())
        from blaze_tpu.plan import statstore
        if statstore.enabled():
            qid = getattr(self._query, "query_id", None)
            if qid is not None:
                from blaze_tpu.serving import progress
                values = tree.values or {}
                progress.note_rows(
                    qid, sid,
                    rows=int(values.get("output_rows", 0) or 0),
                    bytes_=int(values.get("io_bytes", 0) or 0))

    def collect_metrics(self) -> Optional[MetricNode]:
        """Merged metric tree of the result stage (the operator tree the
        caller's rows actually flowed through), or None before any run."""
        if not self.stage_metrics:
            return None
        return self.stage_metrics[max(self.stage_metrics)]

    # -- splitting ---------------------------------------------------------

    def split(self, plan: Dict[str, Any]) -> List[Stage]:
        """Returns stages in dependency order; the last one is the result
        stage (its output streams back to the caller, the collect path)."""
        self.stages = []  # a scheduler instance may be reused per query
        root, deps = self._split_node(plan)
        n_tasks, schema = self._plan_info(root)
        result = Stage(sid=len(self.stages), plan=root, partitioning=None,
                       resource_id=None, deps=deps, num_tasks=n_tasks,
                       out_schema=schema)
        self.stages.append(result)
        self._mark_device_exchanges()
        return self.stages

    def _mark_device_exchanges(self) -> None:
        """Planner pass: mark each exchange device-resident when BOTH
        sides of the boundary are mesh-shardable.  The producer side is
        decided by exchange_device_spec (hash keys as direct column
        refs, all-fixed-width row schema); the consumer side declines
        readers under broadcast builds — a broadcast replays EVERY
        partition once per task, which the file path streams through
        the page cache while in-memory device blocks would pin the full
        copy per replay."""
        from blaze_tpu.plan.planner import exchange_device_spec
        demoted: set = set()
        for st in self.stages:
            demoted |= _broadcast_reader_rids(st.plan)
        for st in self.stages:
            if st.partitioning is None or st.resource_id in demoted:
                continue
            st.device_spec = exchange_device_spec(st.partitioning,
                                                  st.out_schema)

    def _split_node(self, d: Dict[str, Any]):
        """Rewrite one node; returns (new_dict, dep_stage_ids)."""
        if not isinstance(d, dict) or "kind" not in d:
            return d, []
        if d["kind"] == "local_exchange":
            child, deps = self._split_node(d["input"])
            part = dict(d["partitioning"])
            n_out = 1 if part["kind"] == "single" \
                else int(part.get("num_partitions", 1))
            sid = len(self.stages)
            rid = f"stage://{self._run_id}/{sid}"
            n_tasks, schema = self._plan_info(child)
            stage = Stage(sid=sid, plan=child, partitioning=part,
                          resource_id=rid, deps=deps, num_tasks=n_tasks,
                          out_schema=schema)
            self.stages.append(stage)
            reader = {"kind": "ipc_reader", "resource_id": rid,
                      "schema": schema,
                      "num_partitions": n_out}
            return reader, [sid]
        out = dict(d)
        deps: List[int] = []
        for key, val in d.items():
            if isinstance(val, dict) and "kind" in val:
                out[key], sub = self._split_node(val)
                deps.extend(sub)
            elif key == "inputs" and isinstance(val, list):  # union
                subs = []
                for v in val:
                    nv, sub = self._split_node(v)
                    subs.append(nv)
                    deps.extend(sub)
                out[key] = subs
        return out, deps

    @staticmethod
    def _plan_info(d: Dict[str, Any]):
        """ONE planning pass per stage: (task count, output schema dict)."""
        from blaze_tpu.plan import create_plan
        from blaze_tpu.plan.types import schema_to_dict
        plan = create_plan(d)
        return max(1, plan.num_partitions), schema_to_dict(plan.schema)

    # -- per-task plan rewrite --------------------------------------------

    def _per_task(self, d, task: int, n_tasks: int,
                  in_broadcast: bool = False):
        if not isinstance(d, dict) or "kind" not in d:
            return d
        k = d["kind"]
        out = dict(d)
        if k in _SCAN_KINDS:
            groups = d.get("file_groups", [])
            if in_broadcast:
                # a broadcast is a full copy: every task sees every file
                all_files = [f for g in groups for f in g]
                new_groups: List[List[str]] = [[] for _ in range(n_tasks)]
                new_groups[task] = all_files
            else:
                if len(groups) > n_tasks:
                    raise ValueError(
                        f"scan has {len(groups)} file groups but the stage "
                        f"runs {n_tasks} tasks; repartition the input")
                # in-process semantics: partition p of a scan with fewer
                # groups than the stage yields nothing (ops emit only for
                # partition < child.num_partitions)
                new_groups = [[] for _ in range(n_tasks)]
                if task < len(groups):
                    new_groups[task] = list(groups[task])
            out["file_groups"] = new_groups
            return out
        # build sides of broadcast joins are full copies for every task
        if k in ("broadcast_join", "broadcast_nested_loop_join"):
            build = d.get("build_side", "right")
            for side in ("left", "right"):
                out[side] = self._per_task(d[side], task, n_tasks,
                                           in_broadcast or side == build)
            if "join_filter" in out and out["join_filter"] is None:
                del out["join_filter"]
            return out
        if k == "broadcast_join_build_hash_map":
            out["input"] = self._per_task(d["input"], task, n_tasks, True)
            return out
        for key, val in d.items():
            if isinstance(val, dict) and "kind" in val:
                out[key] = self._per_task(val, task, n_tasks, in_broadcast)
            elif key == "inputs" and isinstance(val, list):
                out[key] = [self._per_task(v, task, n_tasks, in_broadcast)
                            for v in val]
        return out

    # -- execution ---------------------------------------------------------

    def _run_tasks(self, fn, n: int, what: str, remote=None,
                   sid: Optional[int] = None) -> List[Any]:
        from blaze_tpu.bridge.tasks import default_task_parallelism, run_tasks
        # host placement caps slots harder than the executor-size knob:
        # serial tasks around intra-op-parallel C++ kernels beat
        # GIL-contended task concurrency (see default_task_parallelism)
        workers = min(self._par, default_task_parallelism(n))
        if sid is not None:
            from blaze_tpu.plan import statstore
            if statstore.enabled():
                qid = getattr(self._query, "query_id", None)
                if qid is not None:
                    from blaze_tpu.serving import progress
                    progress.note_stage_start(qid, sid, n)
                    inner = fn

                    def fn(i, _inner=inner, _qid=qid, _sid=sid):
                        out = _inner(i)
                        progress.note_task_done(_qid, _sid)
                        return out
        return run_tasks(fn, n, self._timeout, what, max_workers=workers,
                         query=self._query, remote=remote)

    def _note_placement(self, sid: int, exchange: str,
                        loop_before: int) -> None:
        """Record the OBSERVED placement of one stage.  On the rss/file
        tiers the device loop engages inside the fused operator itself,
        so the evidence is the xla_stats stage_loop_tasks delta across
        the stage's map tasks (best-effort under concurrent queries)."""
        from blaze_tpu.bridge import xla_stats
        after = xla_stats.stage_loop_stats()["stage_loop_tasks"]
        self.stage_placement[sid] = {
            "compute": "device-loop" if after > loop_before else "staged",
            "exchange": exchange}
        self._note_history_stage(sid)

    def _note_history_stage(self, sid: int) -> None:
        """Persist one stage_complete event: observed placement plus the
        merged metric summary of the stage's tasks (bridge/history.py;
        no-op unless auron.tpu.history.enable and a serving query owns
        the run)."""
        from blaze_tpu.bridge import history
        if not history.enabled():
            return
        qid = getattr(self._query, "query_id", None)
        if qid is None:
            return
        placement = self.stage_placement.get(sid, {})
        with self._metrics_lock:
            node = self.stage_metrics.get(sid)
            values = dict(node.values) if node is not None else {}
        metrics = {k: int(values[k]) for k in
                   ("output_rows", "output_batches", "elapsed_compute_ns",
                    "spilled_bytes", "io_bytes") if k in values}
        tasks = next((s.num_tasks for s in self.stages if s.sid == sid),
                     None)
        history.note_stage(qid, sid=sid,
                           exchange=placement.get("exchange", "unknown"),
                           compute=placement.get("compute", "unknown"),
                           tasks=tasks, metrics=metrics)

    @staticmethod
    def _part_of(stage: Stage) -> Dict[str, Any]:
        part = dict(stage.partitioning)
        if part["kind"] == "single":
            part = {"kind": "single", "num_partitions": 1}
        return part

    def _map_data_path(self, sid: int, m: int) -> str:
        return os.path.join(self._dir, f"s{self._run_id}-{sid}-{m}.data")

    def _next_attempt(self, sid: int, m: int) -> int:
        with self._attempt_lock:
            a = self._attempt_seq.get((sid, m), 0)
            self._attempt_seq[(sid, m)] = a + 1
            return a

    def _map_task_def(self, stage: Stage, part: Dict[str, Any],
                      m: int) -> Dict[str, Any]:
        """The self-contained shuffle-writer TaskDefinition for one map
        task — everything a worker PROCESS needs (absolute file paths,
        the per-task plan slice), no scheduler state.

        With speculation enabled every invocation (first run, retry,
        speculative duplicate, recovery re-run) writes under a FRESH
        attempt-suffixed .data/.index pair; the writer's first-wins
        promotion (shuffle.writer.promote_attempt_output) decides which
        attempt owns the final unsuffixed index — ONE os.replace is the
        commit, and the loser's files are discarded unread."""
        from blaze_tpu import config
        data = self._map_data_path(stage.sid, m)
        index = data[:-5] + ".index"
        attempt = 0
        if config.SPECULATION_ENABLE.get():
            attempt = self._next_attempt(stage.sid, m)
            base = data[:-5]
            data = f"{base}.a{attempt}.data"
            index = f"{base}.a{attempt}.index"
        plan = {"kind": "shuffle_writer", "partitioning": part,
                "data_file": data,
                "index_file": index,
                "input": self._per_task(stage.plan, m, stage.num_tasks)}
        return {"stage_id": stage.sid, "partition_id": m,
                "num_partitions": stage.num_tasks,
                "task_attempt_id": attempt, "plan": plan}

    def _run_map_task(self, stage: Stage, part: Dict[str, Any],
                      m: int) -> None:
        """One producer map task: stage plan -> shuffle_writer ->
        .data/.index (the writer commits via tmp + os.replace, so a
        recovery re-run atomically replaces the poisoned output)."""
        from blaze_tpu.bridge.runtime import NativeExecutionRuntime
        from blaze_tpu.plan.proto_serde import task_definition_to_bytes
        td = task_definition_to_bytes(self._map_task_def(stage, part, m))
        rt = NativeExecutionRuntime(td).start()
        try:
            for _ in rt.batches():
                pass
        finally:
            self._record_task_metrics(stage.sid, rt.finalize())
        with self._metrics_lock:
            self.task_runs[(stage.sid, m)] = \
                self.task_runs.get((stage.sid, m), 0) + 1
            self._map_worker[(stage.sid, m)] = None

    @staticmethod
    def _reader_rids(d) -> set:
        """Every stage:// shuffle resource an ipc_reader in this plan
        slice will resolve at execute time."""
        rids: set = set()
        if isinstance(d, dict):
            rid = d.get("resource_id")
            if d.get("kind") == "ipc_reader" and isinstance(rid, str) \
                    and rid.startswith("stage://"):
                rids.add(rid)
            for v in d.values():
                if isinstance(v, dict):
                    rids |= DagScheduler._reader_rids(v)
                elif isinstance(v, list):
                    for x in v:
                        rids |= DagScheduler._reader_rids(x)
        return rids

    def _shuffle_inputs(self, plan) -> Optional[Dict[str, list]]:
        """MapOutputTracker analog: resolve every stage:// reader in a
        per-task plan to its on-disk segment list, so a worker PROCESS
        can read upstream shuffle output without the parent's resource
        map.  {rid: [per-reduce-partition [(data, off, len, sid, mid)]]}.
        None = some input is not file-backed (device or RSS shuffle
        tier) and the task must stay in-process.  An invalidated map
        output raises FetchFailedError here, at dispatch, exactly as
        blocks_for would at read time."""
        inputs: Dict[str, list] = {}
        for rid in self._reader_rids(plan):
            try:
                up_sid = int(rid.rsplit("/", 1)[1])
            except ValueError:
                return None
            outputs = dict(self._stage_outputs.get(up_sid) or {})
            if not outputs:
                return None  # device/RSS tier: blocks live in-process
            n_out = None
            for entry in outputs.values():
                if entry is not None:
                    n_out = len(entry[1]) - 1
                    break
            if n_out is None:
                return None
            parts = []
            for p in range(n_out):
                segs = []
                for map_id in sorted(outputs):
                    entry = outputs[map_id]
                    if entry is None:
                        raise FetchFailedError(
                            up_sid, map_id,
                            "map output invalidated after worker crash")
                    data, offsets = entry
                    length = offsets[p + 1] - offsets[p]
                    if length:
                        segs.append((data, int(offsets[p]), int(length),
                                     up_sid, map_id))
                parts.append(segs)
            inputs[rid] = parts
        return inputs

    def _map_remote(self, stage: Stage, part: Dict[str, Any]):
        """Worker-pool spec factory for this stage's map tasks, or None
        when the pool is disabled (the in-process path stays the
        default).  spec(m) is re-evaluated per ATTEMPT, so shuffle-input
        locations are re-resolved after a lineage recovery round; it
        returns None for a task whose inputs aren't shippable, which
        falls that one task back in-process."""
        from blaze_tpu import config
        if not config.WORKERS_ENABLE.get():
            # serving-mode queries may opt map tasks onto the pool even
            # when the global switch is off, so N admitted queries get
            # process parallelism instead of time-slicing one interpreter
            if self._query is None or not config.SERVING_USE_WORKERS.get():
                return None

        def spec(m: int) -> Optional[Dict[str, Any]]:
            td = self._map_task_def(stage, part, m)
            si = self._shuffle_inputs(td["plan"]["input"])
            if si is None:
                return None
            if si:
                td["shuffle_inputs"] = si
            return {"fn": "blaze_tpu.parallel.workers:run_shuffle_map_task",
                    "args": (td,)}
        return spec

    def _absorb_remote_results(self, stage: Stage, results,
                               map_ids=None) -> None:
        """Fold worker-process map-task results into scheduler state:
        the metric tree rode the result frame home, and the producing
        worker's id is remembered so a later crash of that worker can
        re-validate exactly these outputs."""
        if map_ids is None:
            map_ids = range(len(results))
        for m, res in zip(map_ids, results):
            if not isinstance(res, dict):
                continue  # in-process fallback already recorded itself
            tree = res.get("metrics")
            if tree:
                self._record_task_metrics(stage.sid,
                                          MetricNode.from_dict(tree))
            with self._metrics_lock:
                self.task_runs[(stage.sid, m)] = \
                    self.task_runs.get((stage.sid, m), 0) + 1
                self._map_worker[(stage.sid, m)] = res.get("_worker_id")

    def _read_map_output(self, stage: Stage, m: int, n_out: int) -> tuple:
        """Validated (data_file, offsets) for one map output; a bad index
        is re-raised carrying the producer's (stage, map) identity so the
        recovery loop knows exactly which task to re-run.

        Under speculation the unsuffixed index is the COMMITTED winner's
        (one os.replace promoted it) and the claim file names which
        attempt's .data file backs it — resolve_attempt_data maps the
        base path to the winner; without a claim (speculation off) the
        base path IS the data file, byte-identical to the old behavior."""
        from blaze_tpu.shuffle.exchange import read_index_file
        from blaze_tpu.shuffle.writer import resolve_attempt_data
        base = self._map_data_path(stage.sid, m)
        data, attempt = resolve_attempt_data(base)
        try:
            offsets = read_index_file(base[:-5] + ".index",
                                      expected_partitions=n_out,
                                      data_file=data)
        except FetchFailedError as e:
            raise FetchFailedError(stage.sid, m, e.reason) from e
        with self._attempt_lock:
            self._map_attempt[(stage.sid, m)] = attempt
        return data, offsets

    def _register_stage_files(self, sid: int) -> None:
        """Sweep the scratch dir for this stage's files (attempt-suffixed
        outputs, claim files, promoted indexes) into the cleanup list —
        a losing speculative attempt's leftovers must not outlive the
        scheduler even when the loser already unlinked its own pair."""
        prefix = f"s{self._run_id}-{sid}-"
        try:
            names = os.listdir(self._dir)
        except OSError:
            return
        for name in names:
            if not name.startswith(prefix):
                continue
            p = os.path.join(self._dir, name)
            if p not in self._files:
                self._files.append(p)

    def _clear_map_commit(self, sid: int, m: int) -> None:
        """Un-commit one map output before a lineage-recovery re-run:
        the committed winner's index is the poisoned block being
        recovered, so the claim AND the promoted index must go — a
        fresh attempt can then win the first-wins race cleanly.  A
        no-op when no claim exists (speculation off: the recovery
        re-run os.replaces the unsuffixed index in place, as always)."""
        base = self._map_data_path(sid, m)
        owner = base[:-5] + ".index.owner"
        if not os.path.exists(owner):
            return
        for p in (owner, base[:-5] + ".index"):
            try:
                os.unlink(p)
            except OSError:
                pass

    @staticmethod
    def _is_cancellation(e: BaseException) -> bool:
        """Cancellation/deadline/kill must never be swallowed into a
        shuffle-tier fallback: the query is being torn down, not
        recovering."""
        from blaze_tpu.serving.context import is_cancellation
        return is_cancellation(e)

    # -- cross-query subplan cache (auron.tpu.cache.subplan) ---------------

    def _subplan_cache_key(self, stage: Stage):
        """(fingerprint, snapshot) when this producer stage is shareable
        across queries, else None.  Only LEAF stages qualify: a stage
        reading upstream exchanges carries run-scoped stage:// resource
        ids, so its identity can never match another run's anyway."""
        from blaze_tpu import config
        if not (config.CACHE_ENABLE.get() and config.CACHE_SUBPLAN.get()):
            return None
        if stage.partitioning is None or self._reader_rids(stage.plan):
            return None
        if stage.aqe is not None:
            # an AQE-rewritten stage carries run-scoped derived
            # resources; its static fingerprint no longer describes its
            # shape (belt and braces: rewritten stages always hold
            # readers, which the check above already declines)
            return None
        from blaze_tpu.plan import fingerprint as fp_mod
        snap = fp_mod.source_snapshot(stage.plan)
        if snap is None:
            return None
        part = self._part_of(stage)
        fp = fp_mod.subplan_fingerprint(stage.plan, part, stage.num_tasks)
        return fp, snap

    def _try_cached_producer(self, stage: Stage) -> bool:
        """Serve one map stage from the cross-query cache: publish the
        cached partition blocks under the stage's resource id (the raw-
        bytes block shape the device tier already publishes) and skip
        the whole map wave.  Misses remember the key so the fresh output
        is stored after the file-tier wave commits."""
        key = self._subplan_cache_key(stage)
        if key is None:
            return False
        from blaze_tpu.cache import results as result_cache
        cache = result_cache.get_cache()
        if cache is None:
            return False
        fp, snap = key
        blocks = cache.get_subplan(fp, snap)
        if blocks is None:
            self._pending_subplan[stage.sid] = key
            return False
        sid = stage.sid
        self._cached_stages[sid] = key
        # empty map-output table: _shuffle_inputs finds no file-backed
        # entries, so consumer tasks stay in-process (same contract as
        # the device tier)
        self._stage_outputs[sid] = {}

        def blocks_for(reduce_id: int, _blocks=blocks):
            for blk in _blocks.get(reduce_id, ()):
                yield blk

        put_resource(stage.resource_id, blocks_for)
        if stage.resource_id not in self._resources:
            self._resources.append(stage.resource_id)
        self.stage_placement[sid] = {"compute": "cached",
                                     "exchange": "cached"}
        self._note_history_stage(sid)
        from blaze_tpu.bridge import tracing
        tracing.instant("subplan_cache_hit", stage=sid, fingerprint=fp)
        return True

    def _maybe_store_subplan(self, stage: Stage) -> None:
        """After a file-tier map wave commits, store the per-reduce
        partition bytes (the exact committed .data segments, still in
        their on-disk IPC frame form) so a later query with the same
        producing subtree replays them instead of re-running the wave."""
        key = self._pending_subplan.pop(stage.sid, None)
        if key is None:
            return
        from blaze_tpu.cache import results as result_cache
        cache = result_cache.get_cache()
        if cache is None:
            return
        outputs = self._stage_outputs.get(stage.sid) or {}
        n_out = int(self._part_of(stage).get("num_partitions", 1))
        blocks: Dict[int, list] = {}
        try:
            for map_id in sorted(outputs):
                entry = outputs[map_id]
                if entry is None:
                    return  # invalidated mid-wave: nothing safe to store
                data, offsets = entry
                with open(data, "rb") as f:
                    for r in range(n_out):
                        length = int(offsets[r + 1] - offsets[r])
                        if not length:
                            continue
                        f.seek(int(offsets[r]))
                        blocks.setdefault(r, []).append(f.read(length))
        except OSError:
            return  # torn output: cache nothing, the files stay truth
        cache.put_subplan(key[0], key[1], blocks)

    def _invalidate_cached_stage(self, sid: int) -> None:
        """A cached stage's replay went bad: drop the entry and re-run
        the producer with the cache bypassed — fresh execution is the
        recovery path, never a second replay of suspect bytes."""
        key = self._cached_stages.pop(sid, None)
        if key is None:
            return
        from blaze_tpu.cache import results as result_cache
        cache = result_cache.get_cache()
        if cache is not None:
            cache.invalidate(key[0])

    def _run_producer(self, stage: Stage) -> None:
        """One exchange boundary: device-resident collective when the
        planner marked it eligible; else the elastic shuffle service
        (auron.tpu.shuffle.service) when configured, so concurrent
        queries don't contend on local disk; host shuffle files
        otherwise — and the file path is ALSO the fallback for any
        device- or service-tier failure.  The higher tiers are
        optimizations, never a new failure mode."""
        if self._try_cached_producer(stage):
            return
        if stage.device_spec is not None:
            try:
                self._run_producer_device(stage)
                return
            except (KeyboardInterrupt, SystemExit):
                raise
            except FetchFailedError:
                # an UPSTREAM block was poisoned: the lineage identity
                # must reach the recovery loop, not trigger a fallback
                raise
            except Exception as e:
                if self._is_cancellation(e):
                    raise
                from blaze_tpu.bridge import tracing, xla_stats
                xla_stats.note_device_shuffle_fallback()
                tracing.instant("device_shuffle_fallback",
                                stage=stage.sid, error=type(e).__name__)
        rss_root = self._rss_root()
        if rss_root is not None:
            try:
                self._run_producer_rss(stage, rss_root)
                return
            except (KeyboardInterrupt, SystemExit):
                raise
            except FetchFailedError:
                raise
            except Exception as e:
                if self._is_cancellation(e):
                    raise
                from blaze_tpu.bridge import tracing
                tracing.instant("rss_shuffle_fallback", stage=stage.sid,
                                error=type(e).__name__)
        self._run_producer_file(stage)
        self._maybe_store_subplan(stage)

    @staticmethod
    def _rss_root() -> Optional[str]:
        """Shared-storage root of the elastic shuffle tier, or None for
        local files (the default)."""
        from blaze_tpu import config
        root = config.SHUFFLE_SERVICE.get().strip()
        return root or None

    def _run_map_task_collect(self, stage: Stage,
                              m: int) -> List[pa.RecordBatch]:
        """One producer map task WITHOUT the shuffle_writer wrapper: the
        stage plan's batches come back over the wire for the device
        exchange to repartition.  Same TaskDefinition path, metrics and
        task_runs accounting as the file-shuffle map task."""
        from blaze_tpu.bridge.runtime import NativeExecutionRuntime
        from blaze_tpu.plan.proto_serde import task_definition_to_bytes
        td = task_definition_to_bytes(
            {"stage_id": stage.sid, "partition_id": m,
             "num_partitions": stage.num_tasks,
             "plan": self._per_task(stage.plan, m, stage.num_tasks)})
        rt = NativeExecutionRuntime(td).start()
        try:
            out = list(rt.batches())
        finally:
            self._record_task_metrics(stage.sid, rt.finalize())
        with self._metrics_lock:
            self.task_runs[(stage.sid, m)] = \
                self.task_runs.get((stage.sid, m), 0) + 1
        return out

    def _run_map_task_loop(self, stage: Stage, m: int):
        """One producer map task through the device-resident stage loop
        (runtime/loop.py): ONE program dispatch per chunk of batches,
        then a device-side drain so the map output reaches
        DeviceExchange without a host round trip.  Returns (datas,
        valids, n) device column arrays, or None — disabled, stage
        ineligible, or wholesale fallback — in which case the caller
        runs the staged per-batch collect.  Cancellation and lineage
        (FetchFailed) always propagate."""
        from blaze_tpu.bridge.runtime import NativeExecutionRuntime
        from blaze_tpu.plan import stage_compiler
        from blaze_tpu.plan.proto_serde import task_definition_to_bytes
        if not stage_compiler.stage_loop_active():
            return None
        td = task_definition_to_bytes(
            {"stage_id": stage.sid, "partition_id": m,
             "num_partitions": stage.num_tasks,
             "plan": self._per_task(stage.plan, m, stage.num_tasks)})
        rt = NativeExecutionRuntime(td)  # plan pipeline only: not started
        prog = stage_compiler.compile_task_plan(rt.plan)
        if prog is None:
            return None
        from blaze_tpu.bridge import tracing, xla_stats
        from blaze_tpu.bridge.context import task_scope
        from blaze_tpu.runtime import loop as device_loop
        try:
            with task_scope(rt.task):
                carry = device_loop.run_partition(prog, m,
                                                  ctx=str(stage.sid))
                out = device_loop.drain_device(prog, carry)
        except (KeyboardInterrupt, SystemExit, FetchFailedError):
            raise
        except Exception as e:
            if self._is_cancellation(e):
                raise
            xla_stats.note_stage_loop_fallback()
            tracing.instant("stage_loop_fallback", stage=stage.sid,
                            task=m, reason=str(e))
            return None
        finally:
            self._record_task_metrics(stage.sid, rt.finalize())
        with self._metrics_lock:
            self.task_runs[(stage.sid, m)] = \
                self.task_runs.get((stage.sid, m), 0) + 1
        return out

    @staticmethod
    def _merge_map_outputs(batches: List[pa.RecordBatch], col_tasks,
                           schema):
        """Per-task map outputs -> one (cols, valids) column set for the
        exchange.  All-loop output stays as device arrays (D2D: the
        exchange shards them without a host round trip); any staged
        batches force the host concat path."""
        import numpy as np
        if col_tasks and not batches:
            import jax.numpy as jnp
            ncols = len(col_tasks[0][0])
            cols = [jnp.concatenate([t[0][i] for t in col_tasks])
                    for i in range(ncols)]
            valids = [jnp.concatenate([t[1][i] for t in col_tasks])
                      for i in range(ncols)]
            return cols, valids
        cols, valids = _batches_to_columns(batches, schema)
        for datas, vls, _n in col_tasks:
            for i, (d, v) in enumerate(zip(datas, vls)):
                cols[i] = np.concatenate(
                    [cols[i], np.asarray(d).astype(cols[i].dtype)])
                valids[i] = np.concatenate(
                    [valids[i], np.asarray(v).astype(bool)])
        return cols, valids

    def _exchange_sync(self, stage: Stage, spec, n_out: int, schema):
        """Synchronous device exchange: run the whole map wave, merge
        every task's columns into one set, then ONE exchange + encode.
        The `device_exchange` span covers merge+exchange+encode only —
        NOT the map wave — so the device ledger's barrier_idle category
        sees the real fold-end -> exchange-start gap this path pays.
        shuffle_barrier_idle_ns counts the FIRST-finisher's wait: the
        earliest-completed task's output sits at the barrier until the
        last straggler lands and the merged exchange can start — the
        exact idle the overlapped path dispatches away."""
        import time as _time

        from blaze_tpu import config
        from blaze_tpu.bridge import tracing, xla_stats
        from blaze_tpu.parallel.stage import (DeviceExchange,
                                              DeviceExchangeError)
        from blaze_tpu.shuffle.ipc import write_batches_to_bytes

        done_ns: List[int] = []

        def one_map(m: int):
            out = self._run_map_task_loop(stage, m)
            if out is not None:
                done_ns.append(_time.perf_counter_ns())
                return ("cols", out)
            res = ("batches", self._run_map_task_collect(stage, m))
            done_ns.append(_time.perf_counter_ns())
            return res

        per_task = self._run_tasks(
            one_map, stage.num_tasks,
            f"stage {stage.sid} (device shuffle)", sid=stage.sid)
        batches = [b for kind, out in per_task if kind == "batches"
                   for b in out if b.num_rows]
        col_tasks = [out for kind, out in per_task
                     if kind == "cols" and out[2] > 0]
        loop_tasks = sum(1 for kind, _o in per_task if kind == "cols")
        blocks: Dict[int, bytes] = {}
        if batches or col_tasks:
            with tracing.span("device_exchange", stage=stage.sid,
                              tasks=stage.num_tasks, partitions=n_out):
                cols, valids = self._merge_map_outputs(batches,
                                                       col_tasks, schema)
                est = sum(int(c.nbytes) for c in cols)
                if est > config.SHUFFLE_DEVICE_MAX_BYTES.get():
                    raise DeviceExchangeError(
                        f"map output {est}B exceeds "
                        f"auron.tpu.shuffle.device.maxBytes")
                if done_ns:
                    xla_stats.note_barrier_idle(
                        max(0, _time.perf_counter_ns() - min(done_ns)))
                parts = DeviceExchange().exchange(
                    cols, valids, spec["key_indices"], n_out,
                    ctx=str(stage.sid))
                arrow_schema = schema.to_arrow()
                for r, (datas, vls) in enumerate(parts):
                    if datas and len(datas[0]):
                        rb = _columns_to_batch(datas, vls, arrow_schema)
                        blocks[r] = write_batches_to_bytes([rb])
        return blocks, loop_tasks

    def _exchange_overlapped(self, stage: Stage, spec, n_out: int,
                             schema):
        """Overlap scheduler (auron.tpu.exchange.overlap.enable): each
        map task's columns are DISPATCHED into the mesh collective the
        moment its fold finishes (parallel/stage.py ExchangeTicket) and
        DRAINED on one background thread, so task k's all-to-all and
        partition split run while task k+1 is still folding.  Contracts
        kept vs the synchronous path:

          * dispatch/drain failures — injected `device-collective`
            faults included — are recorded and re-raised only AFTER the
            wave, so task-retry machinery never sees them and the
            wholesale file fallback stays the one failure path;
          * overlap is fenced at hash-table regrow boundaries
            (runtime/loop.py exchange_fence) to keep the atomic
            overflow/rehash contract;
          * cancellation propagates from the wave within one chunk, and
            the drainer thread is always joined (leak_report clean);
          * assembly concatenates per-partition rows in the synchronous
            merge order (staged-batch tasks by task index, then
            device-col tasks) and encodes ONE RecordBatch per
            partition, so published blocks are byte-identical.
        """
        import queue as _queue
        import time as _time

        import numpy as np

        from blaze_tpu import config
        from blaze_tpu.bridge import tracing, xla_stats
        from blaze_tpu.parallel.stage import (DeviceExchange,
                                              DeviceExchangeError)
        from blaze_tpu.runtime import loop as device_loop
        from blaze_tpu.shuffle.ipc import write_batches_to_bytes

        exchange = DeviceExchange()
        depth = max(1, int(config.EXCHANGE_OVERLAP_DEPTH.get()))
        max_bytes = config.SHUFFLE_DEVICE_MAX_BYTES.get()
        slots = threading.Semaphore(depth)
        lock = threading.Lock()
        idle = threading.Condition(lock)
        state = {"inflight": 0, "est": 0, "first_dispatch": None}
        errors: List[BaseException] = []
        parts_by_task: Dict[Tuple[int, int], list] = {}
        q: "_queue.Queue" = _queue.Queue()

        def drainer():
            while True:
                item = q.get()
                if item is None:
                    return
                key, ticket = item
                try:
                    parts = exchange.drain(ticket)
                    parts = [([np.asarray(d) for d in ds],
                              [np.asarray(v) for v in vs])
                             for ds, vs in parts]
                    tracing.emit_span(
                        "device_exchange",
                        _time.perf_counter_ns() - ticket.dispatch_ns,
                        stage=stage.sid, task=key[1], partitions=n_out,
                        overlapped=True)
                    xla_stats.note_exchange_overlap()
                    with lock:
                        parts_by_task[key] = parts
                except BaseException as e:  # re-raised after the wave
                    with lock:
                        errors.append(e)
                finally:
                    with idle:
                        state["inflight"] -= 1
                        idle.notify_all()
                    slots.release()

        def fence():
            # regrow boundary: drain every in-flight ticket before the
            # carry doubles (runtime/loop.py calls this pre-rehash)
            with idle:
                while state["inflight"]:
                    idle.wait(0.05)

        def one_map(m: int):
            out = self._run_map_task_loop(stage, m)
            if out is not None:
                kind, rank = "cols", 1
                cols, valids, nrows = out
            else:
                kind, rank = "batches", 0
                bs = [b for b in self._run_map_task_collect(stage, m)
                      if b.num_rows]
                cols, valids = _batches_to_columns(bs, schema)
                nrows = len(cols[0]) if cols else 0
            with lock:
                doomed = bool(errors)
            if doomed or nrows == 0:
                return (kind, None)
            fold_end = _time.perf_counter_ns()
            slots.acquire()  # backpressure: at most `depth` in flight
            try:
                with lock:
                    state["est"] += sum(int(c.nbytes) for c in cols)
                    est = state["est"]
                if est > max_bytes:
                    raise DeviceExchangeError(
                        f"map output {est}B exceeds "
                        f"auron.tpu.shuffle.device.maxBytes")
                ticket = exchange.dispatch(cols, valids,
                                           spec["key_indices"], n_out,
                                           ctx=str(stage.sid))
                with idle:
                    if state["first_dispatch"] is None:
                        state["first_dispatch"] = ticket.dispatch_ns
                    state["inflight"] += 1
                # barrier idle here is only the backpressure wait for a
                # dispatch slot — vs the sync path's first-finisher wait
                # for the LAST straggler before its one merged exchange
                xla_stats.note_barrier_idle(
                    max(0, ticket.dispatch_ns - fold_end))
                q.put(((rank, m), ticket))
            except BaseException as e:
                slots.release()
                with lock:
                    errors.append(e)
            return (kind, True)

        drain_thread = threading.Thread(
            target=drainer, name=f"exchange-drain-{stage.sid}",
            daemon=True)
        drain_thread.start()
        try:
            with device_loop.exchange_fence(fence):
                per_task = self._run_tasks(
                    one_map, stage.num_tasks,
                    f"stage {stage.sid} (device shuffle)",
                    sid=stage.sid)
        finally:
            q.put(None)
            drain_thread.join()
        if errors:
            raise errors[0]
        loop_tasks = sum(1 for kind, _o in per_task if kind == "cols")

        blocks: Dict[int, bytes] = {}
        keys = sorted(parts_by_task)  # sync merge order
        if keys:
            arrow_schema = schema.to_arrow()
            base = parts_by_task[keys[0]]
            for r in range(n_out):
                part_list = [parts_by_task[k][r] for k in keys]
                ncols = len(base[r][0])
                datas = [np.concatenate(
                    [np.asarray(p[0][i]).astype(base[r][0][i].dtype)
                     for p in part_list]) for i in range(ncols)]
                vls = [np.concatenate(
                    [np.asarray(p[1][i]).astype(bool)
                     for p in part_list]) for i in range(ncols)]
                if datas and len(datas[0]):
                    rb = _columns_to_batch(datas, vls, arrow_schema)
                    blocks[r] = write_batches_to_bytes([rb])
        return blocks, loop_tasks

    def _run_producer_device(self, stage: Stage) -> None:
        """Tentpole path: run the producer's map tasks — through the
        device-resident stage loop when the stage compiles, the staged
        per-batch executor otherwise — repartition their output through
        the mesh collective (parallel/stage.py DeviceExchange) and
        publish per-reduce-partition rows as in-memory IPC bytes blocks
        (shuffle/reader.py read_block consumes raw bytes directly).
        With auron.tpu.exchange.overlap.enable the exchange is
        dispatched per map task and drained in the background
        (_exchange_overlapped); otherwise one synchronous exchange runs
        after the wave (_exchange_sync) — both publish byte-identical
        blocks.  Any failure raises out to _run_producer, which falls
        back to the file path."""
        from blaze_tpu import config
        from blaze_tpu.plan.types import schema_from_dict

        spec = stage.device_spec
        n_out = int(spec["num_partitions"])
        schema = schema_from_dict(stage.out_schema)

        if config.EXCHANGE_OVERLAP_ENABLE.get():
            blocks, loop_tasks = self._exchange_overlapped(
                stage, spec, n_out, schema)
        else:
            blocks, loop_tasks = self._exchange_sync(
                stage, spec, n_out, schema)
        self.stage_placement[stage.sid] = {
            "compute": ("device-loop" if loop_tasks == stage.num_tasks
                        else "mixed" if loop_tasks else "staged"),
            "exchange": "device"}
        self._note_history_stage(stage.sid)
        from blaze_tpu.plan import adaptive, statstore
        if statstore.enabled() or adaptive.enabled():
            self._note_boundary(stage, [len(blocks.get(r, b""))
                                        for r in range(n_out)], "device")

        sid = stage.sid
        self._stage_outputs[sid] = {}

        def blocks_for(reduce_id: int):
            blk = blocks.get(reduce_id)
            if blk is not None:
                yield blk

        put_resource(stage.resource_id, blocks_for)
        if stage.resource_id not in self._resources:
            self._resources.append(stage.resource_id)

    def _run_producer_rss(self, stage: Stage, root: str) -> None:
        """Elastic shuffle tier: map tasks PUSH partition frames to the
        shared-storage shuffle service (shuffle/rss.py, the Celeborn
        analog) instead of writing local .data/.index files.  Each task
        retry pushes under a FRESH attempt id — commits are first-wins,
        so readers see exactly one complete attempt per map regardless
        of mid-push failures."""
        from blaze_tpu.bridge import tracing
        from blaze_tpu.bridge.runtime import NativeExecutionRuntime
        from blaze_tpu.plan.proto_serde import task_definition_to_bytes
        from blaze_tpu.shuffle.rss import rss_client_for

        part = self._part_of(stage)
        n_out = int(part.get("num_partitions", 1))
        client = rss_client_for(root, f"{self._run_id}-{stage.sid}",
                                stage.num_tasks, n_out)
        self._rss_clients.append(client)
        attempts: Dict[int, int] = {}
        attempts_lock = threading.Lock()

        def run_map(m: int) -> None:
            with attempts_lock:
                attempt = attempts.get(m, 0)
                attempts[m] = attempt + 1
            writer = client.partition_writer(m, attempt)
            rid = f"rss://{self._run_id}/{stage.sid}/{m}/a{attempt}"
            put_resource(rid, writer)
            try:
                plan = {"kind": "rss_shuffle_writer", "partitioning": part,
                        "rss_resource_id": rid,
                        "input": self._per_task(stage.plan, m,
                                                stage.num_tasks)}
                td = task_definition_to_bytes(
                    {"stage_id": stage.sid, "partition_id": m,
                     "num_partitions": stage.num_tasks, "plan": plan})
                rt = NativeExecutionRuntime(td).start()
                try:
                    for _ in rt.batches():
                        pass
                finally:
                    self._record_task_metrics(stage.sid, rt.finalize())
                if not writer.commit():
                    # a sibling attempt already committed: this output
                    # is dead (reject-late arbitration); the task still
                    # succeeds — the winner's frames are what readers see
                    from blaze_tpu.bridge import xla_stats as _xs
                    _xs.note_speculation(loser_commits_rejected=1)
            finally:
                remove_resource(rid)
            with self._metrics_lock:
                self.task_runs[(stage.sid, m)] = \
                    self.task_runs.get((stage.sid, m), 0) + 1

        from blaze_tpu.bridge import xla_stats
        loop_before = xla_stats.stage_loop_stats()["stage_loop_tasks"]
        with tracing.span("rss_exchange", stage=stage.sid,
                          tasks=stage.num_tasks, partitions=n_out):
            self._run_tasks(run_map, stage.num_tasks,
                            f"stage {stage.sid} (rss push)",
                            sid=stage.sid)
        self._note_placement(stage.sid, "rss", loop_before)

        self._stage_outputs[stage.sid] = {}
        timeout = self._timeout

        def blocks_for(reduce_id: int):
            for blk in client.reader_blocks(reduce_id, timeout_s=timeout):
                yield blk

        put_resource(stage.resource_id, blocks_for)
        if stage.resource_id not in self._resources:
            self._resources.append(stage.resource_id)

    def _run_producer_file(self, stage: Stage) -> None:
        from blaze_tpu.shuffle.reader import FileSegmentBlock

        os.makedirs(self._dir, exist_ok=True)
        part = self._part_of(stage)
        n_out = int(part.get("num_partitions", 1))

        for m in range(stage.num_tasks):
            data = self._map_data_path(stage.sid, m)
            for p in (data, data[:-5] + ".index"):
                if p not in self._files:
                    self._files.append(p)

        from blaze_tpu.bridge import tracing, xla_stats
        loop_before = xla_stats.stage_loop_stats()["stage_loop_tasks"]
        with tracing.span("shuffle_exchange", stage=stage.sid,
                          tasks=stage.num_tasks,
                          partitioning=part["kind"]):
            try:
                results = self._run_tasks(
                    lambda m: self._run_map_task(stage, part, m),
                    stage.num_tasks, f"stage {stage.sid} (shuffle write)",
                    remote=self._map_remote(stage, part),
                    sid=stage.sid)
            finally:
                # attempt-suffixed outputs, claim files and a late
                # loser's leftovers all join the cleanup list even when
                # the wave itself failed
                self._register_stage_files(stage.sid)
        self._absorb_remote_results(stage, results)
        self._note_placement(stage.sid, "file", loop_before)

        self._stage_outputs[stage.sid] = {
            m: self._read_map_output(stage, m, n_out)
            for m in range(stage.num_tasks)}
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_host_exchange(sum(
            int(off[-1])
            for _, off in self._stage_outputs[stage.sid].values()))
        from blaze_tpu.plan import adaptive, statstore
        if statstore.enabled() or adaptive.enabled():
            self._note_boundary(stage, [
                sum(int(off[r + 1] - off[r])
                    for _, off in self._stage_outputs[stage.sid].values())
                for r in range(n_out)], "file")

        sid = stage.sid

        def blocks_for(reduce_id: int):
            # live read of the output map, in map-id order: recovered
            # outputs are picked up, and reduce input order stays
            # deterministic across recovery rounds
            outputs = self._stage_outputs[sid]
            for map_id in sorted(outputs):
                entry = outputs[map_id]
                if entry is None:
                    # invalidated after a worker crash: the producer
                    # must re-run before any reduce reads this slot
                    raise FetchFailedError(
                        sid, map_id,
                        "map output invalidated after worker crash")
                data, offsets = entry
                length = offsets[reduce_id + 1] - offsets[reduce_id]
                if length:
                    yield FileSegmentBlock(data, offsets[reduce_id],
                                           length, stage_id=sid,
                                           map_id=map_id)

        put_resource(stage.resource_id, blocks_for)
        if stage.resource_id not in self._resources:
            self._resources.append(stage.resource_id)

    # -- lineage recovery --------------------------------------------------

    def _recover_map_output(self, ff: FetchFailedError,
                            stages_by_id: Dict[int, Stage]) -> None:
        """Re-run exactly the map task that produced a poisoned block and
        republish its output (Spark's stage-resubmission narrowed to one
        task: in-process there is no executor loss, so only the named
        output can be bad)."""
        stage = stages_by_id.get(ff.stage_id)
        if stage is None or stage.partitioning is None \
                or not 0 <= ff.map_id < stage.num_tasks:
            raise ff  # no lineage to recover from
        if ff.stage_id in self._cached_stages:
            # the poisoned blocks were a cross-query cache replay:
            # invalidate the entry and re-produce the stage for real
            # (cache bypassed — the run owns fresh files from here on)
            self._invalidate_cached_stage(ff.stage_id)
            self._run_producer_file(stage)
            return
        from blaze_tpu.bridge import tracing, xla_stats
        part = self._part_of(stage)
        with tracing.span("stage_recovery", stage=ff.stage_id,
                          map_task=ff.map_id):
            # the poisoned block IS the committed winner: clear its
            # commit claim first so the recovery re-run's fresh attempt
            # can win the first-wins arbitration (also heals a torn
            # claim-without-index crash window)
            self._clear_map_commit(stage.sid, ff.map_id)
            # through the task pool: the re-run gets the same bounded
            # retry/backoff as any task (transient faults may still
            # fire), and under the worker pool it is process-isolated
            # like any other map task
            remote = self._map_remote(stage, part)
            try:
                results = self._run_tasks(
                    lambda _i: self._run_map_task(stage, part, ff.map_id),
                    1,
                    f"stage {ff.stage_id} recovery (map {ff.map_id})",
                    remote=(lambda _i: remote(ff.map_id))
                    if remote else None)
            finally:
                self._register_stage_files(stage.sid)
            self._absorb_remote_results(stage, results,
                                        map_ids=[ff.map_id])
            self._stage_outputs[stage.sid][ff.map_id] = \
                self._read_map_output(stage, ff.map_id,
                                      int(part.get("num_partitions", 1)))
        xla_stats.note_stage_recovery(1)
        from blaze_tpu.bridge import history
        if history.enabled():
            history.note_stage_recovery(
                getattr(self._query, "query_id", None),
                sid=ff.stage_id, map_task=ff.map_id)

    def invalidate_worker_outputs(self, worker_id) -> None:
        """WorkerPool crash listener: re-validate every committed map
        output the dead worker produced.  Committed outputs are FILES
        (tmp + os.replace), so unlike an executor's in-memory block
        store they normally survive the process — but a crash wedged
        between the .data and .index commits (or mid-rename) leaves a
        torn pair.  Anything that fails validation is marked None in
        the map-output table; blocks_for converts that into the
        FetchFailedError the lineage recovery loop already handles, so
        ONLY the poisoned producers re-run."""
        if worker_id is None:
            return
        with self._metrics_lock:
            owned = [key for key, w in self._map_worker.items()
                     if w == worker_id]
        if not owned:
            return
        stages_by_id = {st.sid: st for st in self.stages}
        for sid, m in owned:
            stage = stages_by_id.get(sid)
            outputs = self._stage_outputs.get(sid)
            if stage is None or outputs is None or m not in outputs \
                    or outputs[m] is None:
                continue
            n_out = int(self._part_of(stage).get("num_partitions", 1))
            try:
                outputs[m] = self._read_map_output(stage, m, n_out)
            except FetchFailedError:
                outputs[m] = None
                log.warning("stage %d map %d output invalidated after "
                            "worker %s crash", sid, m, worker_id)

    # -- AQE small-query fast path -----------------------------------------

    @staticmethod
    def _scan_input_bytes(plan: Dict[str, Any]) -> int:
        """Total bytes behind every file scan in the plan; local files
        only — any non-stat-able input (remote FS, mem tables count 0)
        disables the estimate with a sentinel."""
        total = 0
        stack = [plan]
        while stack:
            d = stack.pop()
            if not isinstance(d, dict):
                continue
            if d.get("kind") in _SCAN_KINDS:
                for group in d.get("file_groups", []):
                    for p in group:
                        try:
                            total += os.path.getsize(p)
                        except (OSError, TypeError):
                            return 1 << 62
            for v in d.values():
                if isinstance(v, dict):
                    stack.append(v)
                elif isinstance(v, list):
                    stack.extend(x for x in v if isinstance(x, dict))
        return total

    def _run_single_task(self, plan: Dict[str, Any]) -> pa.Table:
        """Local execution mode: the whole query runs in-process with
        exchanges as LocalShuffleExchange — the analog of Spark AQE's
        local shuffle reader / coalesce-to-one-partition on small
        queries, where per-stage fixed costs (task spin-up, plan
        round-trips, shuffle files) dominate the actual work several
        times over.  Exchanges never leave the process, so nothing
        needs a wire encoding."""
        from blaze_tpu.plan import create_plan
        from blaze_tpu.plan.column_pruning import prune_columns
        from blaze_tpu.plan.fused import fuse_plan
        from blaze_tpu.plan.planner import collapse_filter_project

        node = fuse_plan(prune_columns(
            collapse_filter_project(create_plan(plan))))
        out = node.execute_collect().to_arrow()
        self._record_task_metrics(0, node.collect_metrics())
        if isinstance(out, pa.RecordBatch):
            return pa.Table.from_batches([out])
        return out

    def _note_boundary(self, stage: Stage, part_bytes: List[int],
                       exchange: str) -> None:
        """Capture one shuffle boundary's per-partition bytes for the
        statistics store, keyed by the producer's subtree fingerprint.
        Must run at producer completion — cleanup() clears the
        map-output table before run_collect returns.  (The rss tier
        holds no local sizes; its boundaries are not captured.)"""
        try:
            from blaze_tpu.plan import fingerprint as fp_mod
            part = (self._part_of(stage) if stage.partitioning is not None
                    else None)
            # an AQE-rewritten stage records under its DERIVED
            # fingerprint: its plan embeds run-scoped derived resource
            # ids, and the static identity must never accrete stats
            # from a rewritten shape
            fp = (stage.aqe or {}).get("fingerprint") or \
                fp_mod.subplan_fingerprint(stage.plan, part,
                                           stage.num_tasks)
            with self._metrics_lock:
                node = self.stage_metrics.get(stage.sid)
                rows = (int(node.values.get("output_rows", 0) or 0)
                        if node is not None else 0)
            self.stage_boundaries[stage.sid] = {
                "fingerprint": fp, "sid": stage.sid,
                "tasks": stage.num_tasks,
                "partitions": len(part_bytes),
                "partition_bytes": [int(b) for b in part_bytes],
                "exchange": exchange, "output_rows": rows}
        except Exception:
            pass

    def _stats_begin(self, plan: Dict[str, Any]) -> None:
        """Arm the statistics feedback plane for this run: fingerprint
        the plan, baseline the counter plane + duration reservoirs, and
        register live progress.  No-op (one boolean) when
        auron.tpu.stats.enable is off."""
        from blaze_tpu.plan import statstore
        self.stats_fingerprint = None
        self.stage_boundaries = {}
        self._stats_base = None
        if not statstore.enabled():
            return
        try:
            import time
            from blaze_tpu.bridge import xla_stats
            from blaze_tpu.plan import fingerprint as fp_mod
            self.stats_fingerprint = fp_mod.plan_fingerprint(plan)
            self._stats_base = xla_stats.snapshot()
            self._stats_dur0 = {k: len(v) for k, v in
                                xla_stats.duration_samples().items()}
            self._stats_t0 = time.perf_counter()
            qid = getattr(self._query, "query_id", None)
            if qid is not None:
                prior = statstore.prior(self.stats_fingerprint)
                prior_wall = None
                if prior is not None:
                    prior_wall = (prior.get("derived") or {}).get(
                        "wall_p50_s")
                    if prior_wall:
                        xla_stats.note_stats(eta_seeded=1)
                from blaze_tpu.serving import progress
                progress.note_query_start(qid, self.stats_fingerprint,
                                          prior_wall)
        except Exception:
            self.stats_fingerprint = None
            self._stats_base = None

    def _stats_end(self, ok: bool) -> None:
        """Close the feedback loop: settle live progress and, on
        success, ingest this run's observation into the statstore
        (failed runs would poison the priors).  Never raises."""
        base, self._stats_base = self._stats_base, None
        if base is None:
            return
        try:
            import time
            from blaze_tpu.bridge import xla_stats
            from blaze_tpu.plan import statstore
            wall_s = time.perf_counter() - self._stats_t0
            qid = getattr(self._query, "query_id", None)
            if qid is not None:
                from blaze_tpu.serving import progress
                progress.note_query_done(
                    qid, "finished" if ok else "failed", wall_s=wall_s)
            if not ok:
                return
            delta = xla_stats.delta(base)
            samples = xla_stats.duration_samples()
            task_ns = samples.get("task_ns", [])[
                self._stats_dur0.get("task_ns", 0):]
            # host-lane eviction evidence, as counter deltas (per-query
            # slice of the process plane; approximate under concurrency,
            # same caveat as the history attribution)
            reasons = {}
            for key, reason in (("stage_loop_fallbacks", "stage_loop"),
                                ("scatter_lane_declines", "scatter_lane"),
                                ("expr_eager_batches", "expr_eager"),
                                # per-column causes (ISSUE 20): WHY the
                                # stage left the device lane, not just
                                # that it did
                                ("host_evictions_string", "string_column"),
                                ("host_evictions_decimal",
                                 "decimal_column"),
                                ("host_evictions_other", "other_column")):
                n = int(delta.get(key, 0))
                if n > 0:
                    reasons[reason] = n
            statstore.ingest({
                "fingerprint": self.stats_fingerprint,
                "wall_s": wall_s,
                "task_ns": task_ns,
                "counters": {k: int(delta.get(k, 0))
                             for k in statstore.INGEST_COUNTERS},
                "fallback_reasons": reasons,
                "stages": sorted(self.stage_boundaries.values(),
                                 key=lambda b: b["sid"]),
            })
        except Exception:
            pass

    def run_collect(self, plan: Dict[str, Any]) -> pa.Table:
        """Execute the whole DAG; returns the result stage's output."""
        from blaze_tpu.bridge import tracing
        self._stats_begin(plan)
        ok = False
        # every span the scheduler (and anything below it) emits carries
        # the owning query id, so one query stitches into one trace
        with tracing.execution_context(
                query=getattr(self._query, "query_id", None)):
            try:
                out = self._run_collect(plan)
                ok = True
                return out
            finally:
                self._stats_end(ok)

    def _run_collect(self, plan: Dict[str, Any]) -> pa.Table:
        from blaze_tpu.bridge.runtime import NativeExecutionRuntime
        from blaze_tpu.plan.proto_serde import task_definition_to_bytes
        from blaze_tpu.plan.types import schema_from_dict

        from blaze_tpu import config
        if self._query is not None:
            self._query.check()  # shed before any work if already overdue
        self.stage_metrics = {}  # instance may be reused per query
        self.task_runs = {}
        threshold = config.DAG_SINGLE_TASK_BYTES.get()
        if threshold > 0 and self._scan_input_bytes(plan) <= threshold:
            self.exec_mode = "local"
            try:
                return self._run_single_task(plan)
            finally:
                self.cleanup()  # the owned scratch dir lives on tmpfs

        self.exec_mode = "staged"
        # re-arm the scratch dir: a streaming executor reuses one
        # scheduler across micro-batch epochs and cleanup() removed it
        # at the end of the previous epoch
        os.makedirs(self._dir, exist_ok=True)
        # history-driven planning (plan/adaptive.py): seed broadcast
        # choices, partition counts and the agg strategy from statstore
        # priors BEFORE the split — _stats_begin already fingerprinted
        # the ORIGINAL plan, so priors stay keyed consistently across
        # cold and warm runs.  Returns the plan unchanged when off.
        from blaze_tpu.plan import adaptive
        self.aqe_events = []
        plan = adaptive.seed_plan(plan, self)
        stages = self.split(plan)
        stages_by_id = {st.sid: st for st in stages}
        max_recoveries = max(0, config.STAGE_MAX_RECOVERIES.get())
        # under the worker pool, a crashed worker's committed outputs
        # are re-validated immediately (invalidate_worker_outputs) so a
        # torn commit surfaces as lineage recovery, not a bad read
        crash_pool = None
        if config.WORKERS_ENABLE.get() or (
                self._query is not None
                and config.SERVING_USE_WORKERS.get()):
            from blaze_tpu.parallel import workers as _workers
            crash_pool = _workers.get_pool()
            if crash_pool is not None:
                crash_pool.add_crash_listener(
                    self.invalidate_worker_outputs)
        try:
            result = stages[-1]
            out_schema = schema_from_dict(result.out_schema).to_arrow()

            def run_result(p: int) -> List[pa.RecordBatch]:
                td = task_definition_to_bytes(
                    {"stage_id": result.sid, "partition_id": p,
                     "num_partitions": result.num_tasks,
                     "plan": self._per_task(result.plan, p,
                                            result.num_tasks)})
                rt = NativeExecutionRuntime(td).start()
                try:
                    return list(rt.batches())
                finally:
                    self._record_task_metrics(result.sid, rt.finalize())

            # bounded lineage recovery: a FetchFailedError anywhere in
            # the DAG names the producer map task whose output is
            # poisoned; re-run just that task, then resume from the
            # first stage that never completed (auron.tpu.stage
            # .maxRecoveries caps the rounds so persistent corruption
            # still terminates)
            completed: set = set()
            recoveries = 0
            # adaptive re-planning hook (plan/adaptive.py): fires
            # between a producer's map-output commit and the next
            # dispatch; None when auron.tpu.aqe.enable is off
            aqe_rt = adaptive.runtime_for(self)
            while True:
                try:
                    for st in stages[:-1]:
                        if st.sid not in completed:
                            self._run_producer(st)
                            completed.add(st.sid)
                            if aqe_rt is not None:
                                aqe_rt.on_producer_commit(
                                    st, completed, stages_by_id)
                    from blaze_tpu.bridge import xla_stats
                    loop_before = xla_stats.stage_loop_stats()[
                        "stage_loop_tasks"]
                    parts = self._run_tasks(
                        run_result, result.num_tasks,
                        f"stage {result.sid} (result)", sid=result.sid)
                    self._note_placement(result.sid, "result",
                                         loop_before)
                    break
                except FetchFailedError as ff:
                    recoveries += 1
                    if recoveries > max_recoveries:
                        raise FetchFailedError(
                            ff.stage_id, ff.map_id,
                            f"{ff.reason} (gave up after "
                            f"{max_recoveries} recovery rounds)") from ff
                    self._recover_map_output(ff, stages_by_id)
            batches = [b for bl in parts for b in bl if b.num_rows]
            if not batches:
                return out_schema.empty_table()
            return pa.Table.from_batches(batches)
        finally:
            if crash_pool is not None:
                crash_pool.remove_crash_listener(
                    self.invalidate_worker_outputs)
            self.cleanup()

    def cleanup(self) -> None:
        """Idempotent AND safe under concurrent callers: run_collect's
        finally, a cancelling service thread, context-manager exit and
        __del__ may all race here.  State lists are swapped out under a
        lock, so every resource/file is released exactly once."""
        # __del__ can run during interpreter shutdown after the lock (or
        # the module globals) are torn down — degrade to best-effort
        lock = getattr(self, "_cleanup_lock", None)
        if lock is None:
            return
        with lock:
            resources, self._resources = self._resources, []
            files, self._files = self._files, []
            rss_clients, self._rss_clients = self._rss_clients, []
            self._stage_outputs = {}
            self._map_worker = {}
            self._map_attempt = {}
            self._attempt_seq = {}
        for rid in resources:
            try:
                remove_resource(rid)
            except Exception:
                pass
        for path in files:
            try:
                os.unlink(path)
            except OSError:
                pass
        for client in rss_clients:
            try:
                client.cleanup()
            except Exception:
                pass
        if self._owns_dir:
            import shutil
            # recreated lazily by the next _run_producer if reused
            shutil.rmtree(self._dir, ignore_errors=True)

    def leak_report(self) -> Dict[str, List[str]]:
        """What this scheduler still holds: shuffle temp files on disk,
        resource-map entries, RSS shuffle roots, and the owned scratch
        dir.  Empty lists everywhere == nothing leaked; tests assert
        exactly that after failed/cancelled queries."""
        from blaze_tpu.bridge.resource import get_resource
        report: Dict[str, List[str]] = {
            "files": [], "resources": [], "rss_roots": [], "dirs": []}
        with self._cleanup_lock:
            files = list(self._files)
            resources = list(self._resources)
            rss_clients = list(self._rss_clients)
        for path in files:
            if os.path.exists(path):
                report["files"].append(path)
        for rid in resources:
            if get_resource(rid) is not None:
                report["resources"].append(rid)
        for client in rss_clients:
            if os.path.isdir(client.root):
                report["rss_roots"].append(client.root)
        if self._owns_dir and os.path.isdir(self._dir):
            leftovers = [os.path.join(self._dir, f)
                         for f in os.listdir(self._dir)]
            if leftovers:
                report["dirs"].append(self._dir)
                report["files"].extend(leftovers)
        # not a leak: the flight recorder's post-mortem artifact for this
        # query, referenced here so failure triage starts from the leak
        # report.  Key present only when a dump exists.
        qid = getattr(self._query, "query_id", None)
        if qid is not None:
            from blaze_tpu.bridge import context as _bctx
            dump = _bctx.flight_dump(qid)
            if dump is not None and dump.get("path"):
                report["flight_dump"] = [dump["path"]]
        return report

    def __enter__(self) -> "DagScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.cleanup()

    def __del__(self) -> None:
        # last-resort backstop for callers that drop the scheduler
        # without run_collect ever reaching its finally (put_resource
        # entries would otherwise leak process-wide); interpreter
        # shutdown may have torn down globals, so never let this raise
        try:
            self.cleanup()
        except Exception:
            pass

    # -- observability -----------------------------------------------------

    def describe(self) -> str:
        lines = []
        for st in self.stages:
            kind = "result" if st.partitioning is None else \
                st.partitioning["kind"]
            lines.append(f"stage {st.sid}: tasks={st.num_tasks} "
                         f"out={kind} deps={st.deps}")
        return "\n".join(lines)


def execute_spark_plan_json(plan_json, num_partitions: int = 2,
                            work_dir: Optional[str] = None) -> pa.Table:
    """Front door: Spark `toJSON` physical plan -> converter -> stage DAG
    -> protobuf tasks -> engine.  The full L6->wire->L3 production path in
    one call (ref: what AuronConverters + Spark's scheduler do together)."""
    import time as _time

    from blaze_tpu.bridge import ui
    from blaze_tpu.convert.spark import convert_spark_plan
    res = convert_spark_plan(plan_json, num_partitions=num_partitions)
    t0 = _time.perf_counter()
    out = DagScheduler(work_dir=work_dir).run_collect(res.plan)
    ui.record_completion(res.query_id, _time.perf_counter() - t0)
    return out
