"""Protobuf plan-serde: the preserved wire boundary.

`blaze_tpu/plan/proto/auron.proto` is vendored VERBATIM from the reference
(`native-engine/auron-planner/proto/auron.proto`, Apache-2.0) per SURVEY.md
§7 step 3: the proto is the engine-neutral contract the existing JVM layer
(AuronConverters / NativeConverters) emits, so adopting it byte-for-byte
preserves the drop-in `TaskDefinition` boundary (ref auron.proto:814,
rt.rs:79-90, planner.rs:122 create_plan / :924 try_parse_physical_expr).

This module maps proto messages <-> the engine's plan-IR dicts (the
vocabulary of plan/planner.py `create_plan`), so one decoder services both
wire formats.  `ScalarValue` follows the reference encoding exactly: a
one-batch Arrow IPC stream whose column 0 row 0 is the value
(ref auron-planner/src/lib.rs:451-459).

Conventions where the reference delegates to the JVM side:
  * UDF wrappers resolve through the resource map by `expr_string`
    (`udf://<expr_string>`); `serialized` is opaque to the engine.
  * scalar-subquery wrappers use `serialized` (utf-8) as the resource uuid.
  * merge-mode agg children are placeholders on the wire (ref
    NativeAggBase.getNativeAggrInfo); acc columns are located positionally
    from `initial_input_buffer_offset`, exactly like the native AggContext.
"""

from __future__ import annotations

import io
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from blaze_tpu.plan.proto import auron_pb2 as pb

# ---------------------------------------------------------------------------
# ArrowType <-> type dicts ({"id": ...} of plan/types.py)
# ---------------------------------------------------------------------------

_SIMPLE_DECODE = {
    "NONE": "null", "BOOL": "bool", "INT8": "int8", "INT16": "int16",
    "INT32": "int32", "INT64": "int64", "FLOAT32": "float32",
    "FLOAT64": "float64", "UTF8": "utf8", "LARGE_UTF8": "utf8",
    "BINARY": "binary", "LARGE_BINARY": "binary", "DATE32": "date32",
}

_SIMPLE_ENCODE = {
    "null": "NONE", "bool": "BOOL", "int8": "INT8", "int16": "INT16",
    "int32": "INT32", "int64": "INT64", "float32": "FLOAT32",
    "float64": "FLOAT64", "utf8": "UTF8", "binary": "BINARY",
    "date32": "DATE32",
}


def type_from_proto(at: pb.ArrowType) -> Dict[str, Any]:
    kind = at.WhichOneof("arrow_type_enum")
    if kind is None:
        raise ValueError("ArrowType with no variant set")
    if kind in _SIMPLE_DECODE:
        return {"id": _SIMPLE_DECODE[kind]}
    if kind == "TIMESTAMP":
        # engine-wide timestamp repr is int64 micros (Spark semantics)
        return {"id": "timestamp_us"}
    if kind == "DECIMAL":
        return {"id": "decimal", "precision": int(at.DECIMAL.whole),
                "scale": int(at.DECIMAL.fractional)}
    if kind in ("LIST", "LARGE_LIST"):
        lst = at.LIST if kind == "LIST" else at.LARGE_LIST
        return {"id": "list", "children": [field_from_proto(lst.field_type)]}
    if kind == "STRUCT":
        return {"id": "struct",
                "children": [field_from_proto(f)
                             for f in at.STRUCT.sub_field_types]}
    if kind == "MAP":
        return {"id": "map", "children": [field_from_proto(at.MAP.key_type),
                                          field_from_proto(at.MAP.value_type)]}
    if kind == "DICTIONARY":
        return type_from_proto(at.DICTIONARY.value)
    raise ValueError(f"unsupported ArrowType variant {kind!r}")


def type_to_proto(t: Dict[str, Any]) -> pb.ArrowType:
    out = pb.ArrowType()
    tid = t["id"]
    if tid in _SIMPLE_ENCODE:
        getattr(out, _SIMPLE_ENCODE[tid]).SetInParent()
        return out
    if tid == "timestamp_us":
        out.TIMESTAMP.time_unit = pb.Microsecond
        return out
    if tid == "decimal":
        out.DECIMAL.whole = t.get("precision", 0)
        out.DECIMAL.fractional = t.get("scale", 0)
        return out
    if tid == "list":
        out.LIST.field_type.CopyFrom(field_to_proto(t["children"][0]))
        return out
    if tid == "struct":
        for c in t.get("children", []):
            out.STRUCT.sub_field_types.append(field_to_proto(c))
        return out
    if tid == "map":
        out.MAP.key_type.CopyFrom(field_to_proto(t["children"][0]))
        out.MAP.value_type.CopyFrom(field_to_proto(t["children"][1]))
        return out
    raise ValueError(f"unsupported type id {tid!r}")


def field_from_proto(f: pb.Field) -> Dict[str, Any]:
    t = type_from_proto(f.arrow_type)
    # nested children may ride on the Field for struct/union parity
    if f.children and not t.get("children"):
        t["children"] = [field_from_proto(c) for c in f.children]
    return {"name": f.name, "type": t, "nullable": f.nullable}


def field_to_proto(fd: Dict[str, Any]) -> pb.Field:
    f = pb.Field(name=fd["name"], nullable=fd.get("nullable", True))
    f.arrow_type.CopyFrom(type_to_proto(fd["type"]))
    return f


def schema_from_proto(s: pb.Schema) -> Dict[str, Any]:
    return {"fields": [field_from_proto(f) for f in s.columns]}


def schema_to_proto(sd: Dict[str, Any]) -> pb.Schema:
    s = pb.Schema()
    for f in sd["fields"]:
        s.columns.append(field_to_proto(f))
    return s


# ---------------------------------------------------------------------------
# ScalarValue: one-batch Arrow IPC stream, column 0 row 0
# (ref auron-planner/src/lib.rs:451-459)
# ---------------------------------------------------------------------------

def scalar_from_proto(sv: pb.ScalarValue) -> Tuple[Any, Dict[str, Any]]:
    from blaze_tpu.plan.types import type_to_dict
    from blaze_tpu.schema import DataType
    with pa.ipc.open_stream(io.BytesIO(sv.ipc_bytes)) as r:
        rb = next(iter(r))
    col = rb.column(0)
    val = col[0].as_py() if col[0].is_valid else None
    return val, type_to_dict(DataType.from_arrow(col.type))


def scalar_to_proto(value: Any, type_dict: Dict[str, Any]) -> pb.ScalarValue:
    from blaze_tpu.plan.types import type_from_dict
    t = type_from_dict(type_dict).to_arrow()
    rb = pa.record_batch([pa.array([value], type=t)], names=["c0"])
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return pb.ScalarValue(ipc_bytes=sink.getvalue())


# ---------------------------------------------------------------------------
# Binary operators (ref from_proto_binary_op, auron-planner/src/lib.rs:73)
# ---------------------------------------------------------------------------

_BINOP_DECODE = {
    "And": "and", "Or": "or", "Eq": "==", "NotEq": "!=", "LtEq": "<=",
    "Lt": "<", "Gt": ">", "GtEq": ">=", "Plus": "+", "Minus": "-",
    "Multiply": "*", "Divide": "/", "Modulo": "%",
    "IsNotDistinctFrom": "<=>",
}
_BINOP_ENCODE = {
    "and": "And", "or": "Or", "==": "Eq", "!=": "NotEq", "<=": "LtEq",
    "<": "Lt", ">": "Gt", ">=": "GtEq", "+": "Plus", "-": "Minus",
    "*": "Multiply", "/": "Divide", "%": "Modulo", "<=>": "IsNotDistinctFrom",
}

# proto ScalarFunction enum name -> engine registry name (funcs/)
_SCALAR_FN_DECODE = {
    "Abs": "abs", "Acos": "acos", "Asin": "asin", "Atan": "atan",
    "Ascii": "ascii", "Ceil": "ceil", "Cos": "cos", "Exp": "exp",
    "Floor": "floor", "Ln": "ln", "Log10": "log10", "Log2": "log2",
    "Round": "round", "Signum": "signum", "Sin": "sin", "Sqrt": "sqrt",
    "Tan": "tan", "Trunc": "trunc", "Btrim": "trim",
    "CharacterLength": "char_length", "Chr": "chr", "Concat": "concat",
    "ConcatWithSeparator": "concat_ws", "DateTrunc": "date_trunc",
    "Lpad": "lpad", "Lower": "lower", "Ltrim": "ltrim",
    "OctetLength": "octet_length", "RegexpReplace": "regexp_replace",
    "Repeat": "repeat", "Replace": "replace", "Reverse": "reverse",
    "Rpad": "rpad", "Rtrim": "rtrim", "Strpos": "strpos",
    "Substr": "substring", "Translate": "translate", "Trim": "trim",
    "Upper": "upper", "Expm1": "expm1", "Power": "pow", "IsNaN": "isnan",
    "Least": "least", "Greatest": "greatest",
}
_SCALAR_FN_ENCODE = {v: k for k, v in _SCALAR_FN_DECODE.items()}
# name collisions resolved toward the canonical enum entry
_SCALAR_FN_ENCODE["trim"] = "Trim"

_AGG_FN_DECODE = {
    pb.MIN: "min", pb.MAX: "max", pb.SUM: "sum", pb.AVG: "avg",
    pb.COUNT: "count", pb.COLLECT_LIST: "collect_list",
    pb.COLLECT_SET: "collect_set", pb.FIRST: "first",
    pb.FIRST_IGNORES_NULL: "first_ignores_null",
    pb.BLOOM_FILTER: "bloom_filter", pb.UDAF: "udaf",
    pb.BRICKHOUSE_COLLECT: "brickhouse.collect",
    pb.BRICKHOUSE_COMBINE_UNIQUE: "brickhouse.combine_unique",
}
_AGG_FN_ENCODE = {v: k for k, v in _AGG_FN_DECODE.items()}

_JOIN_TYPE_DECODE = {
    pb.INNER: "inner", pb.LEFT: "left", pb.RIGHT: "right", pb.FULL: "full",
    pb.SEMI: "left_semi", pb.ANTI: "left_anti", pb.EXISTENCE: "existence",
}
_JOIN_TYPE_ENCODE = {v: k for k, v in _JOIN_TYPE_DECODE.items()}

_WINDOW_RANK_DECODE = {
    pb.ROW_NUMBER: "row_number", pb.RANK: "rank", pb.DENSE_RANK: "dense_rank",
    pb.PERCENT_RANK: "percent_rank", pb.CUME_DIST: "cume_dist",
}
_WINDOW_RANK_ENCODE = {v: k for k, v in _WINDOW_RANK_DECODE.items()}


# ---------------------------------------------------------------------------
# PhysicalExprNode -> expr IR dicts
# ---------------------------------------------------------------------------

def expr_from_proto(e: pb.PhysicalExprNode) -> Dict[str, Any]:
    kind = e.WhichOneof("ExprType")
    if kind is None:
        raise ValueError("PhysicalExprNode with no variant set")
    if kind == "column":
        if e.column.name:
            return {"kind": "column", "name": e.column.name}
        return {"kind": "column", "index": int(e.column.index)}
    if kind == "bound_reference":
        return {"kind": "column", "index": int(e.bound_reference.index)}
    if kind == "literal":
        val, t = scalar_from_proto(e.literal)
        return {"kind": "literal", "value": val, "type": t}
    if kind == "binary_expr":
        wire_op = e.binary_expr.op
        if wire_op in ("RegexMatch", "RegexIMatch"):
            pat, _ = scalar_from_proto(e.binary_expr.r.literal)
            return {"kind": "rlike",
                    "child": expr_from_proto(e.binary_expr.l),
                    "pattern": pat,
                    "case_insensitive": wire_op == "RegexIMatch"}
        if wire_op == "StringConcat":
            # the engine's binary "+" rejects strings; concat is a fn
            return {"kind": "scalar_function", "name": "concat",
                    "args": [expr_from_proto(e.binary_expr.l),
                             expr_from_proto(e.binary_expr.r)]}
        op = _BINOP_DECODE.get(wire_op)
        if op is None:
            raise ValueError(f"unsupported binary op {wire_op!r}")
        return {"kind": "binary", "op": op,
                "l": expr_from_proto(e.binary_expr.l),
                "r": expr_from_proto(e.binary_expr.r)}
    if kind == "is_null_expr":
        return {"kind": "is_null",
                "child": expr_from_proto(e.is_null_expr.expr)}
    if kind == "is_not_null_expr":
        return {"kind": "is_not_null",
                "child": expr_from_proto(e.is_not_null_expr.expr)}
    if kind == "not_expr":
        return {"kind": "not", "child": expr_from_proto(e.not_expr.expr)}
    if kind == "case_":
        c = e.case_
        operand = (expr_from_proto(c.expr)
                   if c.HasField("expr") else None)
        branches = []
        for wt in c.when_then_expr:
            w = expr_from_proto(wt.when_expr)
            if operand is not None:
                w = {"kind": "binary", "op": "==", "l": operand, "r": w}
            branches.append([w, expr_from_proto(wt.then_expr)])
        out: Dict[str, Any] = {"kind": "case", "branches": branches}
        if c.HasField("else_expr"):
            out["else"] = expr_from_proto(c.else_expr)
        return out
    if kind in ("cast", "try_cast"):
        node = e.cast if kind == "cast" else e.try_cast
        return {"kind": kind, "child": expr_from_proto(node.expr),
                "type": type_from_proto(node.arrow_type)}
    if kind == "negative":
        return {"kind": "scalar_function", "name": "negative",
                "args": [expr_from_proto(e.negative.expr)]}
    if kind == "in_list":
        values = []
        for v in e.in_list.list:
            if v.WhichOneof("ExprType") != "literal":
                raise ValueError("in_list values must be literals")
            values.append(scalar_from_proto(v.literal)[0])
        return {"kind": "in_list",
                "child": expr_from_proto(e.in_list.expr),
                "values": values, "negated": e.in_list.negated}
    if kind == "scalar_function":
        sf = e.scalar_function
        enum_name = pb.ScalarFunction.Name(sf.fun)
        if enum_name == "AuronExtFunctions":
            name = sf.name
        elif enum_name == "Coalesce":
            return {"kind": "coalesce",
                    "args": [expr_from_proto(a) for a in sf.args]}
        else:
            name = _SCALAR_FN_DECODE.get(enum_name)
            if name is None:
                raise ValueError(
                    f"unsupported scalar function {enum_name!r}")
        d = {"kind": "scalar_function", "name": name,
             "args": [expr_from_proto(a) for a in sf.args]}
        if sf.HasField("return_type"):
            d["return_type"] = type_from_proto(sf.return_type)
        return d
    if kind == "like_expr":
        le = e.like_expr
        pat, _ = scalar_from_proto(le.pattern.literal)
        return {"kind": "like", "child": expr_from_proto(le.expr),
                "pattern": pat, "negated": le.negated,
                "case_insensitive": le.case_insensitive}
    if kind == "sc_and_expr":
        return {"kind": "binary", "op": "and",
                "l": expr_from_proto(e.sc_and_expr.left),
                "r": expr_from_proto(e.sc_and_expr.right)}
    if kind == "sc_or_expr":
        return {"kind": "binary", "op": "or",
                "l": expr_from_proto(e.sc_or_expr.left),
                "r": expr_from_proto(e.sc_or_expr.right)}
    if kind == "spark_udf_wrapper_expr":
        u = e.spark_udf_wrapper_expr
        d = {"kind": "udf", "name": u.expr_string,
             "args": [expr_from_proto(p) for p in u.params],
             "type": type_from_proto(u.return_type)}
        payload = u.serialized.decode("utf-8", "backslashreplace")
        if payload and payload != u.expr_string:
            # the wrapped-expression payload (converter fallback) rides
            # the wire so the host evaluator can interpret it
            d["serialized"] = payload
        return d
    if kind == "spark_scalar_subquery_wrapper_expr":
        s = e.spark_scalar_subquery_wrapper_expr
        return {"kind": "scalar_subquery",
                "uuid": s.serialized.decode("utf-8", "backslashreplace"),
                "type": type_from_proto(s.return_type)}
    if kind == "get_indexed_field_expr":
        key, _ = scalar_from_proto(e.get_indexed_field_expr.key)
        return {"kind": "get_indexed_field",
                "child": expr_from_proto(e.get_indexed_field_expr.expr),
                "index": key}
    if kind == "get_map_value_expr":
        key, _ = scalar_from_proto(e.get_map_value_expr.key)
        return {"kind": "get_map_value",
                "child": expr_from_proto(e.get_map_value_expr.expr),
                "key": key}
    if kind == "named_struct":
        t = type_from_proto(e.named_struct.return_type)
        names = [c["name"] for c in t.get("children", [])]
        return {"kind": "named_struct", "names": names,
                "args": [expr_from_proto(v) for v in e.named_struct.values]}
    if kind == "string_starts_with_expr":
        return {"kind": "string_starts_with",
                "child": expr_from_proto(e.string_starts_with_expr.expr),
                "pattern": e.string_starts_with_expr.prefix}
    if kind == "string_ends_with_expr":
        return {"kind": "string_ends_with",
                "child": expr_from_proto(e.string_ends_with_expr.expr),
                "pattern": e.string_ends_with_expr.suffix}
    if kind == "string_contains_expr":
        return {"kind": "string_contains",
                "child": expr_from_proto(e.string_contains_expr.expr),
                "pattern": e.string_contains_expr.infix}
    if kind == "row_num_expr":
        return {"kind": "row_num"}
    if kind == "spark_partition_id_expr":
        return {"kind": "spark_partition_id"}
    if kind == "monotonic_increasing_id_expr":
        return {"kind": "monotonically_increasing_id"}
    if kind == "spark_randn_expr":
        return {"kind": "randn", "seed": int(e.spark_randn_expr.seed)}
    if kind == "bloom_filter_might_contain_expr":
        b = e.bloom_filter_might_contain_expr
        return {"kind": "bloom_filter_might_contain", "uuid": b.uuid,
                "value": expr_from_proto(b.value_expr)}
    raise ValueError(f"unsupported expression variant {kind!r}")


def sort_spec_from_proto(e: pb.PhysicalExprNode) -> Dict[str, Any]:
    if e.WhichOneof("ExprType") != "sort":
        raise ValueError("expected PhysicalSortExprNode")
    s = e.sort
    return {"expr": expr_from_proto(s.expr), "descending": not s.asc,
            "nulls_first": s.nulls_first}


# ---------------------------------------------------------------------------
# expr IR dicts -> PhysicalExprNode
# ---------------------------------------------------------------------------

def expr_to_proto(d: Dict[str, Any]) -> pb.PhysicalExprNode:
    e = pb.PhysicalExprNode()
    k = d["kind"]
    if k == "column":
        if d.get("name"):
            e.column.name = d["name"]
            if d.get("index") is not None:
                e.column.index = d["index"]
        else:
            e.bound_reference.index = d["index"]
            e.bound_reference.nullable = True
        return e
    if k == "literal":
        e.literal.CopyFrom(scalar_to_proto(d.get("value"), d["type"]))
        return e
    if k == "binary":
        e.binary_expr.op = _BINOP_ENCODE[d["op"]]
        e.binary_expr.l.CopyFrom(expr_to_proto(d["l"]))
        e.binary_expr.r.CopyFrom(expr_to_proto(d["r"]))
        return e
    if k == "is_null":
        e.is_null_expr.expr.CopyFrom(expr_to_proto(d["child"]))
        return e
    if k == "is_not_null":
        e.is_not_null_expr.expr.CopyFrom(expr_to_proto(d["child"]))
        return e
    if k == "not":
        e.not_expr.expr.CopyFrom(expr_to_proto(d["child"]))
        return e
    if k == "case":
        for w, t in d["branches"]:
            wt = e.case_.when_then_expr.add()
            wt.when_expr.CopyFrom(expr_to_proto(w))
            wt.then_expr.CopyFrom(expr_to_proto(t))
        if d.get("else") is not None:
            e.case_.else_expr.CopyFrom(expr_to_proto(d["else"]))
        return e
    if k == "if":
        # if(c, a, b) is case [(c, a)] else b on the wire
        wt = e.case_.when_then_expr.add()
        wt.when_expr.CopyFrom(expr_to_proto(d["cond"]))
        wt.then_expr.CopyFrom(expr_to_proto(d["then"]))
        e.case_.else_expr.CopyFrom(expr_to_proto(d["else"]))
        return e
    if k == "coalesce":
        e.scalar_function.fun = pb.Coalesce
        e.scalar_function.name = "coalesce"
        for a in d["args"]:
            e.scalar_function.args.append(expr_to_proto(a))
        return e
    if k in ("cast", "try_cast"):
        node = e.cast if k == "cast" else e.try_cast
        node.expr.CopyFrom(expr_to_proto(d["child"]))
        node.arrow_type.CopyFrom(type_to_proto(d["type"]))
        return e
    if k == "in_list":
        e.in_list.expr.CopyFrom(expr_to_proto(d["child"]))
        e.in_list.negated = d.get("negated", False)
        for v in d["values"]:
            lit = e.in_list.list.add()
            lit.literal.CopyFrom(scalar_to_proto(v, _value_type(v)))
        return e
    if k == "scalar_function":
        name = d["name"]
        enum_name = _SCALAR_FN_ENCODE.get(name)
        if enum_name is not None:
            e.scalar_function.fun = getattr(pb, enum_name)
        else:
            e.scalar_function.fun = pb.AuronExtFunctions
        e.scalar_function.name = name
        for a in d.get("args", []):
            e.scalar_function.args.append(expr_to_proto(a))
        if d.get("return_type"):
            e.scalar_function.return_type.CopyFrom(
                type_to_proto(d["return_type"]))
        return e
    if k == "like":
        e.like_expr.negated = d.get("negated", False)
        e.like_expr.case_insensitive = d.get("case_insensitive", False)
        e.like_expr.expr.CopyFrom(expr_to_proto(d["child"]))
        e.like_expr.pattern.literal.CopyFrom(
            scalar_to_proto(d["pattern"], {"id": "utf8"}))
        return e
    if k == "rlike":
        e.binary_expr.op = "RegexMatch"
        e.binary_expr.l.CopyFrom(expr_to_proto(d["child"]))
        e.binary_expr.r.literal.CopyFrom(
            scalar_to_proto(d["pattern"], {"id": "utf8"}))
        return e
    if k in ("string_starts_with", "string_ends_with", "string_contains"):
        node = {"string_starts_with": e.string_starts_with_expr,
                "string_ends_with": e.string_ends_with_expr,
                "string_contains": e.string_contains_expr}[k]
        node.expr.CopyFrom(expr_to_proto(d["child"]))
        attr = {"string_starts_with": "prefix", "string_ends_with": "suffix",
                "string_contains": "infix"}[k]
        setattr(node, attr, d["pattern"])
        return e
    if k == "named_struct":
        for v in d["args"]:
            e.named_struct.values.append(expr_to_proto(v))
        e.named_struct.return_type.CopyFrom(type_to_proto(
            {"id": "struct",
             "children": [{"name": n, "type": {"id": "null"},
                           "nullable": True} for n in d["names"]]}))
        return e
    if k == "get_indexed_field":
        e.get_indexed_field_expr.expr.CopyFrom(expr_to_proto(d["child"]))
        e.get_indexed_field_expr.key.CopyFrom(
            scalar_to_proto(d["index"], _value_type(d["index"])))
        return e
    if k == "get_map_value":
        e.get_map_value_expr.expr.CopyFrom(expr_to_proto(d["child"]))
        e.get_map_value_expr.key.CopyFrom(
            scalar_to_proto(d["key"], _value_type(d["key"])))
        return e
    if k == "row_num":
        e.row_num_expr.SetInParent()
        return e
    if k == "spark_partition_id":
        e.spark_partition_id_expr.SetInParent()
        return e
    if k == "monotonically_increasing_id":
        e.monotonic_increasing_id_expr.SetInParent()
        return e
    if k in ("rand", "randn"):
        e.spark_randn_expr.seed = d.get("seed", 0)
        return e
    if k == "bloom_filter_might_contain":
        e.bloom_filter_might_contain_expr.uuid = d["uuid"]
        e.bloom_filter_might_contain_expr.value_expr.CopyFrom(
            expr_to_proto(d["value"]))
        return e
    if k == "scalar_subquery":
        s = e.spark_scalar_subquery_wrapper_expr
        s.serialized = d["uuid"].encode("utf-8")
        s.return_type.CopyFrom(type_to_proto(d["type"]))
        s.return_nullable = True
        return e
    if k == "udf":
        u = e.spark_udf_wrapper_expr
        u.expr_string = d["name"]
        u.serialized = d.get("serialized", d["name"]).encode("utf-8")
        u.return_type.CopyFrom(type_to_proto(d["type"]))
        u.return_nullable = True
        for a in d.get("args", []):
            u.params.append(expr_to_proto(a))
        return e
    raise ValueError(f"cannot encode expression kind {k!r}")


def sort_spec_to_proto(d: Dict[str, Any]) -> pb.PhysicalExprNode:
    e = pb.PhysicalExprNode()
    e.sort.expr.CopyFrom(expr_to_proto(d["expr"]))
    e.sort.asc = not d.get("descending", False)
    e.sort.nulls_first = d.get("nulls_first",
                               not d.get("descending", False))
    return e


def _value_type(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"id": "bool"}
    if isinstance(v, int):
        return {"id": "int64"}
    if isinstance(v, float):
        return {"id": "float64"}
    if isinstance(v, bytes):
        return {"id": "binary"}
    return {"id": "utf8"}


# ---------------------------------------------------------------------------
# Partitioning (ref parse_protobuf_partitioning, planner.rs:1201)
# ---------------------------------------------------------------------------

def partitioning_from_proto(p: pb.PhysicalRepartition) -> Dict[str, Any]:
    kind = p.WhichOneof("RepartitionType")
    if kind == "single_repartition":
        return {"kind": "single"}
    if kind == "hash_repartition":
        h = p.hash_repartition
        return {"kind": "hash",
                "exprs": [expr_from_proto(e) for e in h.hash_expr],
                "num_partitions": int(h.partition_count)}
    if kind == "round_robin_repartition":
        return {"kind": "round_robin",
                "num_partitions": int(p.round_robin_repartition
                                      .partition_count)}
    if kind == "range_repartition":
        r = p.range_repartition
        specs = [sort_spec_from_proto(e) for e in r.sort_expr.expr]
        bounds_cols: List[List[Any]] = [[] for _ in specs]
        types: List[Optional[pa.DataType]] = [None] * len(specs)
        for sv in r.list_value:
            val, _ = scalar_from_proto(sv)
            if len(specs) == 1:
                bounds_cols[0].append(val)
            else:
                # multi-key bounds ride as struct scalars
                for i, (_k, v) in enumerate(val.items()):
                    bounds_cols[i].append(v)
        import base64
        arrays = [pa.array(c) for c in bounds_cols]
        rb = pa.record_batch(arrays, names=[f"b{i}"
                                            for i in range(len(arrays))])
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, rb.schema) as w:
            w.write_batch(rb)
        return {"kind": "range", "specs": specs,
                "num_partitions": int(r.partition_count),
                "bounds_ipc": base64.b64encode(sink.getvalue())
                .decode("ascii")}
    raise ValueError(f"unsupported repartition {kind!r}")


def partitioning_to_proto(d: Dict[str, Any]) -> pb.PhysicalRepartition:
    p = pb.PhysicalRepartition()
    k = d["kind"]
    if k == "single":
        p.single_repartition.partition_count = 1
        return p
    if k == "hash":
        p.hash_repartition.partition_count = d["num_partitions"]
        for e in d["exprs"]:
            p.hash_repartition.hash_expr.append(expr_to_proto(e))
        return p
    if k == "round_robin":
        p.round_robin_repartition.partition_count = d["num_partitions"]
        return p
    if k == "range":
        import base64
        r = p.range_repartition
        r.partition_count = d["num_partitions"]
        for s in d["specs"]:
            r.sort_expr.expr.append(sort_spec_to_proto(s))
        with pa.ipc.open_stream(io.BytesIO(
                base64.b64decode(d["bounds_ipc"]))) as rd:
            rb = next(iter(rd))
        from blaze_tpu.plan.types import type_to_dict
        from blaze_tpu.schema import DataType
        for i in range(rb.num_rows):
            if rb.num_columns == 1:
                col = rb.column(0)
                r.list_value.append(scalar_to_proto(
                    col[i].as_py(),
                    type_to_dict(DataType.from_arrow(col.type))))
            else:
                row = {rb.schema.field(j).name: rb.column(j)[i].as_py()
                       for j in range(rb.num_columns)}
                struct_t = {"id": "struct", "children": [
                    {"name": rb.schema.field(j).name,
                     "type": type_to_dict(
                         DataType.from_arrow(rb.column(j).type)),
                     "nullable": True}
                    for j in range(rb.num_columns)]}
                r.list_value.append(scalar_to_proto(row, struct_t))
        return p
    raise ValueError(f"cannot encode partitioning {k!r}")


# ---------------------------------------------------------------------------
# PhysicalPlanNode -> plan IR dicts
# ---------------------------------------------------------------------------

def _file_groups_from_conf(conf: pb.FileScanExecConf
                           ) -> Tuple[List[List[str]], Dict[str, Any]]:
    """The wire carries ONE file group (this task's); rebuild the
    positional file_groups list so plan.execute(partition_index) finds it."""
    n = max(1, int(conf.num_partitions))
    idx = int(conf.partition_index)
    groups: List[List[str]] = [[] for _ in range(n)]
    paths = [f.path for f in conf.file_group.files]
    groups[min(idx, n - 1)] = paths
    schema = schema_from_proto(conf.schema)
    extra: Dict[str, Any] = {}
    if conf.HasField("partition_schema") and \
            len(conf.partition_schema.columns):
        extra["partition_schema"] = schema_from_proto(
            conf.partition_schema)
        pvals: List[List[List[Any]]] = [[] for _ in range(n)]
        pvals[min(idx, n - 1)] = [
            [scalar_from_proto(sv)[0] for sv in f.partition_values]
            for f in conf.file_group.files]
        extra["partition_values"] = pvals
    elif any(f.partition_values for f in conf.file_group.files):
        raise ValueError("partition_values without partition_schema")
    return groups, schema, extra


def plan_from_proto(n: pb.PhysicalPlanNode) -> Dict[str, Any]:
    kind = n.WhichOneof("PhysicalPlanType")
    if kind is None:
        raise ValueError("PhysicalPlanNode with no variant set")

    if kind in ("parquet_scan", "orc_scan"):
        node = n.parquet_scan if kind == "parquet_scan" else n.orc_scan
        groups, schema, extra = _file_groups_from_conf(node.base_conf)
        d: Dict[str, Any] = {"kind": kind, "schema": schema,
                             "file_groups": groups, **extra}
        if node.base_conf.projection:
            # projection indices address file schema + partition schema
            # combined, in that order (ref NativeParquetScanBase.scala:55:
            # relation.schema = file columns + partition columns)
            all_fields = list(schema["fields"])
            if "partition_schema" in extra:
                all_fields += list(extra["partition_schema"]["fields"])
            names = [all_fields[i]["name"]
                     for i in node.base_conf.projection]
            d["projection"] = names
        if kind == "parquet_scan" and node.pruning_predicates:
            pred = expr_from_proto(node.pruning_predicates[0])
            for p in node.pruning_predicates[1:]:
                pred = {"kind": "binary", "op": "and", "l": pred,
                        "r": expr_from_proto(p)}
            d["predicate"] = pred
        return d
    if kind == "ipc_reader":
        return {"kind": "ipc_reader",
                "resource_id": n.ipc_reader.ipc_provider_resource_id,
                "schema": schema_from_proto(n.ipc_reader.schema),
                "num_partitions": int(n.ipc_reader.num_partitions)}
    if kind == "ffi_reader":
        return {"kind": "ffi_reader",
                "resource_id": n.ffi_reader
                .export_iter_provider_resource_id,
                "schema": schema_from_proto(n.ffi_reader.schema),
                "num_partitions": int(n.ffi_reader.num_partitions)}
    if kind == "empty_partitions":
        return {"kind": "empty_partitions",
                "schema": schema_from_proto(n.empty_partitions.schema),
                "num_partitions": int(n.empty_partitions.num_partitions)}
    if kind == "kafka_scan":
        ks = n.kafka_scan
        return {"kind": "kafka_scan",
                "schema": schema_from_proto(ks.schema),
                "topic": ks.kafka_topic,
                "properties_json": ks.kafka_properties_json,
                "batch_size": int(ks.batch_size),
                "startup_mode": pb.KafkaStartupMode.Name(ks.startup_mode)
                .lower(),
                "operator_id": ks.auron_operator_id,
                "format": pb.KafkaFormat.Name(ks.data_format).lower(),
                "format_config_json": ks.format_config_json,
                "mock_data_json_array": ks.mock_data_json_array}

    if kind == "debug":
        return {"kind": "debug", "input": plan_from_proto(n.debug.input),
                "tag": n.debug.debug_id}
    if kind == "shuffle_writer":
        sw = n.shuffle_writer
        return {"kind": "shuffle_writer",
                "input": plan_from_proto(sw.input),
                "partitioning":
                    partitioning_from_proto(sw.output_partitioning),
                "data_file": sw.output_data_file,
                "index_file": sw.output_index_file}
    if kind == "rss_shuffle_writer":
        rw = n.rss_shuffle_writer
        return {"kind": "rss_shuffle_writer",
                "input": plan_from_proto(rw.input),
                "partitioning":
                    partitioning_from_proto(rw.output_partitioning),
                "rss_resource_id": rw.rss_partition_writer_resource_id}
    if kind == "ipc_writer":
        return {"kind": "ipc_writer",
                "input": plan_from_proto(n.ipc_writer.input),
                "sink_resource_id": n.ipc_writer.ipc_consumer_resource_id}
    if kind == "projection":
        pr = n.projection
        return {"kind": "project", "input": plan_from_proto(pr.input),
                "exprs": [expr_from_proto(e) for e in pr.expr],
                "names": list(pr.expr_name)}
    if kind == "filter":
        return {"kind": "filter", "input": plan_from_proto(n.filter.input),
                "predicates": [expr_from_proto(e) for e in n.filter.expr]}
    if kind == "sort":
        s = n.sort
        d = {"kind": "sort", "input": plan_from_proto(s.input),
             "specs": [sort_spec_from_proto(e) for e in s.expr]}
        if s.HasField("fetch_limit"):
            if s.fetch_limit.offset:
                raise NotImplementedError("sort fetch offset")
            d["fetch"] = int(s.fetch_limit.limit)
        return d
    if kind == "limit":
        d = {"kind": "limit", "input": plan_from_proto(n.limit.input),
             "limit": int(n.limit.limit)}
        if n.limit.offset:
            d["offset"] = int(n.limit.offset)
        return d
    if kind == "union":
        return {"kind": "union",
                "inputs": [plan_from_proto(i.input) for i in n.union.input],
                "input_partitions": [int(i.partition)
                                     for i in n.union.input],
                "num_partitions": int(n.union.num_partitions),
                "cur_partition": int(n.union.cur_partition)}
    if kind == "rename_columns":
        return {"kind": "rename_columns",
                "input": plan_from_proto(n.rename_columns.input),
                "names": list(n.rename_columns.renamed_column_names)}
    if kind == "expand":
        ex = n.expand
        return {"kind": "expand", "input": plan_from_proto(ex.input),
                "projections": [[expr_from_proto(e) for e in p.expr]
                                for p in ex.projections],
                "names": [f.name for f in ex.schema.columns]}
    if kind == "coalesce_batches":
        return {"kind": "coalesce_batches",
                "input": plan_from_proto(n.coalesce_batches.input),
                "batch_size": int(n.coalesce_batches.batch_size) or None}
    if kind == "agg":
        return _agg_from_proto(n.agg)
    if kind in ("sort_merge_join", "hash_join", "broadcast_join"):
        return _join_from_proto(kind, n)
    if kind == "broadcast_join_build_hash_map":
        b = n.broadcast_join_build_hash_map
        return {"kind": "broadcast_join_build_hash_map",
                "input": plan_from_proto(b.input),
                "keys": [expr_from_proto(e) for e in b.keys]}
    if kind == "window":
        return _window_from_proto(n.window)
    if kind == "generate":
        return _generate_from_proto(n.generate)
    if kind == "parquet_sink":
        ps = n.parquet_sink
        return {"kind": "parquet_sink",
                "input": plan_from_proto(ps.input),
                "fs_resource_id": ps.fs_resource_id,
                "num_dyn_parts": int(ps.num_dyn_parts),
                "props": {p.key: p.value for p in ps.prop}}
    if kind == "orc_sink":
        os_ = n.orc_sink
        return {"kind": "orc_sink",
                "input": plan_from_proto(os_.input),
                "fs_resource_id": os_.fs_resource_id,
                "num_dyn_parts": int(os_.num_dyn_parts),
                "props": {p.key: p.value for p in os_.prop}}
    raise ValueError(f"unsupported plan variant {kind!r}")


def _agg_from_proto(agg: pb.AggExecNode) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "kind": ("hash_agg" if agg.exec_mode == pb.HASH_AGG else "sort_agg"),
        "input": plan_from_proto(agg.input),
    }
    groupings = []
    for e, name in zip(agg.grouping_expr, agg.grouping_expr_name):
        groupings.append({"expr": expr_from_proto(e), "name": name})
    d["groupings"] = groupings
    aggs = []
    # merge-mode acc columns are positional: groupings first, then each
    # agg's acc fields in order, starting at initial_input_buffer_offset
    # past the groupings (ref NativeAggBase.scala:147-153: input schema =
    # groupings ++ aggBufferAttrs)
    acc_pos = len(groupings) + int(agg.initial_input_buffer_offset)
    for e, name, mode in zip(agg.agg_expr, agg.agg_expr_name, agg.mode):
        if e.WhichOneof("ExprType") != "agg_expr":
            raise ValueError("agg_expr entry is not a PhysicalAggExprNode")
        an = e.agg_expr
        fn_name = _AGG_FN_DECODE.get(an.agg_function)
        if fn_name is None:
            raise ValueError(
                f"unsupported AggFunction {an.agg_function}")
        mode_name = {pb.PARTIAL: "partial", pb.PARTIAL_MERGE: "partial_merge",
                     pb.FINAL: "final"}[mode]
        entry: Dict[str, Any] = {"fn": fn_name, "mode": mode_name,
                                 "name": name}
        n_acc = _ACC_FIELD_COUNT.get(fn_name, 1)
        if mode_name == "partial":
            entry["args"] = [expr_from_proto(c) for c in an.children]
        else:
            entry["args"] = [{"kind": "column", "index": acc_pos + i}
                             for i in range(n_acc)]
        acc_pos += n_acc
        if fn_name == "udaf":
            entry.setdefault("options", {})["udaf_name"] = \
                an.udaf.serialized.decode("utf-8", "backslashreplace")
        aggs.append(entry)
    d["aggs"] = aggs
    if agg.supports_partial_skipping:
        d["supports_partial_skipping"] = True
    if agg.initial_input_buffer_offset:
        d["initial_input_buffer_offset"] = \
            int(agg.initial_input_buffer_offset)
    return d


# acc-column counts per agg kind (must match ops/agg/functions.py
# acc_fields): avg carries (sum, count); collect/bloom/udaf carry one
# opaque host column
_ACC_FIELD_COUNT = {
    "sum": 1, "count": 1, "min": 1, "max": 1, "first": 1,
    "first_ignores_null": 1, "avg": 2, "collect_list": 1, "collect_set": 1,
    "bloom_filter": 1, "udaf": 1,
}


def _join_from_proto(kind: str, n: pb.PhysicalPlanNode) -> Dict[str, Any]:
    node = getattr(n, kind)
    d: Dict[str, Any] = {
        "kind": kind,
        "left": plan_from_proto(node.left),
        "right": plan_from_proto(node.right),
        "left_keys": [expr_from_proto(o.left) for o in node.on],
        "right_keys": [expr_from_proto(o.right) for o in node.on],
        "join_type": _JOIN_TYPE_DECODE[node.join_type],
    }
    if kind == "hash_join":
        d["build_side"] = ("left" if node.build_side == pb.LEFT_SIDE
                           else "right")
        if node.HasField("filter"):
            d["join_filter"] = expr_from_proto(node.filter.expression)
    elif kind == "broadcast_join":
        d["build_side"] = ("left" if node.broadcast_side == pb.LEFT_SIDE
                           else "right")
        if node.cached_build_hash_map_id:
            d["broadcast_id"] = node.cached_build_hash_map_id
        if node.is_null_aware_anti_join:
            d["null_aware_anti"] = True
        if not node.on:
            # keyless broadcast join = nested-loop join (see encode)
            d["kind"] = "broadcast_nested_loop_join"
    else:  # sort_merge_join
        if node.HasField("filter"):
            d["join_filter"] = expr_from_proto(node.filter.expression)
    return d


def _window_from_proto(w: pb.WindowExecNode) -> Dict[str, Any]:
    funcs = []
    for we in w.window_expr:
        name = we.field.name
        if we.func_type == pb.Agg:
            fn_name = _AGG_FN_DECODE.get(we.agg_func)
            if fn_name is None:
                raise ValueError(f"unsupported window agg {we.agg_func}")
            funcs.append({"kind": "agg", "fn": fn_name, "name": name,
                          "args": [expr_from_proto(c) for c in we.children]})
            continue
        wf = we.window_func
        if wf in _WINDOW_RANK_DECODE:
            funcs.append({"kind": _WINDOW_RANK_DECODE[wf], "name": name})
        elif wf == pb.LEAD:
            entry = {"kind": "lead", "name": name,
                     "expr": expr_from_proto(we.children[0])}
            if len(we.children) > 1:
                off, _ = scalar_from_proto(we.children[1].literal)
                entry["offset"] = off
                if off is not None and off < 0:
                    entry["kind"] = "lag"
                    entry["offset"] = -off
            if len(we.children) > 2:
                entry["default"], _ = scalar_from_proto(
                    we.children[2].literal)
            funcs.append(entry)
        elif wf in (pb.NTH_VALUE, pb.NTH_VALUE_IGNORE_NULLS):
            entry = {"kind": "nth_value", "name": name,
                     "expr": expr_from_proto(we.children[0])}
            if len(we.children) > 1:
                entry["n"], _ = scalar_from_proto(we.children[1].literal)
            if wf == pb.NTH_VALUE_IGNORE_NULLS:
                entry["ignore_nulls"] = True
            funcs.append(entry)
        else:
            raise ValueError(f"unsupported window function {wf}")
    d: Dict[str, Any] = {"kind": "window",
                         "input": plan_from_proto(w.input),
                         "functions": funcs,
                         "partition_by": [expr_from_proto(e)
                                          for e in w.partition_spec],
                         "order_by": [sort_spec_from_proto(e)
                                      for e in w.order_spec]}
    if w.HasField("group_limit"):
        d["group_limit"] = int(w.group_limit.k)
    return d


def _generate_from_proto(g: pb.GenerateExecNode) -> Dict[str, Any]:
    func = g.generator.func
    children = [expr_from_proto(c) for c in g.generator.child]
    if func in (pb.Explode, pb.PosExplode):
        gen: Dict[str, Any] = {
            "kind": "explode" if func == pb.Explode else "posexplode",
            "child": children[0], "outer": g.outer}
    elif func == pb.JsonTuple:
        fields = []
        for c in g.generator.child[1:]:
            fields.append(scalar_from_proto(c.literal)[0])
        gen = {"kind": "json_tuple", "child": children[0], "fields": fields}
    elif func == pb.Udtf:
        gen = {"kind": "udtf",
               "name": g.generator.udtf.serialized.decode(
                   "utf-8", "backslashreplace"),
               "args": children,
               "fields": [field_from_proto(f) for f in g.generator_output]}
    else:
        raise ValueError(f"unsupported generator {func}")
    return {"kind": "generate", "input": plan_from_proto(g.input),
            "generator": gen,
            "required_child_output": list(g.required_child_output)}


# ---------------------------------------------------------------------------
# plan IR dicts -> PhysicalPlanNode (tests + front-end corpus)
# ---------------------------------------------------------------------------

def plan_to_proto(d: Dict[str, Any]) -> pb.PhysicalPlanNode:
    n = pb.PhysicalPlanNode()
    k = d["kind"]

    if k in ("parquet_scan", "orc_scan"):
        node = n.parquet_scan if k == "parquet_scan" else n.orc_scan
        conf = node.base_conf
        groups = d["file_groups"]
        non_empty = [i for i, g in enumerate(groups) if g]
        if len(non_empty) > 1:
            raise ValueError(
                "the wire carries ONE file group per task "
                "(FileScanExecConf); emit one TaskDefinition per partition")
        conf.num_partitions = len(groups)
        idx = non_empty[0] if non_empty else 0
        conf.partition_index = idx
        pschema = d.get("partition_schema")
        pvals = (d.get("partition_values") or [])
        group_vals = pvals[idx] if idx < len(pvals) else []
        for fi, path in enumerate(groups[idx]):
            pf = conf.file_group.files.add(path=path)
            if pschema is not None and fi < len(group_vals):
                for v, fld in zip(group_vals[fi], pschema["fields"]):
                    pf.partition_values.append(
                        scalar_to_proto(v, fld["type"]))
        if pschema is not None:
            conf.partition_schema.CopyFrom(schema_to_proto(pschema))
        conf.schema.CopyFrom(schema_to_proto(d["schema"]))
        if d.get("projection"):
            names = [f["name"] for f in d["schema"]["fields"]]
            if d.get("partition_schema"):
                names += [f["name"]
                          for f in d["partition_schema"]["fields"]]
            for p in d["projection"]:
                conf.projection.append(names.index(p))
        if k == "parquet_scan" and d.get("predicate"):
            node.pruning_predicates.append(expr_to_proto(d["predicate"]))
        return n
    if k == "ipc_reader":
        n.ipc_reader.ipc_provider_resource_id = d["resource_id"]
        n.ipc_reader.schema.CopyFrom(schema_to_proto(d["schema"]))
        n.ipc_reader.num_partitions = d.get("num_partitions", 1)
        return n
    if k == "ffi_reader":
        n.ffi_reader.export_iter_provider_resource_id = d["resource_id"]
        n.ffi_reader.schema.CopyFrom(schema_to_proto(d["schema"]))
        n.ffi_reader.num_partitions = d.get("num_partitions", 1)
        return n
    if k == "empty_partitions":
        n.empty_partitions.schema.CopyFrom(schema_to_proto(d["schema"]))
        n.empty_partitions.num_partitions = d.get("num_partitions", 1)
        return n
    if k == "kafka_scan":
        ks = n.kafka_scan
        ks.kafka_topic = d.get("topic", "")
        ks.kafka_properties_json = d.get("properties_json", "")
        ks.schema.CopyFrom(schema_to_proto(d["schema"]))
        ks.batch_size = d.get("batch_size", 0)
        ks.startup_mode = getattr(pb, d.get("startup_mode",
                                            "group_offset").upper())
        ks.auron_operator_id = d.get("operator_id", "")
        ks.data_format = getattr(pb, d.get("format", "json").upper())
        ks.format_config_json = d.get("format_config_json", "")
        ks.mock_data_json_array = d.get("mock_data_json_array", "")
        return n
    if k == "debug":
        n.debug.input.CopyFrom(plan_to_proto(d["input"]))
        n.debug.debug_id = d.get("tag", "debug")
        return n
    if k == "shuffle_writer":
        n.shuffle_writer.input.CopyFrom(plan_to_proto(d["input"]))
        n.shuffle_writer.output_partitioning.CopyFrom(
            partitioning_to_proto(d["partitioning"]))
        n.shuffle_writer.output_data_file = d["data_file"]
        n.shuffle_writer.output_index_file = d["index_file"]
        return n
    if k == "rss_shuffle_writer":
        n.rss_shuffle_writer.input.CopyFrom(plan_to_proto(d["input"]))
        n.rss_shuffle_writer.output_partitioning.CopyFrom(
            partitioning_to_proto(d["partitioning"]))
        n.rss_shuffle_writer.rss_partition_writer_resource_id = \
            d["rss_resource_id"]
        return n
    if k == "ipc_writer":
        n.ipc_writer.input.CopyFrom(plan_to_proto(d["input"]))
        n.ipc_writer.ipc_consumer_resource_id = d["sink_resource_id"]
        return n
    if k == "project":
        n.projection.input.CopyFrom(plan_to_proto(d["input"]))
        for e in d["exprs"]:
            n.projection.expr.append(expr_to_proto(e))
        for name in d["names"]:
            n.projection.expr_name.append(name)
        return n
    if k == "filter_project":
        # no combined node on the wire: filter feeding projection
        inner = {"kind": "filter", "input": d["input"],
                 "predicates": d["predicates"]}
        return plan_to_proto({"kind": "project", "input": inner,
                              "exprs": d["exprs"], "names": d["names"]})
    if k == "filter":
        n.filter.input.CopyFrom(plan_to_proto(d["input"]))
        for e in d["predicates"]:
            n.filter.expr.append(expr_to_proto(e))
        return n
    if k == "sort":
        n.sort.input.CopyFrom(plan_to_proto(d["input"]))
        for s in d["specs"]:
            n.sort.expr.append(sort_spec_to_proto(s))
        if d.get("fetch") is not None:
            n.sort.fetch_limit.limit = d["fetch"]
        return n
    if k == "limit":
        n.limit.input.CopyFrom(plan_to_proto(d["input"]))
        n.limit.limit = d["limit"]
        n.limit.offset = d.get("offset", 0)
        return n
    if k == "union":
        for i, child in enumerate(d["inputs"]):
            inp = n.union.input.add()
            inp.input.CopyFrom(plan_to_proto(child))
            parts = d.get("input_partitions")
            inp.partition = parts[i] if parts else 0
        n.union.num_partitions = d.get("num_partitions", 1)
        n.union.cur_partition = d.get("cur_partition", 0)
        return n
    if k == "rename_columns":
        n.rename_columns.input.CopyFrom(plan_to_proto(d["input"]))
        for name in d["names"]:
            n.rename_columns.renamed_column_names.append(name)
        return n
    if k == "expand":
        n.expand.input.CopyFrom(plan_to_proto(d["input"]))
        for proj in d["projections"]:
            p = n.expand.projections.add()
            for e in proj:
                p.expr.append(expr_to_proto(e))
        for name in d["names"]:
            n.expand.schema.columns.add(name=name)
        return n
    if k == "coalesce_batches":
        n.coalesce_batches.input.CopyFrom(plan_to_proto(d["input"]))
        n.coalesce_batches.batch_size = d.get("batch_size") or 0
        return n
    if k in ("hash_agg", "sort_agg"):
        return _agg_to_proto(d)
    if k in ("sort_merge_join", "hash_join", "broadcast_join"):
        return _join_to_proto(d)
    if k == "broadcast_nested_loop_join":
        # no dedicated wire node (ref auron.proto PhysicalPlanType): a
        # KEYLESS broadcast_join IS a nested-loop join — encode as
        # broadcast_join with an empty `on` list; decode reverses it.
        # The wire node has no filter field; for INNER joins a residual
        # condition is equivalent to a FilterExec over the cross product,
        # so lift it (outer variants would change null-extension
        # semantics and are rejected)
        filt = d.get("join_filter")
        if filt is not None and d.get("join_type", "inner") != "inner":
            raise ValueError(
                "outer broadcast_nested_loop_join with a join_filter "
                "has no wire encoding (lifting would change "
                "null-extension semantics)")
        bare = {key: v for key, v in d.items() if key != "join_filter"}
        inner = _join_to_proto(dict(bare, kind="broadcast_join",
                                    left_keys=[], right_keys=[]))
        if filt is None:
            return inner
        n.filter.input.CopyFrom(inner)
        n.filter.expr.append(expr_to_proto(filt))
        return n
    if k == "broadcast_join_build_hash_map":
        n.broadcast_join_build_hash_map.input.CopyFrom(
            plan_to_proto(d["input"]))
        for e in d["keys"]:
            n.broadcast_join_build_hash_map.keys.append(expr_to_proto(e))
        return n
    if k == "window":
        return _window_to_proto(d)
    if k == "generate":
        return _generate_to_proto(d)
    if k == "parquet_sink":
        n.parquet_sink.input.CopyFrom(plan_to_proto(d["input"]))
        n.parquet_sink.fs_resource_id = d.get("fs_resource_id",
                                              d.get("path", ""))
        n.parquet_sink.num_dyn_parts = d.get("num_dyn_parts", 0)
        for key, value in d.get("props", {}).items():
            n.parquet_sink.prop.add(key=key, value=value)
        return n
    if k == "orc_sink":
        n.orc_sink.input.CopyFrom(plan_to_proto(d["input"]))
        n.orc_sink.fs_resource_id = d.get("fs_resource_id",
                                          d.get("path", ""))
        n.orc_sink.num_dyn_parts = d.get("num_dyn_parts", 0)
        for key, value in d.get("props", {}).items():
            n.orc_sink.prop.add(key=key, value=value)
        return n
    raise ValueError(f"cannot encode plan kind {k!r}")


def _agg_to_proto(d: Dict[str, Any]) -> pb.PhysicalPlanNode:
    n = pb.PhysicalPlanNode()
    agg = n.agg
    agg.input.CopyFrom(plan_to_proto(d["input"]))
    agg.exec_mode = pb.HASH_AGG if d["kind"] == "hash_agg" else pb.SORT_AGG
    for g in d.get("groupings", []):
        agg.grouping_expr.append(expr_to_proto(g["expr"]))
        agg.grouping_expr_name.append(g["name"])
    for a in d.get("aggs", []):
        mode = a.get("mode", "partial")
        if mode == "complete":
            raise ValueError("complete agg mode has no wire encoding; "
                             "split into partial+final")
        agg.mode.append({"partial": pb.PARTIAL,
                         "partial_merge": pb.PARTIAL_MERGE,
                         "final": pb.FINAL}[mode])
        agg.agg_expr_name.append(a["name"])
        e = pb.PhysicalExprNode()
        e.agg_expr.agg_function = _AGG_FN_ENCODE[a["fn"]]
        if mode == "partial":
            for c in a.get("args", []):
                e.agg_expr.children.append(expr_to_proto(c))
        else:
            # placeholders on the wire (ref NativeAggBase createPlaceholder);
            # decode rebinds positionally
            for c in a.get("args", []):
                e.agg_expr.children.append(expr_to_proto(
                    {"kind": "literal", "value": None, "type": {"id": "null"}}
                ))
        if a.get("fn") == "udaf":
            e.agg_expr.udaf.serialized = \
                a.get("options", {}).get("udaf_name", "").encode("utf-8")
        agg.agg_expr.append(e)
    agg.initial_input_buffer_offset = d.get("initial_input_buffer_offset", 0)
    agg.supports_partial_skipping = d.get("supports_partial_skipping", False)
    return n


def _join_to_proto(d: Dict[str, Any]) -> pb.PhysicalPlanNode:
    n = pb.PhysicalPlanNode()
    k = d["kind"]
    node = getattr(n, k)
    node.left.CopyFrom(plan_to_proto(d["left"]))
    node.right.CopyFrom(plan_to_proto(d["right"]))
    for lk, rk in zip(d["left_keys"], d["right_keys"]):
        on = node.on.add()
        on.left.CopyFrom(expr_to_proto(lk))
        on.right.CopyFrom(expr_to_proto(rk))
    jt = d.get("join_type", "inner")
    if jt in ("right_semi", "right_anti"):
        # the wire has no right-sided semi/anti (ref JoinType enum,
        # auron.proto:515-523); front-ends swap children instead
        raise ValueError(f"{jt} has no wire encoding; swap the sides")
    node.join_type = _JOIN_TYPE_ENCODE[jt]
    if k == "hash_join":
        node.build_side = (pb.LEFT_SIDE
                           if d.get("build_side", "right") == "left"
                           else pb.RIGHT_SIDE)
        if d.get("join_filter"):
            node.filter.expression.CopyFrom(expr_to_proto(d["join_filter"]))
    elif k == "broadcast_join":
        node.broadcast_side = (pb.LEFT_SIDE
                               if d.get("build_side", "right") == "left"
                               else pb.RIGHT_SIDE)
        if d.get("broadcast_id"):
            node.cached_build_hash_map_id = d["broadcast_id"]
        node.is_null_aware_anti_join = d.get("null_aware_anti", False)
    else:
        if d.get("join_filter"):
            node.filter.expression.CopyFrom(expr_to_proto(d["join_filter"]))
        for _ in d["left_keys"]:
            node.sort_options.add(asc=True, nulls_first=True)
    return n


def _window_to_proto(d: Dict[str, Any]) -> pb.PhysicalPlanNode:
    n = pb.PhysicalPlanNode()
    w = n.window
    w.input.CopyFrom(plan_to_proto(d["input"]))
    for f in d["functions"]:
        we = w.window_expr.add()
        we.field.name = f["name"]
        fk = f["kind"]
        if fk == "agg":
            if f.get("running") is False and d.get("order_by"):
                # the wire (like the reference's WindowExprNode) carries
                # no frame spec: whole-partition aggregation is encoded
                # by an EMPTY order_spec (Spark semantics) — an agg that
                # wants it WITH ordering would silently decode as a
                # running frame, so refuse loudly
                raise ValueError(
                    "whole-partition window agg frame with order_by has "
                    "no wire encoding; drop order_by (partition-sorted "
                    "input still groups correctly)")
            we.func_type = pb.Agg
            we.agg_func = _AGG_FN_ENCODE[f["fn"]]
            for c in f.get("args", []):
                we.children.append(expr_to_proto(c))
        elif fk in _WINDOW_RANK_ENCODE:
            we.func_type = pb.Window
            we.window_func = _WINDOW_RANK_ENCODE[fk]
        elif fk in ("lead", "lag"):
            we.func_type = pb.Window
            we.window_func = pb.LEAD
            we.children.append(expr_to_proto(f["expr"]))
            off = f.get("offset", 1)
            if fk == "lag":
                off = -off
            we.children.append(expr_to_proto(
                {"kind": "literal", "value": off, "type": {"id": "int64"}}))
            if f.get("default") is not None:
                we.children.append(expr_to_proto(
                    {"kind": "literal", "value": f["default"],
                     "type": _value_type(f["default"])}))
        elif fk == "nth_value":
            we.func_type = pb.Window
            we.window_func = (pb.NTH_VALUE_IGNORE_NULLS
                              if f.get("ignore_nulls") else pb.NTH_VALUE)
            we.children.append(expr_to_proto(f["expr"]))
            we.children.append(expr_to_proto(
                {"kind": "literal", "value": f.get("n", 1),
                 "type": {"id": "int64"}}))
        else:
            raise ValueError(f"cannot encode window function {fk!r}")
    for e in d.get("partition_by", []):
        w.partition_spec.append(expr_to_proto(e))
    for s in d.get("order_by", []):
        w.order_spec.append(sort_spec_to_proto(s))
    if d.get("group_limit") is not None:
        w.group_limit.k = d["group_limit"]
    w.output_window_cols = True
    return n


def _generate_to_proto(d: Dict[str, Any]) -> pb.PhysicalPlanNode:
    n = pb.PhysicalPlanNode()
    g = n.generate
    g.input.CopyFrom(plan_to_proto(d["input"]))
    gen = d["generator"]
    gk = gen["kind"]
    if gk in ("explode", "posexplode"):
        g.generator.func = pb.Explode if gk == "explode" else pb.PosExplode
        g.generator.child.append(expr_to_proto(gen["child"]))
        g.outer = gen.get("outer", False)
    elif gk == "json_tuple":
        g.generator.func = pb.JsonTuple
        g.generator.child.append(expr_to_proto(gen["child"]))
        for f in gen["fields"]:
            g.generator.child.append(expr_to_proto(
                {"kind": "literal", "value": f, "type": {"id": "utf8"}}))
    elif gk == "udtf":
        g.generator.func = pb.Udtf
        g.generator.udtf.serialized = gen["name"].encode("utf-8")
        for a in gen.get("args", []):
            g.generator.child.append(expr_to_proto(a))
        for f in gen.get("fields", []):
            g.generator_output.append(field_to_proto(f))
    else:
        raise ValueError(f"cannot encode generator {gk!r}")
    req_names = d.get("required_child_output")
    if req_names is None:
        # The wire carries NAMES (proto `required_child_output`); an
        # untranslated/absent list used to serialize empty, which decodes
        # as "keep zero child columns" and silently narrowed the output
        # (wire-report-caught on gq1).  Index form translates via the
        # child's output names; the keep-all default enumerates them all;
        # ambiguous duplicate names cannot ride this name-keyed wire
        # field and raise rather than rebinding to the wrong column.
        names = _output_names_of(d["input"])
        if d.get("required_cols") is not None:
            req_names = [names[i] for i in d["required_cols"]]
        else:
            req_names = list(names)  # keep-all (GenerateExec default)
        dupes = {x for x in req_names if names.count(x) > 1}
        if dupes:
            raise ValueError(
                f"generate required columns {sorted(dupes)} are "
                f"ambiguous duplicate names; the wire carries names — "
                f"rename the child columns first")
    for name in req_names or []:
        g.required_child_output.append(name)
    return n


def _output_names_of(d: Dict[str, Any]) -> List[str]:
    """Output column names of a plan dict WITHOUT constructing operator
    trees (serialization must not depend on execution-time resources,
    e.g. memory_scan/udtf resource-map entries).  Falls back to the
    planner for exotic shapes."""
    k = d.get("kind")
    if k in ("parquet_scan", "orc_scan"):
        if d.get("projection"):
            return list(d["projection"])
        names = [f["name"] for f in d["schema"]["fields"]]
        if d.get("partition_schema"):
            names += [f["name"] for f in d["partition_schema"]["fields"]]
        return names
    if k in ("ipc_reader", "ffi_reader", "empty_partitions",
             "memory_scan", "kafka_scan"):
        return [f["name"] for f in d["schema"]["fields"]]
    if k in ("project", "filter_project", "rename_columns", "expand"):
        return list(d["names"])
    if k in ("filter", "limit", "sort", "local_exchange", "debug",
             "coalesce_batches"):
        return _output_names_of(d["input"])
    from blaze_tpu.plan.planner import create_plan as _cp
    return [f.name for f in _cp(d).schema]


# ---------------------------------------------------------------------------
# TaskDefinition (ref auron.proto:814, rt.rs:79-90)
# ---------------------------------------------------------------------------

def task_definition_from_bytes(data: bytes) -> Dict[str, Any]:
    td = pb.TaskDefinition()
    td.ParseFromString(data)
    out: Dict[str, Any] = {
        "stage_id": int(td.task_id.stage_id),
        "partition_id": int(td.task_id.partition_id),
        "task_attempt_id": int(td.task_id.task_id),
        "plan": plan_from_proto(td.plan),
    }
    if td.HasField("output_partitioning"):
        out["output_partitioning"] = \
            partitioning_from_proto(td.output_partitioning)
    return out


def task_definition_to_bytes(td_dict: Dict[str, Any]) -> bytes:
    td = pb.TaskDefinition()
    td.task_id.stage_id = td_dict.get("stage_id", 0)
    td.task_id.partition_id = td_dict.get("partition_id", 0)
    td.task_id.task_id = td_dict.get("task_attempt_id", 0)
    td.plan.CopyFrom(plan_to_proto(td_dict["plan"]))
    if td_dict.get("output_partitioning"):
        td.output_partitioning.CopyFrom(
            partitioning_to_proto(td_dict["output_partitioning"]))
    return td.SerializeToString()
