"""Vendored wire contract.

`auron.proto` is copied VERBATIM from the reference
(`native-engine/auron-planner/proto/auron.proto`, Apache License 2.0,
Apache Auron incubating) — it is the engine-neutral plan/expr serde
contract that the JVM front-end layers emit, adopted byte-for-byte per
SURVEY.md §7 step 3 so the existing Spark/Flink extensions can target this
engine through the preserved `TaskDefinition` boundary.

`auron_pb2.py` is generated output:
    protoc --python_out=. auron.proto   (from this directory)
"""

from blaze_tpu.plan.proto import auron_pb2  # noqa: F401
