"""Expression decoding: IR dicts -> PhysicalExpr trees.

Parity: try_parse_physical_expr (ref auron-planner/src/planner.rs:924)
pattern-matching the PhysicalExprNode oneof (~35 kinds, auron.proto:60-141)
plus from_proto_binary_op (ref src/lib.rs:73).

Expression kinds (the `kind` discriminator):
  column, literal, binary, is_null, is_not_null, not, case, if, coalesce,
  in_list, cast, try_cast, like, rlike, string_starts_with,
  string_ends_with, string_contains, scalar_function, named_struct,
  get_indexed_field, get_map_value, row_num, spark_partition_id,
  monotonically_increasing_id, rand, randn, bloom_filter_might_contain,
  scalar_subquery, udf
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from blaze_tpu.exprs import (BinaryExpr, BloomFilterMightContain,
                             BoundReference, CaseWhen, Cast, Coalesce,
                             GetIndexedField, GetMapValue, If, InList,
                             IsNotNull, IsNull, Like, Literal,
                             MonotonicallyIncreasingId, NamedStruct, Not,
                             PhysicalExpr, RLike, Rand, RowNum,
                             ScalarSubqueryWrapper, SparkPartitionId,
                             StringPredicate, TryCast, UDFWrapper)
from blaze_tpu.funcs import ScalarFunctionExpr
from blaze_tpu.plan.types import type_from_dict
from blaze_tpu.schema import Schema


def expr_from_dict(d: Dict[str, Any], schema: Optional[Schema] = None
                   ) -> PhysicalExpr:
    """Decode one expression node, then constant-fold it if every child
    is a Literal.  Recursive child decodes come back through this
    wrapper, so folding a single node here yields full bottom-up
    folding across the tree (exprs/fold.py, auron.tpu.expr.constFold)."""
    from blaze_tpu.exprs.fold import fold_node
    e = _expr_from_dict(d, schema)
    return fold_node(e, schema)


def _expr_from_dict(d: Dict[str, Any], schema: Optional[Schema] = None
                    ) -> PhysicalExpr:
    k = d["kind"]
    if k == "column":
        idx = d.get("index")
        if idx is None:
            if schema is None:
                raise ValueError("named column ref requires an input schema")
            idx = schema.index_of(d["name"])
        return BoundReference(idx, d.get("name", ""))
    if k == "literal":
        return Literal(d.get("value"), type_from_dict(d["type"]))
    if k == "binary":
        return BinaryExpr(d["op"], expr_from_dict(d["l"], schema),
                          expr_from_dict(d["r"], schema))
    if k == "is_null":
        return IsNull(expr_from_dict(d["child"], schema))
    if k == "is_not_null":
        return IsNotNull(expr_from_dict(d["child"], schema))
    if k == "not":
        return Not(expr_from_dict(d["child"], schema))
    if k == "case":
        branches = tuple((expr_from_dict(w, schema), expr_from_dict(t, schema))
                         for w, t in d["branches"])
        other = (expr_from_dict(d["else"], schema)
                 if d.get("else") is not None else None)
        return CaseWhen(branches, other)
    if k == "if":
        return If(expr_from_dict(d["cond"], schema),
                  expr_from_dict(d["then"], schema),
                  expr_from_dict(d["else"], schema))
    if k == "coalesce":
        return Coalesce(tuple(expr_from_dict(a, schema) for a in d["args"]))
    if k == "in_list":
        return InList(expr_from_dict(d["child"], schema),
                      tuple(d["values"]), d.get("negated", False))
    if k in ("cast", "try_cast"):
        cls = Cast if k == "cast" else TryCast
        return cls(expr_from_dict(d["child"], schema),
                   type_from_dict(d["type"]))
    if k == "like":
        return Like(expr_from_dict(d["child"], schema), d["pattern"],
                    d.get("negated", False), d.get("case_insensitive", False))
    if k == "rlike":
        return RLike(expr_from_dict(d["child"], schema), d["pattern"],
                     d.get("case_insensitive", False))
    if k in ("string_starts_with", "string_ends_with", "string_contains"):
        kind = k.replace("string_", "")
        return StringPredicate(kind, expr_from_dict(d["child"], schema),
                               d["pattern"])
    if k == "scalar_function":
        args = tuple(expr_from_dict(a, schema) for a in d.get("args", ()))
        out_t = (type_from_dict(d["return_type"])
                 if d.get("return_type") else None)
        return ScalarFunctionExpr(d["name"], args, out_t)
    if k == "named_struct":
        return NamedStruct(tuple(d["names"]),
                           tuple(expr_from_dict(a, schema)
                                 for a in d["args"]))
    if k == "get_indexed_field":
        return GetIndexedField(expr_from_dict(d["child"], schema),
                               d["index"], type_from_dict(d["type"]))
    if k == "get_map_value":
        return GetMapValue(expr_from_dict(d["child"], schema), d["key"],
                           type_from_dict(d["type"]))
    if k == "row_num":
        return RowNum()
    if k == "spark_partition_id":
        return SparkPartitionId()
    if k == "monotonically_increasing_id":
        return MonotonicallyIncreasingId()
    if k in ("rand", "randn"):
        return Rand(d.get("seed", 0), normal=(k == "randn"))
    if k == "bloom_filter_might_contain":
        return BloomFilterMightContain(d["uuid"],
                                       expr_from_dict(d["value"], schema))
    if k == "scalar_subquery":
        return ScalarSubqueryWrapper(d["uuid"], type_from_dict(d["type"]))
    if k == "udf":
        from blaze_tpu.bridge.resource import get_resource
        fn = get_resource(f"udf://{d['name']}")
        if fn is None:
            raise KeyError(f"UDF {d['name']!r} not registered in the "
                           f"resource map (udf://{d['name']})")
        return UDFWrapper(d["name"], fn,
                          tuple(expr_from_dict(a, schema)
                                for a in d.get("args", ())),
                          type_from_dict(d["type"]))
    raise ValueError(f"unknown expression kind {k!r}")


def sort_spec_from_dict(d: Dict[str, Any], schema: Optional[Schema] = None):
    """{expr, descending, nulls_first} -> SortExec spec tuple."""
    return (expr_from_dict(d["expr"], schema),
            bool(d.get("descending", False)),
            bool(d.get("nulls_first", not d.get("descending", False))))
