"""EXPLAIN ANALYZE: execute a plan and render the annotated operator tree.

Parity role: Spark's `EXPLAIN ANALYZE` / the SQL-tab per-node SQLMetrics
view over the reference engine.  `explain_analyze` runs the query through
the production task path, merges the per-partition metric trees into one
query-level profile (MetricNode.merge_from), snapshots XLA compile and
host<->device transfer counters around the run, and renders the result as
an annotated plan text or a JSON-ready dict.

The profile is registered with the observability service
(bridge/profiling.record_profile), so the same data is retrievable over
HTTP at /profile/<qid> and folded into /metrics.prom.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from blaze_tpu.bridge.metrics import BASELINE_METRICS, MetricNode


def _fmt_ns(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.2f}s"
    if ns >= 1_000_000:
        return f"{ns / 1e6:.2f}ms"
    if ns >= 1_000:
        return f"{ns / 1e3:.1f}us"
    return f"{ns}ns"


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n}B"


def format_speculation_footer(x) -> Optional[str]:
    """The explain-analyze "speculation:" footer for one run's engine
    stats, or None when no hedging (or rejected loser commit) happened
    — speculation is off by default and the profile must stay
    byte-identical then."""
    if not any(x.get(k) for k in ("speculation_attempts",
                                  "speculation_wins",
                                  "speculation_loser_commits_rejected",
                                  "speculation_commit_races")):
        return None
    return (
        f"speculation: waves={x.get('speculation_waves', 0)} "
        f"attempts={x.get('speculation_attempts', 0)} "
        f"wins={x.get('speculation_wins', 0)} "
        f"losers_cancelled="
        f"{x.get('speculation_losers_cancelled', 0)} "
        f"loser_commits_rejected="
        f"{x.get('speculation_loser_commits_rejected', 0)} "
        f"commit_races={x.get('speculation_commit_races', 0)} "
        f"duplicate_commits="
        f"{x.get('speculation_duplicate_commits', 0)}")


def format_work_sharing_footer(x) -> Optional[str]:
    """The explain-analyze "work sharing:" footer (result/subplan cache,
    single-flight, shared scan decode), or None when the run touched
    none of it — the cache is off by default and the profile must stay
    byte-identical then."""
    if not any(x.get(k) for k in (
            "result_cache_hits", "result_cache_misses",
            "result_cache_puts", "subplan_cache_hits",
            "subplan_cache_misses", "single_flight_coalesces",
            "scan_share_hits", "scan_share_misses")):
        return None

    def rate(hits: int, misses: int) -> str:
        total = hits + misses
        return f"{hits / total:.0%}" if total else "n/a"

    rc_h = x.get("result_cache_hits", 0)
    rc_m = x.get("result_cache_misses", 0)
    sp_h = x.get("subplan_cache_hits", 0)
    sp_m = x.get("subplan_cache_misses", 0)
    ss_h = x.get("scan_share_hits", 0)
    ss_m = x.get("scan_share_misses", 0)
    return (
        f"work sharing: result={rc_h}/{rc_h + rc_m} "
        f"({rate(rc_h, rc_m)}) "
        f"subplan={sp_h}/{sp_h + sp_m} ({rate(sp_h, sp_m)}) "
        f"coalesced={x.get('single_flight_coalesces', 0)} "
        f"promoted={x.get('single_flight_promotions', 0)} "
        f"scan_share={ss_h}/{ss_h + ss_m} ({rate(ss_h, ss_m)}) "
        f"saved={_fmt_bytes(x.get('scan_share_bytes_saved', 0))} "
        f"evictions={x.get('result_cache_evictions', 0)} "
        f"invalidations={x.get('result_cache_invalidations', 0)}")


def format_aqe_footer(x) -> Optional[str]:
    """The explain-analyze "aqe:" footer (runtime rewrites and
    history-seeded planning), or None when adaptive execution never
    fired — AQE is off by default and the profile must stay
    byte-identical then."""
    if not (x.get("aqe_rewrites") or x.get("aqe_history_seeds")):
        return None
    return (
        f"aqe: rewrites={x.get('aqe_rewrites', 0)} "
        f"broadcast={x.get('aqe_broadcast_switches', 0)} "
        f"coalesced={x.get('aqe_partitions_coalesced', 0)} "
        f"skew_splits={x.get('aqe_skew_splits', 0)} "
        f"history_seeds={x.get('aqe_history_seeds', 0)} "
        f"stages_elided={x.get('aqe_stages_elided', 0)} "
        f"saved={_fmt_bytes(x.get('aqe_bytes_saved', 0))}")


def format_encodings_footer(x) -> Optional[str]:
    """The explain-analyze "encodings:" footer (dictionary-encoded
    strings and scaled-int/limb decimals on the device lanes), or None
    when no encoding lane fired — the encoding knobs are off by default
    and the profile must stay byte-identical then."""
    ev = (x.get("host_evictions_string", 0)
          + x.get("host_evictions_decimal", 0)
          + x.get("host_evictions_other", 0))
    if not (x.get("dict_encoded_columns")
            or x.get("decimal_scaled_int32_dispatches")
            or x.get("decimal_scaled_int64_dispatches")
            or x.get("decimal_limb_dispatches") or ev):
        return None
    return (
        f"encodings: dict_cols={x.get('dict_encoded_columns', 0)} "
        f"remaps={x.get('dict_exchange_remaps', 0)} "
        f"dec_i32={x.get('decimal_scaled_int32_dispatches', 0)} "
        f"dec_i64={x.get('decimal_scaled_int64_dispatches', 0)} "
        f"dec_limb={x.get('decimal_limb_dispatches', 0)} "
        f"evictions=string:{x.get('host_evictions_string', 0)}"
        f"/decimal:{x.get('host_evictions_decimal', 0)}"
        f"/other:{x.get('host_evictions_other', 0)}")


def format_bottleneck_footer(report) -> Optional[str]:
    """The explain-analyze "bottleneck:" footer from a
    bridge/critical_path.bottleneck_report dict, or None when no spans
    were traced — tracing is off by default and the profile must stay
    byte-identical then."""
    if not report or not report.get("span_count"):
        return None
    cats = report.get("categories") or {}
    parts = [f"{k}={cats[k]:.3f}s" for k in sorted(cats) if cats.get(k)]
    head = f"bottleneck: wall={report.get('wall_s', 0):.3f}s"
    dom = report.get("dominant")
    if dom:
        head += (f" dominant={dom} "
                 f"({report.get('dominant_fraction', 0):.0%})")
    return head + ((" " + " ".join(parts)) if parts else "")


def _node_line(node: MetricNode) -> str:
    v = node.values
    total = v.get("elapsed_compute_ns", 0)
    self_ns = max(0, total - sum(c.values.get("elapsed_compute_ns", 0)
                                 for c in node.children))
    parts = [f"rows={v.get('output_rows', 0)}",
             f"batches={v.get('output_batches', 0)}",
             f"time={_fmt_ns(total)}"]
    if node.children:
        parts.append(f"(self {_fmt_ns(self_ns)})")
    if v.get("mem_used", 0):
        parts.append(f"mem={_fmt_bytes(v['mem_used'])}")
    if v.get("spilled_bytes", 0):
        parts.append(f"spilled={_fmt_bytes(v['spilled_bytes'])}")
    if v.get("io_bytes", 0):
        parts.append(f"io={_fmt_bytes(v['io_bytes'])}")
    for k in sorted(v):
        if k not in BASELINE_METRICS and v[k]:
            parts.append(f"{k}={v[k]}")
    return f"{node.name or '?'}  [{' '.join(parts)}]"


def render_tree(node: MetricNode, indent: str = "", last: bool = True,
                root: bool = True) -> List[str]:
    if root:
        lines = [_node_line(node)]
        child_indent = ""
    else:
        branch = "└─ " if last else "├─ "
        lines = [indent + branch + _node_line(node)]
        child_indent = indent + ("   " if last else "│  ")
    for i, c in enumerate(node.children):
        lines.extend(render_tree(c, child_indent,
                                 last=(i == len(node.children) - 1),
                                 root=False))
    return lines


@dataclass
class QueryProfile:
    """One executed query's merged profile (the /profile/<qid> payload)."""
    query_id: str
    wall_ns: int
    tree: MetricNode
    partitions: int
    exec_mode: str
    xla: Dict[str, int] = field(default_factory=dict)
    kernels: Dict[str, dict] = field(default_factory=dict)
    placement: str = ""
    output_rows: int = 0
    # critical-path category attribution (bridge/critical_path.py
    # bottleneck_report over the run's spans); None when tracing was off
    bottleneck: Optional[dict] = None
    # result table, only populated under keep_result=True; NOT serialized
    result: Optional[Any] = None

    def to_dict(self) -> dict:
        d = {
            "query_id": self.query_id,
            "wall_ns": self.wall_ns,
            "tree": self.tree.to_dict(),
            "partitions": self.partitions,
            "exec_mode": self.exec_mode,
            "xla": dict(self.xla),
            "kernels": {k: dict(v) for k, v in self.kernels.items()},
            "placement": self.placement,
            "output_rows": self.output_rows,
        }
        if self.bottleneck is not None:
            d["bottleneck"] = self.bottleneck
        return d

    def render_text(self) -> str:
        lines = [f"== query profile {self.query_id} "
                 f"(wall {_fmt_ns(self.wall_ns)}, "
                 f"{self.partitions} partition(s), "
                 f"mode={self.exec_mode}, placement={self.placement}) =="]
        lines.extend(render_tree(self.tree))
        x = self.xla
        lines.append(
            f"XLA: compiles={x.get('total_compiles', 0)} "
            f"cache_hits={x.get('total_cache_hits', 0)} "
            f"compile_time={_fmt_ns(x.get('total_compile_ns', 0))}")
        churny = [f"{k} ({v['distinct_signatures']} signatures)"
                  for k, v in sorted(self.kernels.items())
                  if v.get("shape_churn")]
        if churny:
            lines.append("shape-churn kernels: " + ", ".join(churny))
        lines.append(
            f"transfers: h2d={_fmt_bytes(x.get('h2d_bytes', 0))} "
            f"({x.get('h2d_transfers', 0)}) "
            f"d2h={_fmt_bytes(x.get('d2h_bytes', 0))} "
            f"({x.get('d2h_transfers', 0)})")
        if x.get("bucket_batches"):
            lines.append(
                f"batch shaping: bucketed_caps={x.get('bucket_batches', 0)} "
                f"new_buckets={x.get('distinct_buckets', 0)} "
                f"pad_rows={x.get('bucket_pad_rows', 0)}")
        if x.get("prefetch_batches") or x.get("prefetch_wait_ns"):
            lines.append(
                f"prefetch: batches={x.get('prefetch_batches', 0)} "
                f"consumer_wait={_fmt_ns(x.get('prefetch_wait_ns', 0))} "
                f"({x.get('prefetch_waits', 0)} waits)")
        if (x.get("expr_fused_batches") or x.get("expr_eager_batches")
                or x.get("expr_programs_built")):
            looked_up = (x.get("expr_programs_built", 0)
                         + x.get("expr_program_cache_hits", 0))
            rate = (x.get("expr_program_cache_hits", 0) / looked_up
                    if looked_up else 0.0)
            lines.append(
                f"expr programs: built={x.get('expr_programs_built', 0)} "
                f"cache_hits={x.get('expr_program_cache_hits', 0)} "
                f"(hit_rate={rate:.2f}) "
                f"fused_batches={x.get('expr_fused_batches', 0)} "
                f"eager_batches={x.get('expr_eager_batches', 0)} "
                f"evictions={x.get('expr_program_evictions', 0)}")
        if x.get("partial_agg_skip_events") or x.get("partial_agg_probe_rows"):
            probe_rows = x.get("partial_agg_probe_rows", 0)
            ratio = (x.get("partial_agg_probe_groups", 0) / probe_rows
                     if probe_rows else 0.0)
            events = x.get("partial_agg_skip_events", 0)
            switch_row = (x.get("partial_agg_switch_rows", 0) // events
                          if events else 0)
            lines.append(
                f"partial agg: probe_ratio={ratio:.2f} "
                f"skip_events={events} switch_row={switch_row} "
                f"passed_rows={x.get('partial_agg_skipped_rows', 0)} "
                f"spill_switches={x.get('partial_agg_spill_switches', 0)}")
        if any(x.get(k) for k in ("task_retries", "task_failures",
                                  "fetch_failures", "stage_recoveries",
                                  "faults_injected")):
            lines.append(
                f"fault tolerance: attempts={x.get('task_attempts', 0)} "
                f"retries={x.get('task_retries', 0)} "
                f"retry_wait={_fmt_ns(x.get('task_retry_wait_ns', 0))} "
                f"failures={x.get('task_failures', 0)} "
                f"fetch_failures={x.get('fetch_failures', 0)} "
                f"recoveries={x.get('stage_recoveries', 0)} "
                f"recovered_map_tasks={x.get('recovered_map_tasks', 0)} "
                f"faults_injected={x.get('faults_injected', 0)}")
        if any(x.get(k) for k in ("worker_tasks", "worker_crashes",
                                  "worker_hangs", "worker_blacklisted")):
            lines.append(
                f"workers: tasks={x.get('worker_tasks', 0)} "
                f"spawns={x.get('worker_spawns', 0)} "
                f"crashes={x.get('worker_crashes', 0)} "
                f"hangs={x.get('worker_hangs', 0)} "
                f"restarts={x.get('worker_restarts', 0)} "
                f"blacklisted={x.get('worker_blacklisted', 0)} "
                f"cancels={x.get('worker_cancels', 0)}")
        spec_line = format_speculation_footer(x)
        if spec_line is not None:
            lines.append(spec_line)
        ws_line = format_work_sharing_footer(x)
        if ws_line is not None:
            lines.append(ws_line)
        aqe_line = format_aqe_footer(x)
        if aqe_line is not None:
            lines.append(aqe_line)
        enc_line = format_encodings_footer(x)
        if enc_line is not None:
            lines.append(enc_line)
        if any(x.get(k) for k in ("shuffle_device_bytes",
                                  "shuffle_host_bytes",
                                  "shuffle_device_fallbacks")):
            lines.append(
                f"shuffle: device={_fmt_bytes(x.get('shuffle_device_bytes', 0))} "
                f"({x.get('shuffle_device_collectives', 0)} collectives, "
                f"{x.get('shuffle_device_exchanges', 0)} exchanges, "
                f"{x.get('shuffle_device_rows', 0)} rows) "
                f"host={_fmt_bytes(x.get('shuffle_host_bytes', 0))} "
                f"fallbacks={x.get('shuffle_device_fallbacks', 0)}")
            if x.get("shuffle_device_overlap_exchanges") \
                    or x.get("shuffle_barrier_idle_ns"):
                lines.append(
                    f"  overlap: exchanges="
                    f"{x.get('shuffle_device_overlap_exchanges', 0)} "
                    f"barrier_idle="
                    f"{_fmt_ns(x.get('shuffle_barrier_idle_ns', 0))}")
        saved_w = x.get("worker_frame_compressed_bytes_saved", 0)
        saved_r = x.get("rss_put_compressed_bytes_saved", 0)
        if saved_w or saved_r:
            lines.append(
                f"frame compression: worker={_fmt_bytes(saved_w)} saved "
                f"rss_put={_fmt_bytes(saved_r)} saved")
        if any(x.get(k) for k in ("stage_loop_tasks",
                                  "stage_loop_fallbacks")):
            lines.append(
                f"stage loop: tasks={x.get('stage_loop_tasks', 0)} "
                f"programs={x.get('stage_loop_calls', 0)} "
                f"batches={x.get('stage_loop_batches', 0)} "
                f"rows={x.get('stage_loop_rows', 0)} "
                f"dispatches_avoided="
                f"{x.get('stage_loop_staged_dispatches_avoided', 0)} "
                f"regrows={x.get('stage_loop_regrows', 0)} "
                f"fallbacks={x.get('stage_loop_fallbacks', 0)}")
        if x.get("stream_epochs"):
            epochs = x.get("stream_epochs", 0)
            wall = x.get("stream_epoch_wall_ns", 0)
            lines.append(
                f"stream: epochs={epochs} "
                f"epoch_wall={_fmt_ns(wall // max(1, epochs))}/avg "
                f"rows={x.get('stream_rows', 0)} "
                f"records={x.get('stream_records', 0)} "
                f"late={x.get('stream_late_records', 0)} "
                f"watermark_delay={x.get('stream_watermark_delay_ms_last', 0)}ms "
                f"state={_fmt_bytes(x.get('stream_window_state_bytes_last', 0))} "
                f"lag={x.get('stream_source_lag_records_last', 0)} "
                f"ckpts={x.get('stream_checkpoints', 0)} "
                f"recoveries={x.get('stream_recoveries', 0)} "
                f"sink_commits={x.get('stream_sink_commits', 0)} "
                f"dup_skips={x.get('stream_sink_dup_skips', 0)}")
        lane_keys = ("scatter_lane_hash_pallas",
                     "scatter_lane_hash_interpret",
                     "scatter_lane_hash_scatter",
                     "scatter_lane_partition_pallas",
                     "scatter_lane_partition_interpret",
                     "scatter_lane_partition_scatter")
        if any(x.get(k) for k in lane_keys):
            lines.append(
                "scatter lanes: hash="
                f"{x.get('scatter_lane_hash_pallas', 0)}p/"
                f"{x.get('scatter_lane_hash_interpret', 0)}i/"
                f"{x.get('scatter_lane_hash_scatter', 0)}s "
                "partition="
                f"{x.get('scatter_lane_partition_pallas', 0)}p/"
                f"{x.get('scatter_lane_partition_interpret', 0)}i/"
                f"{x.get('scatter_lane_partition_scatter', 0)}s "
                f"declines={x.get('scatter_lane_declines', 0)} "
                f"fault_fallbacks="
                f"{x.get('scatter_lane_fault_fallbacks', 0)}")
        bn_line = format_bottleneck_footer(self.bottleneck)
        if bn_line is not None:
            lines.append(bn_line)
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.render_text()


def _run_execution_plan(plan, keep_result: bool) -> tuple:
    """Run every partition of an in-process ExecutionPlan through the
    task runtime; returns (merged tree, partitions, rows, table|None)."""
    import pyarrow as pa

    from blaze_tpu.bridge.runtime import NativeExecutionRuntime

    n = plan.num_partitions
    merged = MetricNode()
    rows = 0
    batches = []
    for p in range(n):
        rt = NativeExecutionRuntime(
            {"stage_id": 0, "partition_id": p, "num_partitions": n},
            plan=plan)
        # snapshot BEFORE start(): the producer thread begins pulling
        # batches immediately, and the fused tree may be shared across
        # partition runtimes (counters accumulate on the same nodes)
        before = rt.plan.collect_metrics()
        rt.start()
        try:
            for rb in rt.batches():
                rows += rb.num_rows
                if keep_result:
                    batches.append(rb)
        finally:
            after = rt.finalize()
        merged.merge_from(after.diff(before))
    table = None
    if keep_result:
        table = (pa.Table.from_batches(batches) if batches
                 else pa.Table.from_batches([], schema=plan.schema.to_arrow()))
    return merged, n, rows, table


_READER_NODES = ("IpcReaderExec", "FFIReaderExec")


def _stitch_stages(tree: MetricNode, deps: List[int], sched) -> MetricNode:
    """Reconnect producer-stage metric trees under the reader nodes that
    consumed them, recreating the full pre-split operator tree.  Reader
    nodes appear in the result tree in the same DFS order the splitter
    discovered the exchanges (Stage.deps order)."""
    pending = list(deps)

    def walk(node: MetricNode) -> None:
        # snapshot: the appended subtree was stitched recursively with its
        # OWN stage's deps — walking into it would consume this level's
        children = list(node.children)
        if node.name in _READER_NODES and pending:
            sid = pending.pop(0)
            sub = sched.stage_metrics.get(sid)
            if sub is not None and sid < len(sched.stages):
                node.children.append(
                    _stitch_stages(sub, sched.stages[sid].deps, sched))
        for c in children:
            walk(c)

    walk(tree)
    return tree


def _run_plan_dict(plan: Dict[str, Any],
                   work_dir: Optional[str]) -> tuple:
    """Run an engine-IR dict through the stage DAG scheduler."""
    from blaze_tpu.plan.stages import DagScheduler

    sched = DagScheduler(work_dir=work_dir)
    table = sched.run_collect(plan)
    tree = sched.collect_metrics() or MetricNode()
    if sched.exec_mode == "staged" and sched.stages:
        tree = _stitch_stages(tree, sched.stages[-1].deps, sched)
    if sched.exec_mode == "staged" and sched.stages:
        partitions = sched.stages[-1].num_tasks
    else:
        partitions = 1
    return (tree, partitions, table.num_rows, sched.exec_mode or "local",
            table)


def explain_analyze(plan: Union[Dict[str, Any], Any], *,
                    query_id: Optional[str] = None,
                    work_dir: Optional[str] = None,
                    record: bool = True,
                    keep_result: bool = False) -> QueryProfile:
    """Execute `plan` (an ExecutionPlan instance or an engine-IR dict)
    and return the merged query profile.

    `print(explain_analyze(plan))` renders the annotated operator tree;
    `.to_dict()` is the JSON served on /profile/<qid> when `record`.
    With `keep_result` the output table rides along on `.result` (for
    harnesses that profile AND verify rows in one run)."""
    from blaze_tpu.bridge import profiling, tracing, ui, xla_stats
    from blaze_tpu.bridge.placement import host_resident
    from blaze_tpu.ops.base import ExecutionPlan

    qid = query_id or ui.next_query_id()
    xla_before = xla_stats.snapshot()
    t0 = time.perf_counter_ns()
    with tracing.execution_context(query=qid), \
            tracing.span("explain_analyze", query=qid):
        if isinstance(plan, ExecutionPlan):
            tree, partitions, rows, table = _run_execution_plan(
                plan, keep_result)
            mode = "local"
        else:
            tree, partitions, rows, mode, table = _run_plan_dict(
                plan, work_dir)
    wall_ns = time.perf_counter_ns() - t0

    bottleneck = None
    spans = tracing.spans_for_query(qid)
    if spans:
        from blaze_tpu.bridge import critical_path
        bottleneck = critical_path.bottleneck_report(spans, wall_ns / 1e9)

    profile = QueryProfile(
        query_id=qid, wall_ns=wall_ns, tree=tree, partitions=partitions,
        exec_mode=mode, xla=xla_stats.delta(xla_before),
        kernels=xla_stats.compile_report()["kernels"],
        placement="host" if host_resident() else "device",
        output_rows=rows, bottleneck=bottleneck,
        result=table if keep_result else None)
    if record:
        profiling.record_profile(qid, profile.to_dict())
        ui.record_completion(qid, wall_ns / 1e9, metrics=tree.to_dict())
    return profile
