"""Adaptive query execution: re-plan at stage boundaries from stats the
engine already collects.

Spark AQE's insight applies directly at our L6/L7 interception point:
once a producer stage's map outputs are committed, their per-partition
byte sizes are EXACT (the map-output table, PR 6), while everything the
static planner assumed was an estimate.  The scheduler therefore calls
`AqeRuntime.on_producer_commit` between a stage's map-output commit and
its consumer's dispatch; not-yet-dispatched consumers may be rewritten
by three rules:

- **broadcast switch** — the observed build side of a shuffle-hash join
  fits under the broadcast threshold (plan/advisor.py: ONE threshold
  shared with the advisor findings, so report and rewrite can never
  disagree): the join becomes a broadcast build, the probe's exchange
  is elided outright (its producer never runs; the probe subtree is
  inlined into the consumer).
- **partition coalescing** — adjacent tiny reduce partitions merge up
  to `auron.tpu.aqe.coalesceTargetBytes`, so reducers stop paying
  per-partition dispatch tax.  Applied identically to EVERY reader of
  the consumer (hash co-partitioning puts each key at the same index on
  all sides, so unioning the same groups on both join inputs is exact).
- **skew split** — one partition exceeds `skewFactor x median`: its
  map segments split across N sub-tasks, each joining against the full
  (replicated) build partition; the tiny remainder partitions coalesce
  in the same rewrite (Spark composes OptimizeSkewedJoin with
  CoalesceShufflePartitions the same way).

On top, `seed_plan` is the **history-driven planner**: at bind time the
statstore's per-fingerprint quantiles (PR 16) pre-broadcast
historically-small build sides, shrink partition counts toward the
coalesce target, and pre-select the partial-agg skip strategy — the
second run of a dashboard query plans better than the first even on a
cache miss.

Contracts every rewrite preserves:

- **fingerprints** — a rewritten stage gets a DERIVED fingerprint
  (plan/fingerprint.py derived_fingerprint), so the subplan cache and
  statstore never see the static shape's identity on rewritten output;
- **lineage** — derived reader closures delegate to the scheduler's
  live map-output table, so invalidated outputs still surface as
  FetchFailedError naming the original producer map task and recovery
  re-runs exactly that task;
- **cancellation** — rewritten stages read through IpcReaderExec's
  per-block cancellation checks, unchanged;
- **bit identity** — every rule is a pure re-bucketing of the same
  shuffle segments (or the standard broadcast equivalence for inner
  joins), so results match the static plan exactly.

All rewrites construct the new plan FULLY before committing any
scheduler mutation; a failure mid-evaluation leaves the static plan
untouched.  Disabled (`auron.tpu.aqe.enable`, default off) the whole
module is one lazily-probed boolean — the executed plan is
byte-identical to today.
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("blaze_tpu.aqe")

__all__ = ["enabled", "history_seed_enabled", "reset_conf_probe",
           "seed_plan", "runtime_for", "AqeRuntime"]

_lock = threading.Lock()
_enabled = False
_conf_probed = False  # lazy one-shot auron.tpu.aqe.enable probe


def _probe_conf() -> None:
    global _conf_probed, _enabled
    with _lock:
        if _conf_probed:
            return
        _conf_probed = True
    try:
        from blaze_tpu import config
        if config.AQE_ENABLE.get():
            _enabled = True
    except Exception:
        pass


def enabled() -> bool:
    """One near-free boolean at the stage boundary once probed (the
    statstore.enabled pattern)."""
    if not _conf_probed:
        _probe_conf()
    return _enabled


def reset_conf_probe() -> None:
    """Test helper: forget the probe so the next call re-reads
    `auron.tpu.aqe.enable`."""
    global _conf_probed, _enabled
    with _lock:
        _conf_probed = False
        _enabled = False


def history_seed_enabled() -> bool:
    if not enabled():
        return False
    try:
        from blaze_tpu import config
        return bool(config.AQE_HISTORY_SEED.get())
    except Exception:
        return False


def _coalesce_target() -> int:
    try:
        from blaze_tpu import config
        return max(1, int(config.AQE_COALESCE_TARGET.get()))
    except Exception:
        return 16 << 20


def _skew_max_splits() -> int:
    try:
        from blaze_tpu import config
        return max(2, int(config.AQE_SKEW_MAX_SPLITS.get()))
    except Exception:
        return 8


# -- IR helpers -------------------------------------------------------------


def _walk_nodes(d: Any):
    """Every {"kind": ...} dict node of an IR subtree."""
    stack: List[Any] = [d]
    while stack:
        n = stack.pop()
        if isinstance(n, dict):
            if "kind" in n:
                yield n
            stack.extend(n.values())
        elif isinstance(n, (list, tuple)):
            stack.extend(n)


def _is_stage_reader(d: Any) -> bool:
    return (isinstance(d, dict) and d.get("kind") == "ipc_reader"
            and isinstance(d.get("resource_id"), str)
            and d["resource_id"].startswith("stage://"))


def _rid_sid(rid: str) -> Optional[int]:
    """Producer stage id of a stage:// resource, None for derived rids
    (which embed '#') or anything unparseable."""
    try:
        tail = rid.rsplit("/", 1)[1]
        return int(tail)
    except (IndexError, ValueError):
        return None


def _stage_reader_nodes(plan: Dict[str, Any]) -> List[Dict[str, Any]]:
    """ipc_reader nodes over stage:// exchanges, excluding readers under
    broadcast build sides (those replay every partition per task and
    must keep their original registration)."""
    from blaze_tpu.plan.stages import _broadcast_reader_rids
    excluded = _broadcast_reader_rids(plan)
    return [n for n in _walk_nodes(plan) if _is_stage_reader(n)
            and n["resource_id"] not in excluded]


def _has_scan(plan: Dict[str, Any]) -> bool:
    return any(n.get("kind") in ("parquet_scan", "orc_scan")
               for n in _walk_nodes(plan))


def _rid_refs(stages, rid: str) -> int:
    n = 0
    for st in stages:
        for node in _walk_nodes(st.plan):
            if node.get("kind") == "ipc_reader" \
                    and node.get("resource_id") == rid:
                n += 1
    return n


def _median(values: List[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    if not n:
        return 0.0
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def _stage_base_fp(sched, stage) -> str:
    from blaze_tpu.plan import fingerprint as fp_mod
    part = (sched._part_of(stage) if stage.partitioning is not None
            else None)
    return fp_mod.subplan_fingerprint(stage.plan, part, stage.num_tasks)


# -- runtime re-planning ----------------------------------------------------


def runtime_for(sched) -> Optional["AqeRuntime"]:
    """The scheduler's per-run AQE hook, or None when disabled (the
    disabled path stays one boolean; no object, no state)."""
    if not enabled():
        return None
    return AqeRuntime(sched)


class AqeRuntime:
    """Holds the per-run rewrite state; one instance per staged
    run_collect.  All methods run on the scheduler's driver thread
    between a producer commit and the next dispatch — never
    concurrently with the stage they rewrite."""

    def __init__(self, sched):
        self._sched = sched
        self._rewritten: set = set()  # consumer sids already rewritten

    # -- entry point -------------------------------------------------------

    def on_producer_commit(self, stage, completed: set,
                           stages_by_id: Dict[int, Any]) -> None:
        """Called by the scheduler right after `stage`'s map outputs
        commit.  Never raises: any failure abandons the rewrite and the
        static plan proceeds untouched."""
        try:
            self._on_commit(completed, stages_by_id)
        except Exception:
            log.debug("aqe: rewrite evaluation failed after stage %s",
                      stage.sid, exc_info=True)

    def _on_commit(self, completed: set,
                   stages_by_id: Dict[int, Any]) -> None:
        for c in self._sched.stages:
            if c.sid in completed or c.sid in self._rewritten:
                continue
            if self._try_broadcast(c, completed, stages_by_id):
                continue
            if self._try_skew_split(c, completed):
                continue
            self._try_coalesce(c, completed)

    # -- shared bookkeeping ------------------------------------------------

    def _commit_rewrite(self, c, rule: str, new_plan, num_tasks: int,
                        derived_fp: str, detail: Dict[str, Any]) -> None:
        c.plan = new_plan
        c.num_tasks = int(num_tasks)
        c.aqe = {"rule": rule, "fingerprint": derived_fp, **detail}
        self._rewritten.add(c.sid)
        from blaze_tpu.bridge import tracing
        tracing.instant("aqe_rewrite", stage=c.sid, rule=rule,
                        tasks=c.num_tasks)
        self._sched.aqe_events.append(
            {"rule": rule, "stage": c.sid, "tasks": c.num_tasks,
             "fingerprint": derived_fp, **detail})
        from blaze_tpu.plan import statstore
        if statstore.enabled():
            qid = getattr(self._sched._query, "query_id", None)
            if qid is not None:
                from blaze_tpu.serving import progress
                progress.note_stage_replan(qid, c.sid, c.num_tasks)

    def _register(self, rid: str, closure) -> None:
        from blaze_tpu.bridge.resource import put_resource
        put_resource(rid, closure)
        if rid not in self._sched._resources:
            self._sched._resources.append(rid)

    def _join_with_reader_children(self, plan) -> Optional[Dict[str, Any]]:
        """The unique inner hash_join whose children are both direct
        stage readers — and those readers must be the ONLY exchange
        inputs of the plan (so task count is driven by them alone)."""
        joins = [n for n in _walk_nodes(plan)
                 if n.get("kind") == "hash_join"
                 and n.get("join_type", "inner") == "inner"
                 and _is_stage_reader(n.get("left"))
                 and _is_stage_reader(n.get("right"))]
        if len(joins) != 1 or _has_scan(plan):
            return None
        j = joins[0]
        readers = _stage_reader_nodes(plan)
        if len(readers) != 2:
            return None
        if {r["resource_id"] for r in readers} != \
                {j["left"]["resource_id"], j["right"]["resource_id"]}:
            return None
        return j

    # -- rule 1: join-strategy switch --------------------------------------

    def _try_broadcast(self, c, completed: set,
                       stages_by_id: Dict[int, Any]) -> bool:
        """Observed build side fits under the broadcast threshold while
        the probe producer has NOT run yet: switch to a broadcast build
        and elide the probe's exchange entirely — the probe subtree is
        inlined into the consumer, so its shuffle is never written."""
        sched = self._sched
        join = self._join_with_reader_children(c.plan)
        if join is None:
            return False
        build_key = "right" if join.get("build_side", "right") == "right" \
            else "left"
        probe_key = "left" if build_key == "right" else "right"
        build_sid = _rid_sid(join[build_key]["resource_id"])
        probe_sid = _rid_sid(join[probe_key]["resource_id"])
        if build_sid is None or probe_sid is None:
            return False
        if build_sid not in completed or probe_sid in completed:
            return False
        pstage = stages_by_id.get(probe_sid)
        if pstage is None or pstage.partitioning is None:
            return False
        boundary = sched.stage_boundaries.get(build_sid)
        if not boundary:
            return False
        total = sum(int(b) for b in boundary.get("partition_bytes") or [])
        from blaze_tpu.plan import advisor
        if total > advisor.broadcast_threshold():
            return False
        # eliding the probe producer requires both exchanges to feed
        # ONLY this consumer
        if _rid_refs(sched.stages, join[build_key]["resource_id"]) != 1:
            return False
        if _rid_refs(sched.stages, join[probe_key]["resource_id"]) != 1:
            return False

        derived_fp_base = _stage_base_fp(sched, c)
        new_plan = copy.deepcopy(c.plan)
        njoin = self._join_with_reader_children(new_plan)
        if njoin is None:
            return False
        broadcast_id = f"aqe-bc-{sched._run_id}-{c.sid}"
        njoin["kind"] = "broadcast_join"
        njoin["broadcast_id"] = broadcast_id
        njoin[probe_key] = copy.deepcopy(pstage.plan)

        from blaze_tpu.plan import fingerprint as fp_mod
        dfp = fp_mod.derived_fingerprint(
            derived_fp_base, "broadcast",
            {"build_bytes": int(total), "build": build_sid,
             "probe": probe_sid})
        # estimated bytes saved: the probe shuffle that will never be
        # written (scan-size proxy; sentinel value means unknown -> 0)
        saved = sched._scan_input_bytes(pstage.plan)
        if saved >= (1 << 62):
            saved = 0
        self._commit_rewrite(
            c, "broadcast", new_plan, pstage.num_tasks, dfp,
            {"build_bytes": int(total), "broadcast_id": broadcast_id,
             "elided_stage": probe_sid})
        completed.add(probe_sid)
        sched.stage_placement[probe_sid] = {"compute": "elided",
                                            "exchange": "elided"}
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_aqe(rewrites=1, broadcast_switches=1,
                           stages_elided=1, bytes_saved=int(saved))
        return True

    # -- rule 3: skew split (+ composed coalesce of the remainder) ---------

    def _try_skew_split(self, c, completed: set) -> bool:
        sched = self._sched
        join = self._join_with_reader_children(c.plan)
        if join is None:
            return False
        build_key = "right" if join.get("build_side", "right") == "right" \
            else "left"
        probe_key = "left" if build_key == "right" else "right"
        build, probe = join[build_key], join[probe_key]
        build_sid = _rid_sid(build["resource_id"])
        probe_sid = _rid_sid(probe["resource_id"])
        if build_sid is None or probe_sid is None:
            return False
        if build_sid not in completed or probe_sid not in completed:
            return False
        pb = sched.stage_boundaries.get(probe_sid)
        if not pb or not sched.stage_boundaries.get(build_sid):
            return False
        part_bytes = [int(b) for b in pb.get("partition_bytes") or []]
        n_out = len(part_bytes)
        if n_out < 2 or n_out != int(probe.get("num_partitions", 1)) \
                or n_out != int(build.get("num_partitions", 1)) \
                or c.num_tasks != n_out:
            return False
        med = _median([float(b) for b in part_bytes])
        from blaze_tpu.plan import advisor
        factor = advisor.skew_factor()
        if med <= 0:
            return False
        hot = max(range(n_out), key=lambda i: (part_bytes[i], -i))
        if part_bytes[hot] < factor * med:
            return False
        # splitting needs the probe's per-map file segments
        outputs = sched._stage_outputs.get(probe_sid)
        if not outputs:
            return False  # device/RSS/cached tier: no local segments
        from blaze_tpu.bridge.resource import get_resource
        if not callable(get_resource(probe["resource_id"])) \
                or not callable(get_resource(build["resource_id"])):
            return False
        maps: List[Tuple[int, int]] = []
        for m in sorted(outputs):
            entry = outputs[m]
            if entry is None:
                return False  # mid-invalidation: recovery first
            _data, off = entry
            ln = int(off[hot + 1] - off[hot])
            if ln:
                maps.append((m, ln))
        n_split = min(_skew_max_splits(), len(maps))
        if n_split < 2:
            return False
        # contiguous map-id chunks, balanced by segment bytes: each map
        # goes to the chunk its cumulative start offset falls into, so
        # near-equal segments split evenly and one dominant segment
        # still leaves the rest in their own chunk
        total_hot = sum(ln for _m, ln in maps)
        buckets: List[List[int]] = [[] for _ in range(n_split)]
        acc = 0
        for m, ln in maps:
            j = min(n_split - 1, acc * n_split // total_hot)
            buckets[j].append(m)
            acc += ln
        chunks = [b for b in buckets if b]
        if len(chunks) < 2:
            return False
        # composed task spec: the hot partition's chunks in place, the
        # rest coalesced toward the target (Spark's skew+coalesce pair)
        target_b = _coalesce_target()
        spec: List[tuple] = []
        group: List[int] = []
        gacc = 0

        def flush():
            nonlocal group, gacc
            if group:
                spec.append(("parts", group))
                group, gacc = [], 0

        for q in range(n_out):
            if q == hot:
                flush()
                for chunk in chunks:
                    spec.append(("maps", hot, chunk))
                continue
            if group and gacc + part_bytes[q] > target_b:
                flush()
            group.append(q)
            gacc += part_bytes[q]
        flush()
        new_n = len(spec)
        coalesced = (n_out - 1) - sum(1 for e in spec if e[0] == "parts")

        probe_rid, build_rid = probe["resource_id"], build["resource_id"]
        new_probe_rid = f"{probe_rid}#aqe-s{c.sid}"
        new_build_rid = f"{build_rid}#aqe-s{c.sid}"

        def probe_blocks(reduce_id: int, _spec=spec, _rid=probe_rid,
                         _sid=probe_sid, _sched=sched):
            from blaze_tpu.bridge.resource import get_resource as _get
            from blaze_tpu.faults import FetchFailedError
            from blaze_tpu.shuffle.reader import FileSegmentBlock
            entry = _spec[reduce_id]
            if entry[0] == "parts":
                src = _get(_rid)
                if src is None:
                    raise KeyError(f"shuffle resource {_rid!r} not found")
                for q in entry[1]:
                    for blk in src(q):
                        yield blk
                return
            _kind, hot_p, map_ids = entry
            # live read of the map-output table: a recovered map task's
            # fresh output is what this sub-task fetches
            outs = _sched._stage_outputs.get(_sid) or {}
            for m in map_ids:
                e = outs.get(m)
                if e is None:
                    raise FetchFailedError(
                        _sid, m, "map output invalidated after worker "
                                 "crash")
                data, off = e
                ln = int(off[hot_p + 1] - off[hot_p])
                if ln:
                    yield FileSegmentBlock(data, int(off[hot_p]), ln,
                                           stage_id=_sid, map_id=m)

        def build_blocks(reduce_id: int, _spec=spec, _rid=build_rid):
            from blaze_tpu.bridge.resource import get_resource as _get
            src = _get(_rid)
            if src is None:
                raise KeyError(f"shuffle resource {_rid!r} not found")
            entry = _spec[reduce_id]
            parts = entry[1] if entry[0] == "parts" else [entry[1]]
            for q in parts:
                for blk in src(q):
                    yield blk

        derived_fp_base = _stage_base_fp(sched, c)
        new_plan = copy.deepcopy(c.plan)
        njoin = self._join_with_reader_children(new_plan)
        if njoin is None:
            return False
        njoin[probe_key]["resource_id"] = new_probe_rid
        njoin[probe_key]["num_partitions"] = new_n
        njoin[build_key]["resource_id"] = new_build_rid
        njoin[build_key]["num_partitions"] = new_n

        from blaze_tpu.plan import fingerprint as fp_mod
        dfp = fp_mod.derived_fingerprint(
            derived_fp_base, "skew_split",
            {"hot": hot, "splits": len(chunks), "partitions": n_out,
             "tasks": new_n})
        self._register(new_probe_rid, probe_blocks)
        self._register(new_build_rid, build_blocks)
        self._commit_rewrite(
            c, "skew_split", new_plan, new_n, dfp,
            {"hot_partition": hot, "hot_bytes": part_bytes[hot],
             "median_bytes": med, "splits": len(chunks),
             "partitions": n_out})
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_aqe(rewrites=1, skew_splits=1,
                           partitions_coalesced=max(0, coalesced))
        return True

    # -- rule 2: partition coalescing --------------------------------------

    def _try_coalesce(self, c, completed: set) -> bool:
        """Merge adjacent tiny reduce partitions up to the target size.
        The SAME grouping applies to every reader of the consumer —
        co-partitioned inputs (both join sides) stay aligned because
        hash partitioning puts a key at the same index on all sides."""
        sched = self._sched
        if _has_scan(c.plan):
            return False
        readers = _stage_reader_nodes(c.plan)
        if not readers:
            return False
        n_out: Optional[int] = None
        prods: set = set()
        for r in readers:
            sid = _rid_sid(r["resource_id"])
            if sid is None:
                return False
            np_ = int(r.get("num_partitions", 1))
            if n_out is None:
                n_out = np_
            elif np_ != n_out:
                return False
            prods.add(sid)
        if not n_out or n_out < 2 or c.num_tasks != n_out:
            return False
        if any(p not in completed for p in prods):
            return False
        from blaze_tpu.bridge.resource import get_resource
        per_part = [0] * n_out
        for p in prods:
            b = sched.stage_boundaries.get(p)
            if not b:
                return False
            pb = b.get("partition_bytes") or []
            if len(pb) != n_out:
                return False
            for i, v in enumerate(pb):
                per_part[i] += int(v)
        for r in readers:
            if not callable(get_resource(r["resource_id"])):
                return False
        target = _coalesce_target()
        groups: List[List[int]] = []
        cur: List[int] = []
        acc = 0
        for q in range(n_out):
            if cur and acc + per_part[q] > target:
                groups.append(cur)
                cur, acc = [], 0
            cur.append(q)
            acc += per_part[q]
        if cur:
            groups.append(cur)
        if len(groups) >= n_out:
            return False

        rid_map: Dict[str, str] = {}
        closures: Dict[str, Any] = {}
        for r in readers:
            rid = r["resource_id"]
            if rid in rid_map:
                continue
            new_rid = f"{rid}#aqe-c{c.sid}"

            def blocks_for(reduce_id: int, _rid=rid, _groups=groups):
                from blaze_tpu.bridge.resource import get_resource as _get
                src = _get(_rid)
                if src is None:
                    raise KeyError(f"shuffle resource {_rid!r} not found")
                for q in _groups[reduce_id]:
                    for blk in src(q):
                        yield blk

            rid_map[rid] = new_rid
            closures[new_rid] = blocks_for

        derived_fp_base = _stage_base_fp(sched, c)
        new_plan = copy.deepcopy(c.plan)
        for r in _stage_reader_nodes(new_plan):
            r["num_partitions"] = len(groups)
            r["resource_id"] = rid_map[r["resource_id"]]

        from blaze_tpu.plan import fingerprint as fp_mod
        dfp = fp_mod.derived_fingerprint(
            derived_fp_base, "coalesce",
            {"partitions": n_out, "groups": [list(g) for g in groups]})
        for new_rid, closure in closures.items():
            self._register(new_rid, closure)
        self._commit_rewrite(
            c, "coalesce", new_plan, len(groups), dfp,
            {"partitions": n_out, "groups": len(groups)})
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_aqe(rewrites=1,
                           partitions_coalesced=n_out - len(groups))
        return True


# -- history-driven planning (bind time) ------------------------------------


def _exchange_sfp(ex: Dict[str, Any]) -> Optional[str]:
    """The subplan fingerprint this exchange's producer stage records
    into the statstore — computable at bind time only for LEAF subtrees
    (a nested exchange becomes a run-scoped stage:// reader after the
    split, so non-leaf identities never match across runs)."""
    child = ex.get("input")
    if not isinstance(child, dict):
        return None
    if any(n.get("kind") == "local_exchange" for n in _walk_nodes(child)):
        return None
    part = dict(ex.get("partitioning") or {})
    if part.get("kind") == "single":
        part = {"kind": "single", "num_partitions": 1}
    try:
        from blaze_tpu.plan import create_plan
        n_tasks = max(1, create_plan(child).num_partitions)
    except Exception:
        return None
    from blaze_tpu.plan import fingerprint as fp_mod
    return fp_mod.subplan_fingerprint(child, part, n_tasks)


def _desired_partitions(prior: Dict[str, Any], sfp: str,
                        n_out: int) -> Optional[int]:
    """History-implied partition count: enough partitions of
    coalesceTargetBytes each to hold the boundary's p50 total bytes.
    Shrink-only — history never raises parallelism above the static
    plan."""
    from blaze_tpu.plan import statstore
    st = (prior.get("stages") or {}).get(sfp)
    if not st:
        return None
    p50 = statstore.sketch_quantile(st.get("total_bytes") or {}, 0.5)
    if p50 is None or p50 <= 0:
        return None
    new_n = max(1, -(-int(p50) // _coalesce_target()))
    return new_n if new_n < n_out else None


def _agg_skip_ratio() -> float:
    try:
        from blaze_tpu import config
        return float(config.PARTIAL_AGG_SKIPPING_RATIO.get())
    except Exception:
        return 0.8


def seed_plan(plan: Dict[str, Any], sched=None) -> Dict[str, Any]:
    """Bind-time history seeding: returns the (deep-copied) rewritten
    plan, or `plan` unchanged when seeding is off, no prior exists, or
    anything at all goes wrong — a corrupted or empty statstore always
    falls back to static planning with zero errors."""
    if not history_seed_enabled():
        return plan
    try:
        return _seed_plan(plan, sched)
    except Exception:
        log.debug("aqe: history seeding failed; static plan kept",
                  exc_info=True)
        return plan


def _seed_plan(plan: Dict[str, Any], sched) -> Dict[str, Any]:
    from blaze_tpu.plan import advisor, statstore
    from blaze_tpu.plan import fingerprint as fp_mod
    if not statstore.enabled():
        return plan
    prior = statstore.prior(fp_mod.plan_fingerprint(plan))
    if not prior:
        return plan
    by_fp: Dict[str, Dict[str, dict]] = {}
    for rec in advisor.recommendations(prior):
        by_fp.setdefault(rec["fingerprint"], {})[rec["rule"]] = rec

    seeds: List[dict] = []
    new_plan = copy.deepcopy(plan)

    # 1) pre-broadcast historically-small build sides: splice out BOTH
    # exchanges of the join (broadcast needs no co-partitioning)
    for node in _walk_nodes(new_plan):
        if node.get("kind") != "hash_join" \
                or node.get("join_type", "inner") != "inner":
            continue
        build_key = "right" if node.get("build_side", "right") == "right" \
            else "left"
        probe_key = "left" if build_key == "right" else "right"
        build = node.get(build_key)
        if not isinstance(build, dict) \
                or build.get("kind") != "local_exchange":
            continue
        sfp = _exchange_sfp(build)
        rec = by_fp.get(sfp, {}).get("broadcast") if sfp else None
        if rec is None:
            continue
        dfp = fp_mod.derived_fingerprint(sfp, "seed_broadcast",
                                         {"threshold": rec["threshold"]})
        node["kind"] = "broadcast_join"
        node["broadcast_id"] = f"aqe-seed-{dfp[:16]}"
        node[build_key] = build["input"]
        probe = node.get(probe_key)
        if isinstance(probe, dict) and probe.get("kind") == "local_exchange":
            node[probe_key] = probe["input"]
        seeds.append({"rule": "seed_broadcast", "fingerprint": dfp,
                      "evidence": dict(rec["evidence"])})

    # 2) shrink partition counts toward the coalesce target.  Join
    # children must stay co-partitioned: both sides move to ONE unified
    # count (the max of the sides' desires keeps the most parallelism).
    handled: set = set()
    for node in _walk_nodes(new_plan):
        if node.get("kind") not in ("hash_join", "sort_merge_join"):
            continue
        left, right = node.get("left"), node.get("right")
        if not (isinstance(left, dict)
                and left.get("kind") == "local_exchange"
                and isinstance(right, dict)
                and right.get("kind") == "local_exchange"):
            continue
        handled.add(id(left))
        handled.add(id(right))
        desires = []
        for side in (left, right):
            part = side.get("partitioning") or {}
            if part.get("kind") != "hash":
                desires = []
                break
            n_out = int(part.get("num_partitions", 1))
            sfp = _exchange_sfp(side)
            if sfp is None or "skew_split" in by_fp.get(sfp, {}):
                continue  # keep partitions for the runtime skew rule
            want = _desired_partitions(prior, sfp, n_out)
            if want is not None:
                desires.append(want)
        if not desires:
            continue
        unified = max(desires)
        for side in (left, right):
            n_out = int(side["partitioning"].get("num_partitions", 1))
            if unified < n_out:
                side["partitioning"]["num_partitions"] = unified
                seeds.append({"rule": "seed_partitions",
                              "from": n_out, "to": unified})
    for node in _walk_nodes(new_plan):
        if node.get("kind") != "local_exchange" or id(node) in handled:
            continue
        part = node.get("partitioning") or {}
        if part.get("kind") != "hash":
            continue
        n_out = int(part.get("num_partitions", 1))
        sfp = _exchange_sfp(node)
        if sfp is None or "skew_split" in by_fp.get(sfp, {}):
            continue
        want = _desired_partitions(prior, sfp, n_out)
        if want is not None:
            node["partitioning"]["num_partitions"] = want
            seeds.append({"rule": "seed_partitions",
                          "from": n_out, "to": want})

    # 3) pre-select the partial-agg skip strategy when history already
    # shows the grouping barely reduces (the probe would decide the
    # same thing — this just skips the probe's buffering warm-up).
    # `supports_partial_skipping` survives the protobuf round trip and
    # the planner threads it to AggExec as skip_partial_hint.
    ratio = (prior.get("derived") or {}).get("agg_probe_ratio")
    if ratio is not None and float(ratio) >= _agg_skip_ratio():
        for node in _walk_nodes(new_plan):
            if node.get("kind") != "hash_agg" or not node.get("groupings"):
                continue
            modes = [a.get("mode", "partial")
                     for a in node.get("aggs") or []]
            if modes and all(m == "partial" for m in modes) \
                    and not node.get("supports_partial_skipping"):
                node["supports_partial_skipping"] = True
                seeds.append({"rule": "seed_agg_skip",
                              "ratio": float(ratio)})

    if not seeds:
        return plan
    from blaze_tpu.bridge import tracing, xla_stats
    xla_stats.note_aqe(history_seeds=len(seeds))
    tracing.instant("aqe_history_seed", seeds=len(seeds))
    if sched is not None:
        for s in seeds:
            sched.aqe_events.append({"stage": None, **s})
    return new_plan
