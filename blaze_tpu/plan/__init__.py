"""Plan IR + serde + planner (ref: native-engine/auron-planner).

The IR is JSON-friendly nested dicts mirroring the reference proto's
PhysicalPlanNode/PhysicalExprNode oneofs — see planner.py and exprs.py for
the kind lists and proto line citations.
"""

from blaze_tpu.plan.explain import QueryProfile, explain_analyze
from blaze_tpu.plan.exprs import expr_from_dict, sort_spec_from_dict
from blaze_tpu.plan.planner import (CoalesceBatchesExec, create_plan,
                                    decode_task_definition,
                                    partitioning_from_dict, plan_from_json,
                                    plan_to_json)
from blaze_tpu.plan.types import (field_from_dict, field_to_dict,
                                  schema_from_dict, schema_to_dict,
                                  type_from_dict, type_to_dict)

__all__ = [
    "QueryProfile", "explain_analyze",
    "expr_from_dict", "sort_spec_from_dict",
    "CoalesceBatchesExec", "create_plan", "decode_task_definition",
    "partitioning_from_dict", "plan_from_json", "plan_to_json",
    "field_from_dict", "field_to_dict", "schema_from_dict",
    "schema_to_dict", "type_from_dict", "type_to_dict",
]
