"""Physical planner: plan IR dicts -> operator trees.

Parity: PhysicalPlanner::create_plan (ref auron-planner/src/planner.rs:
122-922) pattern-matching the PhysicalPlanNode oneof (28 operators,
auron.proto:27-56), parse_protobuf_partitioning (planner.rs:1201) and
TaskDefinition decoding (auron.proto:814, rt.rs:79-90).

Node kinds: parquet_scan, memory_scan, filter, project, filter_project,
sort, limit, union, rename_columns, expand, empty_partitions, debug,
hash_agg, sort_agg, sort_merge_join, hash_join, broadcast_join, window,
generate, shuffle_writer, rss_shuffle_writer, ipc_reader, ipc_writer,
ffi_reader, coalesce_batches, parquet_sink.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from blaze_tpu.ops import (AggExec, DebugExec, EmptyPartitionsExec,
                           ExpandExec, FilterExec, FilterProjectExec,
                           GenerateExec, LimitExec, MemoryScanExec,
                           ParquetScanExec, ProjectExec, RenameColumnsExec,
                           SortExec, UnionExec, WindowExec)
from blaze_tpu.ops.agg import AggExecMode, AggMode, make_agg
from blaze_tpu.ops.agg.exec import AggExec as _AggExec
from blaze_tpu.ops.base import CoalesceStream, ExecutionPlan
from blaze_tpu.ops.generate import (ExplodeGenerator, JsonTupleGenerator,
                                    UDTFGenerator)
from blaze_tpu.ops.joins import (BroadcastJoinExec, JoinType,
                                 ShuffledHashJoinExec, SortMergeJoinExec)
from blaze_tpu.ops.window import (LeadLagFunc, NthValueFunc, RankFunc,
                                  WindowAggFunc, WindowRankType)
from blaze_tpu.plan.exprs import expr_from_dict, sort_spec_from_dict
from blaze_tpu.plan.types import schema_from_dict
from blaze_tpu.schema import Schema
from blaze_tpu.shuffle import (FFIReaderExec, HashPartitioning, IpcReaderExec,
                               IpcWriterExec, LocalShuffleExchange,
                               Partitioning, RangePartitioning,
                               RoundRobinPartitioning, RssShuffleWriterExec,
                               ShuffleWriterExec, SinglePartitioning)


class CoalesceBatchesExec(ExecutionPlan):
    """Explicit re-batching node (ref CoalesceStream auto-wrap,
    rt.rs:160-166; also a plan-addressable node for parity)."""

    def __init__(self, child: ExecutionPlan, batch_size: Optional[int] = None):
        super().__init__([child])
        self._batch_size = batch_size

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int):
        return iter(CoalesceStream(self.children[0].execute(partition),
                                   self._batch_size, metrics=self.metrics))


def create_plan(d: Dict[str, Any]) -> ExecutionPlan:
    """Decode one plan node (and recursively its children)."""
    k = d["kind"]

    if k == "parquet_scan":
        schema = schema_from_dict(d["schema"])
        pred = (expr_from_dict(d["predicate"], schema)
                if d.get("predicate") else None)
        pschema = (schema_from_dict(d["partition_schema"])
                   if d.get("partition_schema") else None)
        return ParquetScanExec(schema, d["file_groups"],
                               projection=d.get("projection"),
                               predicate=pred,
                               partition_schema=pschema,
                               partition_values=d.get("partition_values"))
    if k == "memory_scan":
        import pyarrow as pa
        schema = schema_from_dict(d["schema"])
        from blaze_tpu.bridge.resource import get_resource
        table = get_resource(d["resource_id"])
        if table is None:
            raise KeyError(f"memory_scan resource {d['resource_id']!r}")
        return MemoryScanExec.from_arrow(table,
                                         d.get("num_partitions", 1))
    if k == "ipc_reader":
        return IpcReaderExec(d["resource_id"], schema_from_dict(d["schema"]),
                             d.get("num_partitions", 1))
    if k == "ffi_reader":
        return FFIReaderExec(d["resource_id"], schema_from_dict(d["schema"]),
                             d.get("num_partitions", 1))
    if k == "empty_partitions":
        return EmptyPartitionsExec(schema_from_dict(d["schema"]),
                                   d.get("num_partitions", 1))
    if k == "orc_scan":
        from blaze_tpu.ops.orc import OrcScanExec
        opschema = (schema_from_dict(d["partition_schema"])
                    if d.get("partition_schema") else None)
        return OrcScanExec(schema_from_dict(d["schema"]), d["file_groups"],
                           projection=d.get("projection"),
                           partition_schema=opschema,
                           partition_values=d.get("partition_values"))
    if k == "kafka_scan":
        return _create_kafka_scan(d)

    child = create_plan(d["input"]) if "input" in d else None
    in_schema = child.schema if child is not None else None

    if k == "filter":
        preds = [expr_from_dict(p, in_schema) for p in d["predicates"]]
        return FilterExec(child, preds)
    if k == "project":
        exprs = [expr_from_dict(e, in_schema) for e in d["exprs"]]
        return ProjectExec(child, exprs, d["names"])
    if k == "filter_project":
        preds = [expr_from_dict(p, in_schema) for p in d["predicates"]]
        exprs = [expr_from_dict(e, in_schema) for e in d["exprs"]]
        return FilterProjectExec(child, preds, exprs, d["names"])
    if k == "sort":
        specs = [sort_spec_from_dict(s, in_schema) for s in d["specs"]]
        return SortExec(child, specs, fetch=d.get("fetch"))
    if k == "limit":
        return LimitExec(child, d["limit"], offset=d.get("offset", 0))
    if k == "union":
        children = [create_plan(c) for c in d["inputs"]]
        return UnionExec(children)
    if k == "rename_columns":
        return RenameColumnsExec(child, d["names"])
    if k == "expand":
        projections = [[expr_from_dict(e, in_schema) for e in proj]
                       for proj in d["projections"]]
        return ExpandExec(child, projections, d["names"])
    if k == "debug":
        return DebugExec(child, d.get("tag", "debug"))
    if k == "coalesce_batches":
        return CoalesceBatchesExec(child, d.get("batch_size"))

    if k in ("hash_agg", "sort_agg"):
        groups = [(expr_from_dict(g["expr"], in_schema), g["name"])
                  for g in d.get("groupings", [])]
        aggs = []
        for a in d.get("aggs", []):
            children = [expr_from_dict(c, in_schema)
                        for c in a.get("args", [])]
            fn = make_agg(a["fn"], children, **a.get("options", {}))
            aggs.append((fn, AggMode(a.get("mode", "partial")), a["name"]))
        mode = (AggExecMode.HASH_AGG if k == "hash_agg"
                else AggExecMode.SORT_AGG)
        return AggExec(child, groups, aggs, mode,
                       skip_partial_hint=bool(
                           d.get("supports_partial_skipping")))

    if k == "broadcast_nested_loop_join":
        from blaze_tpu.ops.joins.bnlj import BroadcastNestedLoopJoinExec
        left = create_plan(d["left"])
        right = create_plan(d["right"])
        flt = (expr_from_dict(d["join_filter"])
               if d.get("join_filter") else None)
        return BroadcastNestedLoopJoinExec(
            left, right, JoinType(d.get("join_type", "inner")),
            build_side=d.get("build_side", "right"), join_filter=flt,
            broadcast_id=d.get("broadcast_id"))

    if k == "broadcast_join_build_hash_map":
        from blaze_tpu.ops.joins.exec import BuildHashMapExec
        keys = [expr_from_dict(e, in_schema) for e in d["keys"]]
        return BuildHashMapExec(child, keys)

    if k in ("sort_merge_join", "hash_join", "broadcast_join"):
        left = create_plan(d["left"])
        right = create_plan(d["right"])
        lkeys = [expr_from_dict(e, left.schema) for e in d["left_keys"]]
        rkeys = [expr_from_dict(e, right.schema) for e in d["right_keys"]]
        jt = JoinType(d.get("join_type", "inner"))
        flt = None
        if d.get("join_filter"):
            flt = expr_from_dict(d["join_filter"])  # bound on joined schema
        cls = {"sort_merge_join": SortMergeJoinExec,
               "hash_join": ShuffledHashJoinExec,
               "broadcast_join": BroadcastJoinExec}[k]
        kw = dict(build_side=d.get("build_side", "right"), join_filter=flt,
                  null_aware_anti=d.get("null_aware_anti", False))
        if k == "broadcast_join" and d.get("broadcast_id"):
            kw["broadcast_id"] = d["broadcast_id"]
            # a build-map stage on the broadcast side shares its map with
            # this join through the cache id (ref cached_build_hash_map_id,
            # broadcast_join_build_hash_map_exec.rs)
            from blaze_tpu.ops.joins.exec import BuildHashMapExec
            build = right if d.get("build_side", "right") == "right" else left
            if isinstance(build, BuildHashMapExec):
                build.cache_id = d["broadcast_id"]
        return cls(left, right, lkeys, rkeys, jt, **kw)

    if k == "window":
        funcs = []
        for w in d["functions"]:
            wk = w["kind"]
            if wk in [t.value for t in WindowRankType]:
                funcs.append(RankFunc(w["name"], WindowRankType(wk)))
            elif wk in ("lead", "lag"):
                off = w.get("offset", 1)
                funcs.append(LeadLagFunc(
                    w["name"], expr_from_dict(w["expr"], in_schema),
                    off if wk == "lead" else -off, w.get("default")))
            elif wk == "nth_value":
                funcs.append(NthValueFunc(
                    w["name"], expr_from_dict(w["expr"], in_schema),
                    w.get("n", 1),
                    ignore_nulls=w.get("ignore_nulls", False)))
            elif wk == "agg":
                children = [expr_from_dict(c, in_schema)
                            for c in w.get("args", [])]
                funcs.append(WindowAggFunc(
                    w["name"], make_agg(w["fn"], children),
                    running=w.get("running", True)))
            else:
                raise ValueError(f"unknown window function kind {wk!r}")
        part = [expr_from_dict(e, in_schema)
                for e in d.get("partition_by", [])]
        order = [sort_spec_from_dict(s, in_schema)
                 for s in d.get("order_by", [])]
        return WindowExec(child, funcs, part, order,
                          group_limit=d.get("group_limit"))

    if k == "generate":
        g = d["generator"]
        gk = g["kind"]
        if gk in ("explode", "posexplode"):
            gen = ExplodeGenerator(expr_from_dict(g["child"], in_schema),
                                   position=(gk == "posexplode"),
                                   outer=g.get("outer", False))
        elif gk == "json_tuple":
            gen = JsonTupleGenerator(expr_from_dict(g["child"], in_schema),
                                     g["fields"])
        elif gk == "udtf":
            from blaze_tpu.bridge.resource import get_resource
            from blaze_tpu.plan.types import field_from_dict
            fn = get_resource(f"udtf://{g['name']}")
            gen = UDTFGenerator(
                args=[expr_from_dict(a, in_schema)
                      for a in g.get("args", [])],
                fn=fn, fields=[field_from_dict(f) for f in g["fields"]])
        else:
            raise ValueError(f"unknown generator kind {gk!r}")
        required = d.get("required_cols")
        if required is None and d.get("required_child_output") is not None:
            required = [in_schema.index_of(nm)
                        for nm in d["required_child_output"]]
        return GenerateExec(child, gen, required,
                            outer=g.get("outer", False))

    if k == "shuffle_writer":
        part = partitioning_from_dict(d["partitioning"], in_schema)
        return ShuffleWriterExec(child, part, d["data_file"], d["index_file"])
    if k == "rss_shuffle_writer":
        from blaze_tpu.bridge.resource import get_resource
        part = partitioning_from_dict(d["partitioning"], in_schema)
        writer = get_resource(d["rss_resource_id"])
        return RssShuffleWriterExec(child, part, writer)
    if k == "local_exchange":
        part = partitioning_from_dict(d["partitioning"], in_schema)
        return LocalShuffleExchange(child, part,
                                    stage_id=d.get("stage_id", 0))
    if k == "ipc_writer":
        from blaze_tpu.bridge.resource import get_resource
        sink = get_resource(d["sink_resource_id"])
        return IpcWriterExec(child, sink)
    if k == "parquet_sink":
        from blaze_tpu.ops.sink import ParquetSinkExec
        return ParquetSinkExec(child, _sink_path(d),
                               partition_cols=d.get("partition_cols"))
    if k == "orc_sink":
        from blaze_tpu.ops.sink import OrcSinkExec
        return OrcSinkExec(child, _sink_path(d))

    raise ValueError(f"unknown plan node kind {k!r}")


def collapse_filter_project(node: ExecutionPlan) -> ExecutionPlan:
    """Planner rewrite: merge adjacent Filter->Project chains into one
    `FilterProjectExec`, and Project->Project into a single Project by
    substituting the inner projections into the outer's bound references
    — so the whole-stage expression compiler (exprs/program.py) traces
    the full chain as ONE XLA program instead of one per operator.

    Runs before prune_columns/fuse_plan in the runtime rewrite chains
    (both passes already understand FilterProjectExec).  Stateful inner
    expressions are never substituted (duplication would re-evaluate
    them); collapse simply stops at those nodes."""
    from blaze_tpu import config
    if not config.COLLAPSE_FILTER_PROJECT.get():
        return node
    return _collapse(node)


def _collapse(node: ExecutionPlan) -> ExecutionPlan:
    kids = node.children
    for i, c in enumerate(kids):
        kids[i] = _collapse(c)
    if isinstance(node, ProjectExec):
        child = node.children[0]
        if isinstance(child, FilterExec):
            return FilterProjectExec(child.children[0], child._predicates,
                                     node._exprs, node._names)
        if isinstance(child, ProjectExec):
            merged = _substitute_all(node._exprs, child._exprs)
            if merged is not None:
                return ProjectExec(child.children[0], merged, node._names)
    return node


#: Pure expression classes safe to duplicate/re-evaluate when an inner
#: projection substitutes into several outer references.  Stateful or
#: context-reading exprs (Rand, RowNum, UDFs, subqueries, scalar
#: functions...) are deliberately absent: substitution bails.
def _pure(e) -> bool:
    from blaze_tpu.exprs import (BinaryExpr, BoundReference, CaseWhen, Cast,
                                 Coalesce, If, InList, IsNotNull, IsNull,
                                 Like, Literal, Not, RLike, StringPredicate)
    ok = (BoundReference, Literal, BinaryExpr, Not, IsNull, IsNotNull, If,
          CaseWhen, Coalesce, InList, Cast, Like, RLike, StringPredicate)
    return isinstance(e, ok) and all(_pure(c) for c in e.children())


def _substitute_all(outer, inner):
    """outer exprs rewritten over inner's input, or None to bail."""
    if not all(_pure(e) for e in inner):
        return None
    from blaze_tpu.exprs.fold import map_exprs
    from blaze_tpu.exprs import BoundReference

    def subst(e):
        if isinstance(e, BoundReference):
            return inner[e.index]
        return map_exprs(e, subst)

    try:
        return [subst(e) for e in outer]
    except (TypeError, IndexError):
        return None


def _sink_path(d: Dict[str, Any]) -> str:
    """Sinks address their output through either a direct path or a
    host-registered FS resource (ref NativeParquetSinkUtils via the JVM
    resource map, jni_bridge.rs:452-453)."""
    if d.get("path"):
        return d["path"]
    rid = d.get("fs_resource_id", "")
    from blaze_tpu.bridge.resource import get_resource
    resolved = get_resource(rid)
    return resolved if resolved is not None else rid


def _create_kafka_scan(d: Dict[str, Any]) -> ExecutionPlan:
    """(ref flink/kafka_scan_exec.rs:81 + kafka_mock_scan_exec.rs)"""
    import json as _json
    from blaze_tpu.ops.kafka import (JsonDeserializer, KafkaRecord,
                                     KafkaScanExec, MockKafkaScanExec,
                                     PbDeserializer)
    schema = schema_from_dict(d["schema"])
    fmt = d.get("format", "json")
    if fmt == "json":
        deser = JsonDeserializer(schema)
    elif fmt == "protobuf":
        cfg = _json.loads(d.get("format_config_json") or "{}")
        deser = PbDeserializer(schema, cfg)
    else:
        raise ValueError(f"unknown kafka format {fmt!r}")
    ts_field = d.get("event_time_field")
    mock = d.get("mock_data_json_array")
    if mock:
        rows = _json.loads(mock)
        recs = [KafkaRecord(value=_json.dumps(r).encode("utf-8"), offset=i)
                for i, r in enumerate(rows)]
        return MockKafkaScanExec(schema, deser, [recs],
                                 event_time_field=ts_field)
    source = d.get("operator_id") or d.get("topic")
    return KafkaScanExec(schema, deser, f"kafka://{source}",
                         d.get("num_partitions", 1),
                         event_time_field=ts_field)


def partitioning_from_dict(d: Dict[str, Any],
                           schema: Optional[Schema]) -> Partitioning:
    """(ref parse_protobuf_partitioning, planner.rs:1201)"""
    k = d["kind"]
    if k == "hash":
        exprs = [expr_from_dict(e, schema) for e in d["exprs"]]
        return HashPartitioning(exprs, d["num_partitions"])
    if k == "round_robin":
        return RoundRobinPartitioning(d["num_partitions"])
    if k == "single":
        return SinglePartitioning()
    if k == "range":
        import base64
        import io
        import pyarrow as pa
        specs = [sort_spec_from_dict(s, schema) for s in d["specs"]]
        with pa.ipc.open_stream(io.BytesIO(
                base64.b64decode(d["bounds_ipc"]))) as r:
            bounds = next(iter(r))
        return RangePartitioning(specs, d["num_partitions"], bounds)
    raise ValueError(f"unknown partitioning kind {k!r}")


# fixed-width row schemas the mesh exchange can carry: each column
# travels as one jnp array + one bool validity lane.  date32 rides as
# int32 and timestamp_us as int64 — the murmur3 pid of the underlying
# integer is identical either way (partitioning.py hashes them through
# the same mode), so re-tagging at the arrow boundary is lossless.
_DEVICE_EXCHANGE_TIDS = frozenset((
    "bool", "int8", "int16", "int32", "int64", "float32", "float64",
    "date32", "timestamp_us"))


def _note_exchange_type_eviction(tid) -> None:
    """An exchange boundary just stayed on the host file shuffle because
    of a column TYPE (not mode/keys): account the reason so the advisor
    and bench placement reports show what actually evicted it."""
    from blaze_tpu.bridge import xla_stats
    if tid in ("utf8", "binary"):
        xla_stats.note_encoding(host_evictions_string=1)
    elif tid == "decimal":
        xla_stats.note_encoding(host_evictions_decimal=1)
    else:
        xla_stats.note_encoding(host_evictions_other=1)


def exchange_device_spec(partitioning: Optional[Dict[str, Any]],
                         out_schema: Optional[Dict[str, Any]]
                         ) -> Optional[Dict[str, Any]]:
    """Tentpole planner pass: decide whether one exchange boundary can
    go device-resident, i.e. ride the mesh collective instead of the
    host file shuffle.  Returns {'key_indices', 'num_partitions'} when
    BOTH sides of the boundary are mesh-shardable:

      map side    every output column fixed-width (no strings/decimals/
                  nested — those still need the host row format) so the
                  whole row set shards as flat device arrays;
      reduce side the hash keys are direct column references, so the
                  Spark-compatible pid is computable on device with the
                  ONE shared hash definition (H.spark_partition_ids).

    `auron.tpu.shuffle.device`: off -> never; on -> whenever eligible;
    auto (default) -> eligible AND compute is device-resident (bridge/
    placement) AND more than one device in the mesh — or the stage
    loop is forced on (auron.tpu.stage.deviceLoop.enable=on), whose
    device-resident map output should stay D2D.  Host-pinned
    placement (CPU tests, tunneled backends) keeps the file path: there
    the collective is emulation-only overhead, and a 1-device
    collective never beats the local fast path.
    """
    from blaze_tpu import config

    mode = (config.SHUFFLE_DEVICE.get() or "auto").strip().lower()
    if mode not in ("on", "auto"):
        return None
    if not partitioning or partitioning.get("kind") != "hash":
        return None
    n_out = int(partitioning.get("num_partitions", 1))
    if n_out < 1:
        return None
    fields = (out_schema or {}).get("fields", [])
    if not fields:
        return None
    for f in fields:
        t = f.get("type", {})
        tid = t.get("id")
        if tid in _DEVICE_EXCHANGE_TIDS:
            continue
        if (tid == "decimal" and int(t.get("precision", 99)) <= 18
                and config.ENCODING_DECIMAL_ENABLE.get()):
            # p<=18 decimals already travel as unscaled int64 on device
            # (batch._arrow_fixed_values), hash as longs (kernels/
            # hashing "decimal" tid), and rebuild losslessly on the
            # reduce side (batch.decimal_from_unscaled) — mesh-shardable
            continue
        _note_exchange_type_eviction(tid)
        return None
    names = [f.get("name") for f in fields]
    key_indices = []
    for e in partitioning.get("exprs", []):
        if not isinstance(e, dict) or e.get("kind") != "column":
            return None  # computed keys still go through the host path
        idx = e.get("index")
        if idx is None:
            name = e.get("name")
            idx = names.index(name) if name in names else None
        if idx is None or not (0 <= int(idx) < len(fields)):
            return None
        key_indices.append(int(idx))
    if not key_indices:
        return None
    if mode == "auto":
        import jax

        from blaze_tpu.bridge.placement import host_resident
        if config.STAGE_DEVICE_LOOP_ENABLE.get().strip().lower() == "on":
            # a forced stage loop produces device-resident map output
            # (runtime/loop.py drain_device); keeping the exchange on
            # device avoids a pointless D2H just to re-upload
            pass
        elif host_resident() or len(jax.devices()) < 2:
            return None
    return {"key_indices": key_indices, "num_partitions": n_out}


# ---------------------------------------------------------------------------
# TaskDefinition (ref auron.proto:814, rt.rs:79-90)
# ---------------------------------------------------------------------------

def decode_task_definition(data) -> Dict[str, Any]:
    """Accepts a dict (already decoded), a JSON string/bytes, or raw
    protobuf `TaskDefinition` bytes (the preserved wire contract,
    ref auron.proto:814 / rt.rs:79-90)."""
    if isinstance(data, (bytes, bytearray)):
        data = bytes(data)
        head = data.lstrip()[:1]
        if head in (b"{", b"["):  # JSON IR
            data = data.decode("utf-8")
        else:
            from blaze_tpu.plan.proto_serde import task_definition_from_bytes
            return task_definition_from_bytes(data)
    if isinstance(data, str):
        data = json.loads(data)
    return data


def plan_to_json(d: Dict[str, Any]) -> str:
    return json.dumps(d, separators=(",", ":"))


def plan_from_json(s) -> Dict[str, Any]:
    return decode_task_definition(s)
