"""Fused-stage compiler: planner trees -> single-XLA-program aggregation.

The eager AggExec (ops/agg/exec.py) materializes an Arrow partial batch per
input batch, with a host sync for the group count — general, but it leaves
the device idle between batches.  This pass rewrites eligible
scan→filter→project→partial-agg subtrees so the aggregation loop body is
ONE jit'd XLA program per batch with a persistent on-device group table and
no host syncs (the rt.rs:156 whole-chain-in-one-task analog; SURVEY §7
step 5).

Two fused strategies, chosen at plan time:

  * DENSE (pack_dense_keys + dense_partial_agg): every grouping key is an
    integer column whose global [min, max] bounds are known — from parquet
    row-group statistics or an in-memory table scan.  Group ids are pure
    arithmetic; the loop body is a handful of scatter-reduces.  Zero host
    syncs until the final table decode.
  * SORTED (partial_agg_table): fixed-width keys without usable bounds.
    A fixed-capacity sorted table carries across batches; one scalar
    overflow check per batch.  On overflow the stage degrades to
    pass-through partials (the AGG_TRIGGER_PARTIAL_SKIPPING analog,
    ref agg_table.rs:108-122) — correct for PARTIAL mode because the
    final-agg stage downstream re-merges.

Anything else (string keys, host aggs, avg/collect, merge modes) stays on
the eager path.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import BoundReference, PhysicalExpr
from blaze_tpu.ops.agg.exec import AggExec, AggMode
from blaze_tpu.ops.agg.functions import CountAgg, MinMaxAgg, SumAgg
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.basic import (DebugExec, FilterExec, FilterProjectExec,
                                 ProjectExec)
from blaze_tpu.ops.scan import MemoryScanExec, ParquetScanExec
from blaze_tpu.parallel.stage import (dense_partial_agg, pack_dense_keys,
                                      partial_agg_table, unpack_dense_keys)
from blaze_tpu.schema import Field, Schema


def fuse_plan(plan: ExecutionPlan) -> ExecutionPlan:
    """Rewrite eligible AggExec nodes into FusedPartialAggExec, in place
    for inner nodes (children lists are mutable; schemas are identical by
    construction)."""
    if not config.FUSED_STAGE_ENABLE.get():
        return plan
    replaced = _try_fuse_agg(plan)
    if replaced is not None:
        plan = replaced
    for i, child in enumerate(plan.children):
        plan.children[i] = fuse_plan(child)
    return plan


# ---------------------------------------------------------------------------
# eligibility + bounds discovery
# ---------------------------------------------------------------------------

_FUSABLE_CHAIN = (FilterExec, ProjectExec, FilterProjectExec, DebugExec)


def _try_fuse_agg(node: ExecutionPlan) -> Optional["FusedPartialAggExec"]:
    if not isinstance(node, AggExec) or isinstance(node,
                                                   FusedPartialAggExec):
        return None
    groups = node._group_exprs
    aggs = node._aggs
    if not groups or not aggs:
        return None
    child = node.children[0]
    in_schema = child.schema

    modes = {m for _, m, _ in aggs}
    if len(modes) != 1:
        return None
    mode = next(iter(modes))
    complete = mode in (AggMode.COMPLETE, AggMode.FINAL)
    merging = mode in (AggMode.PARTIAL_MERGE, AggMode.FINAL)

    specs: List[Tuple[str, str, Optional[PhysicalExpr]]] = []
    for fn, _m, _name in aggs:
        if isinstance(fn, SumAgg):
            out_kind = "sum"
        elif isinstance(fn, CountAgg):
            out_kind = "count"
        elif isinstance(fn, MinMaxAgg):
            out_kind = fn.name  # "min" | "max"
        else:
            return None
        arg = fn.children[0] if fn.children else None
        if merging and arg is None:
            return None  # merge modes must reference their acc column
        if arg is not None and not arg.data_type(in_schema).is_fixed_width:
            return None
        if out_kind in ("sum", "min", "max"):
            if arg is None or not (arg.data_type(in_schema).is_integer or
                                   arg.data_type(in_schema).is_floating):
                return None
        # merging counts SUMS the partial counts
        reduce_kind = "sum" if (merging and out_kind == "count") \
            else out_kind
        specs.append((reduce_kind, out_kind, arg))

    key_types = [e.data_type(in_schema) for e, _ in groups]
    if not all(t.is_fixed_width for t in key_types):
        return None

    # dense needs integer keys with discoverable bounds
    ranges = None
    if all(t.is_integer for t in key_types):
        ranges = _discover_ranges(child, groups)
        if ranges is not None:
            total = 1
            for lo, hi in ranges:
                total *= (hi - lo + 2)
            if total > config.FUSED_STAGE_CAPACITY.get():
                ranges = None
    # the sorted path handles overflow two ways: PARTIAL degrades to
    # pass-through (downstream re-merges); exact modes GROW the table
    grow = complete or merging
    return FusedPartialAggExec(child, groups, aggs, specs, ranges,
                               complete, grow)


def _discover_ranges(child: ExecutionPlan,
                     groups) -> Optional[List[Tuple[int, int]]]:
    ranges = []
    for e, _name in groups:
        b = _column_bounds(child, e)
        if b is None:
            return None
        ranges.append(b)
    return ranges


def _column_bounds(node: ExecutionPlan, expr: PhysicalExpr
                   ) -> Optional[Tuple[int, int]]:
    """Trace a grouping expression down a schema-transparent chain to its
    source scan column and read global [min, max] from parquet row-group
    statistics (the stats the scan's own pruning uses) or an in-memory
    table pass."""
    while True:
        if not isinstance(expr, BoundReference):
            return None
        if isinstance(node, (FilterExec, DebugExec)):
            node = node.children[0]
            continue
        if isinstance(node, (ProjectExec, FilterProjectExec)):
            exprs = node._exprs
            if expr.index >= len(exprs):
                return None
            expr = exprs[expr.index]
            node = node.children[0]
            continue
        break
    if isinstance(node, ParquetScanExec):
        return _parquet_bounds(node, expr.index)
    if isinstance(node, MemoryScanExec):
        return _memory_bounds(node, expr.index)
    return None


def _parquet_bounds(scan: ParquetScanExec, col_index: int
                    ) -> Optional[Tuple[int, int]]:
    from blaze_tpu.ops.scan import parquet_metadata
    name = scan.schema[col_index].name
    lo = hi = None
    for group in scan._file_groups:
        for path in group:
            try:
                md = parquet_metadata(path)
            except Exception:
                return None
            fidx = md.schema.names.index(name) \
                if name in md.schema.names else -1
            if fidx < 0:
                return None
            for rg in range(md.num_row_groups):
                st = md.row_group(rg).column(fidx).statistics
                if st is None or not st.has_min_max:
                    return None
                mn, mx = st.min, st.max
                if not isinstance(mn, (int, np.integer)):
                    return None
                lo = mn if lo is None else min(lo, mn)
                hi = mx if hi is None else max(hi, mx)
    if lo is None:
        return None
    return int(lo), int(hi)


def _memory_bounds(scan: MemoryScanExec, col_index: int
                   ) -> Optional[Tuple[int, int]]:
    lo = hi = None
    for part in scan._partitions:
        for cb in part:
            col = cb.columns[col_index]
            data = np.asarray(col.data)[:cb.num_rows]
            valid = np.asarray(col.validity)[:cb.num_rows]
            if cb.selection is not None:
                valid = valid & np.asarray(cb.selection)[:cb.num_rows]
            if not valid.any():
                continue
            mn, mx = int(data[valid].min()), int(data[valid].max())
            lo = mn if lo is None else min(lo, mn)
            hi = mx if hi is None else max(hi, mx)
    if lo is None:
        return None
    return lo, hi


# ---------------------------------------------------------------------------
# the fused operator
# ---------------------------------------------------------------------------

class FusedPartialAggExec(ExecutionPlan):
    """Drop-in replacement for a partial/complete AggExec over fixed-width
    keys: same output schema, single-XLA-program loop body."""

    def __init__(self, child: ExecutionPlan, group_exprs, aggs,
                 specs: Sequence[Tuple[str, str, Optional[PhysicalExpr]]],
                 ranges: Optional[List[Tuple[int, int]]],
                 complete: bool, grow: bool = False):
        super().__init__([child])
        self._group_exprs = list(group_exprs)
        self._aggs = list(aggs)
        self._specs = list(specs)  # (reduce_kind, out_kind, arg)
        self._ranges = ranges
        self._complete = complete
        self._grow = grow  # exact modes grow the table instead of skipping
        self._in_schema = child.schema
        self._out_schema = self._build_schema()

    def _build_schema(self) -> Schema:
        fields: List[Field] = []
        for e, name in self._group_exprs:
            fields.append(Field(name, e.data_type(self._in_schema)))
        for fn, mode, name in self._aggs:
            if mode in (AggMode.FINAL, AggMode.COMPLETE):
                fields.append(Field(name, fn.output_type(self._in_schema)))
            else:
                for f in fn.acc_fields(self._in_schema):
                    fields.append(Field(f"{name}.{f.name}", f.data_type,
                                        f.nullable))
        return Schema(fields)

    @property
    def schema(self) -> Schema:
        return self._out_schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def fused_mode(self) -> str:
        return "dense" if self._ranges is not None else "sorted"

    def execute(self, partition: int) -> BatchIterator:
        if self._ranges is not None:
            yield from self._execute_dense(partition)
        else:
            yield from self._execute_sorted(partition)

    # -- dense: no host syncs in the loop ----------------------------------
    def _execute_dense(self, partition: int) -> BatchIterator:
        num_slots = 1
        for lo, hi in self._ranges:
            num_slots *= (hi - lo + 2)
        kinds = [rk for rk, _ok, _a in self._specs]
        carry = None
        n_batches = 0
        for batch in self.children[0].execute(partition):
            kd, kv, ad, av, mask = self._device_inputs(batch)
            step = self._dense_step(batch.capacity, num_slots, tuple(kinds))
            if carry is None:
                carry = _init_carry(kinds, ad, num_slots)
            carry = step(carry, kd, kv, ad, av, mask)
            n_batches += 1
        self.metrics.add("fused_batches", n_batches)
        if carry is None:
            return
        yield from self._emit_dense(carry, num_slots)

    def _dense_step(self, capacity: int, num_slots: int, kinds):
        # the factory is memoized at module level so every task/plan
        # instance with the same (ranges, kinds, slots) shares one jit
        # cache — a fresh runtime per task must NOT recompile
        return _dense_step_factory(tuple(self._ranges), kinds, num_slots)

    def _emit_dense(self, carry, num_slots: int) -> BatchIterator:
        accs, avalid, occupied = carry
        # Compact ON DEVICE before reading back: the table has num_slots
        # entries (possibly millions) but only `count` occupied.  Ship the
        # occupied prefix, padded to a power-of-two bucket so XLA sees a
        # handful of shapes instead of one per distinct count.
        count = int(jnp.sum(occupied))
        if count == 0:
            return
        padded = _bucket(count, num_slots)
        slots_dev = jnp.argsort(~occupied, stable=True)[:padded]
        fetch = ([jnp.take(a, slots_dev) for a in accs],
                 [jnp.take(v, slots_dev) for v in avalid],
                 slots_dev)
        host_accs, host_avalid, slots = jax.device_get(fetch)
        slots = slots[:count]
        # slot -> key decode host-side (shared stride logic, no round trip)
        host_keys = unpack_dense_keys(slots, self._ranges, xp=np)
        yield from self._emit_rows(
            host_keys, [a[:count] for a in host_accs],
            [v[:count] for v in host_avalid])

    # -- sorted: carry table + per-batch overflow check --------------------
    def _execute_sorted(self, partition: int) -> BatchIterator:
        carry_slots = config.ON_DEVICE_AGG_CAPACITY.get()
        kinds = [rk for rk, _ok, _a in self._specs]
        merge_kinds = ["sum" if k == "count" else k for k in kinds]
        carry = None
        skipping = False
        for batch in self.children[0].execute(partition):
            kd, kv, ad, av, mask = self._device_inputs(batch)
            # a batch cannot hold more groups than rows, so capacity slots
            # make the per-batch table lossless
            table = partial_agg_table(
                list(zip(kd, kv)),
                [(k, d, v) for k, d, v in zip(kinds, ad, av)],
                mask, batch.capacity)
            if skipping:
                yield from self._emit_table(table)
                continue
            if carry is None:
                merged = _resize_table(table, merge_kinds, carry_slots)
            else:
                merged = _merge_tables(carry, table, merge_kinds,
                                       carry_slots)
            # num_groups counts ALL boundaries even past the slot cap, and
            # merged >= per-batch count, so this ONE scalar sync per batch
            # covers both the batch table and the merge
            while int(merged.num_groups) > carry_slots:
                if not self._grow:
                    merged = None
                    break
                # exact modes (final/merge/complete) DOUBLE the table and
                # re-merge — both inputs are still intact and lossless
                carry_slots *= 2
                self.metrics.add("table_grown", 1)
                if carry is None:
                    merged = _resize_table(table, merge_kinds, carry_slots)
                else:
                    merged = _merge_tables(carry, table, merge_kinds,
                                           carry_slots)
            if merged is None:
                # degrade to pass-through partials
                # (ref AGG_TRIGGER_PARTIAL_SKIPPING, agg_table.rs:108-122)
                skipping = True
                self.metrics.add("partial_skipped", 1)
                if carry is not None:
                    yield from self._emit_table(carry)
                    carry = None
                yield from self._emit_table(table)
                continue
            carry = merged
        if carry is not None:
            yield from self._emit_table(carry)

    def _emit_table(self, table) -> BatchIterator:
        # groups sit packed at the front of the table (gids are a cumsum),
        # so only the valid prefix crosses the tunnel
        count = int(jnp.minimum(table.num_groups, table.slot_valid.shape[0]))
        if count == 0:
            return
        padded = _bucket(count, table.slot_valid.shape[0])
        keys_h, kvalid_h, accs_h, avalid_h = jax.device_get(
            ([k[:padded] for k in table.keys],
             [v[:padded] for v in table.key_valid],
             [a[:padded] for a in table.accs],
             [v[:padded] for v in table.acc_valid]))
        keys = [(kd[:count], kv[:count])
                for kd, kv in zip(keys_h, kvalid_h)]
        accs = [a[:count] for a in accs_h]
        avalid = [v[:count] for v in avalid_h]
        yield from self._emit_rows(keys, accs, avalid)

    # -- shared emission ----------------------------------------------------
    def _device_inputs(self, batch: ColumnBatch):
        cap = batch.capacity
        kd, kv = [], []
        for e, _name in self._group_exprs:
            dv = e.evaluate(batch).to_device(cap)
            kd.append(dv.data)
            kv.append(dv.validity)
        ad, av = [], []
        for _rk, _ok, arg in self._specs:
            if arg is None:
                ad.append(None)
                av.append(None)
            else:
                dv = arg.evaluate(batch).to_device(cap)
                ad.append(dv.data)
                av.append(dv.validity)
        return tuple(kd), tuple(kv), tuple(ad), tuple(av), batch.row_mask()

    def _emit_rows(self, keys, accs, avalid) -> BatchIterator:
        n = len(accs[0]) if accs else len(keys[0][0])
        arrays: List[pa.Array] = []
        out_arrow = self._out_schema.to_arrow()
        i = 0
        for (kd, kv), f in zip(keys, out_arrow):
            arrays.append(_to_arrow(kd, kv, f.type))
            i += 1
        for (_rk, out_kind, _arg), a, v in zip(self._specs, accs, avalid):
            f = out_arrow.field(i)
            if out_kind == "count":
                # count never nulls, whether counted or summed from accs
                arrays.append(_to_arrow(a, np.ones(n, dtype=bool), f.type))
            else:
                arrays.append(_to_arrow(a, v, f.type))
            i += 1
        rb = pa.RecordBatch.from_arrays(arrays, schema=out_arrow)
        bs = config.BATCH_SIZE.get()
        for off in range(0, rb.num_rows, bs):
            chunk = rb.slice(off, min(bs, rb.num_rows - off))
            self.metrics.add("output_rows", chunk.num_rows)
            yield ColumnBatch.from_arrow(chunk)


import functools


@functools.lru_cache(maxsize=128)
def _dense_step_factory(ranges, kinds, num_slots: int):
    ranges = list(ranges)

    @partial(jax.jit, donate_argnums=0)
    def step(carry, key_data, key_valid, agg_data, agg_valid, mask):
        accs, avalid, occupied = carry
        gid, _total = pack_dense_keys(list(zip(key_data, key_valid)),
                                      ranges)
        batch_specs = [(kind, vd, vv)
                       for kind, vd, vv in zip(kinds, agg_data, agg_valid)]
        a2, v2, occ2 = dense_partial_agg(gid, num_slots, batch_specs, mask)
        new_a, new_v = [], []
        for kind, a, av, b, bv in zip(kinds, accs, avalid, a2, v2):
            if kind in ("sum", "count"):
                new_a.append(a + b)
                new_v.append(av | bv)
            elif kind == "min":
                both = av & bv
                new_a.append(jnp.where(both, jnp.minimum(a, b),
                                       jnp.where(bv, b, a)))
                new_v.append(av | bv)
            else:  # max
                both = av & bv
                new_a.append(jnp.where(both, jnp.maximum(a, b),
                                       jnp.where(bv, b, a)))
                new_v.append(av | bv)
        return (tuple(new_a), tuple(new_v), occupied | occ2)

    return step


def _init_carry(kinds, agg_data, num_slots: int):
    accs, avalid = [], []
    for kind, vd in zip(kinds, agg_data):
        if kind == "count":
            accs.append(jnp.zeros(num_slots, dtype=jnp.int64))
            avalid.append(jnp.ones(num_slots, dtype=bool))
            continue
        if kind == "sum":
            dt = (jnp.float64 if jnp.issubdtype(vd.dtype, jnp.floating)
                  else jnp.int64)
        else:
            dt = vd.dtype
        accs.append(jnp.zeros(num_slots, dtype=dt))
        avalid.append(jnp.zeros(num_slots, dtype=bool))
    occupied = jnp.zeros(num_slots, dtype=bool)
    return (tuple(accs), tuple(avalid), occupied)


def _bucket(count: int, cap: int) -> int:
    """Next power of two >= count (min 1024), clamped to cap — keeps the
    device slice shapes to a handful of variants."""
    b = 1024
    while b < count:
        b <<= 1
    return min(b, cap)


def _resize_table(t, merge_kinds, num_slots: int):
    """Re-aggregate a lossless table into the carry capacity (caller has
    checked num_groups fits)."""
    keys = list(zip(t.keys, t.key_valid))
    specs = [(kind, acc, av) for kind, acc, av in
             zip(merge_kinds, t.accs, t.acc_valid)]
    return partial_agg_table(keys, specs, t.slot_valid, num_slots)


def _merge_tables(a, b, merge_kinds, num_slots: int):
    keys = [(jnp.concatenate([ka, kb]), jnp.concatenate([va, vb]))
            for (ka, kb), (va, vb) in
            zip(zip(a.keys, b.keys), zip(a.key_valid, b.key_valid))]
    specs = []
    for kind, aa, ab, va, vb in zip(merge_kinds, a.accs, b.accs,
                                    a.acc_valid, b.acc_valid):
        specs.append((kind, jnp.concatenate([aa, ab]),
                      jnp.concatenate([va, vb])))
    mask = jnp.concatenate([a.slot_valid, b.slot_valid])
    return partial_agg_table(keys, specs, mask, num_slots)


def _to_arrow(data: np.ndarray, valid: np.ndarray,
              t: pa.DataType) -> pa.Array:
    arr = pa.array(data, mask=~np.asarray(valid, dtype=bool))
    if not arr.type.equals(t):
        arr = arr.cast(t, safe=False)
    return arr
