"""Fused-stage compiler: planner trees -> single-XLA-program aggregation.

The eager AggExec (ops/agg/exec.py) materializes an Arrow partial batch per
input batch, with a host sync for the group count — general, but it leaves
the device idle between batches.  This pass rewrites eligible
scan→filter→project→partial-agg subtrees so the aggregation loop body is
ONE jit'd XLA program per batch with a persistent on-device group table and
no host syncs (the rt.rs:156 whole-chain-in-one-task analog; SURVEY §7
step 5).

Two fused strategies, chosen at plan time:

  * DENSE (pack_dense_keys + in-place scatter carry): every grouping key
    is an integer column whose global [min, max] bounds are known — from
    parquet row-group statistics or an in-memory table scan.  Group ids
    are pure arithmetic; the loop body scatter-accumulates into a donated
    carry (O(batch) per step).  Zero host syncs until the final decode.
  * HASH (hash_agg_step, parallel/stage.py): fixed-width keys without
    usable bounds.  A device open-addressing table (linear-probe rounds
    of scatter/gather — no lax.sort, which takes minutes to compile on
    TPU) carries across batches; one scalar overflow check per batch.
    On overflow exact modes grow+rehash; PARTIAL degrades to batch-local
    dedup pass-through (the AGG_TRIGGER_PARTIAL_SKIPPING analog,
    ref agg_table.rs:108-122) because the final stage re-merges.

Anything else (string keys, host aggs, avg/collect, merge modes) stays on
the eager path.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge import xla_stats
from blaze_tpu.bridge.xla_stats import meter_jit
from blaze_tpu.exprs import BoundReference, PhysicalExpr
from blaze_tpu.ops.agg.exec import AggExec, AggMode
from blaze_tpu.ops.agg.functions import CountAgg, MinMaxAgg, SumAgg
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.basic import (DebugExec, FilterExec, FilterProjectExec,
                                 ProjectExec)
from blaze_tpu.ops.scan import MemoryScanExec, ParquetScanExec
from blaze_tpu.parallel.stage import (hash_agg_step, init_accumulators,
                                      init_hash_carry, pack_dense_keys,
                                      rehash_carry, scatter_accumulate,
                                      unpack_dense_keys)
from blaze_tpu.schema import Field, Schema, TypeId


def fuse_plan(plan: ExecutionPlan) -> ExecutionPlan:
    """Rewrite eligible AggExec nodes into FusedPartialAggExec, in place
    for inner nodes (children lists are mutable; schemas are identical by
    construction)."""
    if not config.FUSED_STAGE_ENABLE.get():
        return plan
    replaced = _try_fuse_agg(plan)
    if replaced is not None:
        plan = replaced
    for i, child in enumerate(plan.children):
        plan.children[i] = fuse_plan(child)
    return plan


# ---------------------------------------------------------------------------
# eligibility + bounds discovery
# ---------------------------------------------------------------------------

_FUSABLE_CHAIN = (FilterExec, ProjectExec, FilterProjectExec, DebugExec)


def _try_fuse_agg(node: ExecutionPlan) -> Optional["FusedPartialAggExec"]:
    if not isinstance(node, AggExec) or isinstance(node,
                                                   FusedPartialAggExec):
        return None
    groups = node._group_exprs
    aggs = node._aggs
    if not groups or not aggs:
        return None
    child = node.children[0]
    in_schema = child.schema

    modes = {m for _, m, _ in aggs}
    if len(modes) != 1:
        return None
    mode = next(iter(modes))
    complete = mode in (AggMode.COMPLETE, AggMode.FINAL)
    merging = mode in (AggMode.PARTIAL_MERGE, AggMode.FINAL)

    specs: List[Tuple[str, str, Optional[PhysicalExpr]]] = []
    for fn, _m, _name in aggs:
        if isinstance(fn, SumAgg):
            out_kind = "sum"
        elif isinstance(fn, CountAgg):
            out_kind = "count"
        elif isinstance(fn, MinMaxAgg):
            out_kind = fn.name  # "min" | "max"
        else:
            return None
        arg = fn.children[0] if fn.children else None
        if merging and arg is None:
            return None  # merge modes must reference their acc column
        if arg is not None and not arg.data_type(in_schema).is_fixed_width:
            return None
        if out_kind in ("sum", "min", "max"):
            if arg is None or not (arg.data_type(in_schema).is_integer or
                                   arg.data_type(in_schema).is_floating):
                return None
        # merging counts SUMS the partial counts
        reduce_kind = "sum" if (merging and out_kind == "count") \
            else out_kind
        specs.append((reduce_kind, out_kind, arg))

    key_types = [e.data_type(in_schema) for e, _ in groups]
    fixed_keys = all(t.is_fixed_width for t in key_types)
    if not fixed_keys:
        # utf8 group keys can't reach the device strategies, but Arrow's
        # hash aggregation handles them natively — admit them when the
        # host-vectorized path will actually run (placement is decided
        # before plans build, so this is stable for the task).  The
        # eager fallback re-lexsorts buffered partials per combine,
        # which dominated string-keyed queries (q79 at SF1: 10.5s -> the
        # acero path).
        from blaze_tpu.bridge.placement import host_resident
        if not all(t.is_fixed_width or t.id == TypeId.UTF8
                   for t in key_types):
            return None
        host_ok = (host_resident()
                   and config.FUSED_HOST_VECTORIZED_ENABLE.get()
                   and _host_vectorized_eligible(groups, specs, in_schema))
        # device placement: utf8 keys ride the dict-code strategy —
        # dictionary-encode to dense i32 codes, group on device
        # (_execute_dict_device), decode at emit.  min/max over float
        # args are excluded: the step's jnp.minimum folding propagates
        # NaN where Spark's total order skips it (AggExec handles that;
        # see MinMaxAgg._reduce)
        dict_ok = (config.FUSED_DICT_DEVICE_ENABLE.get() and
                   not any(rk in ("min", "max") and arg is not None
                           and arg.data_type(in_schema).is_floating
                           for rk, _ok, arg in specs))
        if not host_ok and not dict_ok:
            return None

    # dense needs integer keys with discoverable bounds
    ranges = None
    if fixed_keys and all(t.is_integer for t in key_types):
        ranges = _discover_ranges(child, groups)
        if ranges is not None:
            total = 1
            for lo, hi in ranges:
                total *= (hi - lo + 2)
            if total > config.FUSED_STAGE_CAPACITY.get():
                ranges = None
            elif total > (1 << 20):
                # sparsity heuristic: a table much larger than the input
                # can't be dense — the O(slots) carry traffic loses to
                # the hash table (distinct groups <= rows by definition)
                rows = _source_row_count(child)
                if rows is not None and total > 4 * rows:
                    ranges = None
    # the sorted path handles overflow two ways: PARTIAL degrades to
    # pass-through (downstream re-merges); exact modes GROW the table
    grow = complete or merging
    # absorb the filter/project chain between agg and source into the jit
    # step when every expression traces (the CachedExprsEvaluator work
    # moves INSIDE the XLA program: one dispatch per batch, ref rt.rs:156
    # whole-chain-in-one-task)
    source, chain = _absorbable_chain(child)
    node = FusedPartialAggExec(child, groups, aggs, specs, ranges,
                               complete, grow, source=source, chain=chain)
    if ranges is not None:
        node._mxu_meta = _plan_mxu_meta(child, specs, ranges, in_schema)
    return node


def _host_vectorized_eligible(group_exprs, specs, in_schema) -> bool:
    """Restrict the Arrow group_by path to where its semantics are
    bit-identical to the device kernels: integer-family (or utf8) keys
    (float keys need NaN/-0.0 normalization, decimals the unscaled-int
    representation) and sum/count on non-decimal args; min/max only on
    non-float args (Spark orders NaN largest; Arrow min_max skips
    NaN)."""
    for e, _n in group_exprs:
        t = e.data_type(in_schema)
        if t.is_floating or t.id == TypeId.DECIMAL:
            return False
    for rk, _ok, arg in specs:
        if arg is None:
            continue
        t = arg.data_type(in_schema)
        if t.id == TypeId.DECIMAL:
            return False
        if rk in ("min", "max") and t.is_floating:
            return False
    return True


def _absorbable_chain(child: ExecutionPlan):
    """Peel Filter/Project/FilterProject off the agg's child.  Returns
    (source_plan, chain_steps) where chain_steps apply source->agg order;
    (child, []) when nothing absorbs."""
    steps = []
    node = child
    while True:
        if isinstance(node, FilterExec):
            steps.append(("filter", node._predicates, None, None))
        elif isinstance(node, ProjectExec):
            steps.append(("project", None, node._exprs, node.schema))
        elif isinstance(node, FilterProjectExec):
            # appended top-down; the final reverse() restores filter-then-
            # project execution order
            steps.append(("project", None, node._exprs, node.schema))
            steps.append(("filter", node._predicates, None, None))
        else:
            break
        node = node.children[0]
    steps.reverse()
    return node, steps


def _chain_cache_key(source_schema: Schema, chain, group_exprs, specs):
    chain_k = []
    for kind, preds, exprs, _schema in chain:
        if kind == "filter":
            chain_k.append(("f", tuple(p.cache_key() for p in preds)))
        else:
            chain_k.append(("p", tuple(e.cache_key() for e in exprs)))
    return (tuple((f.name, f.data_type.id.value) for f in source_schema),
            tuple(chain_k),
            tuple(e.cache_key() for e, _ in group_exprs),
            tuple((rk, ok, a.cache_key() if a is not None else None)
                  for rk, ok, a in specs),
            # encoding knobs change what the chain traces (int32 code
            # slots for utf8; limb compares for unequal-scale decimals):
            # key them so toggling never reuses a stale prepare
            bool(config.ENCODING_DICT_ENABLE.get()),
            bool(config.ENCODING_DECIMAL_ENABLE.get()))


def _source_row_count(child: ExecutionPlan):
    """Total input rows from scan metadata (parquet footers / in-memory
    partitions); None when the source is opaque."""
    node = child
    while isinstance(node, _FUSABLE_CHAIN):
        node = node.children[0]
    if isinstance(node, ParquetScanExec):
        from blaze_tpu.ops.scan import parquet_metadata
        total = 0
        for group in node._file_groups:
            for path in group:
                try:
                    total += parquet_metadata(path).num_rows
                except Exception:
                    return None
        return total
    if isinstance(node, MemoryScanExec):
        return sum(cb.num_rows for part in node._partitions
                   for cb in part)
    return None


def _discover_ranges(child: ExecutionPlan,
                     groups) -> Optional[List[Tuple[int, int]]]:
    ranges = []
    for e, _name in groups:
        b = _column_bounds(child, e)
        if b is None:
            return None
        ranges.append(b)
    return ranges


def _column_bounds(node: ExecutionPlan, expr: PhysicalExpr,
                   float_ok: bool = False) -> Optional[Tuple]:
    """Trace a grouping expression down a schema-transparent chain to its
    source scan column and read global [min, max] from parquet row-group
    statistics (the stats the scan's own pruning uses) or an in-memory
    table pass.  `float_ok` additionally admits float statistics (the MXU
    strategy's fixed-point planning needs value bounds, not just keys)."""
    while True:
        if not isinstance(expr, BoundReference):
            return None
        if isinstance(node, (FilterExec, DebugExec)):
            node = node.children[0]
            continue
        if isinstance(node, (ProjectExec, FilterProjectExec)):
            exprs = node._exprs
            if expr.index >= len(exprs):
                return None
            expr = exprs[expr.index]
            node = node.children[0]
            continue
        break
    if isinstance(node, ParquetScanExec):
        return _parquet_bounds(node, expr.index, float_ok)
    if isinstance(node, MemoryScanExec):
        return _memory_bounds(node, expr.index, float_ok)
    return None


def _parquet_bounds(scan: ParquetScanExec, col_index: int,
                    float_ok: bool = False) -> Optional[Tuple]:
    from blaze_tpu.ops.scan import parquet_metadata
    name = scan.schema[col_index].name
    lo = hi = None
    is_float = False
    for group in scan._file_groups:
        for path in group:
            try:
                md = parquet_metadata(path)
            except Exception:
                return None
            fidx = md.schema.names.index(name) \
                if name in md.schema.names else -1
            if fidx < 0:
                return None
            for rg in range(md.num_row_groups):
                st = md.row_group(rg).column(fidx).statistics
                if st is None or not st.has_min_max:
                    return None
                mn, mx = st.min, st.max
                if isinstance(mn, float) and not isinstance(
                        mn, (int, np.integer)):
                    if not float_ok:
                        return None
                    is_float = True
                elif not isinstance(mn, (int, np.integer)):
                    return None
                lo = mn if lo is None else min(lo, mn)
                hi = mx if hi is None else max(hi, mx)
    if lo is None:
        return None
    if is_float:
        return float(lo), float(hi)
    return int(lo), int(hi)


def _memory_bounds(scan: MemoryScanExec, col_index: int,
                   float_ok: bool = False) -> Optional[Tuple]:
    lo = hi = None
    for part in scan._partitions:
        for cb in part:
            col = cb.columns[col_index]
            data = np.asarray(col.data)[:cb.num_rows]
            valid = np.asarray(col.validity)[:cb.num_rows]
            if cb.selection is not None:
                valid = valid & np.asarray(cb.selection)[:cb.num_rows]
            if not valid.any():
                continue
            if np.issubdtype(data.dtype, np.floating) and not float_ok:
                return None
            mn, mx = data[valid].min(), data[valid].max()
            lo = mn if lo is None else min(lo, mn)
            hi = mx if hi is None else max(hi, mx)
    if lo is None:
        return None
    if np.issubdtype(type(lo), np.floating) or isinstance(lo, float):
        return float(lo), float(hi)
    return int(lo), int(hi)


# ---------------------------------------------------------------------------
# MXU strategy planning (kernels/mxu_agg.py): compact dense tables
# aggregate as one-hot matmuls in an exact 8-bit-limb integer tier —
# the TPU fast path (no scatters, no 64-bit emulation in the hot loop)
# ---------------------------------------------------------------------------

from typing import NamedTuple


class _MxuVerifyFailed(Exception):
    """A float sum column failed the fixed-point exactness verify on
    device; the partition re-runs through the scatter strategy."""


class _MxuSpec(NamedTuple):
    kind: str          # count_star | count | sum | min | max
    arr_valid: int     # value-array index of the validity block (-1)
    arr_cents: int     # value-array index of the cents blocks (-1)
    scatter_idx: int   # min/max scatter accumulator index (-1)
    off: int           # integer offset subtracted into the limb domain
    scale: int         # 1 for ints; fixed-point scale for floats
    is_float: bool


class _MxuMeta(NamedTuple):
    layout: tuple      # MxuAggLayout
    specs: Tuple[_MxuSpec, ...]
    arrays: Tuple[Tuple[str, int], ...]   # ("valid"|"cents", spec_index)
    scatter: Tuple[Tuple[bool, int], ...]  # (is_min, spec_index)


def _plan_mxu_meta(child, specs, ranges, in_schema) -> Optional[_MxuMeta]:
    """Static eligibility + layout for the MXU dense strategy.  Every
    aggregated value must map to a non-negative integer domain that
    8-bit limbs cover: ints shift by their stats minimum; floats scale
    to fixed-point cents (verified exactly on device at runtime).  Any
    miss keeps the spec — and therefore the stage — on the scatter
    path."""
    import math

    from blaze_tpu.kernels import mxu_agg

    if not config.AGG_MXU_ENABLE.get():
        return None
    total = 1
    for lo, hi in ranges:
        total *= (hi - lo + 2)
    if total > config.AGG_MXU_MAX_SLOTS.get():
        return None
    scale_conf = config.AGG_MXU_DECIMAL_SCALE.get()
    arrays: List[Tuple[str, int]] = []
    bits: List[int] = []
    mspecs: List[_MxuSpec] = []
    scatter: List[Tuple[bool, int]] = []
    valid_by_arg: Dict = {}  # arg cache_key -> shared validity array idx

    def valid_block(si, arg) -> int:
        """Validity blocks dedup across specs over the same argument
        (sum+count+min over one column is the common rollup shape; each
        block is a full matmul column group, so sharing is real money)."""
        try:
            k = arg.cache_key()
        except Exception:
            k = ("id", id(arg))
        if k in valid_by_arg:
            return valid_by_arg[k]
        arrays.append(("valid", si))
        bits.append(1)
        valid_by_arg[k] = len(arrays) - 1
        return valid_by_arg[k]

    for si, (rk, _ok, arg) in enumerate(specs):
        if rk == "count":
            if arg is None:
                mspecs.append(_MxuSpec("count_star", -1, -1, -1, 0, 1,
                                       False))
            else:
                mspecs.append(_MxuSpec("count", valid_block(si, arg), -1,
                                       -1, 0, 1, False))
            continue
        if rk not in ("sum", "min", "max") or arg is None:
            return None
        t = arg.data_type(in_schema)
        is_float = t.is_floating
        if not (is_float or t.is_integer):
            return None
        if is_float and t.id != TypeId.FLOAT64:
            # float32 carries ~6e-8 relative rounding: the fixed-point
            # verify could never pass and every partition would fold
            # then fall back — strictly worse than going scatter direct
            return None
        b = _column_bounds(child, arg, float_ok=is_float)
        if b is None:
            return None
        lo, hi = b
        if is_float:
            if not (math.isfinite(float(lo)) and math.isfinite(float(hi))):
                return None
            clo = int(math.floor(float(lo) * scale_conf)) - 1
            chi = int(math.ceil(float(hi) * scale_conf)) + 1
            scale = scale_conf
        else:
            clo, chi, scale = int(lo), int(hi), 1
        span_bits = mxu_agg.limb_bits_for(clo, chi)
        if span_bits > 31:
            return None
        vi = valid_block(si, arg)
        if rk == "sum":
            arrays.append(("cents", si))
            bits.append(span_bits)
            mspecs.append(_MxuSpec("sum", vi, len(arrays) - 1, -1, clo,
                                   scale, is_float))
        else:
            scatter.append((rk == "min", si))
            mspecs.append(_MxuSpec(rk, vi, -1, len(scatter) - 1, clo,
                                   scale, is_float))
    layout = mxu_agg.plan_layout(total, bits)
    if layout is None:
        return None
    return _MxuMeta(layout, tuple(mspecs), tuple(arrays), tuple(scatter))


# ---------------------------------------------------------------------------
# the fused operator
# ---------------------------------------------------------------------------

class FusedPartialAggExec(ExecutionPlan):
    """Drop-in replacement for a partial/complete AggExec over fixed-width
    keys: same output schema, single-XLA-program loop body."""

    def __init__(self, child: ExecutionPlan, group_exprs, aggs,
                 specs: Sequence[Tuple[str, str, Optional[PhysicalExpr]]],
                 ranges: Optional[List[Tuple[int, int]]],
                 complete: bool, grow: bool = False,
                 source: Optional[ExecutionPlan] = None, chain=None):
        super().__init__([child])
        self._group_exprs = list(group_exprs)
        self._aggs = list(aggs)
        self._specs = list(specs)  # (reduce_kind, out_kind, arg)
        self._ranges = ranges
        self._complete = complete
        self._grow = grow  # exact modes grow the table instead of skipping
        self._in_schema = child.schema
        self._out_schema = self._build_schema()
        # chain absorption: iterate the SOURCE and run filter/project
        # inside the jit step.  Falls back to the eager child when the
        # chain doesn't trace (strings, host-only exprs).
        self._source = source if source is not None else child
        self._chain = list(chain or [])
        self._prepare = None
        self._prepare_key = None
        self._mxu_meta = None  # set by _try_fuse_agg when stats qualify
        if self._chain or source is not None:
            self._prepare_key = _chain_cache_key(
                self._source.schema, self._chain, self._group_exprs,
                self._specs)
            self._prepare = _prepare_factory(
                self._prepare_key, self._source.schema, self._chain,
                self._group_exprs, self._specs)

    def _build_schema(self) -> Schema:
        fields: List[Field] = []
        for e, name in self._group_exprs:
            fields.append(Field(name, e.data_type(self._in_schema)))
        for fn, mode, name in self._aggs:
            if mode in (AggMode.FINAL, AggMode.COMPLETE):
                fields.append(Field(name, fn.output_type(self._in_schema)))
            else:
                for f in fn.acc_fields(self._in_schema):
                    fields.append(Field(f"{name}.{f.name}", f.data_type,
                                        f.nullable))
        return Schema(fields)

    @property
    def schema(self) -> Schema:
        return self._out_schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def fused_mode(self) -> str:
        return "dense" if self._ranges is not None else "sorted"

    def _use_host_vectorized(self) -> bool:
        from blaze_tpu.bridge.placement import host_resident
        return (config.FUSED_HOST_VECTORIZED_ENABLE.get() and
                host_resident() and self._host_vectorized_eligible())

    @property
    def _has_var_keys(self) -> bool:
        return any(not e.data_type(self._in_schema).is_fixed_width
                   for e, _n in self._group_exprs)

    def _stage_loop_program(self):
        """StageProgram for the device-resident loop, or None when the
        knob or placement declines it / the stage doesn't compile.
        Under 'auto' the loop and the host Arrow lane are mutually
        exclusive (stage_loop_active requires device placement), so
        there is no priority question between them."""
        from blaze_tpu.plan import stage_compiler
        if not stage_compiler.stage_loop_active():
            return None
        return stage_compiler.try_compile(self)

    def execute(self, partition: int) -> BatchIterator:
        prog = self._stage_loop_program()
        if prog is not None:
            # device-resident stage loop (runtime/loop.py): ONE jit'd
            # program folds a chunk of batches, amortizing dispatch per
            # chunk instead of per batch.  The loop emits only at its
            # final drain, so StageLoopFallback here is lossless and the
            # partition re-runs through the staged lanes below.
            from blaze_tpu.runtime.loop import (StageLoopFallback,
                                                execute_loop)
            try:
                yield from execute_loop(prog, partition)
                return
            except StageLoopFallback:
                xla_stats.note_stage_loop_fallback()
                self.metrics.add("stage_loop_fallback", 1)
        if self._has_var_keys and not self._use_host_vectorized():
            # re-check the ADMISSION-time exclusion (dict_ok in
            # _try_fuse_agg): a plan fused for the host path whose
            # placement/config drifted must fail LOUDLY, not run the
            # NaN-propagating fold on float min/max args
            dict_safe = not any(
                rk in ("min", "max") and arg is not None
                and arg.data_type(self._in_schema).is_floating
                for rk, _ok, arg in self._specs)
            if config.FUSED_DICT_DEVICE_ENABLE.get() and dict_safe:
                try:
                    yield from self._execute_dict_device(partition)
                    return
                except _DictCapExceeded:
                    # nothing emitted yet (dict path emits only at the
                    # final drain).  Arrow's host agg is only a valid
                    # stand-in where it is both ENABLED and eligible;
                    # otherwise the generic AggExec engine (exact Spark
                    # semantics incl. float-key normalization)
                    self.metrics.add("dict_device_fallback", 1)
                    if (config.FUSED_HOST_VECTORIZED_ENABLE.get()
                            and self._host_vectorized_eligible()):
                        for rb in self._execute_host_vectorized(
                                partition):
                            yield ColumnBatch.from_arrow(rb)
                    else:
                        agg = AggExec(self.children[0],
                                      self._group_exprs, self._aggs)
                        yield from agg.execute(partition)
                    return
            raise RuntimeError(
                "fused utf8-key aggregation requires host placement "
                "(placement changed after plan fusion?)")
        if self._use_host_vectorized():
            # host placement: Arrow's multithreaded C++ hash aggregation
            # (GIL-releasing) is the host-engine analog of the reference's
            # native vectorized agg — faster than driving XLA-CPU programs
            # batch-by-batch from Python (ref agg_table.rs InMemTable)
            for rb in self._execute_host_vectorized(partition):
                yield ColumnBatch.from_arrow(rb)
        elif self._ranges is not None:
            if self._mxu_meta is not None and self._mxu_active():
                try:
                    yield from self._execute_mxu(partition)
                    return
                except _MxuVerifyFailed:
                    # float column wasn't fixed-point-exact after all:
                    # nothing has been emitted yet (the MXU path only
                    # emits after its final drain), so the partition
                    # re-runs losslessly through the scatter strategy
                    self.metrics.add("mxu_verify_fallback", 1)
            yield from self._execute_dense(partition)
        else:
            yield from self._execute_sorted(partition)

    def _mxu_active(self) -> bool:
        if self._prepare is None:
            return False
        if config.AGG_MXU_FORCE.get():
            return True
        from blaze_tpu.bridge.placement import host_resident
        return not host_resident() and jax.default_backend() == "tpu"

    def arrow_batches(self, partition: int):
        """Arrow-resident output: the host-vectorized path produces Arrow
        record batches natively; handing them to Arrow-resident consumers
        (runtime root, shuffle writer, Acero joins) skips the
        ColumnBatch round trip in both directions."""
        if self._use_host_vectorized():
            yield from self._execute_host_vectorized(partition)
        else:
            yield from super().arrow_batches(partition)

    # -- host placement: Arrow C++ hash aggregation ------------------------
    def _host_vectorized_eligible(self) -> bool:
        return _host_vectorized_eligible(self._group_exprs, self._specs,
                                         self._in_schema)

    def _execute_host_vectorized(self, partition: int) -> BatchIterator:
        import pyarrow as pa

        from blaze_tpu.memory import MemConsumer, MemManager

        key_names = [n for _e, n in self._group_exprs]

        state = {"chunks": [], "rows": 0, "bytes": 0, "merged": None}

        class _Consumer(MemConsumer):
            """Budget discipline for the buffered raw chunks: memory
            pressure forces the acc-table re-merge early (the InMemTable
            mem_used -> spill trigger analog, ref agg_table.rs:323)."""

            def __init__(c):
                super().__init__("host_vectorized_agg")
                c.metrics = self.metrics

            def spill(c) -> int:
                if not state["chunks"]:
                    return 0
                released = state["bytes"]
                state["merged"] = self._host_group_by(
                    state["chunks"], state["merged"], key_names)
                state["chunks"] = []
                state["rows"] = 0
                state["bytes"] = 0
                c.update_mem_used(
                    state["merged"].nbytes if state["merged"] is not None
                    else 0)
                return released

        consumer = _Consumer()
        consumer.set_spillable(MemManager.get())
        # re-merge threshold bounds memory by distinct groups instead of
        # input rows
        limit = config.FUSED_HOST_COLLECT_ROWS.get()
        # partial-agg skipping (the AGG_TRIGGER_PARTIAL_SKIPPING analog,
        # ref agg_table.rs:108-122): a PARTIAL aggregation whose observed
        # cardinality ratio is too high stops aggregating and passes raw
        # rows through in acc form — the final stage re-merges
        can_skip = (not self._complete and not self._grow and
                    config.PARTIAL_AGG_SKIPPING_ENABLE.get())
        skip_ratio = config.PARTIAL_AGG_SKIPPING_RATIO.get()
        skip_min = config.PARTIAL_AGG_SKIPPING_MIN_ROWS.get()
        next_check = skip_min  # re-probe every minRows stride: clustered
        # inputs whose tail turns high-cardinality must still trip the
        # protection (matches the non-fused path's per-flush check,
        # ops/agg/exec.py _should_skip_partials)
        rows_seen = 0
        skipping = False
        merged_bytes = 0
        try:
            for tbl in self._host_input_tables(partition, key_names):
                if tbl is None or tbl.num_rows == 0:
                    continue
                if skipping:
                    xla_stats.note_partial_agg_rows(tbl.num_rows)
                    yield from self._host_passthrough(tbl, key_names)
                    continue
                rows_seen += tbl.num_rows
                state["chunks"].append(tbl)
                state["rows"] += tbl.num_rows
                state["bytes"] += tbl.nbytes  # running total: O(1)/batch
                if state["merged"] is not None:
                    merged_bytes = state["merged"].nbytes
                consumer.update_mem_used(state["bytes"] + merged_bytes)
                # the skip decision checkpoints at minRows-sized strides
                # (not only the much larger collect limit) on a BOUNDED
                # probe — a distinct-count over a UNIFORM row sample of
                # everything buffered, NOT a full merge (the reference
                # measures the ratio on the minRows-row prefix its hash
                # table absorbed, agg_table.rs:108-122; a uniform sample
                # across the whole buffer additionally catches cyclic
                # keys whose repeats a prefix/tail window would miss).
                # Skipping then releases the raw buffer straight through
                # without ever aggregating it.
                # NOTE: update_mem_used above may have spilled THIS
                # consumer synchronously, emptying the chunk buffer —
                # nothing left to probe until more rows arrive
                if can_skip and rows_seen >= next_check \
                        and state["chunks"]:
                    probe = self._sample_rows(
                        state["chunks"], state["rows"],
                        min(skip_min,
                            config.PARTIAL_AGG_SKIPPING_PROBE_ROWS.get()))
                    n_distinct = self._probe_distinct(probe, key_names)
                    xla_stats.note_partial_agg_probe(probe.num_rows,
                                                     n_distinct)
                    if (n_distinct / max(1, probe.num_rows)
                            > skip_ratio):
                        skipping = True
                        self.metrics.add("partial_skipped", 1)
                        xla_stats.note_partial_agg_skip(rows_seen)
                        if state["merged"] is not None:
                            yield from self._emit_host(state["merged"],
                                                       key_names)
                            state["merged"] = None
                        for c in state["chunks"]:
                            # buffered raw chunks leave UNAGGREGATED —
                            # they are pass-through rows too
                            xla_stats.note_partial_agg_rows(c.num_rows)
                            yield from self._host_passthrough(c, key_names)
                        state["chunks"] = []
                        state["rows"] = 0
                        state["bytes"] = 0
                        consumer.update_mem_used(0)
                        continue
                    next_check = rows_seen + skip_min
                if state["rows"] >= limit:
                    consumer.spill()
                    self.metrics.add("host_vectorized_merges", 1)
            if state["chunks"] or state["merged"] is not None:
                state["merged"] = self._host_group_by(
                    state["chunks"], state["merged"], key_names)
        finally:
            consumer.unregister()
        merged = state["merged"]
        if merged is None:
            return
        self.metrics.add("host_vectorized_batches", 1)
        yield from self._emit_host(merged, key_names)

    def _emit_host(self, merged, key_names) -> BatchIterator:
        yield from self._emit_batches(self._host_finalize(merged,
                                                          key_names))

    def _emit_batches(self, rb):
        """Arrow record-batch chunks (the host-vectorized generators stay
        Arrow-resident; execute() wraps into ColumnBatch at the edge)."""
        bs = config.BATCH_SIZE.get()
        for off in range(0, rb.num_rows, bs):
            yield rb.slice(off, min(bs, rb.num_rows - off))

    def _host_passthrough(self, tbl, key_names) -> BatchIterator:
        """One raw keys/args table emitted in PARTIAL-output (acc) form
        without grouping: sum acc = the value, count acc = 1 per valid
        row (1 per row for count(*)), min/max acc = the value.  The
        downstream FINAL aggregation re-merges (partial skipping)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        out_arrow = self._out_schema.to_arrow()
        arrays = []
        for i, f in enumerate(out_arrow):
            if i < len(key_names):
                col = tbl.column(i)
            else:
                spec_i = i - len(key_names)
                rk, _ok, arg = self._specs[spec_i]
                src = tbl.column(len(key_names) + spec_i)
                if rk == "count":
                    col = (pa.array(np.ones(tbl.num_rows,
                                            dtype=np.int64))
                           if arg is None else
                           pc.if_else(pc.is_valid(src), 1, 0))
                else:
                    col = src
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            if not col.type.equals(f.type):
                col = col.cast(f.type, safe=False)
            arrays.append(col)
        rb = pa.RecordBatch.from_arrays(arrays, schema=out_arrow)
        yield from self._emit_batches(rb)

    @staticmethod
    def _mask_filter(tbl, preds, schema, filt):
        """Conjunction of direct-kernel masks (cheaper than Acero's
        Table.filter(Expression) plan construction); Expression fallback
        when any predicate declines."""
        from blaze_tpu.exprs.arrow_compat import eval_filter_mask
        import pyarrow.compute as pc
        mask = None
        for p in preds:
            m = eval_filter_mask(p, schema, tbl)
            if m is None:
                return tbl.filter(filt)
            mask = m if mask is None else pc.and_kleene(mask, m)
        return tbl.filter(mask)

    @staticmethod
    def _probe_distinct(probe, key_names) -> int:
        """Distinct-group count of the probe sample.  Integer keys
        combine into one mixed hash and count via np.unique — ~3x
        cheaper than a group_by on the sample.  A hash collision merges
        two real groups, UNDER-counting distincts and biasing the ratio
        toward KEEPING the aggregation — mildly against the protection
        this probe provides — but at probe sizes (<=50K keys in a
        64-bit space) the expected collision count is ~1e-7, far below
        the ratio's decision margin.  Non-integer keys fall back to the
        exact group_by."""
        import numpy as np
        import pyarrow as pa
        mixed = None
        for name in key_names:
            col = probe.column(name)
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            if not pa.types.is_integer(col.type):
                mixed = None
                break
            v = col.cast(pa.int64(), safe=False).fill_null(
                -0x6A09E667F3BCC909).to_numpy(zero_copy_only=False)
            h = (v.view(np.uint64) * np.uint64(0x9E3779B97F4A7C15)) \
                if mixed is None else \
                ((mixed ^ v.view(np.uint64)) *
                 np.uint64(0x9E3779B97F4A7C15))
            mixed = h ^ (h >> np.uint64(29))
        if mixed is None:
            return probe.group_by(key_names,
                                  use_threads=True).aggregate([]).num_rows
        return int(len(np.unique(mixed)))

    @staticmethod
    def _sample_rows(chunks, total_rows: int, max_rows: int):
        """Uniform strided row sample (≤ max_rows) across all buffered
        chunks.  A sample that spans the whole buffer sees key REPEATS
        that any contiguous window would miss (e.g. keys cycling with a
        period longer than the window), so the cardinality ratio it
        yields under-estimates on repetitive data — the conservative
        direction for the skip decision."""
        tbl = (chunks[0] if len(chunks) == 1
               else pa.concat_tables(chunks))
        if total_rows <= max_rows:
            return tbl
        stride = total_rows / max_rows
        idx = np.minimum((np.arange(max_rows) * stride).astype(np.int64),
                         total_rows - 1)
        return tbl.take(idx)

    def _host_input_tables(self, partition: int, key_names):
        """Iterator of keys+args Arrow tables for the host-vectorized agg.

        Three paths, fastest first:
          1. pushdown scan -> Arrow-resident column selection (every
             grouping/arg expression is a bare column): record batches go
             from the parquet reader into the agg with ZERO numpy round
             trips;
          2. pushdown scan -> ColumnBatch expression evaluation;
          3. engine-side child stream (partition constants, non-arrow
             predicates, non-parquet sources).
        """
        scan = self._host_scan_arrow(partition)
        if scan is None and not self._chain:
            # sources that natively hold Arrow data (IpcReader: the
            # reduce-side merge input) stream it in without a ColumnBatch
            # round trip, same as the pushdown-scan path
            from blaze_tpu.ops.base import ExecutionPlan as _EP
            src = self._source
            if type(src).arrow_batches is not _EP.arrow_batches:
                scan = src.arrow_batches(partition)
        if scan is None:
            for batch in self.children[0].execute(partition):
                yield self._host_keys_args_table(batch, key_names)
            return
        idxs = self._bare_column_indices()
        for rb in scan:
            if rb.num_rows == 0:
                continue
            self.metrics.add("pushdown_rows", rb.num_rows)
            if idxs is not None:
                cols = [rb.column(i) for i in idxs]
                names = list(key_names) + [
                    f"__arg{i}" for i in range(len(self._specs))]
                yield pa.table(cols, names=names)
            elif isinstance(rb, pa.RecordBatch):
                yield self._host_keys_args_table(
                    ColumnBatch.from_arrow(rb), key_names)
            else:
                # eager reads hand back a Table: convert chunk-wise (a
                # combine_chunks of >2 GiB string data would overflow
                # 32-bit offsets)
                for piece in rb.to_batches():
                    if piece.num_rows:
                        yield self._host_keys_args_table(
                            ColumnBatch.from_arrow(piece), key_names)

    def _bare_column_indices(self):
        """Source-schema column index per key+arg when every expression is
        a BoundReference (valid only for an all-filter chain, where the
        agg input schema IS the source schema); None otherwise."""
        if any(kind != "filter" for kind, *_rest in self._chain):
            return None
        idxs = []
        for e, _n in self._group_exprs:
            if not isinstance(e, BoundReference):
                return None
            idxs.append(e.index)
        for _rk, _ok, arg in self._specs:
            if arg is None:  # count(*): any column carries the row count
                idxs.append(idxs[0])
            elif isinstance(arg, BoundReference):
                idxs.append(arg.index)
            else:
                return None
        return idxs

    def _host_scan_arrow(self, partition: int):
        """Push the absorbed filter chain into Arrow's C++ parquet reader
        (predicate + projection pushdown, the parquet_exec.rs analog) when
        the source is a plain parquet scan and every predicate translates
        exactly; None -> engine-side path.  Yields Arrow record batches
        (or tables).

        Small inputs take an EAGER read (pq.read_table + vectorized
        mask): measurably faster than the dataset scanner, which pays
        per-fragment scheduling overhead.  Inputs above the eager
        threshold stream through the scanner for bounded memory."""
        from blaze_tpu.exprs.arrow_compat import to_arrow_filter
        from blaze_tpu.ops.scan import ParquetScanExec, open_source
        src = self._source
        if not isinstance(src, ParquetScanExec):
            return None
        if src._partition_schema is not None:
            return None  # partition constants need engine-side assembly
        filt = None
        plain_preds = []
        for kind, preds, _exprs, _schema in self._chain:
            if kind != "filter":
                return None
            for p in preds or ():
                e = to_arrow_filter(p, src.schema)
                if e is None:
                    return None
                filt = e if filt is None else (filt & e)
                plain_preds.append(p)
        paths = src._file_groups[partition]
        if not paths:
            return iter(())
        import pyarrow.parquet as pq
        eager_limit = config.FUSED_HOST_EAGER_SCAN_BYTES.get()
        try:
            local = all(isinstance(p, str) and os.path.exists(p)
                        for p in paths)
            if (local and sum(os.path.getsize(p) for p in paths)
                    <= eager_limit):
                columns = [f.name for f in src._file_part]
                if plain_preds:
                    return self._eager_pruned_read(
                        paths, columns, plain_preds, src, filt)
                return iter((pq.read_table(paths, columns=columns,
                                           use_threads=True),))
            import pyarrow.dataset as ds
            dataset = ds.dataset([open_source(p) for p in paths],
                                 format="parquet",
                                 schema=src._file_part.to_arrow())
            scanner = dataset.scanner(filter=filt, batch_size=1 << 20,
                                      use_threads=True)
            return scanner.to_batches()
        except Exception:
            return None  # schema evolution etc.: engine-side scan

    def _eager_pruned_read(self, paths, columns, plain_preds, src, filt):
        """Eager read with row-group statistics pruning + mask elision.

        Parity: the reference's parquet row-group/page filtering (ref
        conf.rs:43 `enable.pageFiltering`, parquet_exec.rs page_filtering)
        applied to the eager host path.  A metadata-only pass drops row
        groups the predicate provably never matches; groups the stats
        prove FULLY matching skip the vectorized mask entirely (range
        predicates over date-clustered fact tables make both the common
        case).  Falls back to one whole read_table when nothing prunes —
        identical cost to the pre-pruning path."""
        import functools
        import pyarrow as pa
        import pyarrow.parquet as pq
        from blaze_tpu.exprs.binary import BinaryExpr
        from blaze_tpu.ops.pruning import prune_with_stats, split_covered
        from blaze_tpu.ops.scan import open_source

        pred = functools.reduce(
            lambda a, b: BinaryExpr("and", a, b), plain_preds)
        files = []          # (ParquetFile, covered_groups, boundary_groups)
        kept_total = 0
        groups_total = 0
        for p in paths:
            f = pq.ParquetFile(open_source(p))
            # deterministic schema-evolution guard: the lazy per-file
            # reads below run OUTSIDE the caller's try/fallback, so a
            # file missing a projected column must be detected HERE
            # (falling back to the engine-side scan, which aligns
            # schemas per batch)
            names = set(f.schema_arrow.names)
            if any(c not in names for c in columns):
                raise LookupError("schema evolution: engine-side scan")
            md = f.metadata
            kept = prune_with_stats(md, src.schema, pred,
                                    list(range(md.num_row_groups)))
            groups_total += md.num_row_groups
            kept_total += len(kept)
            if kept:
                # split kept groups into provably-fully-covered (mask
                # elided) vs boundary (masked) — only boundary rows pay
                # the vectorized filter; one metadata pass per file
                covered, boundary = split_covered(md, src.schema, pred,
                                                  kept)
                files.append((f, covered, boundary))
        self.metrics.add("pruned_row_groups", groups_total - kept_total)
        if kept_total == groups_total and all(
                not c for _f, c, _b in files):
            # nothing pruned, nothing elided: single multithreaded read
            # across files — identical cost to the pre-pruning path
            tbl = pq.read_table(paths, columns=columns, use_threads=True)
            return iter((self._mask_filter(tbl, plain_preds, src.schema,
                                           filt),))
        if not files:
            return iter(())

        def read_one(f, covered, boundary):
            """One file's kept rows: covered groups pass unmasked,
            boundary groups get the vectorized filter.  All kept groups
            decode in ONE read_row_groups call (one reader setup, one
            thread fan-out) — covered groups come first, so the
            unmasked region is a head slice and only the boundary tail
            pays the filter.  Decode errors past the (already-validated)
            metadata follow the scan operator's corrupted-file policy —
            these reads run lazily, outside the caller's fallback
            window."""
            try:
                kept_groups = list(covered) + list(boundary)
                if not kept_groups:
                    return None
                tbl = f.read_row_groups(kept_groups, columns=columns,
                                        use_threads=True)
                if not boundary:
                    return tbl
                md = f.metadata
                head_rows = sum(md.row_group(g).num_rows
                                for g in covered)
                btbl = self._mask_filter(tbl.slice(head_rows),
                                         plain_preds, src.schema, filt)
                if not covered:
                    return btbl
                return pa.concat_tables([tbl.slice(0, head_rows), btbl])
            except Exception:
                if config.IGNORE_CORRUPTED_FILES.get():
                    return None
                raise

        def gen():
            # double-buffer: file i+1 decodes on a worker thread (Arrow
            # releases the GIL) while file i flows through mask/agg/IPC
            # downstream — scan and compute overlap inside one task (the
            # tokio-pipelining analog of rt.rs:156)
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(max_workers=1) as pool:
                nxt = pool.submit(read_one, *files[0])
                for i in range(len(files)):
                    tbl = nxt.result()
                    if i + 1 < len(files):
                        nxt = pool.submit(read_one, *files[i + 1])
                    if tbl is not None and tbl.num_rows:
                        yield tbl
        return gen()

    def _host_keys_args_table(self, batch: ColumnBatch, key_names):
        """Evaluate keys + agg args on the (numpy-resident) batch and pack
        them into an Arrow table [k0..kn, a0..am]."""
        import pyarrow as pa
        batch = batch.compact()
        n = batch.num_rows
        if n == 0:
            return None
        arrays = []
        names = []
        for (e, name) in self._group_exprs:
            arrays.append(e.evaluate(batch).to_host(n))
            names.append(name)
        for i, (_rk, _ok, arg) in enumerate(self._specs):
            if arg is None:  # count(*): count rows via a key column
                arrays.append(arrays[0])
            else:
                arrays.append(arg.evaluate(batch).to_host(n))
            names.append(f"__arg{i}")
        return pa.table(arrays, names=names)

    @staticmethod
    def _pack_keys_info(tbl, key_names):
        """Integer group keys pack losslessly into ONE non-negative
        int64: per key k -> k - min + 1 (null -> 0, its own Spark group),
        mixed-radix combined across keys.  Returns (packed int64 column
        with no nulls, spans, mins), or None when any key is non-integer
        or the radix product would overflow int64."""
        import pyarrow as pa
        import pyarrow.compute as pc
        cols = []
        spans = []
        mins = []
        total = 1
        for n in key_names:
            col = tbl.column(n)
            if not pa.types.is_integer(col.type):
                return None
            mm = pc.min_max(col)
            if not mm["min"].is_valid:  # all-null key: span = {null}
                lo, span = 0, 1
            else:
                lo = mm["min"].as_py()
                span = mm["max"].as_py() - lo + 2  # +1 for the null slot
            total *= span
            if total > (1 << 62):
                return None
            cols.append(col)
            spans.append(span)
            mins.append(lo)
        # null-free keys pack in ONE fused numpy expression (zero-copy
        # views in, one output buffer) instead of a chain of pa.compute
        # dispatches; any null key falls back to the Arrow kernels,
        # whose fill_null provides the null->slot-0 encoding
        if all(c.null_count == 0 for c in cols):
            import numpy as np
            packed_np = None
            for col, span, lo in zip(cols, spans, mins):
                cc = (col.combine_chunks()
                      if isinstance(col, pa.ChunkedArray) else col)
                enc = cc.to_numpy(zero_copy_only=False).astype(
                    np.int64, copy=False) + (1 - lo)
                packed_np = enc if packed_np is None else \
                    packed_np * span + enc
            return pa.array(packed_np), spans, mins
        packed = None
        for col, span, lo in zip(cols, spans, mins):
            enc = pc.fill_null(
                pc.add(pc.cast(col, pa.int64(), safe=False), 1 - lo), 0)
            packed = enc if packed is None else \
                pc.add(pc.multiply(packed, span), enc)
        return packed, spans, mins

    @staticmethod
    def _unpack_np_keys(out_k, key_types, spans, mins):
        """Decode packed keys (numpy int64) back to per-key pa arrays,
        restoring nulls.  Null-free keys (the overwhelmingly common
        case: fact-table join/group keys) skip the mask pass entirely,
        letting pa.array zero-copy the decoded buffer instead of
        re-copying it next to a validity bitmap."""
        import numpy as np
        import pyarrow as pa
        parts = []
        k = out_k
        for span in reversed(spans):
            parts.append(k % span)
            k = k // span
        parts.reverse()
        out = []
        for enc, lo, t in zip(parts, mins, key_types):
            nulls = enc == 0
            mask = nulls if nulls.any() else None
            arr = pa.array(enc + (lo - 1), mask=mask)
            if not arr.type.equals(t):
                arr = arr.cast(t, safe=False)
            out.append(arr)
        return out

    _KERNEL_MIN_ROWS = 4096

    def _native_group_by(self, tbl, key_names, kinds):
        """Hash group-aggregation through the native agg kernel
        (agg_kernel.cpp blaze_group_agg_i64): packed int64 key + flat
        accumulator arrays, ~4x Arrow's group_by on high-cardinality
        integer keys.  `kinds` = [(op, col_name_or_None)] in __acc
        output order; op in sum/count/min/max.  Returns the full output
        column list [keys..., accs...] or None -> Arrow fallback."""
        import ctypes

        import numpy as np
        import pyarrow as pa
        import pyarrow.compute as pc

        from blaze_tpu.bridge.native import get_agg_kernel
        lib = get_agg_kernel()
        n = tbl.num_rows
        if (lib is None or not key_names or n < self._KERNEL_MIN_ROWS
                or n >= (1 << 31)):
            return None
        # op eligibility first — packing is two full passes over the
        # table, pointless if any agg can't ride the kernel anyway
        for op_name, colname in kinds:
            if colname is None or op_name == "count":
                continue
            t = tbl.column(colname).type
            if op_name == "sum":
                if not (pa.types.is_floating(t) or pa.types.is_integer(t)):
                    return None
            elif op_name in ("min", "max"):
                if not pa.types.is_integer(t):
                    return None
            else:
                return None
        info = self._pack_keys_info(tbl, key_names)
        if info is None:
            return None
        packed, spans, mins = info
        ops = []
        val_nps = []       # keeps numpy operands alive across the call
        valid_nps = []
        out_nps = []
        out_valid_nps = []
        post = []          # (arrow_type_or_None, is_count)
        for op_name, colname in kinds:
            if op_name == "count" and colname is None:
                ops.append(2)
                val_nps.append(None)
                valid_nps.append(None)
                out_nps.append(np.empty(n, np.int64))
                out_valid_nps.append(np.empty(n, np.uint8))
                post.append((None, True))
                continue
            col = tbl.column(colname)
            t = col.type
            if op_name == "count":
                # only the operand's validity matters; never cast values
                ops.append(2)
                val_nps.append(None)
                valid_nps.append(np.ascontiguousarray(
                    col.combine_chunks().is_valid().to_numpy(
                        zero_copy_only=False), dtype=np.uint8)
                    if col.null_count else None)
                out_nps.append(np.empty(n, np.int64))
                out_valid_nps.append(np.empty(n, np.uint8))
                post.append((None, True))
                continue
            if op_name == "sum" and pa.types.is_floating(t):
                op, target, out_t = 0, pa.float64(), None
            elif op_name == "sum" and pa.types.is_integer(t):
                op, target, out_t = 1, pa.int64(), None
            elif op_name in ("min", "max") and pa.types.is_integer(t):
                op = 3 if op_name == "min" else 4
                target, out_t = pa.int64(), t
            else:
                return None
            ops.append(op)
            cc = col.combine_chunks()
            if col.null_count:
                vals = pc.fill_null(pc.cast(cc, target, safe=False), 0)
                valid_nps.append(np.ascontiguousarray(
                    cc.is_valid().to_numpy(zero_copy_only=False),
                    dtype=np.uint8))
            else:
                # identity casts still copy; hand the buffer over as-is
                vals = cc if cc.type.equals(target) else \
                    pc.cast(cc, target, safe=False)
                valid_nps.append(None)
            val_nps.append(np.ascontiguousarray(
                vals.to_numpy(zero_copy_only=False)))
            out_nps.append(np.empty(
                n, np.float64 if op == 0 else np.int64))
            out_valid_nps.append(np.empty(n, np.uint8))
            post.append((out_t, op == 2))
        key_np = np.ascontiguousarray(
            packed.combine_chunks().to_numpy(zero_copy_only=False)
            if isinstance(packed, pa.ChunkedArray)
            else packed.to_numpy(zero_copy_only=False), dtype=np.int64)
        out_keys = np.empty(n, np.int64)

        def ptr(a):
            return ctypes.c_void_p(a.ctypes.data) if a is not None else None

        n_aggs = len(ops)
        has_rows = hasattr(lib, "blaze_group_agg_i64_rows")
        first_rows = np.empty(n, np.int32) if has_rows else None
        call_args = [
            ptr(key_np), n, n_aggs,
            (ctypes.c_int32 * n_aggs)(*ops),
            (ctypes.c_void_p * n_aggs)(*[ptr(a) for a in val_nps]),
            (ctypes.c_void_p * n_aggs)(*[ptr(a) for a in valid_nps]),
            ptr(out_keys),
            (ctypes.c_void_p * n_aggs)(*[ptr(a) for a in out_nps]),
            (ctypes.c_void_p * n_aggs)(*[ptr(a) for a in out_valid_nps])]
        if has_rows:
            ng = lib.blaze_group_agg_i64_rows(*call_args,
                                              ptr(first_rows))
        else:
            ng = lib.blaze_group_agg_i64(*call_args)
        if ng < 0:
            return None
        if has_rows:
            # materialize keys with one gather per original column —
            # nulls ride along for free; the mixed-radix int64 division
            # decode is the slowest scalar path numpy has
            idx = pa.array(first_rows[:ng])
            out = [pc.take(tbl.column(kn), idx) for kn in key_names]
        else:
            key_types = [tbl.column(kn).type for kn in key_names]
            out = self._unpack_np_keys(out_keys[:ng], key_types, spans,
                                       mins)
        for (out_t, is_count), vals, valid in zip(post, out_nps,
                                                  out_valid_nps):
            mask = None if is_count else (valid[:ng] == 0)
            arr = pa.array(vals[:ng], mask=mask)
            if out_t is not None and not arr.type.equals(out_t):
                arr = arr.cast(out_t, safe=False)
            out.append(arr)
        self.metrics.add("native_agg_rows", n)
        return out

    def _grouped(self, tbl, key_names, aggspec):
        """tbl.group_by with multi-integer-key PACKING: Arrow's hash
        aggregation hashes/compares every key column per row, so N
        integer keys pack into ONE computed int64 key (_pack_keys_info),
        cutting per-row hash work on multi-key aggregations.  The
        packed column is decoded back to the original key columns —
        including nulls, which Spark groups as their own key — after
        aggregation.  Falls back to the plain multi-column group_by
        whenever packing is inapplicable."""
        import pyarrow as pa
        if len(key_names) < 2 or tbl.num_rows < self._KERNEL_MIN_ROWS:
            return tbl.group_by(key_names, use_threads=True) \
                      .aggregate(aggspec), tbl, None
        info = self._pack_keys_info(tbl, key_names)
        if info is None:
            return tbl.group_by(key_names, use_threads=True) \
                      .aggregate(aggspec), tbl, None
        packed, spans, mins = info
        ptbl = tbl.drop_columns(key_names).append_column("__gk", packed)
        g = ptbl.group_by(["__gk"], use_threads=True).aggregate(aggspec)
        return g, tbl, (spans, mins)

    @classmethod
    def _unpack_keys(cls, g, tbl, key_names, packing):
        """Decode the packed __gk column of an aggregate result back to
        the original key columns (None packing: keys are already
        present).  Delegates to the single mixed-radix decoder."""
        import numpy as np
        import pyarrow as pa
        if packing is None:
            return [g.column(n) for n in key_names]
        spans, mins = packing
        k = g.column("__gk")
        if isinstance(k, pa.ChunkedArray):
            k = k.combine_chunks()
        key_types = [tbl.column(n).type for n in key_names]
        return cls._unpack_np_keys(
            np.ascontiguousarray(k.to_numpy(zero_copy_only=False),
                                 dtype=np.int64),
            key_types, spans, mins)

    def _host_group_by(self, chunks, merged, key_names):
        """group_by over buffered raw chunks, then merge with the running
        acc table (merge fns: sum->sum, count->sum, min/max idempotent).

        Output columns are selected BY NAME (`"{col}_{fn}"`), never by
        position — Arrow versions have differed on whether keys come
        first or last in aggregate output."""
        import pyarrow as pa
        import pyarrow.compute as pc
        acc_names = [f"__acc{i}" for i in range(len(self._specs))]
        out = None
        if chunks:
            tbl = pa.concat_tables(chunks)
            kinds = [(rk, None if (rk == "count" and arg is None)
                      else f"__arg{i}")
                     for i, (rk, _ok, arg) in enumerate(self._specs)]
            cols = self._native_group_by(tbl, key_names, kinds)
            if cols is not None:
                out = pa.table(cols, names=key_names + acc_names)
            else:
                aggspec = []
                out_names = []
                for i, (rk, _ok, arg) in enumerate(self._specs):
                    if rk == "count":
                        mode = "all" if arg is None else "only_valid"
                        aggspec.append((f"__arg{i}", "count",
                                        pc.CountOptions(mode=mode)))
                    else:
                        aggspec.append((f"__arg{i}", rk))
                    out_names.append(f"__arg{i}_{rk}")
                g, tbl, packing = self._grouped(tbl, key_names, aggspec)
                out = pa.table(
                    self._unpack_keys(g, tbl, key_names, packing) +
                    [g.column(n) for n in out_names],
                    names=key_names + acc_names)
        if merged is None:
            return out
        if out is None:
            return merged
        # merge two acc tables: counts sum, sums sum, min/max re-reduce
        both = pa.concat_tables([merged, out])
        merge_fns = [("sum" if rk in ("sum", "count") else rk,
                      f"__acc{i}")
                     for i, (rk, _ok, _a) in enumerate(self._specs)]
        cols = self._native_group_by(both, key_names, merge_fns)
        if cols is not None:
            return pa.table(cols, names=key_names + acc_names)
        merge_spec = []
        merge_names = []
        for f, cn in merge_fns:
            merge_spec.append((cn, f))
            merge_names.append(f"{cn}_{f}")
        m, both, packing = self._grouped(both, key_names, merge_spec)
        return pa.table(
            self._unpack_keys(m, both, key_names, packing) +
            [m.column(n) for n in merge_names],
            names=key_names + acc_names)

    def _host_finalize(self, merged, key_names):
        """Acc table -> output RecordBatch in self._out_schema order/types.
        `merged` columns are key_names + __acc{i} by construction."""
        import pyarrow as pa
        out_arrow = self._out_schema.to_arrow()
        arrays = []
        for i, f in enumerate(out_arrow):
            if i < len(key_names):
                col = merged.column(key_names[i])
            else:
                col = merged.column(f"__acc{i - len(key_names)}")
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            if i >= len(key_names):
                _rk, ok, _a = self._specs[i - len(key_names)]
                if ok == "count" and col.null_count:
                    col = col.fill_null(0)  # count never nulls
            if not col.type.equals(f.type):
                col = col.cast(f.type, safe=False)
            arrays.append(col)
        return pa.RecordBatch.from_arrays(arrays, schema=out_arrow)

    def _acc_dtypes(self) -> Tuple:
        """Carry accumulator dtype per spec (no evaluation needed)."""
        out = []
        for rk, _ok, arg in self._specs:
            if rk == "count" or arg is None:
                out.append(jnp.int64)
                continue
            dt = arg.data_type(self._in_schema).jnp_dtype()
            if rk == "sum":
                dt = (jnp.float64 if jnp.issubdtype(dt, jnp.floating)
                      else jnp.int64)
            out.append(dt)
        return tuple(out)

    # -- MXU strategy: matmul aggregation in the i32 limb tier -------------
    def _execute_mxu(self, partition: int) -> BatchIterator:
        """Fold windows through the MXU histogram kernel; drain the i32
        limb table into host int64 accumulators within its exactness
        bound; emit once at partition end.  Raises _MxuVerifyFailed
        before any emission when a float column breaks the fixed-point
        contract."""
        from blaze_tpu.kernels import mxu_agg
        meta = self._mxu_meta
        layout = meta.layout
        S = layout.num_slots
        nb = layout.n_blocks
        use_pallas = jax.default_backend() == "tpu"
        fold = _mxu_fold_factory(self._prepare_key, self._prepare,
                                 tuple(self._ranges), meta, use_pallas)
        wide_presence = np.zeros(S, np.int64)
        wide_vals = [np.zeros(S, np.int64) for _ in meta.arrays]
        wide_mm = [np.full(S, (2**31 - 1) if is_min else -(2**31), np.int64)
                   for is_min, _si in meta.scatter]
        carry = None
        bound = 0
        n_batches = 0

        def fresh_carry():
            mm = tuple(jnp.full(S, (2**31 - 1) if is_min else -(2**31),
                                dtype=jnp.int32)
                       for is_min, _si in meta.scatter)
            return (jnp.zeros((layout.sh, layout.sl * nb), jnp.int32),
                    mm, jnp.asarray(True))

        def drain():
            nonlocal carry, bound
            if carry is None:
                return
            table, mm, ok = jax.device_get(carry)
            carry = None
            bound = 0
            if not bool(ok):
                raise _MxuVerifyFailed()
            presence, vals = mxu_agg.split_blocks(np.asarray(table), layout)
            wide_presence[:] += presence
            for i in range(len(wide_vals)):
                wide_vals[i][:] += vals[i]
            for i, (is_min, _si) in enumerate(meta.scatter):
                op = np.minimum if is_min else np.maximum
                wide_mm[i][:] = op(wide_mm[i], np.asarray(mm[i], np.int64))

        for cols_stacked, masks, count in _batch_windows(
                self._source.execute(partition),
                config.FUSED_FOLD_WINDOW.get()):
            wrows = int(masks.shape[0]) * int(masks.shape[1])
            if wrows > mxu_agg.MAX_ROWS_PER_TABLE:
                # a single window breaching the int32 exactness bound
                # cannot drain mid-fold; nothing has been emitted, so
                # the scatter strategy re-runs the partition losslessly
                raise _MxuVerifyFailed()
            if bound + wrows > mxu_agg.MAX_ROWS_PER_TABLE:
                drain()
            if carry is None:
                carry = fresh_carry()
            carry = fold(carry, cols_stacked, masks)
            bound += wrows
            n_batches += count
        drain()
        self.metrics.add("fused_batches", n_batches)
        self.metrics.add("mxu_rows", int(wide_presence.sum()))

        slots = np.nonzero(wide_presence)[0]
        if len(slots) == 0:
            return
        keys = unpack_dense_keys(slots, self._ranges, xp=np)
        accs: List[np.ndarray] = []
        avalid: List[np.ndarray] = []
        ones = np.ones(len(slots), dtype=bool)
        for sp in meta.specs:
            if sp.kind == "count_star":
                accs.append(wide_presence[slots])
                avalid.append(ones)
            elif sp.kind == "count":
                accs.append(wide_vals[sp.arr_valid][slots])
                avalid.append(ones)
            elif sp.kind == "sum":
                vc = wide_vals[sp.arr_valid][slots]
                tot = wide_vals[sp.arr_cents][slots] + vc * sp.off
                accs.append(tot / sp.scale if sp.is_float else tot)
                avalid.append(vc > 0)
            else:  # min / max
                vc = wide_vals[sp.arr_valid][slots]
                raw = wide_mm[sp.scatter_idx][slots] + sp.off
                accs.append(raw / sp.scale if sp.is_float else raw)
                avalid.append(vc > 0)
        yield from self._emit_rows(keys, accs, avalid)

    # -- dense: no host syncs in the loop ----------------------------------
    def _execute_dense(self, partition: int) -> BatchIterator:
        num_slots = 1
        for lo, hi in self._ranges:
            num_slots *= (hi - lo + 2)
        kinds = [rk for rk, _ok, _a in self._specs]
        carry = None
        n_batches = 0
        if self._prepare is not None:
            # fold a WINDOW of batches through one XLA program: the
            # dispatch count drops by the window size and the carry is
            # updated in place inside the program (no per-batch
            # full-table copies — they dominated on backends without
            # donation and on tunneled devices)
            fold = _dense_fold_factory(self._prepare_key, self._prepare,
                                       tuple(self._ranges), tuple(kinds),
                                       num_slots)
            for cols_stacked, masks, count in _batch_windows(
                    self._source.execute(partition),
                    config.FUSED_FOLD_WINDOW.get()):
                if carry is None:
                    carry = _init_carry(kinds, self._acc_dtypes(),
                                        num_slots)
                carry = fold(carry, cols_stacked, masks)
                n_batches += count
        else:
            for batch in self.children[0].execute(partition):
                kd, kv, ad, av, mask = self._device_inputs(batch)
                step = self._dense_step(batch.capacity, num_slots,
                                        tuple(kinds))
                if carry is None:
                    carry = _init_carry(kinds, self._acc_dtypes(),
                                        num_slots)
                carry = step(carry, kd, kv, ad, av, mask)
                n_batches += 1
        self.metrics.add("fused_batches", n_batches)
        if carry is None:
            return
        yield from self._emit_dense(carry, num_slots)

    def _dense_step(self, capacity: int, num_slots: int, kinds):
        # the factory is memoized at module level so every task/plan
        # instance with the same (ranges, kinds, slots) shares one jit
        # cache — a fresh runtime per task must NOT recompile
        return _dense_step_factory(tuple(self._ranges), kinds, num_slots)

    @staticmethod
    def _drain_table(carry, num_slots: int):
        """Compact ON DEVICE before reading back: the table has
        num_slots entries (possibly millions) but only `count` occupied.
        Ship the occupied prefix, padded to a power-of-two bucket so XLA
        sees a handful of shapes instead of one per distinct count.
        Returns (host_accs, host_avalid, slots) trimmed to count, or
        None when the table is empty.  Shared by the dense and
        dict-device emit paths."""
        accs, avalid, occupied = carry
        count = int(jnp.sum(occupied))
        if count == 0:
            return None
        padded = _bucket(count, num_slots)
        # nonzero with a static size is an O(slots) scan (vs argsort's
        # full sort) and keeps slot order; entries past `count` are fill
        slots_dev = jnp.nonzero(occupied, size=padded, fill_value=0)[0]
        fetch = ([jnp.take(a, slots_dev) for a in accs],
                 [jnp.take(v, slots_dev) for v in avalid],
                 slots_dev)
        host_accs, host_avalid, slots = jax.device_get(fetch)
        return ([a[:count] for a in host_accs],
                [v[:count] for v in host_avalid], slots[:count])

    def _emit_dense(self, carry, num_slots: int) -> BatchIterator:
        drained = self._drain_table(carry, num_slots)
        if drained is None:
            return
        host_accs, host_avalid, slots = drained
        # slot -> key decode host-side (shared stride logic, no round trip)
        host_keys = unpack_dense_keys(slots, self._ranges, xp=np)
        yield from self._emit_rows(host_keys, host_accs, host_avalid)

    # -- var-width keys on device: dictionary-code dense strategy ----------
    # (VERDICT r4 #8 / SURVEY §7 hard-part #1: keep string group keys as
    # dense integer codes so the device never touches bytes — the
    # parquet-dictionary-code idea applied at the stage boundary)
    def _execute_dict_device(self, partition: int) -> BatchIterator:
        """Group by var-width keys ON DEVICE: every key column
        dictionary-encodes (host, vectorized pyarrow) against an
        accumulated per-key dictionary; the dense i32 codes pack into
        one group id and aggregate through the same sort-free
        scatter-reduce kernel as bounded int keys.  Dictionary growth
        past a key's power-of-two capacity re-lays the table out host-
        side (pure stride arithmetic) and recompiles once per doubling.
        Keys decode back through the dictionaries only at emit."""
        nkeys = len(self._group_exprs)
        kinds = tuple(rk for rk, _ok, _a in self._specs)
        dicts: List[Optional[pa.Array]] = [None] * nkeys
        caps = [16] * nkeys
        limit = config.FUSED_DICT_DEVICE_MAX_SLOTS.get()
        carry = None  # (accs, avalid, occupied) device arrays
        n_batches = 0

        def total_slots(cs):
            t = 1
            for c in cs:
                t *= (c + 1)  # +1: null slot per key (range 0..c-1)
            return t

        for batch in self.children[0].execute(partition):
            cap = batch.capacity
            sel = (batch.selected_mask() if batch.selection is not None
                   else None)
            code_cols = []
            grew = False
            for i, (e, _n) in enumerate(self._group_exprs):
                arr = e.evaluate(batch).to_host(batch.num_rows)
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
                codes, valid, dicts[i] = _global_dict_codes(
                    arr, dicts[i], cap, sel)
                while len(dicts[i]) > caps[i]:
                    caps[i] *= 2
                    grew = True
                code_cols.append((codes, valid))
            if total_slots(caps) > limit:
                raise _DictCapExceeded
            if grew and carry is not None:
                carry = _relayout_dict_table(carry, kinds,
                                             self._acc_dtypes(),
                                             old_caps, caps)
            old_caps = list(caps)
            ad, av = [], []
            for _rk, _ok, arg in self._specs:
                if arg is None:
                    ad.append(None)
                    av.append(None)
                else:
                    dv = arg.evaluate(batch).to_device(cap)
                    ad.append(_pad_lane(dv.data))
                    av.append(_pad_lane(dv.validity))
            mask = _pad_lane(batch.row_mask())
            pcap = mask.shape[0]
            if carry is None:
                carry = _init_carry(kinds, self._acc_dtypes(),
                                    total_slots(caps))
            step = _dict_dense_step(tuple(caps), kinds, pcap)
            kd = tuple(_pad_lane(c) for c, _v in code_cols)
            kv = tuple(_pad_lane(v) for _c, v in code_cols)
            carry = step(carry, kd, kv, tuple(ad), tuple(av), mask)
            n_batches += 1
        self.metrics.add("fused_batches", n_batches)
        self.metrics.add("dict_device_batches", n_batches)
        if carry is None:
            return
        yield from self._emit_dict(carry, caps, dicts)

    def _emit_dict(self, carry, caps, dicts) -> BatchIterator:
        num_slots = 1
        for c in caps:
            num_slots *= (c + 1)
        drained = self._drain_table(carry, num_slots)
        if drained is None:
            return
        host_accs, host_avalid, slots = drained
        count = len(slots)
        ranges = [(0, c - 1) for c in caps]
        decoded = unpack_dense_keys(slots, ranges, xp=np)
        out_arrow = self._out_schema.to_arrow()
        key_fields = [out_arrow.field(i) for i in range(len(dicts))]
        arrays: List[pa.Array] = []
        for (code, kvalid), d, f in zip(decoded, dicts, key_fields):
            idx = pa.array(np.where(kvalid, code, 0), pa.int64(),
                           mask=~kvalid)  # null code -> null key
            arrays.append(d.take(idx).cast(f.type))
        i = len(dicts)
        for (_rk, out_kind, _arg), a, v in zip(self._specs, host_accs,
                                               host_avalid):
            f = out_arrow.field(i)
            if out_kind == "count":
                arrays.append(_to_arrow(a[:count],
                                        np.ones(count, bool), f.type))
            else:
                arrays.append(_to_arrow(a[:count], v[:count], f.type))
            i += 1
        rb = pa.RecordBatch.from_arrays(arrays, schema=out_arrow)
        bs = config.BATCH_SIZE.get()
        for off in range(0, rb.num_rows, bs):
            chunk = rb.slice(off, min(bs, rb.num_rows - off))
            yield ColumnBatch.from_arrow(chunk)

    # -- unbounded keys: device open-addressing hash table -----------------
    # (ref agg_hash_map.rs; replaces the earlier sort-based table — a
    # multi-operand lax.sort program takes minutes to COMPILE on TPU and
    # the eager form blew the SF10 reduce-stage timeout outright)
    def _execute_sorted(self, partition: int) -> BatchIterator:
        slots = _pow2(config.ON_DEVICE_AGG_CAPACITY.get())
        kinds = tuple(rk for rk, _ok, _a in self._specs)
        carry = None
        skipping = False
        if self._prepare is not None:
            # prepare is INLINED into the step jit: one dispatch per batch
            # (a second program would pay another tunnel round trip and
            # materialize kd/kv/ad/av between programs)
            stream = self._source.execute(partition)
            lane = _hash_lane()
            raw_step = _hash_chain_step_factory(self._prepare_key,
                                                self._prepare, kinds, lane)
            step = lambda c, b: raw_step(c, *_source_inputs(b))  # noqa: E731
        else:
            stream = self.children[0].execute(partition)
            lane = _hash_lane()
            raw_step = _hash_step_jit(kinds, lane)
            step = lambda c, b: raw_step(  # noqa: E731
                c, *self._device_inputs(b))
        key_dtypes = [e.data_type(self._in_schema).jnp_dtype()
                      for e, _n in self._group_exprs]
        rows_seen = 0
        for batch in stream:
            if skipping:
                # batch-local dedup then pass through (downstream
                # re-merges) — ref AGG_TRIGGER_PARTIAL_SKIPPING,
                # agg_table.rs:108-122
                xla_stats.note_partial_agg_rows(batch.selected_count())
                yield from self._emit_hash(
                    self._insert_batch_local(step, key_dtypes, kinds,
                                             batch))
                continue
            rows_seen += batch.selected_count()
            if carry is None:
                carry = init_hash_carry(key_dtypes, kinds,
                                        self._acc_dtypes(), slots)
            new_carry, overflow, _ng = step(carry, batch)
            while int(overflow) > 0:
                if not self._grow:
                    new_carry = None
                    break
                # exact modes (final/merge/complete) DOUBLE and rehash —
                # the step is atomic, so carry is intact and lossless
                slots *= 2
                self.metrics.add("table_grown", 1)
                bigger, re_ovf, _ = _rehash_jit(kinds, slots, lane)(carry)
                if int(re_ovf) > 0:
                    continue  # rare probe clustering: double again
                carry = bigger
                new_carry, overflow, _ng = step(carry, batch)
            if new_carry is None:
                skipping = True
                self.metrics.add("partial_skipped", 1)
                xla_stats.note_partial_agg_skip(rows_seen)
                if carry is not None:
                    yield from self._emit_hash(carry)
                    carry = None
                yield from self._emit_hash(
                    self._insert_batch_local(step, key_dtypes, kinds,
                                             batch))
                continue
            carry = new_carry
        if carry is not None:
            yield from self._emit_hash(carry)

    def _insert_batch_local(self, step, key_dtypes, kinds, batch):
        """One batch into a fresh table (grow-on-overflow; a batch has at
        most capacity distinct groups, so this terminates)."""
        slots = _pow2(2 * batch.capacity)
        while True:
            local = init_hash_carry(key_dtypes, kinds,
                                    self._acc_dtypes(), slots)
            out, overflow, _ng = step(local, batch)
            if int(overflow) == 0:
                return out
            slots *= 2

    def _emit_hash(self, carry, key_dicts=None) -> BatchIterator:
        count = int(jnp.sum(carry.used))
        if count == 0:
            return
        padded = _bucket(count, carry.used.shape[0])
        sel = jnp.nonzero(carry.used, size=padded, fill_value=0)[0]
        keys_h, kvalid_h, accs_h, avalid_h = jax.device_get(
            ([jnp.take(k, sel) for k in carry.keys],
             [jnp.take(v, sel) for v in carry.key_valid],
             [jnp.take(a, sel) for a in carry.accs],
             [jnp.take(v, sel) for v in carry.acc_valid]))
        keys = [(kd[:count], kv[:count])
                for kd, kv in zip(keys_h, kvalid_h)]
        accs = [a[:count] for a in accs_h]
        avalid = [v[:count] for v in avalid_h]
        yield from self._emit_rows(keys, accs, avalid,
                                   key_dicts=key_dicts)

    # -- shared emission ----------------------------------------------------
    def _device_inputs(self, batch: ColumnBatch):
        cap = batch.capacity
        kd, kv = [], []
        for e, _name in self._group_exprs:
            dv = e.evaluate(batch).to_device(cap)
            kd.append(_pad_lane(dv.data))
            kv.append(_pad_lane(dv.validity))
        ad, av = [], []
        for _rk, _ok, arg in self._specs:
            if arg is None:
                ad.append(None)
                av.append(None)
            else:
                dv = arg.evaluate(batch).to_device(cap)
                ad.append(_pad_lane(dv.data))
                av.append(_pad_lane(dv.validity))
        return (tuple(kd), tuple(kv), tuple(ad), tuple(av),
                _pad_lane(batch.row_mask()))

    def _emit_rows(self, keys, accs, avalid,
                   key_dicts=None) -> BatchIterator:
        n = len(accs[0]) if accs else len(keys[0][0])
        arrays: List[pa.Array] = []
        out_arrow = self._out_schema.to_arrow()
        i = 0
        for j, ((kd, kv), f) in enumerate(zip(keys, out_arrow)):
            d = key_dicts[j] if key_dicts is not None else None
            if d is not None:
                # dict-encoded key: the table folded int32 codes; decode
                # through the stream's final dictionary snapshot (its
                # prefix covers every code of every earlier batch)
                idx = pa.array(np.where(kv, kd.astype(np.int64), 0),
                               pa.int64(), mask=~kv)
                arrays.append(d.take(idx).cast(f.type))
            else:
                arrays.append(_to_arrow(kd, kv, f.type))
            i += 1
        for (_rk, out_kind, _arg), a, v in zip(self._specs, accs, avalid):
            f = out_arrow.field(i)
            if out_kind == "count":
                # count never nulls, whether counted or summed from accs
                arrays.append(_to_arrow(a, np.ones(n, dtype=bool), f.type))
            else:
                arrays.append(_to_arrow(a, v, f.type))
            i += 1
        rb = pa.RecordBatch.from_arrays(arrays, schema=out_arrow)
        bs = config.BATCH_SIZE.get()
        for off in range(0, rb.num_rows, bs):
            chunk = rb.slice(off, min(bs, rb.num_rows - off))
            yield ColumnBatch.from_arrow(chunk)


import functools

from blaze_tpu.batch import DeviceColumn


def _pad_lane(a):
    """Pad a host-resident (numpy) array up to its capacity bucket before
    it enters a jit program — unpadded lengths would compile one program
    per distinct tail-batch size; the geometric ladder bounds the set of
    static shapes every stage kernel ever sees (batch.bucket_capacity)."""
    if not isinstance(a, np.ndarray):
        return a
    from blaze_tpu.batch import bucket_capacity
    cap = bucket_capacity(a.shape[0])
    if cap == a.shape[0]:
        return a
    return np.pad(a, (0, cap - a.shape[0]))


def _source_inputs(batch: ColumnBatch):
    """Flatten a source batch for the jit step: device columns become
    (data, validity) pairs; host (string) columns pass as None — any
    expression touching one failed the pre-trace and never reaches here."""
    cols_flat = tuple((_pad_lane(c.data), _pad_lane(c.validity))
                      if isinstance(c, DeviceColumn) else None
                      for c in batch.columns)
    return cols_flat, _pad_lane(batch.row_mask())


def _make_prepare(source_schema: Schema, chain, group_exprs, specs):
    """The in-graph chain evaluator: rebuild the batch from traced arrays,
    run filter/project expression trees, emit key/agg device columns."""
    def prepare(cols_flat, mask):
        cap = mask.shape[0]
        cols = [DeviceColumn(f.data_type, cf[0], cf[1])
                if cf is not None else None
                for f, cf in zip(source_schema, cols_flat)]
        batch = ColumnBatch(source_schema, cols, cap, selection=mask)
        for kind, preds, exprs, out_schema in chain:
            if kind == "filter":
                m = None
                for p in preds:
                    pm = p.evaluate(batch).as_mask(batch)
                    m = pm if m is None else (m & pm)
                if m is not None:
                    batch = batch.with_selection(m)
            else:
                new_cols = [e.evaluate(batch).to_column(cap)
                            for e in exprs]
                batch = ColumnBatch(out_schema, new_cols, cap,
                                    batch.selection)
        kd, kv, ad, av = [], [], [], []
        for e, _name in group_exprs:
            v = e.evaluate(batch).to_device(cap)
            kd.append(v.data)
            kv.append(v.validity)
        for _rk, _ok, arg in specs:
            if arg is None:
                ad.append(None)
                av.append(None)
            else:
                v = arg.evaluate(batch).to_device(cap)
                ad.append(v.data)
                av.append(v.validity)
        return tuple(kd), tuple(kv), tuple(ad), tuple(av), batch.row_mask()
    return prepare


# key -> raw prepare fn | None when the chain doesn't trace
_PREPARE_CACHE: Dict = {}
_DENSE_STEP_CACHE: Dict = {}
_CACHE_LIMIT = 128  # bounded like _dense_step_factory's lru_cache


def _evict_if_full(cache: Dict) -> None:
    if len(cache) >= _CACHE_LIMIT:
        cache.pop(next(iter(cache)))  # FIFO: oldest compiled entry


def _utf8_ref_free(expr, schema: Schema) -> bool:
    """True when no BoundReference in the tree resolves to utf8 — inside
    a traced chain such a reference would see raw dictionary codes,
    whose comparison/order semantics are NOT string semantics."""
    if isinstance(expr, BoundReference):
        return schema[expr.index].data_type.id != TypeId.UTF8
    return all(_utf8_ref_free(c, schema) for c in expr.children())


def _dict_chain_safe(source_schema: Schema, chain, group_exprs,
                     specs) -> bool:
    """Static admission for tracing utf8 columns as int32 dictionary
    codes: codes may only PASS THROUGH (identity projections, bare group
    references) — never be computed on.  A filter, computed projection,
    or agg argument touching utf8 would trace successfully on codes but
    compute code-order semantics, so any such use rejects the chain and
    it keeps the eager/staged path."""
    sch = source_schema
    for kind, preds, exprs, out_schema in chain:
        if kind == "filter":
            if not all(_utf8_ref_free(p, sch) for p in preds):
                return False
        else:
            for e in exprs:
                if isinstance(e, BoundReference):
                    continue  # identity: codes flow through unchanged
                if not _utf8_ref_free(e, sch):
                    return False
            sch = out_schema
    for e, _n in group_exprs:
        if (e.data_type(sch).id == TypeId.UTF8
                and not isinstance(e, BoundReference)):
            return False
    for _rk, _ok, arg in specs:
        if arg is not None and not _utf8_ref_free(arg, sch):
            return False
    return True


def _dict_key_sources(agg):
    """Per-group-key SOURCE column indices for dict-encoded utf8 keys
    (None entries = plain fixed-width key), or None when the stage's
    var-width keys are not admissible as dictionary codes.  Each utf8
    key must be a bare reference whose chain lineage is identity
    projections all the way down — the source index is what the runtime
    loop watches for dictionaries."""
    if not config.ENCODING_DICT_ENABLE.get():
        return None
    out = []
    for e, _n in agg._group_exprs:
        dt = e.data_type(agg._in_schema)
        if dt.is_fixed_width:
            out.append(None)
            continue
        if dt.id != TypeId.UTF8 or not isinstance(e, BoundReference):
            return None
        idx = e.index
        for kind, _preds, exprs, _schema in reversed(agg._chain):
            if kind != "project":
                continue
            pe = exprs[idx]
            if not isinstance(pe, BoundReference):
                return None
            idx = pe.index
        out.append(idx)
    return tuple(out)


def _prepare_factory(key, source_schema: Schema, chain, group_exprs,
                     specs):
    if key in _PREPARE_CACHE:
        return _PREPARE_CACHE[key]
    _evict_if_full(_PREPARE_CACHE)
    prepare = _make_prepare(source_schema, chain, group_exprs, specs)
    dict_ok = (config.ENCODING_DICT_ENABLE.get()
               and _dict_chain_safe(source_schema, chain, group_exprs,
                                    specs))

    def _slot(f):
        if f.data_type.is_fixed_width:
            return (jax.ShapeDtypeStruct((128,), f.data_type.jnp_dtype()),
                    jax.ShapeDtypeStruct((128,), jnp.bool_))
        if dict_ok and f.data_type.id == TypeId.UTF8:
            # dict-encoded utf8: the program only ever sees int32 codes
            # (the runtime loop guards that every utf8 source column
            # actually arrives as a DictColumn, falling back otherwise)
            return (jax.ShapeDtypeStruct((128,), jnp.int32),
                    jax.ShapeDtypeStruct((128,), jnp.bool_))
        return None

    try:
        fake_cols = tuple(_slot(f) for f in source_schema)
        jax.eval_shape(prepare, fake_cols,
                       jax.ShapeDtypeStruct((128,), jnp.bool_))
        result = prepare  # consumers inline it into their own jit step
    except Exception:
        result = None  # strings / host-only exprs: stay on the eager path
    _PREPARE_CACHE[key] = result
    return result


def _batch_windows(stream, window: int):
    """Stack up to `window` source batches into (cols_stacked, masks,
    count) with uniform capacity (tail batches pad with masked lanes)."""
    buf = []
    for batch in stream:
        buf.append(_source_inputs(batch))
        if len(buf) >= window:
            yield _stack_window(buf)
            buf = []
    if buf:
        yield _stack_window(buf)


def _stack_window(items):
    cap = max(m.shape[0] for _c, m in items)

    def padto(a):
        if a.shape[0] == cap:
            return a
        widths = [(0, cap - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    masks = jnp.stack([padto(m) for _c, m in items])
    ncols = len(items[0][0])
    cols = []
    for i in range(ncols):
        if items[0][0][i] is None:
            cols.append(None)
        else:
            cols.append((jnp.stack([padto(c[i][0]) for c, _m in items]),
                         jnp.stack([padto(c[i][1]) for c, _m in items])))
    return tuple(cols), masks, len(items)


def _dense_fold_factory(key, prepare, ranges, kinds, num_slots: int):
    """ONE XLA program folding a whole window of batches into the carry
    (fori_loop keeps the carry in place inside the program)."""
    skey = ("fold", key, ranges, kinds, num_slots)
    fold = _DENSE_STEP_CACHE.get(skey)
    if fold is not None:
        return fold
    _evict_if_full(_DENSE_STEP_CACHE)

    def fold_impl(carry, cols_stacked, masks):
        def body(b, c):
            cols_b = tuple(
                None if col is None else (col[0][b], col[1][b])
                for col in cols_stacked)
            kd, kv, ad, av, m = prepare(cols_b, masks[b])
            gid, _total = pack_dense_keys(list(zip(kd, kv)), list(ranges))
            return _scatter_into_carry(c, gid, kinds, ad, av, m,
                                       num_slots)
        return jax.lax.fori_loop(0, masks.shape[0], body, carry)

    fold = meter_jit(fold_impl, name="fused.dense_fold", donate_argnums=0)
    fold.raw = fold_impl  # see _mxu_fold_factory: embeddable traced body
    _DENSE_STEP_CACHE[skey] = fold
    return fold


def _mxu_fold_factory(key, prepare, ranges, meta: _MxuMeta,
                      use_pallas: bool):
    """ONE XLA program folding a window of batches through the MXU
    histogram kernel (kernels/mxu_agg.py).  The whole chain — filter/
    project, i32 group-id packing, fixed-point limb extraction, the
    matmul table update and the min/max scatters — lowers into a single
    dispatch; no 64-bit op survives into the hot loop except the one
    `value - offset` shift per aggregated column."""
    from blaze_tpu.kernels import mxu_agg
    from blaze_tpu.parallel.stage import pack_dense_keys_i32

    skey = ("mxu", key, ranges, meta, use_pallas)
    fold = _DENSE_STEP_CACHE.get(skey)
    if fold is not None:
        return fold
    _evict_if_full(_DENSE_STEP_CACHE)
    layout = meta.layout
    sentinel = jnp.int32(layout.num_slots)

    def fold_impl(carry, cols_stacked, masks):
        def body(b, c):
            table, mm_accs, ok = c
            cols_b = tuple(
                None if col is None else (col[0][b], col[1][b])
                for col in cols_stacked)
            kd, kv, ad, av, m = prepare(cols_b, masks[b])
            gid, _total = pack_dense_keys_i32(list(zip(kd, kv)),
                                              list(ranges))
            gid = jnp.where(m, gid, sentinel)
            valids = {}
            cents = {}
            for si, sp in enumerate(meta.specs):
                if sp.kind == "count_star":
                    continue
                v = av[si]
                valids[si] = v if v is not None else jnp.ones_like(m)
                if sp.kind == "count":
                    continue
                data = ad[si]
                if sp.is_float:
                    scale = float(sp.scale)
                    c = jnp.rint(data * scale)
                    # fixed-point verify WITHOUT division: XLA may fold
                    # `c / scale == data` into a reciprocal multiply
                    # (excess precision), breaking FP equality.  A
                    # genuine scaled value satisfies |v*s - rint(v*s)|
                    # <= |c| * 4.5e-16 (two roundings); 1e-12 leaves a
                    # 2000x margin while any dirt it admits perturbs
                    # the sum below 1e-12 relative — under the 1e-9
                    # result comparator by three orders.
                    exact = (jnp.abs(data * scale - c)
                             <= (jnp.abs(c) + 1.0) * 1e-12)
                    exact = exact | ~valids[si] | ~m
                    ok = ok & exact.all()
                    cents[si] = (c - sp.off).astype(jnp.int32)
                else:
                    cents[si] = (data.astype(jnp.int64) - sp.off
                                 ).astype(jnp.int32)
            arrays = []
            for akind, si in meta.arrays:
                if akind == "valid":
                    arrays.append((valids[si] & m).astype(jnp.int32))
                else:
                    arrays.append(jnp.where(valids[si], cents[si], 0))
            table = table + mxu_agg.window_table(
                gid, arrays, layout, force_ref=not use_pallas)
            new_mm = []
            for (is_min, si), acc in zip(meta.scatter, mm_accs):
                ident = jnp.int32((2**31 - 1) if is_min else -(2**31))
                val = jnp.where(valids[si] & m, cents[si], ident)
                if is_min:
                    acc = acc.at[gid].min(val, mode="drop")
                else:
                    acc = acc.at[gid].max(val, mode="drop")
                new_mm.append(acc)
            return (table, tuple(new_mm), ok)
        return jax.lax.fori_loop(0, masks.shape[0], body, carry)

    fold = meter_jit(fold_impl, name="fused.mxu_fold", donate_argnums=0)
    # raw traced body, for callers embedding the fold in a larger
    # program (bench device loop): a nested-jit call boundary inside a
    # fori_loop defeats XLA's cross-stage fusion on TPU (~10x slower)
    fold.raw = fold_impl
    _DENSE_STEP_CACHE[skey] = fold
    return fold


@functools.lru_cache(maxsize=128)
def _dense_step_factory(ranges, kinds, num_slots: int):
    ranges = list(ranges)

    @partial(meter_jit, name="fused.dense_step", donate_argnums=0)
    def step(carry, key_data, key_valid, agg_data, agg_valid, mask):
        gid, _total = pack_dense_keys(list(zip(key_data, key_valid)),
                                      ranges)
        return _scatter_into_carry(carry, gid, kinds, agg_data, agg_valid,
                                   mask, num_slots)

    return step


def _scatter_into_carry(carry, gid, kinds, agg_data, agg_valid, mask,
                        num_slots: int):
    """In-place (donated) scatter update: O(batch) work per step instead of
    materializing and merging a full O(num_slots) per-batch table.  The
    accumulate switch itself is shared with the hash table
    (stage.scatter_accumulate) so null/identity semantics stay in one
    place."""
    accs, avalid, occupied = carry
    g = jnp.where(mask, gid, num_slots)  # masked rows drop out of range
    occupied = occupied.at[g].max(mask, mode="drop")
    specs = [(k, d, v) for k, d, v in zip(kinds, agg_data, agg_valid)]
    new_a, new_v = scatter_accumulate(g, specs, mask, accs, avalid)
    return (tuple(new_a), tuple(new_v), occupied)


def _init_carry(kinds, acc_dtypes, num_slots: int):
    accs, avalid = init_accumulators(kinds, acc_dtypes, num_slots)
    occupied = jnp.zeros(num_slots, dtype=bool)
    return (accs, avalid, occupied)


class _DictCapExceeded(Exception):
    """Dict-device code table would exceed maxSlots; caller falls back."""


def _global_dict_codes(arr: pa.Array, global_arr: Optional[pa.Array],
                       cap: int, sel: Optional[np.ndarray] = None):
    """Fused-stage wrapper over the SHARED incremental encoder
    (ops/agg/exec.py incremental_dict_codes): i32 codes for the
    pack_dense_keys_i32 tier, and filter-DESELECTED rows nulled out
    BEFORE encoding so they can neither grow the dictionary (spurious
    _DictCapExceeded on selective filters) nor inflate the code table
    capacity — the agg mask drops them from the reduction anyway."""
    from blaze_tpu.ops.agg.exec import incremental_dict_codes
    if sel is not None and not sel.all():
        import pyarrow.compute as pc
        arr = pc.if_else(pa.array(sel[:len(arr)]), arr,
                         pa.nulls(len(arr), arr.type))
    codes, valid, global_arr, _grew = incremental_dict_codes(
        arr, global_arr, cap)
    return codes.astype(np.int32), valid, global_arr


def _relayout_dict_table(carry, kinds, acc_dtypes, old_caps, new_caps):
    """Move a dict-code dense table to a larger layout after dictionary
    growth: decode occupied slots to per-key codes (pure stride math,
    host-side), recompute slot ids under the new strides, scatter accs
    1:1 (codes are unique per slot, no merging)."""
    accs, avalid, occupied = jax.device_get(carry)
    occ = np.nonzero(occupied)[0]
    old_ranges = [(0, c - 1) for c in old_caps]
    decoded = unpack_dense_keys(occ, old_ranges, xp=np)
    new_total = 1
    strides = []
    for c in new_caps:
        strides.append(new_total)
        new_total *= (c + 1)
    new_slot = np.zeros(len(occ), dtype=np.int64)
    for (code, kvalid), c, stride in zip(decoded, new_caps, strides):
        k = np.where(kvalid, code, c)  # null slot is code==cap
        new_slot += k * stride
    n_accs, n_avalid = [], []
    from blaze_tpu.parallel.stage import init_accumulators
    fresh_accs, fresh_avalid = init_accumulators(kinds, acc_dtypes,
                                                 new_total)
    for fa, a in zip(fresh_accs, accs):
        na = np.asarray(fa).copy()
        na[new_slot] = a[occ]
        n_accs.append(jnp.asarray(na))
    for fv, v in zip(fresh_avalid, avalid):
        nv = np.asarray(fv).copy()
        nv[new_slot] = v[occ]
        n_avalid.append(jnp.asarray(nv))
    n_occ = np.zeros(new_total, dtype=bool)
    n_occ[new_slot] = True
    return (tuple(n_accs), tuple(n_avalid), jnp.asarray(n_occ))


@functools.lru_cache(maxsize=64)
def _dict_dense_step(caps: tuple, kinds: tuple, capacity: int):
    """One jit program per (caps, kinds, capacity): pack the per-key
    codes into a dense group id and fold the batch into the carry —
    combine is elementwise (slots are stable), so the carry never
    round-trips to host between batches."""
    from blaze_tpu.parallel.stage import (_identity, dense_partial_agg,
                                          pack_dense_keys_i32)
    ranges = tuple((0, c - 1) for c in caps)

    @partial(meter_jit, name="fused.dict_device_step")
    def step(carry, kd, kv, ad, av, mask):
        accs, avalid, occupied = carry
        gid, total = pack_dense_keys_i32(list(zip(kd, kv)), ranges)
        specs = [(k, a, v) for k, a, v in zip(kinds, ad, av)]
        b_accs, b_avalid, b_occ = dense_partial_agg(
            gid.astype(jnp.int64), total, specs, mask)
        out_accs, out_avalid = [], []
        for kind, ca, cv, ba, bv in zip(kinds, accs, avalid,
                                        b_accs, b_avalid):
            if kind in ("sum", "count"):
                out_accs.append(ca + ba)  # empty batch slots are 0
            elif kind == "min":
                # dense_partial_agg ZEROES empty slots — re-identity
                # them or a later batch drags every min toward 0
                ba = jnp.where(bv, ba, _identity(ba.dtype, False))
                out_accs.append(jnp.minimum(ca, ba))
            else:  # max
                ba = jnp.where(bv, ba, _identity(ba.dtype, True))
                out_accs.append(jnp.maximum(ca, ba))
            out_avalid.append(cv | bv)
        return (tuple(out_accs), tuple(out_avalid), occupied | b_occ)

    return step


def _bucket(count: int, cap: int) -> int:
    """Next power of two >= count (min 1024), clamped to cap — keeps the
    device slice shapes to a handful of variants."""
    b = 1024
    while b < count:
        b <<= 1
    return min(b, cap)


def _pow2(n: int) -> int:
    return max(16, 1 << (int(n) - 1).bit_length())


def _hash_lane() -> str:
    """Resolve the probe/claim lane ONCE per dispatch site (host-side,
    kernels/lane.py) — it then rides every cache key below so flipping
    `auron.tpu.kernels.pallas` retraces instead of reusing a stale
    program."""
    from blaze_tpu.kernels import lane as lane_mod
    return lane_mod.resolve("hash")


@functools.lru_cache(maxsize=128)
def _hash_step_jit(kinds, lane: str = "scatter"):
    """One compiled program per batch: probe-insert + scatter-accumulate
    into the device hash table (kernels in parallel/stage.py)."""
    def f(carry, kd, kv, ad, av, mask):
        specs = [(k, d, v) for k, d, v in zip(kinds, ad, av)]
        return hash_agg_step(carry, list(zip(kd, kv)), specs, mask,
                             lane=lane)
    return meter_jit(f, name="fused.hash_step")


@functools.lru_cache(maxsize=128)
def _rehash_jit(kinds, new_slots: int, lane: str = "scatter"):
    return meter_jit(lambda c: rehash_carry(c, list(kinds), new_slots,
                                            lane=lane),
                     name="fused.rehash")


def _hash_chain_step_factory(key, prepare, kinds, lane: str = "scatter"):
    """Chain + probe-insert + accumulate as ONE compiled program."""
    skey = ("hash", key, kinds, lane)
    step = _DENSE_STEP_CACHE.get(skey)
    if step is not None:
        return step
    _evict_if_full(_DENSE_STEP_CACHE)

    @partial(meter_jit, name="fused.hash_chain_step")
    def step(carry, cols_flat, mask):
        kd, kv, ad, av, m = prepare(cols_flat, mask)
        specs = [(k, d, v) for k, d, v in zip(kinds, ad, av)]
        return hash_agg_step(carry, list(zip(kd, kv)), specs, m,
                             lane=lane)

    _DENSE_STEP_CACHE[skey] = step
    return step


def _to_arrow(data: np.ndarray, valid: np.ndarray,
              t: pa.DataType) -> pa.Array:
    arr = pa.array(data, mask=~np.asarray(valid, dtype=bool))
    if not arr.type.equals(t):
        arr = arr.cast(t, safe=False)
    return arr
