"""DataType / Schema serde for the plan IR.

Parity: the ArrowType serde section of the reference proto
(ref auron-planner/proto/auron.proto:825-988) — each logical type maps to a
JSON-friendly dict so any engine front-end (the AuronSparkSessionExtension
layer) can emit plans without Arrow IPC machinery.  A protobuf binding can
map these dicts 1:1 onto the reference's messages.
"""

from __future__ import annotations

from typing import Any, Dict

from blaze_tpu.schema import DataType, Field, Schema, TypeId


def type_to_dict(t: DataType) -> Dict[str, Any]:
    out: Dict[str, Any] = {"id": t.id.value}
    if t.id == TypeId.DECIMAL:
        out["precision"] = t.precision
        out["scale"] = t.scale
    if t.children:
        out["children"] = [field_to_dict(f) for f in t.children]
    return out


def type_from_dict(d: Dict[str, Any]) -> DataType:
    tid = TypeId(d["id"])
    children = tuple(field_from_dict(c) for c in d.get("children", ()))
    return DataType(tid, d.get("precision", 0), d.get("scale", 0), children)


def field_to_dict(f: Field) -> Dict[str, Any]:
    return {"name": f.name, "type": type_to_dict(f.data_type),
            "nullable": f.nullable}


def field_from_dict(d: Dict[str, Any]) -> Field:
    return Field(d["name"], type_from_dict(d["type"]), d.get("nullable", True))


def schema_to_dict(s: Schema) -> Dict[str, Any]:
    return {"fields": [field_to_dict(f) for f in s]}


def schema_from_dict(d: Dict[str, Any]) -> Schema:
    return Schema([field_from_dict(f) for f in d["fields"]])
