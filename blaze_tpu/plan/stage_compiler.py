"""Stage compiler: eligible fused-agg pipelines -> StageProgram.

The whole-stage expression pass (exprs/program.py, PR 3) collapsed
filter/project chains into one program *per batch*; the fused-agg pass
(plan/fused.py) made the agg loop body one program *per batch*.  Both
still pay a Python dispatch per batch x operator.  This pass walks a map
stage's operator chain and, when the whole post-scan pipeline is
traceable, emits a `StageProgram` the device-resident loop
(runtime/loop.py) folds in chunks — ONE dispatch per chunk of batches,
the Flare whole-stage-compilation analog.

Eligibility (anything else stays on the staged per-batch executor):
  * the stage root (under CoalesceBatches re-batching) is a
    FusedPartialAggExec on the HASH lane (`_ranges is None`) — the dense
    lane already has its own windowed fold (fused.dense_fold);
  * the filter/project chain traced (`_prepare` survived the
    jax.eval_shape probe — no strings / host-only exprs in the chain);
  * every group key is fixed-width (utf8 keys belong to the Arrow host
    lane), and there is at least one group key;
  * the source plan is re-executable, so a wholesale fallback can re-run
    the partition from scratch losslessly (the loop emits nothing until
    its final drain).

One `StageProgram` fingerprint = (chain cache key, reduce kinds, key
dtypes, acc dtypes, grow mode).  The loop's fold program is cached per
fingerprint; capacity rungs and chunk widths become jit signatures
inside that one program, so steady state sees zero recompiles
(stage_loop_programs_built / stage_loop_program_cache_hits account the
fingerprint-level lookups).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Optional, Tuple

from blaze_tpu import config
from blaze_tpu.bridge import xla_stats

_retry_local = threading.local()


class decline_loop_scope:
    """`with decline_loop_scope():` — stage_loop_active() is False on
    this thread for the duration.  The task retry loop (bridge/tasks.py)
    wraps attempts 2..n in it: a retried task takes the most
    conservative path, since the loop is an optimization and was live
    during the attempt that just failed."""

    def __enter__(self):
        _retry_local.decline = getattr(_retry_local, "decline", 0) + 1

    def __exit__(self, *exc):
        _retry_local.decline -= 1
        return False


class StageLoopIneligible(RuntimeError):
    """The stage does not compile to a device-resident loop; the caller
    uses the staged per-batch executor (not an error, a verdict)."""


@dataclass(frozen=True)
class StageProgram:
    """A compiled stage pipeline the runtime loop can fold.

    `agg` is the live FusedPartialAggExec the program was compiled from:
    it owns the source plan, the output schema and the drain/emission
    helpers; everything the jit'd fold body needs (prepare fn, reduce
    kinds, dtypes) is captured here so the fold cache never keys on the
    plan instance.
    """
    agg: Any                       # FusedPartialAggExec
    prepare: Any                   # traced chain evaluator (fused._make_prepare)
    prepare_key: Any               # fused chain cache key
    kinds: Tuple[str, ...]         # reduce kinds per agg spec
    key_dtypes: Tuple[Any, ...]    # jnp dtypes of the group keys
    acc_dtypes: Tuple[Any, ...]    # jnp dtypes of the accumulators
    grow: bool                     # exact modes grow the table on overflow
    fingerprint: Tuple             # process-wide program identity
    # per-group-key SOURCE column index when the key is dict-encoded
    # utf8 (codes fold as int32; the loop captures each stream's last
    # dictionary to decode the drain); None entries are plain keys
    dict_keys: Tuple[Any, ...] = ()

    @property
    def source(self):
        return self.agg._source

    @property
    def out_schema(self):
        return self.agg.schema


def stage_loop_mode() -> str:
    return config.STAGE_DEVICE_LOOP_ENABLE.get().strip().lower()


def stage_loop_active() -> bool:
    """'on' forces the loop wherever it compiles (tests/bench on CPU
    hosts); 'auto' runs it only for device-resident compute, where the
    per-batch dispatch RTT it amortizes actually exists — on host
    placement the staged Arrow lanes win."""
    if getattr(_retry_local, "decline", 0) > 0:
        return False
    mode = stage_loop_mode()
    if mode == "on":
        return True
    if mode != "auto":
        return False
    from blaze_tpu.bridge.placement import host_resident
    return not host_resident()


# insertion-ordered; bounded like fused._PREPARE_CACHE
_SEEN_FINGERPRINTS: dict = {}
_SEEN_LIMIT = 256


def compile_fused_agg(agg) -> StageProgram:
    """StageProgram for one FusedPartialAggExec, or StageLoopIneligible
    with the reason (surfaced in explain / tracing)."""
    from blaze_tpu.plan.fused import FusedPartialAggExec
    if not isinstance(agg, FusedPartialAggExec):
        raise StageLoopIneligible(f"stage root {type(agg).__name__} is "
                                  "not a fused partial agg")
    if agg._ranges is not None:
        raise StageLoopIneligible("dense lane has its own windowed fold")
    dict_keys: Tuple[Any, ...] = ()
    if agg._has_var_keys:
        from blaze_tpu.plan.fused import _dict_key_sources
        admitted = _dict_key_sources(agg)
        if admitted is None:
            # a string key just evicted this stage from the device loop
            xla_stats.note_encoding(host_evictions_string=1)
            raise StageLoopIneligible("variable-width group keys")
        dict_keys = admitted
    if agg._prepare is None:
        raise StageLoopIneligible("filter/project chain did not trace")
    if not agg._group_exprs:
        raise StageLoopIneligible("no group keys")
    if not getattr(agg._source, "reexecutable", True):
        raise StageLoopIneligible("source is not re-executable: wholesale "
                                  "fallback could not re-run the partition")
    import jax.numpy as jnp
    kinds = tuple(rk for rk, _ok, _a in agg._specs)
    key_dtypes = tuple(
        jnp.int32 if dict_keys and dict_keys[i] is not None
        else e.data_type(agg._in_schema).jnp_dtype()
        for i, (e, _n) in enumerate(agg._group_exprs))
    acc_dtypes = tuple(agg._acc_dtypes())
    fingerprint = (agg._prepare_key, kinds,
                   tuple(str(d) for d in key_dtypes),
                   tuple(str(d) for d in acc_dtypes), bool(agg._grow),
                   dict_keys)
    hit = fingerprint in _SEEN_FINGERPRINTS
    xla_stats.note_stage_program(cache_hit=hit)
    if not hit:
        if len(_SEEN_FINGERPRINTS) >= _SEEN_LIMIT:
            _SEEN_FINGERPRINTS.pop(next(iter(_SEEN_FINGERPRINTS)))
        _SEEN_FINGERPRINTS[fingerprint] = True
    return StageProgram(agg=agg, prepare=agg._prepare,
                        prepare_key=agg._prepare_key, kinds=kinds,
                        key_dtypes=key_dtypes, acc_dtypes=acc_dtypes,
                        grow=bool(agg._grow), fingerprint=fingerprint,
                        dict_keys=dict_keys)


def try_compile(agg) -> Optional[StageProgram]:
    """compile_fused_agg, with ineligibility as None (the common caller
    shape: `prog = try_compile(agg); if prog is None: staged path`)."""
    try:
        return compile_fused_agg(agg)
    except StageLoopIneligible:
        return None


def compile_task_plan(plan) -> Optional[StageProgram]:
    """Stage-level entry for the scheduler: unwrap re-batching nodes and
    compile the stage root.  None = run the staged per-batch executor."""
    if not stage_loop_active():
        return None
    from blaze_tpu.plan.planner import CoalesceBatchesExec
    node = plan
    while isinstance(node, CoalesceBatchesExec):
        node = node.children[0]
    return try_compile(node)
