"""Per-fingerprint observed-statistics store — the feedback half of the
adaptive-execution loop (ROADMAP item 1).

At query finish the DAG scheduler hands this module one *observation*:
the plan fingerprint (plan/fingerprint.py), per-shuffle-boundary
partition bytes lifted from the map-output table before cleanup, task
duration samples from the xla_stats reservoirs, and the counter deltas
that carry agg-probe ratios, cache hit rates, and host-lane eviction
evidence.  Observations merge into one bounded JSONL record per
fingerprint under <history dir>/stats, so the Nth run of a recurring
query reads sharper priors than the first: quantiles come from
bounded-error mergeable sketches, ratios from accumulated tallies.

Design rules, shared with bridge/history.py:

- Off by default (`auron.tpu.stats.enable`); the probe is lazy and
  disabled sites pay one boolean — zero writes, zero allocation.
- Module scope imports nothing heavy (no jax, no pyarrow): the store
  must be readable from tooling on a machine with neither.
- Deterministic replay: a record is the *last valid JSON line* of its
  fingerprint file; torn trailing lines (crash mid-append) are skipped.
  Re-serializing a replayed record is byte-identical to what was
  written (plain dict/list/float JSON, sorted keys).

The quantile sketch is a deliberately simple mergeable centroid list
(value, weight pairs kept sorted; nearest-neighbour collapse past the
centroid budget).  With budget K the rank error is bounded by the
largest collapsed weight fraction — ~1/K of total weight per merge
step — which is plenty for "is partition 7 really 12x the median"
decisions, and unlike t-digest it is exactly reproducible from its
JSON form.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "STATS_SCHEMA_VERSION", "enabled", "reset_conf_probe", "stats_dir",
    "sketch_new", "sketch_add", "sketch_merge", "sketch_quantile",
    "ingest", "prior", "StatStore",
]

STATS_SCHEMA_VERSION = 1

#: counter deltas an observation may carry; everything else is dropped
#: at ingest so record size stays bounded by this schema, not by what
#: future counter families happen to exist.
INGEST_COUNTERS = (
    "partial_agg_probe_rows", "partial_agg_probe_groups",
    "partial_agg_skip_events",
    "expr_programs_built", "expr_program_cache_hits",
    "expr_fused_batches", "expr_eager_batches",
    "stage_loop_programs_built", "stage_loop_program_cache_hits",
    "stage_loop_fallbacks", "scatter_lane_declines",
    "shuffle_device_bytes", "shuffle_host_bytes",
    "shuffle_barrier_idle_ns", "shuffle_device_overlap_exchanges",
    "aqe_rewrites", "aqe_bytes_saved", "aqe_history_seeds",
    "dict_encoded_columns", "dict_exchange_remaps",
    "decimal_scaled_int32_dispatches", "decimal_scaled_int64_dispatches",
    "decimal_limb_dispatches",
    "host_evictions_string", "host_evictions_decimal",
    "host_evictions_other",
)

#: appended lines per fingerprint file before it is compacted down to
#: its single latest merged record (bounds file growth; replay only
#: ever needs the last line).
_MAX_LINES = 8

_lock = threading.Lock()
_enabled = False
_conf_probed = False  # lazy one-shot auron.tpu.stats.enable probe


def _probe_conf() -> None:
    global _conf_probed, _enabled
    with _lock:
        if _conf_probed:
            return
        _conf_probed = True
    try:
        from blaze_tpu import config
        if config.STATS_ENABLE.get():
            _enabled = True
    except Exception:
        pass


def enabled() -> bool:
    """One near-free boolean at every emit site once probed (the
    auron.tpu.trace.enable pattern)."""
    if not _conf_probed:
        _probe_conf()
    return _enabled


def reset_conf_probe() -> None:
    """Test helper: forget the probe so the next call re-reads
    `auron.tpu.stats.enable`."""
    global _conf_probed, _enabled
    with _lock:
        _conf_probed = False
        _enabled = False


def stats_dir() -> str:
    """Resolved store directory (auron.tpu.stats.dir; empty rides the
    history dir so one retention story covers both)."""
    try:
        from blaze_tpu import config
        d = config.STATS_DIR.get()
    except Exception:
        d = ""
    if d:
        return d
    from blaze_tpu.bridge import history
    return os.path.join(history.history_dir(), "stats")


def _max_fingerprints() -> int:
    try:
        from blaze_tpu import config
        return max(1, config.STATS_MAX_FINGERPRINTS.get())
    except Exception:
        return 256


def _centroid_budget() -> int:
    try:
        from blaze_tpu import config
        return max(4, config.STATS_SKETCH_CENTROIDS.get())
    except Exception:
        return 64


# ---------------------------------------------------------------------------
# Quantile sketch: sorted (value, weight) centroids, mergeable, bounded.
# ---------------------------------------------------------------------------

def sketch_new() -> Dict[str, Any]:
    return {"centroids": [], "count": 0, "min": None, "max": None}


def _compress(centroids: List[List[float]], budget: int
              ) -> List[List[float]]:
    """Collapse the closest adjacent pair (weighted mean) until within
    budget.  Ties break to the leftmost pair, so compression — and
    therefore every on-disk record — is deterministic."""
    cs = sorted(([float(v), float(w)] for v, w in centroids),
                key=lambda c: c[0])
    while len(cs) > budget:
        best, best_gap = 0, None
        for i in range(len(cs) - 1):
            gap = cs[i + 1][0] - cs[i][0]
            if best_gap is None or gap < best_gap:
                best, best_gap = i, gap
        a, b = cs[best], cs[best + 1]
        w = a[1] + b[1]
        cs[best:best + 2] = [[(a[0] * a[1] + b[0] * b[1]) / w, w]]
    return cs


def sketch_add(sk: Dict[str, Any], values: Iterable[float],
               budget: Optional[int] = None) -> Dict[str, Any]:
    vals = [float(v) for v in values]
    if not vals:
        return sk
    budget = budget or _centroid_budget()
    cs = list(sk.get("centroids") or []) + [[v, 1.0] for v in vals]
    sk["centroids"] = _compress(cs, budget)
    sk["count"] = int(sk.get("count") or 0) + len(vals)
    lo, hi = min(vals), max(vals)
    sk["min"] = lo if sk.get("min") is None else min(float(sk["min"]), lo)
    sk["max"] = hi if sk.get("max") is None else max(float(sk["max"]), hi)
    return sk


def sketch_merge(a: Dict[str, Any], b: Dict[str, Any],
                 budget: Optional[int] = None) -> Dict[str, Any]:
    budget = budget or _centroid_budget()
    out = sketch_new()
    cs = list(a.get("centroids") or []) + list(b.get("centroids") or [])
    out["centroids"] = _compress(cs, budget) if cs else []
    out["count"] = int(a.get("count") or 0) + int(b.get("count") or 0)
    mins = [x["min"] for x in (a, b) if x.get("min") is not None]
    maxs = [x["max"] for x in (a, b) if x.get("max") is not None]
    out["min"] = min(mins) if mins else None
    out["max"] = max(maxs) if maxs else None
    return out


def sketch_quantile(sk: Dict[str, Any], q: float) -> Optional[float]:
    """Weighted-rank interpolation across centroid midpoints; exact at
    the extremes (min/max are tracked separately)."""
    cs = sk.get("centroids") or []
    total = sum(w for _v, w in cs)
    if not cs or total <= 0:
        return None
    q = min(1.0, max(0.0, float(q)))
    if q <= 0.0:
        return float(sk["min"]) if sk.get("min") is not None else cs[0][0]
    if q >= 1.0:
        return float(sk["max"]) if sk.get("max") is not None else cs[-1][0]
    target = q * total
    run = 0.0
    prev_v, prev_mid = None, 0.0
    for v, w in cs:
        mid = run + w / 2.0
        if target <= mid:
            if prev_v is None or mid == prev_mid:
                return float(v)
            frac = (target - prev_mid) / (mid - prev_mid)
            return float(prev_v + (v - prev_v) * frac)
        run += w
        prev_v, prev_mid = v, mid
    return float(cs[-1][0])


def sketch_spread(sk: Dict[str, Any]) -> Optional[float]:
    """p90 - p10 width: the "are my priors getting sharper" scalar the
    tests and the ETA seeding use."""
    p10, p90 = sketch_quantile(sk, 0.10), sketch_quantile(sk, 0.90)
    if p10 is None or p90 is None:
        return None
    return float(p90 - p10)


# ---------------------------------------------------------------------------
# Record shape and merge.
# ---------------------------------------------------------------------------

def _new_record(fingerprint: str) -> Dict[str, Any]:
    return {
        "v": STATS_SCHEMA_VERSION,
        "fingerprint": fingerprint,
        "run_count": 0,
        "wall_s": sketch_new(),
        "task_ms": sketch_new(),
        "stages": {},
        "counters": {},
        "derived": {},
        "fallback_reasons": {},
    }


def _new_stage(sid: int) -> Dict[str, Any]:
    return {
        "sid": sid,
        "run_count": 0,
        "partitions": 0,
        "tasks": 0,
        "exchange": "",
        "partition_bytes": sketch_new(),
        "total_bytes": sketch_new(),
        "skew": sketch_new(),
        "output_rows": sketch_new(),
        "last_partition_bytes": [],
    }


def _median(values: List[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    if not n:
        return 0.0
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def _merge_stage(st: Dict[str, Any], obs: Dict[str, Any],
                 budget: int) -> None:
    part_bytes = [float(b) for b in (obs.get("partition_bytes") or [])]
    st["run_count"] = int(st.get("run_count") or 0) + 1
    st["sid"] = int(obs.get("sid", st.get("sid", -1)))
    st["partitions"] = len(part_bytes) or int(obs.get("partitions") or 0)
    st["tasks"] = int(obs.get("tasks") or st.get("tasks") or 0)
    if obs.get("exchange"):
        st["exchange"] = str(obs["exchange"])
    if part_bytes:
        sketch_add(st["partition_bytes"], part_bytes, budget)
        sketch_add(st["total_bytes"], [sum(part_bytes)], budget)
        med = _median(part_bytes)
        if med > 0:
            sketch_add(st["skew"], [max(part_bytes) / med], budget)
        # bounded verbatim copy of the latest run, so the advisor can
        # name the skewed partition ("partition 7 is 12x median")
        st["last_partition_bytes"] = [int(b) for b in part_bytes[:256]]
    if obs.get("output_rows") is not None:
        sketch_add(st["output_rows"], [float(obs["output_rows"])], budget)


def merge_observation(rec: Dict[str, Any], obs: Dict[str, Any]
                      ) -> Dict[str, Any]:
    """Fold one finished run into the fingerprint's record (pure; used
    by ingest() and directly by tests)."""
    budget = _centroid_budget()
    rec["run_count"] = int(rec.get("run_count") or 0) + 1
    if obs.get("wall_s") is not None:
        sketch_add(rec["wall_s"], [float(obs["wall_s"])], budget)
    task_ns = obs.get("task_ns") or []
    if task_ns:
        sketch_add(rec["task_ms"], [ns / 1e6 for ns in task_ns], budget)
    counters = rec.setdefault("counters", {})
    for k in INGEST_COUNTERS:
        d = int((obs.get("counters") or {}).get(k, 0))
        if d or k in counters:
            counters[k] = int(counters.get(k, 0)) + d
    for reason, n in (obs.get("fallback_reasons") or {}).items():
        fr = rec.setdefault("fallback_reasons", {})
        fr[str(reason)] = int(fr.get(str(reason), 0)) + int(n)
    stages = rec.setdefault("stages", {})
    for sobs in obs.get("stages") or []:
        sfp = sobs.get("fingerprint")
        if not sfp:
            continue
        st = stages.get(sfp)
        if st is None:
            st = stages[sfp] = _new_stage(int(sobs.get("sid", -1)))
        _merge_stage(st, sobs, budget)
    rec["derived"] = _derive(rec)
    return rec


def _derive(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Ratios recomputed from the accumulated tallies (never merged as
    ratios — the Nth run's ratio weights every run's rows)."""
    c = rec.get("counters") or {}
    out: Dict[str, Any] = {}
    rows = int(c.get("partial_agg_probe_rows", 0))
    if rows:
        out["agg_probe_ratio"] = round(
            int(c.get("partial_agg_probe_groups", 0)) / rows, 6)
    built = int(c.get("expr_programs_built", 0))
    hits = int(c.get("expr_program_cache_hits", 0))
    if built + hits:
        out["expr_cache_hit_rate"] = round(hits / (built + hits), 6)
    sl_built = int(c.get("stage_loop_programs_built", 0))
    sl_hits = int(c.get("stage_loop_program_cache_hits", 0))
    if sl_built + sl_hits:
        out["stage_loop_cache_hit_rate"] = round(
            sl_hits / (sl_built + sl_hits), 6)
    wall = rec.get("wall_s") or {}
    p50 = sketch_quantile(wall, 0.5)
    if p50 is not None:
        out["wall_p50_s"] = round(p50, 6)
        spread = sketch_spread(wall)
        if spread is not None:
            out["wall_spread_s"] = round(spread, 6)
    return out


# ---------------------------------------------------------------------------
# Disk layout: one JSONL file per fingerprint; last valid line wins.
# ---------------------------------------------------------------------------

def _fp_path(root: str, fingerprint: str) -> str:
    safe = "".join(ch for ch in fingerprint if ch.isalnum() or ch in "-_")
    return os.path.join(root, f"fp-{safe}.jsonl")


def _dumps(rec: Dict[str, Any]) -> str:
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _read_last_record(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn append; keep scanning backwards
        if isinstance(rec, dict) and rec.get("v") == STATS_SCHEMA_VERSION:
            return rec
    return None


class StatStore:
    """Read/replay view over a stats directory.  Construction touches
    no state; every method re-reads disk so a fresh process replays
    exactly what was written."""

    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root or stats_dir()

    def fingerprints(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        fps = [n[3:-6] for n in names
               if n.startswith("fp-") and n.endswith(".jsonl")]
        return sorted(fps)

    def record(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        return _read_last_record(_fp_path(self.root, fingerprint))

    def records(self) -> List[Dict[str, Any]]:
        out = []
        for fp in self.fingerprints():
            rec = self.record(fp)
            if rec is not None:
                out.append(rec)
        return out

    def summary(self) -> List[Dict[str, Any]]:
        """Per-fingerprint digest for the /stats listing endpoint."""
        out = []
        for rec in self.records():
            d = rec.get("derived") or {}
            out.append({
                "fingerprint": rec.get("fingerprint"),
                "run_count": rec.get("run_count"),
                "wall_p50_s": d.get("wall_p50_s"),
                "wall_spread_s": d.get("wall_spread_s"),
                "stages": len(rec.get("stages") or {}),
            })
        return out


def prior(fingerprint: Optional[str]) -> Optional[Dict[str, Any]]:
    """Merged record for a fingerprint, or None (store disabled, never
    seen, or unreadable)."""
    if not fingerprint or not enabled():
        return None
    return StatStore().record(fingerprint)


def _prune(root: str) -> None:
    cap = _max_fingerprints()
    try:
        names = [n for n in os.listdir(root)
                 if n.startswith("fp-") and n.endswith(".jsonl")]
    except OSError:
        return
    if len(names) <= cap:
        return
    paths = [os.path.join(root, n) for n in names]
    try:
        paths.sort(key=lambda p: (os.path.getmtime(p), p))
    except OSError:
        paths.sort()
    for p in paths[:len(paths) - cap]:
        try:
            os.remove(p)
        except OSError:
            pass


def ingest(obs: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Merge one finished-run observation into its fingerprint record
    and persist it.  Returns the merged record (None when disabled or
    the observation carries no fingerprint).  Failures are swallowed —
    the stats plane must never fail a query."""
    if not enabled():
        return None
    fingerprint = obs.get("fingerprint")
    if not fingerprint:
        return None
    try:
        root = stats_dir()
        os.makedirs(root, exist_ok=True)
        path = _fp_path(root, fingerprint)
        with _lock:
            rec = _read_last_record(path) or _new_record(fingerprint)
            merge_observation(rec, obs)
            line = _dumps(rec) + "\n"
            n_lines = 0
            if os.path.exists(path):
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        n_lines = sum(1 for _ in f)
                except OSError:
                    n_lines = 0
            if n_lines + 1 > _MAX_LINES:
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(line)
                os.replace(tmp, path)
            else:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(line)
        _prune(root)
        try:
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_stats(
                ingests=1,
                runs_merged=1 if rec["run_count"] > 1 else 0,
                fingerprints_last=len(StatStore(root).fingerprints()))
        except Exception:
            pass
        return rec
    except Exception:
        return None
