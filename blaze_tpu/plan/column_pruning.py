"""Column-pruning optimizer pass (Catalyst ColumnPruning analog).

The reference receives plans already pruned by Catalyst — every
FileSourceScanExec carries a projection of exactly the referenced columns
(ref NativeParquetScanBase.scala:55).  Plans authored directly against
the engine IR (tests, itest queries, embedded users) scan full schemas,
which on wide TPC-DS facts wastes most of the parquet decode + host
conversion.  This pass recovers Catalyst's behavior engine-side:

  * REQUIRED column indices flow DOWN the decoded ExecutionPlan tree
    (each operator contributes the columns its own expressions touch);
  * at an unpartitioned ParquetScanExec the projection narrows to the
    required columns (schema order);
  * an old->new index MAPPING flows back UP through schema-preserving
    operators (filter/sort/limit/exchange), and every affected
    expression rewrites its BoundReferences; joins merge the two child
    mappings with the right-side offset shift.

Operators not modeled here act as barriers: their subtree is revisited
with required=None, so pruning still happens beneath nested
projections/aggregations deeper down.  Gated by `auron.tpu.columnPruning`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from blaze_tpu.exprs.base import BoundReference, PhysicalExpr

Mapping = Optional[Dict[int, int]]


# ---------------------------------------------------------------------------
# expression helpers
# ---------------------------------------------------------------------------

def expr_columns(e: PhysicalExpr, out: Set[int]) -> None:
    if isinstance(e, BoundReference):
        out.add(e.index)
    for c in e.children():
        expr_columns(c, out)


def _rewrite_value(v, mapping: Dict[int, int]):
    if isinstance(v, BoundReference):
        return BoundReference(mapping[v.index], v.name)
    if isinstance(v, PhysicalExpr):
        return rewrite_expr(v, mapping)
    if isinstance(v, tuple):
        return tuple(_rewrite_value(x, mapping) for x in v)
    if isinstance(v, list):
        return [_rewrite_value(x, mapping) for x in v]
    return v


def rewrite_expr(e: PhysicalExpr, mapping: Dict[int, int]) -> PhysicalExpr:
    """Rebuild an expression tree with BoundReference indices remapped.
    Expressions are frozen dataclasses whose PhysicalExpr-valued fields
    (possibly inside tuples/lists) are rewritten recursively."""
    if isinstance(e, BoundReference):
        return BoundReference(mapping[e.index], e.name)
    if not dataclasses.is_dataclass(e):
        # non-dataclass expression: bail out conservatively by signaling
        # the caller (treated as a barrier upstream)
        raise _Unprunable()
    changes = {}
    for f in dataclasses.fields(e):
        old = getattr(e, f.name)
        new = _rewrite_value(old, mapping)
        if new is not old:
            changes[f.name] = new
    return dataclasses.replace(e, **changes) if changes else e


class _Unprunable(Exception):
    pass


def _cols_of(exprs: Sequence[PhysicalExpr]) -> Set[int]:
    out: Set[int] = set()
    for e in exprs:
        expr_columns(e, out)
    return out


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def prune_columns(plan):
    """Entry point: returns the (possibly rebuilt) plan."""
    from blaze_tpu import config
    if not config.COLUMN_PRUNING_ENABLE.get():
        return plan
    try:
        new, _mapping = _prune(plan, None)
        return new
    except _Unprunable:
        return plan


def _identity(n: int) -> Dict[int, int]:
    return {i: i for i in range(n)}


def _prune(plan, required: Optional[Set[int]]):
    """Returns (new_plan, mapping).  `mapping` is None when the node's
    output columns are unchanged; otherwise old->new indices (parents
    MUST rewrite their expressions through it)."""
    from blaze_tpu.ops.agg.exec import AggExec
    from blaze_tpu.ops.basic import (DebugExec, FilterExec,
                                     FilterProjectExec, LimitExec,
                                     ProjectExec)
    from blaze_tpu.ops.joins.exec import BaseJoinExec
    from blaze_tpu.ops.scan import ParquetScanExec
    from blaze_tpu.ops.sort import SortExec

    if isinstance(plan, ParquetScanExec):
        return _prune_scan(plan, required)

    if isinstance(plan, FilterExec):
        child_req = (None if required is None else
                     required | _cols_of(plan._predicates))
        child, m = _prune(plan.children[0], child_req)
        if m is None:
            plan.children[0] = child
            return plan, None
        preds = [rewrite_expr(p, m) for p in plan._predicates]
        return FilterExec(child, preds), m

    if isinstance(plan, (DebugExec, LimitExec)):
        child, m = _prune(plan.children[0], required)
        plan.children[0] = child
        if m is None:
            return plan, None
        return plan, m  # schema passthrough; parent rewrites

    if isinstance(plan, SortExec):
        child_req = (None if required is None else
                     required | _cols_of([s[0] for s in plan._specs]))
        child, m = _prune(plan.children[0], child_req)
        if m is None:
            plan.children[0] = child
            return plan, None
        specs = [(rewrite_expr(e, m), d, nf) for e, d, nf in plan._specs]
        return SortExec(child, specs, fetch=getattr(plan, "_fetch",
                                                    None)), m

    if isinstance(plan, (ProjectExec, FilterProjectExec)):
        exprs = list(plan._exprs)
        preds = list(getattr(plan, "_predicates", []) or [])
        child_req = _cols_of(exprs + preds)
        child, m = _prune(plan.children[0], child_req)
        if m is None:
            plan.children[0] = child
            return plan, None
        new_exprs = [rewrite_expr(e, m) for e in exprs]
        names = [f.name for f in plan.schema]
        if isinstance(plan, FilterProjectExec):
            new_preds = [rewrite_expr(p, m) for p in preds]
            return (FilterProjectExec(child, new_preds, new_exprs,
                                      names), None)
        return ProjectExec(child, new_exprs, names), None

    if isinstance(plan, AggExec):
        group_exprs = [e for e, _n in plan._group_exprs]
        arg_exprs: List[PhysicalExpr] = []
        for fn, _mode, _name in plan._aggs:
            arg_exprs.extend(fn.children)
        child_req = _cols_of(group_exprs + arg_exprs)
        child, m = _prune(plan.children[0], child_req)
        if m is None:
            plan.children[0] = child
            return plan, None
        groups = [(rewrite_expr(e, m), n) for e, n in plan._group_exprs]
        aggs = []
        for fn, mode, name in plan._aggs:
            new_fn = type(fn).__new__(type(fn))
            new_fn.__dict__.update(fn.__dict__)
            new_fn.children = [rewrite_expr(c, m) for c in fn.children]
            aggs.append((new_fn, mode, name))
        return (type(plan)(child, groups, aggs,
                           exec_mode=plan._exec_mode), None)

    if isinstance(plan, BaseJoinExec):
        n_left = len(plan.children[0].schema)
        n_right = len(plan.children[1].schema)
        jt = plan.join_type.value
        if required is None or jt not in ("inner", "left", "right",
                                          "full"):
            # semi/anti/existence output shapes differ; recurse with
            # key+filter requirements only when output is one side —
            # keep it simple: no pruning through those joins, but still
            # descend for nested opportunities
            plan.children[0] = _prune(plan.children[0], None)[0]
            plan.children[1] = _prune(plan.children[1], None)[0]
            return plan, None
        filt_cols: Set[int] = set()
        if plan.join_filter is not None:
            expr_columns(plan.join_filter, filt_cols)
        left_req = ({i for i in required if i < n_left} |
                    _cols_of(plan.left_keys) |
                    {i for i in filt_cols if i < n_left})
        right_req = ({i - n_left for i in required if i >= n_left} |
                     _cols_of(plan.right_keys) |
                     {i - n_left for i in filt_cols if i >= n_left})
        lchild, lm = _prune(plan.children[0], left_req)
        rchild, rm = _prune(plan.children[1], right_req)
        if lm is None and rm is None:
            plan.children[0] = lchild
            plan.children[1] = rchild
            return plan, None
        lm = lm or _identity(n_left)
        rm = rm or _identity(n_right)
        new_n_left = len(lchild.schema)
        joined = dict(lm)
        joined.update({n_left + o: new_n_left + n
                       for o, n in rm.items()})
        kwargs = dict(join_type=plan.join_type,
                      build_side=plan.build_side,
                      join_filter=(rewrite_expr(plan.join_filter, joined)
                                   if plan.join_filter is not None
                                   else None),
                      existence_col=plan._existence_col,
                      null_aware_anti=plan.null_aware_anti)
        from blaze_tpu.ops.joins.exec import BroadcastJoinExec
        if isinstance(plan, BroadcastJoinExec):
            kwargs["broadcast_id"] = plan._broadcast_id
        new = type(plan)(lchild, rchild,
                         [rewrite_expr(k, lm) for k in plan.left_keys],
                         [rewrite_expr(k, rm) for k in plan.right_keys],
                         **kwargs)
        return new, joined

    # unknown operator: barrier — no requirements cross it, but nested
    # subtrees still get their own chances
    for i, child in enumerate(plan.children):
        plan.children[i] = _prune(child, None)[0]
    return plan, None


def _prune_scan(scan, required: Optional[Set[int]]):
    from blaze_tpu.ops.scan import ParquetScanExec
    if required is None or scan._partition_schema is not None:
        return scan, None
    n = len(scan.schema)
    req = sorted(i for i in required if i < n)
    if len(req) == n:
        return scan, None
    names = [scan.schema[i].name for i in req]
    new = ParquetScanExec(scan._file_schema, scan._file_groups,
                          projection=names,
                          predicate=scan._predicate,
                          batch_rows=scan._batch_rows)
    mapping = {old: new_i for new_i, old in enumerate(req)}
    return new, mapping

