"""Structured findings derived from the statistics feedback plane:
the statstore's merged per-fingerprint record (plan/statstore.py) plus
the finished query's bottleneck report (bridge/critical_path.py).

Each finding is a small JSON object — ``{"kind", "stage", "summary",
"evidence"}`` — embedded in the history ``finished`` event and counted
in the ``stats_advisor_findings`` Prometheus counter.  Findings are
*advice for the next run* (and PR 17's adaptive pass reads the same
record directly); they never change execution here.

Kinds (docs/observability.md keeps the table):

- ``broadcast_candidate``   a shuffle boundary small enough to broadcast
- ``skew_partition``        one partition >> median: skew-split candidate
- ``host_eviction``         stage-loop/scatter work evicted to the host
- ``low_cache_hit_rate``    expr/stage-loop program cache churns
- ``high_cardinality_agg``  partial-agg probe says grouping won't reduce
- ``dominant_bottleneck``   one wall-clock category owns most of the run
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from blaze_tpu.plan import statstore

__all__ = ["FINDING_KINDS", "findings", "recommendations",
           "broadcast_threshold", "skew_factor"]

FINDING_KINDS = ("broadcast_candidate", "skew_partition", "host_eviction",
                 "low_cache_hit_rate", "high_cardinality_agg",
                 "dominant_bottleneck")

#: category -> what to try, for dominant_bottleneck summaries
_BOTTLENECK_HINTS = {
    "scan_decode": "consider narrower projection or scan-share cache",
    "device_compute": "device-bound; check stage-loop chunk sizing",
    "host_compute": "host-bound; check host-lane evictions",
    "exchange_wire": "exchange-bound; broadcast or fewer partitions",
    "barrier_idle": "map->exchange barrier; rebalance producer tasks",
    "dispatch_gap": "scheduler idle; raise task parallelism",
    "admission_wait": "queue-bound; raise admission concurrency",
    "retry_backoff": "retries dominate; investigate task failures",
}


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024.0 or unit == "GB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GB"


def broadcast_threshold() -> int:
    """The single broadcast-bytes threshold shared by advisor findings
    and the AQE pass: `auron.tpu.aqe.broadcastThreshold` when set
    (>= 0), else the advisor's `stats.advisor.broadcastBytes`."""
    try:
        from blaze_tpu import config
        v = int(config.AQE_BROADCAST_THRESHOLD.get())
        if v >= 0:
            return v
        return int(config.STATS_ADVISOR_BROADCAST_BYTES.get())
    except Exception:
        return 8 << 20


def skew_factor() -> float:
    """The single skew ratio shared by advisor findings and the AQE
    pass: `auron.tpu.aqe.skewFactor` when set (> 0), else the
    advisor's `stats.advisor.skewFactor`."""
    try:
        from blaze_tpu import config
        v = float(config.AQE_SKEW_FACTOR.get())
        if v > 0:
            return v
        return float(config.STATS_ADVISOR_SKEW_FACTOR.get())
    except Exception:
        return 4.0


def _median(values: List[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    if not n:
        return 0.0
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def _stage_recommendations(sfp: str, st: Dict[str, Any]
                           ) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    sid = st.get("sid")
    total_p50 = statstore.sketch_quantile(st.get("total_bytes") or {}, 0.5)
    partitions = int(st.get("partitions") or 0)
    thr = broadcast_threshold()
    if total_p50 is not None and 0 < total_p50 <= thr and partitions > 1:
        out.append({
            "rule": "broadcast", "stage": sid, "fingerprint": sfp,
            "threshold": thr,
            "evidence": {"fingerprint": sfp,
                         "total_bytes_p50": round(total_p50, 1),
                         "threshold_bytes": thr,
                         "partitions": partitions},
        })
    last = [float(b) for b in (st.get("last_partition_bytes") or [])]
    med = _median(last)
    factor = skew_factor()
    if last and med > 0:
        worst = max(range(len(last)), key=lambda i: (last[i], -i))
        ratio = last[worst] / med
        if ratio >= factor:
            out.append({
                "rule": "skew_split", "stage": sid, "fingerprint": sfp,
                "threshold": factor,
                "evidence": {"fingerprint": sfp, "partition": worst,
                             "partition_bytes": int(last[worst]),
                             "median_bytes": round(med, 1),
                             "ratio": round(ratio, 2),
                             "factor": factor},
            })
    return out


def recommendations(record: Optional[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Structured `(rule, threshold, evidence)` records the AQE pass
    (plan/adaptive.py) consumes directly.  The broadcast/skew findings
    below are rendered FROM these records, so the advisor's report and
    the rewrites the engine actually applies share one threshold
    source and can never disagree."""
    out: List[Dict[str, Any]] = []
    rec = record or {}
    for sfp in sorted(rec.get("stages") or {}):
        out.extend(_stage_recommendations(sfp, rec["stages"][sfp]))
    out.sort(key=lambda r: (r["rule"],
                            -1 if r["stage"] is None else int(r["stage"]),
                            r["fingerprint"]))
    return out


def _stage_findings(sfp: str, st: Dict[str, Any]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for r in _stage_recommendations(sfp, st):
        ev = r["evidence"]
        sid = r["stage"]
        if r["rule"] == "broadcast":
            out.append({
                "kind": "broadcast_candidate", "stage": sid,
                "summary": (f"stage {sid} shuffle writes "
                            f"{_fmt_bytes(ev['total_bytes_p50'])} (p50) "
                            f"across {ev['partitions']} partitions: fits "
                            f"broadcast threshold "
                            f"{_fmt_bytes(ev['threshold_bytes'])}"),
                "evidence": dict(ev),
            })
        elif r["rule"] == "skew_split":
            out.append({
                "kind": "skew_partition", "stage": sid,
                "summary": (f"stage {sid} partition {ev['partition']} is "
                            f"{ev['ratio']:.1f}x median "
                            f"({_fmt_bytes(ev['partition_bytes'])} vs "
                            f"{_fmt_bytes(ev['median_bytes'])}): "
                            f"skew-split candidate"),
                "evidence": dict(ev),
            })
    return out


def findings(record: Optional[Dict[str, Any]],
             bottleneck: Optional[Dict[str, Any]] = None
             ) -> List[Dict[str, Any]]:
    """Derive advisor findings; deterministic given (record,
    bottleneck), sorted by (kind, stage)."""
    out: List[Dict[str, Any]] = []
    rec = record or {}
    for sfp in sorted(rec.get("stages") or {}):
        out.extend(_stage_findings(sfp, rec["stages"][sfp]))
    for reason, n in sorted((rec.get("fallback_reasons") or {}).items()):
        if int(n) > 0:
            out.append({
                "kind": "host_eviction", "stage": None,
                "summary": f"host-evicted: {reason} x{int(n)}",
                "evidence": {"reason": reason, "count": int(n)},
            })
    derived = rec.get("derived") or {}
    counters = rec.get("counters") or {}
    for rate_key, built_key, hits_key, what in (
            ("expr_cache_hit_rate", "expr_programs_built",
             "expr_program_cache_hits", "expr-program"),
            ("stage_loop_cache_hit_rate", "stage_loop_programs_built",
             "stage_loop_program_cache_hits", "stage-loop")):
        rate = derived.get(rate_key)
        lookups = (int(counters.get(built_key, 0)) +
                   int(counters.get(hits_key, 0)))
        if rate is not None and lookups >= 8 and rate < 0.5:
            out.append({
                "kind": "low_cache_hit_rate", "stage": None,
                "summary": (f"{what} cache hit rate {rate:.0%} over "
                            f"{lookups} lookups: compile churn"),
                "evidence": {"plane": what, "hit_rate": rate,
                             "lookups": lookups},
            })
    ratio = derived.get("agg_probe_ratio")
    if ratio is not None and ratio >= 0.8:
        out.append({
            "kind": "high_cardinality_agg", "stage": None,
            "summary": (f"partial-agg probe ratio {ratio:.2f} "
                        f"(groups/rows): partial agg barely reduces — "
                        f"skip candidate"),
            "evidence": {
                "agg_probe_ratio": ratio,
                "probe_rows": int(counters.get(
                    "partial_agg_probe_rows", 0)),
                "probe_groups": int(counters.get(
                    "partial_agg_probe_groups", 0))},
        })
    if bottleneck:
        dom = bottleneck.get("dominant")
        frac = float(bottleneck.get("dominant_fraction") or 0.0)
        if dom and frac >= 0.5:
            hint = _BOTTLENECK_HINTS.get(dom, "")
            out.append({
                "kind": "dominant_bottleneck", "stage": None,
                "summary": (f"{dom} owns {frac:.0%} of wall"
                            + (f": {hint}" if hint else "")),
                "evidence": {"category": dom, "fraction": frac,
                             "wall_s": bottleneck.get("wall_s")},
            })
    out.sort(key=lambda f: (f["kind"],
                            -1 if f["stage"] is None else int(f["stage"]),
                            f["summary"]))
    return out
