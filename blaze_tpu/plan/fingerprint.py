"""Canonical plan fingerprints for cross-query work sharing.

A fingerprint is a blake2s digest over the canonical JSON form of an
engine-IR subtree (sorted keys, compact separators), so two submissions
of the same logical plan — regardless of dict insertion order — hash
identically.  The IR dicts ARE the canonical form: every PhysicalExpr
`cache_key` (exprs/base.py) is derived 1:1 from its IR dict, and stage
identity (StageProgram fingerprints, PR 8) is derived from the same
subtree, so hashing the IR subsumes both.

The digest deliberately EXCLUDES the source data version.  That lives
in a separate `source_snapshot` — the stat-derived (mtime_ns, size) of
every scanned file plus any `snapshot_id` a connector stamps on its IR
node (the Iceberg snapshot analog) — which the cache stores alongside
each entry and re-validates on every lookup.  A snapshot mismatch is an
invalidation, not a different key: the stale entry is actively evicted.

Uncacheable plans return `None` from `source_snapshot`:

* any non-file source (`memory_scan`, `kafka_scan`) — no version signal;
* run-scoped readers (`ipc_reader`, `ffi_reader`) — their resource ids
  are minted per run and never collide across queries anyway;
* sinks (`*_sink`, `*shuffle_writer`) — side effects must re-execute;
* un-stat-able files (remote FS, deleted) — no invalidation evidence;
* plans with no versioned source at all — nothing to validate against.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

#: file-backed scan kinds whose `file_groups` feed the snapshot
_FILE_SCAN_KINDS = ("parquet_scan", "orc_scan")

#: kinds that make the containing plan uncacheable outright
_UNCACHEABLE_KINDS = ("memory_scan", "kafka_scan", "ipc_reader",
                      "ffi_reader", "parquet_sink", "orc_sink",
                      "shuffle_writer", "rss_shuffle_writer",
                      "ipc_writer")


def _canonical(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str).encode()


def _digest(obj: Any) -> str:
    return hashlib.blake2s(_canonical(obj)).hexdigest()


def _walk(plan: Dict[str, Any]):
    """Yield every dict node of an IR tree (children live in arbitrary
    keys: input/left/right/children/file_groups/...)."""
    stack: List[Any] = [plan]
    while stack:
        d = stack.pop()
        if isinstance(d, dict):
            yield d
            stack.extend(d.values())
        elif isinstance(d, (list, tuple)):
            stack.extend(d)


def plan_fingerprint(plan: Dict[str, Any]) -> str:
    """Content digest of a whole IR (sub)tree; data-version agnostic."""
    return _digest(plan)


def subplan_fingerprint(stage_plan: Dict[str, Any],
                        partitioning: Optional[Dict[str, Any]],
                        num_tasks: int) -> str:
    """Identity of one exchange-producing map stage: the stage subtree
    plus the partitioning that shaped its shuffle output — two stages
    agreeing on both produce byte-identical partition blocks."""
    return _digest({"plan": stage_plan, "partitioning": partitioning,
                    "num_tasks": int(num_tasks)})


def derived_fingerprint(base_fp: str, rule: str,
                        params: Dict[str, Any]) -> str:
    """Fingerprint of an AQE-rewritten subtree: a digest over the
    ORIGINAL fingerprint plus the rewrite rule and its parameters.
    Derivation (rather than re-hashing the mutated IR, which embeds
    run-scoped resource ids) keeps the identity deterministic across
    runs while guaranteeing it can never collide with the static
    shape — so the subplan cache and statstore treat a rewritten stage
    as a distinct shape, never a stale hit."""
    return _digest({"base": base_fp, "rule": rule, "params": params})


def source_snapshot(plan: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Version stamp of every data source under `plan`, or None when the
    plan is uncacheable (see module docstring)."""
    files: Dict[str, List[int]] = {}
    snapshots: List[str] = []
    for node in _walk(plan):
        kind = node.get("kind")
        if kind in _UNCACHEABLE_KINDS:
            return None
        if kind in _FILE_SCAN_KINDS:
            for group in node.get("file_groups") or []:
                for path in group:
                    if not isinstance(path, str):
                        return None
                    try:
                        st = os.stat(path)
                    except OSError:
                        return None
                    files[path] = [st.st_mtime_ns, st.st_size]
        snap_id = node.get("snapshot_id")
        if snap_id is not None:
            snapshots.append(str(snap_id))
    if not files and not snapshots:
        return None
    return {"files": files, "snapshots": sorted(snapshots)}


def snapshot_digest(snapshot: Dict[str, Any]) -> str:
    return _digest(snapshot)


def result_cache_key(plan: Dict[str, Any]
                     ) -> Optional[Tuple[str, Dict[str, Any]]]:
    """(fingerprint, snapshot) for a whole query, or None when the plan
    cannot be cached or deduplicated."""
    snap = source_snapshot(plan)
    if snap is None:
        return None
    return plan_fingerprint(plan), snap
